"""HTTP ask/tell front end over the :class:`StudyScheduler`.

Grown out of ``obs/serve.py``'s fail-open stdlib-daemon pattern — the
same ``ThreadingHTTPServer`` + daemon-thread shape, now serving
*proposals* instead of metrics.  Endpoints (all JSON):

* ``POST /study`` — ``{"space": <spec>}`` (``service/spacespec.py``
  schema) or ``{"zoo": "<zoo name>"}``, plus optional ``seed``,
  ``n_startup_jobs``, ``max_trials`` and the ``tpe.suggest`` tuning
  kwargs → ``{"study_id": ...}`` (an opaque ``filestore.new_run_id``).
* ``POST /ask`` — ``{"study_id": ..., "n": 1}`` →
  ``{"trials": [{"tid": ..., "params": {label: value}}, ...]}``.
  Concurrent asks coalesce into one batched cohort tick per wave.
* ``POST /tell`` — ``{"study_id": ..., "tid": ..., "loss": ...}`` (or
  ``"results": [{tid, loss[, status]}, ...]``) → ``{"ok": true}``.
* ``POST /close`` — ``{"study_id": ...}`` frees the study's slot.
* ``GET /studies`` — the study table: per-study status + cohort/slot
  roll-up + cohort-program cache counters.
* ``GET /study/<id>/timeline`` — the study's live audit timeline
  (ISSUE 11): admit, every ask (wave/algo/degrade/trace), every tell,
  shed/void, evict/re-admit, crash-resume boundary.
* ``GET /healthz`` — machine-readable replica health (ISSUE 12):
  replica id, held shard leases + epochs, drain state, WAL sync
  health; the rolling-restart script and ``obs/top.py``'s FLEET row
  consume it.
* ``GET /metrics`` / ``GET /snapshot`` — the obs integration:
  Prometheus exposition of every registry namespace (the ``service.*``
  family and the ``slo_*`` error-budget gauges ride along) and a JSON
  snapshot with the study table, degrade-ladder state and SLO section.

Request observability (ISSUE 11, armed by default): every request
carries a W3C-``traceparent``-style trace context — extracted from the
inbound header (malformed ones degrade to a fresh trace, never an
error) or minted — echoed on every response (JSON ``trace`` field +
``X-Trace-Id`` header, 429/503 included) and threaded through the
scheduler's wave/tick spans and the WAL.  The SLO plane
(``obs/slo.py``, ``HYPEROPT_TPU_SERVICE_SLO``) evaluates availability /
ask-latency / shed-rate burn rates from the handler's own traffic; the
opt-in access log (``HYPEROPT_TPU_SERVICE_ACCESS_LOG``) writes one
JSONL record per request and taps the flight ring.

Error mapping is in-band and typed: schema errors answer 400, unknown
studies 404, quota exhaustion and load sheds 429 (+ ``Retry-After``
from the live wave-latency EWMA), draining 503 — all as ``{"ok":
false, "error": ...}`` JSON.  A handler bug answers 500 once per
request and never propagates into the scheduler (the obs/serve.py
contract); every response increments a per-endpoint status-class
counter (``service.http.<endpoint>.<c>xx``) and a 500 records the
exception in the flight ring, so handler failures are observable
instead of vanishing into the fail-open path.

Overload control (ISSUE 10): ``POST /ask`` passes through a bounded
admission queue (``HYPEROPT_TPU_SERVICE_QUEUE``) and a per-request
monotonic deadline (``X-Deadline-Ms`` header, clamped by
``HYPEROPT_TPU_SERVICE_DEADLINE_MS``); past the bound — or when the
deadline cannot cover the predicted wait — the server sheds with 429
instead of queuing unboundedly.  Tells shed only at 4x the ask bound
(they are cheap and preserve client work).

Fleet mode (ISSUE 12): ``--fleet`` (with ``--store``) joins the
replicated serving fleet — N replicas over one store root partition
the study keyspace into leased study-shards (``service/fleet.py``),
each served by its own scheduler + shard-epoch WAL; a study owned by
another replica answers **307** with the owner's advertised address
(``Location`` header + JSON ``location``), which ``ServiceClient``
follows transparently.  Single-scheduler mode is byte-for-byte the
pre-fleet path.

Arming: ``python -m hyperopt_tpu.service.server [--port P]`` (or
``HYPEROPT_TPU_SERVICE=<port>`` with no ``--port``); ``--port 0`` binds
an ephemeral port and ``--announce`` prints ``SERVICE_URL <url>`` for
harnesses (``scripts/service_smoke.py``).  SIGTERM drains gracefully:
stop admitting, finish in-flight waves, compact + close the WAL (fleet
mode: hand off every held shard so survivors adopt it), exit 0.
"""

from __future__ import annotations

import json
import logging
import threading
import time

from ..obs import reqtrace
from ..obs.serve import prometheus_text, split_hostport
from ..obs.tenant import ANON, sanitize_tenant
from ..obs.trace import JsonlSink, Tracer
from ..exceptions import StoreFullError
from .fleet import ShardNotOwned, ShardUnavailable
from .overload import (AdmissionGuard, Deadline, OverloadError,
                       StoreFullShed)
from .scheduler import (DrainingError, DuplicateTellError,
                        QuarantinedStudyError, StaleOwnershipError,
                        StudyQuotaError, StudyScheduler,
                        UnknownStudyError)
from .spacespec import SpaceSpecError, space_from_spec

__all__ = ["ServiceHTTPServer", "main"]

logger = logging.getLogger(__name__)

_STUDY_KWARGS = ("n_startup_jobs", "max_trials", "prior_weight",
                 "n_EI_candidates", "gamma", "linear_forgetting",
                 "ei_select", "ei_tau", "prior_eps", "canary", "tenant")


class _RequestError(Exception):
    """Typed in-band failure: (HTTP status, message)."""

    def __init__(self, status, message):
        super().__init__(message)
        self.status = int(status)


def _timeline_study_id(path):
    """``/study/<id>/timeline`` → the study id, else None (one level
    only — a nested or empty id is not this route)."""
    if not (path.startswith("/study/") and path.endswith("/timeline")):
        return None
    sid = path[len("/study/"):-len("/timeline")].rstrip("/")
    if not sid or "/" in sid:
        return None
    return sid


class ServiceHTTPServer:
    """Daemon-thread ask/tell server over one scheduler (see module
    docstring).  Fail-open lifecycle matches ``obs/serve.py``:
    ``start()`` warns and returns False on a bind failure instead of
    raising, ``stop()`` is idempotent."""

    def __init__(self, port, scheduler=None, host=None, store_root=None,
                 guard=None, trace=None, slo=None, access_log=None,
                 fleet=None):
        from .._env import (parse_load_slo, parse_quality_slo,
                            parse_reqtrace, parse_service_access_log,
                            parse_service_deadline_ms, parse_service_slo)
        from ..obs.metrics import get_metrics

        try:
            if host is None:
                host, port = split_hostport(port)
            self.port = int(port)
        except (TypeError, ValueError):
            self.port = None  # start() warns and fails open
        self.host = host or "127.0.0.1"
        # fleet mode (ISSUE 12): a FleetReplica owns one scheduler per
        # held study-shard; study-scoped requests route through it (a
        # shard owned elsewhere answers 307 + the owner's address).
        # Single-scheduler mode is byte-for-byte the pre-fleet path.
        self.fleet = fleet
        if fleet is not None:
            self.scheduler = None
            self.metrics = fleet.metrics
        else:
            self.scheduler = scheduler if scheduler is not None else (
                StudyScheduler(store_root=store_root, wave_window=0.005))
            self.metrics = self.scheduler.metrics
        # the process's compile plane (ISSUE 14), for scrape-time gauge
        # refresh: fleet replicas share one via scheduler_kwargs,
        # single-scheduler mode reads the scheduler's
        if fleet is not None:
            self.compile_plane = (fleet.scheduler_kwargs.get(
                "compile_plane") or None)
        else:
            self.compile_plane = self.scheduler.compile_plane
        self.guard = (guard if guard is not None
                      else AdmissionGuard(metrics=self.metrics))
        if fleet is not None:
            # every adopted shard's scheduler feeds the one guard its
            # wave latencies — that EWMA sizes every Retry-After hint
            fleet.overload = self.guard
            for sched in fleet.schedulers.values():
                if sched.overload is None:
                    sched.overload = self.guard
        elif self.scheduler.overload is None:
            self.scheduler.overload = self.guard
        self.default_deadline_ms = parse_service_deadline_ms()
        # request-trace plane (ISSUE 11): parse/mint/echo/stamp trace
        # context per request.  Pure metadata, zero threads; `trace=False`
        # (or HYPEROPT_TPU_REQTRACE=off) restores the pre-PR handler path
        self.trace_enabled = (parse_reqtrace() if trace is None
                              else bool(trace))
        # handler spans feed the flight ring through a sink-less tracer
        self._tracer = Tracer()
        # SLO error-budget plane: None = disarmed (no gauges, no
        # escalation); targets resolve from HYPEROPT_TPU_SERVICE_SLO
        self.slo = None
        if slo is not False:
            targets = parse_service_slo() if slo in (None, True) else slo
            if targets is not None:
                from ..obs.slo import SLOPlane

                self.slo = SLOPlane(targets,
                                    metrics=self.metrics,
                                    escalation=self._slo_escalation)
        # search-quality SLO (ISSUE 16): when BOTH the burn-rate plane
        # and a scheduler-side quality plane are armed, install the
        # stagnant-fraction objective and point the plane(s) at it —
        # one good/bad event per live tell, replay excluded
        if self.slo is not None:
            q_targets = parse_quality_slo()
            if q_targets is not None and self._quality_planes():
                for name, spec in q_targets.items():
                    self.slo.add_objective(name, spec)
                for plane in self._quality_planes():
                    plane.slo = self.slo
        # fleet-imbalance SLO (ISSUE 17): when BOTH the burn-rate
        # plane and a scheduler-side cost ledger are armed, install the
        # `imbalance` objective.  The skew bound rides the spec dict
        # (add_objective ignores unknown keys); the server keeps it and
        # feeds one pre-judged good/bad event per load-gauge refresh
        self.load_skew_max = None
        if self.slo is not None:
            from .._env import parse_load

            l_targets = parse_load_slo()
            # fleet replicas adopt shards AFTER server construction, so
            # "a cost ledger is armed" must be judged from the kwargs
            # future schedulers will be built with, not the (empty)
            # current plane list
            armed = bool(self._load_planes()) or (
                self.fleet is not None
                and self.fleet.scheduler_kwargs.get("load") is not False
                and (self.fleet.scheduler_kwargs.get("load") is not None
                     or parse_load()))
            if l_targets is not None and armed:
                for name, spec in l_targets.items():
                    self.slo.add_objective(name, spec)
                self.load_skew_max = l_targets.get(
                    "imbalance", {}).get("skew_max")
        # per-tenant SLO objectives (ISSUE 20): the targets grammar
        # (HYPEROPT_TPU_TENANT_SLO) judged server-side per finished ask;
        # objectives install lazily (`tenant:<id>:<name>`) and ONLY for
        # up to top-K tenants — the burn-rate plane's cardinality stays
        # bounded exactly like the tenant ledger's
        self.tenant_slo = None
        self._tenant_objs = set()
        if self.slo is not None:
            from .._env import parse_tenant_slo, parse_tenant_top_k

            self.tenant_slo = parse_tenant_slo()
            self._tenant_obj_bound = parse_tenant_top_k()
        # opt-in structured access log (JSONL; one record per request)
        log_path = (parse_service_access_log() if access_log is None
                    else (access_log or None))
        self.access_log = JsonlSink(log_path) if log_path else None
        # blackbox prober (ISSUE 18): disarmed = None — zero threads,
        # zero allocations, no probe SLO objectives installed.  Armed
        # post-start via arm_prober() (it needs the bound URL).
        self.prober = None
        self._httpd = None
        self._thread = None
        self._stopped = False

    # -- request handling --------------------------------------------------

    def handle(self, method, path, body, headers=None):
        """Route one request; returns ``(status, payload dict)``.  Pure
        (no socket I/O) so tests can drive it directly.  ``headers`` is
        a lower-cased mapping (the deadline and ``traceparent`` headers
        ride in it); a 429/503 payload carries ``retry_after`` seconds,
        which the HTTP layer also emits as a ``Retry-After`` header.

        Trace plumbing (ISSUE 11, armed by default): a valid inbound
        ``traceparent`` continues the caller's trace, a malformed one
        degrades to a fresh trace — NEVER a 4xx/5xx (the fuzz pin) —
        and every response carries the trace id in its JSON body
        (``trace``) plus an ``X-Trace-Id`` header from the HTTP layer,
        so a client can correlate its own retries, including through a
        429/503."""
        headers = headers or {}
        observing = (self.slo is not None or self.access_log is not None
                     or bool(self._tenant_planes()))
        if not self.trace_enabled and not observing:
            # fully disarmed: the pre-PR handler path, nothing extra
            status, payload = self._handle(method, path, body, headers)
            self._count_response(method, path, status)
            return status, payload
        t0 = time.perf_counter()
        req_id = reqtrace.sanitize_request_id(headers.get("x-request-id"))
        if self.trace_enabled:
            ctx = reqtrace.extract_or_mint(headers.get("traceparent"))
            with reqtrace.use(ctx):
                with self._tracer.span("service.handle",
                                       trace=ctx.trace_id,
                                       span=ctx.span_id, method=method,
                                       path=path):
                    status, payload = self._handle(method, path, body,
                                                   headers)
            if isinstance(payload, dict):
                payload.setdefault("trace", ctx.trace_id)
        else:
            # tracing off, but the SLO plane / access log still observe
            ctx = None
            status, payload = self._handle(method, path, body, headers)
        latency = time.perf_counter() - t0
        if req_id and isinstance(payload, dict):
            # echo a sane client X-Request-Id (hostile ones were dropped
            # by the sanitizer) — the client's own correlation token
            payload.setdefault("request_id", req_id)
        self._count_response(method, path, status)
        try:
            # hostile ids already answered 400 inside _handle; they are
            # attributed to no one (a row minted per hostile id would BE
            # the cardinality bomb the ledger bounds against)
            tenant = sanitize_tenant(headers.get("x-tenant"))
        except ValueError:
            tenant = None
        self._observe_response(method, path, status, latency, payload,
                               ctx, req_id,
                               probe=headers.get("x-probe") == "1",
                               tenant=tenant)
        return status, payload

    def _observe_response(self, method, path, status, latency_sec,
                          payload, ctx, req_id, probe=False, tenant=None):
        """Post-response observability: feed the SLO plane and write the
        access-log record (JSONL + flight ring).  Never raises.
        ``probe`` marks blackbox-prober traffic (the ``x-probe: 1``
        header): it must NOT feed the server-side tenant SLO objectives
        — the prober judges itself through its own ``probe_*``
        objectives — but it stays in the access log, tagged.  ``tenant``
        (ISSUE 20) is the request's sanitized principal (None = hostile
        header, already 400d): it feeds the tenant ledger's ask-latency
        sketch + shed counters and the per-tenant SLO objectives, with
        probe traffic excluded from BOTH, exactly as from the global
        tenant SLOs."""
        ep = self._endpoint_label(method, path)
        shed = bool(status == 429 and isinstance(payload, dict)
                    and payload.get("retry_after") is not None)
        if self.slo is not None and not probe:
            try:
                self.slo.record_request(ep, status,
                                        latency_sec=latency_sec,
                                        shed=shed)
            except Exception:  # noqa: BLE001 - observability never fails a req
                # log once, keep the plane alive: disabling it on a
                # transient fault would freeze the last-published slo_*
                # gauges at plausible-but-dead values on /metrics
                if not self._slo_warned:
                    self._slo_warned = True
                    logger.warning("slo plane record failed (continuing)",
                                   exc_info=True)
        if tenant is not None and not probe and ep == "ask":
            try:
                self._observe_tenant(tenant, payload, status,
                                     latency_sec, shed)
            except Exception:  # noqa: BLE001 - observability never fails a req
                pass
        if self.access_log is None:
            return
        try:
            rec = {"kind": "access", "ts": time.time(), "method": method,
                   "path": path, "status": int(status),
                   "latency_ms": round(latency_sec * 1e3, 3),
                   "trace": ctx.trace_id if ctx is not None else None}
            if probe:
                rec["probe"] = True
            if tenant is not None and tenant != ANON:
                # the access log's tenant column; anonymous records stay
                # byte-identical to pre-ISSUE-20
                rec["tenant"] = tenant
            if req_id:
                rec["request_id"] = req_id
            if isinstance(payload, dict):
                if status >= 400 and payload.get("error"):
                    rec["reason"] = str(payload["error"])[:200]
                if shed:
                    rec["shed"] = True
                if payload.get("degraded"):
                    rec["degraded"] = True
                if payload.get("study_id"):
                    rec["study_id"] = payload["study_id"]
                if payload.get("wave") is not None:
                    # the wave sequence that served this ask — joins an
                    # access record to the cohort tick (and its cost
                    # attribution) that produced the response
                    rec["wave"] = payload["wave"]
            self.access_log.write(rec)
            # the flight-ring tap: the last requests ride into every
            # postmortem dump next to the spans that served them
            from ..obs.flight import get_flight

            get_flight().record(rec)
        except Exception:  # noqa: BLE001
            pass

    def _slo_escalation(self):
        """The SLO plane's fast-burn escalation: ONE bounded device
        capture when the error budget starts burning page-hot, so "SLO
        violated" comes with the device trace of the slow wave.  Needs
        the capture plane armed (``HYPEROPT_TPU_PROFILE=<dir>``);
        without it the escalation only logs.  The capture itself runs on
        a short-lived background thread — the hook fires from inside a
        request's ``_observe_response`` (or a scrape), and blocking THAT
        thread for the bounded capture window would inject seconds of
        latency into exactly the overloaded path the SLO just flagged
        (the watchdog's ``capture_on_stall`` makes the same choice)."""
        import os as _os

        from ..obs.profiler import DeviceProfiler, split_profile_mode

        cap_dir, _full = split_profile_mode(
            _os.environ.get("HYPEROPT_TPU_PROFILE"))
        if cap_dir is None:
            logger.warning(
                "SLO fast burn-rate alert: error budget burning hot "
                "(no device capture — arm HYPEROPT_TPU_PROFILE=<dir> to "
                "get one)")
            return
        prof = self._escalation_profiler
        if prof is None:
            prof = self._escalation_profiler = DeviceProfiler(cap_dir)

        def _capture():
            rec = prof.capture(2.0, reason="slo_burn")
            logger.warning("SLO fast burn-rate alert: captured device "
                           "trace (ok=%s dir=%s)", rec.get("ok"),
                           rec.get("dir"))

        threading.Thread(target=_capture, name="hyperopt-slo-escalation",
                         daemon=True).start()

    _escalation_profiler = None
    _slo_warned = False

    @staticmethod
    def _endpoint_label(method, path):
        """Metric-friendly endpoint label: known routes by name, the
        rest pooled (an attacker probing random paths must not mint
        unbounded metric families)."""
        known = ("/study", "/ask", "/tell", "/close", "/studies",
                 "/metrics", "/snapshot", "/healthz", "/fleet/load",
                 "/probes", "/tenants", "/")
        if path in known:
            return path.strip("/").replace("/", "_") or "root"
        if _timeline_study_id(path) is not None:
            return "timeline"
        return "other"

    def _count_response(self, method, path, status):
        ep = self._endpoint_label(method, path)
        cls = int(status) // 100
        self.metrics.counter(f"service.http.{ep}.{cls}xx").inc()

    def _record_failure(self, method, path, exc):
        """A handler exception became a 500: record it in the flight
        ring (it used to vanish into the fail-open path — invisible to
        every post-mortem)."""
        try:
            from ..obs.flight import get_flight

            get_flight().record({
                "kind": "service_error", "ts": time.time(),
                "method": method, "path": path,
                "error": f"{type(exc).__name__}: {exc}"})
        except Exception:  # noqa: BLE001 - forensics must never cascade
            pass

    def _route(self, study_id):
        """The scheduler serving ``study_id`` — always ``self.scheduler``
        in single-server mode; in fleet mode the replica's routing table
        (which raises :class:`ShardNotOwned` → 307 with the owner's
        address, or :class:`ShardUnavailable` → retryable 503)."""
        if self.fleet is None:
            return self.scheduler
        return self.fleet.scheduler_for(study_id)

    def healthz_dict(self):
        """``GET /healthz``: replica identity, held shard leases +
        epochs, drain state and WAL sync health — machine-readable (the
        rolling-restart script and ``obs/top.py``'s FLEET row consume
        it).  Single-server mode reports the same shape with no shard
        table."""
        if self.fleet is not None:
            out = self.fleet.healthz()
            if self.prober is not None:
                # blackbox verdict fields (ISSUE 18): the rolling-restart
                # gate reads these — fail-open (never flips `ok`; the
                # gate decides what "blackbox-green" requires)
                out["probe"] = self.prober.healthz_fields()
            return out
        sched = self.scheduler
        out = {"ok": True, "replica": None, "addr": self.url,
               "n_shards": None, "shards_held": [], "shards": {},
               "draining": sched._draining,
               "wal_sync_errors": self.metrics.counter(
                   "service.wal.sync_errors").value,
               "ts": time.time()}
        if sched.journal is not None:
            out["wal"] = {"path": sched.journal.path,
                          "appends": sched.journal.appends,
                          "syncs": sched.journal.syncs,
                          "compactions": sched.journal.compactions}
        store = sched.store_health()
        if store is not None:
            out["store"] = store
            if store.get("store_full"):
                out["ok"] = False
        if sched.tenants is not None:
            try:  # tenant column (ISSUE 20): roll-up only, fail-open
                ts = sched.tenants.status()
                out["tenants"] = {"tracked": ts["tenants"],
                                  "sheds": ts["sheds"],
                                  "evictions": ts["evictions"]}
            except Exception:  # noqa: BLE001
                pass
        out["ok"] = out["ok"] and not sched._draining
        if self.prober is not None:
            out["probe"] = self.prober.healthz_fields()
        return out

    def _studies_status(self):
        if self.fleet is not None:
            return self.fleet.studies_status()
        return self.scheduler.studies_status()

    def _handle(self, method, path, body, headers):
        try:
            # hostile-tenant hardening (ISSUE 20): a malformed
            # ``x-tenant`` answers 400 on EVERY route (the ValueError
            # maps below) — never 500, never a minted ledger row
            tenant = sanitize_tenant(headers.get("x-tenant"))
            if method == "GET":
                if path == "/studies":
                    return 200, self._studies_status()
                if path == "/tenants":
                    return 200, self.tenants_dict()
                if path == "/healthz":
                    return 200, self.healthz_dict()
                if path == "/snapshot":
                    return 200, self.snapshot_dict()
                if path == "/fleet/load":
                    return 200, self.fleet_load_dict()
                if path == "/probes":
                    return 200, self.probes_dict()
                sid = _timeline_study_id(path)
                if sid is not None:
                    return 200, self._route(sid).study_timeline(sid)
                if path == "/":
                    return 200, {
                        "ok": True,
                        "endpoints": ["POST /study", "POST /ask",
                                      "POST /tell", "POST /close",
                                      "GET /studies",
                                      "GET /study/<id>/timeline",
                                      "GET /healthz",
                                      "GET /metrics", "GET /snapshot",
                                      "GET /fleet/load",
                                      "GET /probes",
                                      "GET /tenants"]}
                raise _RequestError(404, f"no such endpoint: {path}")
            if method != "POST":
                raise _RequestError(405, f"{method} not supported")
            if path == "/study":
                return 200, self._create_study(body, tenant)
            if path == "/ask":
                study_id = self._required(body, "study_id")
                sched = self._route(study_id)
                n = int(body.get("n", 1))
                # the client's ask-idempotency token (ISSUE 12): a
                # retried ask answers the originally served trials.
                # Sanitized like X-Request-Id — a hostile value must
                # not become an unbounded-key or log-injection vector
                req_id = body.get("req")
                if not isinstance(req_id, str) or not req_id \
                        or len(req_id) > 200:
                    req_id = None
                deadline = Deadline.from_request(
                    headers.get("x-deadline-ms"), self.default_deadline_ms)
                token = self.guard.admit_ask(deadline, tenant=tenant)
                try:
                    trials = sched.ask(study_id, n, deadline=deadline,
                                       req_id=req_id)
                finally:
                    self.guard.release(token, tenant=tenant)
                out = {"ok": True, "study_id": study_id,
                       "trials": [{k: t[k] for k in
                                   ("tid", "params", "degraded", "algo",
                                    "warming")
                                   if k in t}
                                  for t in trials]}
                wave = next((t.get("wave") for t in trials
                             if t.get("wave") is not None), None)
                if wave is not None:
                    # response metadata: the wave sequence that served
                    # this ask (the access log's correlation key to the
                    # tick's cost attribution); trials stay wave-free
                    out["wave"] = wave
                if any(t.get("degraded") for t in trials):
                    out["degraded"] = True
                if any(t.get("warming") for t in trials):
                    # in-band cold-start honesty (ISSUE 14): this
                    # proposal is random search while the cohort program
                    # compiles off-thread; the study promotes to TPE at
                    # the next wave after the program lands
                    out["warming"] = True
                return 200, out
            if path == "/tell":
                study_id = self._required(body, "study_id")
                sched = self._route(study_id)
                token = self.guard.admit_tell()
                try:
                    results = body.get("results")
                    batch = results is not None
                    if not batch:
                        results = [{"tid": self._required(body, "tid"),
                                    "loss": body.get("loss"),
                                    "status": body.get("status")}]
                    told = dups = 0
                    for r in results:
                        if not isinstance(r, dict) or r.get("tid") is None:
                            raise _RequestError(
                                400, f"each result needs a 'tid': {r!r}")
                        try:
                            sched.tell(study_id, r["tid"],
                                       loss=r.get("loss"),
                                       status=r.get("status"))
                            told += 1
                        except DuplicateTellError:
                            # a retried BATCH must not strand its untold
                            # tail behind one already-settled tid — skip
                            # and report; a single-tid duplicate still
                            # answers 409 so the client learns the
                            # conflict
                            if not batch:
                                raise
                            dups += 1
                finally:
                    self.guard.release(token)
                return 200, {"ok": True, "study_id": study_id,
                             "told": told, "duplicates": dups}
            if path == "/close":
                study_id = self._required(body, "study_id")
                self._route(study_id).close_study(study_id)
                return 200, {"ok": True, "study_id": study_id}
            raise _RequestError(404, f"no such endpoint: {path}")
        except _RequestError as e:
            return e.status, {"ok": False, "error": str(e)}
        except ShardNotOwned as e:
            # 307: the study's shard is served by another replica; the
            # HTTP layer emits Location and the client re-issues the
            # SAME method+body there (bounded hop count client-side)
            return 307, {"ok": False, "error": str(e),
                         "location": e.location}
        except ShardUnavailable as e:
            # the owner died and no survivor adopted the shard yet (or
            # the fleet is rebalancing): retryable, like draining
            return 503, {"ok": False, "error": str(e),
                         "retry_after": e.retry_after}
        except StaleOwnershipError as e:
            # this replica lost the shard's lease at the durability
            # fence: nothing landed; the retry meets the ownership
            # table (and its 307) once the new owner publishes
            return 503, {"ok": False, "error": str(e),
                         "retry_after": 0.25}
        except QuarantinedStudyError as e:
            # 410 Gone (ISSUE 15): the study's journal state was found
            # corrupt — permanent until an operator repairs the store
            # (scrub --repair); retrying is pointless, unlike 429/503
            return 410, {"ok": False, "error": str(e),
                         "quarantined": True}
        except StoreFullShed as e:
            # 507 Insufficient Storage (ISSUE 15): the ask shed at the
            # admission guard because the store is out of space;
            # retryable — the degrade rung is compacting/GCing and the
            # latch re-probes the disk automatically
            return 507, {"ok": False, "error": str(e),
                         "retry_after": e.retry_after}
        except StoreFullError as e:
            # the WAL/store write itself hit ENOSPC at the durability
            # point: nothing was acknowledged; same retryable 507
            return 507, {"ok": False, "error": str(e),
                         "retry_after": 1.0}
        except UnknownStudyError as e:
            return 404, {"ok": False, "error": str(e)}
        except DuplicateTellError as e:
            # 409, not 429: "already told" is permanent — a client
            # retrying a lost tell response must not back off forever
            return 409, {"ok": False, "error": str(e)}
        except DrainingError as e:
            # 503: the process is going away; retry against the restart
            return 503, {"ok": False, "error": str(e), "retry_after": 1.0}
        except OverloadError as e:
            # load shed (queue full / deadline unservable / expired):
            # the retry_after hint is measured from live wave latency
            return 429, {"ok": False, "error": str(e),
                         "retry_after": e.retry_after}
        except StudyQuotaError as e:
            return 429, {"ok": False, "error": str(e)}
        # ValueError/TypeError here are request-shape problems (bad n,
        # non-numeric loss, schema coercions); internal KeyError-class
        # bugs fall through to the 500 handler so server-side alerting
        # sees them instead of the client eating a bogus 400
        except (SpaceSpecError, ValueError, TypeError) as e:
            return 400, {"ok": False,
                         "error": f"{type(e).__name__}: {e}"}
        except Exception as e:  # noqa: BLE001 - fail-open contract
            logger.warning("service: %s %s failed: %s", method, path, e)
            self._record_failure(method, path, e)
            return 500, {"ok": False, "error": f"{type(e).__name__}: {e}"}

    @staticmethod
    def _required(body, key):
        v = body.get(key)
        if v is None:
            raise _RequestError(400, f"missing required field {key!r}")
        return v

    def _create_study(self, body, header_tenant=ANON):
        if "space" in body:
            space = space_from_spec(body["space"])
            space_spec = {"space": body["space"]}
        elif "zoo" in body:
            from ..zoo import ZOO

            rec = ZOO.get(str(body["zoo"]))
            if rec is None:
                raise _RequestError(
                    400, f"unknown zoo domain {body['zoo']!r} "
                         f"(one of {sorted(ZOO)})")
            space = rec.space
            space_spec = {"zoo": str(body["zoo"])}
        else:
            raise _RequestError(400, "POST /study needs 'space' or 'zoo'")
        kwargs = {k: body[k] for k in _STUDY_KWARGS if k in body}
        # tenant (ISSUE 20): an explicit body field wins; the x-tenant
        # header (already sanitized) covers clients that only set the
        # ambient identity.  A hostile BODY value is rejected by
        # ``Study.__init__``'s sanitize — ValueError → 400, never 500.
        if "tenant" not in kwargs and header_tenant != ANON:
            kwargs["tenant"] = header_tenant
        # the wire schema IS the WAL registry entry: every HTTP-created
        # study is crash-resumable
        if self.fleet is not None:
            # fleet placement: mint an id landing in a held shard (ids
            # are server-minted, so creation cannot redirect) — the id
            # already claimed its store subdirectory atomically
            study_id, sched = self.fleet.place_study()
            sched.create_study(space, seed=int(body.get("seed", 0)),
                               study_id=study_id, space_spec=space_spec,
                               **kwargs)
            return {"ok": True, "study_id": study_id}
        study_id = self.scheduler.create_study(
            space, seed=int(body.get("seed", 0)), space_spec=space_spec,
            **kwargs)
        return {"ok": True, "study_id": study_id}

    def _quality_planes(self):
        """Every armed quality plane this server fronts: one per adopted
        shard scheduler in fleet mode, the scheduler's own otherwise."""
        if self.fleet is not None:
            return [s.quality for s in self.fleet.schedulers.values()
                    if s.quality is not None]
        if self.scheduler is not None and self.scheduler.quality is not None:
            return [self.scheduler.quality]
        return []

    def _refresh_quality_gauges(self):
        """Scrape/snapshot-time ``quality.*`` gauge refresh (the
        compile/store pattern): returns the merged status section for
        ``/snapshot``, or None when disarmed."""
        from ..obs.quality import merge_status

        try:
            return merge_status([p.publish()
                                 for p in self._quality_planes()])
        except Exception:  # noqa: BLE001 - fail-open scrape
            return None

    def _load_planes(self):
        """Every armed cost ledger this server fronts: one per adopted
        shard scheduler in fleet mode, the scheduler's own otherwise."""
        if self.fleet is not None:
            return [s.load for s in self.fleet.schedulers.values()
                    if s.load is not None]
        if self.scheduler is not None and self.scheduler.load is not None:
            return [self.scheduler.load]
        return []

    def _refresh_load_gauges(self):
        """Scrape/snapshot-time ``service.load.*`` gauge refresh
        (ISSUE 17): each plane publishes its per-shard gauges, the
        merged view sets the replica-level family — totals, busy
        fraction and the heat-skew scalar — and feeds one good/bad
        event into the ``imbalance`` SLO objective.  Returns the merged
        status section for ``/snapshot``, or None when disarmed."""
        from ..obs.load import merge_status

        try:
            merged = merge_status([p.publish()
                                   for p in self._load_planes()])
        except Exception:  # noqa: BLE001 - fail-open scrape
            return None
        if merged is None:
            return None
        try:
            g = self.metrics.gauge
            g("service.load.device_ms").set(merged["device_ms"])
            g("service.load.heat_ms").set(merged["heat_ms"])
            g("service.load.busy_frac").set(merged["busy_frac"])
            g("service.load.heat_skew").set(merged["heat_skew"])
            g("service.load.studies").set(merged["studies"])
            if self.slo is not None and self.load_skew_max:
                self.slo.record_load(
                    merged["heat_skew"] <= self.load_skew_max)
        except Exception:  # noqa: BLE001 - fail-open scrape
            pass
        return merged

    def _tenant_planes(self):
        """Every armed tenant ledger this server fronts: one per adopted
        shard scheduler in fleet mode, the scheduler's own otherwise."""
        if self.fleet is not None:
            return [s.tenants for s in self.fleet.schedulers.values()
                    if s.tenants is not None]
        if (self.scheduler is not None
                and self.scheduler.tenants is not None):
            return [self.scheduler.tenants]
        return []

    def _tenant_plane_for(self, payload):
        """The tenant ledger the request's study lives on (fleet mode
        routes by the payload's study id; a routing miss falls back to
        the first armed plane — one observation lands on exactly one
        ledger either way, and the merge sums them)."""
        if self.fleet is None:
            return (self.scheduler.tenants
                    if self.scheduler is not None else None)
        sid = (payload.get("study_id")
               if isinstance(payload, dict) else None)
        if sid:
            try:
                return self.fleet.scheduler_for(sid).tenants
            except Exception:  # noqa: BLE001 - not owned / mid-handoff
                pass
        planes = self._tenant_planes()
        return planes[0] if planes else None

    def _observe_tenant(self, tenant, payload, status, latency_sec,
                        shed):
        """One finished (non-probe) ask's tenant accounting: the
        ledger's latency/shed row plus the per-tenant SLO events."""
        plane = self._tenant_plane_for(payload)
        if plane is not None:
            if shed or status == 429:
                plane.observe_request(tenant, shed=True)
            elif status == 200:
                plane.observe_request(tenant, latency_sec=latency_sec)
        if self.slo is None or not self.tenant_slo:
            return
        self._ensure_tenant_objectives(tenant)
        pre = f"tenant:{tenant}:"
        self.slo.record_event(pre + "availability", status < 500)
        self.slo.record_event(pre + "shed_rate", not (shed
                                                      or status == 429))
        if status == 200:
            thr = float(self.tenant_slo.get("ask_p99", {})
                        .get("threshold_ms") or 2000.0)
            self.slo.record_event(pre + "ask_p99",
                                  latency_sec * 1e3 <= thr)

    def _ensure_tenant_objectives(self, tenant):
        """Install this tenant's burn-rate objectives once (idempotent;
        bounded at top-K installed tenants — past the bound a new
        tenant's traffic still counts in the LEDGER's ``other`` bucket,
        it just gets no dedicated burn-rate alarms)."""
        if tenant in self._tenant_objs:
            return
        if len(self._tenant_objs) >= self._tenant_obj_bound:
            return
        for name, spec in self.tenant_slo.items():
            self.slo.add_objective(f"tenant:{tenant}:{name}", spec)
        self._tenant_objs.add(tenant)

    def _refresh_tenant_gauges(self):
        """Scrape/snapshot-time ``service.tenant.*`` gauge refresh
        (ISSUE 20): merge every armed ledger's status (per-shard tables
        in fleet mode — gauges are set ONCE from the merged view, so
        shards never overwrite each other's families) and make sure the
        merged table's tenants have their SLO objectives installed.
        Returns the merged status section for ``/snapshot`` +
        ``GET /tenants``, or None when disarmed."""
        from ..obs.tenant import _metric_label, merge_status

        try:
            merged = merge_status([p.status()
                                   for p in self._tenant_planes()])
        except Exception:  # noqa: BLE001 - fail-open scrape
            return None
        if merged is None:
            return None
        try:
            g = self.metrics.gauge
            g("service.tenant.tracked").set(merged["tenants"])
            g("service.tenant.evictions").set(merged["evictions"])
            g("service.tenant.sheds").set(merged["sheds"])
            g("service.tenant.device_ms").set(merged["device_ms"])
            for tenant, row in merged["table"].items():
                base = f"service.tenant.{_metric_label(tenant)}"
                g(f"{base}.device_ms").set(row["device_ms"])
                g(f"{base}.asks").set(row["asks"])
                g(f"{base}.tells").set(row["tells"])
                g(f"{base}.sheds").set(row["sheds"])
                g(f"{base}.studies").set(row["studies"])
                if row.get("ask_p99_ms") is not None:
                    g(f"{base}.ask_p99_ms").set(row["ask_p99_ms"])
            if self.slo is not None and self.tenant_slo:
                for tenant in merged["table"]:
                    if tenant != "other":
                        self._ensure_tenant_objectives(tenant)
        except Exception:  # noqa: BLE001 - fail-open scrape
            pass
        return merged

    def tenants_dict(self):
        """``GET /tenants``: the bounded per-tenant attribution table
        (merged across shards in fleet mode), freshly published.
        Disarmed servers answer ``{"armed": false}`` instead of a 404 so
        dashboards can scrape unconditionally."""
        out = {"ok": True, "ts": time.time(), "endpoint": "tenants"}
        merged = self._refresh_tenant_gauges()
        if merged is None:
            out["armed"] = False
            return out
        out["armed"] = True
        out.update(merged)
        return out

    def fleet_load_dict(self):
        """``GET /fleet/load``: this replica's merged cost-attribution
        view plus the FLEET-WIDE heat table read from every replica's
        durable ledger under the shared store root — per-shard
        cumulative heat (max over cumulative snapshots, so it survives
        restarts and ownership moves), per-replica latest snapshot, and
        the heat-skew scalar.  Works single-server too (no `fleet`
        section without a store root).  Carries the fleet-merged
        per-tenant heat table (ISSUE 20) when any heat record stamps
        one."""
        out = {"ok": True, "ts": time.time(), "endpoint": "fleet_load"}
        merged = self._refresh_load_gauges()
        if merged is not None:
            out["local"] = merged
        ten = self._refresh_tenant_gauges()
        if ten is not None:
            out["tenants"] = ten
        store_root = None
        if self.fleet is not None:
            out["replica"] = self.fleet.replica_id
            store_root = self.fleet.store_root
        elif self.scheduler is not None:
            store_root = self.scheduler.store_root
        if store_root is not None:
            from ..obs.load import read_heat
            from ..obs.tenant import read_tenant_heat

            try:
                out["fleet"] = read_heat(store_root)
            except Exception:  # noqa: BLE001 - fail-open read
                logger.warning("fleet/load: heat-ledger read failed",
                               exc_info=True)
            try:
                heat = read_tenant_heat(store_root)["tenants"]
                if heat:
                    out["tenant_heat"] = heat
            except Exception:  # noqa: BLE001 - fail-open read
                pass
        return out

    def _refresh_compile_gauges(self):
        """Publish the compile-visibility gauges (ISSUE 14 satellite):
        the cohort-program LRU and the single-study jit LRU counters as
        ``service.compile.*``, refreshed at scrape/snapshot time — cache
        behavior used to be invisible to the scrape plane."""
        from ..algos import tpe

        g = self.metrics.gauge
        for name, stats in (("cohort_cache", tpe.cohort_cache_stats()),
                            ("jit_cache", tpe.jit_cache_stats())):
            for k in ("hits", "misses", "size"):
                g(f"service.compile.{name}.{k}").set(stats[k])

    def snapshot_dict(self):
        """``/snapshot``: the service metrics namespace plus the study
        table — the obs-plane view of the serving layer.  Carries the
        SLO section (budget/burn per objective, freshly evaluated) and
        the degrade-ladder state so ``obs.top``'s service view renders
        from one GET."""
        from ..algos import tpe

        out = {"ts": time.time(), "endpoint": "snapshot",
               "service": True}
        if self.slo is not None:
            out["slo"] = self.slo.publish()  # refresh gauges on scrape
        qual = self._refresh_quality_gauges()
        if qual is not None:
            out["quality"] = qual
        load = self._refresh_load_gauges()
        if load is not None:
            out["load"] = load
        tenants = self._refresh_tenant_gauges()
        if tenants is not None:
            out["tenants"] = tenants
        self._refresh_compile_gauges()
        out["sections"] = {
            "service": self.metrics.snapshot()["metrics"]}
        status = self._studies_status()
        if "fleet" in status:
            out["fleet"] = status["fleet"]
        out["studies"] = status["studies"]
        out["cohorts"] = status["cohorts"]
        out["slot_utilization"] = status["slot_utilization"]
        out["cohort_cache"] = status["cohort_cache"]
        out["jit_cache"] = tpe.jit_cache_stats()
        out["draining"] = status.get("draining", False)
        if "degrade" in status:
            out["degrade"] = status["degrade"]
        if "compile" in status:
            out["compile"] = status["compile"]
        if "wal" in status:
            out["wal"] = status["wal"]
        if "store" in status:
            out["store"] = status["store"]
        if "quarantined" in status:
            out["quarantined"] = status["quarantined"]
        if self.prober is not None:
            out["probes"] = self.prober.status_dict()
        return out

    def probes_dict(self):
        """``GET /probes``: the blackbox prober's rolling verdict view —
        armed state, golden digest + source, per-verdict counts, match
        streak, recent cycles and detection-latency stats.  Disarmed
        servers answer a one-field shape instead of a 404 so dashboards
        can scrape unconditionally."""
        out = {"ok": True, "ts": time.time(), "endpoint": "probes"}
        if self.prober is None:
            out["armed"] = False
            return out
        try:
            out.update(self.prober.status_dict())
        except Exception:  # noqa: BLE001 - fail-open scrape
            out["armed"] = True
            out["error"] = "probe status unavailable"
        return out

    def _refresh_store_gauges(self):
        """Scrape-time disk-watermark poll (ISSUE 15): publish
        ``store.free_bytes`` / ``store.used_frac`` and run the
        enter/exit-low logic even when no wave is ticking — a quiet
        service on a filling disk must still see (and shed) it."""
        try:
            if self.fleet is not None:
                for sched in list(self.fleet.schedulers.values()):
                    sched.store_health(force=True)
            elif self.scheduler is not None:
                self.scheduler.store_health(force=True)
        except Exception:  # noqa: BLE001 - fail-open scrape
            pass

    # -- lifecycle ---------------------------------------------------------

    @property
    def url(self):
        if self._httpd is None:
            return None
        return f"http://{self.host}:{self._httpd.server_address[1]}"

    def start(self):
        """Bind + serve on a daemon thread; False (after one warning) on
        any bind failure."""
        import http.server

        if self.port is None:
            logger.warning("service: unparseable port/host value; "
                           "ask/tell serving disabled")
            return False
        handler = _make_handler(self)
        try:
            self._httpd = http.server.ThreadingHTTPServer(
                (self.host, self.port), handler)
        except (OSError, OverflowError, ValueError) as e:
            logger.warning("service: cannot bind %s:%s (%s); ask/tell "
                           "serving disabled", self.host, self.port, e)
            self._httpd = None
            return False
        self._httpd.daemon_threads = True
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            kwargs={"poll_interval": 0.25},
            name="hyperopt-service-http", daemon=True)
        self._thread.start()
        logger.info("ask/tell service listening on %s", self.url)
        return True

    def drain(self, timeout=30.0):
        """Graceful shutdown (the SIGTERM path): stop admitting (new
        studies and asks answer 503/``DrainingError`` immediately, tells
        keep landing), wait for in-flight waves to finish, compact +
        close the WAL, then stop serving.  In fleet mode every held
        shard is handed off first (lease released, ownership entry
        cleared) so a survivor adopts it — the rolling-restart
        zero-lost-tells path.  Returns True when everything quiesced
        within ``timeout``."""
        if self.prober is not None:
            # stop probing BEFORE the listener starts refusing: a drain
            # must not manufacture error verdicts on its way out
            try:
                self.prober.stop()
            except Exception:  # noqa: BLE001
                pass
        if self.fleet is not None:
            quiesced = self.fleet.drain(timeout=timeout)
        else:
            quiesced = self.scheduler.drain(timeout=timeout)
        self.stop()
        return quiesced

    def arm_prober(self, period=None, targets=None):
        """Arm the blackbox prober (ISSUE 18) against this server —
        called AFTER ``start()`` (the prober probes the real bound URL
        through the real HTTP path).  Installs the ``probe_*`` SLO
        objectives (only now: a disarmed prober leaves the burn-rate
        plane untouched), resolves the sealed verdict-ledger path under
        the store root when one exists, and starts the probe thread.
        Idempotent; returns the prober (or None when unbound)."""
        if self.prober is not None:
            return self.prober
        if not targets and self.url is None:
            logger.warning("probe: server is not bound; prober stays "
                           "disarmed")
            return None
        from .._env import parse_probe_period, parse_probe_slo
        from ..obs.prober import Prober, probes_path_for

        slo_targets = parse_probe_slo() if self.slo is not None else None
        if slo_targets:
            for name, spec in slo_targets.items():
                self.slo.add_objective(name, spec)
        if self.fleet is not None:
            replica = self.fleet.replica_id
            store_root = self.fleet.store_root
            wal_path = None  # per-(shard, epoch) WALs; evidence skips it
        else:
            replica = "single"
            store_root = self.scheduler.store_root
            j = self.scheduler.journal
            wal_path = j.path if j is not None else None
        self.prober = Prober(
            list(targets) if targets else [self.url],
            period=(period if period is not None
                    else parse_probe_period()),
            slo=self.slo if slo_targets else None,
            metrics=self.metrics,
            ledger_path=(probes_path_for(store_root, replica)
                         if store_root else None),
            replica=replica, wal_path=wal_path)
        self.prober.start()
        logger.info("blackbox prober armed: %s every %.3gs",
                    self.prober.targets, self.prober.period)
        return self.prober

    def stop(self):
        if self._stopped:
            return
        self._stopped = True
        httpd, self._httpd = self._httpd, None
        if httpd is not None:
            try:
                httpd.shutdown()
                httpd.server_close()
            except Exception:
                pass


def _make_handler(server):
    import http.server

    class Handler(http.server.BaseHTTPRequestHandler):
        def log_message(self, fmt, *args):
            logger.debug("service http: " + fmt, *args)

        def _answer(self, status, payload, content_type="application/json"):
            data = (payload if isinstance(payload, bytes)
                    else json.dumps(payload, default=str,
                                    sort_keys=True).encode())
            self.send_response(status)
            self.send_header("Content-Type", content_type)
            self.send_header("Content-Length", str(len(data)))
            if isinstance(payload, dict) and payload.get("trace"):
                # echo the request's trace id on EVERY response — incl.
                # 429/503 — so a client can correlate its own retries
                self.send_header("X-Trace-Id", str(payload["trace"]))
            if isinstance(payload, dict) and payload.get("request_id"):
                self.send_header("X-Request-Id",
                                 str(payload["request_id"]))
            if (status == 307 and isinstance(payload, dict)
                    and payload.get("location")):
                # fleet redirect: the owner's advertised address.  The
                # JSON body carries it too (service/client.py reads the
                # payload; standard HTTP clients follow the header)
                self.send_header("Location", str(payload["location"]))
            if (status in (429, 503, 507) and isinstance(payload, dict)
                    and payload.get("retry_after") is not None):
                # RFC 7231 delta-seconds is an INTEGER — a fractional
                # header is discarded by standard clients/proxies.  The
                # wire header rounds up; the JSON payload keeps the
                # precise float for service/client.py
                import math

                self.send_header(
                    "Retry-After",
                    str(max(1, math.ceil(float(payload["retry_after"])))))
            self.end_headers()
            self.wfile.write(data)

        def _dispatch(self, method):
            path = self.path.partition("?")[0]
            try:
                if method == "GET" and path == "/metrics":
                    if server.slo is not None:
                        try:  # refresh slo_* gauges at scrape time
                            server.slo.publish()
                        except Exception:  # noqa: BLE001 - fail-open scrape
                            pass
                    try:  # cache + compile-plane gauges, same contract
                        server._refresh_compile_gauges()
                        if server.compile_plane is not None:
                            server.compile_plane.publish()
                    except Exception:  # noqa: BLE001 - fail-open scrape
                        pass
                    server._refresh_quality_gauges()
                    server._refresh_load_gauges()
                    server._refresh_tenant_gauges()
                    server._refresh_store_gauges()
                    server._count_response(method, path, 200)
                    self._answer(
                        200, prometheus_text().encode(),
                        "text/plain; version=0.0.4; charset=utf-8")
                    return
                body = {}
                if method == "POST":
                    length = int(self.headers.get("Content-Length") or 0)
                    raw = self.rfile.read(length) if length else b"{}"
                    try:
                        body = json.loads(raw or b"{}")
                    except ValueError:
                        self._answer(400, {"ok": False,
                                           "error": "body is not JSON"})
                        return
                    if not isinstance(body, dict):
                        self._answer(400, {"ok": False,
                                           "error": "body must be a JSON "
                                                    "object"})
                        return
                headers = {k.lower(): v for k, v in self.headers.items()}
                status, payload = server.handle(method, path, body,
                                                headers=headers)
                self._answer(status, payload)
            except (BrokenPipeError, ConnectionResetError):
                pass  # client went away mid-write
            except Exception as e:  # noqa: BLE001 - never kill the server
                logger.warning("service http: %s %s failed: %s",
                               method, path, e)
                try:
                    self.send_error(500)
                except Exception:
                    pass

        def do_GET(self):  # noqa: N802 (stdlib handler contract)
            self._dispatch("GET")

        def do_POST(self):  # noqa: N802
            self._dispatch("POST")

    return Handler


def main(argv=None):
    import argparse

    from .._env import parse_service

    p = argparse.ArgumentParser(
        prog="python -m hyperopt_tpu.service.server",
        description="Serve ask/tell hyperparameter optimization over HTTP "
                    "(thousands of concurrent studies batched onto one "
                    "device mesh).")
    p.add_argument("--port", default=None,
                   help="bind port or host:port (0 = ephemeral; default: "
                        "$HYPEROPT_TPU_SERVICE)")
    p.add_argument("--store", default=None,
                   help="FileStore root: persist each study's trials under "
                        "<store>/<study_id>")
    p.add_argument("--max-studies", type=int, default=None,
                   help="admission quota (default: "
                        "$HYPEROPT_TPU_SERVICE_MAX_STUDIES or 4096)")
    p.add_argument("--max-pending", type=int, default=None,
                   help="per-study asked-but-untold quota (default: "
                        "$HYPEROPT_TPU_SERVICE_MAX_PENDING or 64)")
    p.add_argument("--idle-sec", type=float, default=None,
                   help="evict a study's cohort slot after this much "
                        "inactivity (default: "
                        "$HYPEROPT_TPU_SERVICE_IDLE_SEC or 600)")
    p.add_argument("--wal", default=None,
                   help="write-ahead journal: 'auto' (default — under "
                        "--store when given), 'off', or an explicit path "
                        "(default: $HYPEROPT_TPU_SERVICE_WAL)")
    p.add_argument("--compile-plane", default=None,
                   choices=("on", "off"),
                   help="cold-start compile plane (ISSUE 14): warming "
                        "admission + background compilation + census "
                        "kernel bank (default: "
                        "$HYPEROPT_TPU_COMPILE_PLANE or off)")
    p.add_argument("--bank-top-n", type=int, default=None,
                   help="census keys to pre-compile synchronously before "
                        "the listener opens (default: "
                        "$HYPEROPT_TPU_COMPILE_BANK_TOP_N or 8)")
    p.add_argument("--fleet", action="store_true",
                   help="join the replicated serving fleet on --store: "
                        "lease-partitioned study shards, per-shard epoch "
                        "WALs, 307 routing (requires --store)")
    p.add_argument("--fleet-shards", type=int, default=None,
                   help="study-shard count (write-once per store root; "
                        "default: $HYPEROPT_TPU_FLEET_SHARDS or 8)")
    p.add_argument("--replica-id", default=None,
                   help="this replica's fleet identity (default: "
                        "<hostname>-<pid>)")
    p.add_argument("--addr", default=None,
                   help="the URL this replica advertises in the fleet "
                        "ownership table (default: $HYPEROPT_TPU_FLEET_ADDR "
                        "or the bound URL)")
    p.add_argument("--lease-ttl", type=float, default=None,
                   help="shard-lease reclaim TTL in seconds (default: "
                        "$HYPEROPT_TPU_FLEET_LEASE_TTL or 15)")
    p.add_argument("--announce", action="store_true",
                   help="print 'SERVICE_URL <url>' once bound (harness "
                        "handshake)")
    p.add_argument("--probe", default=None, choices=("on", "off"),
                   help="blackbox prober (ISSUE 18): pinned-seed canary "
                        "studies through the real HTTP path, golden-"
                        "stream verdicts on GET /probes (default: "
                        "$HYPEROPT_TPU_PROBE or off)")
    p.add_argument("--probe-period", type=float, default=None,
                   help="probe cycle period in seconds (default: "
                        "$HYPEROPT_TPU_PROBE_PERIOD or 30)")
    args = p.parse_args(argv)

    port = args.port if args.port is not None else parse_service()
    if port is None:
        p.error("no port: pass --port or set HYPEROPT_TPU_SERVICE")
    # cold-start compile plane (ISSUE 14): built HERE — before any
    # scheduler — so the census bank can pre-warm the top-N cohort
    # programs synchronously BEFORE the listener opens, and every shard
    # scheduler (fleet mode) shares one plane/queue/thread
    from .._env import parse_compile_plane

    plane = None
    if (args.compile_plane == "on"
            or (args.compile_plane is None and parse_compile_plane())):
        from .compile_plane import CompilePlane, census_path_for

        plane = CompilePlane(
            census_path=(census_path_for(args.store)
                         if args.store else None))
    wal = None  # env-resolved
    if args.wal is not None:
        # the SAME token sets as _env.parse_service_wal — '--wal true'
        # must not create a journal file literally named 'true'
        raw = args.wal.strip().lower()
        if raw in ("auto", "", "1", "on", "true", "yes"):
            wal = None
        elif raw in ("off", "0", "false", "no"):
            wal = False
        else:
            wal = args.wal
    if args.fleet:
        if not args.store:
            p.error("--fleet needs --store (the shared FileStore root is "
                    "the fleet's coordination plane)")
        if args.wal is not None:
            # fleet mode journals per (shard, epoch) by construction —
            # a --wal value would be silently discarded otherwise
            p.error("--wal does not compose with --fleet: each shard "
                    "journals to its own epoch WAL under "
                    "<store>/fleet/wal/")
        from .._env import parse_fleet_addr
        from .fleet import FleetReplica

        replica = FleetReplica(
            args.store, n_shards=args.fleet_shards,
            replica_id=args.replica_id, lease_ttl=args.lease_ttl,
            scheduler_kwargs={
                "max_studies": args.max_studies,
                "max_pending": args.max_pending,
                "idle_sec": args.idle_sec,
                "wave_window": 0.005,
                "compile_plane": plane if plane is not None else False,
            })
        if plane is not None:
            # kernel bank: top-N census keys compile before the bind
            plane.warm_from_census(top_n=args.bank_top_n)
        server = ServiceHTTPServer(port, fleet=replica)
        if not server.start():
            return 1
        # advertise AFTER the bind: an ephemeral --port 0 has no address
        # until now.  Claims happen after set_addr so every published
        # ownership entry routes 307s somewhere reachable.
        replica.set_addr(args.addr or parse_fleet_addr() or server.url)
        replica.start()
    else:
        sched = StudyScheduler(max_studies=args.max_studies,
                               max_pending=args.max_pending,
                               idle_sec=args.idle_sec,
                               store_root=args.store,
                               wal=wal,
                               wave_window=0.005,
                               compile_plane=(plane if plane is not None
                                              else False))
        if plane is not None:
            # kernel bank pre-warm AFTER the WAL resume (the ctor's
            # replay may itself have compiled programs) and BEFORE the
            # listener opens: returning spaces meet warm programs on
            # their very first ask
            plane.warm_from_census(top_n=args.bank_top_n)
        server = ServiceHTTPServer(port, scheduler=sched)
        if not server.start():
            return 1
    if args.announce:
        print(f"SERVICE_URL {server.url}", flush=True)
    from .._env import parse_probe

    if args.probe == "on" or (args.probe is None and parse_probe()):
        server.arm_prober(period=args.probe_period)

    # graceful drain on SIGTERM: stop admitting, finish in-flight waves,
    # compact + close the WAL, exit 0 — a supervised restart (or spot
    # preemption with notice) must not look like a crash
    import signal

    stop = threading.Event()
    prev = signal.signal(signal.SIGTERM, lambda _s, _f: stop.set())
    try:
        while not stop.is_set():
            stop.wait(0.5)
    except KeyboardInterrupt:
        pass
    finally:
        signal.signal(signal.SIGTERM, prev)
        quiesced = server.drain()
        if plane is not None:
            plane.stop()
        logger.info("service: drained (quiesced=%s); exiting", quiesced)
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
