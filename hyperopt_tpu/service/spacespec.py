"""JSON-wire search-space schema for the ask/tell service.

A study arrives over HTTP, so its search space must travel as data.  The
schema mirrors the ``hp.*`` constructors one-to-one — each node is
``{"dist": <family>, "args": [...]}`` keyed by its label, families taking
options use ``"options"`` — and :func:`space_from_spec` rebuilds the
exact ``hp`` expression tree::

    {"x":   {"dist": "uniform", "args": [-5, 5]},
     "lr":  {"dist": "loguniform", "args": [-6, 0]},
     "opt": {"dist": "choice", "options": [0, 1, 2]},
     "head": {"dist": "choice",
              "options": [{"width": {"dist": "uniformint",
                                     "args": [1, 8]}},
                          "linear"]}}

``choice`` / ``pchoice`` options may be scalars or nested sub-space
mappings (labels must stay unique across branches — the same
``DuplicateLabel`` contract every ``hp`` space has).  Unknown families
raise :class:`SpaceSpecError`, which the server maps to HTTP 400.

Robustness (ISSUE 10): the schema arrives from UNTRUSTED clients, so
every malformed or hostile shape must answer 400 with a typed message —
never a 500, never a hung/exploding server.  Beyond type checks, three
resource bounds cap what one request can make the compiler chew on:
nesting depth (``MAX_DEPTH`` — also the guard that turns a cyclic
mapping, impossible over the wire but possible via the Python API, into
a clean error instead of a ``RecursionError``), total parameter count
(``MAX_LABELS``) and per-choice option count (``MAX_OPTIONS``).  Labels
must be non-empty strings of sane length (``MAX_LABEL_LEN``).
"""

from __future__ import annotations

from .. import hp

__all__ = ["SpaceSpecError", "space_from_spec", "SPEC_FAMILIES",
           "MAX_DEPTH", "MAX_LABELS", "MAX_OPTIONS", "MAX_LABEL_LEN"]


class SpaceSpecError(ValueError):
    """Malformed space spec (HTTP 400, never a 500)."""


#: deepest allowed nesting of choice sub-spaces (a cyclic dict passed via
#: the Python API exhausts this bound long before the recursion limit)
MAX_DEPTH = 16
#: most parameters one study's space may declare, across all branches
MAX_LABELS = 512
#: most options one choice/pchoice may carry
MAX_OPTIONS = 1024
#: longest allowed label string
MAX_LABEL_LEN = 200


#: family name -> (hp constructor, positional arg count[s])
SPEC_FAMILIES = {
    "uniform": (hp.uniform, (2,)),
    "quniform": (hp.quniform, (3,)),
    "uniformint": (hp.uniformint, (2, 3)),
    "loguniform": (hp.loguniform, (2,)),
    "qloguniform": (hp.qloguniform, (3,)),
    "normal": (hp.normal, (2,)),
    "qnormal": (hp.qnormal, (3,)),
    "lognormal": (hp.lognormal, (2,)),
    "qlognormal": (hp.qlognormal, (3,)),
    "randint": (hp.randint, (1, 2)),
}


def _check_label(label):
    if not isinstance(label, str) or not label:
        raise SpaceSpecError(
            f"param labels must be non-empty strings, got {label!r}")
    if len(label) > MAX_LABEL_LEN:
        raise SpaceSpecError(
            f"param label too long ({len(label)} > {MAX_LABEL_LEN} chars)")


def _node_from_spec(label, node, depth, counts):
    if not isinstance(node, dict) or "dist" not in node:
        raise SpaceSpecError(
            f"param {label!r}: expected {{'dist': ..., ...}}, got "
            f"{type(node).__name__}")
    fam = node["dist"]
    if not isinstance(fam, str):
        raise SpaceSpecError(
            f"param {label!r}: 'dist' must be a string, got "
            f"{type(fam).__name__}")
    if fam in ("choice", "pchoice"):
        options = node.get("options")
        if not isinstance(options, list) or not options:
            raise SpaceSpecError(
                f"param {label!r}: {fam} needs a non-empty 'options' list")
        if len(options) > MAX_OPTIONS:
            raise SpaceSpecError(
                f"param {label!r}: {fam} has {len(options)} options "
                f"(limit {MAX_OPTIONS})")
        if fam == "choice":
            return hp.choice(label, [_option(label, o, depth, counts)
                                     for o in options])
        try:
            pairs = [(float(p), _option(label, o, depth, counts))
                     for p, o in options]
        except SpaceSpecError:
            raise
        except (TypeError, ValueError) as e:
            raise SpaceSpecError(
                f"param {label!r}: pchoice options must be "
                f"[probability, option] pairs ({e})") from None
        return hp.pchoice(label, pairs)
    entry = SPEC_FAMILIES.get(fam)
    if entry is None:
        raise SpaceSpecError(
            f"param {label!r}: unknown family {fam!r} "
            f"(one of {sorted(SPEC_FAMILIES) + ['choice', 'pchoice']})")
    fn, arities = entry
    args = node.get("args", [])
    if not isinstance(args, list) or len(args) not in arities:
        raise SpaceSpecError(
            f"param {label!r}: {fam} takes {' or '.join(map(str, arities))} "
            f"args, got {args!r}")
    try:
        return fn(label, *[float(a) for a in args])
    except (TypeError, ValueError) as e:
        raise SpaceSpecError(f"param {label!r}: {e}") from None


def _option(label, opt, depth, counts):
    """A choice option: a scalar literal or a nested sub-space mapping."""
    if isinstance(opt, dict):
        if "dist" in opt:
            raise SpaceSpecError(
                f"param {label!r}: a bare distribution cannot be a choice "
                "option — wrap it in a labeled sub-space mapping")
        return _space_from_spec(opt, depth + 1, counts)
    if isinstance(opt, (int, float, str, bool)) or opt is None:
        return opt
    raise SpaceSpecError(
        f"param {label!r}: option of type {type(opt).__name__} is neither "
        "a scalar nor a sub-space mapping")


def _space_from_spec(spec, depth, counts):
    if depth > MAX_DEPTH:
        raise SpaceSpecError(
            f"space spec nests deeper than {MAX_DEPTH} levels "
            "(cyclic or hostile schema)")
    if not isinstance(spec, dict) or not spec:
        raise SpaceSpecError(
            f"space spec must be a non-empty mapping, got "
            f"{type(spec).__name__}")
    out = {}
    for label, node in spec.items():
        _check_label(label)
        counts["labels"] += 1
        if counts["labels"] > MAX_LABELS:
            raise SpaceSpecError(
                f"space spec declares more than {MAX_LABELS} parameters")
        out[label] = _node_from_spec(label, node, depth, counts)
    return out


def space_from_spec(spec):
    """Rebuild an ``hp`` space from its JSON-wire form (see module
    docstring).  ``spec`` is a ``{label: node}`` mapping; any malformed
    or over-limit shape raises :class:`SpaceSpecError` (HTTP 400)."""
    return _space_from_spec(spec, 0, {"labels": 0})
