"""JSON-wire search-space schema for the ask/tell service.

A study arrives over HTTP, so its search space must travel as data.  The
schema mirrors the ``hp.*`` constructors one-to-one — each node is
``{"dist": <family>, "args": [...]}`` keyed by its label, families taking
options use ``"options"`` — and :func:`space_from_spec` rebuilds the
exact ``hp`` expression tree::

    {"x":   {"dist": "uniform", "args": [-5, 5]},
     "lr":  {"dist": "loguniform", "args": [-6, 0]},
     "opt": {"dist": "choice", "options": [0, 1, 2]},
     "head": {"dist": "choice",
              "options": [{"width": {"dist": "uniformint",
                                     "args": [1, 8]}},
                          "linear"]}}

``choice`` / ``pchoice`` options may be scalars or nested sub-space
mappings (labels must stay unique across branches — the same
``DuplicateLabel`` contract every ``hp`` space has).  Unknown families
raise :class:`SpaceSpecError`, which the server maps to HTTP 400.
"""

from __future__ import annotations

from .. import hp

__all__ = ["SpaceSpecError", "space_from_spec", "SPEC_FAMILIES"]


class SpaceSpecError(ValueError):
    """Malformed space spec (HTTP 400, never a 500)."""


#: family name -> (hp constructor, positional arg count[s])
SPEC_FAMILIES = {
    "uniform": (hp.uniform, (2,)),
    "quniform": (hp.quniform, (3,)),
    "uniformint": (hp.uniformint, (2, 3)),
    "loguniform": (hp.loguniform, (2,)),
    "qloguniform": (hp.qloguniform, (3,)),
    "normal": (hp.normal, (2,)),
    "qnormal": (hp.qnormal, (3,)),
    "lognormal": (hp.lognormal, (2,)),
    "qlognormal": (hp.qlognormal, (3,)),
    "randint": (hp.randint, (1, 2)),
}


def _node_from_spec(label, node):
    if not isinstance(node, dict) or "dist" not in node:
        raise SpaceSpecError(
            f"param {label!r}: expected {{'dist': ..., ...}}, got {node!r}")
    fam = node["dist"]
    if fam in ("choice", "pchoice"):
        options = node.get("options")
        if not isinstance(options, list) or not options:
            raise SpaceSpecError(
                f"param {label!r}: {fam} needs a non-empty 'options' list")
        if fam == "choice":
            return hp.choice(label, [_option(label, o) for o in options])
        try:
            pairs = [(float(p), _option(label, o)) for p, o in options]
        except (TypeError, ValueError) as e:
            raise SpaceSpecError(
                f"param {label!r}: pchoice options must be "
                f"[probability, option] pairs ({e})") from None
        return hp.pchoice(label, pairs)
    entry = SPEC_FAMILIES.get(fam)
    if entry is None:
        raise SpaceSpecError(
            f"param {label!r}: unknown family {fam!r} "
            f"(one of {sorted(SPEC_FAMILIES) + ['choice', 'pchoice']})")
    fn, arities = entry
    args = node.get("args", [])
    if not isinstance(args, list) or len(args) not in arities:
        raise SpaceSpecError(
            f"param {label!r}: {fam} takes {' or '.join(map(str, arities))} "
            f"args, got {args!r}")
    try:
        return fn(label, *[float(a) for a in args])
    except (TypeError, ValueError) as e:
        raise SpaceSpecError(f"param {label!r}: {e}") from None


def _option(label, opt):
    """A choice option: a scalar literal or a nested sub-space mapping."""
    if isinstance(opt, dict):
        if "dist" in opt:
            raise SpaceSpecError(
                f"param {label!r}: a bare distribution cannot be a choice "
                "option — wrap it in a labeled sub-space mapping")
        return space_from_spec(opt)
    if isinstance(opt, (int, float, str, bool)) or opt is None:
        return opt
    raise SpaceSpecError(
        f"param {label!r}: option {opt!r} is neither a scalar nor a "
        "sub-space mapping")


def space_from_spec(spec):
    """Rebuild an ``hp`` space from its JSON-wire form (see module
    docstring).  ``spec`` is a ``{label: node}`` mapping."""
    if not isinstance(spec, dict) or not spec:
        raise SpaceSpecError(f"space spec must be a non-empty mapping, "
                             f"got {spec!r}")
    return {label: _node_from_spec(label, node)
            for label, node in spec.items()}
