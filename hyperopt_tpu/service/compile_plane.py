"""Cold-start compile plane (ISSUE 14): warming admission, background
compilation, and the census-driven ahead-of-time kernel bank.

PAPER.md names the fused TPE tell+ask program as THE hot path — but for
a serving fleet the p99 story is not the warm kernel, it is the XLA
compile every new (space signature, TPE cfg, capacity bucket) cohort key
pays ON the serving path, blocking the wave the new study joins.  This
module moves that compile off-thread and, across restarts, off the
request path entirely:

* **Warming state** — :meth:`CompilePlane.ready_for` answers "is this
  cohort's program compiled for these shapes?" without ever compiling;
  a miss enqueues a background compile job and the scheduler serves the
  cohort's asks host-side via ``rand.suggest`` (flagged ``warming`` in
  the response; ``algo:"rand"`` in the WAL, so crash-resume and shard
  migration replay the warming run bit-identically — the degrade
  ladder's rand floor already proved this exact path end-to-end).  At
  the first wave after the program lands the cohort serves on-device
  and its studies are PROMOTED.

* **Background compilation** — one daemon thread drains the job queue:
  build the cohort program (``tpe.build_suggest_batched`` /
  ``_wide``), then run one dummy tick at the exact input shapes and
  dtypes so the jit's executable cache (and the persistent
  ``HYPEROPT_TPU_COMPILE_CACHE`` on disk) is populated before any real
  ask needs it.  A failing compile is counted and dropped — the plane
  must never wedge the queue, and the affected cohort keeps serving at
  the rand floor.

* **AOT kernel bank** — a space-signature census
  (:class:`SignatureCensus`, JSONL next to the WAL under the store
  root) journals what users actually ask for: one record per cohort key
  at pow2 count milestones, torn-line tolerant, O_APPEND so every fleet
  replica shares one file.  At server start
  :meth:`CompilePlane.warm_from_census` replays it — the top-N keys
  (``HYPEROPT_TPU_COMPILE_BANK_TOP_N``) compile synchronously BEFORE
  the listener opens, the rest in the background — so a restarted
  service greets its returning spaces with warm programs (near-instant
  when ``HYPEROPT_TPU_COMPILE_CACHE`` persists the XLA executables).

Readiness is tracked as (program LRU key, rows-bucket) pairs validated
against ``tpe.cohort_cache_contains`` — an LRU eviction demotes the key
back to warming instead of letting the next tick compile synchronously.
The plane is wholly opt-in (``HYPEROPT_TPU_COMPILE_PLANE``); disarmed,
no thread starts and the scheduler path is byte-identical to
pre-ISSUE-14.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from collections import deque

import numpy as np

from ..obs.metrics import get_metrics
__all__ = ["CompilePlane", "SignatureCensus", "census_path_for"]

logger = logging.getLogger(__name__)

#: census file name under a store root (next to the WAL)
CENSUS_BASENAME = "compile_census.jsonl"

#: append a census record when a key's in-process tick count crosses one
#: of these (bounded appends; the read side max-aggregates per key)
_MILESTONES = frozenset({1, 8, 64, 512, 4096, 32768})


def census_path_for(store_root):
    """The default census location for a scheduler persisting into
    ``store_root`` (shared by every fleet replica on that root)."""
    return os.path.join(str(store_root), CENSUS_BASENAME)


class SignatureCensus:
    """Durable space-signature census: which cohort keys this service
    actually compiles for, with approximate traffic counts.  Append-only
    JSONL via ``O_APPEND`` single-line writes (fleet replicas share the
    file; torn lines are skipped by ``iter_jsonl``).  Best-effort on the
    write side — a census I/O failure costs future warm-start quality,
    never a request."""

    def __init__(self, path):
        self.path = str(path)
        self._counts = {}  # key_id -> in-process tick count
        self._lock = threading.Lock()
        self._warned = False

    @staticmethod
    def key_id(spec, cfg, cap):
        """Canonical identity of one bankable cohort class: the wire
        space spec, the TPE cfg and the capacity bucket.  S and B are
        deliberately OUT of the identity — they drift with live load;
        the census records the latest observed shape instead."""
        return json.dumps([spec, sorted(cfg.items()), int(cap)],
                          sort_keys=True, separators=(",", ":"))

    def note(self, spec, cfg, cap, S, B, widen=False, kid=None):
        """Count one cohort tick for a key; journal at milestones.
        ``spec`` is the study's wire space schema (or zoo wrapper) —
        ``None`` (a direct-API study that never crossed the wire) is
        uncountable and skipped: the bank could never rebuild it.
        ``kid`` is the precomputed :meth:`key_id` — callers on the wave
        hot path cache it per cohort so the per-tick cost is one dict
        increment, not a JSON serialization of the whole space spec."""
        if not isinstance(spec, dict):
            return
        if kid is None:
            kid = self.key_id(spec, cfg, cap)
        with self._lock:
            n = self._counts.get(kid, 0) + 1
            self._counts[kid] = n
            if n in _MILESTONES:
                self._append({
                    "kind": "census", "spec": spec, "cfg": dict(cfg),
                    "cap": int(cap), "S": int(S), "B": int(B),
                    "widen": bool(widen), "count": n, "ts": time.time()})

    def _append(self, rec):
        from . import integrity

        # sealed like every WAL line (ISSUE 15) so scrub verifies the
        # census too; best-effort on ANY OSError — ENOSPC included — a
        # full disk must cost warm-start quality, never a request (and
        # never a slot of the shed budget)
        line = (integrity.seal(rec) + "\n").encode()
        try:
            fd = os.open(self.path,
                         os.O_WRONLY | os.O_CREAT | os.O_APPEND, 0o644)
            try:
                os.write(fd, line)
            finally:
                os.close(fd)
        except OSError as e:
            if not self._warned:
                self._warned = True
                logger.warning("census: cannot append to %s (%s); "
                               "kernel-bank warm starts degrade",
                               self.path, e)

    def read(self):
        """Aggregate the on-disk census: one entry per key with the MAX
        recorded count (milestone appends are monotonic) and the latest
        recorded shape, sorted most-used first."""
        best = {}
        if os.path.exists(self.path):
            from . import integrity

            for chk in integrity.iter_checked_jsonl(self.path):
                if chk.status == integrity.CORRUPT:
                    # a bit-flipped census record only costs one bank
                    # candidate — skip it loudly, never fail a warm-up
                    logger.warning("census: %s:%d corrupt record "
                                   "skipped", self.path, chk.lineno)
                    continue
                if chk.rec is None:
                    continue
                rec = chk.rec
                if rec.get("kind") != "census":
                    continue
                spec, cfg = rec.get("spec"), rec.get("cfg")
                if not isinstance(spec, dict) or not isinstance(cfg, dict):
                    continue
                try:
                    kid = self.key_id(spec, cfg, rec.get("cap", 0))
                except TypeError:
                    continue
                cur = best.get(kid)
                if cur is None or rec.get("count", 0) >= cur.get("count", 0):
                    best[kid] = rec
        return sorted(best.values(),
                      key=lambda r: (-int(r.get("count", 0)),
                                     -float(r.get("ts", 0.0))))


class _Job:
    """One background compile: everything needed to build the program and
    run a dummy tick at the exact shapes.  ``space`` is a built hp space
    (live cohorts pass their CompiledSpace's source via the study) or a
    wire spec dict (census jobs rebuild it lazily on the worker)."""

    __slots__ = ("key", "cs", "spec", "cfg", "S", "cap", "B", "donate",
                 "mesh", "widen", "source")

    def __init__(self, key, cs, spec, cfg, S, cap, B, donate, mesh,
                 widen, source):
        self.key = key
        self.cs = cs
        self.spec = spec
        self.cfg = dict(cfg)
        self.S = int(S)
        self.cap = int(cap)
        self.B = int(B)
        self.donate = bool(donate)
        self.mesh = mesh
        self.widen = bool(widen)
        self.source = source  # "live" | "bank" | "growth"


def _space_from_wire(spec):
    """Rebuild an hp space from a census record's spec wrapper — the same
    forms the WAL admit record uses."""
    if "zoo" in spec:
        from ..zoo import ZOO

        rec = ZOO.get(str(spec["zoo"]))
        return rec.space if rec is not None else None
    if "space" in spec:
        from .spacespec import space_from_spec

        return space_from_spec(spec["space"])
    return None


class CompilePlane:
    """The process's compile machinery: readiness probes, the background
    compile thread, and the census-driven bank.  One instance per server
    process (fleet mode shares it across every shard's scheduler via
    ``scheduler_kwargs``); direct :class:`StudyScheduler` use builds one
    per scheduler when ``HYPEROPT_TPU_COMPILE_PLANE`` arms it."""

    def __init__(self, census_path=None, metrics=None):
        from .._env import enable_persistent_compilation_cache

        # the bank's restart story rides the persistent XLA cache: arm
        # it here so serving processes get it without an fmin entry point
        enable_persistent_compilation_cache()
        self.census = (SignatureCensus(census_path)
                       if census_path else None)
        self.metrics = metrics if metrics is not None else get_metrics(
            "service")
        self._cond = threading.Condition()
        self._queue = deque()
        self._queued = set()   # keys in the queue (dedupe)
        self._ready = {}       # program key -> set of ready rows-buckets
        self._bank_keys = set()    # keys warmed from the census
        self._bank_hit_keys = set()  # bank keys that served live traffic
        self._thread = None
        self._stopped = False
        self.compiled = 0
        self.errors = 0

    # -- readiness ---------------------------------------------------------

    def _is_ready(self, key, K):
        from ..algos import tpe

        buckets = self._ready.get(key)
        if buckets is None or K not in buckets:
            return False
        if not tpe.cohort_cache_contains(key):
            # LRU eviction demoted the program: forget it so the next
            # probe re-enqueues instead of the tick compiling inline
            self._ready.pop(key, None)
            return False
        return True

    def mark_ready(self, key, K=1):
        """Record that (program, rows-bucket) is compiled — called by the
        worker after a dummy tick, and by the scheduler after any
        successful live device tick (live traffic warms keys the plane
        never compiled itself)."""
        with self._cond:
            self._ready.setdefault(key, set()).add(int(K))

    def ready_for(self, key, K, job=None, job_factory=None):
        """True when the program behind ``key`` is compiled for rows
        bucket ``K``.  On a miss, ``job`` (a prepared :class:`_Job`) —
        or ``job_factory()`` , built LAZILY so the steady-state ready
        path never pays job construction — is enqueued for the
        background thread and the caller serves the cohort at the rand
        floor (warming)."""
        with self._cond:
            if self._is_ready(key, K):
                if key in self._bank_keys and key not in self._bank_hit_keys:
                    self._bank_hit_keys.add(key)
                    self.metrics.counter("service.compile.bank.hits").inc()
                return True
            if job is None and job_factory is not None \
                    and key not in self._queued:
                job = job_factory()
            if job is not None and key not in self._queued:
                self._queue.append(job)
                self._queued.add(key)
                # the gauge counts OUTSTANDING work (queued + in-flight:
                # _queued keeps a popped job's key until its finally) —
                # "queue 0" must mean "nothing still compiling"
                self.metrics.gauge("service.compile.queue_depth").set(
                    len(self._queued))
                self.metrics.counter("service.compile.enqueued").inc()
                self._cond.notify()
                self._ensure_thread()
            return False

    def make_job(self, cs, spec, cfg, S, cap, B, donate, mesh=None,
                 widen=False, source="live"):
        """Build the (key, job) pair for one cohort shape — the single
        place the plane derives program keys, shared by the live probe
        path and the census bank."""
        from ..algos import tpe

        if widen:
            prof = tpe.widened_profile(cs)
            if prof is None:
                widen = False
        if widen:
            key = tpe.cohort_key_wide(prof[0], cfg, S, cap, B,
                                      donate=donate)
        else:
            # resolve the EFFECTIVE storage name exactly like _Cohort
            # does (int8/fp8 → itself when codable, else bf16), so the
            # plane warms the program the scheduler will actually ask for
            from .. import quant
            from .._env import parse_hist_dtype

            hd = quant.resolve(cs, parse_hist_dtype(),
                               context="cohort")[0]
            key = tpe.cohort_key(cs, cfg, S, cap, B, donate=donate,
                                 mesh=mesh, hist_dtype=hd)
        return key, _Job(key, cs, spec, cfg, S, cap, B, donate, mesh,
                         widen, source)

    # -- the background worker ---------------------------------------------

    def _ensure_thread(self):
        if self._thread is None or not self._thread.is_alive():
            if self._stopped:
                return
            if not self._atexit_armed:
                # a daemon thread killed MID-XLA at interpreter teardown
                # aborts the process ("terminate called without an
                # active exception"); stop + bounded join beats that
                self._atexit_armed = True
                import atexit

                atexit.register(self.stop)
            self._thread = threading.Thread(
                target=self._loop, name="hyperopt-compile-plane",
                daemon=True)
            self._thread.start()

    _atexit_armed = False

    def _loop(self):
        while True:
            with self._cond:
                while not self._queue and not self._stopped:
                    self._cond.wait(timeout=1.0)
                if self._stopped:
                    return
                job = self._queue.popleft()
            try:
                self._compile(job)
            except Exception as e:  # noqa: BLE001 - never wedge the queue
                self.errors += 1
                self.metrics.counter("service.compile.errors").inc()
                logger.warning("compile plane: job for %r failed: %s",
                               job.key[:2], e)
            finally:
                with self._cond:
                    self._queued.discard(job.key)
                    self.metrics.gauge("service.compile.queue_depth").set(
                        len(self._queued))
                    self._cond.notify_all()  # drain() waiters

    def _compile(self, job):
        """Build the program and run ONE dummy tick at the exact shapes
        (K=1 rows bucket), so the jit's executable cache — and the
        persistent on-disk cache — hold it before any real ask does."""
        from .._env import parse_hist_dtype
        from ..algos import tpe

        import jax.numpy as jnp

        t0 = time.perf_counter()
        cs = job.cs
        if cs is None:
            space = _space_from_wire(job.spec or {})
            if space is None:
                return  # unresumable census entry: nothing to warm
            from ..base import Domain

            cs = Domain(None, space).cs
        S, cap, B = job.S, job.cap, job.B
        L = len(cs.labels)
        # the dummy stack's leaf dtypes must MATCH the live cohort's
        # exactly (an int8/fp8 mirror retraces the jit per dtype): same
        # resolve as _Cohort — quant vals + bf16 losses when armed, the
        # plain float name otherwise
        from .. import quant

        hd, qp = quant.resolve(cs, parse_hist_dtype(), context="cohort")
        vdt = (quant.vals_dtype(hd) if quant.is_quant_name(hd)
               else jnp.dtype(hd))
        ldt = quant.losses_dtype(hd)
        wparams = None
        if job.widen:
            profile, slots = tpe.widened_profile(cs)
            W = sum(e[-1] for e in profile)
            fn = tpe.build_suggest_batched_wide(profile, job.cfg, S, cap,
                                                B, donate=job.donate)
            hist = {
                "vals": jnp.zeros((S, W, cap), vdt),
                "active": jnp.zeros((S, W, cap), bool),
                "losses": jnp.full((S, cap), jnp.inf, ldt),
                "has_loss": jnp.zeros((S, cap), bool),
            }
            rows = np.zeros((S, 1, 2 * W + 3), np.float32)
            rows[:, :, 2 * W + 2] = float(cap)  # no-op scatter row
            wparams = tuple(
                {k: jnp.asarray(v) for k, v in gp.items()}
                for gp in tpe.widened_params(cs, profile, slots,
                                             qparams=qp))
        else:
            fn = tpe.build_suggest_batched(cs, job.cfg, S, cap, B,
                                           donate=job.donate,
                                           mesh=job.mesh, hist_dtype=hd)
            hist = {
                "vals": {l: jnp.zeros((S, cap), vdt) for l in cs.labels},
                "active": {l: jnp.zeros((S, cap), bool)
                           for l in cs.labels},
                "losses": jnp.full((S, cap), jnp.inf, ldt),
                "has_loss": jnp.zeros((S, cap), bool),
            }
            rows = np.zeros((S, 1, 2 * L + 3), np.float32)
            rows[:, :, 2 * L + 2] = float(cap)
        seed_words = np.zeros((S, 2), np.uint32)
        ids = np.zeros((S, B), np.uint32)
        args = (hist, rows, seed_words, ids)
        if wparams is not None:
            args = args + (wparams,)
        out = fn(*args)
        # block so "compiled" means COMPILED, not dispatched
        import jax

        jax.block_until_ready(out[1])
        self.mark_ready(job.key, K=1)
        self.compiled += 1
        dt_s = time.perf_counter() - t0
        self.metrics.counter("service.compile.compiled_total").inc()
        self.metrics.histogram("service.compile.compile_sec").observe(dt_s)
        if job.source == "bank":
            with self._cond:
                self._bank_keys.add(job.key)

    # -- the census bank ---------------------------------------------------

    def census_note(self, spec, cfg, cap, S, B, widen=False, kid=None):
        if self.census is not None:
            self.census.note(spec, cfg, cap, S, B, widen=widen, kid=kid)

    def warm_from_census(self, top_n=None, donate=None, widen=False):
        """Replay the census into warm programs: the ``top_n``
        most-counted keys compile synchronously ON THIS THREAD (the
        pre-listener phase — a server calls this before binding so its
        first requests meet warm programs), the rest enqueue for the
        background thread.  Returns ``(warmed_sync, enqueued)``.

        ``donate`` defaults to the LIVE path's donation mode
        (``tpe._donation_enabled()``): the program key includes the
        donate flag, so a hardcoded value here would warm keys the
        serving probe never asks for whenever HYPEROPT_TPU_NO_DONATION
        is set — wasted pre-listener compile time AND a cold restart."""
        from .._env import parse_compile_bank_top_n
        from ..algos import tpe

        if self.census is None:
            return 0, 0
        if donate is None:
            donate = tpe._donation_enabled()
        if top_n is None:
            top_n = parse_compile_bank_top_n()
        entries = self.census.read()
        warmed = enqueued = 0
        for i, rec in enumerate(entries):
            spec = rec.get("spec")
            space = _space_from_wire(spec or {})
            if space is None:
                continue
            from ..base import Domain

            cs = Domain(None, space).cs
            cfg = rec.get("cfg") or {}
            try:
                key, job = self.make_job(
                    cs, spec, cfg, rec.get("S", 1), rec.get("cap", 16),
                    rec.get("B", 1), donate,
                    widen=bool(rec.get("widen", widen)), source="bank")
            except Exception:  # noqa: BLE001 - hostile census entry
                continue
            with self._cond:
                self._bank_keys.add(key)
                already = self._is_ready(key, 1)
            if already:
                continue
            if i < top_n:
                try:
                    self._compile(job)
                    warmed += 1
                except Exception as e:  # noqa: BLE001
                    self.errors += 1
                    logger.warning("kernel bank: sync warm failed: %s", e)
            else:
                self.ready_for(key, 1, job=job)  # enqueues
                enqueued += 1
        self.metrics.gauge("service.compile.bank.keys").set(
            len(self._bank_keys))
        return warmed, enqueued

    # -- observability / lifecycle -----------------------------------------

    def publish(self):
        """Refresh the plane's gauges (called at scrape/snapshot time) and
        return the status dict the ``/snapshot`` compile section embeds."""
        with self._cond:
            depth = len(self._queued)
            ready = sum(len(v) for v in self._ready.values())
            bank_keys = len(self._bank_keys)
            bank_hits = len(self._bank_hit_keys)
        g = self.metrics.gauge
        g("service.compile.queue_depth").set(depth)
        g("service.compile.ready_programs").set(ready)
        g("service.compile.bank.keys").set(bank_keys)
        return {
            "queue_depth": depth,
            "ready_programs": ready,
            "compiled": self.compiled,
            "errors": self.errors,
            "bank_keys": bank_keys,
            "bank_hits": bank_hits,
            "census_path": (self.census.path
                            if self.census is not None else None),
        }

    def queue_depth(self):
        """Outstanding compiles: enqueued + in-flight."""
        with self._cond:
            return len(self._queued)

    def bank_stats(self):
        with self._cond:
            return {"keys": len(self._bank_keys),
                    "hits": len(self._bank_hit_keys)}

    def drain(self, timeout=60.0):
        """Block until the queue empties (tests and the bench stage)."""
        deadline = time.monotonic() + float(timeout)
        with self._cond:
            while (self._queue or self._queued) and \
                    time.monotonic() < deadline:
                self._cond.wait(timeout=0.05)
            return not (self._queue or self._queued)

    def stop(self, timeout=30.0):
        """Stop the worker and join it (bounded — an in-flight compile
        finishes first; letting teardown kill the thread inside XLA
        aborts the whole process).  Idempotent."""
        with self._cond:
            self._stopped = True
            self._cond.notify_all()
        t = self._thread
        if t is not None and t is not threading.current_thread():
            t.join(timeout=timeout)
