"""Retry-aware HTTP client for the ask/tell service.

The smoke scripts and tests used to drive the server with ad-hoc
``urllib`` calls and bare ``time.sleep`` loops; every harness
re-invented (differently) what to do about a 429, a draining 503 or a
connection reset.  This helper wires :class:`~hyperopt_tpu.retry.RetryPolicy`
into one place:

* **Retryable**: 429, 503 and 507 responses (honoring the server's
  ``Retry-After`` as a FLOOR under the policy's jittered exponential
  backoff — ``RetryPolicy.delay_after``; 507 is the ISSUE-15
  store-full shed — the disk is compacting/GCing and recovers),
  connection-level failures (refused / reset / timeout — the
  crash-restart window the WAL resume gate drives traffic through).
* **Not retryable**: every other status.  A 409 on ``tell`` deserves a
  special note: it means "already told" — for a client retrying a tell
  whose RESPONSE was lost, that is success, and :meth:`tell` reports it
  as such (``duplicate=True``) instead of raising.
* **Deterministic**: backoff jitter comes from the policy's
  ``(key, attempt)`` scheme — two clients hammering a shed server
  spread out, and tests replay exact schedules with an injected
  ``sleep``.

``ServiceClient`` is deliberately tiny — a serving-protocol helper for
harnesses, not an SDK.
"""

from __future__ import annotations

import http.client
import json
import threading
import time
import urllib.error
import urllib.request

from ..obs import reqtrace
from ..obs.trace import Tracer
from ..retry import RetryPolicy

__all__ = ["ServiceClient", "ServiceUnavailable"]

#: client attempt spans feed the process flight ring (sink-less tracer):
#: the client half of the request-trace arc, visible in postmortems
_tracer = Tracer()


class ServiceUnavailable(RuntimeError):
    """Retries exhausted against a shedding/unreachable server; carries
    the last status code (or None for connection-level failures)."""

    def __init__(self, message, status=None):
        super().__init__(message)
        self.status = status


#: connection-level failures worth retrying: the server restarting
#: (refused), dying mid-response (reset/aborted — a SIGKILL between
#: the status line and the body surfaces as IncompleteRead/
#: BadStatusLine, i.e. http.client.HTTPException), or wedged
#: (timeout).  Retrying a possibly-served ask is safe: the per-ask
#: idempotency token answers the original trials (ISSUE 12).
_CONN_ERRORS = (ConnectionError, TimeoutError, urllib.error.URLError,
                OSError, http.client.HTTPException)


class ServiceClient:
    """One service endpoint + one retry policy.  ``retry`` coerces like
    every other retry knob in the repo (None/int/policy); the default
    absorbs a server restart (5 retries, 0.2s base ≈ 6s worst case).

    Fleet-aware (ISSUE 12): ``url`` may be a LIST of replica addresses —
    the first is the primary, the rest are failover seeds rotated to on
    connection-level errors.  A 307 answer (the study's shard is owned
    by another replica) is followed to its ``location`` with a bounded
    hop count (``max_hops``); the resolved owner is cached per study so
    steady-state traffic goes straight to the right replica.  A hop
    budget exhausted (redirect loop / stale ownership table) — or a
    retryable status from a cached route — drops the cache entry and
    degrades to plain retry-with-backoff from the seed list, so routing
    staleness is never worse than a 429."""

    #: bound on 307 redirects followed within one attempt: a loop or a
    #: stale-table ping-pong degrades to backoff instead of spinning
    max_hops = 4

    def __init__(self, url, retry=None, timeout=60.0, deadline_ms=None,
                 sleep=time.sleep, key=0, trace=None, headers=None,
                 tenant=None):
        from .._env import parse_reqtrace
        from ..obs.tenant import ANON, sanitize_tenant

        urls = [url] if isinstance(url, str) else list(url)
        self.urls = [str(u).rstrip("/") for u in urls]
        # static extra headers on EVERY request (the blackbox prober
        # stamps ``x-probe: 1`` so canary traffic stays out of the
        # server-side tenant SLO objectives); attempt-scoped headers
        # (traceparent) still layer on top
        self.headers = dict(headers or {})
        # tenant identity (ISSUE 20): sanitized client-side (same rules
        # the server enforces — fail fast at construction, not per
        # request) and stamped on EVERY request via the static headers,
        # so mid-study traffic (ask/tell/close), retries and 307 fleet
        # redirects all attribute to the same principal.  "anon" sends
        # no header — the wire stays byte-identical to pre-ISSUE-20.
        self.tenant = sanitize_tenant(tenant)
        if self.tenant != ANON:
            self.headers.setdefault("x-tenant", self.tenant)
        self.retry = (RetryPolicy(max_retries=5, base_delay=0.2,
                                  max_delay=5.0)
                      if retry is None else RetryPolicy.coerce(retry))
        self.timeout = float(timeout)
        self.deadline_ms = deadline_ms
        self._sleep = sleep
        self._key = key
        self.retries = 0  # total backoffs taken (harness assertions)
        self.redirects = 0  # total 307 hops followed (harness assertions)
        self._routes = {}  # study_id -> owning replica base URL (fleet)
        # request tracing (ISSUE 11): ONE trace id per logical request —
        # every RetryPolicy attempt reuses it with a FRESH span id, so
        # the server (and the WAL) can tie a client's retries together
        self.trace_enabled = (parse_reqtrace() if trace is None
                              else bool(trace))
        # per-THREAD request-trace state: a shared client may serve
        # concurrent request() calls, and instance-level attempt headers
        # would cross-attribute traces between threads (the pre-trace
        # client built headers from immutable config only)
        self._tls = threading.local()

    # trace id of the calling thread's last logical request, and its
    # per-attempt span ids (harness assertions read these from the same
    # thread that issued the request)
    @property
    def last_trace(self):
        return getattr(self._tls, "last_trace", None)

    @last_trace.setter
    def last_trace(self, v):
        self._tls.last_trace = v

    @property
    def last_spans(self):
        if not hasattr(self._tls, "last_spans"):
            self._tls.last_spans = []
        return self._tls.last_spans

    @last_spans.setter
    def last_spans(self, v):
        self._tls.last_spans = v

    @property
    def _attempt_headers(self):
        return getattr(self._tls, "attempt_headers", None)

    @_attempt_headers.setter
    def _attempt_headers(self, v):
        self._tls.attempt_headers = v

    @property
    def url(self):
        """The attempt-scoped base URL (thread-local, set by
        :meth:`request` for redirect-following and seed rotation);
        outside a request, the primary seed."""
        return getattr(self._tls, "base", None) or self.urls[0]

    @url.setter
    def url(self, v):
        # back-compat: harnesses that retarget a client mid-test
        # (`client.url = new_url`) replace the whole seed list
        self.urls = [str(v).rstrip("/")]
        self._routes.clear()
        self._tls.base = None

    # -- transport ---------------------------------------------------------

    def _once(self, method, path, body):
        """One HTTP exchange → ``(status, payload, retry_after)``.
        Attempt-scoped headers (the ``traceparent`` of THIS attempt)
        ride in ``self._attempt_headers`` — the signature stays what
        every harness that monkeypatches ``_once`` expects."""
        headers = {"Content-Type": "application/json"}
        if self.headers:
            headers.update(self.headers)
        if self.deadline_ms is not None:
            headers["X-Deadline-Ms"] = str(self.deadline_ms)
        if self._attempt_headers:
            headers.update(self._attempt_headers)
        data = (json.dumps(body).encode()
                if method == "POST" else None)
        req = urllib.request.Request(self.url + path, data=data,
                                     headers=headers, method=method)
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as r:
                return r.status, json.loads(r.read()), None
        except urllib.error.HTTPError as e:
            retry_after = e.headers.get("Retry-After")
            try:
                payload = json.loads(e.read())
            except ValueError:
                payload = {"ok": False, "error": f"HTTP {e.code}"}
            return e.code, payload, retry_after

    def request(self, method, path, body=None,
                retryable=(429, 503, 507)):
        """One logical request with retry/backoff.  Returns
        ``(status, payload)`` for any non-retryable answer; raises
        :class:`ServiceUnavailable` when retries run out.  With tracing
        armed, all attempts share one trace id (fresh span id each) and
        the attempt span + ``traceparent`` header carry it.

        Fleet routing: the attempt base starts from the study's cached
        owner (else the seed list); a 307 answer re-issues at its
        ``location`` immediately (no backoff, no retry consumed, at most
        ``max_hops`` per attempt — past that the redirect is treated as
        retryable).  Connection-level failures rotate to the next seed
        URL and drop the study's cached route (the owner may have
        died — the survivor's table answers the next 307)."""
        body = body or {}
        sid = body.get("study_id") if isinstance(body, dict) else None
        last_status, last_err = None, None
        attempt = 0
        hops = 0
        seed_i = 0
        base = self._routes.get(sid) if sid is not None else None
        first = True
        root = reqtrace.mint() if self.trace_enabled else None
        if root is not None:
            self.last_trace = root.trace_id
            self.last_spans = []
        while True:
            ctx = None
            self._attempt_headers = None
            self._tls.base = base or self.urls[seed_i % len(self.urls)]
            if root is not None:
                # fresh span per ATTEMPT (and per redirect hop) under
                # the one logical trace
                ctx = (root if first else reqtrace.child(root))
                self.last_spans.append(ctx.span_id)
                self._attempt_headers = {
                    "traceparent": ctx.traceparent()}
            first = False
            try:
                if ctx is not None:
                    with _tracer.span("client.request",
                                      trace=ctx.trace_id,
                                      span=ctx.span_id, attempt=attempt,
                                      path=path):
                        status, payload, retry_after = self._once(
                            method, path, body)
                else:
                    status, payload, retry_after = self._once(
                        method, path, body)
            except _CONN_ERRORS as e:
                status, payload, retry_after = None, None, None
                last_err = e
                # this base is unreachable: forget any cached route
                # through it and rotate to the next seed
                if sid is not None:
                    self._routes.pop(sid, None)
                base = None
                seed_i += 1
            if (status == 307 and isinstance(payload, dict)
                    and payload.get("location")):
                hops += 1
                self.redirects += 1
                if hops <= self.max_hops:
                    base = str(payload["location"]).rstrip("/")
                    if sid is not None:
                        self._routes[sid] = base
                    continue  # immediate re-issue: no backoff consumed
                # hop budget exhausted: a redirect loop or a stale
                # ownership table — degrade to plain backoff from seeds
                if sid is not None:
                    self._routes.pop(sid, None)
                base = None
                hops = 0
            elif status is not None and status not in retryable:
                return status, payload
            elif status is not None:
                # retryable answer: drop any cached route (the shard may
                # be mid-migration; a seed will 307 to the new owner)
                # and rotate to the next seed — a draining/overloaded
                # replica must not eat the whole retry budget while a
                # healthy peer could serve (sid-less /study included)
                if sid is not None:
                    self._routes.pop(sid, None)
                if base is None:
                    seed_i += 1
                base = None
            last_status = status if status is not None else last_status
            if not self.retry.retries_left(attempt + 1):
                raise ServiceUnavailable(
                    f"{method} {path}: retries exhausted "
                    f"(last status {last_status}, last error {last_err})",
                    status=last_status)
            # the JSON payload carries the precise hint; the header is
            # RFC delta-seconds (integer, rounded up) — prefer precise
            if isinstance(payload, dict) \
                    and payload.get("retry_after") is not None:
                retry_after = payload["retry_after"]
            floor = 0.0
            if retry_after is not None:
                try:
                    floor = float(retry_after)
                except (TypeError, ValueError):
                    pass
            self._sleep(self.retry.delay_after(
                attempt, key=f"{self._key}:{path}", floor=floor))
            self.retries += 1
            attempt += 1
            hops = 0

    # -- protocol helpers --------------------------------------------------

    def create_study(self, space=None, zoo=None, **kwargs):
        body = dict(kwargs)
        if space is not None:
            body["space"] = space
        if zoo is not None:
            body["zoo"] = zoo
        if self.tenant != "anon":
            # explicit in the body too (the header already rides): the
            # admit record's tenant must survive any proxy that strips
            # unknown request headers
            body.setdefault("tenant", self.tenant)
        status, payload = self.request("POST", "/study", body)
        if status != 200:
            raise ServiceUnavailable(
                f"/study failed: {payload.get('error')}", status=status)
        return payload["study_id"]

    def ask(self, study_id, n=1):
        """Returns the response payload's ``trials`` list (each entry
        carries ``degraded``/``algo`` flags when the ladder served it).

        Every logical ask carries a fresh idempotency token (``req``):
        if the response is lost (server crash after the ask became
        durable, dropped connection, a 307 mid-migration) the retry
        answers the ORIGINAL trials instead of burning a new seed draw
        — without it, a retried ask would silently fork the study's
        proposal stream from its deterministic reference."""
        import os as _os

        status, payload = self.request(
            "POST", "/ask", {"study_id": study_id, "n": n,
                             "req": _os.urandom(8).hex()})
        if status != 200:
            raise ServiceUnavailable(
                f"/ask failed: {payload.get('error')}", status=status)
        return payload["trials"]

    def tell(self, study_id, tid, loss=None, status=None):
        """Returns ``{"duplicate": bool}`` — a 409 from a RETRIED tell
        means the first attempt landed and its response was lost, which
        is success, not an error."""
        code, payload = self.request(
            "POST", "/tell",
            {"study_id": study_id, "tid": tid, "loss": loss,
             "status": status})
        if code == 409:
            return {"duplicate": True}
        if code != 200:
            raise ServiceUnavailable(
                f"/tell failed: {payload.get('error')}", status=code)
        return {"duplicate": False}

    def close_study(self, study_id):
        status, payload = self.request("POST", "/close",
                                       {"study_id": study_id})
        return status == 200

    def studies(self):
        status, payload = self.request("GET", "/studies")
        if status != 200:
            raise ServiceUnavailable("/studies failed", status=status)
        return payload
