"""Overload control for the ask/tell service (ISSUE 10): request
deadlines, a bounded admission queue with load-shedding, and the
device-fault degrade ladder's policy object.

Design (DESIGN.md §17):

* **Deadlines are monotonic.**  A request may carry ``X-Deadline-Ms``;
  the server clamps it to its own default
  (``HYPEROPT_TPU_SERVICE_DEADLINE_MS``).  The deadline is stamped once
  at ingress against ``time.monotonic()`` and checked at every wait
  point — an NTP step or suspend never extends (or collapses) a
  request's budget.  An expired ask answers 429 with ``Retry-After``
  (the work was never started; retrying later is exactly right).

* **Bounded admission, shed don't queue.**  At most
  ``HYPEROPT_TPU_SERVICE_QUEUE`` asks may be admitted (waiting for a
  wave or inside one).  Past the bound the server answers 429
  immediately instead of building an unbounded latency queue — the
  overloaded state costs each shed client one cheap round trip, and
  the served ``study_ask_p99_ms`` stays bounded (the overload pin).

* **Sheds /ask before /tell.**  Tells are cheap (a dict update + one
  journal line) and PRESERVE state — shedding a tell loses a client's
  finished work, shedding an ask loses nothing.  The breaker therefore
  gives tells 4x the ask bound, so a saturated service drains results
  while refusing new work.

* **Retry-After is measured, not guessed.**  A live EWMA of wave
  latency (updated by the scheduler after every cohort wave) sizes the
  hint: ``excess waves x wave EWMA``, floored at 50ms — clients built
  on :mod:`hyperopt_tpu.service.client` honor it with deterministic
  jittered backoff.

Everything here is pure policy over an injectable monotonic clock, so
tier-1 tests drive it with a fake clock; the scheduler/server own the
actual waiting.
"""

from __future__ import annotations

import threading
import time

__all__ = ["Deadline", "OverloadError", "DeadlineExceeded",
           "StoreFullShed", "AdmissionGuard", "DegradeLadder",
           "LADDER_LEVELS", "NonFiniteProposal", "is_device_fault"]


class OverloadError(RuntimeError):
    """Load shed (HTTP 429 + ``Retry-After: retry_after`` seconds)."""

    def __init__(self, message, retry_after=0.05):
        super().__init__(message)
        self.retry_after = float(retry_after)


class DeadlineExceeded(OverloadError):
    """The request's deadline expired before (or while) serving it.
    Subclasses :class:`OverloadError` so the HTTP mapping (429 +
    ``Retry-After``) rides along — the client should come back when the
    service is less loaded, which is the same remedy."""


class StoreFullShed(OverloadError):
    """Ask shed because the store is (or just was) out of disk space
    (ISSUE 15): HTTP **507** + ``Retry-After``.  Distinct from 429 so
    clients and dashboards can tell load pressure from disk pressure;
    retryable either way.  Tells are NOT shed on this state — they
    preserve client work and shed last (the existing 4x policy), only
    a genuinely failing WAL append refuses one (also 507)."""


class Deadline:
    """A monotonic request deadline.  ``None`` budget means no deadline
    (both the header and the server default disabled)."""

    __slots__ = ("t_deadline", "_clock")

    def __init__(self, budget_ms, clock=time.monotonic):
        self._clock = clock
        self.t_deadline = (None if budget_ms is None
                           else clock() + float(budget_ms) / 1e3)

    @classmethod
    def from_request(cls, header_ms, default_ms, clock=time.monotonic):
        """Combine the ``X-Deadline-Ms`` header with the server default:
        the TIGHTER of the two wins (a client may shrink its budget,
        never extend the server's).  An unparseable header is ignored —
        a malformed hint must not turn into an infinite budget."""
        budget = default_ms
        if header_ms is not None:
            try:
                ms = float(header_ms)
                if ms > 0 and (budget is None or ms < budget):
                    budget = ms
            except (TypeError, ValueError):
                pass
        return cls(budget, clock=clock)

    def remaining(self):
        """Seconds left, ``None`` when unbounded (never negative)."""
        if self.t_deadline is None:
            return None
        return max(0.0, self.t_deadline - self._clock())

    def expired(self):
        return (self.t_deadline is not None
                and self._clock() >= self.t_deadline)

    def check(self, what="request"):
        if self.expired():
            raise DeadlineExceeded(f"{what} deadline exceeded")


class AdmissionGuard:
    """Bounded admission queue + shed policy + wave-latency EWMA (module
    docstring).  Thread-safe; the scheduler/server call :meth:`admit_ask`
    / :meth:`admit_tell` at ingress and MUST pair each successful admit
    with :meth:`release` (use ``try/finally``)."""

    #: tells shed only past this multiple of the ask bound
    TELL_SLACK = 4

    def __init__(self, max_queue=None, metrics=None, clock=time.monotonic,
                 tenant_quota=None):
        from .._env import parse_service_queue, parse_tenant_quota

        self.max_queue = (parse_service_queue() if max_queue is None
                          else int(max_queue))
        # per-tenant ask budget (ISSUE 20): at most this many admitted
        # asks PER TENANT, checked before the global bound — a noisy
        # tenant sheds per-tenant 429s while everyone else still admits.
        # None resolves HYPEROPT_TPU_TENANT_QUOTA (default off), False
        # disarms, an int arms.  Entries drop at zero inflight, so the
        # map is bounded by concurrency, not tenant cardinality.
        if tenant_quota is None:
            tenant_quota = parse_tenant_quota()
        self.tenant_quota = (None if not tenant_quota
                             else max(1, int(tenant_quota)))
        self._tenant_inflight = {}
        self._clock = clock
        self._lock = threading.Lock()
        self._inflight = {"ask": 0, "tell": 0}
        self._wave_ewma = None  # seconds; None until the first wave lands
        # store-full shed latch (ISSUE 15): armed by the scheduler when
        # a WAL/store write hit ENOSPC (or the disk watermark tripped);
        # expires after its window so ONE probe request reaches the
        # scheduler and re-tests the disk, re-arming on failure —
        # recovery is automatic when space returns, no operator needed
        self._store_full_until = None
        self._store_full_reason = ""
        self._store_retry_after = 1.0
        self.metrics = metrics

    # -- store-full latch (ISSUE 15) ---------------------------------------

    def set_store_full(self, full, reason="", retry_after=1.0):
        """Arm/disarm the store-full ask shed for one latch window
        (``2 x retry_after``, so shed clients retrying on the hint meet
        an open probe window)."""
        with self._lock:
            if full:
                self._store_full_until = (self._clock()
                                          + 2.0 * float(retry_after))
                self._store_full_reason = str(reason)
                self._store_retry_after = float(retry_after)
            else:
                self._store_full_until = None
            self._gauge("service.store_full",
                        1.0 if full else 0.0)

    def _store_full_locked(self):
        until = self._store_full_until
        if until is None:
            return False
        if self._clock() >= until:
            # latch window over: let the next ask through as the probe
            self._store_full_until = None
            self._gauge("service.store_full", 0.0)
            return False
        return True

    # -- admission ---------------------------------------------------------

    def admit_ask(self, deadline=None, tenant=None):
        """Admit one ask or shed.  Sheds when the queue is full OR when
        the request's remaining deadline cannot cover even the predicted
        wait (``queued waves x wave EWMA``) — refusing up front beats
        burning a wave slot on an answer the client will have abandoned.
        A store-full latch (ISSUE 15) sheds with 507 before either.
        With a ``tenant_quota`` armed (ISSUE 20) a tenant past its own
        budget sheds a PER-TENANT 429 (same measured ``Retry-After``)
        before it can contend for the global queue."""
        with self._lock:
            if self._store_full_locked():
                self._count("service.shed.store_full")
                raise StoreFullShed(
                    f"store full: {self._store_full_reason or 'disk'}"
                    " — retry after space frees",
                    retry_after=self._store_retry_after)
            depth = self._inflight["ask"]
            if self.tenant_quota is not None and tenant is not None:
                t_depth = self._tenant_inflight.get(tenant, 0)
                if t_depth >= self.tenant_quota:
                    self._count("service.shed.tenant")
                    raise OverloadError(
                        f"tenant {tenant!r} over its ask budget "
                        f"({t_depth}/{self.tenant_quota} admitted)",
                        retry_after=self._retry_after_locked(depth))
            if depth >= self.max_queue:
                self._count("service.shed.ask")
                raise OverloadError(
                    f"ask queue full ({depth}/{self.max_queue} admitted)",
                    retry_after=self._retry_after_locked(depth))
            if deadline is not None:
                remaining = deadline.remaining()
                predicted = self._predicted_wait_locked(depth)
                if remaining is not None and predicted > remaining:
                    self._count("service.shed.ask")
                    self._count("service.shed.deadline")
                    raise OverloadError(
                        f"deadline too tight: ~{predicted:.3f}s predicted "
                        f"wait vs {remaining:.3f}s remaining",
                        retry_after=self._retry_after_locked(depth))
            self._inflight["ask"] = depth + 1
            if self.tenant_quota is not None and tenant is not None:
                self._tenant_inflight[tenant] = (
                    self._tenant_inflight.get(tenant, 0) + 1)
            self._gauge("service.queue_depth", depth + 1)
        return "ask"

    def admit_tell(self):
        """Admit one tell; sheds only past ``TELL_SLACK x max_queue`` —
        the breaker keeps the state-preserving path open while asks shed."""
        bound = self.max_queue * self.TELL_SLACK
        with self._lock:
            depth = self._inflight["tell"]
            if depth >= bound:
                self._count("service.shed.tell")
                raise OverloadError(
                    f"tell queue full ({depth}/{bound} admitted)",
                    retry_after=self._retry_after_locked(depth))
            self._inflight["tell"] = depth + 1
        return "tell"

    def release(self, token, tenant=None):
        with self._lock:
            self._inflight[token] = max(0, self._inflight[token] - 1)
            if (token == "ask" and tenant is not None
                    and self.tenant_quota is not None):
                left = self._tenant_inflight.get(tenant, 0) - 1
                if left > 0:
                    self._tenant_inflight[tenant] = left
                else:
                    # drop-at-zero keeps the map bounded by concurrency
                    self._tenant_inflight.pop(tenant, None)
            if token == "ask":
                self._gauge("service.queue_depth", self._inflight["ask"])

    # -- wave latency ------------------------------------------------------

    #: EWMA smoothing for wave latency: ~5-wave memory, so Retry-After
    #: tracks a load swing within a few waves without chasing single
    #: outliers
    ALPHA = 0.3

    def observe_wave(self, sec):
        """The scheduler reports each cohort wave's wall time here."""
        sec = float(sec)
        with self._lock:
            self._wave_ewma = (sec if self._wave_ewma is None
                               else (1 - self.ALPHA) * self._wave_ewma
                               + self.ALPHA * sec)
            self._gauge("service.wave_ewma_sec", self._wave_ewma)

    def wave_ewma(self):
        with self._lock:
            return self._wave_ewma

    def _predicted_wait_locked(self, depth):
        """Expected wait for a newly admitted ask: how many waves' worth
        of queue is ahead of it.  With no EWMA yet (cold start) predict 0
        — admit and learn."""
        if self._wave_ewma is None:
            return 0.0
        waves_ahead = 1 + depth // max(1, self.max_queue)
        return waves_ahead * self._wave_ewma

    def _retry_after_locked(self, depth):
        """``Retry-After`` seconds from live wave latency: the time for
        the EXCESS queue to drain, floored at 50ms so a hot client never
        busy-spins on integer-zero hints."""
        ewma = self._wave_ewma if self._wave_ewma is not None else 0.0
        excess_waves = 1 + max(0, depth - self.max_queue) \
            // max(1, self.max_queue)
        return max(0.05, excess_waves * ewma)

    def _count(self, name):
        if self.metrics is not None:
            self.metrics.counter(name).inc()

    def _gauge(self, name, v):
        if self.metrics is not None:
            self.metrics.gauge(name).set(v)


# ---------------------------------------------------------------------------
# device-fault degrade ladder
# ---------------------------------------------------------------------------

#: Ladder levels, walked DOWN on device faults and UP after clean waves.
#: ``cand_scale`` multiplies ``n_EI_candidates`` for the wave's cohort
#: ticks; ``cap_limit`` is the largest cohort capacity bucket still
#: served on device (bigger buckets — the memory-heavy ones — fall back
#: to rand for the wave); ``rand`` serves every TPE ask host-side via
#: ``rand.suggest`` (flagged in the response), touching the device not
#: at all.  Every level keeps serving: the ladder never kills the
#: server, and host-side state (the authoritative arrays, the journal)
#: is untouched by any transition.
LADDER_LEVELS = (
    {"name": "normal", "cand_scale": 1.0, "cap_limit": None, "rand": False},
    {"name": "half_candidates", "cand_scale": 0.5, "cap_limit": None,
     "rand": False},
    {"name": "small_caps", "cand_scale": 0.25, "cap_limit": 64,
     "rand": False},
    {"name": "rand_fallback", "cand_scale": 1.0, "cap_limit": 0,
     "rand": True},
)


class DegradeLadder:
    """Degrade-ladder state machine (pure policy; the scheduler's wave
    path calls :meth:`record_fault` / :meth:`record_clean_wave` and reads
    :meth:`level`).  ``recover_after`` clean waves at a degraded level
    probe one level back up; a fault at ANY level steps one level down
    and resets the clean count — so a persistently faulting device walks
    to rand fallback and stays there until the device proves itself
    again, one recovery step per patience window."""

    def __init__(self, recover_after=8, metrics=None):
        self.recover_after = max(1, int(recover_after))
        self.metrics = metrics
        self._level = 0
        self._clean_waves = 0
        self.faults = 0
        self.transitions = []  # (direction, from_level, to_level) tail
        self._publish()

    def level(self):
        return self._level

    def spec(self):
        return LADDER_LEVELS[self._level]

    @property
    def degraded(self):
        return self._level > 0

    def record_fault(self):
        """One device fault in a cohort tick: step down (bounded at the
        rand floor — rand faults are host bugs, not device pressure)."""
        self.faults += 1
        if self.metrics is not None:
            self.metrics.counter("service.degrade.faults").inc()
        if self._level < len(LADDER_LEVELS) - 1:
            self._transition(self._level + 1, "down")
        self._clean_waves = 0
        return self._level

    def record_clean_wave(self):
        """One wave served with no device fault; after ``recover_after``
        of them, climb one level (the recovery probe — the next wave
        runs at the better level, and a fault there steps straight back
        down)."""
        if self._level == 0:
            return self._level
        self._clean_waves += 1
        if self._clean_waves >= self.recover_after:
            self._transition(self._level - 1, "up")
            self._clean_waves = 0
        return self._level

    def _transition(self, to_level, direction):
        frm, self._level = self._level, to_level
        self.transitions.append((direction, frm, to_level))
        del self.transitions[:-64]
        if self.metrics is not None:
            self.metrics.counter(f"service.degrade.{direction}").inc()
        self._publish()

    def _publish(self):
        if self.metrics is not None:
            self.metrics.gauge("service.degraded").set(self._level)

    def status(self):
        return {"level": self._level, "name": self.spec()["name"],
                "faults": self.faults, "clean_waves": self._clean_waves,
                "recover_after": self.recover_after}


def is_device_fault(exc):
    """Classify an exception from a cohort tick dispatch/readback as a
    device fault the ladder should absorb (vs a host bug it should
    surface).  Matches OOM (``RESOURCE_EXHAUSTED`` — jax raises it as
    ``XlaRuntimeError``), compile failures (``INVALID_ARGUMENT`` /
    ``UNIMPLEMENTED`` from lowering), the chaos plane's injected
    ``OSError`` at the ``tick`` site, and the non-finite-output marker
    the scheduler raises after readback."""
    if isinstance(exc, NonFiniteProposal):
        return True
    if isinstance(exc, OSError):  # chaos ioerr@tick, compile-cache I/O
        return True
    name = type(exc).__name__
    if name in ("XlaRuntimeError", "InternalError", "ResourceExhaustedError"):
        return True
    msg = str(exc)
    return any(tag in msg for tag in (
        "RESOURCE_EXHAUSTED", "RESOURCE EXHAUSTED", "out of memory",
        "Out of memory", "INVALID_ARGUMENT", "UNIMPLEMENTED",
        "FAILED_PRECONDITION"))


class NonFiniteProposal(RuntimeError):
    """A cohort tick read back non-finite proposals (NaN posterior /
    inf EI) — treated as a device fault: the wave retries down-ladder,
    ultimately serving rand proposals, which are always finite."""
