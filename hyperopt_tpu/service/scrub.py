"""Store scrub & repair (ISSUE 15): walk a serving store root offline,
verify every checksummed surface and the cross-file invariants, and —
with ``--repair`` — perform the same quarantine/truncate actions the
live resume path performs, producing a store that boots clean.

::

    python -m hyperopt_tpu.service.scrub <root> [--repair] [--json]

What is scanned:

* **WALs** — ``<root>/service.wal.jsonl`` and every fleet epoch WAL
  ``<root>/fleet/wal/shard*/e*.jsonl``: per-line CRC32C verification
  (ok / unchecked / corrupt / torn via
  :func:`~hyperopt_tpu.service.integrity.iter_checked_jsonl`), plus
  per-study record invariants (a snapshot's ``n_asked >= n_told``, an
  ask/tell record for a study no admit/snapshot introduced).
* **Epoch chains** — per shard: duplicate epoch numbers are flagged;
  a multi-file chain is noted (legal only in the crash window between
  adoption compaction and ancestor deletion).
* **Census** — ``compile_census.jsonl``: per-line verification (the
  bank tolerates loss; scrub still reports it).
* **Ownership table** — ``fleet/owners/shard*.json``: seal
  verification + liveness (an owner with no replica record is stale).
* **Study stores** — every subdirectory with a ``counter`` file: each
  ``*.pkl`` doc must unpickle (a corrupt doc is a media fault the
  pickle layer cannot excuse), the counter must parse, and a DONE doc
  count below the newest WAL snapshot's ``n_told`` is flagged
  (snapshot-vs-store agreement).
* **Attachments** — ``obs_events.jsonl`` / flight dumps: JSONL parse
  sweep (warn-level; these streams are best-effort by contract).

Repair actions (the offline mirror of the live quarantine path):

* a WAL with corrupt lines is renamed to ``*.quarantined`` (+ sealed
  reason record) and rewritten in place with its verified records,
  minus the corrupt studies' records, plus one ``quarantine`` record
  per corrupt study — the next boot quarantines them (410) and every
  healthy study resumes bit-identically;
* a torn tail is dropped by the same rewrite (the truncate);
* a corrupt census line is dropped on rewrite; a corrupt ownership
  entry is removed (the live owner republishes within a heartbeat);
* an unreadable study doc is renamed ``*.quarantined`` so store scans
  skip it permanently instead of re-parsing it forever.

Exit status: 0 clean (or fully repaired), 2 when corruption was found
and ``--repair`` was not given.
"""

from __future__ import annotations

import argparse
import json
import os
import re
import sys
import time

from . import integrity
from .journal import StudyJournal

__all__ = ["scan_store", "repair_store", "main"]

_EPOCH_RE = re.compile(r"^e(\d+)\..+\.jsonl$")


def _wal_paths(root):
    out = []
    for fname in sorted(os.listdir(root)):
        if fname.endswith(".wal.jsonl"):
            out.append(os.path.join(root, fname))
    wal_root = os.path.join(root, "fleet", "wal")
    if os.path.isdir(wal_root):
        for shard in sorted(os.listdir(wal_root)):
            d = os.path.join(wal_root, shard)
            if not os.path.isdir(d):
                continue
            for fname in sorted(os.listdir(d)):
                if _EPOCH_RE.match(fname):
                    out.append(os.path.join(d, fname))
    return out


def _scan_wal(path, findings):
    """One WAL file: per-line classification + per-study invariants.
    Returns the per-file summary dict."""
    counts = {"ok": 0, "unchecked": 0, "corrupt": 0, "torn": 0}
    corrupt_sids = {}
    known = set()
    records = 0
    t0 = time.perf_counter()
    for chk in integrity.iter_checked_jsonl(path):
        records += 1
        counts[chk.status] += 1
        if chk.status == integrity.CORRUPT:
            sid = ((chk.rec or {}).get("sid")
                   or integrity.salvage_sid(chk.raw))
            corrupt_sids.setdefault(sid or "?", []).append(chk.lineno)
            findings.append({
                "kind": "wal_corrupt", "path": path,
                "lineno": chk.lineno, "sid": sid})
            continue
        if chk.status == integrity.TORN:
            findings.append({"kind": "wal_torn_tail", "path": path,
                             "lineno": chk.lineno, "benign": True})
            continue
        rec = chk.rec
        kind, sid = rec.get("kind"), rec.get("sid")
        if kind in ("admit", "snapshot", "quarantine"):
            known.add(sid)
            if kind == "snapshot":
                if int(rec.get("n_asked", 0)) < int(rec.get("n_told", 0)):
                    findings.append({
                        "kind": "snapshot_invariant", "path": path,
                        "lineno": chk.lineno, "sid": sid,
                        "detail": "n_asked < n_told"})
                if not isinstance(rec.get("rstate"), dict):
                    findings.append({
                        "kind": "snapshot_invariant", "path": path,
                        "lineno": chk.lineno, "sid": sid,
                        "detail": "missing rstate"})
        elif kind in ("ask", "tell", "close") and sid not in known:
            # legal mid-chain (an earlier epoch introduced the study);
            # recorded as a note, not a fault, unless this is the only
            # file — the caller downgrades when a chain exists
            findings.append({"kind": "orphan_record", "path": path,
                             "lineno": chk.lineno, "sid": sid,
                             "benign": True})
    return {"path": path, "records": records, "counts": counts,
            "corrupt_sids": {k: v for k, v in corrupt_sids.items()},
            "known_sids": sorted(s for s in known if s),
            "scan_sec": time.perf_counter() - t0}


def _scan_chains(root, findings):
    wal_root = os.path.join(root, "fleet", "wal")
    chains = {}
    if not os.path.isdir(wal_root):
        return chains
    for shard in sorted(os.listdir(wal_root)):
        d = os.path.join(wal_root, shard)
        if not os.path.isdir(d):
            continue
        epochs = []
        for fname in sorted(os.listdir(d)):
            m = _EPOCH_RE.match(fname)
            if m:
                epochs.append(int(m.group(1)))
        dups = sorted({e for e in epochs if epochs.count(e) > 1})
        if dups:
            findings.append({"kind": "epoch_duplicate", "path": d,
                             "epochs": dups})
        if len(epochs) > 1:
            findings.append({"kind": "epoch_chain_pending", "path": d,
                             "epochs": sorted(epochs), "benign": True})
        chains[shard] = sorted(epochs)
    return chains


def _scan_owners(root, findings):
    owners_dir = os.path.join(root, "fleet", "owners")
    replicas_dir = os.path.join(root, "fleet", "replicas")
    out = []
    if not os.path.isdir(owners_dir):
        return out
    live = set()
    if os.path.isdir(replicas_dir):
        live = set(os.listdir(replicas_dir))
    for fname in sorted(os.listdir(owners_dir)):
        path = os.path.join(owners_dir, fname)
        try:
            with open(path) as f:
                rec = json.loads(f.read())
        except (OSError, ValueError):
            findings.append({"kind": "owner_corrupt", "path": path})
            out.append(path)
            continue
        if not isinstance(rec, dict) \
                or integrity.verify_obj(rec) == integrity.CORRUPT:
            findings.append({"kind": "owner_corrupt", "path": path})
            out.append(path)
            continue
        if live and rec.get("replica") not in live:
            findings.append({"kind": "owner_stale", "path": path,
                             "replica": rec.get("replica"),
                             "benign": True})
    return out


def _scan_census(root, findings):
    path = os.path.join(root, "compile_census.jsonl")
    if not os.path.exists(path):
        return None
    counts = {"ok": 0, "unchecked": 0, "corrupt": 0, "torn": 0}
    for chk in integrity.iter_checked_jsonl(path):
        counts[chk.status] += 1
        if chk.status == integrity.CORRUPT:
            findings.append({"kind": "census_corrupt", "path": path,
                             "lineno": chk.lineno})
    return {"path": path, "counts": counts}


def _scan_stores(root, findings):
    import pickle

    swept = docs = bad = 0
    for fname in sorted(os.listdir(root)):
        d = os.path.join(root, fname)
        if not os.path.isfile(os.path.join(d, "counter")):
            continue
        swept += 1
        try:
            with open(os.path.join(d, "counter")) as f:
                int(f.read().strip() or "0")
        except (OSError, ValueError):
            findings.append({"kind": "counter_corrupt",
                             "path": os.path.join(d, "counter")})
        for sub in ("new", "running", "done", "error", "cancel"):
            dirpath = os.path.join(d, sub)
            if not os.path.isdir(dirpath):
                continue
            for doc in sorted(os.listdir(dirpath)):
                if not doc.endswith(".pkl"):
                    continue
                docs += 1
                path = os.path.join(dirpath, doc)
                try:
                    with open(path, "rb") as f:
                        pickle.loads(f.read())
                except Exception:  # noqa: BLE001 - any parse fault counts
                    bad += 1
                    findings.append({"kind": "doc_corrupt", "path": path})
        att = os.path.join(d, "attachments")
        if os.path.isdir(att):
            for doc in sorted(os.listdir(att)):
                if not doc.endswith(".jsonl"):
                    continue
                path = os.path.join(att, doc)
                try:
                    for chk in integrity.iter_checked_jsonl(path):
                        if chk.rec is None \
                                and chk.status == integrity.CORRUPT:
                            findings.append({
                                "kind": "attachment_garbled",
                                "path": path, "lineno": chk.lineno,
                                "benign": True})
                except OSError:
                    continue
    return {"stores": swept, "docs": docs, "corrupt_docs": bad}


def scan_store(root):
    """Full offline scan; returns the report dict (see module
    docstring).  ``report["clean"]`` is True when no NON-benign finding
    surfaced; ``report["findings"]`` lists everything."""
    root = str(root)
    t0 = time.perf_counter()
    findings = []
    wals = [_scan_wal(p, findings) for p in _wal_paths(root)]
    report = {
        "root": root,
        "ts": time.time(),
        "wals": wals,
        "chains": _scan_chains(root, findings),
        "census": _scan_census(root, findings),
        "owners_corrupt": _scan_owners(root, findings),
        "stores": _scan_stores(root, findings),
        "findings": findings,
    }
    report["records_scanned"] = sum(w["records"] for w in wals)
    report["scan_sec"] = time.perf_counter() - t0
    report["records_per_sec"] = (
        report["records_scanned"] / report["scan_sec"]
        if report["scan_sec"] > 0 else 0.0)
    report["faults"] = [f for f in findings if not f.get("benign")]
    report["clean"] = not report["faults"]
    return report


def repair_store(root, report=None):
    """Apply the offline quarantine/truncate actions for every fault in
    ``report`` (a fresh :func:`scan_store` when omitted).  Returns the
    action list; after repair the store boots clean — healthy studies
    resume bit-identically, corrupt ones answer 410."""
    root = str(root)
    if report is None:
        report = scan_store(root)
    actions = []
    for wal in report["wals"]:
        path = wal["path"]
        has_corrupt = wal["counts"]["corrupt"] > 0
        has_torn = wal["counts"]["torn"] > 0
        if not (has_corrupt or has_torn):
            continue
        healthy = []
        corrupt_sids = set()
        for chk in integrity.iter_checked_jsonl(path):
            if chk.status == integrity.CORRUPT:
                sid = ((chk.rec or {}).get("sid")
                       or integrity.salvage_sid(chk.raw))
                if sid:
                    corrupt_sids.add(sid)
                continue
            if chk.status == integrity.TORN:
                continue
            healthy.append(chk.rec)
        jr = StudyJournal(path)
        if has_corrupt:
            reason = ("scrub --repair: corrupt records for "
                      + (", ".join(sorted(corrupt_sids)) or "unknown"))
            qpath = jr.quarantine_segment(reason)
            actions.append({"action": "quarantine_segment", "path": path,
                            "quarantined": qpath})
        kept = [r for r in healthy
                if r.get("sid") not in corrupt_sids]
        kept += [StudyJournal.quarantine_rec(sid, "scrub --repair")
                 for sid in sorted(corrupt_sids)]
        jr.rewrite(kept, verify_old=False)
        actions.append({"action": "rewrite", "path": path,
                        "records": len(kept),
                        "quarantined_studies": sorted(corrupt_sids),
                        "truncated_torn": has_torn})
    census = report.get("census")
    if census and census["counts"]["corrupt"]:
        path = census["path"]
        kept = [chk.rec for chk in integrity.iter_checked_jsonl(path)
                if chk.status in (integrity.OK, integrity.UNCHECKED)]
        tmp = f"{path}.tmp.scrub.{os.getpid()}"
        with open(tmp, "w", encoding="utf-8") as f:
            for rec in kept:
                f.write(integrity.seal(rec) + "\n")
        os.replace(tmp, path)
        actions.append({"action": "census_rewrite", "path": path,
                        "records": len(kept)})
    for path in report.get("owners_corrupt") or []:
        try:
            os.remove(path)
            actions.append({"action": "owner_removed", "path": path})
        except OSError:
            pass
    for f in report["findings"]:
        if f["kind"] in ("doc_corrupt", "counter_corrupt"):
            path = f["path"]
            try:
                os.replace(path, path + ".quarantined")
                actions.append({"action": "doc_quarantined",
                                "path": path})
            except OSError:
                pass
    return actions


def _render(report, out=sys.stdout):
    p = lambda s: print(s, file=out)  # noqa: E731
    p(f"scrub: {report['root']}")
    p(f"  scanned {report['records_scanned']} WAL records across "
      f"{len(report['wals'])} files in {report['scan_sec']:.3f}s "
      f"({report['records_per_sec']:.0f} rec/s)")
    for w in report["wals"]:
        c = w["counts"]
        line = (f"  wal {os.path.relpath(w['path'], report['root'])}: "
                f"{c['ok']} ok")
        if c["unchecked"]:
            line += f"  {c['unchecked']} unchecked (pre-ISSUE-15)"
        if c["torn"]:
            line += f"  {c['torn']} torn-tail"
        if c["corrupt"]:
            line += f"  {c['corrupt']} CORRUPT -> " + ", ".join(
                f"{sid}@{lines}" for sid, lines
                in sorted(w["corrupt_sids"].items()))
        p(line)
    st = report["stores"]
    if st["stores"]:
        line = (f"  stores: {st['stores']} study dirs, "
                f"{st['docs']} docs")
        if st["corrupt_docs"]:
            line += f", {st['corrupt_docs']} CORRUPT"
        p(line)
    if report["census"]:
        c = report["census"]["counts"]
        p(f"  census: {c['ok']} ok, {c['unchecked']} unchecked"
          + (f", {c['corrupt']} CORRUPT" if c["corrupt"] else ""))
    benign = [f for f in report["findings"] if f.get("benign")]
    if benign:
        p(f"  notes: {len(benign)} benign "
          f"({', '.join(sorted({f['kind'] for f in benign}))})")
    if report["clean"]:
        p("  CLEAN: every checksummed surface verified")
    else:
        p(f"  FAULTS: {len(report['faults'])}")
        for f in report["faults"]:
            p(f"    {f['kind']}: {f.get('path')}"
              + (f":{f['lineno']}" if f.get("lineno") else "")
              + (f" sid={f['sid']}" if f.get("sid") else ""))


def main(argv=None):
    parser = argparse.ArgumentParser(
        prog="python -m hyperopt_tpu.service.scrub",
        description="Verify (and optionally repair) a serving store "
                    "root: WAL/census/ownership checksums, cross-file "
                    "invariants, study-doc readability.")
    parser.add_argument("root", help="the store root to scrub")
    parser.add_argument("--repair", action="store_true",
                        help="apply the offline quarantine/truncate "
                             "actions (rename corrupt WAL segments "
                             "aside, rewrite verified records, mark "
                             "corrupt studies quarantined)")
    parser.add_argument("--json", action="store_true",
                        help="print the machine-readable report")
    args = parser.parse_args(argv)
    if not os.path.isdir(args.root):
        print(f"scrub: {args.root} is not a directory", file=sys.stderr)
        return 1
    report = scan_store(args.root)
    if args.repair and not report["clean"]:
        report["repair_actions"] = repair_store(args.root, report)
        report["post"] = scan_store(args.root)
        report["repaired"] = report["post"]["clean"]
    if args.json:
        print(json.dumps(report, default=str))
    else:
        _render(report)
        if args.repair and "repair_actions" in report:
            print(f"  repaired: {len(report['repair_actions'])} actions; "
                  f"post-repair scan "
                  f"{'CLEAN' if report['repaired'] else 'STILL FAULTY'}")
    if report["clean"]:
        return 0
    if args.repair:
        return 0 if report.get("repaired") else 2
    return 2


if __name__ == "__main__":
    sys.exit(main())
