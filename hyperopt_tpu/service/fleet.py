"""Replicated serving fleet: N server replicas, one logical ask/tell
service (ISSUE 12).

PRs 9-11 built a durable, traced, overload-safe serving plane — but
exactly ONE process owned the :class:`StudyScheduler`, the mesh and the
WAL, so a single box was the throughput ceiling and any restart a
brown-out.  This module combines the two planes the repo already has —
``parallel/membership.py``'s lease machinery (PR 8) and the WAL's
bit-identical crash-resume (PR 10) — into a fleet:

* the **study keyspace partitions into M study-shards** —
  :func:`shard_of` buckets a study id by CRC32, pinned forever (the
  shard count is a write-once property of the store root, verified by
  every joiner via ``fleet/params.json``);
* each shard is owned through a **long-lived epoch lease**
  (:class:`~hyperopt_tpu.parallel.membership.EpochLeases`: ``O_EXCL``
  claim, mtime heartbeat, rename-first stale reclaim) and served by its
  own :class:`StudyScheduler` whose WAL is the **(shard, epoch) journal**
  ``fleet/wal/shard<k>/e<epoch>.<replica>.jsonl`` — epochs bump on every
  claim, so two owners' journals can NEVER interleave: a
  reclaimed-from-under-us holder's late appends land in a file fenced
  off by its dead epoch;
* an **ownership table** (``fleet/owners/shard<k>.json``, journaled
  next to the leases) maps each shard to its owner's advertised
  address; a request for a study this replica doesn't own raises
  :class:`ShardNotOwned` → HTTP **307** with the owner's address, which
  :class:`~hyperopt_tpu.service.client.ServiceClient` follows with a
  bounded hop count (loops/stale tables degrade to retry-with-backoff);
* **migration is WAL replay**: adopting a shard (stale reclaim after a
  SIGKILL, or the volunteer handoff of a drain/rebalance) replays the
  shard's epoch-WAL chain oldest-first through
  :meth:`StudyScheduler.resume` — and because resume is pinned
  bit-identical (ISSUE 10), a migrated study's subsequent proposals
  equal the undisturbed single-server reference (tier-1 pinned, and
  end-to-end by ``scripts/fleet_smoke.py``'s SIGKILL + rolling-restart
  phases).  Adoption compacts the chain into one snapshot-led file for
  the new epoch and deletes the ancestors (only after the compaction —
  and its parent-directory entry — are durable);
* a **steward** thread per replica heartbeats its leases, reclaims
  stale ones, and rebalances toward ``ceil(M / live replicas)`` held
  shards — a joining replica is volunteered shards by drain-handoff, a
  dead one's shards are adopted within ~``lease_ttl``.

Consistency note (DESIGN.md §19): ownership mutations are fenced by the
lease epoch — re-verified at every durability point (ask ingress, wave
start, tell ingress), not just at routing — and every acknowledged
mutation is fsynced into the shard's epoch WAL before the client
unblocks, so a SIGKILL loses nothing and a reclaim replays everything.
The residual window is a LIVE holder stalled past ``lease_ttl`` whose
fence check passes immediately before the reclaim lands: its record
reaches a WAL the adopter may already have replayed (and whose file the
adoption compaction may delete), so that acknowledgment can be fenced
out of the fleet's view entirely.  The window is a single
fence-to-fsync interval — microseconds, vs the adopter's
milliseconds-scale claim+scan — and requires the holder to have missed
every heartbeat for a full TTL first; closing it completely would need
per-record fencing on the shared filesystem.
"""

from __future__ import annotations

import json
import logging
import math
import os
import re
import threading
import time
import zlib

from ..filestore import _atomic_write, new_run_id
from ..obs.metrics import get_metrics
from ..parallel.membership import (EpochLeases, publish_params_once,
                                   rotate_for_owner)
from . import integrity
from .journal import StudyJournal, _fsync_dir

__all__ = ["FleetReplica", "ShardNotOwned", "ShardUnavailable",
           "shard_of", "FLEET_DIR"]

logger = logging.getLogger(__name__)

#: fleet metadata directory under a store root
FLEET_DIR = "fleet"


class ShardNotOwned(RuntimeError):
    """This replica does not own the study's shard; ``location`` is the
    advertised address of the replica that does (HTTP 307)."""

    def __init__(self, message, location):
        super().__init__(message)
        self.location = str(location)


class ShardUnavailable(RuntimeError):
    """No replica currently serves the shard (the owner died and no
    survivor adopted it yet, the fleet is mid-rebalance, or this replica
    is still starting) — retryable, HTTP 503 + ``Retry-After``."""

    def __init__(self, message, retry_after=0.5):
        super().__init__(message)
        self.retry_after = float(retry_after)


def shard_of(study_id, n_shards):
    """Study id → shard bucket.  CRC32 — stable across processes,
    Python versions and restarts, unlike the salted builtin ``hash``.
    PINNED (test literal): re-bucketing would strand every persisted
    study behind 307 redirects to the wrong owner."""
    return zlib.crc32(str(study_id).encode()) % int(n_shards)


def _shard_name(shard):
    return f"shard{int(shard):04d}"


class FleetReplica:
    """One replica's membership in the serving fleet: its held shard
    leases, the per-shard schedulers + epoch WALs behind them, and the
    steward that keeps ownership balanced and failure-reclaimed.  The
    HTTP layer (``service/server.py``) routes every study-scoped request
    through :meth:`scheduler_for` and creates studies via
    :meth:`place_study`; everything else here is the control plane."""

    def __init__(self, store_root, n_shards=None, replica_id=None,
                 addr=None, lease_ttl=None, poll=None,
                 scheduler_kwargs=None):
        from .._env import parse_fleet_lease_ttl, parse_fleet_shards

        self.store_root = str(store_root)
        self.n_shards = (parse_fleet_shards() if n_shards is None
                         else int(n_shards))
        if self.n_shards < 1:
            raise ValueError("n_shards must be >= 1")
        self.replica_id = _safe_id(
            replica_id or f"{os.uname().nodename}-{os.getpid()}")
        self.addr = str(addr).rstrip("/") if addr else None
        self.lease_ttl = (parse_fleet_lease_ttl() if lease_ttl is None
                          else float(lease_ttl))
        #: steward sweep period; also the lease heartbeat cadence — four
        #: beats per TTL keeps one lost sweep from looking like a death
        self.poll = (max(0.05, self.lease_ttl / 4.0) if poll is None
                     else float(poll))
        self.member_ttl = 3.0 * self.lease_ttl
        self.scheduler_kwargs = dict(scheduler_kwargs or {})
        self.metrics = get_metrics("service")
        self.overload = None  # AdmissionGuard, wired by the HTTP server

        self._fleet = os.path.join(self.store_root, FLEET_DIR)
        for d in ("owners", "replicas", "wal", "heat"):
            os.makedirs(os.path.join(self._fleet, d), exist_ok=True)
        # durable heat ledger (ISSUE 17): one append-only file per
        # replica under the SHARED root, so shard heat survives
        # restarts and adoption inherits it.  The ledger object is
        # cheap and unconditional; appends only happen for schedulers
        # whose cost ledger is armed.
        from ..obs.load import HeatLedger, heat_path_for

        self.heat = HeatLedger(heat_path_for(self.store_root,
                                             self.replica_id))
        self._heat_last = 0.0  # monotonic ts of the last periodic roll-up
        self.leases = EpochLeases(
            os.path.join(self._fleet, "shardleases"), owner=self.replica_id,
            lease_ttl=self.lease_ttl, metrics=self.metrics)
        self._ensure_params()

        self._lock = threading.RLock()
        self.schedulers = {}   # shard -> StudyScheduler (held shards only)
        self.epochs = {}       # shard -> lease epoch backing the WAL name
        self._verified = {}    # shard -> monotonic ts of last lease verify
        #: how stale a lease verification may get before a study-scoped
        #: request re-reads the lease body (bounds the stalled-holder
        #: acknowledgment window to a fraction of the reclaim TTL)
        self._verify_every = max(0.05, self.lease_ttl / 4.0)
        self._draining = False
        self._stop = threading.Event()
        self._hb_stop = threading.Event()
        self._thread = None
        self._hb_thread = None
        self.adoptions = 0
        self.handoffs = 0
        self.leases_lost = 0

    # -- write-once fleet params (joiners verify) --------------------------

    def _ensure_params(self):
        """First replica pins ``{n_shards}``; every joiner must match —
        a different shard count would re-bucket the whole keyspace
        (``HYPEROPT_TPU_FLEET_SHARDS`` is write-once per store root)."""
        publish_params_once(
            os.path.join(self._fleet, "params.json"),
            {"n_shards": self.n_shards},
            what=f"serving-fleet store {self.store_root}")

    # -- shard-epoch WAL naming --------------------------------------------

    def _wal_dir(self, shard):
        return os.path.join(self._fleet, "wal", _shard_name(shard))

    def _wal_path(self, shard, epoch):
        return os.path.join(self._wal_dir(shard),
                            f"e{int(epoch):05d}.{self.replica_id}.jsonl")

    def wal_chain(self, shard):
        """The shard's existing epoch WAL files, oldest epoch first —
        what an adoption replays.  Normally length ≤ 1 (each adoption
        compacts its ancestors away); longer only after a crash between
        compaction and ancestor deletion, which replays idempotently."""
        d = self._wal_dir(shard)
        try:
            names = os.listdir(d)
        except FileNotFoundError:
            return []
        out = []
        for fname in names:
            m = re.match(r"e(\d+)\..+\.jsonl$", fname)
            if m:
                out.append((int(m.group(1)), os.path.join(d, fname)))
        return [p for _, p in sorted(out)]

    # -- ownership table (routing; journaled next to the leases) -----------

    def _owner_path(self, shard):
        return os.path.join(self._fleet, "owners",
                            f"{_shard_name(shard)}.json")

    def read_owner(self, shard):
        """The shard's published owner entry ``{replica, addr, epoch}``,
        or None.  Advisory — the LEASE is ownership; this table only
        tells routers where to redirect.  Entries are CRC32C-sealed
        (ISSUE 15): a corrupt entry reads as ABSENT (retryable 503
        until the owner's next heartbeat republishes) instead of
        routing 307s to a bit-flipped address; pre-ISSUE-15 unsealed
        entries stay readable."""
        try:
            with open(self._owner_path(shard)) as f:
                rec = json.loads(f.read())
            if not isinstance(rec, dict):
                return None
            if integrity.verify_obj(rec) == integrity.CORRUPT:
                logger.warning("fleet: ownership entry for shard %s is "
                               "corrupt; treating as unowned", shard)
                return None
            return rec
        except (OSError, ValueError):
            return None

    def _publish_ownership(self, shard, epoch):
        _atomic_write(self._owner_path(shard), json.dumps(
            integrity.seal_obj(
                {"shard": int(shard), "replica": self.replica_id,
                 "addr": self.addr, "epoch": int(epoch),
                 "ts": time.time()}),
            sort_keys=True).encode())

    def _clear_ownership(self, shard):
        """Remove our routing entry (drain path) so routers answer a
        retryable 503 instead of bouncing clients to a corpse; never
        touch an entry a NEW owner already published."""
        rec = self.read_owner(shard)
        if rec is not None and rec.get("replica") != self.replica_id:
            return
        try:
            os.remove(self._owner_path(shard))
        except FileNotFoundError:
            pass

    # -- replica records (liveness by mtime; sizes the balance target) -----

    def _replica_path(self, rid=None):
        return os.path.join(self._fleet, "replicas",
                            _safe_id(rid or self.replica_id))

    def join(self):
        _atomic_write(self._replica_path(), json.dumps(
            {"replica": self.replica_id, "addr": self.addr,
             "pid": os.getpid(), "joined": time.time()},
            sort_keys=True).encode())
        self.metrics.counter("service.fleet.joins").inc()

    def heartbeat_replica(self):
        try:
            os.utime(self._replica_path(), None)
        except FileNotFoundError:
            self.join()

    def leave(self):
        try:
            os.remove(self._replica_path())
        except FileNotFoundError:
            pass

    def live_replicas(self):
        """Replica ids whose record heartbeated within ``member_ttl``
        (a dead replica ages out; leaving is optional)."""
        d = os.path.join(self._fleet, "replicas")
        now = time.time()
        out = []
        for fname in sorted(os.listdir(d)):
            try:
                age = now - os.path.getmtime(os.path.join(d, fname))
            except FileNotFoundError:
                continue
            if age <= self.member_ttl:
                out.append(fname)
        return out

    def target_shards(self):
        """How many shards this replica should hold: ``ceil(M / live)``
        — every member computes the same target from the same records,
        so excess holders volunteer handoffs and underfull ones claim,
        converging without any coordinator."""
        live = max(1, len(self.live_replicas()))
        return min(self.n_shards, math.ceil(self.n_shards / live))

    # -- adoption (the migration path) -------------------------------------

    def adopt(self, shard):
        """Claim ``shard`` and rebuild its studies by replaying the
        epoch-WAL chain into a fresh per-shard scheduler (bit-identical
        by the resume pins).  Returns True on success; False when the
        claim was lost to a racing replica (normal contention)."""
        name = _shard_name(shard)
        epoch = self.leases.try_claim(name)
        if epoch is None:
            return False
        t0 = time.perf_counter()
        from .scheduler import StudyScheduler

        os.makedirs(self._wal_dir(shard), exist_ok=True)
        new_path = self._wal_path(shard, epoch)
        chain = [p for p in self.wal_chain(shard) if p != new_path]
        sched = StudyScheduler(store_root=self.store_root, wal=new_path,
                               auto_resume=False, **self.scheduler_kwargs)
        if self.overload is not None:
            sched.overload = self.overload
        # the durability fence: every ask/wave/tell re-verifies the
        # lease so a stalled-then-reclaimed holder refuses the mutation
        # (StaleOwnershipError -> retryable 503) instead of landing
        # state the new owner's replay never saw
        sched.fence = lambda: self._fence(shard)
        try:
            for path in chain:
                sched.resume(StudyJournal(path))
        except Exception:
            # never serve a half-replayed shard: release the claim so a
            # healthier replica (or a retry) adopts it instead
            logger.warning("fleet: replay of %s epoch chain failed; "
                           "releasing the claim", name, exc_info=True)
            self.leases.release(name)
            raise
        if chain and sched._maybe_compact():
            # the chain is now one snapshot-led epoch file; drop the
            # ancestors ONLY after the compacted file (and its directory
            # entry) are durable — a crash in between replays the chain
            # again, idempotently
            _fsync_dir(new_path)
            for path in chain:
                try:
                    os.remove(path)
                except FileNotFoundError:
                    pass
            _fsync_dir(new_path)
        if sched.load is not None:
            # cost-attribution identity + inherited heat (ISSUE 17):
            # the shard's cumulative heat under previous owners comes
            # from the durable ledger (max over cumulative snapshots),
            # NOT from replay — replayed tells are never recounted, so
            # adoption stays bitwise and heat is never doubled.
            # Fail-open: adoption must never fail on observability.
            try:
                from ..obs.load import inherited_heat

                sched.load.bind(shard=shard, replica=self.replica_id)
                sched.load.inherit(inherited_heat(self.store_root, shard))
            except Exception:  # noqa: BLE001
                logger.warning("fleet: heat inheritance for %s failed; "
                               "adopting cold", name, exc_info=True)
        with self._lock:
            self.schedulers[shard] = sched
            self.epochs[shard] = epoch
            self._verified[shard] = time.monotonic()
        self._publish_ownership(shard, epoch)
        self.adoptions += 1
        self.metrics.counter("service.fleet.adoptions").inc()
        self.metrics.histogram("service.fleet.adopt_sec").observe(
            time.perf_counter() - t0)
        self.metrics.gauge("service.fleet.shards_held").set(
            len(self.schedulers))
        return True

    def handoff(self, shard, timeout=30.0):
        """Volunteer-release one shard (drain / rebalance): quiesce its
        scheduler (in-flight waves finish, WAL compacts to one snapshot
        per live study and closes), clear our routing entry, release the
        lease.  The next owner's adoption replays ONE compacted file."""
        with self._lock:
            sched = self.schedulers.pop(shard, None)
            self.epochs.pop(shard, None)
            self._verified.pop(shard, None)
        if sched is None:
            return False
        try:
            sched.drain(timeout=timeout)
        except Exception:  # noqa: BLE001 - the lease must still be freed
            logger.warning("fleet: drain of %s failed mid-handoff",
                           _shard_name(shard), exc_info=True)
        # flush the final heat snapshot BEFORE the lease is released so
        # the next owner's adoption inherits everything this holder
        # attributed (best-effort: HeatLedger.append absorbs OSError)
        if sched.load is not None:
            try:
                self.heat.append(self._heat_rec(sched))
            except Exception:  # noqa: BLE001
                logger.warning("fleet: heat flush for %s failed",
                               _shard_name(shard), exc_info=True)
        self._clear_ownership(shard)
        self.leases.release(_shard_name(shard))
        self.handoffs += 1
        self.metrics.counter("service.fleet.handoffs").inc()
        self.metrics.gauge("service.fleet.shards_held").set(
            len(self.schedulers))
        return True

    def _drop_shard(self, shard):
        """Our lease was reclaimed from under us (we stalled past the
        TTL): stop serving the shard IMMEDIATELY — no drain, no
        compaction (rewriting the fenced epoch file could resurrect a
        journal the adopter already replayed and deleted).  Every
        acknowledged mutation is already fsynced in the epoch WAL the
        reclaimer replays, so nothing acked is lost."""
        sched = self.schedulers.pop(shard, None)
        self.epochs.pop(shard, None)
        self._verified.pop(shard, None)
        if sched is None:
            return
        self.leases_lost += 1
        self.metrics.counter("service.fleet.leases_lost").inc()
        self.metrics.gauge("service.fleet.shards_held").set(
            len(self.schedulers))
        logger.warning("fleet: lost lease on %s (reclaimed by a "
                       "survivor); dropping the shard un-drained",
                       _shard_name(shard))
        # the journal handle is left OPEN on purpose: closing it here
        # (heartbeat/request thread) would race an in-flight append/sync
        # under the scheduler's own lock (StudyJournal is only safe
        # there).  New mutations are refused by the fence; a mutation
        # already past its fence check completes normally into the
        # fenced file (the documented residual window), and the handle
        # dies with the dropped scheduler's GC.

    # -- request routing ---------------------------------------------------

    def _fence(self, shard):
        """The per-shard schedulers' durability-point ownership check:
        a fresh lease-body read (no cache — this is the fence), with a
        lost lease dropping the shard immediately."""
        if self.leases.verify_held(_shard_name(shard)):
            return True
        with self._lock:
            self._drop_shard(shard)
        return False

    def scheduler_for(self, study_id):
        """The scheduler serving ``study_id``'s shard.  Raises
        :class:`ShardNotOwned` (→ 307 + owner address) when another
        replica owns it, :class:`ShardUnavailable` (→ 503 retryable)
        when nobody does yet.  Held leases are re-verified at most every
        ``lease_ttl/4`` so a stalled-then-reclaimed holder stops
        acknowledging within a bounded window."""
        shard = shard_of(study_id, self.n_shards)
        with self._lock:
            sched = self.schedulers.get(shard)
            if sched is not None:
                now = time.monotonic()
                if now - self._verified.get(shard, 0.0) > self._verify_every:
                    if self.leases.verify_held(_shard_name(shard)):
                        self._verified[shard] = now
                    else:
                        self._drop_shard(shard)
                        sched = None
            if sched is not None:
                return sched
        owner = self.read_owner(shard)
        if (owner is not None and owner.get("addr")
                and owner.get("replica") != self.replica_id):
            raise ShardNotOwned(
                f"study {study_id} (shard {shard}) is served by "
                f"{owner['replica']}", owner["addr"])
        raise ShardUnavailable(
            f"shard {shard} has no live owner yet (owner died or fleet "
            "is rebalancing); retry",
            retry_after=max(0.05, self.lease_ttl / 4.0))

    def place_study(self):
        """Mint a study id that lands in a shard THIS replica owns
        (study ids are minted server-side, so creation cannot redirect;
        redraw until the CRC32 bucket is held — expected ``M/held``
        draws).  The id claims its store subdirectory atomically
        (``new_run_id(unique_dir=...)``), so two replicas can never mint
        the same id.  Returns ``(study_id, scheduler)``."""
        with self._lock:
            held = dict(self.schedulers)
        if not held or self._draining:
            raise ShardUnavailable(
                "replica holds no study shards (starting up, draining, "
                "or every shard is owned elsewhere); retry",
                retry_after=max(0.05, self.poll))
        bound = max(64, 32 * self.n_shards // max(1, len(held)))
        for _ in range(bound):
            sid = new_run_id("study", unique_dir=self.store_root)
            shard = shard_of(sid, self.n_shards)
            sched = held.get(shard)
            if sched is not None:
                return sid, sched
            try:  # release the claimed (empty) directory and redraw
                os.rmdir(os.path.join(self.store_root, sid))
            except OSError:
                pass
        raise ShardUnavailable(
            f"could not mint a study id landing in a held shard in "
            f"{bound} draws", retry_after=max(0.05, self.poll))

    # -- the steward (heartbeat / reclaim / rebalance) ---------------------

    def start(self):
        """Join the fleet, run one synchronous steward sweep (so a
        fresh single replica serves immediately), then keep two daemon
        threads: a fast HEARTBEAT loop (lease + member mtimes — never
        blocks on anything slower than ``utime``) and the STEWARD loop
        (reclaim/claim/rebalance).  They are separate on purpose: an
        adoption replay pays XLA compiles for seconds, and a steward
        blocked inside one must not starve this replica's OWN lease
        heartbeats — that self-inflicted staleness is exactly how a
        LIVE replica gets its other shards reclaimed from under it."""
        self.join()
        self.steward_once()
        self._hb_thread = threading.Thread(
            target=self._heartbeat_loop,
            name=f"hyperopt-fleet-heartbeat-{self.replica_id}",
            daemon=True)
        self._hb_thread.start()
        self._thread = threading.Thread(
            target=self._steward_loop,
            name=f"hyperopt-fleet-steward-{self.replica_id}", daemon=True)
        self._thread.start()
        return self

    def _heartbeat_loop(self):
        # its own stop event: the heartbeat must OUTLIVE the steward
        # during drain — a lease expiring while its shard waits in the
        # sequential handoff queue would be reclaimed out of a live
        # (draining) replica, re-opening the zombie window
        while not self._hb_stop.wait(self.poll):
            try:
                self.heartbeat_once()
            except Exception:  # noqa: BLE001 - heartbeats must survive
                logger.warning("fleet: heartbeat sweep failed (continuing)",
                               exc_info=True)

    def _steward_loop(self):
        while not self._stop.wait(self.poll):
            try:
                self.manage_once()
            except Exception:  # noqa: BLE001 - the steward must survive
                logger.warning("fleet: steward sweep failed (continuing)",
                               exc_info=True)

    def steward_once(self):
        """One full sweep (heartbeat + manage) — the unit tests' and
        the synchronous-start entry point; the background threads run
        the two halves independently."""
        self.heartbeat_once()
        self.manage_once()

    def heartbeat_once(self):
        """Refresh the member record and every held lease's mtime;
        notice (and drop) leases reclaimed from under us.  Runs while
        draining too, and iterates the LEASE plane's held set (not the
        scheduler table): a shard mid-handoff is already out of the
        routing table but its lease must stay fresh until the handoff's
        compaction releases it — otherwise a long final wave lets a
        survivor reclaim a lease whose state is still being written."""
        self.heartbeat_replica()
        for name in list(self.leases.held):
            if not self.leases.heartbeat(name):
                with self._lock:
                    self._drop_shard(int(name[len("shard"):]))
        self._roll_heat()

    def _shard_heat(self, sched):
        """One scheduler's cumulative shard heat in ms (0.0 disarmed —
        every shard ties, so heat-aware ordering degrades to the old
        count-only behavior)."""
        return (0.0 if sched is None or sched.load is None
                else sched.load.heat_ms)

    def _roll_heat(self, force=False):
        """Append one cumulative heat snapshot per held armed scheduler
        to this replica's durable ledger file — the fleet-wide
        aggregation every other replica's ``/fleet/load`` and
        ``obs.report --fleet`` read.  Rate-limited to the steward
        cadence (``force`` bypasses, for drain/handoff flushes);
        best-effort throughout — heat durability never fails a
        heartbeat."""
        now = time.monotonic()
        if not force and now - self._heat_last < max(1.0, self.poll):
            return
        self._heat_last = now
        with self._lock:
            scheds = dict(self.schedulers)
        for shard, sched in scheds.items():
            if sched.load is None:
                continue
            try:
                self.heat.append(self._heat_rec(sched))
            except Exception:  # noqa: BLE001
                logger.warning("fleet: heat roll-up for %s failed",
                               _shard_name(shard), exc_info=True)

    @staticmethod
    def _heat_rec(sched):
        """One scheduler's heat-ledger record, with the per-tenant heat
        table piggybacked (ISSUE 20) when the tenant plane is armed —
        an OPTIONAL field pre-ISSUE-20 readers ignore, MAX-merged by
        ``obs.tenant.read_tenant_heat``."""
        rec = sched.load.heat_record()
        if sched.tenants is not None:
            try:
                table = sched.tenants.heat_table()
                if table:
                    rec["tenants"] = table
            except Exception:  # noqa: BLE001 - heat stays load-only
                pass
        return rec

    def manage_once(self):
        """Reclaim stale leases fleet-wide (adopting what we freed
        IMMEDIATELY), claim toward the balance target, hand off excess
        shards."""
        if self._draining:
            return
        freed = self.leases.reclaim(
            [_shard_name(s) for s in range(self.n_shards)])
        if freed:
            self.metrics.counter("service.fleet.reclaims").inc(len(freed))
            # a reclaimed shard's owner is DEAD (stale leases only —
            # graceful handoffs remove their lease file and are never
            # reclaimed), so adopt it now regardless of the balance
            # target: its member record lingers for member_ttl and
            # would otherwise keep every survivor's target too low to
            # claim, leaving the shard 503 for ~3x lease_ttl.
            # Availability beats balance; the later rebalance
            # redistributes.  No thrash risk: only dead owners' shards
            # take this path.
            for name in freed:
                self.adopt(int(name[len("shard"):]))
        target = self.target_shards()
        with self._lock:
            n_held = len(self.schedulers)
        if n_held < target:
            for shard in self._claim_rotation():
                if n_held >= target:
                    break
                with self._lock:
                    if shard in self.schedulers:
                        continue
                if not os.path.exists(
                        self.leases._lease_path(_shard_name(shard))):
                    if self.adopt(shard):
                        n_held += 1
        elif n_held > target and len(self.live_replicas()) > 1:
            # volunteer handoff toward an underfull joiner; one shard
            # per sweep keeps rebalance gradual (no thundering drain).
            # Heat-aware (ISSUE 17): release the HOTTEST held shard
            # first so a rebalance sheds load, not just count — a pure
            # ordering change over the same drain-handoff path
            # (migration stays bitwise).  Disarmed ledgers tie at 0.0
            # and the shard-number tie-break reproduces the old
            # highest-shard pick exactly.
            with self._lock:
                excess = max(
                    self.schedulers,
                    key=lambda k: (self._shard_heat(self.schedulers[k]),
                                   k),
                    default=None)
            if excess is not None:
                self.handoff(excess)

    def _claim_rotation(self):
        """Shards in a deterministic per-replica rotation so
        simultaneous claimers start at different offsets."""
        return rotate_for_owner(range(self.n_shards), self.replica_id)

    # -- lifecycle / views -------------------------------------------------

    @property
    def draining(self):
        return self._draining

    def set_addr(self, addr):
        """Advertise ``addr`` (known only after the HTTP bind for
        ephemeral ports) and refresh every published ownership entry."""
        self.addr = str(addr).rstrip("/") if addr else None
        with self._lock:
            held = dict(self.epochs)
        for shard, epoch in held.items():
            self._publish_ownership(shard, epoch)

    def drain(self, timeout=30.0):
        """The SIGTERM/rolling-restart path: stop stewarding, hand off
        every held shard (quiesce → compact → release, so survivors
        adopt one snapshot-led WAL each), leave the fleet.  Returns True
        when every handoff quiesced in time."""
        self._draining = True
        self._stop.set()  # stop the steward; heartbeats keep running
        if self._thread is not None:
            self._thread.join(timeout=max(1.0, self.poll * 2))
        ok = True
        deadline = time.monotonic() + float(timeout)
        with self._lock:
            held = sorted(self.schedulers)
        for shard in held:
            left = max(0.5, deadline - time.monotonic())
            ok = self.handoff(shard, timeout=left) and ok
        # only now may the heartbeat die: every lease is released
        self._hb_stop.set()
        if self._hb_thread is not None:
            self._hb_thread.join(timeout=max(1.0, self.poll * 2))
        self.leave()
        return ok

    def healthz(self):
        """The machine-readable ``GET /healthz`` body: who this replica
        is, which shard leases (and epochs) it holds, drain state, and
        WAL sync health — what ``scripts/fleet_restart.py`` polls
        between restarts and ``obs/top.py``'s FLEET row renders."""
        with self._lock:
            shards = {}
            heat_ms = busy = 0.0
            any_load = False
            for shard, sched in self.schedulers.items():
                j = sched.journal
                shards[str(shard)] = {
                    "epoch": self.epochs.get(shard),
                    "studies": len(sched._studies),
                    "wal": None if j is None else {
                        "path": j.path, "appends": j.appends,
                        "syncs": j.syncs, "compactions": j.compactions,
                    },
                }
                if sched.load is not None:
                    any_load = True
                    h = sched.load.heat_ms
                    b = sched.load.busy
                    heat_ms += h
                    busy += b
                    shards[str(shard)]["heat_ms"] = round(h, 3)
                    shards[str(shard)]["busy_frac"] = round(b, 4)
        out = {
            "ok": not self._draining,
            "replica": self.replica_id,
            "addr": self.addr,
            "n_shards": self.n_shards,
            "shards_held": sorted(int(k) for k in shards),
            "shards": shards,
            "draining": self._draining,
            "wal_sync_errors": self.metrics.counter(
                "service.wal.sync_errors").value,
            "replicas": self.live_replicas(),
            "adoptions": self.adoptions,
            "handoffs": self.handoffs,
            "leases_lost": self.leases_lost,
            "lease_ttl": self.lease_ttl,
            "ts": time.time(),
        }
        if any_load:
            # per-replica held-shard heat summary (ISSUE 17): the sum
            # of held cumulative heats + the replica's duty cycle —
            # what obs/top.py's FLEET row and the load smoke read
            out["load"] = {"heat_ms": round(heat_ms, 3),
                           "busy_frac": round(busy, 4)}
        tracked = sheds = evictions = 0
        any_tenants = False
        with self._lock:
            for sched in self.schedulers.values():
                if sched.tenants is None:
                    continue
                any_tenants = True
                try:
                    ts = sched.tenants.status()
                    tracked = max(tracked, ts["tenants"])
                    sheds += ts["sheds"]
                    evictions += ts["evictions"]
                except Exception:  # noqa: BLE001 - fail-open roll-up
                    pass
        if any_tenants:
            out["tenants"] = {"tracked": tracked, "sheds": sheds,
                              "evictions": evictions}
        # replica -> advertised addr, from the published ownership
        # table: the `obs.top --fleet <seed-url>` discovery seam (the
        # `replicas` list above is ids only)
        addrs = {}
        if self.addr:
            addrs[self.replica_id] = self.addr
        for shard in range(self.n_shards):
            rec = self.read_owner(shard)
            if rec and rec.get("replica") and rec.get("addr"):
                addrs.setdefault(str(rec["replica"]), rec["addr"])
        out["replica_addrs"] = addrs
        return out

    def studies_status(self):
        """The fleet replica's ``GET /studies`` body: every held
        shard's study table merged, plus the fleet block the dashboard's
        FLEET row reads."""
        with self._lock:
            scheds = dict(self.schedulers)
        studies, cohorts, tenant_stats = [], [], []
        n_slots = n_live = 0
        wal = None
        for shard in sorted(scheds):
            st = scheds[shard].studies_status()
            studies.extend(st["studies"])
            cohorts.extend(st["cohorts"])
            for c in st["cohorts"]:
                n_slots += c["n_slots"]
                n_live += c["n_live"]
            if st.get("wal"):
                wal = st["wal"]  # representative; healthz has all
            if st.get("tenants"):
                tenant_stats.append(st["tenants"])
        from ..algos import tpe

        out = {
            "ts": time.time(),
            "n_studies": len(studies),
            "slot_utilization": (n_live / n_slots) if n_slots else 0.0,
            "cohort_cache": tpe.cohort_cache_stats(),
            "cohorts": cohorts,
            "studies": studies,
            "draining": self._draining,
            "fleet": self.healthz(),
        }
        if tenant_stats:
            from ..obs.tenant import merge_status

            try:
                out["tenants"] = merge_status(tenant_stats)
            except Exception:  # noqa: BLE001 - fail-open roll-up
                pass
        if wal is not None:
            out["wal"] = wal
        return out


def _safe_id(rid):
    """Replica ids become path components (WAL file names, replica
    records) — keep them one component."""
    return re.sub(r"[^A-Za-z0-9._-]", "-", str(rid))
