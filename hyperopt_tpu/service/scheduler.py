"""Admitting scheduler: pack live studies into cohort slots, tick once per
ask wave.

One ``fmin`` owning the whole mesh wastes almost all of the kernel's
throughput on small studies; a production service runs thousands of them
at once.  This module is the host side of the multi-study batch
(ISSUE 9): studies sharing a search space land in a **cohort** — a
fixed-shape stack of device history slots — and every ask wave runs ONE
study-batched fused tell+ask program (``tpe.build_suggest_batched``) for
the whole cohort instead of one device dispatch per study.

Determinism contract (tier-1 pinned): a cohort of N studies proposes
bit-identically to N independent sequential ``fmin`` runs at the same
per-study seeds.  Everything the scheduler does preserves that:

* the per-study ask flow mirrors ``FMinIter._run`` exactly — draw
  ``new_ids`` from the study's Trials, one seed per ask from the study's
  ``rstate`` (``integers(2**31 - 1)``), random search below
  ``n_startup_jobs``, the TPE cfg dict built like ``tpe.suggest``'s;
* per-id PRNG keys derive from the id VALUE and the study seed, never
  from slot position or wave composition, so cohort packing, slot
  padding and eviction/re-admission are all proposal-invariant;
* the cohort's device stack mirrors the per-study host
  ``PaddedHistory`` arrays (the authoritative state) — an evicted study
  re-admits by re-uploading them, bit-for-bit.

Cohort shapes are static by construction: slot counts grow in powers of
two, every study in a cohort shares the space signature, TPE cfg and
capacity bucket, and ask widths pad to a power of two — so the compiled
program LRU (``tpe._cohort_jit_cache``, surfaced as the
``suggest.cohort_cache`` metrics) sees a handful of shapes, not one per
wave.
"""

from __future__ import annotations

import math
import threading
import time

import numpy as np

import jax
import jax.numpy as jnp

from ..algos import rand, tpe
from ..base import (
    JOB_STATE_DONE,
    STATUS_FAIL,
    STATUS_OK,
    Domain,
    Trials,
    coarse_utcnow,
    spec_from_misc,
)
from ..obs.metrics import get_metrics

__all__ = ["StudyScheduler", "Study", "StudyQuotaError",
           "UnknownStudyError", "DuplicateTellError"]


class UnknownStudyError(KeyError):
    """No live study with that id (never created, or closed)."""


class StudyQuotaError(RuntimeError):
    """An admission or per-study quota would be exceeded (HTTP 429)."""


class DuplicateTellError(RuntimeError):
    """The trial was already told (HTTP 409 — a PERMANENT conflict, not a
    retryable quota: a client retrying a lost tell response must not
    back off forever on a 429)."""


def _pow2(n):
    b = 1
    while b < n:
        b *= 2
    return b


class Study:
    """One study's serving state: compiled space, trials, RNG stream and
    quotas.  The ask/tell flow over these fields reproduces ``FMinIter``'s
    loop, which is what makes the cohort determinism pin possible."""

    def __init__(self, study_id, space, seed=0, n_startup_jobs=None,
                 max_trials=None, trials=None, **tpe_kwargs):
        self.study_id = study_id
        self.domain = Domain(None, space)
        self.trials = trials if trials is not None else Trials()
        self.rstate = np.random.default_rng(seed)
        self.seed = int(seed)
        self.n_startup_jobs = int(n_startup_jobs
                                  if n_startup_jobs is not None
                                  else tpe._default_n_startup_jobs)
        self.max_trials = None if max_trials is None else int(max_trials)
        # mirror tpe.suggest_async's cfg construction field for field so
        # the cohort kernel and the single-study kernel share cache keys
        # downstream of the same space
        self.cfg = {
            "prior_weight": float(tpe_kwargs.pop(
                "prior_weight", tpe._default_prior_weight)),
            "n_EI_candidates": int(tpe_kwargs.pop(
                "n_EI_candidates", tpe._default_n_EI_candidates)),
            "gamma": float(tpe_kwargs.pop("gamma", tpe._default_gamma)),
            "LF": int(tpe_kwargs.pop("linear_forgetting",
                                     tpe._default_linear_forgetting)),
            "ei_select": str(tpe_kwargs.pop("ei_select", "argmax")),
            "ei_tau": float(tpe_kwargs.pop("ei_tau", 1.0)),
            "prior_eps": float(tpe_kwargs.pop("prior_eps", 0.0)),
        }
        if tpe_kwargs:
            raise TypeError(f"unknown study kwargs: {sorted(tpe_kwargs)}")
        self.cfg_key = tuple(sorted(self.cfg.items()))
        self.state = "active"
        self.created = time.time()
        self.last_active = self.created
        self.n_asked = 0
        self.n_told = 0

    def next_seed(self):
        """One suggest seed per ask — exactly ``FMinIter``'s
        ``next_seed`` draw, so the study's proposal stream matches the
        sequential ``fmin`` it is pinned against."""
        return int(self.rstate.integers(2**31 - 1))

    def touch(self):
        self.last_active = time.time()

    @property
    def n_trials(self):
        return len(self.trials._dynamic_trials)

    @property
    def n_pending(self):
        return self.n_asked - self.n_told

    def best_loss(self):
        best = None
        for r in self.trials.results:
            loss = r.get("loss")
            if (r.get("status") == STATUS_OK and loss is not None
                    and (best is None or loss < best)):
                best = loss
        return best

    def status_dict(self):
        return {
            "study_id": self.study_id,
            "state": self.state,
            "labels": list(self.domain.cs.labels),
            "n_trials": self.n_trials,
            "n_pending": self.n_pending,
            "n_asked": self.n_asked,
            "n_told": self.n_told,
            "best_loss": self.best_loss(),
            "max_trials": self.max_trials,
            "created": self.created,
            "last_active": self.last_active,
            "seed": self.seed,
        }


class _AskReq:
    """One TPE ask waiting for a cohort tick."""

    __slots__ = ("study", "new_ids", "seed", "docs", "error")

    def __init__(self, study, new_ids, seed):
        self.study = study
        self.new_ids = new_ids
        self.seed = seed
        self.docs = None
        self.error = None


#: smallest cohort slot capacity.  Serving-scale studies are SMALL (tens
#: of trials), and the kernel's cost is dominated by cap-sized sorts and
#: mixture densities — a 128-cap slot for a 12-trial study wastes ~90% of
#: the tick.  Proposals are bitwise capacity-invariant (padding is fully
#: masked — pinned by test), so the cohort can run a much tighter bucket
#: than PaddedHistory's host _MIN_CAP without perturbing determinism.
#: Correctness never depends on slack: a study whose live count outgrows
#: its bucket migrates to the next cohort at its next ask (and the tick's
#: outgrow guard evicts it meanwhile), re-uploading from the
#: authoritative host arrays bit-for-bit.
_COHORT_CAP_FLOOR = 16


def _cohort_cap(n):
    """Power-of-two slot capacity for a study with ``n`` live trials
    (+1 so one settled trial between waves never forces a migration)."""
    cap = _COHORT_CAP_FLOOR
    while cap < n + 1:
        cap *= 2
    return cap


class _Cohort:
    """Fixed-shape device slots for studies sharing (space signature, TPE
    cfg, capacity bucket).  Owns the stacked ``[S, cap]`` device history
    mirror; per-study host arrays stay authoritative — admission uploads
    them once, ticks move only the small pending tell rows.  The cohort
    capacity is the GRADED bucket of :func:`_cohort_cap` — a slot holds
    the live prefix of the study's (possibly larger) host arrays, and a
    study that outgrows the bucket migrates to the next cohort."""

    _ROW_BUCKET = 16  # one fixed row bucket, like PaddedHistory's

    def __init__(self, cs, cfg, cap, hist_dtype="float32"):
        self.cs = cs
        self.cfg = dict(cfg)
        self.cap = int(cap)
        self.hist_dtype = str(hist_dtype)
        self.slots = [None]  # Study | None; length is a power of two
        self.slot_of = {}    # study_id -> slot index
        self._dev = None     # stacked history pytree, or None (rebuild)
        self._synced = {}    # slot -> host rows already folded on device
        self.ticks = 0

    @property
    def n_slots(self):
        return len(self.slots)

    @property
    def n_live(self):
        return len(self.slot_of)

    def admit(self, study):
        """Place ``study`` in a free slot, doubling the slot count when
        full (power-of-two shapes bound the compiled-program set).  The
        stacked mirror rebuilds on the next tick — admissions are rare
        next to ticks (startup graduation, re-admission after eviction)."""
        if study.study_id in self.slot_of:
            return self.slot_of[study.study_id]
        try:
            slot = self.slots.index(None)
        except ValueError:
            self.slots.extend([None] * len(self.slots))
            slot = self.slots.index(None)
        self.slots[slot] = study
        self.slot_of[study.study_id] = slot
        self._dev = None
        return slot

    def evict(self, study_id):
        """Free the study's slot.  The stale stack stays valid — an empty
        slot's rows are no-ops and its outputs are discarded — so
        eviction costs nothing until the slot is re-filled."""
        slot = self.slot_of.pop(study_id, None)
        if slot is not None:
            self.slots[slot] = None
            self._synced.pop(slot, None)
        return slot

    def _history(self, study):
        return study.trials.history_object(self.cs.labels)

    def _upload_stack(self, mesh=None):
        """Full build of the stacked device mirror from every slotted
        study's host arrays (admission / growth / recovery path)."""
        L = self.cs.labels
        S, cap = self.n_slots, self.cap
        vals = {l: np.zeros((S, cap), np.float32) for l in L}
        active = {l: np.zeros((S, cap), bool) for l in L}
        losses = np.full((S, cap), np.inf, np.float32)
        has_loss = np.zeros((S, cap), bool)
        for slot, st in enumerate(self.slots):
            if st is None:
                continue
            ph = self._history(st)
            host = ph.host_padded()
            c = min(cap, ph.cap)  # live prefix; the rest stays padding
            for l in L:
                vals[l][slot, :c] = host["vals"][l][:c]
                active[l][slot, :c] = host["active"][l][:c]
            losses[slot, :c] = host["losses"][:c]
            has_loss[slot, :c] = host["has_loss"][:c]
            self._synced[slot] = ph.n
        dt = jnp.dtype(self.hist_dtype)

        def put(x, floating):
            # jnp.array (copy=True), NOT jnp.asarray: the stack is DONATED
            # into every tick, and on the CPU backend asarray can zero-copy
            # the numpy buffer — donating an aliased buffer lets XLA free
            # memory numpy still owns (glibc "corrupted double-linked
            # list" at the next teardown; reproduced before this guard)
            arr = jnp.array(x, dtype=dt if floating else None)
            if mesh is not None:
                from jax.sharding import NamedSharding, PartitionSpec as P

                arr = jax.device_put(
                    arr, NamedSharding(mesh, P(mesh.axis_names)))
            return arr

        self._dev = {
            "vals": {l: put(vals[l], True) for l in L},
            "active": {l: put(active[l], False) for l in L},
            "losses": put(losses, True),
            "has_loss": put(has_loss, False),
        }

    def tick(self, demand, donate=True, mesh=None):
        """One batched fused tell+ask DISPATCH for the whole cohort.

        ``demand``: ``{slot: (ids_uint32, seed)}`` — at most one ask per
        slot.  Every occupied slot's pending tell rows fold (asking or
        not), so the mirror never lags the host state.  Returns the
        in-flight ``packed [S, B, L]`` device array — the caller reads it
        back AFTER dispatching every other cohort's tick, so one
        cohort's host-side doc building overlaps the next cohort's
        device compute (the wave-level analog of PR 4's
        dispatch/readback overlap).
        """
        self.ticks += 1
        L = len(self.cs.labels)
        B = _pow2(max((len(ids) for ids, _ in demand.values()), default=1))

        # a slot whose study outgrew this capacity bucket is evicted (its
        # next ask re-admits it to the right cohort; the host arrays are
        # authoritative, so nothing is lost) — folding its rows here
        # would scatter past the slot.  A slot that told more than K
        # trials since its last tick forces a full re-upload (rare:
        # serving waves tell a handful per study).
        phs = {}
        for slot, st in enumerate(self.slots):
            if st is None:
                continue
            ph = self._history(st)
            if ph.n > self.cap:
                self.evict(st.study_id)
                continue
            phs[slot] = ph
        # adaptive row bucket: the scatter's cost scales with K, and a
        # serving wave folds one or two rows per slot — pow2-bucketed so
        # the program set stays {K=1,2,4,8,16}; more than _ROW_BUCKET
        # pending rows forces a full re-upload instead
        delta = max([ph.n - self._synced.get(slot, 0)
                     for slot, ph in phs.items()] or [0])
        if self._dev is not None and delta > self._ROW_BUCKET:
            self._dev = None
        if self._dev is None:
            self._upload_stack(mesh=mesh)
            delta = 0
        K = _pow2(max(delta, 1))

        S = self.n_slots
        R = 2 * L + 3
        rows = np.zeros((S, K, R), np.float32)
        rows[:, :, R - 1] = float(self.cap)  # default: dropped no-op
        seed_words = np.zeros((S, 2), np.uint32)
        ids = np.zeros((S, B), np.uint32)
        pending_sync = {}
        for slot, ph in phs.items():
            rows[slot] = ph.pack_rows(self._synced.get(slot, 0), K,
                                      noop_index=self.cap)
            pending_sync[slot] = ph.n
        for slot, (slot_ids, seed) in demand.items():
            seed_words[slot] = tpe._seed_words(seed)
            ids[slot, : len(slot_ids)] = slot_ids
            if len(slot_ids) < B:  # pad by repeating the last id
                ids[slot, len(slot_ids):] = slot_ids[-1]

        run = tpe.build_suggest_batched(
            self.cs, self.cfg, S, self.cap, B, donate=donate, mesh=mesh)
        try:
            new_dev, packed = run(self._dev, rows, seed_words, ids)
        except BaseException:
            # with donation armed the input stack may already be invalid:
            # drop it and rebuild from the authoritative host arrays
            self._dev = None
            self._synced = {}
            raise
        self._dev = new_dev
        self._synced.update(pending_sync)
        return packed

    def abandon_device(self):
        """Drop the (possibly donated-and-poisoned) device stack after a
        failed dispatch or readback; the next tick rebuilds it from the
        authoritative host arrays."""
        self._dev = None
        self._synced = {}


class StudyScheduler:
    """Create/ask/tell over many studies, batched onto cohort ticks.

    Thread-safe.  Concurrent ``ask`` callers coalesce through the
    ``wave_window`` gather pause: the first thread to become the wave
    ticker releases the lock for that window, every asker that arrives
    meanwhile enqueues into the SAME wave, and one batched device tick
    per cohort serves them all.  With ``wave_window=0`` (the default for
    direct in-process use) asks serialize — single-threaded drivers
    should express waves explicitly with :meth:`ask_many`; the HTTP
    server always runs with a small window.

    ``store_root`` persists every study through the existing
    ``FileStore`` (one subdirectory per study id); default is in-memory
    :class:`~hyperopt_tpu.base.Trials`.
    """

    def __init__(self, max_studies=None, max_pending=None, idle_sec=None,
                 store_root=None, wave_window=0.0):
        from .._env import (parse_service_idle_sec,
                            parse_service_max_pending,
                            parse_service_max_studies)

        self.max_studies = (parse_service_max_studies()
                            if max_studies is None else int(max_studies))
        self.max_pending = (parse_service_max_pending()
                            if max_pending is None else int(max_pending))
        self.idle_sec = (parse_service_idle_sec()
                         if idle_sec is None else float(idle_sec))
        if self.idle_sec <= 0:
            # 0 means "never evict on idleness" EVERYWHERE (env grammar,
            # CLI, constructor) — a literal 0 would instead evict every
            # slot at every wave and re-upload every cohort stack
            self.idle_sec = float("inf")
        self.store_root = store_root
        self.wave_window = float(wave_window)
        self._lock = threading.RLock()
        self._cond = threading.Condition(self._lock)
        self._studies = {}
        self._cohorts = {}  # (sig, cfg_key, cap) -> _Cohort
        self._wave_reqs = []
        self._tick_running = False
        self.metrics = get_metrics("service")

    # -- study lifecycle ---------------------------------------------------

    def create_study(self, space, seed=0, study_id=None, **kwargs):
        """Admit a new study; returns its id (``filestore.new_run_id``).
        Raises :class:`StudyQuotaError` past the ``max_studies`` quota."""
        from ..filestore import FileTrials, new_run_id

        with self._lock:
            live = sum(1 for s in self._studies.values()
                       if s.state == "active")
            if live >= self.max_studies:
                raise StudyQuotaError(
                    f"study quota reached ({self.max_studies} live studies)")
            study_id = study_id or new_run_id("study")
            if study_id in self._studies:
                raise StudyQuotaError(f"study id {study_id!r} already exists")
            trials = None
            if self.store_root is not None:
                import os

                trials = FileTrials(os.path.join(self.store_root, study_id))
            st = Study(study_id, space, seed=seed, trials=trials, **kwargs)
            self._studies[study_id] = st
            self.metrics.counter("service.studies_created").inc()
            self.metrics.gauge("service.studies_live").set(live + 1)
            return study_id

    def close_study(self, study_id):
        """Mark a study done and free its cohort slot (its trials stay
        queryable; the admission quota counts only active studies)."""
        with self._lock:
            st = self._get(study_id)
            st.state = "closed"
            self._evict_from_cohort(st)
            self._gc_cohorts()
            self.metrics.gauge("service.studies_live").set(
                sum(1 for s in self._studies.values()
                    if s.state == "active"))

    def _get(self, study_id):
        st = self._studies.get(study_id)
        if st is None:
            raise UnknownStudyError(study_id)
        return st

    # -- cohort packing ----------------------------------------------------

    def _cohort_for(self, st):
        """The cohort matching the study's (space, cfg, capacity) — moving
        the study between cohorts when its capacity bucket grew."""
        ph = st.trials.history_object(st.domain.cs.labels)
        cap = _cohort_cap(ph.n)
        key = (st.domain.cs.signature(), st.cfg_key, cap)
        cohort = self._cohorts.get(key)
        if cohort is None:
            from .._env import parse_hist_dtype

            cohort = self._cohorts[key] = _Cohort(
                st.domain.cs, st.cfg, cap, hist_dtype=parse_hist_dtype())
        if st.study_id not in cohort.slot_of:
            # evict from any smaller-capacity cohort it may still occupy
            self._evict_from_cohort(st)
            cohort.admit(st)
        return cohort

    def _evict_from_cohort(self, st):
        for cohort in self._cohorts.values():
            if cohort.evict(st.study_id) is not None:
                self.metrics.counter("service.evictions").inc()

    def evict_idle(self, now=None):
        """Free cohort slots of studies idle past ``idle_sec`` (the study
        itself survives — its next ask re-admits it bit-identically from
        the host arrays)."""
        now = time.time() if now is None else now
        with self._lock:
            for st in self._studies.values():
                if (st.state == "active"
                        and now - st.last_active > self.idle_sec):
                    self._evict_from_cohort(st)

    def _gc_cohorts(self):
        """Drop cohorts with no live slots.  Studies migrate between
        capacity buckets as they grow, and an abandoned cohort would
        otherwise pin its full stacked device mirror forever (and
        permanently depress slot utilization)."""
        with self._lock:
            for key in [k for k, c in self._cohorts.items()
                        if c.n_live == 0]:
                del self._cohorts[key]

    def slot_utilization(self):
        """Occupied fraction of all cohort slots (1.0 = perfectly packed)."""
        with self._lock:
            total = sum(c.n_slots for c in self._cohorts.values())
            live = sum(c.n_live for c in self._cohorts.values())
            return (live / total) if total else 0.0

    # -- ask / tell --------------------------------------------------------

    def _prepare_ask(self, st, n):
        """Draw ids + seed for one ask, exactly as ``FMinIter`` would.
        Returns finished docs (startup random search, served inline) or an
        :class:`_AskReq` awaiting a cohort tick."""
        if st.state != "active":
            raise UnknownStudyError(f"{st.study_id} is {st.state}")
        n = int(n)
        if n < 1:
            raise ValueError("ask n must be >= 1")
        if st.n_pending + n > self.max_pending:
            raise StudyQuotaError(
                f"{st.study_id}: {st.n_pending} pending + {n} asked would "
                f"exceed the per-study quota ({self.max_pending})")
        if (st.max_trials is not None
                and st.n_trials + n > st.max_trials):
            raise StudyQuotaError(
                f"{st.study_id}: budget exhausted "
                f"({st.n_trials}/{st.max_trials} trials)")
        new_ids = st.trials.new_trial_ids(n)
        st.trials.refresh()
        seed = st.next_seed()
        st.touch()
        st.n_asked += n
        self.metrics.counter("service.asks").inc()
        if len(st.trials.trials) < st.n_startup_jobs:
            docs = rand.suggest(new_ids, st.domain, st.trials, seed)
            self._land(st, docs)
            return docs
        return _AskReq(st, new_ids, seed)

    def _land(self, st, docs):
        st.trials.insert_trial_docs(docs)
        st.trials.refresh()

    def _answers(self, st, docs):
        return [{"study_id": st.study_id, "tid": d["tid"],
                 "params": spec_from_misc(d["misc"])} for d in docs]

    def _run_wave(self, reqs):
        """Group pending asks by cohort and run one tick per cohort (a
        study asked twice in one wave falls to a follow-up round so each
        tick carries at most one ask per slot)."""
        from .._env import parse_shard
        from ..parallel import sharding as _sh

        self.evict_idle()
        while reqs:
            this_round, leftover, seen = [], [], set()
            for r in reqs:
                (leftover if r.study.study_id in seen
                 else this_round).append(r)
                seen.add(r.study.study_id)
            by_cohort = {}
            for r in this_round:
                try:
                    cohort = self._cohort_for(r.study)
                except Exception as e:  # noqa: BLE001 - per-req isolation
                    r.error = e
                    continue
                by_cohort.setdefault(id(cohort), (cohort, []))[1].append(r)
            n_shard = parse_shard()
            # dispatch phase: every cohort's fused program goes onto the
            # device queue before any readback, so the Python doc building
            # below overlaps the remaining cohorts' device compute
            dispatched = []
            for cohort, cohort_reqs in by_cohort.values():
                mesh = None
                if n_shard is not None:
                    m = _sh.suggest_mesh(n_shard)
                    n_dev = int(m.devices.size)
                    # the study axis must divide the mesh; small cohorts
                    # stay single-device rather than padding slots
                    if n_dev > 1 and cohort.n_slots % n_dev == 0:
                        mesh = m
                demand = {}
                for r in cohort_reqs:
                    slot = cohort.slot_of[r.study.study_id]
                    demand[slot] = (np.asarray(
                        [int(i) & 0xFFFFFFFF for i in r.new_ids],
                        np.uint32), r.seed)
                try:
                    packed = cohort.tick(demand,
                                         donate=tpe._donation_enabled(),
                                         mesh=mesh)
                except Exception as e:  # noqa: BLE001
                    for r in cohort_reqs:
                        r.error = e
                    continue
                dispatched.append((cohort, cohort_reqs, packed))
            # readback phase: block per cohort, build and land the docs
            for cohort, cohort_reqs, packed in dispatched:
                try:
                    mat = np.asarray(packed)
                except Exception as e:  # noqa: BLE001 - runtime XLA error
                    cohort.abandon_device()
                    for r in cohort_reqs:
                        r.error = e
                    continue
                for r in cohort_reqs:
                    # per-req isolation: a landing failure (e.g. a full
                    # disk under --store) must error THIS ask, not strand
                    # the rest of the wave unresolved
                    try:
                        slot = cohort.slot_of[r.study.study_id]
                        flats = rand.unpack_flats(
                            cohort.cs, mat[slot], len(r.new_ids))
                        docs = rand.flat_to_new_trial_docs(
                            r.study.domain, r.study.trials, r.new_ids,
                            flats)
                        self._land(r.study, docs)
                        r.docs = docs
                    except Exception as e:  # noqa: BLE001
                        r.error = e
                self.metrics.counter("service.ticks").inc()
                self.metrics.counter("service.tick_asks").inc(
                    len(cohort_reqs))
            reqs = leftover
        self._gc_cohorts()
        stats = tpe.cohort_cache_stats()
        self.metrics.gauge("suggest.cohort_cache.hits").set(stats["hits"])
        self.metrics.gauge("suggest.cohort_cache.misses").set(
            stats["misses"])
        self.metrics.gauge("service.slot_utilization").set(
            self.slot_utilization())

    def ask(self, study_id, n=1):
        """Propose ``n`` new trials for one study.  Concurrent callers
        coalesce: the first thread to reach a quiescent scheduler becomes
        the wave ticker and serves every enqueued ask in one batched
        device tick per cohort."""
        t0 = time.perf_counter()
        with self._cond:
            st = self._get(study_id)
            res = self._prepare_ask(st, n)
            if not isinstance(res, _AskReq):  # startup random search
                self.metrics.histogram("service.ask_sec").observe(
                    time.perf_counter() - t0)
                return self._answers(st, res)
            req = res
            self._wave_reqs.append(req)
            while req.docs is None and req.error is None:
                if self._tick_running:
                    self._cond.wait(timeout=0.25)
                    continue
                self._tick_running = True
                if self.wave_window > 0:
                    # gather window: let concurrent askers enqueue into
                    # this wave while the lock is released
                    self._cond.wait(timeout=self.wave_window)
                batch, self._wave_reqs = self._wave_reqs, []
                try:
                    self._run_wave(batch)
                except Exception as e:  # noqa: BLE001
                    # never strand a wave: an unresolved req would spin
                    # its asker forever (the batch left _wave_reqs above)
                    for r in batch:
                        if r.docs is None and r.error is None:
                            r.error = e
                finally:
                    self._tick_running = False
                    self._cond.notify_all()
        if req.error is not None:
            with self._lock:  # release the reserved pending quota
                req.study.n_asked -= len(req.new_ids)
            raise req.error
        self.metrics.histogram("service.ask_sec").observe(
            time.perf_counter() - t0)
        return self._answers(req.study, req.docs)

    def ask_many(self, requests):
        """Explicit wave: ``[(study_id, n), ...]`` asked in ONE batched
        tick per cohort.  Returns ``{study_id: [answers]}`` — the
        single-threaded driver's way to express an ask wave (bench, the
        determinism tests).

        Partial failure keeps the successes: a study whose cohort tick
        (or doc landing) failed is simply ABSENT from the result (its
        pending quota released, a warning logged) — raising would throw
        away the other studies' already-landed trials, orphaning NEW
        docs the caller could never tell.  Only an all-failed wave
        raises."""
        import logging

        with self._lock:
            out = {}
            reqs = []
            for study_id, n in requests:
                st = self._get(study_id)
                res = self._prepare_ask(st, n)
                if isinstance(res, _AskReq):
                    reqs.append(res)
                else:
                    out.setdefault(study_id, []).extend(
                        self._answers(st, res))
            self._run_wave(reqs)
            failed = []
            for r in reqs:
                if r.error is not None:
                    # release the failed req's pending quota, else
                    # repeated failures wedge the study at 429
                    r.study.n_asked -= len(r.new_ids)
                    failed.append(r)
                else:
                    out.setdefault(r.study.study_id, []).extend(
                        self._answers(r.study, r.docs))
            if failed:
                if not out:
                    raise failed[0].error
                logging.getLogger(__name__).warning(
                    "ask_many: %d of %d studies failed this wave "
                    "(first: %s: %s); returning the successes",
                    len(failed), len(reqs), type(failed[0].error).__name__,
                    failed[0].error)
            return out

    def tell(self, study_id, tid, loss=None, status=None):
        """Report one trial's result.  ``status`` defaults to ok with a
        finite loss, fail otherwise; the doc settles DONE and folds into
        the study's posterior at its next ask (the tell half of the fused
        tell+ask program)."""
        with self._lock:
            st = self._get(study_id)
            tid = int(tid)
            doc = next((d for d in st.trials._dynamic_trials
                        if d["tid"] == tid), None)
            if doc is None:
                raise UnknownStudyError(
                    f"{study_id}: no trial with tid {tid}")
            if doc["state"] == JOB_STATE_DONE:
                raise DuplicateTellError(
                    f"{study_id}: trial {tid} was already told")
            # a finite loss is REQUIRED for an ok record even when the
            # caller says status="ok" — an inf/NaN loss folded into the
            # posterior would poison every later EI split for the study
            ok = (loss is not None and math.isfinite(float(loss))
                  and (status is None or status == STATUS_OK))
            doc["result"] = ({"loss": float(loss), "status": STATUS_OK}
                             if ok else {"status": STATUS_FAIL})
            doc["state"] = JOB_STATE_DONE
            doc["refresh_time"] = coarse_utcnow()
            store = getattr(st.trials, "store", None)
            if store is not None:
                store.settle(doc)
            # base-class refresh on purpose: the doc was mutated in place
            # and written through above, so only the _trials view needs
            # rebuilding — FileTrials.refresh would rescan and unpickle
            # the study's whole on-disk store on every tell (O(n) files)
            Trials.refresh(st.trials)
            st.n_told += 1
            st.touch()
            self.metrics.counter("service.tells").inc()
            if (st.max_trials is not None
                    and st.n_trials >= st.max_trials and st.n_pending == 0):
                st.state = "done"
                self._evict_from_cohort(st)

    # -- status ------------------------------------------------------------

    def study_status(self, study_id):
        with self._lock:
            return self._get(study_id).status_dict()

    def studies_status(self):
        """The ``GET /studies`` payload: per-study status plus the
        cohort/slot roll-up."""
        with self._lock:
            cohorts = [{
                "space_sig": repr(key[0])[:64],
                "cap": c.cap,
                "n_slots": c.n_slots,
                "n_live": c.n_live,
                "ticks": c.ticks,
            } for key, c in self._cohorts.items()]
            return {
                "ts": time.time(),
                "n_studies": len(self._studies),
                "slot_utilization": self.slot_utilization(),
                "cohort_cache": tpe.cohort_cache_stats(),
                "cohorts": cohorts,
                "studies": [s.status_dict()
                            for s in self._studies.values()],
            }
