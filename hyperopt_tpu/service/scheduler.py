"""Admitting scheduler: pack live studies into cohort slots, tick once per
ask wave.

One ``fmin`` owning the whole mesh wastes almost all of the kernel's
throughput on small studies; a production service runs thousands of them
at once.  This module is the host side of the multi-study batch
(ISSUE 9): studies sharing a search space land in a **cohort** — a
fixed-shape stack of device history slots — and every ask wave runs ONE
study-batched fused tell+ask program (``tpe.build_suggest_batched``) for
the whole cohort instead of one device dispatch per study.

Determinism contract (tier-1 pinned): a cohort of N studies proposes
bit-identically to N independent sequential ``fmin`` runs at the same
per-study seeds.  Everything the scheduler does preserves that:

* the per-study ask flow mirrors ``FMinIter._run`` exactly — draw
  ``new_ids`` from the study's Trials, one seed per ask from the study's
  ``rstate`` (``integers(2**31 - 1)``), random search below
  ``n_startup_jobs``, the TPE cfg dict built like ``tpe.suggest``'s;
* per-id PRNG keys derive from the id VALUE and the study seed, never
  from slot position or wave composition, so cohort packing, slot
  padding and eviction/re-admission are all proposal-invariant;
* the cohort's device stack mirrors the per-study host
  ``PaddedHistory`` arrays (the authoritative state) — an evicted study
  re-admits by re-uploading them, bit-for-bit.

Cohort shapes are static by construction: slot counts grow in powers of
two, every study in a cohort shares the space signature, TPE cfg and
capacity bucket, and ask widths pad to a power of two — so the compiled
program LRU (``tpe._cohort_jit_cache``, surfaced as the
``suggest.cohort_cache`` metrics) sees a handful of shapes, not one per
wave.

Durability & device-fault tolerance (ISSUE 10): when a write-ahead
journal is armed (``service/journal.py`` — automatic with a store root),
every admit/ask/tell appends a WAL record before the scheduler's state
advances, and :meth:`StudyScheduler.resume` replays the journal on
construction so a restarted service re-admits every study and proposes
bit-identically to an uninterrupted run.  Device faults during a cohort
tick (OOM, compile failure, non-finite proposals, injected chaos) walk
the :class:`~hyperopt_tpu.service.overload.DegradeLadder` instead of
failing the wave — down to a per-study ``rand.suggest`` fallback, never
killing the server — and climb back after clean waves.
"""

from __future__ import annotations

import logging
import math
import os
import threading
import time
from collections import deque

import numpy as np

import jax
import jax.numpy as jnp

from .. import chaos
from ..algos import rand, tpe
from ..base import (
    JOB_STATE_DONE,
    STATUS_FAIL,
    STATUS_OK,
    Domain,
    Trials,
    coarse_utcnow,
    spec_from_misc,
)
from ..obs import reqtrace
from ..obs.metrics import get_metrics
from ..obs.trace import Tracer
from . import integrity
from .integrity import StoreFullError
from .journal import JournalError, StudyJournal, wal_path_for
from .overload import (LADDER_LEVELS, DeadlineExceeded, DegradeLadder,
                       NonFiniteProposal, is_device_fault)

__all__ = ["StudyScheduler", "Study", "StudyQuotaError",
           "UnknownStudyError", "DuplicateTellError", "DrainingError",
           "StaleOwnershipError", "QuarantinedStudyError"]


class UnknownStudyError(KeyError):
    """No live study with that id (never created, or closed)."""


class QuarantinedStudyError(RuntimeError):
    """The study's journal state was found corrupt and the study is
    quarantined (ISSUE 15): ask/tell/close answer HTTP **410 Gone** —
    permanent until an operator repairs the store (``python -m
    hyperopt_tpu.service.scrub --repair``).  Every OTHER study on the
    same root keeps serving bit-identically; quarantine is a per-study
    fault, never a process fault."""


class StudyQuotaError(RuntimeError):
    """An admission or per-study quota would be exceeded (HTTP 429)."""


class DrainingError(RuntimeError):
    """The service is draining (SIGTERM received): new studies and asks
    are refused (HTTP 503 + ``Retry-After`` — come back after the
    restart), tells still land."""


class DuplicateTellError(RuntimeError):
    """The trial was already told (HTTP 409 — a PERMANENT conflict, not a
    retryable quota: a client retrying a lost tell response must not
    back off forever on a 429)."""


class StaleOwnershipError(RuntimeError):
    """The shard lease backing this scheduler was reclaimed (fleet
    mode, ISSUE 12): the mutation was refused BEFORE anything became
    durable, so the fenced-off epoch WAL gains no record the new
    owner's replay never saw.  Retryable (HTTP 503) — the client's
    retry routes to the new owner via the ownership table."""


def _pow2(n):
    b = 1
    while b < n:
        b *= 2
    return b


#: scheduler spans (service.wave / service.tick) and degrade events feed
#: the process-global flight ring through a sink-less tracer — per-WAVE
#: cost, not per-ask, so the disarmed hot path stays flat
_tracer = Tracer()

#: bound on each study's in-memory audit timeline; the WAL is the
#: durable record, this ring is the live `GET /study/<id>/timeline` view
_STUDY_EVENT_CAP = 512

#: bound on each study's served-ask idempotency map: the retry window
#: only ever needs the most recent handful of request ids, and an
#: unbounded map would grow one entry per ask forever
_SERVED_REQ_CAP = 128


class Study:
    """One study's serving state: compiled space, trials, RNG stream and
    quotas.  The ask/tell flow over these fields reproduces ``FMinIter``'s
    loop, which is what makes the cohort determinism pin possible."""

    def __init__(self, study_id, space, seed=0, n_startup_jobs=None,
                 max_trials=None, trials=None, space_spec=None,
                 canary=False, tenant=None, **tpe_kwargs):
        from ..obs.tenant import ANON, sanitize_tenant

        self.study_id = study_id
        # canary (ISSUE 18): a synthetic blackbox-prober study.  Serves
        # EXACTLY like a tenant study (same ask/tell/WAL path — that is
        # the point of probing), but is excluded from the quality and
        # load tenant telemetry, device-time charging and the census
        # bank, so canary traffic is free.  Round-trips through the WAL
        # admit record like every other admit kwarg.
        self.canary = bool(canary)
        # tenant (ISSUE 20): the opaque principal the study's device
        # time, tells and sheds are attributed to.  Bounded + sanitized
        # here too (a direct-API caller gets the same ValueError the
        # HTTP layer maps to 400); "anon" is the default principal and
        # is NOT stamped into the admit kwargs, so pre-ISSUE-20
        # journals — and tenantless new ones — stay byte-identical.
        self.tenant = sanitize_tenant(tenant)
        self.domain = Domain(None, space)
        self.trials = trials if trials is not None else Trials()
        self.rstate = np.random.default_rng(seed)
        self.seed = int(seed)
        # the WAL registry entry: the JSON-wire space schema (or zoo
        # wrapper) this study can be rebuilt from, plus the admit kwargs
        # verbatim.  None spec = not resumable (direct API studies that
        # never crossed the wire) — journaled anyway so replay can COUNT
        # what it had to skip.
        self.space_spec = space_spec
        self.admit_kwargs = {}
        if self.canary:
            self.admit_kwargs["canary"] = True
        if self.tenant != ANON:
            self.admit_kwargs["tenant"] = self.tenant
        if n_startup_jobs is not None:
            self.admit_kwargs["n_startup_jobs"] = int(n_startup_jobs)
        if max_trials is not None:
            self.admit_kwargs["max_trials"] = int(max_trials)
        self.admit_kwargs.update(
            {k: v for k, v in tpe_kwargs.items()})
        self.n_startup_jobs = int(n_startup_jobs
                                  if n_startup_jobs is not None
                                  else tpe._default_n_startup_jobs)
        self.max_trials = None if max_trials is None else int(max_trials)
        # mirror tpe.suggest_async's cfg construction field for field so
        # the cohort kernel and the single-study kernel share cache keys
        # downstream of the same space
        self.cfg = {
            "prior_weight": float(tpe_kwargs.pop(
                "prior_weight", tpe._default_prior_weight)),
            "n_EI_candidates": int(tpe_kwargs.pop(
                "n_EI_candidates", tpe._default_n_EI_candidates)),
            "gamma": float(tpe_kwargs.pop("gamma", tpe._default_gamma)),
            "LF": int(tpe_kwargs.pop("linear_forgetting",
                                     tpe._default_linear_forgetting)),
            "ei_select": str(tpe_kwargs.pop("ei_select", "argmax")),
            "ei_tau": float(tpe_kwargs.pop("ei_tau", 1.0)),
            "prior_eps": float(tpe_kwargs.pop("prior_eps", 0.0)),
        }
        if tpe_kwargs:
            raise TypeError(f"unknown study kwargs: {sorted(tpe_kwargs)}")
        self.cfg_key = tuple(sorted(self.cfg.items()))
        self.state = "active"
        self.created = time.time()
        self.last_active = self.created
        self.n_asked = 0
        self.n_told = 0
        # warming (ISSUE 14): True while this study's cohort program is
        # still compiling in the background and its TPE-eligible asks
        # are served by flagged rand.suggest; cleared ("promoted") at
        # the first wave served on-device.  Pure serving metadata —
        # never feeds the RNG or the WAL replay.
        self.warming = False
        # the live audit timeline (ISSUE 11): one bounded ring of
        # lifecycle events — admit, every ask (wave/algo/degrade/trace),
        # every tell, shed/void, evict/re-admit, resume boundary —
        # served by `GET /study/<id>/timeline` and joined with the WAL
        # by `obs.report --study`
        self.events = deque(maxlen=_STUDY_EVENT_CAP)
        self.events_dropped = 0
        # ask idempotency (ISSUE 12): client request id -> the tids that
        # ask served.  A RETRIED ask (its response was lost to a crash
        # or a dropped connection AFTER the ask record became durable)
        # answers the SAME trials instead of drawing a fresh seed — the
        # ask-side analog of 409-on-retried-tell.  Bounded (insertion
        # order), journaled on the ask record, snapshot-carried, and
        # rebuilt by WAL replay so the dedupe survives crashes AND
        # shard migrations.
        self.served_reqs = {}
        # incremental best-loss (ISSUE 16): maintained at tell/replay
        # time so /studies scrapes and the quality plane read O(1)
        # instead of rescanning every result doc.  Dirty at construction
        # — a FileTrials handed in here may already hold DONE docs
        # (re-admission, store-ahead reconciliation), so the first read
        # scans once and every tell after that is a min-update.
        self._best = None
        self._best_dirty = True

    def remember_req(self, req_id, tids):
        if not req_id:
            return
        self.served_reqs[str(req_id)] = [int(t) for t in tids]
        while len(self.served_reqs) > _SERVED_REQ_CAP:
            del self.served_reqs[next(iter(self.served_reqs))]

    def note(self, event, **attrs):
        """Append one audit-timeline event (pure metadata — never feeds
        the RNG or the proposals)."""
        if len(self.events) == self.events.maxlen:
            self.events_dropped += 1
        rec = {"ts": time.time(), "event": event}
        rec.update({k: v for k, v in attrs.items() if v is not None})
        self.events.append(rec)

    def timeline_dict(self):
        """The ``GET /study/<id>/timeline`` payload."""
        return {
            "study_id": self.study_id,
            "state": self.state,
            "seed": self.seed,
            "created": self.created,
            "n_trials": self.n_trials,
            "n_asked": self.n_asked,
            "n_told": self.n_told,
            "best_loss": self.best_loss(),
            "events": list(self.events),
            "events_dropped": self.events_dropped,
        }

    def next_seed(self):
        """One suggest seed per ask — exactly ``FMinIter``'s
        ``next_seed`` draw, so the study's proposal stream matches the
        sequential ``fmin`` it is pinned against."""
        return int(self.rstate.integers(2**31 - 1))

    def touch(self):
        self.last_active = time.time()

    @property
    def n_trials(self):
        return len(self.trials._dynamic_trials)

    @property
    def n_pending(self):
        return self.n_asked - self.n_told

    def best_loss(self):
        """Best ok loss so far — O(1) after the first read.  The full
        rescan runs only while dirty (construction, or an external
        refresh of the backing trials); every settled tell keeps the
        cache current via :meth:`record_result`."""
        if self._best_dirty:
            best = None
            for r in self.trials.results:
                loss = r.get("loss")
                if (r.get("status") == STATUS_OK and loss is not None
                        and (best is None or loss < best)):
                    best = loss
            self._best = best
            self._best_dirty = False
        return self._best

    def record_result(self, loss):
        """Fold one settled ok loss (None = failed trial) into the
        incremental best.  While dirty the next :meth:`best_loss` scan
        will pick the doc up anyway, so this stays a pure min-update."""
        if loss is None or self._best_dirty:
            return
        loss = float(loss)
        if self._best is None or loss < self._best:
            self._best = loss

    def mark_best_dirty(self):
        """Invalidate the cached best — required after any path that
        mutates the backing trials without going through
        ``_apply_tell`` (fleet-mode cross-shard refresh)."""
        self._best_dirty = True

    def status_dict(self):
        out = {
            "study_id": self.study_id,
            "state": self.state,
            "labels": list(self.domain.cs.labels),
            "n_trials": self.n_trials,
            "n_pending": self.n_pending,
            "n_asked": self.n_asked,
            "n_told": self.n_told,
            "best_loss": self.best_loss(),
            "max_trials": self.max_trials,
            "created": self.created,
            "last_active": self.last_active,
            "seed": self.seed,
            "warming": self.warming,
        }
        if self.canary:
            # only stamped on synthetic prober studies — tenant status
            # payloads stay byte-for-byte what they always were
            out["canary"] = True
        from ..obs.tenant import ANON

        if self.tenant != ANON:
            # same conditional-stamp rule: anonymous studies keep the
            # pre-ISSUE-20 status payload byte-for-byte
            out["tenant"] = self.tenant
        return out


class _AskReq:
    """One TPE ask waiting for a cohort tick.  ``algo`` records what
    actually served it ("tpe", or "rand" under the degrade ladder) — it
    rides into the WAL record and the flagged ask response; ``replay``
    marks a WAL-regeneration req (already journaled — must not journal
    again); ``deadline`` is the request's monotonic budget."""

    __slots__ = ("study", "new_ids", "seed", "docs", "error", "algo",
                 "degraded", "replay", "deadline", "journaled", "trace",
                 "wave", "req", "warming")

    def __init__(self, study, new_ids, seed, deadline=None, replay=False,
                 trace=None, req=None):
        self.study = study
        self.new_ids = new_ids
        self.seed = seed
        self.docs = None
        self.error = None
        self.algo = "tpe"
        self.degraded = False
        # served at the rand floor because the cohort program is still
        # compiling (ISSUE 14) — flagged in the response, recorded as
        # algo:"rand" in the WAL exactly like the degrade floor
        self.warming = False
        self.replay = replay
        self.deadline = deadline
        # request-trace id (ISSUE 11): captured from the ambient context
        # at ingress, carried into the wave span's links, the cohort-tick
        # stamp, the WAL ask record and the study's audit timeline
        self.trace = trace
        self.req = req  # client idempotency token (ISSUE 12)
        self.wave = None  # wave sequence number, stamped by the ticker
        # True once the served-ask record is in the WAL: a later failure
        # (doc landing) must NOT also journal a void record — two
        # records would replay the one seed draw twice
        self.journaled = False


#: smallest cohort slot capacity.  Serving-scale studies are SMALL (tens
#: of trials), and the kernel's cost is dominated by cap-sized sorts and
#: mixture densities — a 128-cap slot for a 12-trial study wastes ~90% of
#: the tick.  Proposals are bitwise capacity-invariant (padding is fully
#: masked — pinned by test), so the cohort can run a much tighter bucket
#: than PaddedHistory's host _MIN_CAP without perturbing determinism.
#: Correctness never depends on slack: a study whose live count outgrows
#: its bucket migrates to the next cohort at its next ask (and the tick's
#: outgrow guard evicts it meanwhile), re-uploading from the
#: authoritative host arrays bit-for-bit.
_COHORT_CAP_FLOOR = 16


def _cohort_cap(n):
    """Power-of-two slot capacity for a study with ``n`` live trials
    (+1 so one settled trial between waves never forces a migration)."""
    cap = _COHORT_CAP_FLOOR
    while cap < n + 1:
        cap *= 2
    return cap


class _Cohort:
    """Fixed-shape device slots for studies sharing (space signature, TPE
    cfg, capacity bucket).  Owns the stacked ``[S, cap]`` device history
    mirror; per-study host arrays stay authoritative — admission uploads
    them once, ticks move only the small pending tell rows.  The cohort
    capacity is the GRADED bucket of :func:`_cohort_cap` — a slot holds
    the live prefix of the study's (possibly larger) host arrays, and a
    study that outgrows the bucket migrates to the next cohort."""

    _ROW_BUCKET = 16  # one fixed row bucket, like PaddedHistory's

    def __init__(self, cs, cfg, cap, hist_dtype="float32", widen=None):
        from .. import quant

        self.cs = cs
        self.cfg = dict(cfg)
        self.cap = int(cap)
        # int8/fp8 resolve to (name, per-label qparams) when the space is
        # codable, else degrade to bf16 here — the cohort's hist_dtype is
        # always the EFFECTIVE storage name (what cohort_key carries)
        self.hist_dtype, self.qparams = quant.resolve(
            cs, str(hist_dtype), context="cohort")
        self._mk_armed = None  # lazy megakernel.armed(cs) cache
        self.slots = [None]  # Study | None; length is a power of two
        self.slot_of = {}    # study_id -> slot index
        self._dev = None     # stacked history pytree, or None (rebuild)
        self._synced = {}    # slot -> host rows already folded on device
        self.ticks = 0
        self.last_key = None  # (program LRU key, K) of the latest tick
        # compile-plane hot-path caches (ISSUE 14): program keys per
        # (S, B, donate, mesh geom) and the census key id — both pure
        # functions of the cohort's identity, recomputed otherwise on
        # EVERY wave forever
        self._plane_keys = {}
        self._census_kid = None
        # widened-program mode (ISSUE 14): the device stack uses the
        # positional [S, W, cap] slot layout and ticks run the
        # profile-keyed program every compatible space shares.  ``widen``
        # is (profile, slots, wparams) from tpe.widened_profile/params.
        self.widen = widen
        if widen is not None:
            profile, wslots, wparams = widen
            self.wide_profile = profile
            self.wide_W = sum(e[-1] for e in profile)
            self.wparams = wparams
            # canonical slot index of every real label, in cs.labels order
            # (what extract() selects out of the packed [B, W] readback)
            slot_of_label = {}
            off = 0
            for entry, ls in zip(profile, wslots):
                for i, l in enumerate(ls):
                    slot_of_label[l] = off + i
                off += entry[-1]
            self.wide_cols = np.asarray(
                [slot_of_label[l] for l in cs.labels], np.intp)

    def megakernel_armed(self):
        """Whether this cohort's ticks run the fused Pallas program right
        now (drives the tick's child spans, the roofline capture and the
        ``suggest.megakernel`` gauge).  Re-checked per tick — a lowering
        failure disarms the space mid-run and the jnp program takes over
        under its recomputed key."""
        from .. import megakernel

        if self._mk_armed is None:
            # the space-shape check never changes; cache it
            self._mk_armed = (self.widen is None
                              and megakernel.supports(self.cs))
        return bool(self._mk_armed) and megakernel.armed(self.cs)

    @property
    def n_slots(self):
        return len(self.slots)

    @property
    def n_live(self):
        return len(self.slot_of)

    def admit(self, study):
        """Place ``study`` in a free slot, doubling the slot count when
        full (power-of-two shapes bound the compiled-program set).  The
        stacked mirror rebuilds on the next tick — admissions are rare
        next to ticks (startup graduation, re-admission after eviction)."""
        if study.study_id in self.slot_of:
            return self.slot_of[study.study_id]
        try:
            slot = self.slots.index(None)
        except ValueError:
            self.slots.extend([None] * len(self.slots))
            slot = self.slots.index(None)
        self.slots[slot] = study
        self.slot_of[study.study_id] = slot
        self._dev = None
        return slot

    def evict(self, study_id):
        """Free the study's slot.  The stale stack stays valid — an empty
        slot's rows are no-ops and its outputs are discarded — so
        eviction costs nothing until the slot is re-filled."""
        slot = self.slot_of.pop(study_id, None)
        if slot is not None:
            self.slots[slot] = None
            self._synced.pop(slot, None)
        return slot

    def _history(self, study):
        ph = study.trials.history_object(self.cs.labels)
        if self.qparams is not None:
            # snap-at-ingest (quant.py rule 2): arm the study's host
            # history so every value it records is an exact grid point —
            # host uploads and in-trace row folds then encode identically
            ph.ensure_qparams(self.cs)
        return ph

    def _upload_stack(self, mesh=None):
        """Full build of the stacked device mirror from every slotted
        study's host arrays (admission / growth / recovery path).
        Widened cohorts build the positional ``[S, W, cap]`` layout
        instead of the per-label dict — same values in the real slots,
        zeros (inactive) in the padding lanes."""
        L = self.cs.labels
        S, cap = self.n_slots, self.cap
        wide = self.widen is not None
        if wide:
            W = self.wide_W
            vals_w = np.zeros((S, W, cap), np.float32)
            active_w = np.zeros((S, W, cap), bool)
        else:
            vals = {l: np.zeros((S, cap), np.float32) for l in L}
            active = {l: np.zeros((S, cap), bool) for l in L}
        losses = np.full((S, cap), np.inf, np.float32)
        has_loss = np.zeros((S, cap), bool)
        for slot, st in enumerate(self.slots):
            if st is None:
                continue
            ph = self._history(st)
            host = ph.host_padded()
            c = min(cap, ph.cap)  # live prefix; the rest stays padding
            for j, l in enumerate(L):
                if wide:
                    w = self.wide_cols[j]
                    vals_w[slot, w, :c] = host["vals"][l][:c]
                    active_w[slot, w, :c] = host["active"][l][:c]
                else:
                    vals[l][slot, :c] = host["vals"][l][:c]
                    active[l][slot, :c] = host["active"][l][:c]
            losses[slot, :c] = host["losses"][:c]
            has_loss[slot, :c] = host["has_loss"][:c]
            self._synced[slot] = ph.n
        from .. import quant

        quantized = self.qparams is not None
        vdt = None if quantized else jnp.dtype(self.hist_dtype)
        ldt = quant.losses_dtype(self.hist_dtype)

        def put(x, dtype=None):
            # jnp.array (copy=True), NOT jnp.asarray: the stack is DONATED
            # into every tick, and on the CPU backend asarray can zero-copy
            # the numpy buffer — donating an aliased buffer lets XLA free
            # memory numpy still owns (glibc "corrupted double-linked
            # list" at the next teardown; reproduced before this guard)
            arr = jnp.array(x, dtype=dtype)
            if mesh is not None:
                from jax.sharding import NamedSharding, PartitionSpec as P

                arr = jax.device_put(
                    arr, NamedSharding(mesh, P(mesh.axis_names)))
            return arr

        def enc(x, label):
            # int8/fp8: host-side affine encode of snapped grid values —
            # same op order as the in-trace fold (quant.quantize), so the
            # scatter and the upload agree code for code
            if not quantized:
                return put(x, vdt)
            return put(quant.quantize_np(
                x, self.qparams[label], self.hist_dtype))

        if wide:
            if quantized:
                sd = quant.vals_dtype(self.hist_dtype)
                vals_q = np.zeros((S, W, cap), sd)
                for j, l in enumerate(L):
                    w = self.wide_cols[j]
                    vals_q[:, w, :] = quant.quantize_np(
                        vals_w[:, w, :], self.qparams[l], self.hist_dtype)
                vals_dev = put(vals_q)
            else:
                vals_dev = put(vals_w, vdt)
            self._dev = {
                "vals": vals_dev,
                "active": put(active_w),
                "losses": put(losses, ldt),
                "has_loss": put(has_loss),
            }
        else:
            self._dev = {
                "vals": {l: enc(vals[l], l) for l in L},
                "active": {l: put(active[l]) for l in L},
                "losses": put(losses, ldt),
                "has_loss": put(has_loss),
            }

    def tick(self, demand, donate=True, mesh=None, cand_scale=1.0):
        """One batched fused tell+ask DISPATCH for the whole cohort.

        ``demand``: ``{slot: (ids_uint32, seed)}`` — at most one ask per
        slot.  Every occupied slot's pending tell rows fold (asking or
        not), so the mirror never lags the host state.  Returns the
        in-flight ``packed [S, B, L]`` device array — the caller reads it
        back AFTER dispatching every other cohort's tick, so one
        cohort's host-side doc building overlaps the next cohort's
        device compute (the wave-level analog of PR 4's
        dispatch/readback overlap).

        ``cand_scale < 1`` is the degrade ladder shrinking
        ``n_EI_candidates`` for this tick (half/quarter the EI batch —
        the memory- and compute-heavy axis) without touching the
        cohort's identity; the scaled program gets its own LRU entry.
        """
        if self.widen is not None:
            # widened cohorts serve single-device by contract (DESIGN
            # §20): build_suggest_batched_wide has no mesh variant, and
            # a NamedSharding-placed stack would silently recompile the
            # wide jit against sharded inputs — voiding the compile
            # plane's readiness signal (its dummy tick runs unsharded)
            mesh = None
        self.ticks += 1
        L = len(self.cs.labels)
        B = _pow2(max((len(ids) for ids, _ in demand.values()), default=1))

        # a slot whose study outgrew this capacity bucket is evicted (its
        # next ask re-admits it to the right cohort; the host arrays are
        # authoritative, so nothing is lost) — folding its rows here
        # would scatter past the slot.  A slot that told more than K
        # trials since its last tick forces a full re-upload (rare:
        # serving waves tell a handful per study).
        phs = {}
        for slot, st in enumerate(self.slots):
            if st is None:
                continue
            ph = self._history(st)
            if ph.n > self.cap:
                self.evict(st.study_id)
                continue
            phs[slot] = ph
        # adaptive row bucket: the scatter's cost scales with K, and a
        # serving wave folds one or two rows per slot — pow2-bucketed so
        # the program set stays {K=1,2,4,8,16}; more than _ROW_BUCKET
        # pending rows forces a full re-upload instead
        delta = max([ph.n - self._synced.get(slot, 0)
                     for slot, ph in phs.items()] or [0])
        if self._dev is not None and delta > self._ROW_BUCKET:
            self._dev = None
        if self._dev is None:
            if self.qparams is not None:
                # child span: the host-side affine encode of the full
                # stack (the quantize boundary; its in-kernel twin — the
                # dequant fused into the history stream — is inside the
                # fused dispatch span below)
                with _tracer.span("suggest.megakernel.quantize",
                                  cap=self.cap, dtype=self.hist_dtype):
                    self._upload_stack(mesh=mesh)
            else:
                self._upload_stack(mesh=mesh)
            delta = 0
        K = _pow2(max(delta, 1))

        S = self.n_slots
        R = 2 * L + 3
        rows = np.zeros((S, K, R), np.float32)
        rows[:, :, R - 1] = float(self.cap)  # default: dropped no-op
        seed_words = np.zeros((S, 2), np.uint32)
        ids = np.zeros((S, B), np.uint32)
        pending_sync = {}
        for slot, ph in phs.items():
            rows[slot] = ph.pack_rows(self._synced.get(slot, 0), K,
                                      noop_index=self.cap)
            pending_sync[slot] = ph.n
        for slot, (slot_ids, seed) in demand.items():
            seed_words[slot] = tpe._seed_words(seed)
            ids[slot, : len(slot_ids)] = slot_ids
            if len(slot_ids) < B:  # pad by repeating the last id
                ids[slot, len(slot_ids):] = slot_ids[-1]

        cfg = self.cfg
        if cand_scale != 1.0:
            cfg = dict(cfg)
            cfg["n_EI_candidates"] = max(
                1, int(cfg["n_EI_candidates"] * cand_scale))
        if self.widen is not None:
            rows = self._widen_rows(rows)
            run = tpe.build_suggest_batched_wide(
                self.wide_profile, cfg, S, self.cap, B, donate=donate)
            self.last_key = (tpe.cohort_key_wide(
                self.wide_profile, cfg, S, self.cap, B, donate=donate), K)
            args = (self._dev, rows, seed_words, ids,
                    tuple({k: jnp.asarray(v) for k, v in gp.items()}
                          for gp in self.wparams))
        else:
            run = tpe.build_suggest_batched(
                self.cs, cfg, S, self.cap, B, donate=donate, mesh=mesh,
                hist_dtype=self.hist_dtype)
            self.last_key = (tpe.cohort_key(
                self.cs, cfg, S, self.cap, B, donate=donate, mesh=mesh,
                hist_dtype=self.hist_dtype), K)
            args = (self._dev, rows, seed_words, ids)
        if self.megakernel_armed():
            # roofline join (satellite 2): capture the fused program's
            # cost table once so health.roofline_table carries a
            # ``suggest.megakernel`` row next to the jnp programs
            from ..obs.health import capture_jit_cost

            capture_jit_cost(run, args, "suggest.megakernel")
        try:
            if self.megakernel_armed():
                # child span: the fused dispatch — in-kernel history
                # dequant + dual-model accumulate + sample/score
                with _tracer.span("suggest.megakernel.accumulate",
                                  cap=self.cap, n_slots=S):
                    new_dev, packed = run(*args)
            else:
                new_dev, packed = run(*args)
        except BaseException:
            # with donation armed the input stack may already be invalid:
            # drop it and rebuild from the authoritative host arrays
            self._dev = None
            self._synced = {}
            raise
        self._dev = new_dev
        self._synced.update(pending_sync)
        return packed

    def _widen_rows(self, rows):
        """Permute label-ordered tell rows ``[S, K, 2L+3]`` into the
        widened slot order ``[S, K, 2W+3]``: val/active columns move to
        their canonical slots (padding slots stay zero — an inactive
        write into a lane whose output is discarded), the trailing
        (loss, has_loss, index) triple is shared."""
        L = len(self.cs.labels)
        W = self.wide_W
        S, K = rows.shape[0], rows.shape[1]
        out = np.zeros((S, K, 2 * W + 3), np.float32)
        out[:, :, self.wide_cols] = rows[:, :, :L]
        out[:, :, W + self.wide_cols] = rows[:, :, L:2 * L]
        out[:, :, 2 * W:] = rows[:, :, 2 * L:]
        return out

    def extract(self, mat_slot, n):
        """One slot's proposals as an ``[n, L]`` matrix in ``cs.labels``
        order — the identity on the exact-signature layout; widened
        cohorts select the real label columns out of the packed
        ``[B, W]`` slot readback."""
        mat = mat_slot[:n]
        if self.widen is not None:
            mat = mat[:, self.wide_cols]
        return mat

    def row_delta(self):
        """Largest pending tell-row count across slots (what the next
        tick's K bucket would be sized by) — the compile plane's K=1
        enforcement reads this before dispatch."""
        delta = 0
        for slot, st in enumerate(self.slots):
            if st is None:
                continue
            ph = self._history(st)
            if ph.n <= self.cap:
                delta = max(delta, ph.n - self._synced.get(slot, 0))
        return delta if self._dev is not None else 0

    def abandon_device(self):
        """Drop the (possibly donated-and-poisoned) device stack after a
        failed dispatch or readback; the next tick rebuilds it from the
        authoritative host arrays."""
        self._dev = None
        self._synced = {}


class StudyScheduler:
    """Create/ask/tell over many studies, batched onto cohort ticks.

    Thread-safe.  Concurrent ``ask`` callers coalesce through the
    ``wave_window`` gather pause: the first thread to become the wave
    ticker releases the lock for that window, every asker that arrives
    meanwhile enqueues into the SAME wave, and one batched device tick
    per cohort serves them all.  With ``wave_window=0`` (the default for
    direct in-process use) asks serialize — single-threaded drivers
    should express waves explicitly with :meth:`ask_many`; the HTTP
    server always runs with a small window.

    ``store_root`` persists every study through the existing
    ``FileStore`` (one subdirectory per study id); default is in-memory
    :class:`~hyperopt_tpu.base.Trials`.

    ``wal`` arms the write-ahead journal: ``None`` resolves
    ``HYPEROPT_TPU_SERVICE_WAL`` (auto = journal under ``store_root``
    when there is one), ``False`` disarms, a path or
    :class:`~hyperopt_tpu.service.journal.StudyJournal` arms explicitly.
    An armed journal replays automatically on construction
    (``auto_resume=False`` defers to an explicit :meth:`resume`).

    ``degrade`` is the device-fault ladder patience (clean waves before
    a recovery probe): ``None`` resolves ``HYPEROPT_TPU_SERVICE_DEGRADE``
    (default 8), ``False`` disarms (a tick fault then errors the asks it
    was serving, the pre-ladder behavior).

    ``overload`` is an optional
    :class:`~hyperopt_tpu.service.overload.AdmissionGuard`; the
    scheduler feeds it wave latencies (the ``Retry-After`` EWMA) — the
    HTTP server owns admission itself.
    """

    def __init__(self, max_studies=None, max_pending=None, idle_sec=None,
                 store_root=None, wave_window=0.0, wal=None, degrade=None,
                 overload=None, auto_resume=True, compile_plane=None,
                 widen=None, quality=None, load=None, tenants=None):
        from .._env import (parse_compile_plane, parse_compile_widen,
                            parse_load, parse_quality, parse_service_degrade,
                            parse_service_idle_sec,
                            parse_service_max_pending,
                            parse_service_max_studies,
                            parse_service_wal, parse_store_gc,
                            parse_store_watermark, parse_tenant,
                            parse_tenant_top_k)

        self.max_studies = (parse_service_max_studies()
                            if max_studies is None else int(max_studies))
        self.max_pending = (parse_service_max_pending()
                            if max_pending is None else int(max_pending))
        self.idle_sec = (parse_service_idle_sec()
                         if idle_sec is None else float(idle_sec))
        if self.idle_sec <= 0:
            # 0 means "never evict on idleness" EVERYWHERE (env grammar,
            # CLI, constructor) — a literal 0 would instead evict every
            # slot at every wave and re-upload every cohort stack
            self.idle_sec = float("inf")
        self.store_root = store_root
        self.wave_window = float(wave_window)
        self._lock = threading.RLock()
        self._cond = threading.Condition(self._lock)
        self._studies = {}
        self._cohorts = {}  # (sig, cfg_key, cap) -> _Cohort
        self._wave_reqs = []
        self._tick_running = False
        self._draining = False
        self._wave_seq = 0  # wave sequence: the id request spans fan into
        self.metrics = get_metrics("service")
        self.overload = overload
        # cold-start compile plane (ISSUE 14): None resolves
        # HYPEROPT_TPU_COMPILE_PLANE (default off — disarmed, the wave
        # path is byte-identical to pre-ISSUE-14), False disarms, an
        # instance arms explicitly (the server/fleet share one across
        # schedulers).  ``widen`` likewise resolves
        # HYPEROPT_TPU_COMPILE_WIDEN and is cached here so a scheduler's
        # program layout never flips mid-flight.
        self._owns_plane = False
        if compile_plane is None and parse_compile_plane():
            from .compile_plane import CompilePlane, census_path_for

            compile_plane = CompilePlane(
                census_path=(census_path_for(store_root)
                             if store_root is not None else None),
                metrics=self.metrics)
            # built here → stopped here (drain): a shared plane (server
            # main / fleet scheduler_kwargs) is its creator's to stop
            self._owns_plane = True
        self.compile_plane = compile_plane or None
        self.widen = (parse_compile_widen() if widen is None
                      else bool(widen))
        # ownership fence (ISSUE 12): fleet mode installs a callable
        # answering "does this scheduler's shard lease still stand?".
        # Checked at every DURABILITY point (ask ingress, wave start,
        # tell ingress) so a stalled-then-reclaimed holder refuses the
        # mutation instead of acknowledging into a fenced epoch WAL.
        # None (single-server mode) = never fenced.
        self.fence = None

        if wal is None:
            mode = parse_service_wal()
            if mode == "auto":
                self.journal = (StudyJournal(wal_path_for(store_root))
                                if store_root is not None else None)
            elif mode is None:
                self.journal = None
            else:
                self.journal = StudyJournal(mode)
        elif wal is False:
            self.journal = None
        elif isinstance(wal, StudyJournal):
            self.journal = wal
        else:
            self.journal = StudyJournal(wal)

        if degrade is None:
            patience = parse_service_degrade()
        elif degrade is False:
            patience = None
        else:
            patience = int(degrade)
        self.degrade = (DegradeLadder(patience, metrics=self.metrics)
                        if patience is not None else None)

        # storage-integrity plane (ISSUE 15): per-study quarantine map
        # (sid -> {reason, ts}; durable via `quarantine` WAL records),
        # the disk watermark over whatever durable root this scheduler
        # writes, and the store-full shed latch the ENOSPC path arms
        self._quarantined = {}
        self._gc_enabled = parse_store_gc()
        self._store_full = False
        self._store_full_src = None  # "watermark" | "enospc" | None
        self._last_rung = 0.0
        self._rung_running = False
        self.last_gc = None
        self.watermark = None
        wm_root = (store_root if store_root is not None
                   else (os.path.dirname(self.journal.path) or "."
                         if self.journal is not None else None))
        if wm_root is not None:
            self.watermark = integrity.DiskWatermark(
                wm_root, threshold=parse_store_watermark(),
                metrics=self.metrics)

        # search-quality telemetry plane (ISSUE 16): None resolves
        # HYPEROPT_TPU_QUALITY (default ON — pure tell-time metadata,
        # zero threads, never feeds proposals), False disarms (the tell
        # path pays one `is None` check and nothing else), an instance
        # arms explicitly (tests inject fakes; the server wires the SLO
        # hook in after construction).  Built BEFORE auto_resume so
        # replayed tells rebuild the convergence state too.
        if quality is None:
            from ..obs.quality import QualityPlane

            self.quality = (QualityPlane(metrics=self.metrics,
                                         tracer=_tracer)
                            if parse_quality() else None)
        elif quality is False:
            self.quality = None
        else:
            self.quality = quality

        # load & cost attribution ledger (ISSUE 17): None resolves
        # HYPEROPT_TPU_LOAD (default ON — pure wave-time arithmetic,
        # zero threads, never feeds proposals), False disarms (the wave
        # path pays one `is None` check and nothing else), an instance
        # arms explicitly.  Replayed tells are NOT recounted — adopted
        # heat arrives through the durable heat ledger (CostLedger
        # .inherit), so replay stays bitwise and heat is never doubled.
        if load is None:
            from ..obs.load import CostLedger

            self.load = (CostLedger(metrics=self.metrics)
                         if parse_load() else None)
        elif load is False:
            self.load = None
        else:
            self.load = load

        # tenant observatory (ISSUE 20): None resolves
        # HYPEROPT_TPU_TENANT (default ON — same wave-time arithmetic
        # shape as the cost ledger, bounded top-K rows, never feeds
        # proposals), False disarms (`self.tenants is None` — the wave
        # path pays one identity check and allocates nothing), an
        # instance arms explicitly.  Built BEFORE auto_resume: replayed
        # admits + tells ARE the crash-resume rebuild of the tenant
        # tables (unlike heat there is no durable tenant-inherit path).
        if tenants is None:
            from ..obs.tenant import TenantLedger

            self.tenants = (TenantLedger(metrics=self.metrics,
                                         top_k=parse_tenant_top_k())
                            if parse_tenant() else None)
        elif tenants is False:
            self.tenants = None
        else:
            self.tenants = tenants

        self.last_resume = None  # stats dict of the latest WAL replay
        if auto_resume and self.journal is not None:
            self.resume()

    # -- study lifecycle ---------------------------------------------------

    def create_study(self, space, seed=0, study_id=None, space_spec=None,
                     _replay=False, **kwargs):
        """Admit a new study; returns its id (``filestore.new_run_id``).
        Raises :class:`StudyQuotaError` past the ``max_studies`` quota.
        ``space_spec`` (the JSON-wire schema the space was built from)
        makes the study WAL-resumable; the HTTP front end always passes
        it.  Replayed admissions (``_replay``) bypass the quota — the
        quota is admission control for NEW work, and a restart with a
        smaller ``HYPEROPT_TPU_SERVICE_MAX_STUDIES`` must not silently
        drop journaled studies."""
        from ..filestore import FileTrials, new_run_id

        chaos.point("admit", self.metrics)
        with self._lock:
            if self._draining and not _replay:
                raise DrainingError("service is draining; not admitting "
                                    "new studies")
            if (not _replay and self.fence is not None
                    and not self.fence()):
                # an admit journaled into a fenced epoch WAL would mint
                # a study id no future owner ever learns about
                raise StaleOwnershipError(
                    "shard lease lost; study admission refused")
            live = sum(1 for s in self._studies.values()
                       if s.state == "active")
            if live >= self.max_studies and not _replay:
                raise StudyQuotaError(
                    f"study quota reached ({self.max_studies} live studies)")
            study_id = study_id or new_run_id("study")
            if study_id in self._studies:
                raise StudyQuotaError(f"study id {study_id!r} already exists")
            trials = None
            if self.store_root is not None:
                import os

                trials = FileTrials(os.path.join(self.store_root, study_id))
            st = Study(study_id, space, seed=seed, trials=trials,
                       space_spec=space_spec, **kwargs)
            trace = reqtrace.current_trace_id()
            if self.journal is not None and not _replay:
                try:
                    self.journal.append(StudyJournal.admit_rec(
                        study_id, space_spec, st.seed, st.admit_kwargs,
                        trace=trace))
                    self.journal.sync()  # admits are rare; durable now
                except StoreFullError as e:
                    # typed 507 to the client; arm the shed so the next
                    # admissions fail fast at the guard
                    self._enter_store_full(f"admit WAL append: {e}")
                    raise
            st.note("admit", trace=trace,
                    replay=True if _replay else None)
            self._studies[study_id] = st
            if self.tenants is not None and not st.canary:
                # replay INCLUDED: WAL replay is how crash-resume
                # rebuilds the tenant tables (admit kwargs carry the
                # tenant).  Canary traffic is free here exactly as in
                # the quality and cost planes.
                try:
                    self.tenants.note_study(st.tenant)
                except Exception as e:  # noqa: BLE001
                    logging.getLogger(__name__).warning(
                        "tenant note_study failed: %s", e)
            self.metrics.counter("service.studies_created").inc()
            self.metrics.gauge("service.studies_live").set(live + 1)
            return study_id

    def close_study(self, study_id):
        """Mark a study done and free its cohort slot (its trials stay
        queryable; the admission quota counts only active studies).  A
        settled study triggers WAL compaction — its records are dead
        weight for every future replay."""
        with self._lock:
            st = self._get(study_id)
            if self.fence is not None and not self.fence():
                raise StaleOwnershipError(
                    f"{study_id}: shard lease lost; close refused")
            st.state = "closed"
            trace = reqtrace.current_trace_id()
            if self.journal is not None:
                self.journal.append(StudyJournal.close_rec(study_id,
                                                           trace=trace))
                self.journal.sync()
            st.note("close", trace=trace)
            if self.tenants is not None and not st.canary:
                try:
                    self.tenants.forget_study(st.tenant)
                except Exception:
                    pass
            self._evict_from_cohort(st)
            self._gc_cohorts()
            self.metrics.gauge("service.studies_live").set(
                sum(1 for s in self._studies.values()
                    if s.state == "active"))
            self._maybe_compact()

    def _get(self, study_id):
        if study_id in self._quarantined:
            raise QuarantinedStudyError(
                f"{study_id} is quarantined "
                f"({self._quarantined[study_id].get('reason', 'corrupt')})")
        st = self._studies.get(study_id)
        if st is None:
            raise UnknownStudyError(study_id)
        return st

    # -- storage-integrity plane (ISSUE 15) --------------------------------

    def _quarantine_study(self, sid, reason):
        """Per-study corruption fault: mark the study quarantined (410
        on ask/tell, listed in ``/studies``), free its cohort slot,
        emit the timeline event.  The study's trials stay on disk
        untouched — evidence, like the renamed WAL segment."""
        if sid in self._quarantined:
            return
        self._quarantined[sid] = {"reason": str(reason),
                                  "ts": time.time()}
        st = self._studies.get(sid)
        if st is not None:
            st.state = "quarantined"
            self._evict_from_cohort(st)
            st.note("quarantine", reason=str(reason))
        self.metrics.counter("service.integrity.quarantines").inc()
        logging.getLogger(__name__).warning(
            "service: study %s QUARANTINED (%s) — 410 on ask/tell; "
            "every other study keeps serving", sid, reason)

    def _enter_store_full(self, reason, retry_after=1.0,
                          source="enospc"):
        """Arm the store-full shed: the admission guard answers asks
        with 507 + Retry-After for one latch window, then lets a probe
        request through to re-test the disk (re-arming on failure) —
        recovery is automatic when space returns.  Kicks the degrade
        rung (compact + bounded GC) off-thread: reclaiming space beats
        shedding, but running it on the request path under the
        scheduler lock would block every concurrent tell behind an
        I/O sweep of an already-sick disk.

        ``source`` records WHO armed us: a ``watermark`` latch clears
        when statvfs says space returned; an ``enospc`` latch clears
        only on a SUCCESSFUL durable write (the guard window expiry is
        its probe) — EDQUOT, and injected faults, can report plenty of
        free blocks while every write still fails."""
        self._run_space_rung_async()
        self._store_full = True
        self._store_full_src = source
        self.metrics.gauge("store.full").set(1)
        if self.overload is not None:
            self.overload.set_store_full(
                True, reason=reason, retry_after=retry_after)

    def _exit_store_full(self):
        if not self._store_full:
            return
        self._store_full = False
        self._store_full_src = None
        self.metrics.gauge("store.full").set(0)
        if self.overload is not None:
            self.overload.set_store_full(False)

    def _run_space_rung_async(self, cooldown=5.0):
        """Spawn the space-pressure degrade rung on a daemon thread:
        compact the quiescent WAL (dead records are reclaimable bytes)
        and run the bounded store GC.  Cooldown-limited and
        single-flight — the rung must not become its own I/O storm
        while the disk stays full, and requests shed cheaply at the
        guard while it works."""
        now = time.monotonic()
        if now - self._last_rung < cooldown or self._rung_running:
            return
        self._last_rung = now
        self._rung_running = True

        def rung():
            try:
                try:
                    with self._lock:
                        self._maybe_compact()
                except Exception:  # noqa: BLE001 - full disks fail this
                    pass
                if self._gc_enabled and self.store_root is not None:
                    try:
                        self.last_gc = integrity.gc_store_root(
                            self.store_root, metrics=self.metrics)
                    except Exception:  # noqa: BLE001
                        logging.getLogger(__name__).warning(
                            "service: store gc failed", exc_info=True)
            finally:
                self._rung_running = False

        threading.Thread(target=rung, name="hyperopt-store-rung",
                         daemon=True).start()

    def _check_store(self, force=False):
        """The per-wave / per-scrape watermark poll.  Entering
        low-space runs the rung and arms the shed; while space STAYS
        low the guard latch is re-armed each poll (it expires on its
        own window otherwise — one ~2s shed and then full traffic onto
        a filling disk); leaving low-space clears a watermark-armed
        latch.  An ``enospc``-armed latch is deliberately NOT cleared
        here — statvfs can show free blocks while every write fails
        (EDQUOT, failing controller); only a successful durable write
        clears it.  Cheap on the hot path — statvfs at most once per
        second."""
        if self.watermark is None:
            return None
        state = self.watermark.sample(force=force)
        if state is None:
            return None
        if state["low"]:
            reason = (f"disk watermark: {state['free_bytes']} bytes "
                      f"free ({state['free_frac']:.1%})")
            if not self._store_full:
                self._enter_store_full(reason, source="watermark")
            elif self.overload is not None:
                self.overload.set_store_full(True, reason=reason,
                                             retry_after=1.0)
        elif self._store_full and self._store_full_src == "watermark":
            self._exit_store_full()
        return state

    def store_health(self, force=False):
        """The ``/snapshot``·``/metrics`` storage block: disk state,
        shed latch, quarantine count, last GC."""
        with self._lock:
            state = self._check_store(force=force)
            out = {
                "store_full": self._store_full,
                "quarantined": len(self._quarantined),
            }
            if state is not None:
                out.update({k: state[k] for k in
                            ("free_bytes", "used_frac", "low")})
            if self.last_gc is not None:
                out["gc"] = self.last_gc
            return out

    # -- cohort packing ----------------------------------------------------

    def _cohort_for(self, st):
        """The cohort matching the study's (space, cfg, capacity) — moving
        the study between cohorts when its capacity bucket grew."""
        ph = st.trials.history_object(st.domain.cs.labels)
        cap = _cohort_cap(ph.n)
        key = (st.domain.cs.signature(), st.cfg_key, cap)
        cohort = self._cohorts.get(key)
        if cohort is None:
            from .. import quant
            from .._env import parse_hist_dtype

            widen_info = None
            if self.widen:
                prof = tpe.widened_profile(st.domain.cs)
                if prof is not None:
                    # widened + quantized: the per-slot scale/zero/log
                    # tables ride the runtime wparams (identity rows when
                    # unquantized), so one compiled program per profile
                    # survives the dtype push
                    qp = quant.resolve(st.domain.cs, parse_hist_dtype(),
                                       context="cohort")[1]
                    widen_info = (prof[0], prof[1], tpe.widened_params(
                        st.domain.cs, prof[0], prof[1], qparams=qp))
            cohort = self._cohorts[key] = _Cohort(
                st.domain.cs, st.cfg, cap, hist_dtype=parse_hist_dtype(),
                widen=widen_info)
        if st.study_id not in cohort.slot_of:
            # evict from any smaller-capacity cohort it may still occupy
            self._evict_from_cohort(st)
            cohort.admit(st)
            st.note("cohort_admit", cap=cohort.cap)
        return cohort

    def _evict_from_cohort(self, st):
        for cohort in self._cohorts.values():
            if cohort.evict(st.study_id) is not None:
                self.metrics.counter("service.evictions").inc()
                st.note("evict", cap=cohort.cap)

    def evict_idle(self, now=None):
        """Free cohort slots of studies idle past ``idle_sec`` (the study
        itself survives — its next ask re-admits it bit-identically from
        the host arrays)."""
        now = time.time() if now is None else now
        with self._lock:
            for st in self._studies.values():
                if (st.state == "active"
                        and now - st.last_active > self.idle_sec):
                    self._evict_from_cohort(st)

    def _gc_cohorts(self):
        """Drop cohorts with no live slots.  Studies migrate between
        capacity buckets as they grow, and an abandoned cohort would
        otherwise pin its full stacked device mirror forever (and
        permanently depress slot utilization)."""
        with self._lock:
            for key in [k for k, c in self._cohorts.items()
                        if c.n_live == 0]:
                del self._cohorts[key]

    def slot_utilization(self):
        """Occupied fraction of all cohort slots (1.0 = perfectly packed)."""
        with self._lock:
            total = sum(c.n_slots for c in self._cohorts.values())
            live = sum(c.n_live for c in self._cohorts.values())
            return (live / total) if total else 0.0

    # -- ask / tell --------------------------------------------------------

    def _prepare_ask(self, st, n, deadline=None, req_id=None):
        """Draw ids + seed for one ask, exactly as ``FMinIter`` would.
        Returns finished docs (startup random search, served inline) or an
        :class:`_AskReq` awaiting a cohort tick.

        ``req_id`` is the client's idempotency token: a retried ask
        whose first attempt was served (response lost to a crash or
        dropped connection) answers the SAME trials — checked before
        anything else, state and quotas included, because the original
        ask already passed them and may even have finished the study."""
        if req_id is not None:
            tids = st.served_reqs.get(str(req_id))
            if tids is not None:
                by_tid = {d["tid"]: d
                          for d in st.trials._dynamic_trials}
                docs = [by_tid[t] for t in tids if t in by_tid]
                if len(docs) == len(tids):
                    self.metrics.counter(
                        "service.asks_deduped").inc(len(tids))
                    st.note("ask_dedupe", tids=tids,
                            trace=reqtrace.current_trace_id())
                    return docs
        if st.state != "active":
            raise UnknownStudyError(f"{st.study_id} is {st.state}")
        if self._draining:
            raise DrainingError("service is draining; not admitting "
                                "new asks")
        if self.fence is not None and not self.fence():
            raise StaleOwnershipError(
                f"{st.study_id}: shard lease lost; ask refused")
        n = int(n)
        if n < 1:
            raise ValueError("ask n must be >= 1")
        if st.n_pending + n > self.max_pending:
            raise StudyQuotaError(
                f"{st.study_id}: {st.n_pending} pending + {n} asked would "
                f"exceed the per-study quota ({self.max_pending})")
        if (st.max_trials is not None
                and st.n_trials + n > st.max_trials):
            raise StudyQuotaError(
                f"{st.study_id}: budget exhausted "
                f"({st.n_trials}/{st.max_trials} trials)")
        new_ids = st.trials.new_trial_ids(n)
        st.trials.refresh()
        seed = st.next_seed()
        st.touch()
        st.n_asked += n
        self.metrics.counter("service.asks").inc()
        trace = reqtrace.current_trace_id()
        if len(st.trials.trials) < st.n_startup_jobs:
            journaled = False
            try:
                docs = rand.suggest(new_ids, st.domain, st.trials, seed)
                self._journal_ask(st, new_ids, seed, "rand", trace=trace,
                                  req=req_id)
                journaled = True
                self._land(st, docs)
                if self.journal is not None:
                    self.journal.sync()
            except Exception:
                st.n_asked -= n  # release the reserved pending quota
                if not journaled:
                    # the draw is burned either way; keep replay's seed
                    # stream aligned (a journaled-but-unlanded record
                    # already accounts for the draw — never void twice)
                    self._journal_void_ask(st, new_ids, seed, trace=trace)
                raise
            st.remember_req(req_id, new_ids)
            st.note("ask", tids=[int(t) for t in new_ids], algo="rand",
                    startup=True, trace=trace)
            return docs
        return _AskReq(st, new_ids, seed, deadline=deadline, trace=trace,
                       req=req_id)

    def _journal_ask(self, st, new_ids, seed, algo, trace=None, req=None):
        """WAL the served ask (ids + seed + serving algo + idempotency
        token) BEFORE its docs land — crash-ordering argument in
        ``journal.py``."""
        if self.journal is not None:
            self.journal.append(StudyJournal.ask_rec(
                st.study_id, new_ids, seed, algo, trace=trace, req=req))

    def _journal_void_ask(self, st, new_ids, seed, trace=None,
                          reason=None):
        """A FAILED/SHED ask still consumed one seed draw from the
        study's RNG stream AND its allocated trial ids (both
        irreversibly); record them as a ``void`` ask so replay advances
        the stream and retires the same ids identically.  One timeline
        event per void, ``reason`` naming a shed (deadline) when that is
        what failed it.  Best effort on the WAL side: if the journal
        itself is down, losing one draw record is logged, not fatal
        (the serving path already failed)."""
        st.note("void", tids=[int(t) for t in new_ids], trace=trace,
                reason=reason)
        if self.journal is None:
            return
        try:
            self.journal.append(StudyJournal.ask_rec(
                st.study_id, new_ids, seed, "void", trace=trace))
            self.journal.sync()
        except JournalError as e:
            logging.getLogger(__name__).warning(
                "service: could not journal void ask for %s: %s",
                st.study_id, e)

    def _land(self, st, docs):
        st.trials.insert_trial_docs(docs)
        st.trials.refresh()

    def _answers(self, st, docs, algo="tpe", degraded=False,
                 warming=False, wave=None):
        out = [{"study_id": st.study_id, "tid": d["tid"],
                "params": spec_from_misc(d["misc"])} for d in docs]
        if wave is not None:
            # the wave sequence that served this ask — response
            # metadata only (the HTTP layer lifts it into the access
            # log's `wave` field); proposals never depend on it
            for a in out:
                a["wave"] = int(wave)
        if degraded:
            # flag degraded service in-band: the client learns its
            # proposal came from the ladder (possibly plain random
            # search) instead of silently getting worse suggestions
            for a in out:
                a["degraded"] = True
                a["algo"] = algo
        if warming:
            # same in-band honesty for the compile plane's warming
            # state: this proposal is random search while the cohort
            # program compiles — NOT a fault, the study promotes to TPE
            # at the next wave after the program lands
            for a in out:
                a["warming"] = True
                a["algo"] = algo
        return out

    def _ladder_spec(self):
        return (self.degrade.spec() if self.degrade is not None
                else LADDER_LEVELS[0])

    def _serve_rand_fallback(self, r):
        """The degrade ladder's floor: serve one TPE ask host-side via
        ``rand.suggest`` with the SAME recorded ids + seed — the device
        is never touched, the response is flagged, and the WAL records
        ``algo="rand"`` so a replay regenerates the same docs."""
        docs = rand.suggest(r.new_ids, r.study.domain, r.study.trials,
                            r.seed)
        r.algo = "rand"
        r.degraded = True
        self.metrics.counter("service.degraded_asks").inc(len(r.new_ids))
        return docs

    def _cohort_plane_key(self, cohort, S, B, donate, mesh):
        """The program LRU key for one cohort shape, cached on the
        cohort — the readiness probe runs on EVERY wave forever, and
        re-deriving signatures/profiles there is pure hot-path waste."""
        geom = (None if mesh is None
                else (tuple(mesh.shape.items()),
                      tuple(d.id for d in mesh.devices.flat)))
        # megakernel arming is part of the program's identity: a lowering
        # fallback mid-run flips the cohort to the plain key, and a memo
        # blind to that would probe the dead armed key forever (perpetual
        # warming floor)
        ck = (S, B, donate, geom, cohort.megakernel_armed())
        key = cohort._plane_keys.get(ck)
        if key is None:
            if cohort.widen is not None:
                key = tpe.cohort_key_wide(cohort.wide_profile, cohort.cfg,
                                          S, cohort.cap, B, donate=donate)
            else:
                key = tpe.cohort_key(cohort.cs, cohort.cfg, S, cohort.cap,
                                     B, donate=donate, mesh=mesh,
                                     hist_dtype=cohort.hist_dtype)
            cohort._plane_keys[ck] = key
        return key

    def _plane_ready(self, cohort, cohort_reqs, mesh):
        """One cohort's compile-plane gate: census-count the tick, probe
        program readiness (enqueueing a background compile job on a
        miss — built lazily, the ready path never constructs one),
        enforce the K=1 rows-bucket contract when ready, and pre-warm
        the doubled slot count when the cohort is about to grow.
        Returns False when the wave must serve this cohort at the
        warming floor."""
        plane = self.compile_plane
        B = _pow2(max(len(r.new_ids) for r in cohort_reqs))
        S, cap = cohort.n_slots, cohort.cap
        donate = tpe._donation_enabled()
        widen = cohort.widen is not None
        pmesh = None if widen else mesh
        spec0 = next((r.study.space_spec for r in cohort_reqs
                      if r.study.space_spec is not None), None)
        if plane.census is not None and spec0 is not None \
                and any(not r.study.canary for r in cohort_reqs):
            # canary-only ticks never feed the census bank: the prober's
            # synthetic signature must not displace a real tenant space
            # from the top-N pre-warm set
            from .compile_plane import SignatureCensus

            if cohort._census_kid is None:
                cohort._census_kid = SignatureCensus.key_id(
                    spec0, cohort.cfg, cap)
            plane.census_note(spec0, cohort.cfg, cap, S, B, widen=widen,
                              kid=cohort._census_kid)
        key = self._cohort_plane_key(cohort, S, B, donate, pmesh)

        def live_job():
            return plane.make_job(cohort.cs, spec0, cohort.cfg, S, cap,
                                  B, donate, mesh=pmesh, widen=widen,
                                  source="live")[1]

        if not plane.ready_for(key, 1, job_factory=live_job):
            return False
        # the plane only ever compiles the K=1 rows bucket; a larger
        # pending delta would jit a fresh K variant synchronously in the
        # tick — rebuild from the authoritative host arrays instead
        # (full upload, K back to 1)
        if cohort.row_delta() > 1:
            cohort.abandon_device()
        if cohort.n_live == cohort.n_slots:
            # the next admission doubles the slot count — a brand-new
            # study would otherwise demote the WHOLE cohort to warming
            # for a wave; compile the grown shape ahead of it
            gkey = self._cohort_plane_key(cohort, 2 * S, B, donate, pmesh)
            plane.ready_for(
                gkey, 1,
                job_factory=lambda: plane.make_job(
                    cohort.cs, spec0, cohort.cfg, 2 * S, cap, B, donate,
                    mesh=pmesh, widen=widen, source="growth")[1])
        return True

    def _finish_req(self, r, docs):
        """Journal (write-ahead) + land one served ask.  Replay reqs are
        already in the WAL and must not journal twice.  Warming/promote
        transitions live here: the study enters warming with its first
        rand-floor-because-cold ask and is promoted at the first wave an
        on-device program serves it."""
        if not r.replay:
            self._journal_ask(r.study, r.new_ids, r.seed, r.algo,
                              trace=r.trace, req=r.req)
            r.journaled = True
        self._land(r.study, docs)
        r.study.remember_req(r.req, r.new_ids)
        r.docs = docs
        if r.warming and not r.study.warming:
            r.study.warming = True
            r.study.note("warming", wave=r.wave, trace=r.trace)
        elif r.study.warming and not r.warming and r.algo == "tpe":
            r.study.warming = False
            r.study.note("promote", wave=r.wave, trace=r.trace)
            self.metrics.counter("service.compile.promotions").inc()
        r.study.note("ask", tids=[int(t) for t in r.new_ids], algo=r.algo,
                     wave=r.wave, trace=r.trace,
                     degraded=True if r.degraded else None,
                     warming=True if r.warming else None,
                     replay=True if r.replay else None)

    def _dispatch_cohort(self, cohort, cohort_reqs, mesh, spec):
        """One cohort tick dispatch at ladder level ``spec``.  Returns the
        in-flight packed array, or None when this level serves the
        cohort host-side (rand floor / capacity bucket over the level's
        limit).  The tick span is stamped with the wave id and the
        request traces it serves (fan-in: the flow-event arc's device
        hop)."""
        if spec["rand"] or (spec["cap_limit"] is not None
                            and cohort.cap > spec["cap_limit"]):
            return None
        if (self.compile_plane is not None
                and spec["cand_scale"] == 1.0
                and not any(r.replay for r in cohort_reqs)
                and not self._plane_ready(cohort, cohort_reqs, mesh)):
            # warming (ISSUE 14): the cohort's program is still
            # compiling off-thread — serve this wave's reqs at the rand
            # floor (flagged), never block the wave on XLA.  Replay reqs
            # bypass the gate: a WAL record that says "tpe" MUST
            # regenerate through tpe, compile cost and all.  Ladder
            # levels below normal bypass too — the fault path already
            # retries synchronously and owns its own floor.
            for r in cohort_reqs:
                r.warming = True
            self.metrics.counter("service.compile.warming_asks").inc(
                len(cohort_reqs))
            return None
        chaos.io_point("tick", self.metrics)
        # scrape-visible arming state: 1 while ticks run the fused Pallas
        # program, 0 on the jnp path (flips live on a lowering fallback)
        self.metrics.gauge("suggest.megakernel").set(
            1.0 if cohort.megakernel_armed() else 0.0)
        demand = {}
        for r in cohort_reqs:
            slot = cohort.slot_of[r.study.study_id]
            demand[slot] = (np.asarray(
                [int(i) & 0xFFFFFFFF for i in r.new_ids],
                np.uint32), r.seed)
        wave = next((r.wave for r in cohort_reqs if r.wave is not None),
                    None)
        links = sorted({r.trace for r in cohort_reqs if r.trace})
        with _tracer.span("service.tick", wave=wave, cap=cohort.cap,
                          n_asks=len(cohort_reqs), ladder=spec["name"],
                          **({"links": links} if links else {})):
            return cohort.tick(demand, donate=tpe._donation_enabled(),
                               mesh=mesh, cand_scale=spec["cand_scale"])

    def _readback_cohort(self, cohort, cohort_reqs, packed):
        """Block on one cohort's tick and build + land every req's docs
        (per-req isolation for landing failures).  Raises on readback
        failure or non-finite proposals — the ladder's caller decides
        whether to retry down-ladder."""
        try:
            mat = np.asarray(packed)
        except BaseException:
            cohort.abandon_device()
            raise
        # chaos `corrupt@tick` (ISSUE 18): a seeded SILENT perturbation
        # of the read-back proposals — no flag, no error, finite values.
        # Exactly the fault class only the blackbox prober's golden
        # digest can catch; a no-op attribute check when chaos is off.
        mat = chaos.corrupt_floats("tick", mat, self.metrics)
        live = [cohort.extract(mat[cohort.slot_of[r.study.study_id]],
                               len(r.new_ids))
                for r in cohort_reqs
                if r.study.study_id in cohort.slot_of]
        if live and not all(np.all(np.isfinite(x)) for x in live):
            cohort.abandon_device()
            raise NonFiniteProposal(
                "cohort tick read back non-finite proposals")
        for r in cohort_reqs:
            # per-req isolation: a landing failure (e.g. a full disk
            # under --store) must error THIS ask, not strand the rest
            # of the wave unresolved
            try:
                slot = cohort.slot_of[r.study.study_id]
                flats = rand.unpack_flats(
                    cohort.cs, cohort.extract(mat[slot], len(r.new_ids)),
                    len(r.new_ids))
                docs = rand.flat_to_new_trial_docs(
                    r.study.domain, r.study.trials, r.new_ids, flats)
                if self.degrade is not None and self.degrade.degraded:
                    r.degraded = True
                self._finish_req(r, docs)
            except Exception as e:  # noqa: BLE001
                r.error = e
        if self.compile_plane is not None and cohort.last_key is not None:
            # a live device tick IS a compile proof: record it so the
            # plane never demotes a traffic-warmed program to warming
            self.compile_plane.mark_ready(*cohort.last_key)
        self.metrics.counter("service.ticks").inc()
        self.metrics.counter("service.tick_asks").inc(len(cohort_reqs))

    def _serve_cohort_host_side(self, cohort_reqs):
        """Serve a cohort's reqs entirely host-side (the rand floor) —
        either the degrade ladder's floor or the compile plane's warming
        state (same ids + seed through ``rand.suggest``, same WAL
        ``algo:"rand"`` record, different response flag)."""
        for r in cohort_reqs:
            try:
                if r.warming:
                    docs = rand.suggest(r.new_ids, r.study.domain,
                                        r.study.trials, r.seed)
                    r.algo = "rand"
                    self.metrics.counter(
                        "service.compile.warming_served").inc(
                        len(r.new_ids))
                else:
                    docs = self._serve_rand_fallback(r)
                self._finish_req(r, docs)
            except Exception as e:  # noqa: BLE001
                r.error = e

    def _charge_wave(self, cohort, cohort_reqs, device_sec):
        """Feed one cohort tick to the cost ledger (ISSUE 17) and the
        tenant ledger (ISSUE 20): the measured dispatch+readback
        seconds, attributed across the tick's studies (resp. tenants)
        by their K-row share.  Armed path only (callers guard on either
        plane being armed); a ledger fault is absorbed — attribution
        must never fail a wave — and neither ledger touches the reqs'
        docs/seeds, so armed proposals stay bit-identical to disarmed
        (the standing obs invariant)."""
        # canary reqs are never charged: probe traffic must read as
        # free in the cost observatory (it is synthetic, and billing
        # it would skew every per-study share on a quiet fleet)
        billable = [r for r in cohort_reqs if not r.study.canary]
        if not billable:
            return
        # cohort history footprint the tick streamed: per label an
        # f32 vals plane + a bool active plane, plus the f32 losses
        # + bool has_loss planes — all [n_slots, cap]
        hbm = float(cohort.n_slots * cohort.cap
                    * (len(cohort.cs.labels) * 5 + 5))
        if self.load is not None:
            try:
                entries = [(r.study.study_id, len(r.new_ids))
                           for r in billable]
                n_ask = 0
                for _, k in entries:
                    n_ask += k
                cand = float(n_ask * cohort.cfg.get("n_EI_candidates", 24))
                self.load.observe_tick(entries, device_sec, cand=cand,
                                       hbm_bytes=hbm,
                                       cohort=f"cap{cohort.cap}")
            except Exception as e:  # noqa: BLE001
                logging.getLogger(__name__).warning(
                    "load observe_tick failed: %s", e)
        if self.tenants is not None:
            try:
                self.tenants.observe_tick(
                    [(r.study.tenant, len(r.new_ids)) for r in billable],
                    device_sec, hbm_bytes=hbm)
            except Exception as e:  # noqa: BLE001
                logging.getLogger(__name__).warning(
                    "tenant observe_tick failed: %s", e)

    def _retry_cohort_down_ladder(self, cohort, cohort_reqs, mesh, exc):
        """A cohort tick device-faulted: walk the ladder down and retry
        synchronously until the cohort serves (the rand floor always
        does) or the fault stops looking like device pressure.  Returns
        the number of faults absorbed; req errors are set on a
        non-device failure."""
        faults = 0
        while True:
            if self.degrade is None or not is_device_fault(exc):
                for r in cohort_reqs:
                    if r.docs is None and r.error is None:
                        r.error = exc
                return faults
            faults += 1
            self.degrade.record_fault()
            spec = self._ladder_spec()
            # the degrade decision, stamped with the traces it affects —
            # "whose requests were served below full quality, and why"
            _tracer.event(
                "service.degrade", level=spec["name"],
                fault=f"{type(exc).__name__}: {exc}"[:200],
                wave=next((r.wave for r in cohort_reqs
                           if r.wave is not None), None),
                links=sorted({r.trace for r in cohort_reqs if r.trace}))
            try:
                packed = self._dispatch_cohort(
                    cohort, cohort_reqs, mesh, spec)
                if packed is None:
                    self._serve_cohort_host_side(cohort_reqs)
                else:
                    self._readback_cohort(cohort, cohort_reqs, packed)
                return faults
            except Exception as e:  # noqa: BLE001
                exc = e

    def _run_wave(self, reqs):
        """Group pending asks by cohort and run one tick per cohort (a
        study asked twice in one wave falls to a follow-up round so each
        tick carries at most one ask per slot).  Device faults walk the
        degrade ladder (never failing the wave while the rand floor can
        serve it); the wave's wall time feeds the overload guard's
        ``Retry-After`` EWMA; served asks journal before landing and the
        WAL fsyncs ONCE per wave, before any asker unblocks.

        The wave is one span with ``links`` = the request traces it
        serves (fan-in: N request spans → one wave span), and every req
        is stamped with the wave's sequence number — the join key the
        audit timeline and the flow-event export use."""
        self._wave_seq += 1
        wave = self._wave_seq
        for r in reqs:
            r.wave = wave
        attrs = {"wave": wave, "n_reqs": len(reqs)}
        links = sorted({r.trace for r in reqs if r.trace})
        if links:
            attrs["links"] = links
        with _tracer.span("service.wave", **attrs):
            self._run_wave_inner(reqs)

    def _run_wave_inner(self, reqs):
        from .._env import parse_shard
        from ..parallel import sharding as _sh

        t_wave = time.perf_counter()
        if self.fence is not None and not self.fence():
            # the lease died while this wave queued: refuse it BEFORE
            # any journal append or doc landing — the seeds drawn stay
            # in-memory only, so the new owner's replayed stream never
            # diverges (clients retry against it with their req tokens)
            err = StaleOwnershipError("shard lease lost; wave refused")
            for r in reqs:
                if r.docs is None and r.error is None:
                    r.error = err
            return
        wave_faults = 0
        served_any = False
        # disk-watermark poll (ISSUE 15): cheap (statvfs cached ~1s);
        # entering low-space compacts + GCs before any shed is armed
        self._check_store()
        self.evict_idle()
        # either attribution plane armed → measure tick wall time
        charge = self.load is not None or self.tenants is not None
        if self.tenants is not None and len(reqs) > 1:
            # weighted-fair packing (ISSUE 20): stable-reorder the wave
            # by deficit-round-robin over tenants so a light tenant's
            # asks pack ahead of a noisy one's backlog.  Stable per
            # tenant → stable per study (a study has ONE tenant), so the
            # first-come one-ask-per-study round split below picks the
            # same req per study; only the packing ORDER changes — and
            # per-id PRNG keys never depend on order, so proposals stay
            # bit-identical to the unfair packer (pinned by test).
            try:
                order = self.tenants.drr_order(
                    [r.study.tenant for r in reqs])
                rank = {t: i for i, t in enumerate(order)}
                reqs = sorted(reqs,
                              key=lambda r: rank.get(r.study.tenant,
                                                     len(rank)))
            except Exception as e:  # noqa: BLE001 - packing is advisory
                logging.getLogger(__name__).warning(
                    "tenant drr_order failed (first-come order): %s", e)
        while reqs:
            this_round, leftover, seen = [], [], set()
            for r in reqs:
                (leftover if r.study.study_id in seen
                 else this_round).append(r)
                seen.add(r.study.study_id)
            by_cohort = {}
            for r in this_round:
                try:
                    cohort = self._cohort_for(r.study)
                except Exception as e:  # noqa: BLE001 - per-req isolation
                    r.error = e
                    continue
                by_cohort.setdefault(id(cohort), (cohort, []))[1].append(r)
            n_shard = parse_shard()
            # dispatch phase: every cohort's fused program goes onto the
            # device queue before any readback, so the Python doc building
            # below overlaps the remaining cohorts' device compute.  A
            # dispatch-time device fault retries down-ladder synchronously
            # (overlap is sacrificed only in fault scenarios).
            dispatched = []
            for cohort, cohort_reqs in by_cohort.values():
                mesh = None
                if n_shard is not None:
                    m = _sh.suggest_mesh(n_shard)
                    n_dev = int(m.devices.size)
                    # the study axis must divide the mesh; small cohorts
                    # stay single-device rather than padding slots
                    if n_dev > 1 and cohort.n_slots % n_dev == 0:
                        mesh = m
                spec = self._ladder_spec()
                # cost attribution (ISSUE 17): measured dispatch +
                # readback seconds per cohort tick.  Disarmed pays one
                # `is None` check and allocates nothing (0.0 is a code
                # constant; the dispatched tuple exists either way).
                t_c = time.perf_counter() if charge else 0.0
                try:
                    packed = self._dispatch_cohort(
                        cohort, cohort_reqs, mesh, spec)
                except Exception as e:  # noqa: BLE001
                    wave_faults += self._retry_cohort_down_ladder(
                        cohort, cohort_reqs, mesh, e)
                    served_any = True
                    if charge:
                        self._charge_wave(cohort, cohort_reqs,
                                          time.perf_counter() - t_c)
                    continue
                if packed is None:  # ladder floor: host-side service
                    self._serve_cohort_host_side(cohort_reqs)
                    served_any = True
                    if charge:
                        # host-side service spends no device time; the
                        # charge still counts the asks/waves so /studies
                        # cost columns cover rand-floor studies too
                        self._charge_wave(cohort, cohort_reqs, 0.0)
                    continue
                dt_disp = (time.perf_counter() - t_c if charge else 0.0)
                dispatched.append((cohort, cohort_reqs, mesh, packed,
                                   dt_disp))
            # readback phase: block per cohort, build and land the docs
            for cohort, cohort_reqs, mesh, packed, dt_disp in dispatched:
                served_any = True
                t_c = time.perf_counter() if charge else 0.0
                try:
                    self._readback_cohort(cohort, cohort_reqs, packed)
                except Exception as e:  # noqa: BLE001 - runtime XLA error
                    wave_faults += self._retry_cohort_down_ladder(
                        cohort, cohort_reqs, mesh, e)
                if charge:
                    self._charge_wave(
                        cohort, cohort_reqs,
                        dt_disp + (time.perf_counter() - t_c))
            reqs = leftover
        if self.journal is not None:
            try:
                self.journal.sync()
                if (self._store_full
                        and self._store_full_src == "enospc"):
                    # the probe wave's durable write SUCCEEDED: space
                    # is back (only a real write can prove that — see
                    # _check_store on EDQUOT)
                    self._exit_store_full()
            except JournalError as e:
                # docs already landed; failing the responses now would
                # desync clients from served state.  Count loudly — a
                # failing WAL fsync is a disk-level event the operator
                # must see, not a reason to abandon a served wave.
                logging.getLogger(__name__).warning(
                    "service: WAL sync failed after wave: %s", e)
                self.metrics.counter("service.wal.sync_errors").inc()
                if isinstance(e, StoreFullError):
                    # arm the store-full shed so the NEXT wave's asks
                    # are refused up front instead of served un-durably
                    self._enter_store_full(f"wave WAL sync: {e}")
        if self.degrade is not None and served_any and not wave_faults:
            self.degrade.record_clean_wave()
        dt = time.perf_counter() - t_wave
        self.metrics.histogram("service.wave_sec").observe(dt)
        if self.overload is not None:
            self.overload.observe_wave(dt)
        self._gc_cohorts()
        stats = tpe.cohort_cache_stats()
        self.metrics.gauge("suggest.cohort_cache.hits").set(stats["hits"])
        self.metrics.gauge("suggest.cohort_cache.misses").set(
            stats["misses"])
        self.metrics.gauge("service.slot_utilization").set(
            self.slot_utilization())
        if self.compile_plane is not None:
            self.metrics.gauge("service.compile.warming_studies").set(
                sum(1 for s in self._studies.values()
                    if s.warming and s.state == "active"))

    def ask(self, study_id, n=1, deadline=None, req_id=None):
        """Propose ``n`` new trials for one study.  Concurrent callers
        coalesce: the first thread to reach a quiescent scheduler becomes
        the wave ticker and serves every enqueued ask in one batched
        device tick per cohort.  ``deadline`` (an
        :class:`~hyperopt_tpu.service.overload.Deadline`) sheds the ask
        while it is still QUEUED once expired — a req already inside a
        wave completes and answers (the work is done and journaled).
        ``req_id`` makes the ask idempotent across client retries (see
        :meth:`_prepare_ask`)."""
        chaos.point("ask", self.metrics)
        t0 = time.perf_counter()
        if deadline is not None:
            deadline.check("ask")
        with self._cond:
            st = self._get(study_id)
            try:
                res = self._prepare_ask(st, n, deadline=deadline,
                                        req_id=req_id)
            except StoreFullError as e:
                # the startup-path WAL append hit ENOSPC: typed 507 to
                # this client, shed armed for the ones behind it
                self._enter_store_full(f"ask WAL append: {e}")
                raise
            if not isinstance(res, _AskReq):  # startup random search
                self.metrics.histogram("service.ask_sec").observe(
                    time.perf_counter() - t0)
                return self._answers(st, res)
            req = res
            self._wave_reqs.append(req)
            while req.docs is None and req.error is None:
                if (req.deadline is not None and req.deadline.expired()
                        and req in self._wave_reqs):
                    # still queued: shed cleanly (nothing served, nothing
                    # journaled; the seed draw is released with the quota
                    # in the error path below, matching any failed ask)
                    self._wave_reqs.remove(req)
                    req.error = DeadlineExceeded(
                        f"{study_id}: ask deadline expired while queued")
                    break
                if self._tick_running:
                    self._cond.wait(timeout=0.25)
                    continue
                self._tick_running = True
                if self.wave_window > 0:
                    # gather window: let concurrent askers enqueue into
                    # this wave while the lock is released
                    self._cond.wait(timeout=self.wave_window)
                batch, self._wave_reqs = self._wave_reqs, []
                try:
                    self._run_wave(batch)
                except Exception as e:  # noqa: BLE001
                    # never strand a wave: an unresolved req would spin
                    # its asker forever (the batch left _wave_reqs above)
                    for r in batch:
                        if r.docs is None and r.error is None:
                            r.error = e
                finally:
                    self._tick_running = False
                    self._cond.notify_all()
            if req.error is not None:
                # release the quota and journal the burned draw INSIDE
                # the lock scope: a concurrent tell/close could
                # otherwise compact (snapshot the post-draw rstate) in
                # the window before the void record lands, making
                # replay draw the failed seed twice
                req.study.n_asked -= len(req.new_ids)
                if isinstance(req.error, StoreFullError):
                    self._enter_store_full(
                        f"wave WAL append: {req.error}")
                if not req.journaled and not isinstance(
                        req.error, StaleOwnershipError):
                    # the void note names a deadline shed explicitly —
                    # ONE timeline event per failed/shed ask, matching
                    # the single WAL void record.  A FENCED req never
                    # voids: its journal is dead to every future
                    # replay, and the burned draw was in-memory only
                    self._journal_void_ask(
                        req.study, req.new_ids, req.seed,
                        trace=req.trace,
                        reason=("deadline_shed"
                                if isinstance(req.error, DeadlineExceeded)
                                else None))
        if req.error is not None:
            raise req.error
        self.metrics.histogram("service.ask_sec").observe(
            time.perf_counter() - t0)
        return self._answers(req.study, req.docs, algo=req.algo,
                             degraded=req.degraded, warming=req.warming,
                             wave=req.wave)

    def ask_many(self, requests):
        """Explicit wave: ``[(study_id, n), ...]`` asked in ONE batched
        tick per cohort.  Returns ``{study_id: [answers]}`` — the
        single-threaded driver's way to express an ask wave (bench, the
        determinism tests).

        Partial failure keeps the successes: a study whose cohort tick
        (or doc landing) failed is simply ABSENT from the result (its
        pending quota released, a warning logged) — raising would throw
        away the other studies' already-landed trials, orphaning NEW
        docs the caller could never tell.  Only an all-failed wave
        raises."""
        with self._lock:
            out = {}
            reqs = []
            for study_id, n in requests:
                st = self._get(study_id)
                res = self._prepare_ask(st, n)
                if isinstance(res, _AskReq):
                    reqs.append(res)
                else:
                    out.setdefault(study_id, []).extend(
                        self._answers(st, res))
            self._run_wave(reqs)
            failed = []
            for r in reqs:
                if r.error is not None:
                    # release the failed req's pending quota, else
                    # repeated failures wedge the study at 429
                    r.study.n_asked -= len(r.new_ids)
                    if not r.journaled and not isinstance(
                            r.error, StaleOwnershipError):
                        self._journal_void_ask(r.study, r.new_ids, r.seed,
                                               trace=r.trace)
                    failed.append(r)
                else:
                    out.setdefault(r.study.study_id, []).extend(
                        self._answers(r.study, r.docs, algo=r.algo,
                                      degraded=r.degraded,
                                      warming=r.warming, wave=r.wave))
            if failed:
                if not out:
                    raise failed[0].error
                logging.getLogger(__name__).warning(
                    "ask_many: %d of %d studies failed this wave "
                    "(first: %s: %s); returning the successes",
                    len(failed), len(reqs), type(failed[0].error).__name__,
                    failed[0].error)
            return out

    def tell(self, study_id, tid, loss=None, status=None):
        """Report one trial's result.  ``status`` defaults to ok with a
        finite loss, fail otherwise; the doc settles DONE and folds into
        the study's posterior at its next ask (the tell half of the fused
        tell+ask program).  The WAL record appends (and fsyncs) before
        the state mutates: a tell is never acknowledged un-durably, and
        never lost to a crash after acknowledgment."""
        chaos.point("tell", self.metrics)
        with self._lock:
            st = self._get(study_id)
            if self.fence is not None and not self.fence():
                raise StaleOwnershipError(
                    f"{study_id}: shard lease lost; tell refused")
            tid = int(tid)
            doc = next((d for d in st.trials._dynamic_trials
                        if d["tid"] == tid), None)
            if (doc is None and self.fence is not None
                    and getattr(st.trials, "store", None) is not None):
                # miss-path fallback, FLEET MODE ONLY (the fence is the
                # fleet marker): the doc may have landed in the shared
                # store a heartbeat before this owner's adoption scan —
                # one full rescan before 404ing a tell the client was
                # legitimately answered for.  Single-server mode keeps
                # the cheap 404 (no migration can race there, and a
                # hostile unknown-tid tell must not buy an O(files)
                # unpickling rescan under the scheduler lock).
                st.trials.refresh()
                st.mark_best_dirty()  # the rescan may have pulled in
                # docs settled by another shard owner
                doc = next((d for d in st.trials._dynamic_trials
                            if d["tid"] == tid), None)
            if doc is None:
                raise UnknownStudyError(
                    f"{study_id}: no trial with tid {tid}")
            if doc["state"] == JOB_STATE_DONE:
                raise DuplicateTellError(
                    f"{study_id}: trial {tid} was already told")
            trace = reqtrace.current_trace_id()
            if self.journal is not None:
                try:
                    self.journal.append(StudyJournal.tell_rec(
                        study_id, tid, loss, status, trace=trace))
                    self.journal.sync()
                except StoreFullError as e:
                    # the tell was NOT applied (write-ahead ordering):
                    # typed 507, retryable — tells shed LAST, so only a
                    # genuinely failing append refuses one
                    self._enter_store_full(f"tell WAL append: {e}")
                    raise
                else:
                    if (self._store_full
                            and self._store_full_src == "enospc"):
                        # a durable write succeeded: the full-disk
                        # latch clears (a WATERMARK latch does not —
                        # writes still succeeding is exactly what
                        # low-but-not-full looks like)
                        self._exit_store_full()
            st.note("tell", tid=tid, trace=trace)
            self._apply_tell(st, doc, loss, status)
            if st.state == "done":
                self._maybe_compact()

    def _apply_tell(self, st, doc, loss, status, replay=False):
        """Settle one told doc into the study (shared by the live path
        and WAL replay — replay must fold results identically)."""
        # a finite loss is REQUIRED for an ok record even when the
        # caller says status="ok" — an inf/NaN loss folded into the
        # posterior would poison every later EI split for the study
        ok = (loss is not None and math.isfinite(float(loss))
              and (status is None or status == STATUS_OK))
        doc["result"] = ({"loss": float(loss), "status": STATUS_OK}
                         if ok else {"status": STATUS_FAIL})
        doc["state"] = JOB_STATE_DONE
        doc["refresh_time"] = coarse_utcnow()
        store = getattr(st.trials, "store", None)
        if store is not None:
            store.settle(doc)
        # base-class refresh on purpose: the doc was mutated in place
        # and written through above, so only the _trials view needs
        # rebuilding — FileTrials.refresh would rescan and unpickle
        # the study's whole on-disk store on every tell (O(n) files)
        Trials.refresh(st.trials)
        st.n_told += 1
        st.touch()
        ok_loss = float(loss) if ok else None
        st.record_result(ok_loss)
        self.metrics.counter("service.tells").inc()
        if self.quality is not None and not st.canary:
            try:
                self.quality.observe_tell(st, ok_loss, replay=replay)
            except Exception as e:  # noqa: BLE001 - never fail a tell
                logging.getLogger(__name__).warning(
                    "quality observe_tell failed: %s", e)
        if self.load is not None and not replay and not st.canary:
            # replayed tells are never recounted: adopted heat arrives
            # through the durable heat ledger (CostLedger.inherit), so
            # migration replay stays bitwise and heat is never doubled
            try:
                self.load.observe_tell(st.study_id)
            except Exception as e:  # noqa: BLE001 - never fail a tell
                logging.getLogger(__name__).warning(
                    "load observe_tell failed: %s", e)
        if self.tenants is not None and not st.canary:
            # replayed tells COUNT here (unlike the cost ledger): the
            # tenant table has no durable inherit path — WAL replay IS
            # the crash-resume rebuild (satellite 4)
            try:
                self.tenants.observe_tell(st.tenant)
            except Exception as e:  # noqa: BLE001 - never fail a tell
                logging.getLogger(__name__).warning(
                    "tenant observe_tell failed: %s", e)
        if (st.max_trials is not None
                and st.n_trials >= st.max_trials and st.n_pending == 0):
            st.state = "done"
            self._evict_from_cohort(st)

    # -- WAL resume / compaction / drain -----------------------------------

    def _space_from_admit(self, rec):
        """Rebuild the ``hp`` space from an admit/snapshot record's spec
        wrapper (``{"space": <schema>}`` or ``{"zoo": <name>}``), or None
        when the study was never resumable (direct API admission)."""
        spec = rec.get("spec")
        if not isinstance(spec, dict):
            return None
        if "zoo" in spec:
            from ..zoo import ZOO

            zrec = ZOO.get(str(spec["zoo"]))
            return zrec.space if zrec is not None else None
        if "space" in spec:
            from .spacespec import space_from_spec

            return space_from_spec(spec["space"])
        return None

    def resume(self, source=None):
        """Replay a WAL into this (fresh) scheduler: re-admit every
        journaled study, advance each seed stream draw-for-draw, re-land
        any doc the store does not already hold (regenerated through the
        same serving path — bit-identical by the PR-9 determinism pins)
        and re-apply un-settled tells idempotently.  Returns a stats
        dict (also kept as ``last_resume``); None when no WAL is armed.
        Safe on an empty/missing journal (no-op stats).

        ``source`` replays SOMEONE ELSE'S journal (a
        :class:`~hyperopt_tpu.service.journal.StudyJournal`) while this
        scheduler's own WAL stays the append/compaction target — the
        fleet's shard-migration path (ISSUE 12): an adopting replica
        replays the dead owner's shard-epoch WAL chain here, oldest
        epoch first.  Sequential calls compose: records are idempotent
        and an epoch-head ``snapshot`` for a study an earlier epoch
        already rebuilt is a no-op skip (by the determinism pins, the
        replayed state IS the snapshotted state)."""
        journal = self.journal if source is None else source
        if journal is None:
            return None
        t0 = time.perf_counter()
        stats = {"studies": 0, "asks": 0, "regenerated": 0, "tells": 0,
                 "duplicate_tells": 0, "skipped": 0, "errors": 0,
                 "seed_mismatches": 0, "verified": 0, "unchecked": 0,
                 "torn": 0, "corrupt_records": 0,
                 "corrupt_unattributed": 0, "quarantined": 0,
                 "quarantine_skipped": 0, "snapshot_corrupt_recovered": 0,
                 "reconciled_tells": 0}
        # replay-scoped context: which (sid, tid) tells this replay has
        # accounted (store-ahead vs genuine duplicate), and the highest
        # VOID tid per study (ids a failed ask retired — the tid
        # allocator must stay past them, exactly as the live run's did)
        self._replay_ctx = {"told": set(), "void_max": {}}
        # corruption quarantine (ISSUE 15): a corrupt record is a
        # PER-STUDY fault.  The sid is taken from the parsed record
        # (bad checksum, intact framing) or salvaged by regex from the
        # broken line; from the first corrupt record on, every later
        # record for that study is skipped (its state chain is broken)
        # and the study quarantines at the end of the pass.  Without a
        # store the healthy records are kept verbatim so the live WAL
        # can be rewritten after the corrupt segment is renamed aside.
        corrupt = {}
        keep_raw = source is None and self.store_root is None
        healthy = [] if keep_raw else None
        with self._lock:
            for chk in journal.checked_records():
                if chk.status == integrity.TORN:
                    stats["torn"] += 1
                    continue
                if chk.status == integrity.CORRUPT:
                    stats["corrupt_records"] += 1
                    rec = chk.rec or {}
                    sid = rec.get("sid") or integrity.salvage_sid(chk.raw)
                    if sid is None:
                        stats["corrupt_unattributed"] += 1
                        logging.getLogger(__name__).warning(
                            "service: %s:%d: corrupt WAL record with no "
                            "salvageable study id; record lost (scrub "
                            "will still report it)",
                            journal.path, chk.lineno)
                        continue
                    if (rec.get("kind") == "snapshot"
                            and sid in self._studies
                            and sid not in corrupt):
                        # a corrupt SNAPSHOT whose study the earlier
                        # chain already rebuilt: full-chain replay
                        # recovered it — no quarantine needed (the
                        # healthy replay would have skipped this
                        # duplicate admit anyway)
                        stats["snapshot_corrupt_recovered"] += 1
                        continue
                    corrupt.setdefault(
                        sid, f"corrupt record at {journal.path}:"
                             f"{chk.lineno}")
                    continue
                if chk.status == integrity.OK:
                    stats["verified"] += 1
                else:
                    stats["unchecked"] += 1
                rec = chk.rec
                sid = rec.get("sid")
                if sid is not None and (sid in corrupt
                                        or sid in self._quarantined):
                    stats["quarantine_skipped"] += 1
                    continue
                try:
                    self._replay_record(rec, stats)
                except Exception as e:  # noqa: BLE001 - per-record isolation
                    stats["errors"] += 1
                    logging.getLogger(__name__).warning(
                        "service: WAL replay failed for %r: %s", rec, e)
                    continue
                if healthy is not None:
                    healthy.append(rec)
            for sid, reason in corrupt.items():
                self._quarantine_study(sid, reason)
                stats["quarantined"] += 1
            if corrupt:
                self._quarantine_wal_segment(journal, corrupt, healthy)
            # store-ahead reconciliation (ISSUE 15): a DONE doc whose
            # tell record the journal lost can only mean the medium
            # destroyed a DURABLE line (the tell fsyncs before the doc
            # settles, so a genuine crash-torn tail never leaves a
            # DONE doc behind).  The store holds the acknowledged
            # result — realign the counter to it instead of reporting
            # a phantom pending ask forever.  Tells never draw from
            # the RNG stream, so reconciliation cannot perturb the
            # bitwise-resume pin.
            for st in self._studies.values():
                if getattr(st.trials, "store", None) is None \
                        or st.study_id in self._quarantined:
                    continue
                done = sum(1 for d in st.trials._dynamic_trials
                           if d["state"] == JOB_STATE_DONE)
                if done > st.n_told:
                    stats["reconciled_tells"] += done - st.n_told
                    logging.getLogger(__name__).warning(
                        "service: %s: %d acknowledged tell(s) missing "
                        "from the journal (torn/corrupt tail?) — "
                        "reconciled from the store's DONE docs",
                        st.study_id, done - st.n_told)
                    st.n_told = done
                    if (st.max_trials is not None
                            and st.n_trials >= st.max_trials
                            and st.n_pending == 0):
                        st.state = "done"
            for st in self._studies.values():
                # the crash-resume boundary on every resumed timeline:
                # everything before this marker was replayed from the
                # WAL, everything after is live traffic
                st.note("resume", n_trials=st.n_trials,
                        n_told=st.n_told)
            self.metrics.gauge("service.studies_live").set(
                sum(1 for s in self._studies.values()
                    if s.state == "active"))
            for st in self._studies.values():
                # reclaim tid-allocator gaps left by asks that died
                # un-journaled mid-wave: per-trial PRNG streams key off
                # the id VALUE, so a gap would diverge every later
                # proposal from the uninterrupted reference.  VOID ids
                # (failed asks the live run survived) stay retired —
                # the live run's allocator is past them too.
                store = getattr(st.trials, "store", None)
                if store is not None:
                    tids = [d["tid"] for d in st.trials._dynamic_trials]
                    nxt = max(max(tids, default=-1),
                              self._replay_ctx["void_max"].get(
                                  st.study_id, -1)) + 1
                    store.reset_counter(nxt)
            self._maybe_compact()
        del self._replay_ctx
        stats["replay_sec"] = time.perf_counter() - t0
        for key in ("studies", "asks", "regenerated", "tells",
                    "duplicate_tells", "skipped", "errors"):
            if stats[key]:
                self.metrics.counter(f"service.wal.replay_{key}").inc(
                    stats[key])
        for key, name in (("verified", "service.integrity.verified"),
                          ("unchecked", "service.integrity.unchecked"),
                          ("torn", "service.integrity.torn"),
                          ("corrupt_records",
                           "service.integrity.corrupt_records"),
                          ("corrupt_unattributed",
                           "service.integrity.corrupt_unattributed"),
                          ("quarantine_skipped",
                           "service.integrity.quarantine_skipped"),
                          ("snapshot_corrupt_recovered",
                           "service.integrity.snapshot_recovered"),
                          ("reconciled_tells",
                           "service.integrity.reconciled_tells")):
            if stats[key]:
                self.metrics.counter(name).inc(stats[key])
        self.metrics.gauge("service.wal.replay_sec").set(
            stats["replay_sec"])
        self.last_resume = stats
        if stats["studies"] or stats["errors"]:
            logging.getLogger(__name__).warning(
                "service: WAL resume: %d studies, %d asks "
                "(%d regenerated), %d tells (%d duplicates skipped), "
                "%d skipped, %d errors in %.3fs",
                stats["studies"], stats["asks"], stats["regenerated"],
                stats["tells"], stats["duplicate_tells"],
                stats["skipped"], stats["errors"], stats["replay_sec"])
        return stats

    def _quarantine_wal_segment(self, journal, corrupt, healthy):
        """Preserve the corrupt journal file as evidence and leave a
        clean live WAL behind (ISSUE 15).  The segment renames to
        ``*.quarantined`` with a sealed reason record; the live path is
        then rebuilt — from store-backed snapshots via the normal
        compaction when a store exists (``resume`` calls
        ``_maybe_compact`` right after), or by rewriting the verified
        healthy records directly when the WAL is the only copy."""
        reasons = "; ".join(f"{sid}: {r}" for sid, r in
                            sorted(corrupt.items()))
        journal.quarantine_segment(reasons)
        if journal is not self.journal or self.journal is None:
            return  # a source segment (fleet epoch chain): our own WAL
            # gains the quarantine records through compaction
        if self.store_root is None and healthy is not None:
            recs = list(healthy) + [
                StudyJournal.quarantine_rec(sid, info.get("reason", ""))
                for sid, info in sorted(self._quarantined.items())]
            try:
                self.journal.rewrite(recs, verify_old=False)
            except JournalError as e:
                logging.getLogger(__name__).warning(
                    "service: could not rewrite WAL after quarantine: "
                    "%s (healthy studies stay live in-memory; the "
                    "quarantined segment holds the records)", e)

    def _replay_record(self, rec, stats):
        kind = rec.get("kind")
        sid = rec.get("sid")
        if kind == "quarantine":
            # the durable per-study quarantine marker: re-mark and move
            # on — resume-twice with a quarantined segment present is
            # idempotent through this record
            self._quarantine_study(sid, rec.get("reason", "journaled"))
            return
        if kind in ("admit", "snapshot"):
            if sid in self._studies:
                return  # duplicate admit (compaction raced a crash)
            space = self._space_from_admit(rec)
            if space is None:
                stats["skipped"] += 1
                logging.getLogger(__name__).warning(
                    "service: WAL study %s has no resumable space spec; "
                    "skipping it", sid)
                return
            self.create_study(space, seed=rec.get("seed", 0),
                              study_id=sid, space_spec=rec.get("spec"),
                              _replay=True, **(rec.get("kwargs") or {}))
            st = self._studies[sid]
            if kind == "snapshot":
                st.rstate.bit_generator.state = rec["rstate"]
                st.n_asked = int(rec.get("n_asked", 0))
                st.n_told = int(rec.get("n_told", 0))
                st.state = rec.get("state", "active")
                for rid, tids in (rec.get("served") or {}).items():
                    st.remember_req(rid, tids)
                if self.quality is not None and st.n_told \
                        and not st.canary:
                    # a compacted WAL carries no tell records for the
                    # settled history, so the tracker state (best-so-far,
                    # plateau clock, timeline events) is rebuilt from the
                    # store-settled docs in tid order — deterministic, so
                    # every further resume regenerates identical events.
                    # Docs beyond the snapshot's n_told belong to later
                    # tell records, which fold their own.
                    try:
                        folded = 0
                        for d in st.trials._dynamic_trials:
                            if folded >= st.n_told:
                                break
                            if d["state"] != JOB_STATE_DONE:
                                continue
                            res = d.get("result") or {}
                            ok_loss = (res.get("loss")
                                       if res.get("status") == STATUS_OK
                                       else None)
                            self.quality.observe_tell(st, ok_loss,
                                                      replay=True)
                            folded += 1
                    except Exception as e:  # noqa: BLE001
                        logging.getLogger(__name__).warning(
                            "quality snapshot fold failed: %s", e)
            stats["studies"] += 1
            return
        st = self._studies.get(sid)
        if st is None:
            stats["skipped"] += 1
            return
        if kind == "ask":
            drawn = st.next_seed()  # the live draw, replayed exactly
            seed = int(rec.get("seed", drawn))
            if drawn != seed:
                # trust the RECORD (it is what produced the served
                # docs); a mismatch means journal/stream skew and is
                # worth counting loudly
                stats["seed_mismatches"] += 1
            tids = [int(t) for t in rec.get("tids") or []]
            if rec.get("algo") == "void" or not tids:
                # a failed ask the live run survived: the draw is
                # replayed (above) and its ids stay retired — in-memory
                # allocation counts known ids, the store counter floor
                # is applied after replay
                if tids:
                    st.trials._ids.update(tids)
                    self._replay_ctx["void_max"][sid] = max(
                        max(tids),
                        self._replay_ctx["void_max"].get(sid, -1))
                return
            st.n_asked += len(tids)
            # the idempotency map replays with the record: a client
            # whose ask response died with the old process retries
            # against the resumed/migrated study and must get the SAME
            # tids, not a fresh draw
            st.remember_req(rec.get("req"), tids)
            existing = {d["tid"] for d in st.trials._dynamic_trials}
            if all(t in existing for t in tids):
                stats["asks"] += 1
                return  # the store already holds this ask's docs
            # in-flight at the crash: regenerate through the algo that
            # served it (recorded — never re-derived: replay-time trial
            # counts include later store docs)
            if rec.get("algo") == "rand":
                docs = rand.suggest(tids, st.domain, st.trials, seed)
                self._land(st, docs)
                st.note("ask", tids=tids, algo="rand", replay=True,
                        trace=rec.get("trace"))
            else:
                req = _AskReq(st, tids, seed, replay=True,
                              trace=rec.get("trace"))
                self._run_wave([req])
                if req.error is not None:
                    raise req.error
            stats["asks"] += 1
            stats["regenerated"] += 1
        elif kind == "tell":
            tid = int(rec["tid"])
            key = (sid, tid)
            doc = next((d for d in st.trials._dynamic_trials
                        if d["tid"] == tid), None)
            if doc is None:
                stats["skipped"] += 1
            elif key in self._replay_ctx["told"]:
                # the SAME tell twice in the journal (crash between the
                # append and the client's retry): exactly-once — skip
                stats["duplicate_tells"] += 1
            elif doc["state"] == JOB_STATE_DONE:
                # store-ahead: the tell settled into the FileStore
                # before the crash.  The result is already folded; only
                # the scheduler-side bookkeeping needs replaying.
                self._replay_ctx["told"].add(key)
                st.n_told += 1
                st.note("tell", tid=tid, replay=True,
                        trace=rec.get("trace"))
                stats["tells"] += 1
                # the result is already in the store, but the tell-time
                # bookkeeping (incremental best, quality plane) must
                # still fold it — observation is once per told trial on
                # BOTH replay branches
                res = doc.get("result") or {}
                ok_loss = (res.get("loss")
                           if res.get("status") == STATUS_OK else None)
                st.record_result(ok_loss)
                if self.quality is not None and not st.canary:
                    try:
                        self.quality.observe_tell(st, ok_loss,
                                                  replay=True)
                    except Exception as e:  # noqa: BLE001
                        logging.getLogger(__name__).warning(
                            "quality observe_tell failed: %s", e)
                if self.tenants is not None and not st.canary:
                    # the tenant table rebuilds from replay on BOTH tell
                    # branches — store-ahead tells count too
                    try:
                        self.tenants.observe_tell(st.tenant)
                    except Exception as e:  # noqa: BLE001
                        logging.getLogger(__name__).warning(
                            "tenant observe_tell failed: %s", e)
                if (st.max_trials is not None
                        and st.n_trials >= st.max_trials
                        and st.n_pending == 0):
                    st.state = "done"
            else:
                self._replay_ctx["told"].add(key)
                # note BEFORE _apply_tell, matching the live tell path,
                # so quality events interleave identically on replay
                st.note("tell", tid=tid, replay=True,
                        trace=rec.get("trace"))
                self._apply_tell(st, doc, rec.get("loss"),
                                 rec.get("status"), replay=True)
                stats["tells"] += 1
        elif kind == "close":
            st.state = "closed"
            self._evict_from_cohort(st)
        # unknown kinds: forward-compat, ignored

    def _maybe_compact(self):
        """Compact the WAL to one snapshot record per live study — only
        with a store (without one the ask records ARE the trial data)
        and only at quiescent points (a pending ask's seed draw is not
        yet journaled; snapshotting the advanced RNG would replay that
        draw twice).  Settled/closed studies drop out of the journal —
        their trials stay on disk, but a restart forgets the registry
        entry (by design: the WAL bounds at O(live studies))."""
        if self.journal is None or self.store_root is None:
            return False
        if self._tick_running or self._wave_reqs:
            return False
        recs = [StudyJournal.snapshot_rec(s)
                for s in self._studies.values() if s.state == "active"]
        # quarantine markers survive every compaction: a restart must
        # keep answering 410 for a corrupt study until an operator
        # repairs the store, not resurrect it as unknown (404)
        recs += [StudyJournal.quarantine_rec(sid, info.get("reason", ""))
                 for sid, info in sorted(self._quarantined.items())]
        try:
            self.journal.rewrite(recs)
        except JournalError as e:
            logging.getLogger(__name__).warning(
                "service: WAL compaction failed: %s", e)
            self.metrics.counter("service.wal.compact_errors").inc()
            return False
        self.metrics.counter("service.wal.compactions").inc()
        return True

    def drain(self, timeout=30.0):
        """Graceful-drain half of SIGTERM handling: stop admitting (new
        studies AND new asks answer 429 via ``_draining``; tells keep
        landing — they preserve client work), wait for in-flight waves
        to finish, then compact and close the WAL.  Per-study stores
        need no settling pass — every mutation wrote through at tell
        time.  Returns True when the scheduler quiesced within
        ``timeout``."""
        with self._cond:
            self._draining = True
            deadline = time.monotonic() + float(timeout)
            while self._tick_running or self._wave_reqs:
                left = deadline - time.monotonic()
                if left <= 0:
                    break
                self._cond.wait(timeout=min(0.25, left))
            quiesced = not (self._tick_running or self._wave_reqs)
            if self.journal is not None:
                if quiesced:
                    self._maybe_compact()
                try:
                    self.journal.close()
                except JournalError:
                    pass
        if self._owns_plane and self.compile_plane is not None:
            # outside the lock: stop() joins the worker, and a worker
            # mid-compile never needs the scheduler lock — but joining
            # under it would still serialize drain behind XLA
            self.compile_plane.stop(timeout=5.0)
        return quiesced

    # -- status ------------------------------------------------------------

    def study_status(self, study_id):
        with self._lock:
            return self._get(study_id).status_dict()

    def study_timeline(self, study_id):
        """The ``GET /study/<id>/timeline`` payload: the study's live
        audit timeline (admit, every ask with wave/algo/degrade/trace,
        every tell, shed/void, evict/re-admit, resume boundary).  The
        WAL holds the durable copy; ``obs.report --study`` joins both."""
        with self._lock:
            # quarantined studies stay INSPECTABLE: the timeline (with
            # its quarantine event) is exactly what the operator needs
            # before deciding to scrub --repair — only ask/tell/close
            # answer 410
            st = self._studies.get(study_id)
            if st is not None:
                return st.timeline_dict()
            return self._get(study_id).timeline_dict()

    def studies_status(self):
        """The ``GET /studies`` payload: per-study status plus the
        cohort/slot roll-up."""
        with self._lock:
            cohorts = [{
                "space_sig": repr(key[0])[:64],
                "cap": c.cap,
                "n_slots": c.n_slots,
                "n_live": c.n_live,
                "ticks": c.ticks,
            } for key, c in self._cohorts.items()]
            studies = [s.status_dict() for s in self._studies.values()]
            if self.quality is not None:
                for s in studies:
                    q = self.quality.study_status(s.get("study_id"))
                    if q is not None:
                        s["quality"] = q
            if self.load is not None:
                for s in studies:
                    c = self.load.study_status(s.get("study_id"))
                    if c is not None:
                        s["load"] = c
            for sid, info in sorted(self._quarantined.items()):
                if sid not in self._studies:
                    # quarantined before its admit record could replay:
                    # listed anyway — a study the operator must know
                    # about is not allowed to vanish from /studies
                    studies.append({"study_id": sid,
                                    "state": "quarantined",
                                    "quarantine_reason":
                                        info.get("reason")})
            out = {
                "ts": time.time(),
                "n_studies": len(self._studies),
                "slot_utilization": self.slot_utilization(),
                "cohort_cache": tpe.cohort_cache_stats(),
                "cohorts": cohorts,
                "studies": studies,
                "draining": self._draining,
            }
            if self.tenants is not None:
                out["tenants"] = self.tenants.status()
            if self._quarantined:
                out["quarantined"] = {
                    sid: info.get("reason")
                    for sid, info in sorted(self._quarantined.items())}
            store = self.store_health()
            if store is not None:
                out["store"] = store
            if self.degrade is not None:
                out["degrade"] = self.degrade.status()
            if self.compile_plane is not None:
                comp = self.compile_plane.publish()
                comp["warming_studies"] = sum(
                    1 for s in self._studies.values()
                    if s.warming and s.state == "active")
                comp["widen"] = self.widen
                out["compile"] = comp
            if self.journal is not None:
                out["wal"] = {
                    "path": self.journal.path,
                    "appends": self.journal.appends,
                    "compactions": self.journal.compactions,
                    "size_bytes": self.journal.size_bytes(),
                    "last_resume": self.last_resume,
                }
            return out
