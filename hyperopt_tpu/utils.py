"""General utilities.

Parity target: ``hyperopt/utils.py`` (sym: import_tokens, json_call,
get_most_recent_inds, fast_isin, temp_dir, working_dir, get_closest_dir,
coarse_utcnow).  ``use_obj_for_literal_in_memo`` has no analog — it patched
``Ctrl`` objects into pyll interpreter memos, and there is no interpreter
here (``Ctrl`` is passed to ``Domain.evaluate`` directly).
"""

from __future__ import annotations

import contextlib
import os
import shutil

import numpy as np

from .base import coarse_utcnow  # noqa: F401  (re-export, reference parity)

__all__ = [
    "import_tokens",
    "json_call",
    "get_most_recent_inds",
    "fast_isin",
    "temp_dir",
    "working_dir",
    "path_split_all",
    "get_closest_dir",
    "coarse_utcnow",
    "LRUCache",
]


_LRU_MISS = object()  # module-level so LRUCache instances pickle cleanly


class LRUCache:
    """Bounded most-recently-used mapping for compiled-program caches (no
    reference analog — upstream has no compiled programs to cache).  Each
    entry pins an XLA executable and possibly a user closure, so the
    unbounded-dict alternative leaks memory across sweeps of spaces, configs,
    or per-call lambdas.

    ``hits``/``misses`` count ``get`` outcomes for the obs metrics registry
    (``device_fmin`` publishes its compiled-run cache's rates).

    Thread-safe: the compile plane (ISSUE 14) builds programs into the
    cohort jit cache from a background thread while serving threads get
    and probe it — without the lock, ``put``'s eviction iterator racing
    a concurrent ``get``'s pop/re-insert raises "dictionary changed
    size during iteration" inside a live tick, and ``get``'s transient
    pop window makes a membership probe miss a present key."""

    def __init__(self, maxsize):
        import threading

        self.maxsize = int(maxsize)
        # maxsize < 1 would make put() evict from an empty dict
        # (StopIteration from next(iter({}))) — fail at construction, not
        # at the first insert (ADVICE.md round 5)
        assert self.maxsize >= 1, f"LRUCache maxsize must be >= 1, got {maxsize}"
        self._d = {}
        self._lock = threading.Lock()
        self.hits = 0
        self.misses = 0

    # pickle support (device_fmin's run cache rides Trials pickles):
    # locks are process-local, rebuild on load
    def __getstate__(self):
        state = self.__dict__.copy()
        del state["_lock"]
        return state

    def __setstate__(self, state):
        import threading

        self.__dict__.update(state)
        self._lock = threading.Lock()

    def get(self, key, default=None):
        # sentinel, not None: a stored None value must register as a hit
        with self._lock:
            v = self._d.pop(key, _LRU_MISS)
            if v is _LRU_MISS:
                self.misses += 1
                return default
            self.hits += 1
            self._d[key] = v  # re-insert: most-recently-used at the end
            return v

    def put(self, key, value):
        with self._lock:
            self._d.pop(key, None)  # overwrite must not evict an extra entry
            while len(self._d) >= self.maxsize:
                self._d.pop(next(iter(self._d)))  # evict least-recently-used
            self._d[key] = value

    def contains(self, key):
        """Non-mutating membership probe: no hit/miss counted, recency
        untouched (the compile plane's readiness check must not make the
        probed entry look hot to the eviction policy)."""
        with self._lock:
            return key in self._d

    def stats(self):
        with self._lock:
            return {"hits": self.hits, "misses": self.misses,
                    "size": len(self._d), "maxsize": self.maxsize}

    def __len__(self):
        return len(self._d)


def import_tokens(tokens):
    """Import a dotted path given as a token list (utils.py sym: import_tokens)."""
    module = __import__(tokens[0])
    out = module
    for t in tokens[1:]:
        out = getattr(out, t)
    return out


def json_call(json_spec, args=(), kwargs=None):
    """Call a function named by dotted string or ('name', args, kwargs) spec
    (utils.py sym: json_call)."""
    if kwargs is None:
        kwargs = {}
    if isinstance(json_spec, str):
        return import_tokens(json_spec.split("."))(*args, **kwargs)
    if isinstance(json_spec, (list, tuple)):
        name = json_spec[0]
        extra_args = json_spec[1] if len(json_spec) > 1 else []
        extra_kwargs = json_spec[2] if len(json_spec) > 2 else {}
        return import_tokens(name.split("."))(
            *(list(args) + list(extra_args)), **{**kwargs, **extra_kwargs}
        )
    raise TypeError(f"cannot json_call {json_spec!r}")


def get_most_recent_inds(obj):
    """Indices of documents that are the latest version of their _id
    (utils.py sym: get_most_recent_inds)."""
    ids = np.asarray([d["_id"] for d in obj])
    versions = np.asarray([d["version"] for d in obj])
    s = np.lexsort((versions, ids))  # by _id, then version
    recent = np.ones(len(s), dtype=bool)
    if len(s) > 1:
        recent[:-1] = ids[s][1:] != ids[s][:-1]
    return s[recent]


def fast_isin(X, Y):
    """Boolean mask of which X appear in Y; both 1-D (utils.py sym: fast_isin)."""
    return np.isin(np.asarray(X), np.asarray(Y))


@contextlib.contextmanager
def temp_dir(dir, erase_after=False, with_sentinel=True):
    """Create ``dir`` (and a sentinel marking it safe to delete); optionally
    remove it afterwards (utils.py sym: temp_dir)."""
    created_by_me = False
    if not os.path.exists(dir):
        os.makedirs(dir)
        created_by_me = True
        if with_sentinel:
            open(os.path.join(dir, ".hyperopt_temp_sentinel"), "w").close()
    try:
        yield dir
    finally:
        if erase_after and created_by_me and os.path.exists(dir):
            sentinel = os.path.join(dir, ".hyperopt_temp_sentinel")
            if not with_sentinel or os.path.exists(sentinel):
                shutil.rmtree(dir)


@contextlib.contextmanager
def working_dir(dir):
    """chdir into ``dir`` for the block (utils.py sym: working_dir)."""
    cwd = os.getcwd()
    os.chdir(dir)
    try:
        yield dir
    finally:
        os.chdir(cwd)


def path_split_all(path):
    """All components of a path (utils.py sym: path_split_all)."""
    parts = []
    while True:
        path, tail = os.path.split(path)
        if tail:
            parts.append(tail)
        else:
            if path:
                parts.append(path)
            break
    parts.reverse()
    return parts


def get_closest_dir(workdir):
    """Deepest existing ancestor of ``workdir`` plus the first missing
    component (utils.py sym: get_closest_dir)."""
    closest_dir = ""
    for wdi in path_split_all(workdir):
        if os.path.isdir(os.path.join(closest_dir, wdi)):
            closest_dir = os.path.join(closest_dir, wdi)
        else:
            break
    assert closest_dir != workdir
    return closest_dir, wdi
