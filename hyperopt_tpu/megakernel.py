"""Quantized-history fused-suggest megakernel (ISSUE 19).

One tiled Pallas kernel per numeric label fuses the hot middle of the
ask tick — truncated-mixture candidate SAMPLING (interval-indicator
component pick over the below model's truncated-weight CDF, then
``x = mu + sigma * ndtri(u)``) and the dual below/above ``GMM1_lpdf``
EI accumulation, streamed over the component axis (prior + history
slots — the shardable history axis) with f32 ``(max, scaled-sum)``
streaming-logsumexp carries.  The jnp path materializes the
``[components, candidates]`` matrix twice and round-trips the sampled
candidates through HBM between the sample and score ops; here the
candidate block stays in VMEM/registers across both phases — one pass,
no materialized matrix, both models in the same loop.

Division of labor (docs/DESIGN.md §25):

* **XLA preamble** — row fold (``tpe._apply_rows``, donation-aliased),
  below/above split, adaptive-Parzen fits (the neighbor-gap sigma rule
  needs a sort — not tileable), truncation tables (alpha/beta/CDF) and
  the uniform draws.  History dequantization (int8/fp8 codes → f32)
  happens at the fit's read boundary (``tpe._read_vals``), so the
  quantized cohort feeds the kernel the same f32 component tables.
* **Pallas kernel** — component tables live in SMEM (dynamic scalar
  reads; a dynamic lane index into VMEM is not lowerable), candidates
  tile the VPU as (8, 128) blocks padded to 1024 lanes.  Loop 1 picks
  each candidate's component by first-CDF-crossing indicator carry;
  loop 2 accumulates BOTH mixtures' log-densities with streaming
  logsumexp.  All accumulators are f32 regardless of the history
  storage dtype (the §13 contract).
* **XLA postamble** — truncation normalizers, exp for log-space labels,
  the pinned ``_select_candidate`` / ``_mix_prior`` RNG stream, and
  ``rand.pack_labels`` — identical structure to the jnp cohort program,
  so donation, sharding rules and the scheduler/compile-plane contract
  are untouched.

Arming ladder: ``HYPEROPT_TPU_MEGAKERNEL=1`` arms on TPU backends;
``=interpret`` runs the same kernel through the Pallas interpreter on
any backend (CI).  A space the kernel cannot express (discrete or
value-quantized ``q*`` labels) simply doesn't arm — the jnp program
serves it.  A LOWERING failure disarms the space permanently
(warn-once + ``suggest.megakernel.fallback`` counter) and
``tpe.build_suggest_batched`` rebuilds the plain program under the
recomputed cohort key — an ask never fails because hand-scheduling was
misconfigured.

This module also absorbs the validated EI-pair kernel that previously
lived in ``pallas_ei.py`` (``ei_diff`` / ``ei_diff_reference``); that
module is now a deprecated re-export shim.  The measured verdict that
kept the EI pair out of the default path — XLA already fuses the jnp
lpdf formulation near-optimally at small component counts — is
recorded in DESIGN.md §25 ("when hand-scheduling pays"); the
megakernel targets the regime it identified: large candidate axes and
component counts where the ``[m, n]`` intermediates stop fitting VMEM,
now with the extra HBM round trip between sample and score also
removed.
"""

from __future__ import annotations

import functools
import logging
import math

import numpy as np

import jax
import jax.numpy as jnp
from jax.scipy.special import ndtri

__all__ = [
    "mode",
    "supports",
    "armed",
    "build_cohort",
    "ei_diff",
    "ei_diff_reference",
    "pallas_available",
    "fallback_count",
]

logger = logging.getLogger(__name__)

# log(sqrt(2*pi))
_LOG_SQRT_2PI = 0.9189385332046727
# stand-in for -inf that survives max/exp arithmetic without NaNs
_VERY_NEG = -1e30

_LANES = 128
_SUBLANES = 8
_BLOCK = _LANES * _SUBLANES  # candidates per grid step

# spaces whose kernel failed to lower on this process' backend — armed()
# turns False for them so cohort_key recomputes plain (see build_cohort)
_failed = set()
_warned = set()


def _count(name):
    try:
        from .obs.metrics import get_metrics

        get_metrics("service").counter(name).inc()
    except Exception:  # noqa: BLE001 - telemetry must not take down an ask
        pass


def fallback_count():
    """Current ``suggest.megakernel.fallback`` counter value (tests)."""
    from .obs.metrics import get_metrics

    snap = get_metrics("service").snapshot()["metrics"]
    return int(snap.get("suggest.megakernel.fallback", 0) or 0)


def _disarm(cs, err):
    """Lowering failed: warn once per space, bump the scrape-visible
    counter, and mark the space so ``armed()`` — and therefore
    ``tpe.cohort_key`` — flips to the plain jnp program."""
    sig = cs.signature()
    _failed.add(sig)
    if sig not in _warned:
        _warned.add(sig)
        logger.warning(
            "megakernel lowering failed for this space; serving the jnp "
            "cohort program instead (warn-once; ask unaffected): %s", err)
    _count("suggest.megakernel.fallback")


def mode():
    """``"off"`` | ``"on"`` | ``"interpret"`` — the resolved arming knob.
    The deprecated ``HYPEROPT_TPU_PALLAS=1`` alias maps to ``"on"``
    (with its own warn-once in ``_env.parse_pallas``)."""
    from ._env import parse_megakernel, parse_pallas

    m = parse_megakernel()
    if m == "off" and parse_pallas():
        return "on"
    return m


def pallas_available():
    """True when the default backend lowers Mosaic (i.e. a real TPU)."""
    try:
        return jax.default_backend() == "tpu"
    except Exception:
        return False


def supports(cs):
    """True when every label is a numeric, un-value-quantized family —
    the shapes the fused sample+score kernel expresses.  Discrete and
    ``q*`` labels keep the jnp program (no fallback event: an
    unsupported SPACE is a routing decision, not a failure)."""
    from .algos.tpe import _parzen_from

    for l in cs.labels:
        dist = cs.params[l].dist
        if dist.family in ("categorical", "randint"):
            return False
        try:
            _, _, _, _, q, _ = _parzen_from(dist)
        except ValueError:
            return False
        if q is not None:
            return False
    return True


def armed(cs):
    """Whether THIS space's cohort builds as the megakernel right now:
    opted in, expressible, not lowering-failed, and on a backend that
    can run it (TPU, or any backend under ``interpret``)."""
    m = mode()
    if m == "off":
        return False
    if cs.signature() in _failed:
        return False
    if not supports(cs):
        return False
    return m == "interpret" or pallas_available()


# ---------------------------------------------------------------------------
# the fused sample + dual-lpdf kernel
# ---------------------------------------------------------------------------


def _make_fused_kernel(m, low, high):
    """Kernel body for ``m`` mixture components and STATIC t-space bounds
    (``±inf`` for the unbounded families — the clip resolves at trace
    time, mirroring ``tpe._trunc_masses``'s static-bounds doctrine).

    Refs: ``uc``/``u0`` — uniform draws, (8, 128) VMEM blocks;
    ``cdf/mb/sb/ab/bb`` — below model's normalized truncated-weight CDF,
    locations, scales, per-component truncation cdfs (SMEM);
    ``wb``/``wa,ma,sa`` — raw weights of both models for the lpdf pass
    (SMEM).  Outs: sampled candidate ``x`` (t-space) and the raw
    two-mixture log-density difference ``ei`` (truncation normalizers
    are scalars applied by the caller)."""
    bounded = math.isfinite(low) and math.isfinite(high)
    if bounded:
        hi_in = float(np.nextafter(np.float32(high), np.float32(low)))

    def kernel(uc_ref, u0_ref, cdf_ref, mb_ref, sb_ref, ab_ref, bb_ref,
               wb_ref, wa_ref, ma_ref, sa_ref, x_ref, ei_ref):
        uc = uc_ref[:]
        u0 = u0_ref[:]

        # -- loop 1: component pick.  First index i with uc <= cdf[i]
        # equals the jnp path's #{cdf entries < uc} (cdf nondecreasing),
        # expressed as an indicator carry instead of a per-lane gather.
        def pick(i, carry):
            done, mu, s, a, b = carry
            sel = jnp.where(done < 0.5,
                            jnp.where(uc <= cdf_ref[i], 1.0, 0.0),
                            0.0)
            mu = jnp.where(sel > 0.5, mb_ref[i], mu)
            s = jnp.where(sel > 0.5, sb_ref[i], s)
            a = jnp.where(sel > 0.5, ab_ref[i], a)
            b = jnp.where(sel > 0.5, bb_ref[i], b)
            return done + sel, mu, s, a, b

        shape = uc.shape
        init = (jnp.zeros(shape, jnp.float32),
                jnp.full(shape, mb_ref[m - 1], jnp.float32),
                jnp.full(shape, sb_ref[m - 1], jnp.float32),
                jnp.full(shape, ab_ref[m - 1], jnp.float32),
                jnp.full(shape, bb_ref[m - 1], jnp.float32))
        _, mu_s, s_s, a_s, b_s = jax.lax.fori_loop(0, m, pick, init)

        # -- inverse-CDF draw inside the picked component's truncated
        # interval (tpe.gmm1_sample math, f32 throughout)
        u = a_s + u0 * (b_s - a_s)
        u = jnp.clip(u, 1e-7, 1.0 - 1e-7)
        x = mu_s + s_s * ndtri(u)
        if bounded:
            # strictly inside the half-open [low, high) support — a
            # sample at exactly `high` scores -inf under both models
            x = jnp.clip(x, jnp.float32(low), jnp.float32(hi_in))

        # -- loop 2: dual streaming logsumexp over the SAME component
        # stream; the candidate block never leaves VMEM between phases
        def lse(i, carry):
            mxb, seb, mxa, sea = carry

            def comp(w, mu, s):
                logw = jnp.where(w > 0.0, jnp.log(jnp.maximum(w, 1e-12)),
                                 jnp.float32(_VERY_NEG))
                return (logw - 0.5 * ((x - mu) / s) ** 2
                        - jnp.log(s) - jnp.float32(_LOG_SQRT_2PI))

            cb = comp(wb_ref[i], mb_ref[i], sb_ref[i])
            nb = jnp.maximum(mxb, cb)
            seb = seb * jnp.exp(mxb - nb) + jnp.exp(cb - nb)
            ca = comp(wa_ref[i], ma_ref[i], sa_ref[i])
            na = jnp.maximum(mxa, ca)
            sea = sea * jnp.exp(mxa - na) + jnp.exp(ca - na)
            return nb, seb, na, sea

        neg = jnp.full(shape, _VERY_NEG, jnp.float32)
        zero = jnp.zeros(shape, jnp.float32)
        mxb, seb, mxa, sea = jax.lax.fori_loop(
            0, m, lse, (neg, zero, neg, zero))
        x_ref[:] = x
        ei_ref[:] = (mxb + jnp.log(seb)) - (mxa + jnp.log(sea))

    return kernel


@functools.lru_cache(maxsize=None)
def _build_fused(n, m, low, high, interpret):
    """pallas_call wrapper for ``n`` padded candidates (multiple of 1024)
    and ``m`` components; cached per (shape, bounds, interpret)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    rows = n // _LANES
    grid = rows // _SUBLANES
    comp_spec = pl.BlockSpec(memory_space=pltpu.SMEM)
    blk = pl.BlockSpec((_SUBLANES, _LANES), lambda i: (i, 0))

    def call(uc2d, u02d, cdf, mb, sb, ab, bb, wb, wa, ma, sa):
        return pl.pallas_call(
            _make_fused_kernel(m, low, high),
            out_shape=(jax.ShapeDtypeStruct((rows, _LANES), jnp.float32),
                       jax.ShapeDtypeStruct((rows, _LANES), jnp.float32)),
            grid=(grid,),
            in_specs=[blk, blk] + [comp_spec] * 9,
            out_specs=(blk, blk),
            interpret=interpret,
        )(uc2d, u02d, cdf, mb, sb, ab, bb, wb, wa, ma, sa)

    return call


def _fused_sample_ei(key, obs, below_mask, above_mask, cfg, parz,
                     interpret):
    """The fused replacement for ``tpe._propose_numeric``'s middle:
    Parzen fits + truncation tables in XLA, sample+score in the kernel,
    normalizers in XLA.  Returns ``(samples value-space, ei)`` over
    ``cfg['n_EI_candidates']`` candidates — drop-in for the jnp pair.

    RNG: same ``split(key)`` → (component draw, interval draw) stream as
    ``gmm1_sample``; draws beyond ``n_cand`` pad the 1024-lane tile with
    a constant and are sliced off (their EI is never consumed)."""
    from .algos import tpe

    prior_mu, prior_sigma, low, high, q, log_space = parz
    assert q is None
    t_obs = jnp.log(jnp.maximum(obs, tpe.EPS)) if log_space else obs
    fit = functools.partial(
        tpe.adaptive_parzen_normal,
        prior_weight=cfg["prior_weight"],
        prior_mu=jnp.float32(prior_mu),
        prior_sigma=jnp.float32(prior_sigma),
        LF=cfg["LF"],
    )
    wb, mb, sb = fit(t_obs, below_mask)
    wa, ma, sa = fit(t_obs, above_mask)
    ab, bb, mass_b, pb = tpe._trunc_masses(wb, mb, sb, low, high)
    _, _, _, pa = tpe._trunc_masses(wa, ma, sa, low, high)
    cdf = jnp.cumsum(wb * mass_b)
    cdf = cdf / jnp.maximum(cdf[-1], tpe.EPS)

    n_cand = int(cfg["n_EI_candidates"])
    n_pad = ((n_cand + _BLOCK - 1) // _BLOCK) * _BLOCK
    k_comp, k_u = jax.random.split(key)
    uc = jax.random.uniform(k_comp, (n_cand,))
    u0 = jax.random.uniform(k_u, (n_cand,))
    if n_pad != n_cand:
        pad = [(0, n_pad - n_cand)]
        uc = jnp.pad(uc, pad, constant_values=0.5)
        u0 = jnp.pad(u0, pad, constant_values=0.5)

    run = _build_fused(n_pad, int(wb.shape[0]), float(low), float(high),
                       bool(interpret))
    x2d, ei2d = run(uc.reshape(n_pad // _LANES, _LANES),
                    u0.reshape(n_pad // _LANES, _LANES),
                    cdf, mb, sb, ab, bb, wb, wa, ma, sa)
    x = x2d.reshape(n_pad)[:n_cand]
    ei = ei2d.reshape(n_pad)[:n_cand]
    # truncation normalizers (scalars; the log-space Jacobian cancels in
    # the below−above difference, exactly as in tpe._ei_pallas)
    ei = (ei - jnp.log(jnp.maximum(pb, tpe.EPS))
          + jnp.log(jnp.maximum(pa, tpe.EPS)))
    samples = jnp.exp(x) if log_space else x
    return samples, ei


def _propose_fused(cs, cfg, qparams, interpret):
    """``propose(history, key) -> {label: value}`` with the fused kernel
    in place of the jnp sample+score middle; split, selection and
    prior-mix reuse ``tpe``'s pinned RNG stream bit for bit."""
    from .algos import tpe

    parz_of = {l: tpe._parzen_from(cs.params[l].dist) for l in cs.labels}

    def propose(history, key):
        from .spaces import label_hash

        losses = jnp.asarray(history["losses"]).astype(jnp.float32)
        has_loss = jnp.asarray(history["has_loss"])
        below, above = tpe.split_below_above(
            losses, has_loss, cfg["gamma"], cfg["LF"])
        out = {}
        for label in cs.labels:
            parz = parz_of[label]
            _, _, low, high, q, log_space = parz
            vals = tpe._read_vals(history, label, qparams)
            active = jnp.asarray(history["active"][label])
            k = jax.random.fold_in(key, label_hash(label))
            samples, ei = _fused_sample_ei(
                k, vals, below & active, above & active, cfg, parz,
                interpret)
            ei = jnp.where(jnp.isnan(ei), -jnp.inf, ei)
            val, ei_sel = tpe._select_candidate(k, samples, ei, cfg)
            prior_mu, prior_sigma = parz[0], parz[1]
            t_obs = (jnp.log(jnp.maximum(vals, tpe.EPS))
                     if log_space else vals)
            fit = functools.partial(
                tpe.adaptive_parzen_normal,
                prior_weight=cfg["prior_weight"],
                prior_mu=jnp.float32(prior_mu),
                prior_sigma=jnp.float32(prior_sigma),
                LF=cfg["LF"],
            )
            wb, mb, sb = fit(t_obs, below & active)
            wa, ma, sa = fit(t_obs, above & active)
            lpdf = tpe.lgmm1_lpdf if log_space else tpe.gmm1_lpdf
            v, _, _ = tpe._mix_prior(
                k, cfg, val, ei_sel,
                lambda kp, p=parz: tpe._prior_draw_numeric(
                    kp, p[0], p[1], p[2], p[3], p[4], p[5]),
                lambda xs, a=(wb, mb, sb), b=(wa, ma, sa), lo=low, hi=high,
                qq=q, f=lpdf: (f(xs, *a, lo, hi, qq) - f(xs, *b, lo, hi, qq)),
            )
            out[label] = v
        return out

    return propose


def build_cohort(cs, cfg, n_studies, cap, n_ids, donate=True, mesh=None,
                 qparams=None):
    """The megakernel build of ``tpe.build_suggest_batched``'s program:
    same ``run(hist_stack, rows_stack, seed_words[S, 2], ids[S, B]) ->
    (hist_stack', packed[S, B, L])`` signature, same donation and
    partition rules — only the per-label sample+score middle is the
    fused Pallas kernel.  Returns None when the kernel fails to LOWER
    for this space's shapes (and disarms the space — the caller then
    rebuilds plain under the recomputed cohort key).

    The lowering probe compiles the kernel eagerly at its concrete
    shapes (component count ``cap + 1``, 1024-lane candidate tile,
    including a vmap axis standing in for the study×id batching) so a
    Mosaic failure surfaces HERE, at build time, never inside an ask.
    """
    from .algos import rand, tpe

    interpret = mode() == "interpret"
    m = int(cap) + 1  # prior component + one per history slot
    try:
        for label in cs.labels:
            _, _, low, high, _, _ = tpe._parzen_from(cs.params[label].dist)
            n_cand = int(cfg["n_EI_candidates"])
            n_pad = ((n_cand + _BLOCK - 1) // _BLOCK) * _BLOCK
            blk = jax.ShapeDtypeStruct((2, n_pad // _LANES, _LANES),
                                       jnp.float32)
            tab = jax.ShapeDtypeStruct((2, m), jnp.float32)
            kern = _build_fused(n_pad, m, float(low), float(high),
                                interpret)
            jax.jit(jax.vmap(kern)).lower(
                blk, blk, *([tab] * 9)).compile()
    except Exception as e:  # noqa: BLE001 - any lowering error disarms
        _disarm(cs, e)
        return None

    propose = _propose_fused(cs, cfg, qparams, interpret)
    labels = cs.labels

    def one(history, rows, seed_words, ids):
        hist = tpe._apply_rows(labels, history, rows, qparams)
        k = jax.random.fold_in(
            jax.random.PRNGKey(seed_words[0]), seed_words[1])
        keys = jax.vmap(lambda i: jax.random.fold_in(k, i))(ids)
        out = jax.vmap(propose, in_axes=(None, 0))(hist, keys)
        return hist, rand.pack_labels(cs, out)

    run = jax.vmap(one)
    donate_kw = {"donate_argnums": (0,)} if donate else {}
    if mesh is None:
        return jax.jit(run, **donate_kw)
    from .parallel import sharding as _sh

    in_sh, out_sh = _sh.suggest_batched_shardings(mesh, labels)
    return jax.jit(run, in_shardings=in_sh, out_shardings=out_sh,
                   **donate_kw)


# ---------------------------------------------------------------------------
# the EI-pair kernel (formerly pallas_ei.py) — the score-only fusion the
# sharded candidate axis and the per-label `_ei_pallas` opt-in consume
# ---------------------------------------------------------------------------


def ei_diff_reference(x, wb, mb, sb, wa, ma, sa):
    """jnp twin of the kernel: logsumexp_b(x) - logsumexp_a(x) over the two
    (weights, mus, sigmas) mixtures, no truncation terms."""
    from jax.scipy.special import logsumexp

    def model(w, mu, s):
        logw = jnp.where(w > 0, jnp.log(jnp.maximum(w, 1e-12)), -jnp.inf)
        comp = (logw[:, None]
                - 0.5 * ((x[None, :] - mu[:, None]) / s[:, None]) ** 2
                - jnp.log(s)[:, None] - _LOG_SQRT_2PI)
        return logsumexp(comp, axis=0)

    return model(wb, mb, sb) - model(wa, ma, sa)


def _make_ei_kernel(m):
    """Kernel body for ``m`` live components; component tables live in
    SMEM (dynamic scalar reads)."""

    def kernel(x_ref, wb_ref, mb_ref, sb_ref, wa_ref, ma_ref, sa_ref,
               out_ref):
        x = x_ref[:]

        def mixture_lse(w_ref, mu_ref, s_ref):
            def body(i, carry):
                mx, se = carry
                w = w_ref[i]
                mu = mu_ref[i]
                s = s_ref[i]
                logw = jnp.where(w > 0.0, jnp.log(jnp.maximum(w, 1e-12)),
                                 jnp.float32(_VERY_NEG))
                comp = (logw - 0.5 * ((x - mu) / s) ** 2
                        - jnp.log(s) - jnp.float32(_LOG_SQRT_2PI))
                new_mx = jnp.maximum(mx, comp)
                se = se * jnp.exp(mx - new_mx) + jnp.exp(comp - new_mx)
                return new_mx, se

            init = (jnp.full(x.shape, _VERY_NEG, jnp.float32),
                    jnp.zeros(x.shape, jnp.float32))
            mx, se = jax.lax.fori_loop(0, m, body, init)
            return mx + jnp.log(se)

        llb = mixture_lse(wb_ref, mb_ref, sb_ref)
        lla = mixture_lse(wa_ref, ma_ref, sa_ref)
        out_ref[:] = llb - lla

    return kernel


@functools.lru_cache(maxsize=None)
def _build_ei(n, m, interpret=False):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    rows = n // _LANES
    grid = rows // _SUBLANES

    def call(x2d, wb, mb, sb, wa, ma, sa):
        comp_spec = pl.BlockSpec(memory_space=pltpu.SMEM)
        return pl.pallas_call(
            _make_ei_kernel(m),
            out_shape=jax.ShapeDtypeStruct((rows, _LANES), jnp.float32),
            grid=(grid,),
            in_specs=[
                pl.BlockSpec((_SUBLANES, _LANES), lambda i: (i, 0)),
                comp_spec, comp_spec, comp_spec,
                comp_spec, comp_spec, comp_spec,
            ],
            out_specs=pl.BlockSpec((_SUBLANES, _LANES), lambda i: (i, 0)),
            interpret=interpret,
        )(x2d, wb, mb, sb, wa, ma, sa)

    return call


def ei_diff(x, wb, mb, sb, wa, ma, sa):
    """EI score ``lpdf_below(x) - lpdf_above(x)`` (no truncation terms).

    Uses the pallas kernel when the candidate count tiles the TPU grid
    (multiple of 1024) on a TPU backend — or on any backend under
    ``HYPEROPT_TPU_MEGAKERNEL=interpret`` — jnp twin otherwise.
    """
    if wb.shape[0] != wa.shape[0]:
        # the kernel bakes ONE component count into both fori_loops (TPE's
        # below/above models share the padded cap, so this never triggers
        # from tpe.py) — mismatched mixtures must take the shape-generic path
        return ei_diff_reference(x, wb, mb, sb, wa, ma, sa)
    n = x.shape[0]
    interpret = mode() == "interpret"
    if n % _BLOCK == 0 and (pallas_available() or interpret):
        x2d = x.reshape(n // _LANES, _LANES)
        out = _build_ei(n, int(wb.shape[0]), interpret)(
            x2d, wb, mb, sb, wa, ma, sa)
        return out.reshape(n)
    return ei_diff_reference(x, wb, mb, sb, wa, ma, sa)
