"""Typed search-space IR compiled to jittable samplers.

This module replaces the reference's interpreted pyll stack — the ``Apply``
graph + ``rec_eval`` interpreter (``hyperopt/pyll/base.py`` sym: Apply,
rec_eval), the stochastic node library (``hyperopt/pyll/stochastic.py`` sym:
uniform..categorical, sample), the ``hp.*`` constructors
(``hyperopt/pyll_utils.py`` sym: hp_uniform..hp_choice) and the vectorizer
(``hyperopt/vectorize.py`` sym: VectorizeHelper) — with a TPU-first design:

* A search space is a small **static expression tree** (``Expr``): ``Param``
  leaves (labeled distributions), ``Choice`` branch points, arithmetic ``Op``
  nodes, containers and literals.  The structure is fixed at build time, so
  JAX's tracer plays the role of ``rec_eval``: ``compile_space`` lowers the
  tree ONCE into a pure function ``sample_flat(key) -> {label: value}`` that
  jits, vmaps and shards.  There is no runtime graph interpreter.
* The reference's lazy ``switch`` evaluation of conditional spaces (rec_eval
  special case, pyll/base.py) cannot exist under XLA's static dataflow.
  Instead every parameter is drawn unconditionally and a boolean **active
  mask** per label is computed from the drawn choice indices — the dense
  analog of vectorize.py's sparse ``(idxs, vals)`` representation.
* RNG: per-label ``jax.random.fold_in`` of a stable CRC32 label hash replaces
  the reference's threading of one mutable numpy RandomState through the graph
  (``hyperopt/pyll/stochastic.py`` sym: recursive_set_rng_kwarg).

Distribution semantics match the reference's stochastic nodes
(``hyperopt/pyll/stochastic.py``):

* ``loguniform(low, high)``: ``exp(uniform(low, high))`` — bounds in log space.
* ``q*``: ``round(x / q) * q`` in value space.
* ``lognormal(mu, sigma)``: mu/sigma parameterize the underlying normal.
* ``randint(low, high)``: integer in ``[low, high)``.
* ``uniformint(low, high)``: integer in ``[low, high]`` via quantized uniform.
"""

from __future__ import annotations

import dataclasses
import math
import zlib
from typing import Any, Callable

import numpy as np

import jax
import jax.numpy as jnp

from .exceptions import DuplicateLabel, InvalidAnnotatedParameter

__all__ = [
    "Expr",
    "Literal",
    "Op",
    "Container",
    "Param",
    "Choice",
    "Dist",
    "ParamInfo",
    "CompiledSpace",
    "as_expr",
    "compile_space",
    "sample",
    "space_eval",
    "expr_to_config",
    "label_hash",
]

# Families whose flat value is integral (stored i32): branch indices and ints.
INT_FAMILIES = frozenset({"randint", "uniformint", "categorical"})


def label_hash(label: str) -> int:
    """Stable 32-bit hash of a parameter label, used to fold RNG keys."""
    return zlib.crc32(label.encode("utf-8")) & 0x7FFFFFFF


# ---------------------------------------------------------------------------
# Expression tree
# ---------------------------------------------------------------------------


class Expr:
    """Base class for space expressions.

    Supports the arithmetic the reference exposes via ``Apply`` operator
    dunders (pyll/base.py sym: Apply.__add__ etc.) so idioms like
    ``hp.uniform('x', 0, 1) + 1`` keep working; the ops are compiled, not
    interpreted.
    """

    def __add__(self, other):
        return Op("add", (self, as_expr(other)))

    def __radd__(self, other):
        return Op("add", (as_expr(other), self))

    def __sub__(self, other):
        return Op("sub", (self, as_expr(other)))

    def __rsub__(self, other):
        return Op("sub", (as_expr(other), self))

    def __mul__(self, other):
        return Op("mul", (self, as_expr(other)))

    def __rmul__(self, other):
        return Op("mul", (as_expr(other), self))

    def __truediv__(self, other):
        return Op("truediv", (self, as_expr(other)))

    def __rtruediv__(self, other):
        return Op("truediv", (as_expr(other), self))

    def __floordiv__(self, other):
        return Op("floordiv", (self, as_expr(other)))

    def __pow__(self, other):
        return Op("pow", (self, as_expr(other)))

    def __rpow__(self, other):
        return Op("pow", (as_expr(other), self))

    def __neg__(self):
        return Op("neg", (self,))

    def __abs__(self):
        return Op("abs", (self,))

    def __getitem__(self, idx):
        return Op("getitem", (self, as_expr(idx)))


@dataclasses.dataclass(frozen=True)
class Literal(Expr):
    """A constant embedded in the space (pyll/base.py sym: Literal)."""

    value: Any


@dataclasses.dataclass(frozen=True)
class Op(Expr):
    """A pure elementwise operation over sub-expressions."""

    op: str
    args: tuple

    def __post_init__(self):
        if self.op not in _VALID_OPS:
            raise InvalidAnnotatedParameter(f"unknown op {self.op!r}")


@dataclasses.dataclass(frozen=True)
class Container(Expr):
    """dict / list / tuple of sub-expressions (pyll ``scope.dict``/``pos_args``)."""

    kind: str  # 'dict' | 'list' | 'tuple'
    keys: tuple  # dict keys ('' entries for list/tuple)
    children: tuple


@dataclasses.dataclass(frozen=True)
class Dist(Expr):
    """A distribution spec: family name + flat numeric params.

    The greppable analog of the reference's stochastic scope ops
    (``hyperopt/pyll/stochastic.py`` sym: uniform, quniform, loguniform,
    qloguniform, normal, qnormal, lognormal, qlognormal, randint, categorical).
    """

    family: str
    params: tuple  # family-specific floats (hashable → usable as static arg)


@dataclasses.dataclass(frozen=True)
class Param(Expr):
    """A labeled hyperparameter: the analog of ``scope.hyperopt_param``
    (``hyperopt/pyll_utils.py`` sym: hyperopt_param)."""

    label: str
    dist: Dist
    cast: str = "float"  # 'float' | 'int'


@dataclasses.dataclass(frozen=True)
class Choice(Expr):
    """A conditional branch point: ``hp.choice`` / ``hp.pchoice``.

    The reference compiles choice to ``scope.switch(hyperopt_param(label,
    randint(n)), *options)`` (``hyperopt/pyll_utils.py`` sym: hp_choice).
    Here the selector is itself a Param (family 'randint' for choice,
    'categorical' for pchoice) and the options are sub-expressions.
    """

    label: str
    options: tuple
    p: tuple | None = None  # pchoice probabilities (None → uniform prior)

    @property
    def selector_dist(self) -> Dist:
        n = len(self.options)
        if self.p is None:
            return Dist("randint", (0.0, float(n)))
        return Dist("categorical", tuple(float(x) for x in self.p))


def as_expr(obj: Any) -> Expr:
    """Convert a python structure into an Expr (pyll/base.py sym: as_apply)."""
    if isinstance(obj, Expr):
        return obj
    if isinstance(obj, dict):
        keys = tuple(sorted(obj.keys()))
        return Container("dict", keys, tuple(as_expr(obj[k]) for k in keys))
    if isinstance(obj, (list, tuple)):
        kind = "list" if isinstance(obj, list) else "tuple"
        return Container(kind, tuple("" for _ in obj), tuple(as_expr(o) for o in obj))
    return Literal(obj)


# ---------------------------------------------------------------------------
# Op tables (host + traced)
# ---------------------------------------------------------------------------

_OP_TABLE: dict[str, Callable] = {
    "add": lambda a, b: a + b,
    "sub": lambda a, b: a - b,
    "mul": lambda a, b: a * b,
    "truediv": lambda a, b: a / b,
    "floordiv": lambda a, b: a // b,
    "pow": lambda a, b: a**b,
    "neg": lambda a: -a,
    "abs": lambda a: abs(a),
    "getitem": lambda a, i: a[i],
}

_OP_TABLE_JNP: dict[str, Callable] = dict(
    _OP_TABLE,
    **{
        "exp": jnp.exp,
        "log": jnp.log,
        "sqrt": jnp.sqrt,
        "sin": jnp.sin,
        "cos": jnp.cos,
        "tan": jnp.tan,
        "maximum": jnp.maximum,
        "minimum": jnp.minimum,
    },
)

_OP_TABLE_NP: dict[str, Callable] = dict(
    _OP_TABLE,
    **{
        "exp": np.exp,
        "log": np.log,
        "sqrt": np.sqrt,
        "sin": np.sin,
        "cos": np.cos,
        "tan": np.tan,
        "maximum": np.maximum,
        "minimum": np.minimum,
    },
)

# The full validation set for Op.__post_init__: every op evaluable in both the
# host (numpy) and traced (jnp) tables, incl. the math families round 1 missed.
_VALID_OPS = frozenset(_OP_TABLE_JNP) & frozenset(_OP_TABLE_NP)


# Math helpers mirroring the reference's arithmetic scope ops so spaces can do
# e.g. ``spaces.exp(hp.normal('x', 0, 1))`` (pyll scope: exp/log/sqrt/...).
def _make_unary(name):
    def f(x):
        return Op(name, (as_expr(x),))

    f.__name__ = name
    return f


def _make_binary(name):
    def f(a, b):
        return Op(name, (as_expr(a), as_expr(b)))

    f.__name__ = name
    return f


exp = _make_unary("exp")
log = _make_unary("log")
sqrt = _make_unary("sqrt")
sin = _make_unary("sin")
cos = _make_unary("cos")
tan = _make_unary("tan")
maximum = _make_binary("maximum")
minimum = _make_binary("minimum")
for _n in ("exp", "log", "sqrt", "sin", "cos", "tan", "maximum", "minimum"):
    __all__.append(_n)


# ---------------------------------------------------------------------------
# Compilation
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class ParamInfo:
    """Everything the suggesters need to know about one hyperparameter.

    ``conditions`` is the activation path: a tuple of (choice_label,
    branch_index) pairs; the parameter is *active* in a trial iff every listed
    choice drew the listed branch.  This is the static-shape analog of the
    sparse idxs bookkeeping in ``hyperopt/vectorize.py`` (sym:
    VectorizeHelper.idxs_by_label).
    """

    label: str
    dist: Dist
    cast: str
    conditions: tuple  # ((choice_label, branch_index), ...)

    @property
    def is_int(self) -> bool:
        return self.dist.family in INT_FAMILIES or self.cast == "int"


class CompiledSpace:
    """A search space lowered to jittable functions.

    Replaces ``Domain``'s vectorized sampler program (``hyperopt/base.py``
    sym: Domain.__init__ → VectorizeHelper → s_idxs_vals) with:

    * ``sample_flat(key) -> {label: scalar}`` — draw every parameter.
    * ``active_flat(flat) -> {label: bool}`` — activation masks.
    * ``assemble(flat)`` — rebuild the user-facing structure (host).
    * ``sample(key)`` — one host-side structured sample (analog of
      ``hyperopt/pyll/stochastic.py`` sym: sample).
    """

    def __init__(self, expr: Expr):
        self.expr = expr
        self.params: dict[str, ParamInfo] = {}
        self._collect(expr, ())
        self.labels: tuple[str, ...] = tuple(self.params.keys())
        self._sample_flat_jit = None  # compiled lazily; dropped on pickle

    def signature(self):
        """Canonical hashable key of the param table.  Two CompiledSpace
        instances over the same user space share it, so suggesters key their
        module-level jit caches on this — repeated ``fmin`` calls (each of
        which builds a fresh Domain) reuse compiled kernels instead of
        retracing."""
        sig = getattr(self, "_signature", None)
        if sig is None:
            sig = self._signature = tuple(
                (i.label, i.dist.family, i.dist.params, i.cast, i.conditions)
                for i in self.params.values()
            )
        return sig

    # pickle support: jitted handles are process-local, rebuild lazily.  This
    # is what makes Domain (and thus fmin's trials_save_file checkpoint, which
    # stores the live Domain in trials.attachments) picklable.
    def __getstate__(self):
        state = self.__dict__.copy()
        state["_sample_flat_jit"] = None
        return state

    # -- construction -----------------------------------------------------

    def _add_param(self, label: str, dist: Dist, cast: str, conditions: tuple):
        if not isinstance(label, str):
            raise InvalidAnnotatedParameter(f"label must be a string: {label!r}")
        if label in self.params:
            raise DuplicateLabel(label)
        info = ParamInfo(label, dist, cast, conditions)
        if info.is_int:
            _check_f32_exact_int(info)
        self.params[label] = info

    def _collect(self, node: Expr, conditions: tuple):
        if isinstance(node, Param):
            self._add_param(node.label, node.dist, node.cast, conditions)
        elif isinstance(node, Choice):
            self._add_param(node.label, node.selector_dist, "int", conditions)
            for i, opt in enumerate(node.options):
                self._collect(opt, conditions + ((node.label, i),))
        elif isinstance(node, Op):
            for a in node.args:
                self._collect(a, conditions)
        elif isinstance(node, Container):
            for c in node.children:
                self._collect(c, conditions)
        elif isinstance(node, Literal):
            pass
        else:
            raise InvalidAnnotatedParameter(f"not a space expression: {node!r}")

    # -- sampling ---------------------------------------------------------

    def _sample_groups(self):
        """Labels grouped for batched prior draws: same family (and, for
        categorical, same bucket count) share one vmapped kernel so the
        traced sampler — and its XLA compile time — stops growing with the
        label count.  Cached; order within a group follows ``self.labels``."""
        groups = getattr(self, "_sample_groups_cache", None)
        if groups is None:
            groups = {}
            for label, info in self.params.items():
                fam = info.dist.family
                gkey = (fam, len(info.dist.params)) if fam == "categorical" \
                    else fam
                groups.setdefault(gkey, []).append(label)
            self._sample_groups_cache = groups
        return groups

    def sample_flat(self, key) -> dict:
        """Draw every parameter unconditionally; pure & jittable.

        Same-family labels draw through ONE batched kernel
        (:func:`draw_dist_group`) — bitwise identical per label to unrolled
        :func:`draw_dist` calls (same ``fold_in`` keys, same formulas;
        asserted by tests/test_spaces.py), but the program no longer grows
        with the label count."""
        out = {}
        for _, labels in self._sample_groups().items():
            if len(labels) == 1:
                label = labels[0]
                k = jax.random.fold_in(key, label_hash(label))
                out[label] = draw_dist(self.params[label].dist, k)
                continue
            hashes = jnp.asarray([label_hash(l) for l in labels], jnp.uint32)
            keys = jax.vmap(lambda h: jax.random.fold_in(key, h))(hashes)
            vals = draw_dist_group(
                [self.params[l].dist for l in labels], keys)
            for i, label in enumerate(labels):
                out[label] = vals[i]
        return {label: out[label] for label in self.labels}

    def sample_flat_jit(self, key) -> dict:
        if self._sample_flat_jit is None:
            self._sample_flat_jit = jax.jit(self.sample_flat)
        return self._sample_flat_jit(key)

    def active_flat(self, flat: dict) -> dict:
        """Boolean activation per label, from the drawn choice indices.

        Works on host ints and on tracers (returns jnp bools under trace).
        """
        out = {}
        for label, info in self.params.items():
            act = True
            for (clabel, idx) in info.conditions:
                act = act & (flat[clabel] == idx)
            out[label] = (
                jnp.asarray(act)
                if any(isinstance(flat.get(c), jax.Array) for c, _ in info.conditions)
                else bool(act) if isinstance(act, (bool, np.bool_)) else act
            )
        return out

    # -- assembly ---------------------------------------------------------

    def assemble(self, flat: dict, *, traced: bool = False):
        """Rebuild the user-facing structure from flat per-label values.

        Host mode picks choice branches with concrete ints (the analog of
        rec_eval's lazy ``switch``); traced mode evaluates every branch
        (XLA cannot data-dependent-skip) and SELECTS per leaf, union-merging
        dict branches with different keys: a key absent from the selected
        branch reads as a zero of the right dtype.  This makes the common
        "different hyperparameters per architecture" ``hp.choice`` pattern
        work under jit/vmap (``make_batch_eval``, ``fmin_device``) — the
        objective sees the union structure and gates on the selector value.
        Branch lists of DIFFERENT lengths cannot be merged (shapes must be
        static) and raise; equal non-numeric leaves (e.g. a shared
        ``"kind"`` string) pass through; unequal non-numeric leaves are
        OMITTED from the merged dict (a traced index cannot select a
        string, and the objective could not compute with one anyway) — a
        choice whose entire value would be omitted raises with guidance.
        """
        table = _OP_TABLE_JNP if traced else _OP_TABLE_NP
        _MISSING = object()

        def union_select(idx, per_branch):
            """Select among per-branch values (``_MISSING`` where a branch
            lacks the slot) by traced index ``idx``."""
            present = [v for v in per_branch if v is not _MISSING]
            if all(isinstance(v, dict) for v in present):
                keys = sorted(set().union(*(v.keys() for v in present)))
                out = {}
                for k in keys:
                    sub = union_select(idx, [
                        v[k] if (v is not _MISSING and k in v) else _MISSING
                        for v in per_branch
                    ])
                    if sub is not _MISSING:
                        out[k] = sub
                return out
            if all(isinstance(v, (list, tuple)) for v in present):
                lens = {len(v) for v in present}
                if len(lens) != 1:
                    raise InvalidAnnotatedParameter(
                        "traced hp.choice branches contain sequences of "
                        f"different lengths {sorted(lens)}; static shapes "
                        "cannot be selected under jit — pad the branches or "
                        "evaluate this space on host"
                    )
                n = len(present[0])
                kind = type(present[0])
                items = [
                    union_select(idx, [
                        v[i] if v is not _MISSING else _MISSING
                        for v in per_branch
                    ])
                    for i in range(n)
                ]
                return kind(items) if kind in (list, tuple) else items
            numeric = all(
                isinstance(v, (int, float, np.number, np.ndarray, jax.Array))
                for v in present
            )
            if not numeric:
                if any(isinstance(v, (dict, list, tuple)) for v in present):
                    # mixed structure (dict in one branch, scalar in another)
                    # is a space bug — omitting it would surface as a
                    # confusing KeyError far from the cause
                    raise InvalidAnnotatedParameter(
                        "traced hp.choice branches mix containers and "
                        f"leaves at the same slot ({present!r}); give every "
                        "branch the same shape at this position"
                    )
                if len({repr(v) for v in present}) == 1:
                    return present[0]  # e.g. a shared "kind" string
                # branch-identifying strings etc. cannot be selected by a
                # traced index (and could not participate in traced compute
                # anyway) — omit the slot; gate on the selector value instead
                return _MISSING
            dtype = jnp.result_type(*present)
            stacked = jnp.stack([
                jnp.zeros((), dtype) if v is _MISSING
                else jnp.asarray(v, dtype)
                for v in per_branch
            ])
            return stacked[idx]

        def rec(node: Expr):
            if isinstance(node, Literal):
                return node.value
            if isinstance(node, Param):
                v = flat[node.label]
                if traced:
                    return v
                v = np.asarray(v).item() if hasattr(v, "item") or isinstance(v, np.ndarray) else v
                if node.cast == "int":
                    v = int(round(v))
                return v
            if isinstance(node, Choice):
                idx = flat[node.label]
                if traced and isinstance(idx, jax.Array):
                    outs = [rec(o) for o in node.options]
                    merged = union_select(jnp.asarray(idx, jnp.int32), outs)
                    if merged is _MISSING:
                        # e.g. hp.choice over bare strings, or branches whose
                        # structures cannot be reconciled — never leak the
                        # sentinel into the objective
                        raise InvalidAnnotatedParameter(
                            f"hp.choice({node.label!r}) branches cannot be "
                            "merged under jit (non-numeric or structurally "
                            "incompatible options); encode the options as "
                            "indices/numbers for traced evaluation, or "
                            "evaluate this space on host"
                        )
                    return merged
                idx = int(np.asarray(idx).item()) if not isinstance(idx, int) else idx
                return rec(node.options[idx])
            if isinstance(node, Op):
                return table[node.op](*(rec(a) for a in node.args))
            if isinstance(node, Container):
                vals = [rec(c) for c in node.children]
                if node.kind == "dict":
                    return dict(zip(node.keys, vals))
                return vals if node.kind == "list" else tuple(vals)
            raise InvalidAnnotatedParameter(f"not a space expression: {node!r}")

        return rec(self.expr)

    def sample(self, key):
        """One structured sample on host (pyll/stochastic.py sym: sample)."""
        flat = {k: np.asarray(v) for k, v in self.sample_flat_jit(key).items()}
        return self.assemble(flat)


_F32_EXACT = 2 ** 24  # largest window of exactly representable f32 integers


def _check_f32_exact_int(info: ParamInfo):
    """Integer-family values ride a packed float32 readback
    (``rand.pack_labels``: one [B, L] buffer = one host↔device transfer per
    suggest); integers with |value| >= 2**24 would silently round.  Reject
    such spaces at compile time rather than corrupt values at runtime.
    Unbounded int-cast families (qnormal/qlognormal) can't be checked
    statically and keep the documented f32 caveat."""
    fam, p = info.dist.family, info.dist.params
    if fam in ("randint", "uniformint", "quniform"):
        bound = max(abs(float(p[0])), abs(float(p[1])))
    elif fam == "qloguniform":
        bound = math.exp(float(p[1]))
    else:
        return
    if bound >= _F32_EXACT:
        raise InvalidAnnotatedParameter(
            f"{info.label!r}: integer range |{bound:.3g}| >= 2**24 cannot survive "
            f"the float32 proposal readback exactly; shift/scale the space "
            f"(e.g. sample an offset) to keep integer magnitudes below 2**24"
        )


def compile_space(space: Any) -> CompiledSpace:
    return CompiledSpace(as_expr(space))


# ---------------------------------------------------------------------------
# Distribution draws (jax) — semantics of hyperopt/pyll/stochastic.py
# ---------------------------------------------------------------------------


def _qround(x, q):
    return jnp.round(x / q) * q


def draw_dist(dist: Dist, key, shape=()):
    """Draw from one distribution node; pure function of (dist, key).

    Families/formulas follow ``hyperopt/pyll/stochastic.py`` (sym: uniform,
    quniform, loguniform, qloguniform, normal, qnormal, lognormal, qlognormal,
    randint, categorical).
    """
    fam, p = dist.family, dist.params
    if fam == "uniform":
        low, high = p
        return jax.random.uniform(key, shape, minval=low, maxval=high)
    if fam == "quniform":
        low, high, q = p
        return _qround(jax.random.uniform(key, shape, minval=low, maxval=high), q)
    if fam == "loguniform":
        low, high = p
        return jnp.exp(jax.random.uniform(key, shape, minval=low, maxval=high))
    if fam == "qloguniform":
        low, high, q = p
        return _qround(jnp.exp(jax.random.uniform(key, shape, minval=low, maxval=high)), q)
    if fam == "normal":
        mu, sigma = p
        return mu + sigma * jax.random.normal(key, shape)
    if fam == "qnormal":
        mu, sigma, q = p
        return _qround(mu + sigma * jax.random.normal(key, shape), q)
    if fam == "lognormal":
        mu, sigma = p
        return jnp.exp(mu + sigma * jax.random.normal(key, shape))
    if fam == "qlognormal":
        mu, sigma, q = p
        return _qround(jnp.exp(mu + sigma * jax.random.normal(key, shape)), q)
    if fam == "randint":
        low, high = p
        return jax.random.randint(key, shape, int(low), int(high))
    if fam == "uniformint":
        low, high = p
        return jax.random.randint(key, shape, int(low), int(high) + 1)
    if fam == "categorical":
        probs = jnp.asarray(p)
        return jax.random.categorical(key, jnp.log(probs), shape=shape)
    raise InvalidAnnotatedParameter(f"unknown family {fam!r}")


def draw_dist_group(dists, keys):
    """Vectorized :func:`draw_dist` for ≥2 SAME-family nodes: one batched
    threefry per group instead of one per label, so sampler compile time is
    O(families), not O(labels).  ``keys``: ``[G, key]`` (one per node).

    Per-node results are bitwise identical to the unrolled scalar draws —
    the vmapped primitives consume each key exactly as the scalar calls do,
    and the per-node params broadcast through the same formulas
    (tests/test_spaces.py::test_grouped_sampler_bitwise_matches_unrolled).
    """
    fam = dists[0].family
    if fam in ("uniform", "quniform", "loguniform", "qloguniform"):
        low = jnp.asarray([d.params[0] for d in dists])
        high = jnp.asarray([d.params[1] for d in dists])
        x = jax.vmap(
            lambda k, lo, hi: jax.random.uniform(k, (), minval=lo, maxval=hi)
        )(keys, low, high)
        if fam in ("loguniform", "qloguniform"):
            x = jnp.exp(x)
        if fam in ("quniform", "qloguniform"):
            x = _qround(x, jnp.asarray([d.params[2] for d in dists]))
        return x
    if fam in ("normal", "qnormal", "lognormal", "qlognormal"):
        mu = jnp.asarray([d.params[0] for d in dists])
        sigma = jnp.asarray([d.params[1] for d in dists])
        x = mu + sigma * jax.vmap(lambda k: jax.random.normal(k, ()))(keys)
        if fam in ("lognormal", "qlognormal"):
            x = jnp.exp(x)
        if fam in ("qnormal", "qlognormal"):
            x = _qround(x, jnp.asarray([d.params[2] for d in dists]))
        return x
    if fam in ("randint", "uniformint"):
        off = 1 if fam == "uniformint" else 0
        lo = jnp.asarray([int(d.params[0]) for d in dists])
        hi = jnp.asarray([int(d.params[1]) + off for d in dists])
        return jax.vmap(
            lambda k, a, b: jax.random.randint(k, (), a, b)
        )(keys, lo, hi)
    if fam == "categorical":
        logp = jnp.log(jnp.asarray([list(d.params) for d in dists]))
        return jax.vmap(
            lambda k, lp: jax.random.categorical(k, lp, shape=())
        )(keys, logp)
    raise InvalidAnnotatedParameter(f"unknown family {fam!r}")


# ---------------------------------------------------------------------------
# Public helpers (API parity)
# ---------------------------------------------------------------------------


def rng_to_key(rng):
    """Coerce any of a jax key / int seed / numpy ``Generator`` /
    ``RandomState`` / None (fresh entropy) to a jax PRNG key — the single
    coercion point shared by ``sample`` and the ``pyll.stochastic`` shim."""
    if rng is None:
        return jax.random.PRNGKey(np.random.SeedSequence().entropy % (2**32))
    if isinstance(rng, jax.Array):
        return rng
    if isinstance(rng, (int, np.integer)):
        return jax.random.PRNGKey(int(rng) & 0xFFFFFFFF)
    if isinstance(rng, np.random.Generator):
        return jax.random.PRNGKey(int(rng.integers(2**32, dtype=np.uint64)))
    if isinstance(rng, np.random.RandomState):
        return jax.random.PRNGKey(int(rng.randint(0, 2**31 - 1)))
    raise TypeError(f"cannot derive a PRNG key from rng={rng!r}")


def sample(space: Any, key=None):
    """Sample a structured point (``hyperopt.pyll.stochastic.sample``).
    ``key`` may be a jax key, int seed, numpy Generator/RandomState, or None."""
    return compile_space(space).sample(rng_to_key(key))


def space_eval(space: Any, hp_assignment: dict):
    """Rebuild the structured point from ``{label: value}`` (choice values are
    branch indices) — parity with ``hyperopt/fmin.py`` (sym: space_eval).

    Accepts both scalars and the 1-element lists found in ``trials.vals``.
    """
    flat = {}
    for k, v in hp_assignment.items():
        if isinstance(v, (list, tuple, np.ndarray)):
            if len(v) == 0:
                continue
            v = v[0]
        flat[k] = v
    return compile_space(space).assemble(flat)


def expr_to_config(space: Any) -> dict:
    """Summarize a space as ``{label: {'dist': Dist, 'conditions': (...)}}`` —
    the analog of ``hyperopt/pyll_utils.py`` (sym: expr_to_config), used by
    conditional-space-aware tooling.
    """
    cs = compile_space(space)
    return {
        label: {"dist": info.dist, "cast": info.cast, "conditions": info.conditions}
        for label, info in cs.params.items()
    }
