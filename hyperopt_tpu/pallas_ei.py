"""Pallas TPU kernel for the fused two-model mixture EI score.

The TPE hot op evaluates the SAME candidate vector under two Gaussian
mixtures (below/above Parzen models) and takes the log-density difference
(hyperopt/tpe.py sym: GMM1_lpdf × 2 + broadcast_best).  The jnp path builds
two ``[components, candidates]`` matrices and logsumexps them; this kernel
streams over components with a running (max, scaled-sum) carry, keeping the
candidate block and both accumulators in VMEM/registers — one pass, no
materialized matrix, both models in the same loop.

Scope: the un-quantized, value-space case (``q=None``, not log-space) —
``hp.uniform`` / ``hp.normal`` posteriors, the dominant family.  The
truncation normalizers (``log p_accept``) are scalars applied by the caller.
Numerics match the jnp path up to fp reassociation (streaming vs two-pass
logsumexp); tests assert 1e-4 agreement.

Fallback: any non-TPU backend (or pallas lowering failure) uses the jnp
path — same math, so behavior is identical everywhere.

MEASURED VERDICT (v5e, 2026-07-30): correct to 1e-5 vs the jnp path and
~7% faster in isolation (43.1 vs 46.1 ms per 64×8192 EI pair, tunnel
dispatch overhead included in both).  XLA already fuses the jnp
formulation into a near-optimal kernel, so this module is NOT wired into
the default TPE path — it exists as the validated pallas expression of the
hot op for future shapes where the fusion breaks down (very large
component counts where the [m, n] intermediate stops fitting VMEM).  The
default path keeps the compiler-scheduled version per the "don't
hand-schedule what XLA already fuses" doctrine.
"""

from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp

__all__ = ["ei_diff", "ei_diff_reference", "pallas_available"]

# log(sqrt(2*pi))
_LOG_SQRT_2PI = 0.9189385332046727
# stand-in for -inf that survives max/exp arithmetic without NaNs
_VERY_NEG = -1e30

_LANES = 128
_SUBLANES = 8
_BLOCK = _LANES * _SUBLANES  # candidates per grid step


def ei_diff_reference(x, wb, mb, sb, wa, ma, sa):
    """jnp twin of the kernel: logsumexp_b(x) - logsumexp_a(x) over the two
    (weights, mus, sigmas) mixtures, no truncation terms."""
    from jax.scipy.special import logsumexp

    def model(w, mu, s):
        logw = jnp.where(w > 0, jnp.log(jnp.maximum(w, 1e-12)), -jnp.inf)
        comp = (logw[:, None]
                - 0.5 * ((x[None, :] - mu[:, None]) / s[:, None]) ** 2
                - jnp.log(s)[:, None] - _LOG_SQRT_2PI)
        return logsumexp(comp, axis=0)

    return model(wb, mb, sb) - model(wa, ma, sa)


def _make_kernel(m):
    """Kernel body for ``m`` live components; component tables arrive padded
    to a lane-aligned ``(1, P)`` layout (Mosaic requires the minor dim to be
    a provable multiple of 128)."""

    def kernel(x_ref, wb_ref, mb_ref, sb_ref, wa_ref, ma_ref, sa_ref, out_ref):
        x = x_ref[:]

        def mixture_lse(w_ref, mu_ref, s_ref):
            def body(i, carry):
                mx, se = carry
                # component tables live in SMEM: dynamic scalar reads are
                # exactly what scalar memory supports (a dynamic lane index
                # into VMEM is not lowerable)
                w = w_ref[i]
                mu = mu_ref[i]
                s = s_ref[i]
                logw = jnp.where(w > 0.0, jnp.log(jnp.maximum(w, 1e-12)),
                                 jnp.float32(_VERY_NEG))
                comp = (logw - 0.5 * ((x - mu) / s) ** 2
                        - jnp.log(s) - jnp.float32(_LOG_SQRT_2PI))
                new_mx = jnp.maximum(mx, comp)
                se = se * jnp.exp(mx - new_mx) + jnp.exp(comp - new_mx)
                return new_mx, se

            init = (jnp.full(x.shape, _VERY_NEG, jnp.float32),
                    jnp.zeros(x.shape, jnp.float32))
            mx, se = jax.lax.fori_loop(0, m, body, init)
            return mx + jnp.log(se)

        llb = mixture_lse(wb_ref, mb_ref, sb_ref)
        lla = mixture_lse(wa_ref, ma_ref, sa_ref)
        out_ref[:] = llb - lla

    return kernel


@functools.lru_cache(maxsize=None)
def _build(n, m):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    rows = n // _LANES
    grid = rows // _SUBLANES

    def call(x2d, wb, mb, sb, wa, ma, sa):
        comp_spec = pl.BlockSpec(memory_space=pltpu.SMEM)
        return pl.pallas_call(
            _make_kernel(m),
            out_shape=jax.ShapeDtypeStruct((rows, _LANES), jnp.float32),
            grid=(grid,),
            in_specs=[
                pl.BlockSpec((_SUBLANES, _LANES), lambda i: (i, 0)),
                comp_spec, comp_spec, comp_spec,
                comp_spec, comp_spec, comp_spec,
            ],
            out_specs=pl.BlockSpec((_SUBLANES, _LANES), lambda i: (i, 0)),
        )(x2d, wb, mb, sb, wa, ma, sa)

    return call


def pallas_available():
    """True when the default backend lowers Mosaic (i.e. a real TPU)."""
    try:
        return jax.default_backend() == "tpu"
    except Exception:
        return False


def ei_diff(x, wb, mb, sb, wa, ma, sa):
    """EI score ``lpdf_below(x) - lpdf_above(x)`` (no truncation terms).

    Uses the pallas kernel when the candidate count tiles the TPU grid
    (multiple of 1024) on a TPU backend; jnp twin otherwise.
    """
    if wb.shape[0] != wa.shape[0]:
        # the kernel bakes ONE component count into both fori_loops (TPE's
        # below/above models share the padded cap, so this never triggers
        # from tpe.py) — mismatched mixtures must take the shape-generic path
        return ei_diff_reference(x, wb, mb, sb, wa, ma, sa)
    n = x.shape[0]
    if n % _BLOCK == 0 and pallas_available():
        x2d = x.reshape(n // _LANES, _LANES)
        out = _build(n, int(wb.shape[0]))(
            x2d, wb, mb, sb, wa, ma, sa)
        return out.reshape(n)
    return ei_diff_reference(x, wb, mb, sb, wa, ma, sa)
