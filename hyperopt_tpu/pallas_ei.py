"""Deprecated shim — the EI-pair kernel moved to ``megakernel.py``.

This module's fused two-model mixture EI kernel (and its jnp reference
twin) now live in :mod:`hyperopt_tpu.megakernel`, which extends the
fusion to the whole sample+score middle of the ask tick (ISSUE 19).
The measured verdict that governed this module's scope — XLA already
fuses the jnp lpdf formulation near-optimally at small component
counts, so hand-scheduling only pays where the ``[m, n]`` intermediates
stop fitting VMEM — is recorded in docs/DESIGN.md §25 ("when
hand-scheduling pays").

Importing from here keeps working (the re-exports below are the same
objects), as does the ``HYPEROPT_TPU_PALLAS=1`` arming alias — with a
deprecation warn-once pointing at ``HYPEROPT_TPU_MEGAKERNEL``
(``_env.parse_pallas``).  New code should import
``hyperopt_tpu.megakernel`` directly.
"""

from __future__ import annotations

from .megakernel import ei_diff, ei_diff_reference, pallas_available

__all__ = ["ei_diff", "ei_diff_reference", "pallas_available"]
