"""Fully on-device optimization loop for JAX-traceable objectives.

The reference's ``fmin`` (hyperopt/fmin.py sym: FMinIter.run) is a host loop:
every trial pays a suggest→evaluate→record round-trip through Python.  When
the objective itself is jnp math, the entire ask→tell loop — TPE posterior
fit, candidate sampling, EI argmax, objective evaluation, history update —
can run as ONE ``lax.scan`` program on the accelerator, with zero host
round-trips.  This module has no reference analog; it is the design point
BASELINE.md's sub-second-Branin target asks for (SURVEY.md §7.1 row "one
suggestion per call").

The loop state is the same padded SoA history the host ``Trials`` keeps
(vals/active per label, losses, has_loss), at a fixed capacity of
``max_evals``, so every step is shape-stable and the whole run compiles
once.  Startup trials draw from the prior (rand analog); later steps run the
jitted TPE proposal under ``lax.cond``.
"""

from __future__ import annotations

import contextlib
import time

import numpy as np

import jax
import jax.numpy as jnp

from .algos import tpe
from .base import trials_from_flat_history
from .obs import get_metrics
from .obs.health import record_program_cost
from .obs.watchdog import beat as _wd_beat
from .utils import LRUCache
from .spaces import compile_space, draw_dist, label_hash

__all__ = ["fmin_device", "DeviceLoopRunner", "objective_is_traceable"]

# compiled-run cache: (space expr, objective, capacity, cfg) -> a holder
# {"jit": jitted fn, "compiled": AOT executable or None}.  Expr trees are
# frozen dataclasses (hashable); objectives hash by identity.  The holder
# (not the bare jitted fn) is cached so the one-time AOT compile — the
# measured "compile" half of the obs split — is shared across runner
# instances exactly like the program itself.
_RUN_CACHE = LRUCache(16)

# shared null context for un-annotated dispatches (no per-chunk allocation)
_nullcontext = contextlib.nullcontext()

# compile/execute split + cache hit rates live in the process-global
# "device" metrics namespace: the cache itself is process-global, so its
# rates are a property of the process, not of any one run
_METRICS = get_metrics("device")


def _record_cache_stats():
    s = _RUN_CACHE.stats()
    _METRICS.gauge("run_cache.hits").set(s["hits"])
    _METRICS.gauge("run_cache.misses").set(s["misses"])
    _METRICS.gauge("run_cache.size").set(s["size"])


def _aot_compile(holder, args, hist_name, obs=None):
    """Fill ``holder["compiled"]`` with the AOT executable for ``args``,
    recording compile wall time under ``hist_name`` and the program's
    static FLOP/byte cost under ``<stage>.flops`` / ``<stage>.bytes``
    (obs/health.py joins those with the execute spans into achieved-FLOP/s
    and busy fraction — reading ``cost_analysis()`` is free XLA metadata,
    no device sync).  Falls back to the jitted callable (compile time then
    folds into the first execute) on backends where AOT lowering is
    unavailable."""
    span = (obs.span("device.compile", aggregate=False)
            if obs is not None else None)
    # compile boundary beat: a stall here is XLA (or the tunnel), not the
    # search loop — the watchdog report will show this as the last mark
    _wd_beat("device.compile", stage=hist_name.split(".")[0], mark="pre")
    t0 = time.perf_counter()
    try:
        if span is not None:
            with span:
                compiled = holder["jit"].lower(*args).compile()
        else:
            compiled = holder["jit"].lower(*args).compile()
    except Exception:  # pragma: no cover - backend-dependent AOT support
        _METRICS.counter("aot_fallbacks").inc()
        compiled = holder["jit"]
    else:
        record_program_cost(hist_name.split(".")[0], compiled, _METRICS)
    _METRICS.histogram(hist_name).observe(time.perf_counter() - t0)
    _wd_beat("device.compile", stage=hist_name.split(".")[0], mark="post")
    holder["compiled"] = compiled
    return compiled


def _int_labels(cs):
    """Labels whose evaluation dtype is i32 — the same rule as
    ``ParamInfo.is_int`` (INT_FAMILIES incl. ``uniformint``, plus int-cast
    q-families), so the traced objective sees exactly the dtypes the host
    loop's trial docs deliver."""
    return {l for l, info in cs.params.items() if info.is_int}


def _flat_samplers(cs, cfg, with_tpe=True):
    """``(rand_flat, tpe_flat, typed)`` shared by the whole-run scan and the
    chunked runner — one copy of the sampling/typing semantics.

    ``with_tpe=False`` (a pure random run: startup covers the whole
    capacity) makes ``tpe_flat`` an alias of the prior sampler instead of
    tracing the TPE posterior — XLA compiles BOTH ``lax.cond`` branches, so
    a never-taken TPE branch would still pay its full compile time."""
    ints = _int_labels(cs)

    def rand_flat(key):
        return {
            l: draw_dist(info.dist,
                         jax.random.fold_in(key, label_hash(l))
                         ).astype(jnp.float32)
            for l, info in cs.params.items()
        }

    if with_tpe:
        propose = tpe.build_propose(cs, cfg)

        def tpe_flat(history, key):
            return {l: v.astype(jnp.float32)
                    for l, v in propose(history, key).items()}
    else:
        def tpe_flat(history, key):
            return rand_flat(key)

    def typed(flat):
        """Per-label values with evaluation dtypes (discrete → i32)."""
        return {
            l: jnp.round(v).astype(jnp.int32) if l in ints else v
            for l, v in flat.items()
        }

    return rand_flat, tpe_flat, typed


def _build_step(cs, fn, cap, cfg, n_startup):
    """One ask→tell step: carry = (vals, active, losses, has_loss, key)."""
    rand_flat, tpe_flat, typed = _flat_samplers(cs, cfg,
                                                with_tpe=n_startup < cap)

    def step(carry, i):
        vals, active, losses, has_loss, key = carry
        key, k_prop = jax.random.split(key)
        history = {"losses": losses, "has_loss": has_loss,
                   "vals": vals, "active": active}
        flat = jax.lax.cond(
            i < n_startup,
            lambda k: rand_flat(k),
            lambda k: tpe_flat(history, k),
            k_prop,
        )
        tflat = typed(flat)
        act = cs.active_flat(tflat)
        loss = jnp.asarray(fn(cs.assemble(tflat, traced=True)), jnp.float32)
        ok = jnp.isfinite(loss)  # NaN/Inf objective -> trial recorded, no loss
        vals = {l: vals[l].at[i].set(flat[l]) for l in cs.labels}
        active = {l: active[l].at[i].set(jnp.asarray(act[l], bool)) for l in cs.labels}
        losses = losses.at[i].set(jnp.where(ok, loss, jnp.inf))
        has_loss = has_loss.at[i].set(ok)
        return (vals, active, losses, has_loss, key), loss

    return step


def objective_is_traceable(domain):
    """True when the domain's raw objective abstractly traces to a scalar
    float over the compiled space's typed flat sample — the eligibility
    probe for the device-stepped interactive loop (``fmin(...,
    device_loop=...)``).  Host-math objectives (``math.cos``, ``float()``,
    data-dependent branches) fail the trace and stay on the host path."""
    if domain.pass_expr_memo_ctrl:
        return False
    cs = domain.cs
    ints = _int_labels(cs)
    flat = {
        l: jax.ShapeDtypeStruct((), jnp.int32 if l in ints else jnp.float32)
        for l in cs.labels
    }
    try:
        out = jax.eval_shape(
            lambda f: domain.fn(cs.assemble(f, traced=True)), flat)
    except Exception:
        return False
    return (getattr(out, "shape", None) == ()
            and jnp.issubdtype(out.dtype, jnp.floating))


class DeviceLoopRunner:
    """Chunked device stepper: K sequential fresh-posterior ask→tell steps
    per dispatch, for the standard interactive ``fmin`` loop.

    Queue-1 reference semantics are the worst case for a high-latency link:
    every proposal must see the previous trial's loss, so a host loop pays
    one round trip PER TRIAL — on the tunneled chip (112 ms RTT floor,
    BASELINE.md) that is ~11 s per 100 evals with a ~5 ms device program.
    When the objective is traceable the dependency chain can live on the
    accelerator instead: one ``lax.scan`` program runs ``CHUNK`` sequential
    steps — fold result, fit posterior, propose, evaluate — and the host
    reads back a single packed ``[CHUNK, 2L+1]`` buffer to build the same
    reference-shaped trial docs.  Fresh-posterior-per-trial is preserved
    exactly; the round-trip cost drops to one per CHUNK trials.

    Unlike ``fmin_device`` (whole run = one program), the chunk boundary
    returns control to the host every ``CHUNK`` trials, so ``fmin``'s
    timeout / early_stop_fn / loss_threshold / checkpointing keep working
    at chunk granularity.
    """

    CHUNK = 10

    def __init__(self, domain, cfg, n_startup, cap, obs=None):
        from ._env import parse_hist_dtype, parse_shard

        cs = domain.cs
        self.cs = cs
        self.cap = int(cap)
        self.labels = cs.labels
        self._obs = obs
        L = len(cs.labels)
        # loop-state storage dtype (HYPEROPT_TPU_HIST_DTYPE): the cap-sized
        # carry holds vals/losses compressed; kernels upcast on read.
        # int8/fp8 degrade to bf16 — the resident loop state compresses by
        # plain astype (no affine-code boundary in the chunk program)
        from . import quant

        self.hist_dtype = str(quant.mirror_float_dtype(parse_hist_dtype()))
        # HYPEROPT_TPU_SHARD + a cap past the per-chip threshold: the chunk
        # program compiles with explicit NamedShardings from the
        # partition-rule table, the history axis sharded over the mesh
        self._mesh = None
        if parse_shard() is not None:
            from .parallel import sharding as _sh

            mesh = _sh.suggest_mesh(parse_shard())
            if _sh.should_shard_history(self.cap, mesh):
                self._mesh = mesh
        geom = (None if self._mesh is None
                else tuple(d.id for d in self._mesh.devices.flat))
        # the cap-sized loop state's layout, derived ONCE from the
        # partition-rule table — the compile below and init_state's
        # initial placement both read this, so they cannot diverge
        self._state_sh = None
        if self._mesh is not None:
            from jax.sharding import NamedSharding
            from .parallel import sharding as _sh

            rules = _sh.suggest_partition_rules(shard_history=True)
            hist_specs = _sh.match_partition_rules(
                rules, {"hist": _sh._hist_skeleton(cs.labels)})["hist"]
            ns = lambda s: NamedSharding(self._mesh, s)  # noqa: E731
            self._state_sh = (jax.tree.map(ns, hist_specs["vals"]),
                              jax.tree.map(ns, hist_specs["active"]),
                              ns(hist_specs["losses"]),
                              ns(hist_specs["has_loss"]))
        # the jitted chunk program is cached across runner instances (the
        # shared LRU with fmin_device): a warm re-run of the same
        # (space, objective, cap, cfg) must not recompile
        donate = tpe._donation_enabled()
        # tpe._pallas_armed() changes the traced proposal: fold it in so an
        # env toggle mid-process cannot serve a stale program from the LRU
        cache_key = ("chunk", cs.expr, domain.fn, self.cap, int(n_startup),
                     tuple(sorted(cfg.items())), self.CHUNK, donate,
                     self.hist_dtype, geom, tpe._pallas_armed())
        cached = _RUN_CACHE.get(cache_key)
        _record_cache_stats()
        if cached is not None:
            self._holder = cached
            self._L = L
            return
        fn = domain.fn
        cap_i = self.cap
        chunk = self.CHUNK
        n_startup = int(n_startup)
        rand_flat, tpe_flat, typed = _flat_samplers(
            cs, cfg, with_tpe=n_startup < cap_i)

        # the cap-sized history tuple is DONATED: each chunk's scatters
        # alias the previous state's buffers in place, so a 10-trial chunk
        # never materializes a fresh cap-sized copy of the history.  The
        # caller-side contract (thread the RETURNED state forward, never
        # reuse the argument) is what FMinIter._run_device already does.
        def run_chunk(state, start, limit, seed_words):
            vals, active, losses, has_loss = state
            base = jax.random.fold_in(
                jax.random.PRNGKey(seed_words[0]), seed_words[1])

            def step(carry, off):
                vals, active, losses, has_loss = carry
                i = start + off
                key = jax.random.fold_in(base, i.astype(jnp.uint32))
                history = {"losses": losses, "has_loss": has_loss,
                           "vals": vals, "active": active}
                flat = jax.lax.cond(
                    i < n_startup,
                    lambda k: rand_flat(k),
                    lambda k: tpe_flat(history, k),
                    key,
                )
                tflat = typed(flat)
                act = cs.active_flat(tflat)
                loss = jnp.asarray(fn(cs.assemble(tflat, traced=True)),
                                   jnp.float32)
                ok = jnp.isfinite(loss)
                # steps past `limit` still trace (static chunk) but fold
                # nowhere: index cap is dropped by mode='drop'
                idx = jnp.where(i < limit, i, cap_i)
                vals = {l: vals[l].at[idx].set(
                            flat[l].astype(vals[l].dtype), mode="drop")
                        for l in cs.labels}
                active = {
                    l: active[l].at[idx].set(jnp.asarray(act[l], bool),
                                             mode="drop")
                    for l in cs.labels
                }
                losses = losses.at[idx].set(
                    jnp.where(ok, loss, jnp.inf).astype(losses.dtype),
                    mode="drop")
                has_loss = has_loss.at[idx].set(ok, mode="drop")
                row = jnp.concatenate([
                    jnp.stack([flat[l] for l in cs.labels]),
                    jnp.stack([jnp.asarray(act[l], jnp.float32)
                               for l in cs.labels]),
                    loss[None],
                ])  # [2L + 1]
                return (vals, active, losses, has_loss), row

            state, rows = jax.lax.scan(
                step, (vals, active, losses, has_loss),
                jnp.arange(chunk, dtype=jnp.int32))
            return state, rows

        donate_kw = {"donate_argnums": (0,)} if donate else {}
        if self._mesh is None:
            run_chunk = jax.jit(run_chunk, **donate_kw)
        else:
            # explicit NamedShardings from the partition-rule table
            # (self._state_sh, computed once in __init__): the cap-sized
            # loop state shards its capacity axis over the mesh (per-chip
            # HBM holds cap / n_shards rows); scalars and the
            # [CHUNK, 2L+1] readback replicate.  donate_argnums preserved:
            # the chunk's scatters stay in-place on per-shard buffers.
            from jax.sharding import NamedSharding

            rep = NamedSharding(self._mesh, jax.sharding.PartitionSpec())
            run_chunk = jax.jit(
                run_chunk,
                in_shardings=(self._state_sh, rep, rep, rep),
                out_shardings=(self._state_sh, rep), **donate_kw)

        self._holder = {"jit": run_chunk, "compiled": None}
        self._L = L
        _RUN_CACHE.put(cache_key, self._holder)

    def init_state(self):
        cap = self.cap
        # tag the cap-sized loop state for the devmem live-array census
        # (obs/devmem.py): an OOM dump then says how much HBM the history
        # itself held vs everything else.  A set-add per runner, host-side.
        from .obs.devmem import register_owner

        register_owner("history", (cap,))
        dt = jnp.dtype(self.hist_dtype)
        state = (
            {l: jnp.zeros(cap, dt) for l in self.labels},
            {l: jnp.zeros(cap, bool) for l in self.labels},
            jnp.full(cap, jnp.inf, dt),
            jnp.zeros(cap, bool),
        )
        if self._state_sh is not None:
            # place the initial state with the SAME table-derived specs
            # the chunk program compiled against, so the very first
            # chunk's donation aliases (no resharding copy)
            state = tuple(
                jax.tree.map(jax.device_put, part, sh_part)
                for part, sh_part in zip(state, self._state_sh))
        return state

    def run_chunk(self, state, start, limit, seed):
        """Run one chunk; returns ``(state', rows[limit-start, 2L+1])`` with
        rows already on host (the single readback).

        Obs: the first dispatch AOT-compiles the chunk program under a
        timed "device.compile" span; every dispatch records its execute
        wall clock (call through host readback — the full round trip) into
        the "device" metrics namespace, so a run's suggest time decomposes
        into XLA-compile vs device-execute instead of one opaque number."""
        seed = int(seed)
        words = np.asarray([seed & 0xFFFFFFFF, (seed >> 32) & 0xFFFFFFFF],
                           np.uint32)
        args = (state, np.int32(start), np.int32(limit), words)
        fn = self._holder["compiled"]
        if fn is None:
            fn = _aot_compile(self._holder, args, "chunk.compile_sec",
                              obs=self._obs)
        # execute boundary beats: a quiet period opening after "pre" and
        # never reaching "post" is a hung device program / dead readback
        _wd_beat("device.execute", stage="chunk", start=int(start),
                 mark="pre")
        # device-timeline annotation (obs/profiler.py): a profiler capture
        # overlapping this dispatch shows the chunk program attributed to
        # its trial range; disarmed runs get the shared null context
        ann = (self._obs.annotate("device.chunk", step=int(start),
                                  start=int(start), limit=int(limit))
               if self._obs is not None else _nullcontext)
        t0 = time.perf_counter()
        with ann:
            state, rows = fn(*args)
            rows = np.asarray(rows)[: limit - start]  # the blocking readback
        _METRICS.histogram("chunk.execute_sec").observe(
            time.perf_counter() - t0)
        _METRICS.counter("chunk.dispatches").inc()
        _wd_beat("device.execute", stage="chunk", start=int(start),
                 mark="post")
        return state, rows


def fmin_device(
    fn,
    space,
    max_evals,
    seed=0,
    n_startup_jobs=tpe._default_n_startup_jobs,
    n_EI_candidates=tpe._default_n_EI_candidates,
    gamma=tpe._default_gamma,
    linear_forgetting=tpe._default_linear_forgetting,
    prior_weight=tpe._default_prior_weight,
    return_trials=False,
):
    """Minimize a traceable ``fn`` over ``space`` entirely on device.

    ``fn`` receives the assembled structured point built from traced values
    (``lax.switch`` for choices) and must return a scalar jnp loss.

    Returns ``(best_flat, best_loss)`` — or a reference-shaped ``Trials``
    when ``return_trials=True`` (every trial materialized as a document, so
    downstream tooling/plots work unchanged).
    """
    from ._env import enable_persistent_compilation_cache

    enable_persistent_compilation_cache()
    cs = compile_space(space)
    cap = int(max_evals)
    cfg = {
        "prior_weight": float(prior_weight),
        "n_EI_candidates": int(n_EI_candidates),
        "gamma": float(gamma),
        "LF": int(linear_forgetting),
    }

    cache_key = (cs.expr, fn, cap, int(n_startup_jobs),
                 tuple(sorted(cfg.items())), tpe._pallas_armed())
    holder = _RUN_CACHE.get(cache_key)
    _record_cache_stats()
    if holder is None:
        step = _build_step(cs, fn, cap, cfg, int(n_startup_jobs))

        @jax.jit
        def run(key):
            vals = {l: jnp.zeros(cap, jnp.float32) for l in cs.labels}
            active = {l: jnp.zeros(cap, bool) for l in cs.labels}
            losses = jnp.full(cap, jnp.inf, jnp.float32)
            has_loss = jnp.zeros(cap, bool)
            carry = (vals, active, losses, has_loss, key)
            carry, trace = jax.lax.scan(step, carry, jnp.arange(cap, dtype=jnp.int32))
            vals, active, losses, has_loss, _ = carry
            return vals, active, losses, has_loss, trace

        holder = {"jit": run, "compiled": None}
        _RUN_CACHE.put(cache_key, holder)

    key = seed if isinstance(seed, jax.Array) else jax.random.PRNGKey(int(seed))
    # the AOT executable freezes the key's aval; a raw uint32[2] key and a
    # typed jax.random.key() must not poison each other's cache entry —
    # recompile (jit's lowering cache still makes it cheap) on a sig change
    sig = (key.shape, str(key.dtype))
    run = holder["compiled"] if holder.get("compiled_sig") == sig else None
    if run is None:
        run = _aot_compile(holder, (key,), "whole_run.compile_sec")
        holder["compiled_sig"] = sig
    _wd_beat("device.execute", stage="whole_run", mark="pre")
    t0 = time.perf_counter()
    out = run(key)
    jax.block_until_ready(out)  # strict completion: execute_sec is honest
    _METRICS.histogram("whole_run.execute_sec").observe(
        time.perf_counter() - t0)
    _wd_beat("device.execute", stage="whole_run", mark="post")
    vals, active, losses, has_loss, trace = out

    vals = {l: np.asarray(v) for l, v in vals.items()}
    active = {l: np.asarray(v) for l, v in active.items()}
    losses = np.asarray(losses)
    best_i = int(np.argmin(losses))
    best_flat = {
        l: (int(round(float(vals[l][best_i])))
            if cs.params[l].is_int else float(vals[l][best_i]))
        for l in cs.labels
        if active[l][best_i]
    }
    best_loss = float(losses[best_i])

    if not return_trials:
        return best_flat, best_loss

    return trials_from_flat_history(cs, vals, active, losses, "device_fmin")
