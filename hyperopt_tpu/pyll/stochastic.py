"""``hyperopt.pyll.stochastic`` compatibility: ``sample(space, rng=None)``.

Parity target: ``hyperopt/pyll/stochastic.py`` (sym: sample ≈L200) — the
reference signature takes a numpy ``RandomState``; here any of numpy
``Generator``/``RandomState``, an int seed, a jax PRNG key, or nothing
(fresh entropy) is accepted and mapped onto the compiled sampler's
``jax.random`` key.
"""

from __future__ import annotations

import numpy as np

import jax

from .. import spaces

__all__ = ["sample"]


def _as_key(rng):
    if rng is None:
        return jax.random.PRNGKey(np.random.SeedSequence().entropy % (2**32))
    if isinstance(rng, jax.Array):
        return rng
    if isinstance(rng, (int, np.integer)):
        return jax.random.PRNGKey(int(rng) & 0xFFFFFFFF)
    if isinstance(rng, np.random.Generator):
        return jax.random.PRNGKey(int(rng.integers(2**32, dtype=np.uint64)))
    if isinstance(rng, np.random.RandomState):
        return jax.random.PRNGKey(int(rng.randint(0, 2**31 - 1)))
    raise TypeError(f"cannot derive a PRNG key from rng={rng!r}")


def sample(space, rng=None):
    """One structured draw from ``space`` (pyll/stochastic.py sym: sample)."""
    return spaces.sample(space, _as_key(rng))
