"""``hyperopt.pyll.stochastic`` compatibility: ``sample(space, rng=None)``.

Parity target: ``hyperopt/pyll/stochastic.py`` (sym: sample ≈L200) — the
reference signature takes a numpy ``RandomState``; here any of numpy
``Generator``/``RandomState``, an int seed, a jax PRNG key, or nothing
(fresh entropy) is accepted and mapped onto the compiled sampler's
``jax.random`` key.
"""

from __future__ import annotations

import numpy as np

import jax

from .. import spaces

__all__ = ["sample"]


# kept as a name for back-compat importers; the coercion lives in spaces
_as_key = spaces.rng_to_key


def sample(space, rng=None):
    """One structured draw from ``space`` (pyll/stochastic.py sym: sample)."""
    return spaces.sample(space, rng)
