"""Compatibility facade for the reference's ``hyperopt.pyll`` surface.

Parity target: ``hyperopt/pyll`` (sym: stochastic.sample, as_apply).  The
reference's pyll is an interpreted expression-graph DSL; this framework
replaced it with a compiled space IR (``hyperopt_tpu.spaces`` — the jaxpr
plays the role of the pyll graph, SURVEY.md §7.1).  What survives here is
the *user-facing* subset that reference tutorials and docs actually use:

* ``pyll.stochastic.sample(space)`` — preview one structured draw from a
  search space (the canonical space-debugging idiom).
* ``as_apply`` — alias of ``spaces.as_expr`` (builds the static IR).

The interpreter internals (``scope``, ``rec_eval``, ``Apply`` graph
surgery) intentionally have no analog: spaces compile to jitted samplers,
and custom distributions extend ``spaces.Dist`` instead of registering
scope symbols.  Importing them raises immediately with that guidance.
"""

from ..spaces import as_expr as as_apply  # noqa: F401
from . import stochastic  # noqa: F401

__all__ = ["stochastic", "as_apply"]


def __getattr__(name):
    if name in ("scope", "rec_eval", "Apply", "Literal"):
        raise AttributeError(
            f"hyperopt_tpu.pyll.{name} does not exist: the pyll interpreter "
            "was replaced by the compiled space IR (hyperopt_tpu.spaces). "
            "Build spaces with hp.*, sample with pyll.stochastic.sample, "
            "and extend distributions via hyperopt_tpu.spaces.Dist."
        )
    raise AttributeError(name)
