"""Static, space-derived history quantization (ISSUE 19).

``HYPEROPT_TPU_HIST_DTYPE=int8|fp8`` pushes the device-mirror storage
contract past bf16: per-label affine codes ``t(x) ≈ zero + q * scale``
with ``q`` stored as int8 (round-to-nearest on a 255-point grid) or
float8_e4m3fn (continuous in the same normalized range), so the same HBM
holds 4x the bf16 ``hist_cap``.  Three rules make this safe enough for
the bitwise-resume and donation contracts the mirror already carries:

1.  **qparams are pure functions of the space.**  ``scale``/``zero``
    derive from the ``Dist`` family/params alone (bounds for the uniform
    families, ``mu ± 4σ`` for the unbounded normals, the exact integer
    grid for discrete families) — never from observed data.  Two
    processes holding the same space agree on the code without
    coordination, and a resumed run cannot drift because the data
    changed the code.  Log-space families quantize ``log x`` (their
    Parzen fit consumes ``log x`` anyway), so precision is spent where
    the posterior lives.

2.  **Snap-at-ingest.**  Once a :class:`~hyperopt_tpu.base.PaddedHistory`
    arms qparams, every host value is snapped to the dequantized grid at
    append time (``snap_np``), and already-recorded rows are snapped
    retroactively.  The host numpy arrays stay float32 and authoritative
    — pickle/WAL/checkpoint carry the snapped f32 values, never the
    codes — but every later quantization (full upload, incremental
    scatter, in-trace row fold) rounds an *exact grid point*, which is
    robust to the ≤few-ulp ``log``/``exp`` differences between numpy and
    XLA.  That is what makes a crash-resumed run propose bit-identically
    to the uninterrupted one: both quantize the same grid values to the
    same codes no matter which path (host upload vs device scatter)
    folds a given row.

3.  **Degrade, never fail.**  A space the code cannot represent exactly
    enough (value-quantized ``q*`` families, discrete families wider
    than the code's exact-integer range, bounds too tight for f32 round
    tripping) or a backend without the storage dtype falls back to
    whole-history bf16 with a warn-once and a ``suggest.quant.fallback``
    counter — an ask must never fail because telemetry-grade compression
    was misconfigured (the ``_env`` convention).

Kernels never see the codes: every read site dequantizes to f32 before
the Parzen/EI math (``dequantize`` / the ``read_vals`` helpers in
``algos/tpe.py``), preserving the f32-accumulation contract of
DESIGN.md §13.  Losses stay bf16 under the quant modes — they are
data-dependent (no static scale exists) and they drive the below/above
argsort split, where int8 resolution would reorder ties.
"""

from __future__ import annotations

import logging
import math

import numpy as np

import jax.numpy as jnp

__all__ = [
    "QUANT_NAMES",
    "is_quant_name",
    "vals_dtype",
    "losses_dtype",
    "mirror_float_dtype",
    "label_qparams",
    "space_qparams",
    "resolve",
    "quantize",
    "dequantize",
    "snap_np",
    "fallback_count",
]

logger = logging.getLogger(__name__)

#: storage-dtype names past bf16 (``parse_hist_dtype`` grammar)
QUANT_NAMES = ("int8", "fp8")

EPS = 1e-12
_QMAX = 127.0  # symmetric code range; -128 unused so the grid is odd

# int8 codes round-trip any integer in [-127, 127]; float8_e4m3fn (3
# mantissa bits) only represents integers exactly up to 2**4 — past that
# a discrete bucket would decode to the wrong category
_DISCRETE_LIMIT = {"int8": 255, "fp8": 33}

_warned = set()


def _fallback(reason, key=None):
    """Warn once per (reason key) and bump the scrape-visible counter —
    quant degrade follows the observability convention: never raise."""
    k = key if key is not None else reason
    if k not in _warned:
        _warned.add(k)
        logger.warning(
            "quantized history unavailable (%s); falling back to bf16 "
            "storage for this history (warn-once; ask served normally)",
            reason)
    try:
        from .obs.metrics import get_metrics

        get_metrics("service").counter("suggest.quant.fallback").inc()
    except Exception:  # noqa: BLE001 - telemetry must not take down an ask
        pass


def fallback_count():
    """Current value of the ``suggest.quant.fallback`` counter (tests)."""
    from .obs.metrics import get_metrics

    snap = get_metrics("service").snapshot()["metrics"]
    return int(snap.get("suggest.quant.fallback", 0) or 0)


def is_quant_name(name):
    return str(name) in QUANT_NAMES


def _fp8_dtype():
    try:
        return jnp.dtype(jnp.float8_e4m3fn)
    except (AttributeError, TypeError):  # ancient jax/ml_dtypes
        return None


def vals_dtype(name):
    """jnp storage dtype of the ``vals`` arrays under ``name``, or None
    when the backend lacks it (fp8 on old jax builds)."""
    name = str(name)
    if name == "int8":
        return jnp.dtype(jnp.int8)
    if name == "fp8":
        return _fp8_dtype()
    return jnp.dtype(name)


def quant_dtype_name(dt):
    """``"int8"``/``"fp8"`` when ``dt`` is a quant STORAGE dtype, else
    None — the trace-time dispatch every read/write site keys off (the
    history leaf's dtype, not env state, decides the traced program)."""
    dt = jnp.dtype(dt)
    if dt == jnp.dtype(jnp.int8):
        return "int8"
    f8 = _fp8_dtype()
    if f8 is not None and dt == f8:
        return "fp8"
    return None


def losses_dtype(name):
    """jnp storage dtype of the ``losses`` array: bf16 under the quant
    modes (data-dependent range — no static scale exists, and the
    below/above split argsorts them), else the mode's own dtype."""
    if is_quant_name(name):
        return jnp.dtype(jnp.bfloat16)
    return jnp.dtype(str(name))


def mirror_float_dtype(name):
    """The plain "compress float leaves via astype" dtype for paths that
    mirror history WITHOUT a quantization code path (the multihost
    driver/fleet replication, ``device_fmin``'s resident loop state,
    ``sharding.place_history``): f32/bf16 pass through, the quant names
    degrade to bf16 with a warn-once — an ``astype(int8)`` there would
    silently truncate values, not encode them."""
    if is_quant_name(name):
        _fallback(f"{name} history is not supported on this path "
                  "(affine-code reads are not wired here)",
                  key=("mirror", str(name)))
        return jnp.dtype(jnp.bfloat16)
    return jnp.dtype(str(name))


def label_qparams(dist, name):
    """``(scale, zero, islog)`` for one ``Dist`` under storage ``name``,
    or None when the family cannot be coded exactly enough.

    Numeric families: bounded ones spread the 255-point grid over the
    (log-space, for the log families) bounds; unbounded normals cover
    ``mu ± 4σ`` (the Parzen prior's own mass; codes clip beyond).
    Value-quantized families (``q*``) are refused — their value grid is
    not affine in ``t``-space.  Discrete families use the exact integer
    code (scale 1, zero at the bucket-range midpoint) and are refused
    past the dtype's exact-integer range."""
    from .algos.tpe import _parzen_from, _prior_probs

    name = str(name)
    fam = dist.family
    if fam in ("categorical", "randint"):
        K = int(_prior_probs(dist).shape[0])
        if K > _DISCRETE_LIMIT.get(name, 0):
            return None
        offset = int(dist.params[0]) if fam == "randint" else 0
        return (1.0, float(offset + (K - 1) // 2), False)
    try:
        _, _, low, high, q, islog = _parzen_from(dist)
    except ValueError:
        return None
    if q is not None:
        return None
    if math.isfinite(low) and math.isfinite(high):
        zero = 0.5 * (low + high)
        scale = (high - low) / (2.0 * _QMAX)
    else:
        mu, sigma = float(dist.params[0]), float(dist.params[1])
        zero = mu
        scale = (8.0 * sigma) / (2.0 * _QMAX)
    if not (scale > 0.0) or not math.isfinite(scale):
        return None
    # f32 round-trip guard: re-quantizing a decoded grid point must land
    # within 0.5 code of the original even after the ± few-ulp wobble of
    # (t - zero) cancellation and log/exp.  A grid finer than ~8 ulp of
    # the zero offset cannot guarantee that — degrade instead of drifting.
    if scale <= 8.0 * float(np.spacing(np.float32(abs(zero)))):
        return None
    return (float(scale), float(zero), bool(islog))


def space_qparams(cs, name):
    """Per-label qparams dict for a CompiledSpace, or None when ANY label
    cannot be coded (the whole mirror degrades together — a split-dtype
    mirror would fork every jit cache key for marginal savings) or the
    backend lacks the storage dtype."""
    if vals_dtype(name) is None:
        return None
    out = {}
    for l in cs.labels:
        qp = label_qparams(cs.params[l].dist, name)
        if qp is None:
            return None
        out[l] = qp
    return out


def resolve(cs, name, context="history"):
    """``(effective_name, qparams_or_None)`` — the one place that owns
    the degrade ladder: quant names resolve to themselves plus their
    qparams when the space/backend supports them, else to ``bfloat16``
    with the warn-once + counter."""
    name = str(name)
    if not is_quant_name(name):
        return name, None
    qp = space_qparams(cs, name)
    if qp is None:
        _fallback(f"{name} cannot represent this space", key=(context, name))
        return "bfloat16", None
    return name, qp


# ---------------------------------------------------------------------------
# the code itself — trace-safe jnp on the device path, numpy twin for the
# host snap.  Both compute in f32 with the same operation order, so a
# snapped (grid) value quantizes to the same code everywhere.
# ---------------------------------------------------------------------------


def quantize(x, qp, name):
    """f32 values → storage codes (trace-safe; used by the in-trace row
    folds and the full-upload path)."""
    scale, zero, islog = qp
    x = jnp.asarray(x, jnp.float32)
    t = jnp.log(jnp.maximum(x, EPS)) if islog else x
    q = jnp.clip((t - jnp.float32(zero)) / jnp.float32(scale), -_QMAX, _QMAX)
    if str(name) == "int8":
        q = jnp.round(q)
    return q.astype(vals_dtype(name))


def dequantize(q, qp):
    """Storage codes → f32 values (the kernels' read boundary; fused into
    the megakernel's history-streaming loop on the pallas path)."""
    scale, zero, islog = qp
    t = q.astype(jnp.float32) * jnp.float32(scale) + jnp.float32(zero)
    return jnp.exp(t) if islog else t


def snap_np(x, qp, name):
    """Host numpy encode→decode round trip: the value the device mirror
    will decode for ``x``.  Applied at append time (and retroactively at
    arm time) so the authoritative host arrays hold exact grid points —
    see the module docstring's rule 2.  Idempotent by the ``resolve``
    scale guard."""
    scale, zero, islog = qp
    x = np.asarray(x, np.float32)
    scalar = x.ndim == 0
    x = np.atleast_1d(x)
    t = (np.log(np.maximum(x, np.float32(EPS))).astype(np.float32)
         if islog else x)
    q = np.clip((t - np.float32(zero)) / np.float32(scale), -_QMAX, _QMAX)
    if str(name) == "int8":
        q = np.rint(q).astype(np.float32)
    else:
        import ml_dtypes

        q = q.astype(ml_dtypes.float8_e4m3fn).astype(np.float32)
    t2 = (q * np.float32(scale) + np.float32(zero)).astype(np.float32)
    out = np.exp(t2).astype(np.float32) if islog else t2
    return out[0] if scalar else out


def quantize_np(x, qp, name):
    """Host numpy encode (the full-upload path): same ops and order as
    :func:`quantize`, producing a numpy array in the storage dtype."""
    scale, zero, islog = qp
    x = np.atleast_1d(np.asarray(x, np.float32))
    t = (np.log(np.maximum(x, np.float32(EPS))).astype(np.float32)
         if islog else x)
    q = np.clip((t - np.float32(zero)) / np.float32(scale), -_QMAX, _QMAX)
    if str(name) == "int8":
        return np.rint(q).astype(np.int8)
    import ml_dtypes

    return q.astype(ml_dtypes.float8_e4m3fn)


def qkey(qparams, labels):
    """Hashable form of a qparams dict (jit/updater cache-key component:
    the traced program bakes scale/zero as constants)."""
    if qparams is None:
        return None
    return tuple(qparams[l] for l in labels)
