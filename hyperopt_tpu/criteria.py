"""Analytic acquisition criteria.

Parity target: ``hyperopt/criteria.py`` (sym: EI_empirical, EI_gaussian,
logEI_gaussian, UCB) — demo-grade criteria not wired into TPE (the reference
keeps them as standalone math; same here), expressed in jnp so they jit and
vmap over candidate batches.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ["EI_empirical", "EI_gaussian", "logEI_gaussian", "UCB"]


def EI_empirical(samples, thresh):
    """Expected improvement over ``thresh`` from empirical samples
    (criteria.py sym: EI_empirical)."""
    samples = jnp.asarray(samples)
    improvement = jnp.maximum(samples - thresh, 0.0)
    return jnp.mean(improvement)


def EI_gaussian(mean, var, thresh):
    """Expected improvement over ``thresh`` for N(mean, var)
    (criteria.py sym: EI_gaussian)."""
    sigma = jnp.sqrt(var)
    score = (mean - thresh) / sigma
    n_cdf = 0.5 * (1.0 + jax.lax.erf(score / jnp.sqrt(2.0)))
    n_pdf = jnp.exp(-0.5 * score**2) / jnp.sqrt(2.0 * jnp.pi)
    return sigma * (score * n_cdf + n_pdf)


def logEI_gaussian(mean, var, thresh):
    """log(EI_gaussian), stable far into the tails
    (criteria.py sym: logEI_gaussian)."""
    sigma = jnp.sqrt(var)
    score = (mean - thresh) / sigma
    # for very negative score use the asymptotic expansion of the tail:
    # EI ~ sigma * pdf(score) / score^2  (Mills-ratio expansion)
    n_cdf = 0.5 * (1.0 + jax.lax.erf(score / jnp.sqrt(2.0)))
    n_pdf = jnp.exp(-0.5 * score**2) / jnp.sqrt(2.0 * jnp.pi)
    naive = sigma * (score * n_cdf + n_pdf)
    log_naive = jnp.log(jnp.maximum(naive, jnp.finfo(jnp.float32).tiny))
    log_tail = (
        jnp.log(sigma)
        - 0.5 * score**2
        - 0.5 * jnp.log(2.0 * jnp.pi)
        - 2.0 * jnp.log(jnp.maximum(-score, 1.0))
    )
    return jnp.where(score < -10.0, log_tail, log_naive)


def UCB(mean, var, zscore):
    """Upper confidence bound (criteria.py sym: UCB)."""
    return mean + jnp.sqrt(var) * zscore
