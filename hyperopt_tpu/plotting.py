"""Trial-visualisation helpers.

Parity target: ``hyperopt/plotting.py`` (sym: main_plot_history,
main_plot_histogram, main_plot_vars).  matplotlib is imported lazily so the
core package has no hard dependency on it (reference treats it as an extra).
"""

from __future__ import annotations

import math

import numpy as np

from .base import STATUS_OK

__all__ = ["main_plot_history", "main_plot_histogram", "main_plot_vars"]


def _ok_losses(trials):
    pairs = [
        (d["tid"], d["result"]["loss"])
        for d in trials.trials
        if d["result"].get("status") == STATUS_OK and d["result"].get("loss") is not None
    ]
    return zip(*pairs) if pairs else ((), ())


def main_plot_history(trials, do_show=False, status_colors=None, title="Loss History"):
    """Scatter of loss vs trial id with the running best overlaid
    (plotting.py sym: main_plot_history)."""
    import matplotlib.pyplot as plt

    tids, losses = _ok_losses(trials)
    fig, ax = plt.subplots()
    ax.scatter(tids, losses, s=12, alpha=0.6, label="trial loss")
    if losses:
        best = np.minimum.accumulate(np.asarray(losses))
        ax.plot(tids, best, color="C1", label="best so far")
    ax.set_xlabel("trial")
    ax.set_ylabel("loss")
    ax.set_title(title)
    ax.legend()
    if do_show:
        plt.show()
    return fig


def main_plot_histogram(trials, do_show=False, title="Loss Histogram"):
    """Histogram of ok-trial losses (plotting.py sym: main_plot_histogram)."""
    import matplotlib.pyplot as plt

    _, losses = _ok_losses(trials)
    fig, ax = plt.subplots()
    ax.hist(np.asarray(losses), bins=min(30, max(3, len(losses) // 3 or 3)))
    ax.set_xlabel("loss")
    ax.set_ylabel("count")
    ax.set_title(title)
    if do_show:
        plt.show()
    return fig


def main_plot_vars(trials, do_show=False, columns=3):
    """Per-hyperparameter scatter of value vs loss, colored by recency
    (plotting.py sym: main_plot_vars)."""
    import matplotlib.pyplot as plt

    samples = {}  # label -> (vals, losses, tids)
    for d in trials.trials:
        result = d["result"]
        if result.get("status") != STATUS_OK or result.get("loss") is None:
            continue
        for label, v in d["misc"]["vals"].items():
            if len(v) != 1:
                continue
            entry = samples.setdefault(label, ([], [], []))
            entry[0].append(v[0])
            entry[1].append(result["loss"])
            entry[2].append(d["tid"])
    labels = sorted(samples)
    if not labels:
        fig, _ = plt.subplots()
        return fig
    rows = math.ceil(len(labels) / columns)
    fig, axes = plt.subplots(rows, columns, figsize=(4 * columns, 3 * rows),
                             squeeze=False)
    for i, label in enumerate(labels):
        ax = axes[i // columns][i % columns]
        vals, losses, tids = samples[label]
        sc = ax.scatter(vals, losses, c=tids, cmap="viridis", s=12)
        ax.set_title(label)
        ax.set_ylabel("loss")
    for j in range(len(labels), rows * columns):
        axes[j // columns][j % columns].axis("off")
    fig.colorbar(sc, ax=axes[-1][-1], label="trial id")
    fig.tight_layout()
    if do_show:
        plt.show()
    return fig
