"""Durable cross-process trial store + driver-side Trials backend.

Parity target: ``hyperopt/mongoexp.py`` (sym: MongoJobs ≈L150-500 — atomic
``reserve`` via find_one_and_update, ``new_trial_ids`` via counter doc;
MongoTrials ≈L500-800 — asynchronous=True, exp_key scoping, attachments).
The reference gets durability and single-claim semantics from MongoDB; here
both come from the filesystem, which every TPU pod slice already shares via
NFS/GCS-fuse mounts:

* **Durability** — every trial document is its own pickle file; a crashed
  driver or worker loses nothing that was written.
* **Atomic claim** — claiming NEW→RUNNING is ``os.rename(new/<tid>.pkl,
  running/<tid>.pkl)``: POSIX rename is atomic, exactly one claimant wins
  (the ``find_one_and_update`` analog).  No daemon required.
* **Heartbeats & reclaim** — workers rewrite their RUNNING doc's
  ``refresh_time`` periodically (MongoWorker's heartbeat thread); anyone may
  move a RUNNING doc whose heartbeat is older than ``reserve_timeout`` back
  to NEW (stale-claim recovery, which upstream leaves as a manual query).
* **Counter** — trial ids come from a byte-length-encoded counter file under
  an ``fcntl`` lock (the atomic counter-doc increment).

Layout of a store directory::

    store/
      counter           monotonically increasing tid allocator (fcntl-locked)
      attachments/      named blobs: FMinIter_Domain is the cloudpickled Domain
      new/<tid>.pkl     queued trial documents
      running/<tid>.pkl claimed documents (owner, book_time, refresh_time set)
      done/<tid>.pkl    finished documents (result filled in)
      error/<tid>.pkl   crashed documents (misc['error'] set)

Workers are real processes: ``python -m hyperopt_tpu.worker --store DIR``
(console script ``hyperopt-tpu-worker``), the ``hyperopt-mongo-worker``
analog — see ``worker.py``.
"""

from __future__ import annotations

import errno
import fcntl
import logging
import os
import pickle
import threading
import time

from . import chaos
from .exceptions import StoreFullError
from .retry import RetryPolicy
from .base import (
    JOB_STATE_CANCEL,
    JOB_STATE_DONE,
    JOB_STATE_ERROR,
    JOB_STATE_NEW,
    JOB_STATE_RUNNING,
    Trials,
    coarse_utcnow,
)
from .obs import get_metrics
from .obs.events import (
    TRIAL_CANCELLED,
    TRIAL_CLAIMED,
    TRIAL_FINISHED,
    TRIAL_HEARTBEAT,
    TRIAL_NEW,
    TRIAL_RECLAIMED,
    EventLog,
    FileEventSink,
    load_events,
)

__all__ = ["FileStore", "FileTrials", "ReserveTimeout", "StoreFullError",
           "new_run_id"]

logger = logging.getLogger(__name__)

#: "no space" errnos translated to the typed, retryable StoreFullError
_ENOSPC_ERRNOS = {errno.ENOSPC, getattr(errno, "EDQUOT", errno.ENOSPC)}

_STATE_DIRS = {
    JOB_STATE_NEW: "new",
    JOB_STATE_RUNNING: "running",
    JOB_STATE_DONE: "done",
    JOB_STATE_ERROR: "error",
    JOB_STATE_CANCEL: "cancel",
}


class ReserveTimeout(Exception):
    """No job could be reserved within the allotted time
    (hyperopt/mongoexp.py sym: ReserveTimeout)."""


# seconds below which a transition claim is assumed to be a LIVE in-flight
# transition regardless of the sweep's max_age (see _sweep_orphan_claims)
_CLAIM_GRACE = 5.0

# reserve-contention backoff (ISSUE 8 satellite): when a rename loses the
# claim race, back off a jittered-exponential beat before trying the next
# candidate instead of storming the directory — with many workers the old
# tight loop showed up as pure reserve.contention churn.  Micro-scale
# delays (1ms base, 50ms cap): contention means *other workers are making
# progress*, not that the store is down.
_RESERVE_BACKOFF = RetryPolicy(max_retries=0, base_delay=0.001,
                               max_delay=0.05, jitter=0.5)


def _atomic_write(path, payload: bytes):
    # deterministic fault injection (HYPEROPT_TPU_CHAOS ioerr@io:<p> /
    # enospc@io:<p>): every durable write in the store — docs,
    # heartbeats, attachments, checkpoints, fleet results — shares this
    # one failure point, which is exactly the surface a flaky
    # NFS/GCS-fuse mount (or a full disk) presents
    chaos.io_point("io")
    # pid AND thread id: two same-process threads writing the same target
    # (a heartbeat thread racing the claim path, concurrent reclaim+cancel)
    # would otherwise share one tmp name — the loser's os.replace then
    # crashes on the winner's already-consumed tmp file
    tmp = f"{path}.tmp.{_claim_suffix()}"
    try:
        with open(tmp, "wb") as f:
            f.write(payload)
        os.replace(tmp, path)
    except OSError as e:
        _remove_quiet(tmp)
        if getattr(e, "errno", None) in _ENOSPC_ERRNOS:
            # typed + retryable (ISSUE 15): a full disk is a transient
            # STATE, not a store bug — the serving plane sheds with 507,
            # the worker/executor backs off and retries
            raise StoreFullError(
                e.errno, f"store write failed, disk full: {path}") from e
        raise


def _touch(path):
    """Reset a claim file's mtime to NOW.  ``os.rename`` preserves the
    source's mtime (the doc's last heartbeat write — arbitrarily old), and
    the orphan sweep ages claims by mtime; without the touch a LIVE
    finish/reclaim transition could be swept mid-flight."""
    try:
        os.utime(path, None)
    except FileNotFoundError:
        pass


def _remove_quiet(path):
    """Remove a claim, tolerating its theft by the orphan sweep (possible
    only if this process stalled longer than the sweep's max_age between
    rename and remove — the terminal doc is already written either way and
    state precedence dedupes)."""
    try:
        os.remove(path)
    except FileNotFoundError:
        pass


def _claim_suffix():
    """pid AND thread id: same-process threads (a heartbeat thread beside
    the worker loop, concurrent reclaim+cancel) would otherwise compute the
    SAME claim/tmp name for one trial, and ``os.rename`` silently clobbers
    an existing destination — one thread's live claim file would vanish
    under the other."""
    return f"{os.getpid()}.{threading.get_ident()}"


def new_run_id(prefix="run", unique_dir=None):
    """Auth-agnostic opaque run/study id: ``<prefix>-<12 hex>`` from
    ``os.urandom``.  Collision-safe across processes with no coordination
    (the ask/tell service mints study ids with this — the id doubles as
    the store subdirectory name when studies persist through a
    :class:`FileStore`), and unguessable enough that knowing one study's
    id never reveals a neighbor's.

    ``unique_dir`` makes the allocation collision-PROOF instead of
    merely collision-unlikely: the id is claimed by ``os.mkdir`` of
    ``<unique_dir>/<id>`` — atomic-exclusive on every filesystem the
    store runs on — and a lost race simply redraws.  N fleet replicas
    minting study ids against one shared store root use this; the
    claimed directory IS the study's store subdirectory, so the claim
    costs nothing extra."""
    import binascii

    for _ in range(64):
        run_id = f"{prefix}-{binascii.hexlify(os.urandom(6)).decode()}"
        if unique_dir is None:
            return run_id
        try:
            os.makedirs(unique_dir, exist_ok=True)
            os.mkdir(os.path.join(unique_dir, run_id))
            return run_id
        except FileExistsError:
            continue  # another replica drew the same 48 bits: redraw
    raise RuntimeError(
        f"could not mint a unique id under {unique_dir} in 64 draws "
        "(exhausted 48-bit space, or the directory is not writable)")


# the durable trial-lifecycle event log rides the attachments namespace so
# it shares the store's durability story and is readable as an attachment
_EVENTS_ATTACHMENT = "obs_events.jsonl"

# flight-recorder crash dumps ride the same namespace: one per dying
# process (driver or worker), named flight.<owner>.jsonl — a worker killed
# mid-trial leaves its last moments inside the store it was serving
_FLIGHT_PREFIX = "flight."


class FileStore:
    """Low-level durable job store (hyperopt/mongoexp.py sym: MongoJobs).

    Obs: every state transition (new/claimed/heartbeat/finished/cancelled/
    reclaimed) appends one line to the ``obs_events.jsonl`` attachment —
    O_APPEND writes, so driver and worker processes interleave whole
    records and a post-mortem survives every process on the store dying
    (``read_events()``).  Contention and reclaim counters land in the
    process-global "filestore" metrics namespace."""

    def __init__(self, root):
        self.root = str(root)
        for d in ("attachments", *_STATE_DIRS.values()):
            os.makedirs(os.path.join(self.root, d), exist_ok=True)
        counter = os.path.join(self.root, "counter")
        if not os.path.exists(counter):
            _atomic_write(counter, b"0")
        self.events = EventLog(sink=FileEventSink(
            os.path.join(self.root, "attachments", _EVENTS_ATTACHMENT)))
        self.metrics = get_metrics("filestore")
        self._sleep = time.sleep  # injectable for backoff tests

    def read_events(self):
        """The durable lifecycle log, parsed — every event any process on
        this store ever emitted (the post-mortem entry point)."""
        return load_events(
            os.path.join(self.root, "attachments", _EVENTS_ATTACHMENT))

    # -- flight-recorder dumps (obs/flight.py) ----------------------------

    def flight_dump_path(self, owner):
        """Attachment path for ``owner``'s crash dump (``:`` is swapped out
        so the hostname:pid owner string stays one path component)."""
        safe = str(owner).replace(":", "-").replace(os.sep, "-")
        return os.path.join(self.root, "attachments",
                            f"{_FLIGHT_PREFIX}{safe}.jsonl")

    def arm_flight(self, owner):
        """Arm the process-global flight recorder to dump into this store's
        attachments when THIS process dies (worker processes call this at
        startup — the store then holds the forensics for every process
        that ever served it).  Returns the dump path."""
        from .obs.flight import get_flight

        path = self.flight_dump_path(owner)
        get_flight().install(path)
        return path

    def read_flight_dumps(self):
        """``{owner: records}`` for every flight dump any process left in
        the store (render one with ``obs.report --postmortem <path>``)."""
        from .obs.trace import read_jsonl

        d = os.path.join(self.root, "attachments")
        out = {}
        for fname in sorted(os.listdir(d)):
            if (not fname.startswith(_FLIGHT_PREFIX)
                    or not fname.endswith(".jsonl")):
                continue
            owner = fname[len(_FLIGHT_PREFIX):-len(".jsonl")]
            out[owner] = read_jsonl(os.path.join(d, fname))
        return out

    # -- tid allocation (counter-doc analog) ------------------------------

    def new_trial_ids(self, n):
        path = os.path.join(self.root, "counter")
        with open(path, "r+") as f:
            fcntl.flock(f, fcntl.LOCK_EX)
            try:
                start = int(f.read().strip() or "0")
                f.seek(0)
                f.truncate()
                f.write(str(start + n))
                f.flush()
                os.fsync(f.fileno())
            finally:
                fcntl.flock(f, fcntl.LOCK_UN)
        return list(range(start, start + n))

    def reset_counter(self, value):
        """Clamp the tid allocator DOWN to ``value`` (no-op if it is
        already at or below).  WAL resume uses this to reclaim ids an
        ask consumed before dying un-journaled mid-wave: the TPE kernel
        keys per-trial PRNG streams off the id VALUE, so a counter gap
        would make every post-restart proposal diverge from the
        uninterrupted run the crash-resume pin compares against.  Only
        safe when the caller owns the store exclusively (the service
        scheduler does; worker fleets never call this)."""
        path = os.path.join(self.root, "counter")
        value = int(value)
        with open(path, "r+") as f:
            fcntl.flock(f, fcntl.LOCK_EX)
            try:
                cur = int(f.read().strip() or "0")
                if value < cur:
                    f.seek(0)
                    f.truncate()
                    f.write(str(value))
                    f.flush()
                    os.fsync(f.fileno())
            finally:
                fcntl.flock(f, fcntl.LOCK_UN)

    # -- attachments ------------------------------------------------------

    def set_attachment(self, name, blob: bytes):
        _atomic_write(os.path.join(self.root, "attachments", name), blob)

    def get_attachment(self, name):
        path = os.path.join(self.root, "attachments", name)
        if not os.path.exists(path):
            return None
        with open(path, "rb") as f:
            return f.read()

    def attachment_names(self):
        return sorted(os.listdir(os.path.join(self.root, "attachments")))

    # -- doc IO -----------------------------------------------------------

    def _path(self, state, tid):
        return os.path.join(self.root, _STATE_DIRS[state], f"{tid}.pkl")

    def write_doc(self, doc):
        """Write (or overwrite) a doc in the directory matching its state."""
        fresh = (doc["state"] == JOB_STATE_NEW
                 and not os.path.exists(self._path(JOB_STATE_NEW, doc["tid"])))
        _atomic_write(self._path(doc["state"], doc["tid"]), pickle.dumps(doc))
        if fresh:
            self.events.emit(TRIAL_NEW, doc["tid"])

    def settle(self, doc):
        """Write a TERMINAL doc and drop its superseded ``new``/``running``
        copies.  The ask/tell service's tell path: a served trial goes
        NEW → DONE without ever being worker-claimed, so the
        reserve/finish lifecycle (and its claim files) never applies —
        but leaving the stale ``new/`` copy behind would make every
        ``load_all`` lean on state precedence forever."""
        self.write_doc(doc)
        for state in (JOB_STATE_NEW, JOB_STATE_RUNNING):
            if state != doc["state"]:
                _remove_quiet(self._path(state, doc["tid"]))

    def _read(self, path):
        try:
            with open(path, "rb") as f:
                return pickle.loads(f.read())
        except (FileNotFoundError, EOFError, pickle.UnpicklingError):
            return None  # raced with a rename / partial write: skip this scan

    # residual cross-process races (e.g. a heartbeat re-creating running/
    # in the instant a cancel renames it away) can leave one tid in two
    # directories; readers resolve by precedence so a trial is never
    # double-counted.  DONE over CANCEL: if the work finished anyway,
    # keeping the result is strictly better than discarding it.
    _STATE_PRECEDENCE = {
        JOB_STATE_DONE: 4,
        JOB_STATE_ERROR: 3,
        JOB_STATE_CANCEL: 2,
        JOB_STATE_RUNNING: 1,
        JOB_STATE_NEW: 0,
    }

    def load_all(self):
        """Every doc in the store, state taken from its directory (a doc
        mid-rename can appear in neither — the next scan sees it).  A tid
        present in several directories yields ONE doc, by state precedence."""
        by_tid = {}
        for state, d in _STATE_DIRS.items():
            dirpath = os.path.join(self.root, d)
            for fname in os.listdir(dirpath):
                if not fname.endswith(".pkl"):
                    continue
                doc = self._read(os.path.join(dirpath, fname))
                if doc is None:
                    continue
                doc["state"] = state
                prev = by_tid.get(doc["tid"])
                if (prev is None or self._STATE_PRECEDENCE[state]
                        > self._STATE_PRECEDENCE[prev["state"]]):
                    by_tid[doc["tid"]] = doc
        return sorted(by_tid.values(), key=lambda d: d["tid"])

    def count(self, states):
        if isinstance(states, int):
            states = [states]
        total = 0
        for s in states:
            d = os.path.join(self.root, _STATE_DIRS[s])
            total += sum(1 for f in os.listdir(d) if f.endswith(".pkl"))
        return total

    # -- claim / finish (the Mongo find_one_and_update analog) ------------

    def reserve(self, owner):
        """Atomically claim one NEW job: rename into running/ (exactly one
        claimant can win the rename), then stamp owner/book_time.  Returns
        the claimed doc or None.

        Contention backs off: each lost rename sleeps a jittered
        exponentially-growing beat (1ms base, 50ms cap, deterministic in
        ``(owner, losses-so-far)``) before the next candidate, so N
        workers racing one burst of NEW docs de-synchronize instead of
        storming ``listdir``+``rename`` in lockstep.  The
        ``reserve.backoff_sec`` histogram is the tuning signal."""
        new_dir = os.path.join(self.root, "new")
        contention = 0
        for fname in sorted(os.listdir(new_dir)):
            if not fname.endswith(".pkl"):
                continue
            tid = fname[:-4]
            src = os.path.join(new_dir, fname)
            if self._settled(tid):
                # zombie NEW doc: an at-least-once reclaim raced a finish/
                # cancel that already settled this trial — remove instead of
                # re-running settled work
                _remove_quiet(src)
                continue
            dst = os.path.join(self.root, "running", fname)
            try:
                os.rename(src, dst)
            except FileNotFoundError:
                # another claimant won this one: the contention counter is
                # the store's "how many workers fight per job" signal
                self.metrics.counter("reserve.contention").inc()
                delay = _RESERVE_BACKOFF.delay(contention, key=str(owner))
                contention += 1
                self.metrics.histogram("reserve.backoff_sec").observe(delay)
                self._sleep(delay)
                continue
            doc = self._read(dst)
            if doc is None:
                continue
            now = coarse_utcnow()
            doc["state"] = JOB_STATE_RUNNING
            doc["owner"] = owner
            doc["book_time"] = now
            doc["refresh_time"] = now
            _atomic_write(dst, pickle.dumps(doc))
            self.metrics.counter("reserve.claims").inc()
            self.events.emit(TRIAL_CLAIMED, doc["tid"], owner=str(owner))
            return doc
        return None

    def _settled(self, tid):
        """True when a terminal doc (DONE/ERROR/CANCEL) exists for ``tid``.
        The shared zombie guard: heartbeat/reserve/reclaim/sweep all refuse
        to act on (or resurrect) a trial that has already settled — the
        at-least-once reclaim races can leave NEW/RUNNING leftovers beside a
        terminal doc, and re-running settled work both wastes evaluations
        and leaves duplicate files for precedence to hide."""
        return any(
            os.path.exists(self._path(s, tid))
            for s in (JOB_STATE_DONE, JOB_STATE_ERROR, JOB_STATE_CANCEL)
        )

    def heartbeat(self, doc):
        """Bump refresh_time on a RUNNING doc (MongoWorker heartbeat).
        A cancelled/finished trial is not resurrected: the write is skipped
        once the running file is gone (and the residual TOCTOU window is
        absorbed by ``load_all``'s state precedence)."""
        doc["refresh_time"] = coarse_utcnow()
        tid = doc["tid"]
        if self._settled(tid):
            return  # trial already settled: do not resurrect running/
        path = self._path(JOB_STATE_RUNNING, tid)
        if os.path.exists(path):
            _atomic_write(path, pickle.dumps(doc))
            self.events.emit(TRIAL_HEARTBEAT, tid,
                             owner=str(doc.get("owner")))

    def finish(self, doc, result=None, error=None):
        """RUNNING → DONE/ERROR.  Ownership of the transition is the running
        file itself: renaming it to a private name is the atomic claim.  If
        the rename fails, a concurrent ``cancel``/``reclaim_stale`` took the
        trial first — the result is dropped (returns False) rather than
        written alongside the other party's doc (which would double-count the
        tid in ``load_all``)."""
        tid = doc["tid"]
        run_path = self._path(JOB_STATE_RUNNING, tid)
        claim = f"{run_path}.finish.{_claim_suffix()}"
        try:
            os.rename(run_path, claim)
        except FileNotFoundError:
            self.metrics.counter("finish.dropped").inc()
            logger.warning(
                "trial %s was cancelled/reclaimed before finish; dropping %s",
                tid, "error" if error is not None else "result")
            return False
        _touch(claim)  # claim age = NOW, not the doc's last heartbeat write
        if self._settled(tid):
            # the running file we claimed was a zombie (a heartbeat-TOCTOU
            # resurrection after a concurrent cancel/finish settled the
            # trial): drop this result rather than writing a SECOND
            # terminal doc beside the first
            _remove_quiet(claim)
            self.metrics.counter("finish.dropped").inc()
            logger.warning(
                "trial %s already settled; dropping duplicate %s",
                tid, "error" if error is not None else "result")
            return False
        doc["refresh_time"] = coarse_utcnow()
        if error is not None:
            doc["state"] = JOB_STATE_ERROR
            doc["misc"]["error"] = (str(type(error)), str(error))
        else:
            doc["state"] = JOB_STATE_DONE
            doc["result"] = result
        self.write_doc(doc)
        _remove_quiet(claim)
        sec = None
        if doc.get("book_time") is not None:
            sec = (doc["refresh_time"] - doc["book_time"]).total_seconds()
        self.events.emit(TRIAL_FINISHED, tid,
                         status="error" if error is not None else "ok",
                         sec=sec, owner=str(doc.get("owner")))
        return True

    def reclaim_stale(self, reserve_timeout, to_cancel=False):
        """Move RUNNING docs whose heartbeat is older than reserve_timeout
        seconds back to NEW (worker died mid-trial) — or, with
        ``to_cancel=True``, to CANCEL instead of retrying (the SparkTrials
        timeout→JOB_STATE_CANCEL policy for jobs that must not be re-run;
        the orphan sweep honors the same policy).  Also sweeps aged
        claim-file orphans (see ``_sweep_orphan_claims``) and prunes
        duplicate TERMINAL docs (see ``_prune_terminal_duplicates``).
        Returns count of reclaimed docs (stale RUNNING + recovered
        orphans)."""
        n = self._sweep_orphan_claims(reserve_timeout, to_cancel=to_cancel)
        self._prune_terminal_duplicates()
        run_dir = os.path.join(self.root, "running")
        target = JOB_STATE_CANCEL if to_cancel else JOB_STATE_NEW
        for fname in os.listdir(run_dir):
            if not fname.endswith(".pkl"):
                continue
            path = os.path.join(run_dir, fname)
            doc = self._read(path)
            if doc is None or doc.get("refresh_time") is None:
                continue
            if self._settled(doc["tid"]):
                # zombie RUNNING file beside a terminal doc (a heartbeat
                # TOCTOU resurrection): delete it — a concurrent finish
                # loses its rename and drops the duplicate result, which is
                # the documented contract
                _remove_quiet(path)
                continue
            age = (coarse_utcnow() - doc["refresh_time"]).total_seconds()
            if age < reserve_timeout:
                continue
            # claim the transition by renaming the running file away first;
            # losing the rename means the worker finished (or another
            # reclaimer won) in the meantime — skip, don't duplicate
            claim = f"{path}.reclaim.{_claim_suffix()}"
            try:
                os.rename(path, claim)
            except FileNotFoundError:
                continue
            _touch(claim)
            doc["state"] = target
            doc["owner"] = None
            _atomic_write(self._path(target, doc["tid"]), pickle.dumps(doc))
            _remove_quiet(claim)
            self.metrics.counter("reclaims.stale").inc()
            self.events.emit(TRIAL_RECLAIMED, doc["tid"],
                             heartbeat_age_sec=age,
                             target=_STATE_DIRS[target])
            logger.warning("reclaimed stale trial %s (heartbeat %.0fs old) -> %s",
                           doc["tid"], age, _STATE_DIRS[target])
            n += 1
        return n

    def _prune_terminal_duplicates(self):
        """Remove precedence-loser duplicates among TERMINAL docs.

        The ``_settled`` guards are check-then-write: a ``finish`` and a
        ``cancel`` acting on different zombie copies of one tid can both
        pass their check in the same instant and both write a terminal doc.
        ``load_all``'s precedence already hides the loser from every
        reader; this pass makes the store physically CONVERGE to one doc
        per trial (a fresh write can transiently recreate the race — the
        next reclaim prunes again)."""
        best = {}
        # descending precedence: the first state a tid is seen in wins
        for s in (JOB_STATE_DONE, JOB_STATE_ERROR, JOB_STATE_CANCEL):
            d = os.path.join(self.root, _STATE_DIRS[s])
            for fname in os.listdir(d):
                if not fname.endswith(".pkl"):
                    continue
                tid = fname[:-4]
                if tid in best:
                    logger.warning(
                        "pruning duplicate terminal doc %s/%s (kept %s)",
                        _STATE_DIRS[s], fname, _STATE_DIRS[best[tid]])
                    _remove_quiet(os.path.join(d, fname))
                else:
                    best[tid] = s

    def _sweep_orphan_claims(self, max_age, to_cancel=False):
        """Recover claim files orphaned by a crash mid-transition.

        ``finish``/``reclaim_stale``/``cancel`` all rename the source doc to
        a private ``*.pkl.{finish,reclaim,cancel}.<pid>.<tid>`` claim before
        writing the terminal doc; a crash in that window leaves a claim file
        that ``load_all`` ignores (doesn't end in ``.pkl``) — the trial
        would vanish from every state and the driver would wait until its
        fmin timeout (advisor finding, round 4).  A claim is recovered once
        older than ``max(max_age, _CLAIM_GRACE)`` seconds (60 s for
        sweep-private files) — live transitions ``_touch`` their claim at
        creation, so claim mtime measures claim age, not the doc's last
        heartbeat, and the grace floor keeps a zero/short ``max_age`` from
        stealing a LIVE in-flight transition.  Readable finish/reclaim claims
        go back to NEW for re-evaluation (at-least-once semantics — same
        policy as stale-heartbeat reclaim), or to CANCEL under
        ``to_cancel=True`` (the must-not-re-run policy); cancel claims
        always complete their interrupted transition to CANCEL; unreadable
        ones are removed with a warning (there is no doc left to preserve).
        Returns the number of docs recovered."""
        n = 0
        now = time.time()
        for state_dir in _STATE_DIRS.values():
            dirpath = os.path.join(self.root, state_dir)
            for fname in os.listdir(dirpath):
                if ".pkl." not in fname or ".tmp." in fname:
                    continue
                kind = fname.split(".pkl.", 1)[1].split(".", 1)[0]
                if kind not in ("finish", "reclaim", "cancel"):
                    continue
                path = os.path.join(dirpath, fname)
                try:
                    age = now - os.path.getmtime(path)
                except FileNotFoundError:
                    continue  # another sweeper got it
                # LIVENESS GRACE: a transition claim is _touch()ed at
                # creation and completes in milliseconds, so a claim younger
                # than the grace window is almost certainly a LIVE
                # transition, whatever ``max_age`` says — stealing it would
                # let the victim's unconditional terminal write race the
                # recovery into a duplicated trial (found by the randomized
                # storm test at reserve_timeout=0).  A >grace mid-transition
                # stall still loses this protection; that residue is the
                # same zombie-writer hazard Mongo's stale-reclaim accepts.
                # Sweep-private files get a larger floor: same reasoning,
                # one more indirection.
                floor = max(max_age,
                            60.0 if ".sweep." in fname else _CLAIM_GRACE)
                if age < floor:
                    continue
                # claim the claim: rename to a sweep-private name so two
                # concurrent sweepers can't both recover the same doc
                mine = f"{path}.sweep.{_claim_suffix()}"
                try:
                    os.rename(path, mine)
                except FileNotFoundError:
                    continue
                # rename preserves the source mtime (the ALREADY-AGED claim
                # time) — without the touch, the 60s in-flight floor above
                # would measure the original claim's age and a concurrent
                # sweeper could still steal this file mid-transition
                _touch(mine)
                doc = self._read(mine)
                if doc is None:
                    logger.warning("removing unreadable orphan claim %s", fname)
                    _remove_quiet(mine)
                    continue
                if self._settled(doc["tid"]):
                    # the interrupted transition already completed (its
                    # terminal doc exists): the claim is a leftover, not a
                    # lost trial — recovering it to NEW would re-run settled
                    # work and leave a duplicate doc behind
                    _remove_quiet(mine)
                    continue
                if kind == "cancel" or to_cancel:
                    target = JOB_STATE_CANCEL
                    doc.setdefault("result", {})
                    doc["result"]["status"] = "fail"
                    doc["refresh_time"] = coarse_utcnow()
                else:
                    target = JOB_STATE_NEW
                    doc["owner"] = None
                doc["state"] = target
                _atomic_write(self._path(target, doc["tid"]), pickle.dumps(doc))
                _remove_quiet(mine)
                self.metrics.counter("reclaims.orphan").inc()
                self.events.emit(TRIAL_RECLAIMED, doc["tid"],
                                 orphan_kind=kind, claim_age_sec=age,
                                 target=_STATE_DIRS[target])
                logger.warning(
                    "recovered orphaned %s claim for trial %s (%.0fs old) -> %s",
                    kind, doc["tid"], age, _STATE_DIRS[target])
                n += 1
        return n

    def cancel(self, tid):
        """Move one NEW or RUNNING doc to CANCEL (SparkTrials job-group
        cancellation analog).  The source file is renamed away FIRST (the
        atomic claim — same idiom as ``reserve``/``finish``), so a worker
        that finishes concurrently loses the rename race and drops its
        result instead of writing a duplicate doc.  Returns True if a doc
        was cancelled."""
        for state in (JOB_STATE_NEW, JOB_STATE_RUNNING):
            src = self._path(state, tid)
            claim = f"{src}.cancel.{_claim_suffix()}"
            try:
                os.rename(src, claim)
            except FileNotFoundError:
                continue
            _touch(claim)
            if self._settled(tid):
                # the claimed file was a zombie copy (an at-least-once
                # reclaim raced the transition that settled this trial):
                # nothing to cancel, and writing CANCEL would duplicate the
                # existing terminal doc
                _remove_quiet(claim)
                return False
            doc = self._read(claim)
            if doc is None:
                # do NOT delete: the read may have raced a partial write.
                # Leave the claim for _sweep_orphan_claims, which recovers
                # it (or removes it if truly unreadable) once aged —
                # removing here would permanently destroy the trial doc
                # (advisor finding, round 4).
                logger.warning(
                    "cancel(%s): claim unreadable, leaving %s for orphan sweep",
                    tid, os.path.basename(claim))
                continue
            doc["state"] = JOB_STATE_CANCEL
            doc.setdefault("result", {})
            doc["result"]["status"] = "fail"
            doc["refresh_time"] = coarse_utcnow()
            _atomic_write(self._path(JOB_STATE_CANCEL, tid), pickle.dumps(doc))
            _remove_quiet(claim)
            self.metrics.counter("cancels").inc()
            self.events.emit(TRIAL_CANCELLED, tid,
                             from_state=_STATE_DIRS[state])
            return True
        return False

    # -- store hygiene (ISSUE 15: the space-pressure degrade rung) ---------

    def gc(self, tmp_max_age=300.0, flight_max_age=7 * 86400.0):
        """Bounded garbage collection: reclaim bytes that are provably
        redundant without touching any live trial state.

        * ``new``/``running`` copies SUPERSEDED by a terminal doc (the
          tell path settles NEW→DONE and drops them eagerly, but a
          crash between the write and the drop leaves them for state
          precedence to hide forever);
        * precedence-loser terminal duplicates
          (:meth:`_prune_terminal_duplicates`);
        * ``*.tmp.*`` atomic-write leftovers of dead writers, once
          older than ``tmp_max_age`` (a LIVE write's tmp file exists
          for milliseconds);
        * flight-recorder crash dumps older than ``flight_max_age``
          (forensics age out; ``*.quarantined`` evidence never does).

        Returns ``{reclaimed_bytes, removed}``.  Every removal is
        tolerant of concurrent writers — losing a race to a path that
        vanished is a no-op, exactly like the claim machinery."""
        stats = {"reclaimed_bytes": 0, "removed": 0}

        def rm(path):
            try:
                size = os.path.getsize(path)
                os.remove(path)
            except OSError:
                return
            stats["removed"] += 1
            stats["reclaimed_bytes"] += size

        now = time.time()
        self._prune_terminal_duplicates()
        for state in (JOB_STATE_NEW, JOB_STATE_RUNNING):
            d = os.path.join(self.root, _STATE_DIRS[state])
            for fname in os.listdir(d):
                if fname.endswith(".pkl") and self._settled(fname[:-4]):
                    rm(os.path.join(d, fname))
        for d in ("attachments", *_STATE_DIRS.values()):
            dirpath = os.path.join(self.root, d)
            for fname in os.listdir(dirpath):
                if ".tmp." not in fname:
                    continue
                path = os.path.join(dirpath, fname)
                try:
                    if now - os.path.getmtime(path) > tmp_max_age:
                        rm(path)
                except OSError:
                    continue
        att = os.path.join(self.root, "attachments")
        for fname in os.listdir(att):
            if (fname.startswith(_FLIGHT_PREFIX)
                    and fname.endswith(".jsonl")):
                path = os.path.join(att, fname)
                try:
                    if now - os.path.getmtime(path) > flight_max_age:
                        rm(path)
                except OSError:
                    continue
        if stats["removed"]:
            self.metrics.counter("gc.removed").inc(stats["removed"])
            self.metrics.counter("gc.reclaimed_bytes").inc(
                stats["reclaimed_bytes"])
        return stats


class FileTrials(Trials):
    """Driver-side Trials over a FileStore (mongoexp.py sym: MongoTrials).

    ``asynchronous=True``: the driver inserts NEW docs and polls; separate
    worker *processes* (``hyperopt-tpu-worker``) evaluate them.  Docs are
    updated in place on refresh so the incremental padded-history fold (and
    its out-of-order pending set) keeps working across process boundaries.
    """

    asynchronous = True
    poll_interval_secs = 0.1

    def __init__(self, root, exp_key=None, refresh=True):
        self.store = FileStore(root)
        self._docs_by_tid = {}
        super().__init__(exp_key=exp_key, refresh=refresh)

    @property
    def attachments(self):
        return _StoreAttachments(self.store)

    @attachments.setter
    def attachments(self, value):
        for k, v in dict(value).items():
            self.store.set_attachment(k, _to_bytes(v))

    def refresh(self):
        for doc in self.store.load_all():
            mine = self._docs_by_tid.get(doc["tid"])
            if mine is None:
                self._docs_by_tid[doc["tid"]] = doc
                self._dynamic_trials.append(doc)
            elif doc["state"] != mine["state"] or doc["state"] == JOB_STATE_RUNNING:
                mine.update(doc)  # in place: history folding tracks identity
        super().refresh()

    def insert_trial_doc(self, doc):
        doc = dict(doc)
        self.store.write_doc(doc)
        if doc["tid"] not in self._docs_by_tid:
            self._docs_by_tid[doc["tid"]] = doc
            self._dynamic_trials.append(doc)
        return doc["tid"]

    def insert_trial_docs(self, docs):
        return [self.insert_trial_doc(d) for d in docs]

    def new_trial_ids(self, n):
        return self.store.new_trial_ids(n)

    def count_by_state_unsynced(self, arg):
        return self.store.count(arg)

    def checkpoint_trial(self, doc):
        """Ctrl.checkpoint hook: write the RUNNING doc (with its partial
        result) through to the store, so a worker crash after a checkpoint
        loses only the work since that checkpoint (MongoCtrl.checkpoint
        analog).  Reuses the heartbeat write path: atomic, skipped if the
        trial was cancelled/finished meanwhile."""
        self.store.heartbeat(doc)

    def cancel_unfinished(self):
        """NEW/RUNNING → CANCEL in the store (FMinIter calls this when its
        timeout expires so a dead/hung worker can't wedge the driver)."""
        for state in (JOB_STATE_NEW, JOB_STATE_RUNNING):
            d = os.path.join(self.store.root, _STATE_DIRS[state])
            for fname in os.listdir(d):
                if fname.endswith(".pkl"):
                    self.store.cancel(int(fname[:-4]))
        self.refresh()

    def delete_all(self):
        import shutil

        shutil.rmtree(self.store.root)
        self.store = FileStore(self.store.root)
        self._docs_by_tid = {}
        self._dynamic_trials = []
        self._ids = set()
        self._history = None
        self._history_synced = 0
        self._history_pending = []
        self.refresh()

    def __getstate__(self):
        state = super().__getstate__()
        state.pop("attachments", None)  # lives in the store, not the pickle
        return state


def _to_bytes(v):
    if isinstance(v, (bytes, bytearray)):
        return bytes(v)
    import cloudpickle

    return cloudpickle.dumps(v)


class _StoreAttachments:
    """Dict-like view over the store's attachment blobs (GridFS analog)."""

    def __init__(self, store):
        self._store = store

    def __contains__(self, k):
        return self._store.get_attachment(k) is not None

    def __getitem__(self, k):
        blob = self._store.get_attachment(k)
        if blob is None:
            raise KeyError(k)
        return blob

    def get(self, k, default=None):
        blob = self._store.get_attachment(k)
        return default if blob is None else blob

    def __setitem__(self, k, v):
        self._store.set_attachment(k, _to_bytes(v))

    def keys(self):
        return self._store.attachment_names()
