"""hyperopt_tpu — a TPU-native hyperparameter-optimization framework.

A ground-up JAX/XLA rebuild of the capabilities of hyperopt
(reference: pminervini/hyperopt; see SURVEY.md): ``fmin``, the ``hp.*``
search-space language including conditional ``hp.choice`` spaces, the
``Trials`` store, and the random / TPE / annealing suggesters behind the
``algo=`` plugin boundary — with search spaces compiled to jitted samplers,
device-resident trial history, and the TPE hot path running as vmapped /
mesh-sharded XLA kernels.

Public surface matches ``hyperopt/__init__.py`` (sym: fmin, tpe, rand,
anneal, mix, hp, Trials, trials_from_docs, space_eval, STATUS_*,
JOB_STATE_*), so ``from hyperopt_tpu import fmin, hp, tpe, Trials`` — the
canonical reference idiom — works unchanged.
"""

from . import early_stop, graphviz, hp, obs, pyll, spaces
from .algos import rand
from .base import (
    JOB_STATE_CANCEL,
    JOB_STATE_DONE,
    JOB_STATE_ERROR,
    JOB_STATE_NEW,
    JOB_STATE_RUNNING,
    JOB_STATES,
    STATUS_FAIL,
    STATUS_NEW,
    STATUS_OK,
    STATUS_RUNNING,
    STATUS_STRINGS,
    STATUS_SUSPENDED,
    Ctrl,
    Domain,
    Trials,
    trials_from_docs,
)
from .exceptions import (
    AllTrialsFailed,
    DuplicateLabel,
    InvalidAnnotatedParameter,
    InvalidLoss,
    InvalidResultStatus,
    InvalidTrial,
)
from .fmin import FMinIter, fmin, fmin_pass_expr_memo_ctrl, generate_trials_to_calculate
from .spaces import space_eval

# device_fmin needs algos.tpe; keep the partial-checkout guard intact (the
# name is simply absent — not None — when tpe.py is missing)
try:
    from .device_fmin import fmin_device
except ModuleNotFoundError as _e:  # pragma: no cover
    if _e.name != "hyperopt_tpu.algos.tpe":
        raise

# Algo modules that may land incrementally are re-exported only when present,
# so `from hyperopt_tpu import anneal` fails at the import site (ImportError)
# rather than binding None and failing later at `anneal.suggest`.
from . import algos as _algos

_optional_algos = [
    _name
    for _name in ("tpe", "anneal", "mix", "atpe")
    if hasattr(_algos, _name)
]
for _name in _optional_algos:
    globals()[_name] = getattr(_algos, _name)

__version__ = "0.2.0"

__all__ = [
    "hp",
    "spaces",
    "pyll",
    "graphviz",
    "early_stop",
    "obs",
    "fmin",
    "FMinIter",
    "fmin_pass_expr_memo_ctrl",
    "generate_trials_to_calculate",
    "space_eval",
    "rand",
    "Trials",
    "trials_from_docs",
    "Ctrl",
    "Domain",
    "JOB_STATE_NEW",
    "JOB_STATE_RUNNING",
    "JOB_STATE_DONE",
    "JOB_STATE_ERROR",
    "JOB_STATE_CANCEL",
    "JOB_STATES",
    "STATUS_NEW",
    "STATUS_RUNNING",
    "STATUS_SUSPENDED",
    "STATUS_OK",
    "STATUS_FAIL",
    "STATUS_STRINGS",
    "AllTrialsFailed",
    "DuplicateLabel",
    "InvalidAnnotatedParameter",
    "InvalidLoss",
    "InvalidResultStatus",
    "InvalidTrial",
    "__version__",
] + _optional_algos + (["fmin_device"] if "fmin_device" in globals() else [])
