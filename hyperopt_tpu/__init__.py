"""hyperopt_tpu — a TPU-native hyperparameter-optimization framework.

A ground-up JAX/XLA rebuild of the capabilities of hyperopt
(reference: pminervini/hyperopt; see SURVEY.md): ``fmin``, the ``hp.*``
search-space language including conditional ``hp.choice`` spaces, the
``Trials`` store, and the random / TPE / annealing suggesters behind the
``algo=`` plugin boundary — with search spaces compiled to jitted samplers,
device-resident trial history, and the TPE hot path running as vmapped /
mesh-sharded XLA kernels.
"""

from . import hp, spaces
from .exceptions import (
    AllTrialsFailed,
    DuplicateLabel,
    InvalidAnnotatedParameter,
    InvalidLoss,
    InvalidResultStatus,
    InvalidTrial,
)
from .spaces import space_eval

__version__ = "0.1.0"

__all__ = [
    "hp",
    "spaces",
    "space_eval",
    "AllTrialsFailed",
    "DuplicateLabel",
    "InvalidAnnotatedParameter",
    "InvalidLoss",
    "InvalidResultStatus",
    "InvalidTrial",
    "__version__",
]
