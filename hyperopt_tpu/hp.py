"""User-facing ``hp.*`` search-space constructors.

Parity target: ``hyperopt/hp.py`` + ``hyperopt/pyll_utils.py`` (sym:
hp_choice, hp_pchoice, hp_randint, hp_uniform, hp_quniform, hp_uniformint,
hp_loguniform, hp_qloguniform, hp_normal, hp_qnormal, hp_lognormal,
hp_qlognormal, validate_label).

Semantics (matching the reference's stochastic nodes):

* ``uniform(label, low, high)`` — float in [low, high].
* ``quniform(label, low, high, q)`` — ``round(uniform/q)*q``.
* ``uniformint(label, low, high)`` — integer in [low, high] inclusive.
* ``loguniform(label, low, high)`` — ``exp(uniform(low, high))``; low/high are
  bounds of the *log* of the return value.
* ``normal/lognormal`` — mu/sigma of the (underlying) normal.
* ``randint(label, upper)`` or ``randint(label, low, high)`` — int in [0,upper)
  / [low, high).
* ``choice(label, options)`` — one of options; trial value is the index.
* ``pchoice(label, [(p, option), ...])`` — weighted choice.
"""

from __future__ import annotations

import numpy as np

from .exceptions import InvalidAnnotatedParameter
from .spaces import Choice, Dist, Param, as_expr

__all__ = [
    "choice",
    "pchoice",
    "randint",
    "uniform",
    "quniform",
    "uniformint",
    "loguniform",
    "qloguniform",
    "normal",
    "qnormal",
    "lognormal",
    "qlognormal",
]


def _validate_label(label):
    if not isinstance(label, str):
        raise InvalidAnnotatedParameter(f"label must be a string, got {label!r}")
    return label


def _f(x, name, label):
    try:
        return float(x)
    except (TypeError, ValueError):
        raise InvalidAnnotatedParameter(f"{name} for {label!r} must be numeric, got {x!r}")


def choice(label, options):
    _validate_label(label)
    options = list(options)
    if len(options) == 0:
        raise InvalidAnnotatedParameter(f"choice {label!r} needs at least one option")
    return Choice(label, tuple(as_expr(o) for o in options))


def pchoice(label, p_options):
    _validate_label(label)
    ps, options = [], []
    for pair in p_options:
        try:
            p, opt = pair
        except (TypeError, ValueError):
            raise InvalidAnnotatedParameter(
                f"pchoice {label!r} expects (probability, option) pairs, got {pair!r}"
            )
        ps.append(_f(p, "probability", label))
        options.append(opt)
    total = float(np.sum(ps))
    if not np.isclose(total, 1.0, atol=1e-6):
        raise InvalidAnnotatedParameter(
            f"pchoice {label!r} probabilities sum to {total}, expected 1.0"
        )
    return Choice(label, tuple(as_expr(o) for o in options), p=tuple(ps))


def randint(label, *args):
    _validate_label(label)
    if len(args) == 1:
        low, high = 0.0, _f(args[0], "upper", label)
    elif len(args) == 2:
        low, high = _f(args[0], "low", label), _f(args[1], "high", label)
    else:
        raise InvalidAnnotatedParameter(f"randint {label!r} takes (upper) or (low, high)")
    if high <= low:
        raise InvalidAnnotatedParameter(f"randint {label!r}: empty range [{low}, {high})")
    return Param(label, Dist("randint", (low, high)), cast="int")


def uniform(label, low, high):
    _validate_label(label)
    return Param(label, Dist("uniform", (_f(low, "low", label), _f(high, "high", label))))


def quniform(label, low, high, q):
    _validate_label(label)
    return Param(
        label,
        Dist("quniform", (_f(low, "low", label), _f(high, "high", label), _f(q, "q", label))),
    )


def uniformint(label, low, high, q=1):
    _validate_label(label)
    if _f(q, "q", label) != 1:
        raise InvalidAnnotatedParameter(f"uniformint {label!r} only supports q=1")
    return Param(
        label, Dist("uniformint", (_f(low, "low", label), _f(high, "high", label))), cast="int"
    )


def loguniform(label, low, high):
    _validate_label(label)
    return Param(label, Dist("loguniform", (_f(low, "low", label), _f(high, "high", label))))


def qloguniform(label, low, high, q):
    _validate_label(label)
    return Param(
        label,
        Dist("qloguniform", (_f(low, "low", label), _f(high, "high", label), _f(q, "q", label))),
    )


def normal(label, mu, sigma):
    _validate_label(label)
    return Param(label, Dist("normal", (_f(mu, "mu", label), _f(sigma, "sigma", label))))


def qnormal(label, mu, sigma, q):
    _validate_label(label)
    return Param(
        label,
        Dist("qnormal", (_f(mu, "mu", label), _f(sigma, "sigma", label), _f(q, "q", label))),
    )


def lognormal(label, mu, sigma):
    _validate_label(label)
    return Param(label, Dist("lognormal", (_f(mu, "mu", label), _f(sigma, "sigma", label))))


def qlognormal(label, mu, sigma, q):
    _validate_label(label)
    return Param(
        label,
        Dist("qlognormal", (_f(mu, "mu", label), _f(sigma, "sigma", label), _f(q, "q", label))),
    )
