"""Early-stopping callbacks (hyperopt/early_stop.py sym: no_progress_loss)."""

from __future__ import annotations

import logging

logger = logging.getLogger(__name__)

__all__ = ["no_progress_loss"]


def no_progress_loss(iteration_stop_count=20, percent_increase=0.0):
    """Stop when the best loss has not improved by more than
    ``percent_increase`` percent for ``iteration_stop_count`` iterations.

    Returns a closure suitable for ``fmin(early_stop_fn=...)``; the closure's
    extra positional args thread state between calls, exactly as the
    reference's does.
    """

    def stop_fn(trials, best_loss=None, iteration_no_progress=0):
        new_loss = trials.trials[len(trials.trials) - 1]["result"].get("loss")
        if new_loss is None:
            return False, [best_loss, iteration_no_progress + 1]
        if best_loss is None:
            return False, [new_loss, 0]
        best_loss_threshold = best_loss - abs(best_loss * (percent_increase / 100.0))
        if new_loss < best_loss_threshold:
            best_loss = new_loss
            iteration_no_progress = 0
        else:
            iteration_no_progress += 1
            logger.debug(
                "No progress made: %d iteration on %d. best_loss=%.2f, best_loss_threshold=%.2f, new_loss=%.2f",
                iteration_no_progress,
                iteration_stop_count,
                best_loss if best_loss is not None else float("nan"),
                best_loss_threshold,
                new_loss,
            )
        return iteration_no_progress >= iteration_stop_count, [best_loss, iteration_no_progress]

    return stop_fn
