"""Benchmark: jitted TPE proposal throughput vs the NumPy reference path.

Run by the driver on real TPU hardware with the ambient env.  Prints exactly
ONE JSON line on stdout:

    {"metric": "...", "value": N, "unit": "...", "vs_baseline": N, "backend": "..."}

``vs_baseline`` is the speedup of the jitted candidate-proposal path over a
faithful NumPy reimplementation of the reference hot loop
(``hyperopt/tpe.py`` sym: adaptive_parzen_normal, GMM1 with
rejection-resampling truncation, GMM1_lpdf, broadcast_best) on the same
observation history.  BASELINE.md's north-star target is >=1000x.

Supplementary measurements (Branin fmin wall-clock, per-config details) go
to stderr as human-readable JSON.

Robustness contract (round-3 postmortem): the ambient TPU backend (a
tunneled PJRT plugin) can be broken or HUNG on any given day, and a hang
inside backend init is uncatchable in-process.  Therefore the parent
process NEVER initializes a jax backend.  It (1) measures the NumPy
baseline in-process, (2) probes the ambient backend in a timeout-guarded
subprocess, (3) runs every jax stage in a subprocess that streams one JSON
line per completed stage (so a late hang preserves earlier results),
(4) falls back to a forced-CPU subprocess for stages the ambient attempt
did not produce, and (5) ALWAYS prints the final metric line, tagged with
the backend that produced it ("tpu", "cpu-fallback", or "none").
"""

from __future__ import annotations

import json
import math
import os
import subprocess
import sys
import time

import numpy as np

# ---------------------------------------------------------------------------
# NumPy reference-equivalent TPE hot path (the baseline being beaten).
# Faithful to hyperopt/tpe.py's implementation strategy: python/numpy mix,
# per-sample rejection-resampling loop for truncated GMM draws.
# ---------------------------------------------------------------------------


def np_linear_forgetting_weights(N, LF):
    if N < LF:
        return np.ones(N)
    ramp = np.linspace(1.0 / N, 1.0, num=N - LF) if N - LF > 0 else np.zeros(0)
    return np.concatenate([ramp, np.ones(LF)])


def np_adaptive_parzen_normal(mus, prior_weight, prior_mu, prior_sigma, LF=25):
    """hyperopt/tpe.py sym: adaptive_parzen_normal (numpy, variable length)."""
    mus = np.asarray(mus, dtype=float)
    order = np.argsort(mus)
    prior_pos = int(np.searchsorted(mus[order], prior_mu))
    srtd_mus = np.insert(mus[order], prior_pos, prior_mu)
    m = len(srtd_mus)
    sigma = np.zeros(m)
    if m == 1:
        sigma[:] = prior_sigma
    else:
        sigma[1:-1] = np.maximum(srtd_mus[1:-1] - srtd_mus[:-2],
                                 srtd_mus[2:] - srtd_mus[1:-1])
        sigma[0] = srtd_mus[1] - srtd_mus[0]
        sigma[-1] = srtd_mus[-1] - srtd_mus[-2]
    maxsigma = prior_sigma
    minsigma = prior_sigma / min(100.0, 1.0 + m)
    sigma = np.clip(sigma, minsigma, maxsigma)
    sigma[prior_pos] = prior_sigma
    weights = np_linear_forgetting_weights(len(mus), LF)[order]
    weights = np.insert(weights, prior_pos, prior_weight)
    weights = weights / weights.sum()
    return weights, srtd_mus, sigma


def np_gmm1(rng, weights, mus, sigmas, low, high, size):
    """hyperopt/tpe.py sym: GMM1 — truncation by per-sample rejection."""
    samples = []
    while len(samples) < size:
        active = np.argmax(rng.multinomial(1, weights))
        draw = rng.normal(loc=mus[active], scale=sigmas[active])
        if low <= draw < high:
            samples.append(draw)
    return np.asarray(samples)


def np_normal_cdf(x, mu, sigma):
    from scipy.special import erf

    return 0.5 * (1.0 + erf((np.asarray(x)[..., None] - mu) / (np.sqrt(2) * sigma)))


def np_gmm1_lpdf(x, weights, mus, sigmas, low, high):
    """hyperopt/tpe.py sym: GMM1_lpdf."""
    p_accept = np.sum(weights * (
        0.5 * (1 + np.vectorize(math.erf)((high - mus) / (np.sqrt(2) * sigmas)))
        - 0.5 * (1 + np.vectorize(math.erf)((low - mus) / (np.sqrt(2) * sigmas)))
    ))
    x = np.asarray(x)[:, None]
    comp = (
        np.log(weights)
        - 0.5 * ((x - mus) / sigmas) ** 2
        - np.log(sigmas)
        - 0.5 * np.log(2 * np.pi)
    )
    mx = comp.max(axis=1, keepdims=True)
    lpdf = mx[:, 0] + np.log(np.sum(np.exp(comp - mx), axis=1))
    return lpdf - np.log(p_accept)


def np_tpe_propose(rng, obs_below, obs_above, low, high, n_cand,
                   prior_weight=1.0, LF=25):
    """One reference-equivalent proposal for one hp.uniform parameter."""
    prior_mu, prior_sigma = 0.5 * (low + high), high - low
    wb, mb, sb = np_adaptive_parzen_normal(obs_below, prior_weight, prior_mu, prior_sigma, LF)
    wa, ma, sa = np_adaptive_parzen_normal(obs_above, prior_weight, prior_mu, prior_sigma, LF)
    samples = np_gmm1(rng, wb, mb, sb, low, high, n_cand)
    ll_b = np_gmm1_lpdf(samples, wb, mb, sb, low, high)
    ll_a = np_gmm1_lpdf(samples, wa, ma, sa, low, high)
    return samples[np.argmax(ll_b - ll_a)]


# ---------------------------------------------------------------------------
# measurement
# ---------------------------------------------------------------------------


def bench_numpy(n_obs=60, n_cand=24, repeats=20, blocks=5, seed=0):
    """Best-of-``blocks`` timing: the numpy path is short enough that OS
    scheduling noise dominates a single block (observed 2x swings between
    runs); the fastest block is the honest baseline — overstating the
    baseline can only shrink the reported speedup."""
    rng = np.random.default_rng(seed)
    losses = rng.normal(size=n_obs)
    vals = rng.uniform(-5, 5, size=n_obs)
    n_below = min(int(np.ceil(0.25 * np.sqrt(n_obs))), 25)
    order = np.argsort(losses)
    obs_below = vals[order[:n_below]]
    obs_above = vals[order[n_below:]]
    # warmup
    np_tpe_propose(rng, obs_below, obs_above, -5.0, 5.0, n_cand)
    best = float("inf")
    for _ in range(blocks):
        t0 = time.perf_counter()
        for _ in range(repeats):
            np_tpe_propose(rng, obs_below, obs_above, -5.0, 5.0, n_cand)
        best = min(best, (time.perf_counter() - t0) / repeats)
    dt = best
    return {"proposals_per_sec": 1.0 / dt, "candidates_per_sec": n_cand / dt,
            "n_obs": n_obs, "n_cand": n_cand, "sec_per_proposal": dt}


def bench_jax(n_obs=60, n_cand=8192, repeats=50, seed=0, n_params=1, batch=None):
    """Measure the jitted proposal path.

    ``batch``: propose for this many trial ids per dispatch (vmap over keys) —
    the framework's parallel-suggest design point (BASELINE config #5: 10k
    parallel trials).  ``None`` = single proposal per dispatch, the
    reference-shaped workload.
    """
    import jax
    import jax.numpy as jnp

    from hyperopt_tpu import hp
    from hyperopt_tpu.spaces import compile_space
    from hyperopt_tpu.algos import tpe

    if n_params == 1:
        space = {"x": hp.uniform("x", -5, 5)}
    else:
        space = {f"x{i}": hp.uniform(f"x{i}", -5, 5) for i in range(n_params)}
    cs = compile_space(space)
    cfg = {"prior_weight": 1.0, "n_EI_candidates": n_cand, "gamma": 0.25, "LF": 25}
    propose_one = tpe.build_propose(cs, cfg)

    # key derivation happens in-trace (an iteration index is the only input),
    # exactly like the framework's fused suggest kernel: one dispatch per
    # proposal, no host-side PRNGKey/fold_in round trips
    if batch:

        def run(hist, i):
            k = jax.random.fold_in(jax.random.PRNGKey(0), i)
            keys = jax.vmap(lambda j: jax.random.fold_in(k, j))(
                jnp.arange(batch, dtype=jnp.uint32)
            )
            return jax.vmap(propose_one, in_axes=(None, 0))(hist, keys)

    else:

        def run(hist, i):
            return propose_one(hist, jax.random.fold_in(jax.random.PRNGKey(0), i))

    propose = jax.jit(run)
    t_stage0 = time.perf_counter()

    cap = 64
    while cap < n_obs:
        cap *= 2
    rng = np.random.default_rng(seed)
    losses = np.full(cap, np.inf, np.float32)
    has = np.zeros(cap, bool)
    losses[:n_obs] = rng.normal(size=n_obs)
    has[:n_obs] = True
    hist = {
        "losses": jnp.asarray(losses),
        "has_loss": jnp.asarray(has),
        "vals": {l: jnp.asarray(
            np.where(has, rng.uniform(-5, 5, size=cap), 0).astype(np.float32))
            for l in cs.labels},
        "active": {l: jnp.asarray(has) for l in cs.labels},
    }
    def force(o):
        # fetch one leaf to host: device streams execute in order, so this
        # proves every queued dispatch completed.  (block_until_ready alone
        # is not trustworthy on every remote PJRT transport — round 2's
        # headline number was inflated by exactly that.)
        return np.asarray(jax.tree.leaves(o)[0])

    out = propose(hist, np.uint32(0))  # compile
    force(out)
    # best-of-3 timing blocks (same policy as the numpy baseline): transient
    # contention on a shared tunneled chip swung single-block numbers ±40%
    # between rounds.  Each block keeps the strict force() readback.
    dt = float("inf")
    exec_total = 0.0
    for _ in range(3):
        t0 = time.perf_counter()
        for i in range(repeats):
            out = propose(hist, np.uint32(i))
        force(out)
        block = time.perf_counter() - t0
        exec_total += block
        dt = min(dt, block / repeats)
    eff = n_cand * n_params * (batch or 1)
    # device utilization: achieved FLOP/s against the program's static cost,
    # and the share of the stage's wall clock spent inside dispatch→readback
    # round trips (busy fraction; the complement is compile + setup).  The
    # cost table needs an AOT Compiled handle; that lowering happens AFTER
    # the timed loop and stage-wall capture, so the timed code path (the
    # jitted callable, same as every previous round) and the utilization
    # numbers are both untouched by the measurement itself.
    stage_wall = time.perf_counter() - t_stage0
    from hyperopt_tpu.obs.health import cost_analysis_summary

    cost = None
    try:
        cost = cost_analysis_summary(
            propose.lower(hist, np.uint32(0)).compile())
    except Exception:
        pass
    util = {"busy_fraction": min(1.0, exec_total / stage_wall)}
    if cost:
        util.update(
            flops_per_dispatch=cost["flops"],
            bytes_per_dispatch=cost["bytes"],
            achieved_flops_per_sec=cost["flops"] / dt,
            arithmetic_intensity=(cost["flops"] / cost["bytes"]
                                  if cost["bytes"] else None),
        )
    return {"proposals_per_sec": (batch or 1) / dt,
            "candidates_per_sec": eff / dt,
            "n_obs": n_obs, "n_cand": n_cand, "n_params": n_params,
            "batch": batch or 1, "sec_per_dispatch": dt,
            "device_utilization": util,
            "backend": jax.devices()[0].platform}


def _obs_device_snapshot(wall_sec=None):
    """Compact compile/execute/cache-rate summary from the process-global
    "device" metrics namespace (hyperopt_tpu/obs/) — attached to stage
    results so BENCH_*.json tracks the perf BREAKDOWN, not just the
    headline throughput.  With the stage's ``wall_sec``, adds the
    device-utilization join (achieved FLOP/s, busy fraction) from
    obs/health.py."""
    from hyperopt_tpu.obs import get_metrics
    from hyperopt_tpu.obs.health import utilization_snapshot

    dev = get_metrics("device").snapshot()["metrics"]

    def hist(name):
        h = dev.get(name)
        return {"sum_sec": h["sum"], "count": h["count"]} if h else None

    hits = dev.get("run_cache.hits", 0)
    misses = dev.get("run_cache.misses", 0)
    return {
        "whole_run_compile": hist("whole_run.compile_sec"),
        "whole_run_execute": hist("whole_run.execute_sec"),
        "chunk_compile": hist("chunk.compile_sec"),
        "chunk_execute": hist("chunk.execute_sec"),
        "run_cache_hit_rate": hits / max(1, hits + misses),
        "utilization": utilization_snapshot(wall_sec=wall_sec),
    }


def bench_branin_device(max_evals=1000, seeds=(1, 2, 3, 4)):
    """BASELINE north star: Branin to loss<0.40 in <1s on one chip, via the
    fully on-device lax.scan fmin.  gamma/LF widened beyond the reference
    defaults — TPU-scale candidate counts make the exploit-heavier split
    free (reference cannot afford it)."""
    from hyperopt_tpu.device_fmin import fmin_device
    from hyperopt_tpu.zoo import ZOO

    dom = ZOO["branin"]
    kw = dict(max_evals=max_evals, gamma=2.0, linear_forgetting=100,
              n_EI_candidates=128)
    t_stage0 = time.perf_counter()
    fmin_device(dom.objective, dom.space, seed=0, **kw)  # compile
    losses, walls = [], []
    for s in seeds:
        t0 = time.perf_counter()
        _, loss = fmin_device(dom.objective, dom.space, seed=s, **kw)
        walls.append(time.perf_counter() - t0)
        losses.append(loss)
    return {"best_losses": losses, "wall_clock_sec_max": max(walls),
            "wall_clock_sec_mean": sum(walls) / len(walls),
            "max_evals": max_evals,
            "target": "loss<0.40 in <1s",
            "obs": _obs_device_snapshot(
                wall_sec=time.perf_counter() - t_stage0)}


def _host_branin(d):
    """Branin in pure host math: the interactive-loop bench measures the
    ask→tell suggest path; a jnp objective would add per-op accelerator
    dispatches (expensive over a tunnel) that are not part of that path —
    the reference's objectives run host-side numpy too."""
    x, y = d["x"], d["y"]
    b = 5.1 / (4.0 * math.pi**2)
    c = 5.0 / math.pi
    t = 1.0 / (8.0 * math.pi)
    return (y - b * x**2 + c * x - 6.0) ** 2 + 10.0 * (1 - t) * math.cos(x) + 10.0


def bench_branin_fmin(max_evals=100, seed=0, queues=(1, 4)):
    """The interactive host ask→tell loop (one fused tell+ask device program
    + one packed readback per iteration).  Measured cold (includes jit
    compile; persistent cache may absorb it) and warm, at queue depth 1
    (reference-default semantics) and 4 (posterior ≤3 trials stale)."""
    from hyperopt_tpu import Trials, hp, fmin
    from hyperopt_tpu.algos import tpe

    space = {"x": hp.uniform("x", -5, 10), "y": hp.uniform("y", 0, 15)}
    out = {}
    t_stage0 = time.perf_counter()
    for ql in queues:
        runs = []
        for attempt in ("cold", "warm"):
            t0 = time.perf_counter()
            trials = Trials()
            fmin(_host_branin, space, algo=tpe.suggest, max_evals=max_evals,
                 trials=trials, max_queue_len=ql,
                 rstate=np.random.default_rng(seed), show_progressbar=False)
            dt = time.perf_counter() - t0
            best = min(l for l in trials.losses() if l is not None)
            runs.append({"attempt": attempt, "wall_clock_sec": dt, "best_loss": best})
        out[f"queue_{ql}"] = runs

    # the high-latency-link mitigation (round-5 verdict #9): SAME queue-1
    # fresh-posterior-per-trial semantics, but the ask->tell dependency
    # chain runs on device in chunks of 10 (fmin(device_loop=True)) — one
    # tunnel round trip per 10 trials instead of per trial.  Uses the
    # traceable zoo objective (the host-math objective above cannot trace,
    # which is exactly the boundary the mitigation documents).
    from hyperopt_tpu.zoo import ZOO

    dom = ZOO["branin"]
    runs = []
    for attempt in ("cold", "warm"):
        t0 = time.perf_counter()
        trials = Trials()
        fmin(dom.objective, dom.space, algo=tpe.suggest, max_evals=max_evals,
             trials=trials, device_loop=True,
             rstate=np.random.default_rng(seed), show_progressbar=False)
        dt = time.perf_counter() - t0
        best = min(l for l in trials.losses() if l is not None)
        runs.append({"attempt": attempt, "wall_clock_sec": dt, "best_loss": best})
    out["queue_1_device_loop"] = runs
    out["max_evals"] = max_evals
    # per-phase breakdown of the host loop (suggest vs evaluate vs refresh)
    # plus the device-loop compile/execute split — the measurement substrate
    # later perf PRs diff against
    out["obs"] = {"phase_timings": trials.phase_timings.summary(),
                  **_obs_device_snapshot(
                      wall_sec=time.perf_counter() - t_stage0)}
    return out


def bench_flight_overhead(max_evals=60, repeats=3, seed=0):
    """Forensics acceptance bar (ISSUE 3): the always-on flight recorder
    must keep the DISARMED host ``fmin`` loop inside the established <2%
    overhead bar.  Runs the same warm TPE fmin the ``branin_fmin_tpe``
    headline measures — once with the ring disabled, once enabled — and
    attaches the before/after delta to the bench artifacts, so the bar is
    re-measured (not asserted) every round.  A rand-suggest variant rides
    along as the adversarial worst case: its per-trial work is minimal, so
    it puts the tightest honest bound on the absolute per-trial cost."""
    from hyperopt_tpu import Trials, fmin, hp
    from hyperopt_tpu.algos import rand, tpe
    from hyperopt_tpu.obs.flight import get_flight

    space = {"x": hp.uniform("x", -5, 10), "y": hp.uniform("y", 0, 15)}

    def once(algo):
        t0 = time.perf_counter()
        fmin(_host_branin, space, algo=algo, max_evals=max_evals,
             trials=Trials(), rstate=np.random.default_rng(seed),
             show_progressbar=False)
        return time.perf_counter() - t0

    fr = get_flight()
    was_enabled = fr.enabled
    out = {"max_evals": max_evals, "repeats": repeats,
           "bar": "<2% disarmed fmin overhead (tpe loop)"}
    try:
        for name, algo in (("tpe", tpe.suggest), ("rand", rand.suggest)):
            once(algo)  # warm: jit/space compile shared by both sides
            stage = {}
            for label, enabled in (("flight_off", False),
                                   ("flight_on", True)):
                fr.enabled = enabled
                stage[f"{label}_sec"] = min(
                    once(algo) for _ in range(repeats))
            stage["overhead_frac"] = (
                (stage["flight_on_sec"] - stage["flight_off_sec"])
                / max(stage["flight_off_sec"], 1e-9))
            out[name] = stage
    finally:
        fr.enabled = was_enabled
    # the headline delta is the representative loop's
    out["flight_off_sec"] = out["tpe"]["flight_off_sec"]
    out["flight_on_sec"] = out["tpe"]["flight_on_sec"]
    out["overhead_frac"] = out["tpe"]["overhead_frac"]
    return out


def bench_profiler_overhead(max_evals=60, repeats=3, seed=0):
    """Capture-plane acceptance bar (ISSUE 7): an ARMED-BUT-IDLE device
    profiler (``fmin(profile=<dir>)`` with no capture ever triggered) must
    cost ~nothing over the disarmed loop.  Armed runs pay one
    ``TraceAnnotation`` construction per fmin tick (a TraceMe that no-ops
    while no profiler session is active) — this stage re-measures that
    delta every round so the "annotations are free" claim is data, not
    assertion.  The on/off fractional delta rides the headline line as
    ``profiler_overhead_frac`` (gated absolute, lower-is-better, by
    scripts/bench_gate.py)."""
    import tempfile

    from hyperopt_tpu import Trials, fmin, hp
    from hyperopt_tpu.algos import tpe

    space = {"x": hp.uniform("x", -5, 10), "y": hp.uniform("y", 0, 15)}

    def once(profile):
        t0 = time.perf_counter()
        fmin(_host_branin, space, algo=tpe.suggest, max_evals=max_evals,
             trials=Trials(), rstate=np.random.default_rng(seed),
             show_progressbar=False, profile=profile)
        return time.perf_counter() - t0

    once(None)  # warm: jit/space compile shared by both sides
    out = {"max_evals": max_evals, "repeats": repeats,
           "bar": "armed-but-idle capture plane ~free vs off"}
    with tempfile.TemporaryDirectory() as d:
        out["profiler_off_sec"] = min(once(None) for _ in range(repeats))
        out["profiler_on_sec"] = min(once(d) for _ in range(repeats))
    out["profiler_overhead_frac"] = (
        (out["profiler_on_sec"] - out["profiler_off_sec"])
        / max(out["profiler_off_sec"], 1e-9))
    return out


def bench_trace_overhead(n_asks=40, repeats=3, seed=0):
    """Request-trace plane acceptance bar (ISSUE 11): parsing/minting/
    echoing trace context and stamping it on spans + WAL records must
    cost ~nothing per served ask.  Drives the REAL handler path
    (``ServiceHTTPServer.handle`` — route, admission, wave tick, doc
    build) with tracing armed (inbound ``traceparent`` on every request)
    vs disarmed, same seed, and reports the per-ask delta.  The
    fractional delta rides the headline as ``trace_overhead_frac``
    (gated absolute, lower-is-better, by scripts/bench_gate.py — the
    loose bar catches the plane growing a per-ask serialization or I/O
    cost, not scheduler noise)."""
    from hyperopt_tpu.service.scheduler import StudyScheduler
    from hyperopt_tpu.service.server import ServiceHTTPServer

    space_spec = {"x": {"dist": "uniform", "args": [-5, 10]},
                  "y": {"dist": "uniform", "args": [0, 15]}}
    tp = "00-4bf92f3577b34da6a3ce929d0e0e4736-00f067aa0ba902b7-01"

    def once(armed):
        srv = ServiceHTTPServer(0, scheduler=StudyScheduler(wal=False),
                                trace=armed, slo=armed)
        code, r = srv.handle("POST", "/study", {
            "space": space_spec, "seed": seed, "n_startup_jobs": 4})
        assert code == 200, r
        sid = r["study_id"]
        headers = {"traceparent": tp} if armed else None
        t0 = time.perf_counter()
        for i in range(n_asks):
            code, a = srv.handle("POST", "/ask", {"study_id": sid},
                                 headers=headers)
            assert code == 200, a
            code, _ = srv.handle("POST", "/tell", {
                "study_id": sid, "tid": a["trials"][0]["tid"],
                "loss": float(i % 7)})
            assert code == 200
        return time.perf_counter() - t0

    once(False)  # warm: the cohort jit cache is shared by both sides
    out = {"n_asks": n_asks, "repeats": repeats,
           "bar": "trace/SLO plane ~free per served ask"}
    out["trace_off_sec"] = min(once(False) for _ in range(repeats))
    out["trace_on_sec"] = min(once(True) for _ in range(repeats))
    out["trace_overhead_frac"] = (
        (out["trace_on_sec"] - out["trace_off_sec"])
        / max(out["trace_off_sec"], 1e-9))
    out["trace_overhead_us_per_ask"] = (
        (out["trace_on_sec"] - out["trace_off_sec"]) / n_asks * 1e6)
    return out


def bench_search_quality(n_studies=10, seed0=0):
    """The standing per-algo search-quality table (ISSUE 16): the zoo
    study mix run to budget under each algorithm (tpe / rand / anneal /
    mix / atpe), summarized per algo as ``trials_to_target_<algo>``
    (mean 1-based trial index of the first target-clearing loss; budget
    when unsolved), ``final_regret_<algo>`` (mean simple regret vs the
    known optimum at budget exhaustion) and ``solved_frac_<algo>``.

    These keys are the megakernel's quality bars: ROADMAP item 1's
    int8/fp8 history + fused Pallas scoring loop cannot be bit-exact-
    pinned against the f32 reference, so those PRs land against the
    windowed directional gates on THIS table instead (direction
    metadata in ``trajectory.KEY_DIRECTIONS``)."""
    from functools import partial

    from hyperopt_tpu import Trials, fmin
    from hyperopt_tpu.algos import anneal, atpe, mix, rand, tpe
    from hyperopt_tpu.obs.quality import summarize_run
    from hyperopt_tpu.zoo import make_study_mix

    items = make_study_mix(n_studies, seed0)
    # every item's tpe serving matches its mix-declared startup count,
    # so the table measures the posterior, not a startup-budget skew
    tpe5 = partial(tpe.suggest, n_startup_jobs=5)
    algos = {
        "tpe": tpe5,
        "rand": rand.suggest,
        "anneal": anneal.suggest,
        "mix": partial(mix.suggest,
                       p_suggest=[(0.25, rand.suggest), (0.75, tpe5)]),
        "atpe": atpe.suggest,
    }
    out = {"n_studies": len(items), "seed0": seed0,
           "bar": "tpe beats rand on trials-to-target over the zoo mix"}
    table = {}
    for name, algo in algos.items():
        t2t, regrets, solved = [], [], 0
        for m in items:
            t = Trials()
            fmin(m.domain.objective, m.domain.space, algo=algo,
                 max_evals=m.budget, trials=t,
                 rstate=np.random.default_rng(m.seed),
                 show_progressbar=False)
            s = summarize_run(t.losses(), m.budget,
                              loss_target=m.domain.loss_target,
                              optimum=m.domain.optimum)
            t2t.append(s["trials_to_target"])
            solved += 1 if s["solved"] else 0
            if s["final_regret"] is not None:
                regrets.append(s["final_regret"])
        table[name] = {
            "trials_to_target": float(np.mean(t2t)),
            "final_regret": (float(np.mean(regrets))
                             if regrets else None),
            "solved_frac": solved / len(items),
        }
        out[f"trials_to_target_{name}"] = table[name]["trials_to_target"]
        if table[name]["final_regret"] is not None:
            out[f"final_regret_{name}"] = table[name]["final_regret"]
        out[f"solved_frac_{name}"] = table[name]["solved_frac"]
    out["per_algo"] = table
    # the standing table also lands as a kind="quality" record so the
    # trajectory store carries search quality alongside the perf rows
    # (trajectory.load filters kind=="bench"; the gate is untouched)
    try:
        from hyperopt_tpu.obs import trajectory
        from hyperopt_tpu.obs.quality import quality_record

        trajectory.append(quality_record(
            "bench.search_quality", table,
            config={"n_studies": len(items), "seed0": seed0}))
    except Exception as e:  # noqa: BLE001 - the record is best-effort
        out["trajectory_error"] = str(e)
    return out


def bench_quality_overhead(n_tells=150, repeats=5, seed=0):
    """Quality-plane acceptance bar (ISSUE 16): the per-tell convergence
    tracker (incremental best, EWMA, plateau detector, timeline events)
    must cost ~nothing on the serving path.  Drives the REAL handler
    path (``ServiceHTTPServer.handle`` ask+tell rounds) with the quality
    plane armed vs disarmed, same seed, all-rand asks (startup count >
    round count, so no TPE compile pollutes the min-of-reps), and
    reports the fractional delta as ``quality_overhead_frac`` — gated
    ABSOLUTE at ≤5% (the ``checksum_overhead_frac`` pattern)."""
    from hyperopt_tpu.obs.quality import QualityPlane
    from hyperopt_tpu.service.scheduler import StudyScheduler
    from hyperopt_tpu.service.server import ServiceHTTPServer

    space_spec = {"x": {"dist": "uniform", "args": [-5, 10]},
                  "y": {"dist": "uniform", "args": [0, 15]}}

    def once(armed):
        sched = StudyScheduler(
            wal=False, quality=QualityPlane() if armed else False)
        srv = ServiceHTTPServer(0, scheduler=sched, trace=False,
                                slo=False)
        code, r = srv.handle("POST", "/study", {
            "space": space_spec, "seed": seed,
            "n_startup_jobs": n_tells + 1})
        assert code == 200, r
        sid = r["study_id"]
        t0 = time.perf_counter()
        for i in range(n_tells):
            code, a = srv.handle("POST", "/ask", {"study_id": sid})
            assert code == 200, a
            code, _ = srv.handle("POST", "/tell", {
                "study_id": sid, "tid": a["trials"][0]["tid"],
                "loss": float(i % 7)})
            assert code == 200
        return time.perf_counter() - t0

    once(False)  # warm the route/admission path for both sides
    out = {"n_tells": n_tells, "repeats": repeats,
           "bar": "quality plane <=5% per ask+tell round (absolute)"}
    out["quality_off_sec"] = min(once(False) for _ in range(repeats))
    out["quality_on_sec"] = min(once(True) for _ in range(repeats))
    out["quality_overhead_frac"] = (
        (out["quality_on_sec"] - out["quality_off_sec"])
        / max(out["quality_off_sec"], 1e-9))
    out["quality_overhead_us_per_tell"] = (
        (out["quality_on_sec"] - out["quality_off_sec"])
        / n_tells * 1e6)
    return out


def bench_load_attribution(n_tells=150, repeats=5, seed=0):
    """Cost-attribution acceptance bar (ISSUE 17): the per-wave cost
    ledger (per-study device-time shares, busy EWMA, heat totals) must
    cost ~nothing on the serving path.  Two halves:

    1. armed-vs-disarmed ask+tell rounds through the REAL handler path
       (the ``bench_quality_overhead`` harness with the load ledger as
       the armed plane) → ``attribution_overhead_frac``, gated ABSOLUTE
       at ≤5%.
    2. a deliberately skewed placement — 4 bound ledgers, waves split
       10:1:1:1 with a fixed per-wave device time — merged exactly the
       way ``/fleet/load`` merges them → ``shard_heat_skew``.  Synthetic
       device time on purpose: the gate wants a deterministic pin on the
       share/merge/skew math, not compile-pollution noise.
    """
    from hyperopt_tpu.obs.load import CostLedger, heat_skew, merge_status
    from hyperopt_tpu.service.scheduler import StudyScheduler
    from hyperopt_tpu.service.server import ServiceHTTPServer

    space_spec = {"x": {"dist": "uniform", "args": [-5, 10]},
                  "y": {"dist": "uniform", "args": [0, 15]}}

    def once(armed):
        sched = StudyScheduler(
            wal=False, quality=False,
            load=CostLedger() if armed else False)
        srv = ServiceHTTPServer(0, scheduler=sched, trace=False,
                                slo=False)
        code, r = srv.handle("POST", "/study", {
            "space": space_spec, "seed": seed,
            "n_startup_jobs": n_tells + 1})
        assert code == 200, r
        sid = r["study_id"]
        t0 = time.perf_counter()
        for i in range(n_tells):
            code, a = srv.handle("POST", "/ask", {"study_id": sid})
            assert code == 200, a
            code, _ = srv.handle("POST", "/tell", {
                "study_id": sid, "tid": a["trials"][0]["tid"],
                "loss": float(i % 7)})
            assert code == 200
        return time.perf_counter() - t0

    once(False)  # warm the route/admission path for both sides
    out = {"n_tells": n_tells, "repeats": repeats,
           "bar": "cost attribution <=5% per ask+tell round (absolute)"}
    out["load_off_sec"] = min(once(False) for _ in range(repeats))
    out["load_on_sec"] = min(once(True) for _ in range(repeats))
    out["attribution_overhead_frac"] = (
        (out["load_on_sec"] - out["load_off_sec"])
        / max(out["load_off_sec"], 1e-9))
    out["attribution_overhead_us_per_tell"] = (
        (out["load_on_sec"] - out["load_off_sec"])
        / n_tells * 1e6)

    # half 2: the skewed placement, through the same merge the
    # /fleet/load endpoint uses
    waves_per_shard = {0: 10, 1: 1, 2: 1, 3: 1}
    statuses = []
    for shard, n_waves in waves_per_shard.items():
        led = CostLedger()
        led.bind(shard=shard, replica="bench")
        for w in range(n_waves):
            led.observe_tick([(f"s{shard}", 4)], device_sec=1e-3,
                             cand=96.0, hbm_bytes=1024.0, cohort="cap16")
        statuses.append(led.publish())
    merged = merge_status(statuses)
    out["shard_heat_skew"] = merged["heat_skew"]
    out["skew_check"] = abs(heat_skew(
        [s["heat_ms"] for s in statuses]) - merged["heat_skew"]) < 1e-3
    out["waves_per_shard"] = {str(k): v for k, v in
                              waves_per_shard.items()}
    return out


def bench_tenant_fairness(n_tells=150, repeats=5, seed=0, window_sec=1.5,
                          noisy_threads=4):
    """Tenant-observatory acceptance bars (ISSUE 20), two halves:

    1. ``tenant_overhead_frac`` — armed-vs-disarmed ask+tell rounds
       through the REAL handler path with an ``x-tenant`` header on
       every request (the header is parsed on both sides; only the
       armed side pays the ledger/sketch/gauge work).  Gated ABSOLUTE
       at ≤5%: attribution must be noise on the ask, not a tax.
    2. ``tenant_p99_skew`` — a light tenant's ask p99 under a noisy
       neighbour hammering from ``noisy_threads`` concurrent studies,
       as a multiple of the same light tenant's SOLO p99, with the DRR
       wave packer armed and a real gather window so concurrent askers
       coalesce into shared waves.  The acceptance bar is ≤3x; the
       weighted-fair packer is what keeps the light tenant's tail from
       scaling with the noisy tenant's offered load.
    """
    import threading

    from hyperopt_tpu.obs.tenant import TenantLedger
    from hyperopt_tpu.service.scheduler import StudyScheduler
    from hyperopt_tpu.service.server import ServiceHTTPServer

    space_spec = {"x": {"dist": "uniform", "args": [-5, 10]},
                  "y": {"dist": "uniform", "args": [0, 15]}}

    def once(armed):
        sched = StudyScheduler(
            wal=False, quality=False, load=False,
            tenants=TenantLedger() if armed else False)
        srv = ServiceHTTPServer(0, scheduler=sched, trace=False,
                                slo=False)
        hdr = {"x-tenant": "bench"}
        code, r = srv.handle("POST", "/study", {
            "space": space_spec, "seed": seed,
            "n_startup_jobs": n_tells + 1}, headers=hdr)
        assert code == 200, r
        sid = r["study_id"]
        t0 = time.perf_counter()
        for i in range(n_tells):
            code, a = srv.handle("POST", "/ask", {"study_id": sid},
                                 headers=hdr)
            assert code == 200, a
            code, _ = srv.handle("POST", "/tell", {
                "study_id": sid, "tid": a["trials"][0]["tid"],
                "loss": float(i % 7)}, headers=hdr)
            assert code == 200
        return time.perf_counter() - t0

    once(False)  # warm the route/admission path for both sides
    out = {"n_tells": n_tells, "repeats": repeats,
           "window_sec": window_sec, "noisy_threads": noisy_threads,
           "bar": "tenant plane <=5% per ask+tell round (absolute); "
                  "light-tenant p99 <=3x solo under a noisy neighbour"}
    out["tenant_off_sec"] = min(once(False) for _ in range(repeats))
    out["tenant_on_sec"] = min(once(True) for _ in range(repeats))
    out["tenant_overhead_frac"] = (
        (out["tenant_on_sec"] - out["tenant_off_sec"])
        / max(out["tenant_off_sec"], 1e-9))
    out["tenant_overhead_us_per_ask"] = (
        (out["tenant_on_sec"] - out["tenant_off_sec"])
        / n_tells * 1e6)

    # half 2: the noisy-neighbour mix through real waves.  A gather
    # window makes concurrent askers coalesce into shared waves, which
    # is where the DRR packer orders light-tenant reqs ahead of the
    # noisy tenant's backlog; n_startup_jobs is small so asks leave the
    # inline startup path and actually ride waves.
    def p99(lat):
        lat = sorted(lat)
        return lat[min(len(lat) - 1, int(0.99 * len(lat)))] * 1e3

    def mix(noisy):
        sched = StudyScheduler(wal=False, quality=False, load=False,
                               tenants=TenantLedger(),
                               wave_window=0.005, max_pending=1 << 20)
        srv = ServiceHTTPServer(0, scheduler=sched, trace=False,
                                slo=False)

        def new_study(name, tenant):
            code, r = srv.handle("POST", "/study", {
                "space": space_spec, "seed": seed, "study_id": name,
                "n_startup_jobs": 2, "tenant": tenant})
            assert code == 200, r
            return r["study_id"]

        light = new_study("bench-light", "light")
        loud = [new_study(f"bench-noisy-{i}", "noisy")
                for i in range(noisy_threads)]
        stop = threading.Event()

        def hammer(sid):
            while not stop.is_set():
                srv.handle("POST", "/ask", {"study_id": sid},
                           headers={"x-tenant": "noisy"})

        # warm every study past the inline startup path WITH tells, then
        # freeze: the timed loops are ask-only, so each cohort's padded
        # history shape never widens and no jit recompile lands inside a
        # timed window (the tell path is the overhead half's job)
        for sid, tenant in [(light, "light")] + [(s, "noisy")
                                                 for s in loud]:
            for i in range(4):
                code, a = srv.handle("POST", "/ask", {"study_id": sid},
                                     headers={"x-tenant": tenant})
                assert code == 200, a
                srv.handle("POST", "/tell", {
                    "study_id": sid, "tid": a["trials"][0]["tid"],
                    "loss": float(i)}, headers={"x-tenant": tenant})
        threads = [threading.Thread(target=hammer, args=(s,), daemon=True)
                   for s in (loud if noisy else [])]
        for t in threads:
            t.start()
        lat = []
        t0 = time.perf_counter()
        while time.perf_counter() - t0 < window_sec:
            t1 = time.perf_counter()
            code, a = srv.handle("POST", "/ask", {"study_id": light},
                                 headers={"x-tenant": "light"})
            assert code == 200, a
            lat.append(time.perf_counter() - t1)
        stop.set()
        for t in threads:
            t.join(timeout=5.0)
        return p99(lat), len(lat)

    solo_p99, solo_n = mix(noisy=False)
    mixed_p99, mixed_n = mix(noisy=True)
    out["light_solo_p99_ms"] = solo_p99
    out["light_mixed_p99_ms"] = mixed_p99
    out["light_solo_asks"] = solo_n
    out["light_mixed_asks"] = mixed_n
    out["tenant_p99_skew"] = mixed_p99 / max(solo_p99, 1e-9)
    return out


def bench_blackbox_probe(window_sec=2.0, repeats=2, seed=0,
                         probe_period=1.0):
    """Blackbox-prober acceptance bars (ISSUE 18), two halves:

    1. ``probe_overhead_frac`` — armed-vs-disarmed TENANT ask+tell
       throughput through the REAL handler path while a live prober
       thread runs canary cycles against the bound HTTP URL.  The
       tenant loop is TIME-windowed (not round-counted) over several
       probe periods, so the number is the armed duty cycle a tenant
       actually experiences — measured at a period 30x hotter than the
       production default, so the bar has margin built in.  Gated
       ABSOLUTE at ≤5%: auditing the serving path must be noise on the
       tenants it audits.
    2. ``probe_detection_latency_sec`` — wall seconds from silent
       corruption injected into the readback path (``chaos``
       corrupt@tick, the bit-flip the prober exists to catch) to the
       first non-green verdict, cycles driven synchronously so the
       number measures the detection pipeline, not the probe period.
    """
    from hyperopt_tpu import chaos
    from hyperopt_tpu.obs.prober import Prober
    from hyperopt_tpu.service.scheduler import StudyScheduler
    from hyperopt_tpu.service.server import ServiceHTTPServer

    space_spec = {"x": {"dist": "uniform", "args": [-5, 10]},
                  "y": {"dist": "uniform", "args": [0, 15]}}

    def once(armed):
        sched = StudyScheduler(wal=False, quality=False)
        srv = ServiceHTTPServer(0, scheduler=sched, trace=False,
                                slo=False)
        prober = None
        if armed:
            assert srv.start(), "bench probe server failed to bind"
            prober = srv.arm_prober(period=probe_period)
            assert prober is not None
        try:
            code, r = srv.handle("POST", "/study", {
                "space": space_spec, "seed": seed,
                "n_startup_jobs": 1 << 20})
            assert code == 200, r
            sid = r["study_id"]
            rounds = 0
            t0 = time.perf_counter()
            while time.perf_counter() - t0 < window_sec:
                code, a = srv.handle("POST", "/ask", {"study_id": sid})
                assert code == 200, a
                code, _ = srv.handle("POST", "/tell", {
                    "study_id": sid, "tid": a["trials"][0]["tid"],
                    "loss": float(rounds % 7)})
                assert code == 200
                rounds += 1
            return rounds / (time.perf_counter() - t0)
        finally:
            if prober is not None:
                prober.stop()
            srv.stop()

    # warm both sides: route/admission for the tenant loop, and one
    # armed run so the canary cohort's jit compile (process-global
    # cache) never lands inside a timed window
    once(False)
    once(True)
    out = {"window_sec": window_sec, "repeats": repeats,
           "probe_period_sec": probe_period,
           "bar": "prober <=5% on tenant ask+tell throughput "
                  "(absolute); corruption detected in bounded cycles"}
    out["probe_off_rps"] = max(once(False) for _ in range(repeats))
    out["probe_on_rps"] = max(once(True) for _ in range(repeats))
    out["probe_overhead_frac"] = (
        (out["probe_off_rps"] - out["probe_on_rps"])
        / max(out["probe_off_rps"], 1e-9))

    # half 2: inject → detect, synchronous cycles against the real
    # HTTP path (the canary study itself is served through readback,
    # so the corrupted tick lands in the proposals the probe digests)
    sched = StudyScheduler(wal=False, quality=False)
    srv = ServiceHTTPServer(0, scheduler=sched, trace=False, slo=False)
    assert srv.start(), "bench probe server failed to bind"
    prober = Prober([srv.url], period=probe_period)
    try:
        now = time.time()
        first = prober.run_cycle(now)
        assert first["verdict"] == "ok", first
        chaos.configure(f"{seed}:corrupt@tick:1.0")
        t_inject = time.perf_counter()
        detected = None
        for _ in range(5):  # bounded: the smoke bar is <=3 cycles
            s = prober.run_cycle(time.time())
            if s["verdict"] != "ok":
                detected = time.perf_counter() - t_inject
                out["detect_verdict"] = s["verdict"]
                out["detect_cycles"] = s["cycle"] - first["cycle"]
                break
        assert detected is not None, "prober never saw the corruption"
        out["probe_detection_latency_sec"] = detected
    finally:
        chaos.reset()
        prober.stop()
        srv.stop()
    return out


def bench_fleet_recovery(reps=5, lease_ttl=0.25, poll=0.01):
    """Elastic-fleet recovery latency (ISSUE 8): wall seconds from a
    controller dying mid-shard (claimed lease, heartbeats stop) to a
    survivor HOLDING the reclaimed lease.  Honest measurement — the
    survivor really polls ``reclaim_stale``+``try_claim`` against real
    lease files, so the number is ``lease_ttl`` + reclaim/claim filesystem
    cost + poll jitter; the trajectory gate watches it for the failure
    mode where reclaim stops working and recovery degrades to the barrier
    timeout."""
    import tempfile
    import time as _t

    from hyperopt_tpu.parallel.membership import FleetMembership

    lat = []
    for rep in range(reps):
        with tempfile.TemporaryDirectory() as tmp:
            dead = FleetMembership(tmp, owner=f"dead:{rep}",
                                   lease_ttl=lease_ttl)
            live = FleetMembership(tmp, owner=f"live:{rep}",
                                   lease_ttl=lease_ttl)
            assert dead.try_claim(0, 0)
            t0 = _t.monotonic()  # the "death": heartbeats stop here
            while True:
                live.reclaim_stale(0, 1)
                if live.try_claim(0, 0):
                    break
                _t.sleep(poll)
            lat.append(_t.monotonic() - t0)
    lat.sort()
    return {
        "recovery_latency_sec": lat[len(lat) // 2],
        "recovery_latency_max_sec": lat[-1],
        "lease_ttl_sec": lease_ttl,
        "reps": reps,
        "backend": "host",
    }


def _pcts(samples_sec):
    """p50/p95/p99/mean in milliseconds from a raw latency list."""
    ms = sorted(1e3 * s for s in samples_sec)

    def pct(p):
        return ms[min(len(ms) - 1, int(round(p * (len(ms) - 1))))]

    return {"ask_p50_ms": pct(0.50), "ask_p95_ms": pct(0.95),
            "ask_p99_ms": pct(0.99), "ask_mean_ms": sum(ms) / len(ms),
            "n_asks": len(ms)}


def bench_ask_latency(max_evals=60, seed=0):
    """Per-ask wall latency of the sequential host ask→tell loop (ISSUE 4).

    (a) ``tpe``/``rand``: the synchronous per-ask distribution — wall time
    of each ``algo(new_ids, ...)`` call inside a real warm ``fmin`` loop —
    as p50/p95/p99 (the interactive-latency shape a tunneled chip user
    feels).  (b) ``pipelined``: the same TPE loop with a ~2 ms host
    objective at ``lookahead=0`` vs ``lookahead=1`` — per-ask *blocked*
    time (dispatch + readback actually waited on by the loop, the
    ``ask.blocked_sec`` histogram FMinIter records) plus wall clock, so
    the dispatch/readback overlap is measured, not asserted."""
    import functools

    from hyperopt_tpu import Trials, fmin, hp
    from hyperopt_tpu.algos import rand, tpe

    space = {"x": hp.uniform("x", -5, 10), "y": hp.uniform("y", 0, 15)}
    out = {"max_evals": max_evals}
    tpe_algo = functools.partial(tpe.suggest, n_startup_jobs=10)
    for name, algo in (("tpe", tpe_algo), ("rand", rand.suggest)):
        # warm pass: space + kernel compiles shared with the timed pass
        fmin(_host_branin, space, algo=algo, max_evals=max_evals,
             trials=Trials(), rstate=np.random.default_rng(seed),
             show_progressbar=False)
        lat = []

        def timed(ids, dom, tr, s, _algo=algo, _lat=lat):
            t0 = time.perf_counter()
            docs = _algo(ids, dom, tr, s)
            _lat.append(time.perf_counter() - t0)
            return docs

        fmin(_host_branin, space, algo=timed, max_evals=max_evals,
             trials=Trials(), rstate=np.random.default_rng(seed),
             show_progressbar=False)
        out[name] = _pcts(lat)

    def slow_obj(d, _spin=0.002):
        t0 = time.perf_counter()
        while time.perf_counter() - t0 < _spin:
            pass
        return _host_branin(d)

    pipe = {}
    for la in (0, 1):
        t = Trials()
        t0 = time.perf_counter()
        fmin(slow_obj, space, algo=tpe_algo, max_evals=max_evals, trials=t,
             lookahead=la, rstate=np.random.default_rng(seed),
             show_progressbar=False)
        h = t.obs_metrics.histogram("ask.blocked_sec").snapshot()
        pipe[f"lookahead_{la}"] = {
            "wall_clock_sec": time.perf_counter() - t0,
            "blocked_p50_ms": 1e3 * h.get("p50", 0.0),
            "blocked_p99_ms": 1e3 * h.get("p99", 0.0),
            "blocked_mean_ms": 1e3 * h.get("mean", 0.0),
        }
    pipe["p50_improved"] = (pipe["lookahead_1"]["blocked_p50_ms"]
                            < pipe["lookahead_0"]["blocked_p50_ms"])
    out["pipelined"] = pipe
    return out


_CACHE_SNIPPET = r"""
import json, time
t_imp = time.perf_counter()
import numpy as np
from hyperopt_tpu import Trials, fmin, hp
from hyperopt_tpu.algos import tpe
t0 = time.perf_counter()
space = {"x": hp.uniform("x", -5, 10), "y": hp.uniform("y", 0, 15)}
t = Trials()
fmin(lambda d: (d["x"] - 1.0) ** 2 + d["y"], space, algo=tpe.suggest,
     max_evals=25, trials=t, rstate=np.random.default_rng(0),
     show_progressbar=False)
print(json.dumps({"import_sec": t0 - t_imp,
                  "fmin_sec": time.perf_counter() - t0,
                  "suggest_sec": t.phase_timings["suggest"]["sec"]}))
"""


def bench_compile_cache():
    """Cold-vs-warm wall clock through the persistent XLA compilation cache
    (``HYPEROPT_TPU_COMPILE_CACHE=<dir>``): two fresh interpreter runs of
    the same 25-eval TPE fmin against one fresh cache dir — the first pays
    the one-time XLA compile, the second loads AOT entries from disk.
    Forced-CPU subprocesses (the cache mechanics are platform-independent;
    this stage must never contend for the shared chip)."""
    import shutil
    import tempfile

    cache_dir = tempfile.mkdtemp(prefix="hyperopt_cc_")
    env = _forced_cpu_env(os.environ)
    env["HYPEROPT_TPU_COMPILE_CACHE"] = cache_dir
    env.pop("HYPEROPT_TPU_NO_CACHE", None)
    runs = {}
    try:
        for attempt in ("cold", "warm"):
            try:
                proc = subprocess.run(
                    [sys.executable, "-c", _CACHE_SNIPPET], env=env,
                    capture_output=True, text=True, timeout=600,
                    cwd=os.path.dirname(os.path.abspath(__file__)))
                if proc.returncode != 0:
                    return {"error": proc.stderr[-500:], "attempt": attempt}
                runs[attempt] = json.loads(
                    proc.stdout.strip().splitlines()[-1])
            except Exception as e:
                return {"error": f"{type(e).__name__}: {e}",
                        "attempt": attempt}
        out = {
            "cache_dir_entries": len(os.listdir(cache_dir)),
            "cold_fmin_sec": runs["cold"]["fmin_sec"],
            "warm_fmin_sec": runs["warm"]["fmin_sec"],
            "cold_suggest_sec": runs["cold"]["suggest_sec"],
            "warm_suggest_sec": runs["warm"]["suggest_sec"],
            "warm_speedup": runs["cold"]["fmin_sec"]
            / max(runs["warm"]["fmin_sec"], 1e-9),
            "backend": "cpu-subprocess",
        }
        return out
    finally:
        shutil.rmtree(cache_dir, ignore_errors=True)


def bench_devmem(max_evals=200, seed=0):
    """Device-memory telemetry stage (ISSUE 5): run the on-device Branin
    loop with the devmem sampler armed and attach peak HBM + the live-array
    census to the stage results (and the headline line), so memory
    regressions — a leaked cap-sized buffer, a history that stopped being
    donated — show up in the bench trajectory next to the throughput
    numbers.  ``peak_hbm_bytes`` is gated lower-is-better by
    ``scripts/bench_gate.py``.  On backends without ``memory_stats`` (CPU)
    the byte fields come back None and the census alone is recorded.

    ``peak_bytes_in_use`` is PROCESS-cumulative (the backend never resets
    it), so this stage runs FIRST in ``_JAX_STAGES``: the recorded peak is
    attributable to this stage's loop, not to whichever later stage
    happened to allocate most."""
    from hyperopt_tpu.device_fmin import fmin_device
    from hyperopt_tpu.obs import RunObs, ObsConfig
    from hyperopt_tpu.obs.devmem import DevMemSampler, roll_up
    from hyperopt_tpu.zoo import ZOO

    dom = ZOO["branin"]
    obs = RunObs(ObsConfig(level="basic"), run_id="bench-devmem")
    sampler = DevMemSampler(obs, period=0.0)  # every explicit call samples
    t0 = time.perf_counter()
    fmin_device(dom.objective, dom.space, max_evals=max_evals, seed=seed)
    rec = sampler.sample(reason="bench")
    wall = time.perf_counter() - t0
    obs.finish()
    if rec is None:  # sampler failed open (backend raised): census-only
        return {"wall_clock_sec": wall, "max_evals": max_evals,
                "error": "devmem sampling unavailable on this backend"}
    devices, census = rec["devices"], rec["census"]
    in_use, peak, limit, _ = roll_up(devices)
    out = {"wall_clock_sec": wall, "max_evals": max_evals,
           "n_devices": len(devices),
           "memory_stats_available": in_use is not None,
           "census": {k: dict(v) for k, v in census.items()},
           "history_bytes": census.get("history", {}).get("bytes", 0)}
    if peak is not None:
        out["peak_hbm_bytes"] = peak
        out["bytes_in_use"] = in_use
        if limit:
            out["bytes_limit"] = limit
            out["hbm_watermark_frac"] = peak / limit
    return out


def bench_hr_conditional(max_evals=100, seed=0):
    """BASELINE config #3: Hartmann6 + 20-D Rosenbrock mixed conditional
    space under TPE (28 hyperparameters, nested hp.choice)."""
    from hyperopt_tpu import Trials, fmin
    from hyperopt_tpu.algos import tpe
    from hyperopt_tpu.zoo import ZOO

    dom = ZOO["hr_conditional"]
    t0 = time.perf_counter()
    trials = Trials()
    fmin(dom.objective, dom.space, algo=tpe.suggest, max_evals=max_evals,
         trials=trials, max_queue_len=4,
         rstate=np.random.default_rng(seed), show_progressbar=False)
    dt = time.perf_counter() - t0
    best = min(l for l in trials.losses() if l is not None)
    n_hartmann = sum(
        1 for d in trials.trials if d["misc"]["vals"].get("family") == [0]
    )
    return {"wall_clock_sec": dt, "best_loss": best, "max_evals": max_evals,
            "n_hartmann_branch": n_hartmann, "target": dom.loss_target}


def bench_parallel_trials(n_trials=10000, repeats=5, seed=0):
    """BASELINE config #5 analog on ONE chip: sample n_trials configs from
    the prior and evaluate the (traceable) Branin objective for all of them
    in a single vmapped device program — the batched-trial-evaluation design
    point MongoTrials needs a cluster for."""
    import jax
    import jax.numpy as jnp

    from hyperopt_tpu.spaces import compile_space
    from hyperopt_tpu.zoo import ZOO

    dom = ZOO["branin"]
    cs = compile_space(dom.space)

    def run(i):
        key = jax.random.fold_in(jax.random.PRNGKey(seed), i)
        keys = jax.vmap(lambda j: jax.random.fold_in(key, j))(
            jnp.arange(n_trials, dtype=jnp.uint32)
        )
        flats = jax.vmap(cs.sample_flat)(keys)
        losses = jax.vmap(lambda f: dom.objective(cs.assemble(f, traced=True)))(flats)
        return jnp.min(losses)

    fn = jax.jit(run)
    jax.block_until_ready(fn(np.uint32(0)))  # compile
    t0 = time.perf_counter()
    for i in range(repeats):
        best = fn(np.uint32(i))
    best = float(jax.block_until_ready(best))
    dt = (time.perf_counter() - t0) / repeats
    return {"trials_per_sec": n_trials / dt, "n_trials": n_trials,
            "sec_per_batch": dt, "best_loss_last": best}


def bench_parallel_trials_tpe(n_trials=10240, generations=3, hist_cap=1024,
                              n_cand=32, seed=0, domain="branin",
                              ei_tau=0.5, prior_eps=0.1, gamma=2.0,
                              n_best=128):
    """BASELINE config #5, TPE-DRIVEN (round-3 verdict: the 10k-parallel
    path must run TPE, not prior sampling).  Generation loop: one jitted
    program proposes ``n_trials`` candidates from the TPE posterior (vmapped
    over trial keys), evaluates the traceable objective for all of them, and
    folds a bounded reservoir (best half + random half, capacity
    ``hist_cap``) back as the next generation's observation set — the
    device-scale analog of linear forgetting, keeping the Parzen component
    count fixed while the trial count scales.

    Batch diversity (round-4 verdict): every proposal in a generation shares
    ONE posterior, so a hard per-proposal EI argmax collapses the whole batch
    onto the same marginal mode — BENCH_r04 measured later generations
    getting WORSE than prior sampling.  The fix is in the kernel
    (``tpe._select_candidate``): stochastic EI selection (``i ∝
    softmax(EI/tau)`` by Gumbel-max, per-proposal key) plus ε-prior mixing,
    so the batch spreads over the EI landscape and n_cand can be LARGE
    again.  ``prior_best`` is the best of the same total trial budget spent
    on pure prior sampling — the bar the TPE path must beat."""
    import jax
    import jax.numpy as jnp

    from hyperopt_tpu.algos import tpe
    from hyperopt_tpu.spaces import compile_space
    from hyperopt_tpu.zoo import ZOO

    dom = ZOO[domain]
    cs = compile_space(dom.space)
    # gamma wider than the reference default: with hist_cap=1024 live
    # observations, gamma=0.25 puts only ceil(0.25*32)=8 points in the below
    # model — too few to concentrate (its sigma floor is prior_sigma/9).
    # gamma=2.0 -> 64 below points, the same setting the on-device Branin
    # bench validated (bench_branin_device).
    cfg = {"prior_weight": 1.0, "n_EI_candidates": n_cand, "gamma": gamma,
           "LF": hist_cap, "ei_select": "softmax", "ei_tau": ei_tau,
           "prior_eps": prior_eps}
    propose = tpe.build_propose(cs, cfg)
    labels = cs.labels

    def one_generation(hist, gi):
        key = jax.random.fold_in(jax.random.PRNGKey(seed), gi)
        keys = jax.vmap(lambda j: jax.random.fold_in(key, j))(
            jnp.arange(n_trials, dtype=jnp.uint32)
        )
        flats = jax.vmap(propose, in_axes=(None, 0))(hist, keys)
        flats = {l: v.astype(jnp.float32) for l, v in flats.items()}
        losses = jax.vmap(
            lambda f: dom.objective(cs.assemble(f, traced=True))
        )(flats)
        # bounded reservoir for the next posterior: merge the OLD reservoir
        # with this generation (discarding it would let the posterior forget
        # the best-ever points and regress).  The elite slice is SMALL
        # (n_best=128 of 1024): round 4 kept best-512 and the above-model
        # saturated with near-optimal points, so EI = ll_below - ll_above
        # actively penalized the optimum region and later generations got
        # WORSE.  TPE's split assumes history is a representative sample;
        # the reservoir must stay mostly random draws from each generation.
        k_res = jax.random.fold_in(key, 0xFFFF)
        pool_losses = jnp.concatenate(
            [jnp.where(hist["has_loss"], hist["losses"], jnp.inf), losses]
        )
        pool_vals = {
            l: jnp.concatenate([hist["vals"][l], flats[l]]) for l in labels
        }
        _, best_idx = jax.lax.top_k(-pool_losses, n_best)
        rand_idx = hist_cap + jax.random.randint(
            k_res, (hist_cap - n_best,), 0, n_trials
        )
        idx = jnp.concatenate([best_idx, rand_idx])
        new_hist = {
            "losses": pool_losses[idx],
            "has_loss": jnp.isfinite(pool_losses[idx]),
            "vals": {l: pool_vals[l][idx] for l in labels},
            "active": {l: jnp.isfinite(pool_losses[idx]) for l in labels},
        }
        return new_hist, jnp.min(losses)

    gen = jax.jit(one_generation)
    empty = {
        "losses": jnp.full(hist_cap, jnp.inf, jnp.float32),
        "has_loss": jnp.zeros(hist_cap, bool),
        "vals": {l: jnp.zeros(hist_cap, jnp.float32) for l in labels},
        "active": {l: jnp.zeros(hist_cap, bool) for l in labels},
    }
    hist, best = gen(empty, np.uint32(0))  # compile
    jax.block_until_ready(best)
    t0 = time.perf_counter()
    hist = empty
    bests = []
    for gi in range(generations):
        hist, best = gen(hist, np.uint32(gi))
        bests.append(best)
    bests = [float(b) for b in jax.block_until_ready(bests)]
    dt = time.perf_counter() - t0
    total = n_trials * generations

    # the bar: the SAME total budget spent on pure prior sampling
    def prior_best_fn(i):
        key = jax.random.fold_in(jax.random.PRNGKey(seed + 1), i)
        keys = jax.vmap(lambda j: jax.random.fold_in(key, j))(
            jnp.arange(n_trials, dtype=jnp.uint32)
        )
        flats = jax.vmap(cs.sample_flat)(keys)
        return jnp.min(jax.vmap(
            lambda f: dom.objective(cs.assemble(f, traced=True))
        )(flats))

    pb = jax.jit(prior_best_fn)
    prior_best = min(float(pb(np.uint32(i))) for i in range(generations))
    return {"trials_per_sec": total / dt, "n_trials": total,
            "domain": domain, "generations": generations,
            "hist_cap": hist_cap, "n_cand_per_trial": n_cand,
            "ei_select": "softmax", "ei_tau": ei_tau, "prior_eps": prior_eps,
            "sec_total": dt, "best_loss_per_gen": bests,
            "best_loss_overall": min(bests), "prior_best": prior_best,
            "beats_prior": min(bests) < prior_best,
            "monotone_gens": all(b2 < b1 for b1, b2 in zip(bests, bests[1:])),
            "note": "TPE posterior drives every generation"}


def bench_ml_cv(max_evals=64, batch=4096, seed=0):
    """BASELINE config #4 analog: real-ML objective (4-fold CV logistic
    regression, pure jnp — zoo.ml_logreg_cv).  Two measurements: (a) batched
    trial evaluation via ``Domain.make_batch_eval`` — thousands of CV model
    fits in one device program; (b) HPO quality: the fully on-device fmin
    tuning lr/l2/momentum."""
    import jax
    import jax.numpy as jnp

    from hyperopt_tpu.base import Domain
    from hyperopt_tpu.device_fmin import fmin_device
    from hyperopt_tpu.spaces import compile_space
    from hyperopt_tpu.zoo import ZOO

    dom = ZOO["ml_logreg_cv"]
    cs = compile_space(dom.space)

    # (a) batched evaluation: `batch` full CV fits per dispatch
    batch_eval = Domain(dom.objective, dom.space).make_batch_eval()
    keys = jax.vmap(lambda j: jax.random.fold_in(jax.random.PRNGKey(seed), j))(
        jnp.arange(batch, dtype=jnp.uint32)
    )
    flats = jax.jit(jax.vmap(cs.sample_flat))(keys)
    losses = batch_eval(flats)
    jax.block_until_ready(losses)  # compile
    # best-of-3 timing blocks, same policy as the numpy baseline: a shared
    # tunneled chip has transient contention, and a single timed repeat
    # (round-4's method) swung 6x between runs.  float(nanmin) forces a
    # real host readback, so each block has strict completion semantics.
    # Diverged fits (lr at the top of the log range) return NaN — real
    # trial batches contain failures; nanmin is the honest best.
    dt = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        losses = batch_eval(flats)
        best_prior = float(jnp.nanmin(jax.block_until_ready(losses)))
        dt = min(dt, time.perf_counter() - t0)

    # (b) on-device HPO over the CV objective
    t1 = time.perf_counter()
    _, best_loss = fmin_device(dom.objective, dom.space, max_evals=max_evals,
                               seed=seed, n_EI_candidates=64)
    hpo_dt = time.perf_counter() - t1

    # (c) model-FAMILY selection (the sklearn SVM-vs-RF shape): conditional
    # space over two model families, per-family hyperparameters, whole HPO
    # on-device via the union-merge traced assembly
    sel = ZOO["ml_model_select_cv"]
    t2 = time.perf_counter()
    sel_best, sel_loss = fmin_device(sel.objective, sel.space,
                                     max_evals=max_evals, seed=seed,
                                     n_EI_candidates=64)
    sel_dt = time.perf_counter() - t2
    return {"cv_fits_per_sec": batch / dt, "batch": batch,
            "sec_per_batch": dt, "best_prior_loss": best_prior,
            "fmin_device_best_loss": float(best_loss),
            "fmin_device_evals": max_evals,
            "fmin_device_sec": hpo_dt, "loss_target": dom.loss_target,
            "model_select_best_loss": float(sel_loss),
            "model_select_family": int(sel_best.get("model", -1)),
            "model_select_sec": sel_dt}


_SHARDED_SNIPPET = r"""
import json, sys, time
import numpy as np
import jax, jax.numpy as jnp
from hyperopt_tpu.parallel import sharding
from hyperopt_tpu.spaces import compile_space
from hyperopt_tpu import hp
from hyperopt_tpu.algos import tpe

n_dev = len(jax.devices())
space = {f"x{i}": hp.uniform(f"x{i}", -5, 5) for i in range(4)}
cs = compile_space(space)
cfg = {"prior_weight": 1.0, "n_EI_candidates": 256, "gamma": 0.25, "LF": 25}
batch = 256
rng = np.random.default_rng(0)
cap = 128
has = np.zeros(cap, bool); has[:60] = True
hist = {
    "losses": jnp.asarray(np.where(has, rng.normal(size=cap), np.inf).astype(np.float32)),
    "has_loss": jnp.asarray(has),
    "vals": {l: jnp.asarray(np.where(has, rng.uniform(-5, 5, cap), 0).astype(np.float32)) for l in cs.labels},
    "active": {l: jnp.asarray(has) for l in cs.labels},
}
keys = jax.vmap(lambda i: jax.random.fold_in(jax.random.PRNGKey(0), i))(
    jnp.arange(batch, dtype=jnp.uint32))

def timeit(fn, h, reps=3):
    out = fn(h, keys); jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(reps):
        out = fn(h, keys)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / reps

mesh1 = sharding.make_mesh(1)
plain = sharding.suggest_batch_sharded(cs, cfg, mesh1)
t1 = timeit(plain, sharding.replicate_history(hist, mesh1))
mesh = sharding.make_mesh(n_dev)
shard = sharding.suggest_batch_sharded(cs, cfg, mesh)
tn = timeit(shard, sharding.replicate_history(hist, mesh))
print(json.dumps({
    "n_devices": n_dev, "batch": batch, "n_cand": cfg["n_EI_candidates"],
    "sec_1dev": t1, "sec_ndev": tn, "scaling_x": t1 / tn,
    "proposals_per_sec_ndev": batch / tn,
}))
"""


def bench_sharded_scaling():
    """Data-parallel trial-batch scaling on a virtual 8-device CPU mesh
    (shape, not absolute perf — SURVEY.md §4 doctrine).  Runs in a
    subprocess so it never touches the real chip."""
    import os
    import subprocess
    import sys as _sys

    env = _forced_cpu_env(os.environ, n_devices=8)
    try:
        proc = subprocess.run(
            [_sys.executable, "-c", _SHARDED_SNIPPET],
            env=env, capture_output=True, text=True, timeout=600,
            cwd=os.path.dirname(os.path.abspath(__file__)),
        )
        if proc.returncode != 0:
            return {"error": proc.stderr[-500:]}
        return json.loads(proc.stdout.strip().splitlines()[-1])
    except Exception as e:  # timeout/empty stdout must not kill the metric line
        return {"error": f"{type(e).__name__}: {e}"}


_SHARDED_SUGGEST_SNIPPET = r"""
import json, os, sys, time
import numpy as np
import jax
from hyperopt_tpu import Trials, fmin, hp
from hyperopt_tpu.algos import tpe, rand
from hyperopt_tpu.base import Domain, PaddedHistory

space = {f"x{i}": hp.uniform(f"x{i}", -5, 5) for i in range(4)}
def obj(d):
    return sum((v - 1.0) ** 2 for v in d.values())

def populated(n=24):
    t = Trials()
    fmin(obj, space, algo=rand.suggest, max_evals=n, trials=t,
         rstate=np.random.default_rng(0), show_progressbar=False)
    return t

B, n_cand, reps = 64, 256, 4
out = {"batch": B, "n_EI_candidates": n_cand,
       # the pre-round-6 candidate-sharded path proposed ONE winner per
       # dispatch (n_cand candidates on one chip); the sharded fused batch
       # proposes B at once, each over the distributed pool
       "cand_batch_multiple": B}
ref = None
for shards in (1, 2, 4, 8):
    os.environ["HYPEROPT_TPU_SHARD"] = str(shards)
    t = populated()
    dom = Domain(obj, space)
    def ask(seed):
        return tpe.suggest(t.new_trial_ids(B), dom, t, seed,
                           n_startup_jobs=8, n_EI_candidates=n_cand,
                           ei_select="softmax")
    ask(0)  # compile + first (placement-copy) tick
    t0 = time.perf_counter()
    for r in range(1, reps + 1):
        docs = ask(r)
    dt = (time.perf_counter() - t0) / reps
    vals = sorted((d["misc"]["vals"]["x0"][0] for d in docs))
    if shards == 1:
        ref = vals
    out[f"shards_{shards}"] = {
        "sharded_cand_per_sec": B * n_cand / dt,
        "sec_per_ask": dt,
        "proposals_identical_to_1shard": vals == ref,
    }
del os.environ["HYPEROPT_TPU_SHARD"]

# bf16 compressed history: resident float bytes at the SAME cap
labels = tuple(f"x{i}" for i in range(4))
def hist_bytes(dtype):
    ph = PaddedHistory(labels, hist_dtype=dtype)
    for i in range(100):
        ph.append({l: float(i % 7) for l in labels}, float(i))
    dev = ph.device_view()
    return int(sum(dev["vals"][l].nbytes for l in labels)
               + dev["losses"].nbytes)
f32b, bf16b = hist_bytes("float32"), hist_bytes("bfloat16")
out["history_bytes_f32"] = f32b
out["history_bytes_bf16"] = bf16b
out["bf16_reduction_x"] = f32b / max(bf16b, 1)
print(json.dumps(out))
"""


def bench_sharded_suggest():
    """ISSUE 6 headline stage: the FUSED tell+ask program sharded over a
    virtual 8-device CPU mesh at shard counts {1, 2, 4, 8} —
    candidates/sec per shard count (``sharded_cand_per_sec``, gated
    higher-is-better by scripts/bench_gate.py), a proposal batch 64× the
    pre-round-6 one-winner dispatch, per-shard-count bit-equality against
    the 1-shard program, and the bf16 compressed-history byte reduction at
    unchanged cap.  CPU mesh: scaling SHAPE is meaningful, absolute
    numbers are not (SURVEY.md §4)."""
    import os
    import subprocess
    import sys as _sys

    env = _forced_cpu_env(os.environ, n_devices=8)
    try:
        proc = subprocess.run(
            [_sys.executable, "-c", _SHARDED_SUGGEST_SNIPPET],
            env=env, capture_output=True, text=True, timeout=600,
            cwd=os.path.dirname(os.path.abspath(__file__)),
        )
        if proc.returncode != 0:
            return {"error": proc.stderr[-500:]}
        return json.loads(proc.stdout.strip().splitlines()[-1])
    except Exception as e:  # timeout/empty stdout must not kill the metric line
        return {"error": f"{type(e).__name__}: {e}"}


def bench_multi_study(n_studies=1024, waves=4, seq_studies=128, seed=0):
    """ISSUE 9 headline stage: serving throughput of the multi-study
    batched suggest at ``n_studies`` (default 1k) concurrent studies.

    Workload: ``zoo.make_study_mix`` — heterogeneous spaces, so the
    scheduler runs several cohorts at once.  Startup waves seed each
    study past ``n_startup_jobs`` by random search, then ``waves``
    measured ask waves run ONE batched fused tell+ask program per cohort
    (``tpe.build_suggest_batched``) for every study; losses are a cheap
    deterministic host function of the proposal (the stage measures the
    serving hot path — real-objective convergence is the SERVICE_GATE's
    job).  The sequential-loop baseline drives an identical mix subset
    through the single-study ``tpe.suggest`` path — one fused device
    dispatch per study per wave, the pre-batching architecture — and the
    headline is the per-study throughput ratio (acceptance bar: ≥ 8×).

    Reported: ``studies_per_sec`` (batched asks served per wall second),
    ``study_ask_p50/p99_ms`` (per-ask completion latency; every ask in a
    wave completes with its wave — named apart from the single-study
    ``ask_*_ms`` keys so the tail-mined gate series never mix the two),
    ``slot_utilization_frac`` (occupied cohort slots / total — pow2 slot
    padding is the honest denominator), ``vs_sequential_x``.
    """
    import numpy as _np

    from hyperopt_tpu import zoo as zoo_mod
    from hyperopt_tpu.base import Domain, Trials
    from hyperopt_tpu.algos import tpe as tpe_mod
    from hyperopt_tpu.service import StudyScheduler

    def cheap_loss(params):
        # deterministic, shape-free stand-in objective: keeps the stage's
        # wall clock on the serving path instead of host jnp evaluation
        return float(_np.sin(sum(float(v) for v in params.values())))

    mix = zoo_mod.make_study_mix(n_studies, seed0=seed)
    sched = StudyScheduler(max_studies=max(n_studies, 4096))
    sids = [sched.create_study(m.domain.space, seed=m.seed,
                               n_startup_jobs=m.n_startup_jobs)
            for m in mix]

    def wave(n=1):
        answers = sched.ask_many([(sid, n) for sid in sids])
        for sid in sids:
            for a in answers[sid]:
                sched.tell(sid, a["tid"], cheap_loss(a["params"]))

    n_startup = mix[0].n_startup_jobs
    for _ in range(n_startup):  # random-search seeding, unmeasured
        wave()
    wave()  # first TPE wave: pays the per-cohort XLA compiles, unmeasured

    wave_sec = []
    for _ in range(waves):
        t0 = time.perf_counter()
        wave()
        wave_sec.append(time.perf_counter() - t0)
    per_ask_ms = sorted(1e3 * s for s in wave_sec for _ in range(n_studies))
    # best-of-waves, the repo bench convention ("honest strict-readback
    # best-of-3"): the shared box's contention spikes hit whole waves, and
    # the min is the reproducible figure (the tails still ride ask_p99_ms)
    best = min(wave_sec)

    # sequential-loop baseline: identical mix subset, one single-study
    # fused dispatch per study per wave (what the service replaced)
    sub = zoo_mod.make_study_mix(seq_studies, seed0=seed)
    seq = []
    for m in sub:
        t = Trials()
        dom = Domain(None, m.domain.space)
        rstate = _np.random.default_rng(m.seed)
        seq.append((t, dom, rstate))
    from hyperopt_tpu.algos import rand as rand_mod
    from hyperopt_tpu.base import JOB_STATE_DONE, STATUS_OK, spec_from_misc

    def seq_wave():
        t0 = time.perf_counter()
        for t, dom, rstate in seq:
            ids = t.new_trial_ids(1)
            s = int(rstate.integers(2**31 - 1))
            if len(t.trials) < n_startup:
                docs = rand_mod.suggest(ids, dom, t, s)
            else:
                docs = tpe_mod.suggest(ids, dom, t, s,
                                       n_startup_jobs=n_startup)
            t.insert_trial_docs(docs)
            t.refresh()
            for d in docs:
                d["result"] = {"loss": cheap_loss(spec_from_misc(d["misc"])),
                               "status": STATUS_OK}
                d["state"] = JOB_STATE_DONE
            t.refresh()
        return time.perf_counter() - t0

    for _ in range(n_startup + 1):  # seeding + compile wave, unmeasured
        seq_wave()
    seq_sec = [seq_wave() for _ in range(waves)]
    seq_rate = seq_studies / max(min(seq_sec), 1e-9)

    rate = n_studies / max(best, 1e-9)
    status = sched.studies_status()
    return {
        "n_studies": n_studies,
        "waves": waves,
        "studies_per_sec": rate,
        "study_ask_p50_ms": per_ask_ms[len(per_ask_ms) // 2],
        "study_ask_p99_ms": per_ask_ms[min(len(per_ask_ms) - 1,
                                           int(0.99 * len(per_ask_ms)))],
        "slot_utilization_frac": status["slot_utilization"],
        "n_cohorts": len(status["cohorts"]),
        "cohort_cache": status["cohort_cache"],
        "sequential_studies_per_sec": seq_rate,
        "sequential_subset": seq_studies,
        "vs_sequential_x": rate / max(seq_rate, 1e-9),
    }


def bench_service_resume(n_studies=48, waves=5, queue=8, seed=0):
    """ISSUE 10 stage: the durable serving plane's two headline costs.

    (1) ``resume_latency_sec`` — SIGKILL-equivalent restart: a scheduler
    with a store + WAL drives ``n_studies`` through startup + ``waves``
    TPE waves and leaves one ask pending (asked, untold) per study, then
    a FRESH scheduler on the same root replays the journal.  The figure
    is the full construction-to-serving wall time: JSONL replay, the
    per-study store rescan, seed-stream realignment and the tid-counter
    reclamation pass.  (Served asks are already durable in the store, so
    nothing regenerates here — regeneration covers asks that died
    mid-wave, which only the SERVICE_CHAOS_GATE's real SIGKILL can
    produce.)

    (2) ``shed_rate_frac`` — offered load at 2x ask capacity: ``2 *
    queue`` client threads hammer the REAL ``server.handle`` path (pure,
    no sockets) against an ``AdmissionGuard(max_queue=queue)``,
    re-offering immediately on 429; the figure is the shed fraction of
    offered ATTEMPTS (hot-retry weighted, so it sits near 1 under
    saturation).  Its regression mode is a COLLAPSE toward 0 — admission
    no longer bounding the queue — which the higher-is-better gate
    direction catches.  Zero tells may be lost either way (asserted,
    not just measured).
    """
    import tempfile
    import threading as _th

    import numpy as _np

    from hyperopt_tpu import zoo as zoo_mod
    from hyperopt_tpu.service import AdmissionGuard, StudyScheduler
    from hyperopt_tpu.service.server import ServiceHTTPServer

    def cheap_loss(params):
        return float(_np.sin(sum(float(v) for v in params.values())))

    out = {}
    mix = zoo_mod.make_study_mix(n_studies, seed0=seed)
    with tempfile.TemporaryDirectory() as root:
        sched = StudyScheduler(max_studies=max(n_studies, 4096),
                               store_root=root)
        sids = [sched.create_study(
            m.domain.space, seed=m.seed, n_startup_jobs=m.n_startup_jobs,
            space_spec={"zoo": m.domain.name})
            for m in mix]
        for _ in range(mix[0].n_startup_jobs + 1):
            answers = sched.ask_many([(sid, 1) for sid in sids])
            for sid in sids:
                for a in answers[sid]:
                    sched.tell(sid, a["tid"], cheap_loss(a["params"]))
        for _ in range(waves):
            answers = sched.ask_many([(sid, 1) for sid in sids])
            for sid in sids:
                for a in answers[sid]:
                    sched.tell(sid, a["tid"], cheap_loss(a["params"]))
        # leave one ask pending per study: the resume regenerates it
        sched.ask_many([(sid, 1) for sid in sids])
        del sched  # the crash (no drain, no compaction)

        t0 = time.perf_counter()
        resumed = StudyScheduler(max_studies=max(n_studies, 4096),
                                 store_root=root)
        resume_sec = time.perf_counter() - t0
        stats = resumed.last_resume or {}
        out["resume_latency_sec"] = resume_sec
        out["resume_replay_sec"] = stats.get("replay_sec")
        out["resume_studies"] = stats.get("studies")
        out["resume_asks"] = stats.get("asks")
        out["resume_regenerated"] = stats.get("regenerated")
        out["resume_errors"] = stats.get("errors")

    # -- shed rate at 2x capacity over the real handler path ---------------
    sched = StudyScheduler(max_studies=4096, wal=False, wave_window=0.002)
    guard = AdmissionGuard(max_queue=queue, metrics=sched.metrics)
    server = ServiceHTTPServer(0, scheduler=sched, guard=guard)
    n_clients = 2 * queue
    per_client = 6
    spec = {"x": {"dist": "uniform", "args": [-5, 5]}}
    csids = [server.handle("POST", "/study", {
        "space": spec, "seed": 9000 + i, "n_startup_jobs": 2})[1]
        ["study_id"] for i in range(n_clients)]
    offered = [0]
    shed = [0]
    lost_tells = [0]
    client_errors = []
    lock = _th.Lock()

    def client(i):
        # any failure must surface after join() — a dead worker thread
        # would otherwise leave plausible-but-corrupt shed figures to
        # feed the trajectory gate
        try:
            sid = csids[i]
            done = 0
            while done < per_client:
                with lock:
                    offered[0] += 1
                code, payload = server.handle("POST", "/ask",
                                              {"study_id": sid})
                if code == 429:
                    with lock:
                        shed[0] += 1
                    time.sleep(0.002)
                    continue
                assert code == 200, payload
                t = payload["trials"][0]
                code, told = server.handle("POST", "/tell", {
                    "study_id": sid, "tid": t["tid"],
                    "loss": cheap_loss(t["params"])})
                if code != 200:
                    with lock:
                        lost_tells[0] += 1
                done += 1
        except Exception as e:  # noqa: BLE001
            with lock:
                client_errors.append(f"client {i}: "
                                     f"{type(e).__name__}: {e}")

    threads = [_th.Thread(target=client, args=(i,))
               for i in range(n_clients)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    if client_errors:
        raise RuntimeError("service_resume shed clients failed: "
                           + "; ".join(client_errors[:5]))
    out["shed_rate_frac"] = shed[0] / max(1, offered[0])
    out["shed_offered"] = offered[0]
    out["shed_429"] = shed[0]
    out["lost_tells"] = lost_tells[0]
    out["served_asks"] = offered[0] - shed[0]
    assert lost_tells[0] == 0, "tells must never shed below 4x bound"
    return out


def bench_store_integrity(n_studies=12, waves=6, reps=3, seed=0):
    """Storage-integrity plane costs (ISSUE 15), three figures:

    (1) ``checksum_overhead_frac`` — the CRC32C record seal on the
    REAL serving path: ``n_studies`` studies drive ask+tell rounds
    through ``server.handle`` with the WAL's checksum armed vs
    disarmed, interleaved ``reps`` times on twin store roots; the
    figure is the relative delta of the per-mode MIN-of-reps wall
    clock over the full round loop (scheduler noise only ever
    inflates a rep, so the minimum is the cleanest estimate — the
    profiler_overhead methodology).  The seal cost is a constant
    per-record add (never tail-concentrated: compaction's re-verify
    runs off the serving path at quiescent points), so the mean-side
    bound bounds its ``study_ask_p99_ms`` contribution too — the ≤5%
    absolute trajectory bar the acceptance pins.  The armed-mode p99
    round time rides along as ``study_round_p99_ms_checksum`` for
    scale.

    (2) ``gc_reclaimed_bytes`` — the bounded store GC against a
    PLANTED garbage set (superseded ``new/`` copies beside settled
    docs, aged ``*.tmp.*`` leftovers), so the figure measures the
    collector, not the workload.

    (3) ``scrub_records_per_sec`` — offline scrub throughput over the
    stage's own WAL + stores (and a sanity assert that the scrub of a
    healthy store reports clean)."""
    import statistics
    import tempfile

    from hyperopt_tpu.service import StudyScheduler
    from hyperopt_tpu.service.server import ServiceHTTPServer

    spec = {"x": {"dist": "uniform", "args": [-5, 5]},
            "y": {"dist": "loguniform", "args": [1e-3, 1.0]}}

    def build(root, checksum):
        sched = StudyScheduler(max_studies=4096, store_root=root,
                               wave_window=0.0)
        sched.journal.checksum = checksum
        server = ServiceHTTPServer(0, scheduler=sched)
        sids = []
        for i in range(n_studies):
            code, p = server.handle("POST", "/study", {
                "space": spec, "seed": seed + i, "n_startup_jobs": 2})
            assert code == 200, p
            sids.append(p["study_id"])
        return server, sids

    def run_rounds(server, sids, n):
        """n ask+tell rounds per study; returns (wall_sec, [round_sec])."""
        times = []
        t_all = time.perf_counter()
        for _ in range(n):
            for sid in sids:
                t0 = time.perf_counter()
                code, p = server.handle("POST", "/ask",
                                        {"study_id": sid})
                assert code == 200, p
                t = p["trials"][0]
                code, _ = server.handle("POST", "/tell", {
                    "study_id": sid, "tid": t["tid"], "loss": 0.5})
                assert code == 200
                times.append(time.perf_counter() - t0)
        return time.perf_counter() - t_all, times

    out = {"n_studies": n_studies, "waves": waves}
    with tempfile.TemporaryDirectory() as ra, \
            tempfile.TemporaryDirectory() as rb:
        on_server, on_sids = build(ra, True)
        off_server, off_sids = build(rb, False)
        # warm BOTH past the rand-startup threshold (n_startup_jobs=2)
        # so the first TPE wave's XLA compile — shared process-global
        # program cache, so only the FIRST server would pay it — lands
        # in warm-up, not inside one mode's measured window
        run_rounds(on_server, on_sids, 3)
        run_rounds(off_server, off_sids, 3)
        # min-of-reps wall clock, like profiler_overhead: scheduler
        # noise on shared hardware only ever INFLATES a rep, so the
        # per-mode minimum is the cleanest estimate of the real cost
        on_wall, off_wall = [], []
        all_on = []
        for _ in range(reps):
            w, times = run_rounds(on_server, on_sids, waves)
            on_wall.append(w)
            all_on.extend(times)
            w, _t = run_rounds(off_server, off_sids, waves)
            off_wall.append(w)
        best_on, best_off = min(on_wall), min(off_wall)
        all_on.sort()
        out["study_round_p99_ms_checksum"] = (
            all_on[min(len(all_on) - 1, int(0.99 * len(all_on)))] * 1e3)
        out["round_wall_sec_checksum"] = best_on
        out["round_wall_sec_plain"] = best_off
        out["checksum_overhead_frac"] = max(
            0.0, (best_on - best_off) / max(best_off, 1e-9))
        out["round_wall_spread_frac"] = (
            (statistics.median(on_wall) - best_on) / max(best_on, 1e-9))

        # -- planted-garbage GC --------------------------------------------
        from hyperopt_tpu.service.integrity import gc_store_root

        planted = 0
        old = time.time() - 3600
        for sid in on_sids:
            d = os.path.join(ra, sid)
            done = os.path.join(d, "done")
            for fname in os.listdir(done)[:4]:
                blob = open(os.path.join(done, fname), "rb").read()
                sup = os.path.join(d, "new", fname)
                with open(sup, "wb") as f:
                    f.write(blob)
                planted += len(blob)
                tmp = os.path.join(d, "done", fname + ".tmp.999.1")
                with open(tmp, "wb") as f:
                    f.write(b"\0" * 512)
                os.utime(tmp, (old, old))
                planted += 512
        gc = gc_store_root(ra)
        out["gc_planted_bytes"] = planted
        out["gc_reclaimed_bytes"] = gc["reclaimed_bytes"]
        out["gc_removed"] = gc["removed"]
        assert gc["reclaimed_bytes"] >= planted * 0.9, (
            f"gc reclaimed {gc['reclaimed_bytes']} of {planted} planted")

        # -- scrub throughput ----------------------------------------------
        from hyperopt_tpu.service import scrub as scrub_mod

        on_server.scheduler.drain(timeout=10.0)
        report = scrub_mod.scan_store(ra)
        assert report["clean"], report["faults"]
        out["scrub_records"] = report["records_scanned"]
        out["scrub_records_per_sec"] = report["records_per_sec"]
    return out


def bench_coldstart(n_studies=10, warm_asks=4, seed=0):
    """Cold-start compile plane (ISSUE 14): the latency a BRAND-NEW
    space signature pays on the serving path, armed vs the physics.

    Phase 1 (cold): ``n_studies`` studies over ``n_studies`` distinct,
    never-before-seen spaces drive their first TPE-eligible ask through
    a plane-armed scheduler.  ``cold_study_ask_p99_ms`` is the p99 of
    those first asks — served by the warming rand floor while the cohort
    program compiles off-thread, so it must sit at rand-floor cost, not
    XLA-compile cost (the un-armed alternative pays the full compile in
    the request; ``compile_sec_est`` records one measured compile for
    scale).  ``compile_queue_depth_max`` tracks the background queue.

    Phase 2 (bank): a FRESH plane warms from the census phase 1 wrote
    (the restart simulation — the jit LRU already holds the programs,
    but readiness is plane-local), then the same spaces re-admit and
    ask.  ``bank_hit_frac`` = bank keys that served live traffic /
    bank keys warmed; ``warm_study_ask_p99_ms`` is the post-promotion
    ask tail for comparison.
    """
    import tempfile

    import numpy as _np

    from hyperopt_tpu import hp
    from hyperopt_tpu.service.compile_plane import (CompilePlane,
                                                    census_path_for)
    from hyperopt_tpu.service.scheduler import StudyScheduler

    def spaces_for(run_tag):
        # distinct signatures: bounds depend on (seed, i), so no other
        # stage (or phase) has compiled these exact programs
        out = []
        for i in range(n_studies):
            lo = -3.0 - 0.01 * i - 0.001 * seed
            hi = 2.0 + 0.01 * i
            wire = {"x": {"dist": "uniform", "args": [lo, hi]},
                    "lr": {"dist": "loguniform", "args": [lo, 0.0]}}
            out.append(({"x": hp.uniform("x", lo, hi),
                         "lr": hp.loguniform("lr", lo, 0.0)}, wire))
        return out

    out = {}
    with tempfile.TemporaryDirectory() as root:
        plane = CompilePlane(census_path=census_path_for(root))
        sched = StudyScheduler(store_root=root, compile_plane=plane,
                               wal=False)
        built = spaces_for("cold")
        sids = []
        for i, (space, wire) in enumerate(built):
            sids.append(sched.create_study(
                space, seed=seed * 1000 + i, n_startup_jobs=1,
                space_spec={"space": wire}))
        # startup ask (rand, not warming)
        for sid in sids:
            for a in sched.ask(sid):
                sched.tell(sid, a["tid"], loss=0.5)
        cold_ms, depth_max, warming_seen = [], 0, 0
        for sid in sids:
            t0 = time.perf_counter()
            answers = sched.ask(sid)
            cold_ms.append((time.perf_counter() - t0) * 1e3)
            depth_max = max(depth_max, plane.queue_depth())
            if any(a.get("warming") for a in answers):
                warming_seen += 1
            for a in answers:
                sched.tell(sid, a["tid"], loss=0.25)
        t0 = time.perf_counter()
        plane.drain(timeout=300)
        out["compile_drain_sec"] = time.perf_counter() - t0
        # the per-program compile cost a BLOCKING ask would have paid —
        # the scale cold_study_ask_p99_ms is measured against (mean over
        # the plane's measured compile durations, not the drain tail:
        # compiles overlap the cold asks)
        h = plane.metrics.histogram("service.compile.compile_sec")
        out["compile_sec_est"] = (h.total / h.count) if h.count else None
        # post-promotion warm asks
        warm_ms = []
        for _ in range(warm_asks):
            for sid in sids:
                t0 = time.perf_counter()
                answers = sched.ask(sid)
                warm_ms.append((time.perf_counter() - t0) * 1e3)
                for a in answers:
                    sched.tell(sid, a["tid"], loss=0.1)
        plane.stop()

        cold = _np.percentile(cold_ms, [50, 99])
        warm = _np.percentile(warm_ms, [50, 99])
        out.update({
            "cold_study_ask_p50_ms": float(cold[0]),
            "cold_study_ask_p99_ms": float(cold[1]),
            "warm_study_ask_p50_ms": float(warm[0]),
            "warm_study_ask_p99_ms": float(warm[1]),
            "compile_queue_depth_max": depth_max,
            "warming_studies_seen": warming_seen,
            "n_studies": n_studies,
        })

        # phase 2: the restart — a fresh plane warms from the census
        plane2 = CompilePlane(census_path=census_path_for(root))
        t0 = time.perf_counter()
        warmed, enq = plane2.warm_from_census(top_n=n_studies)
        plane2.drain(timeout=300)
        out["bank_warm_sec"] = time.perf_counter() - t0
        out["bank_warmed_sync"] = warmed
        sched2 = StudyScheduler(store_root=root, compile_plane=plane2,
                                wal=False)
        sids2 = []
        for i, (space, wire) in enumerate(built):
            sids2.append(sched2.create_study(
                space, seed=seed * 1000 + 500 + i, n_startup_jobs=1,
                space_spec={"space": wire}))
        rewarming = 0
        for sid in sids2:
            for a in sched2.ask(sid):
                sched2.tell(sid, a["tid"], loss=0.5)
        for sid in sids2:
            if any(a.get("warming") for a in sched2.ask(sid)):
                rewarming += 1
        bank = plane2.bank_stats()
        out["bank_hit_frac"] = (bank["hits"] / bank["keys"]
                                if bank["keys"] else 0.0)
        out["bank_rewarming_studies"] = rewarming
        plane2.stop()
    return out


def bench_fleet_scale(n_studies=24, waves=4, n_shards=8, seed=0):
    """Replicated serving fleet (ISSUE 12): ask/tell throughput through
    in-process fleet replicas at 1→4 replicas on one box
    (``fleet_studies_per_sec`` — the headline key gates the LARGEST
    replica count), plus the shard failover latency
    (``reclaim_latency_sec``): a replica "dies" (stops heartbeating —
    the SIGKILL analog; its leases age past the TTL) and the stage
    measures wall seconds until a survivor holds the reclaimed lease
    AND serves an ask for one of the dead replica's studies, WAL replay
    included.  One replica == one FleetReplica + handler (threads, not
    subprocesses: the stage measures shard routing + per-shard WAL
    costs, not the box's core count — FLEET_GATE's smoke covers real
    processes)."""
    import tempfile
    import threading as _th

    import numpy as _np

    from hyperopt_tpu.service import FleetReplica
    from hyperopt_tpu.service.server import ServiceHTTPServer

    def cheap_loss(params):
        return float(_np.sin(sum(float(v) for v in params.values())))

    spec = {"x": {"dist": "uniform", "args": [-5, 5]}}
    out = {"n_studies": n_studies, "waves": waves, "n_shards": n_shards,
           "by_replicas": {}}

    for n_replicas in (1, 2, 4):
        with tempfile.TemporaryDirectory() as root:
            replicas = [
                FleetReplica(root, n_shards=n_shards,
                             replica_id=f"bench-r{i}",
                             addr=f"inproc://r{i}", lease_ttl=60.0,
                             scheduler_kwargs={"wave_window": 0.0})
                for i in range(n_replicas)]
            servers = [ServiceHTTPServer(0, fleet=r) for r in replicas]
            for r in replicas:
                r.join()
            for _ in range(3):  # converge the shard balance
                for r in replicas:
                    r.steward_once()
            # place studies round-robin across replicas (place_study
            # redraws until the id lands in the PLACING replica's own
            # shards, so always starting at servers[0] would put every
            # study there and leave the other replicas idle — the
            # scaling metric must drive all of them)
            per = {i: [] for i in range(n_replicas)}
            for j in range(n_studies):
                for k in range(n_replicas):
                    i = (j + k) % n_replicas
                    code, payload = servers[i].handle("POST", "/study", {
                        "space": spec, "seed": seed + j,
                        "n_startup_jobs": 2})
                    if code == 200:
                        per[i].append(payload["study_id"])
                        break
                else:
                    raise RuntimeError("no replica could place a study")
            # warm-up round (pays the per-cohort XLA compiles)
            for i, srv in enumerate(servers):
                for sid in per[i]:
                    code, p = srv.handle("POST", "/ask", {"study_id": sid})
                    assert code == 200, p
                    t = p["trials"][0]
                    srv.handle("POST", "/tell", {
                        "study_id": sid, "tid": t["tid"],
                        "loss": cheap_loss(t["params"])})
            errors = []

            def drive(i):
                try:
                    srv = servers[i]
                    for _ in range(waves):
                        for sid in per[i]:
                            code, p = srv.handle("POST", "/ask",
                                                 {"study_id": sid})
                            assert code == 200, p
                            t = p["trials"][0]
                            code, p2 = srv.handle("POST", "/tell", {
                                "study_id": sid, "tid": t["tid"],
                                "loss": cheap_loss(t["params"])})
                            assert code == 200, p2
                except Exception as e:  # noqa: BLE001
                    errors.append(f"replica {i}: {type(e).__name__}: {e}")

            t0 = time.perf_counter()
            threads = [_th.Thread(target=drive, args=(i,))
                       for i in range(n_replicas)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            dt = time.perf_counter() - t0
            if errors:
                raise RuntimeError("fleet_scale drivers failed: "
                                   + "; ".join(errors[:5]))
            out["by_replicas"][str(n_replicas)] = {
                "fleet_studies_per_sec": n_studies * waves / dt,
                "rounds": n_studies * waves,
                "elapsed_sec": dt,
                "shards_held": [len(r.schedulers) for r in replicas],
            }
    # the gated scalar: throughput at the widest fleet
    out["fleet_studies_per_sec"] = (
        out["by_replicas"]["4"]["fleet_studies_per_sec"])

    # -- shard failover: dead replica -> survivor serves its studies -------
    with tempfile.TemporaryDirectory() as root:
        ttl = 0.5
        dead = FleetReplica(root, n_shards=4, replica_id="bench-dead",
                            addr="inproc://dead", lease_ttl=ttl,
                            scheduler_kwargs={"wave_window": 0.0})
        dead.join()
        dead.steward_once()  # claims everything
        sdead = ServiceHTTPServer(0, fleet=dead)
        code, payload = sdead.handle("POST", "/study", {
            "space": spec, "seed": seed, "n_startup_jobs": 2})
        sid = payload["study_id"]
        for _ in range(3):
            code, p = sdead.handle("POST", "/ask", {"study_id": sid})
            t = p["trials"][0]
            sdead.handle("POST", "/tell", {"study_id": sid,
                                           "tid": t["tid"],
                                           "loss": cheap_loss(t["params"])})
        survivor = FleetReplica(root, n_shards=4,
                                replica_id="bench-survivor",
                                addr="inproc://survivor", lease_ttl=ttl,
                                scheduler_kwargs={"wave_window": 0.0})
        survivor.join()
        ssurv = ServiceHTTPServer(0, fleet=survivor)
        # the death: the replica stops heartbeating (nothing else) — the
        # latency measured is TTL expiry + reclaim + WAL replay + serve
        t0 = time.perf_counter()
        deadline = t0 + 30.0
        served = False
        while time.perf_counter() < deadline:
            survivor.steward_once()
            code, p = ssurv.handle("POST", "/ask", {"study_id": sid})
            if code == 200:
                served = True
                break
            time.sleep(0.02)
        if not served:
            raise RuntimeError("survivor never served the dead "
                               "replica's study")
        out["reclaim_latency_sec"] = time.perf_counter() - t0
        out["reclaim_lease_ttl_sec"] = ttl
        out["reclaim_adoptions"] = survivor.adoptions
    return out


def bench_pallas_ei(n=8192, reps=5, seed=0):
    """jnp-vs-pallas crossover for the fused two-model EI score
    (``pallas_ei.ei_diff``) by COMPONENT COUNT — the axis the MEASURED
    VERDICT in pallas_ei.py says decides the winner (very large component
    tables break XLA's fusion; small ones don't).  Keeps that verdict
    current round over round: on a TPU backend both paths run; elsewhere
    the jnp twin alone is recorded with ``pallas_available: false``."""
    import jax
    import jax.numpy as jnp

    from hyperopt_tpu import pallas_ei

    rng = np.random.default_rng(seed)
    x = jnp.asarray(rng.uniform(-3, 3, n).astype(np.float32))
    avail = pallas_ei.pallas_available()
    out = {"n_candidates": n, "pallas_available": bool(avail),
           "by_components": {}}
    crossover = None
    for m in (8, 64, 256, 1024):
        def mix():
            w = rng.uniform(0.1, 1.0, m).astype(np.float32)
            return (jnp.asarray(w / w.sum()),
                    jnp.asarray(rng.uniform(-3, 3, m).astype(np.float32)),
                    jnp.asarray(rng.uniform(0.1, 2.0, m).astype(np.float32)))

        wb, mb, sb = mix()
        wa, ma, sa = mix()
        jnp_fn = jax.jit(pallas_ei.ei_diff_reference)

        def timeit(fn):
            jax.block_until_ready(fn(x, wb, mb, sb, wa, ma, sa))
            t0 = time.perf_counter()
            for _ in range(reps):
                r = fn(x, wb, mb, sb, wa, ma, sa)
            jax.block_until_ready(r)
            return (time.perf_counter() - t0) / reps

        entry = {"jnp_sec": timeit(jnp_fn)}
        if avail:
            entry["pallas_sec"] = timeit(jax.jit(pallas_ei.ei_diff))
            entry["pallas_speedup"] = entry["jnp_sec"] / max(
                entry["pallas_sec"], 1e-12)
            if crossover is None and entry["pallas_speedup"] > 1.0:
                crossover = m
        out["by_components"][str(m)] = entry
    if avail:
        out["crossover_components"] = crossover  # None: jnp won everywhere
    return out


#: the megakernel grid: (hist_cap, n_EI_candidates) — components is cap+1
_MEGAKERNEL_GRID = ((32, 256), (32, 1024), (128, 1024))


def bench_megakernel(reps=4, seed=0):
    """ISSUE 19 stage: the quantized-history fused-suggest megakernel.

    Three measurements.  (1) Fused (armed: Pallas on TPU, interpret
    emulation elsewhere) vs unfused (jnp cohort) candidates/sec over a
    (components, candidates, hist_cap) grid through the REAL
    study-batched tick program (``tpe.build_suggest_batched`` — the
    megakernel arms inside it); the largest grid point's armed
    throughput rides the trajectory as ``megakernel_cand_per_sec``.
    (2) The int8 resident-history byte fraction at EQUAL ``hist_cap``
    vs f32 (vals int8 + losses bf16), gated absolute ≤0.30 as
    ``megakernel_int8_bytes_frac`` — the acceptance criterion that
    quantization pays for its 4× cap.  (3) The tpe quality keys re-run
    with the kernel ARMED over a small zoo mix through the real
    scheduler tick (``armed_*`` keys — the disarmed ``search_quality``
    table stays the gated series).  On CPU the armed path runs the
    interpret emulation, so the fused-vs-unfused RATIO is meaningless
    there — only the armed trend and the byte fraction are (SURVEY.md
    §4); on a TPU backend the ratio is the tentpole's headline."""
    import os

    import jax
    import jax.numpy as jnp

    from hyperopt_tpu import hp, megakernel
    from hyperopt_tpu.algos import tpe
    from hyperopt_tpu.base import Domain, PaddedHistory

    space = {f"x{i}": hp.uniform(f"x{i}", -5, 5) for i in range(6)}
    cs = Domain(None, space).cs
    L = len(cs.labels)
    S, B = 4, 4
    armed_mode = "1" if megakernel.pallas_available() else "interpret"
    rng = np.random.default_rng(seed)
    seeds = np.stack([tpe._seed_words(1000 + s) for s in range(S)])
    ids = np.asarray([[3 + s * B + j for j in range(B)]
                      for s in range(S)], np.uint32)

    def stack_at(cap, n_live):
        devs = []
        for _ in range(S):
            vals = {l: np.zeros(cap, np.float32) for l in cs.labels}
            act = {l: np.zeros(cap, bool) for l in cs.labels}
            losses = np.full(cap, np.inf, np.float32)
            has = np.zeros(cap, bool)
            for i in range(n_live):
                for l in cs.labels:
                    vals[l][i] = rng.uniform(-4, 4)
                    act[l][i] = True
                losses[i] = rng.uniform()
                has[i] = True
            devs.append(
                {"vals": {l: jnp.asarray(vals[l]) for l in cs.labels},
                 "active": {l: jnp.asarray(act[l]) for l in cs.labels},
                 "losses": jnp.asarray(losses),
                 "has_loss": jnp.asarray(has)})
        return jax.tree.map(lambda *xs: jnp.stack(xs), *devs)

    def measure(cap, n_cand, mode):
        os.environ["HYPEROPT_TPU_MEGAKERNEL"] = mode
        cfg = {"prior_weight": 1.0, "n_EI_candidates": n_cand,
               "gamma": 0.25, "LF": 25, "ei_select": "argmax",
               "ei_tau": 1.0, "prior_eps": 0.0}
        fn = tpe.build_suggest_batched(cs, cfg, S, cap, B, donate=False)
        stack = stack_at(cap, n_live=cap // 2)
        rows = np.zeros((S, 16, 2 * L + 3), np.float32)
        rows[:, :, -1] = cap
        jax.block_until_ready(fn(stack, rows, seeds, ids))
        t0 = time.perf_counter()
        for _ in range(reps):
            r = fn(stack, rows, seeds, ids)
        jax.block_until_ready(r)
        return (time.perf_counter() - t0) / reps

    prev = os.environ.get("HYPEROPT_TPU_MEGAKERNEL")
    out = {"S": S, "B": B, "reps": reps, "armed_mode": armed_mode,
           "bar": "int8 history <= 0.3x f32 bytes at equal cap",
           "by_point": {}}
    try:
        gated = None
        for cap, n_cand in _MEGAKERNEL_GRID:
            dt_off = measure(cap, n_cand, "off")
            dt_on = measure(cap, n_cand, armed_mode)
            n_prop = S * B * n_cand
            entry = {"components": cap + 1, "candidates": n_cand,
                     "hist_cap": cap,
                     "unfused_cand_per_sec": n_prop / dt_off,
                     "fused_cand_per_sec": n_prop / dt_on,
                     "fused_speedup": dt_off / max(dt_on, 1e-12)}
            out["by_point"][f"m{cap + 1}_c{n_cand}"] = entry
            gated = entry["fused_cand_per_sec"]
        out["megakernel_cand_per_sec"] = gated  # largest grid point
        out["megakernel_fallbacks"] = megakernel.fallback_count()

        # int8 vs f32 resident history bytes at the SAME cap (the bf16
        # comparison in the sharded_suggest stage, pushed to codes)
        def hist_bytes(dtype):
            ph = PaddedHistory(cs.labels, hist_dtype=dtype)
            ph.ensure_qparams(cs)
            for i in range(100):
                ph.append({l: float(i % 7) - 3.0 for l in cs.labels},
                          float(i))
            dev = ph.device_view()
            return int(sum(dev["vals"][l].nbytes for l in cs.labels)
                       + dev["losses"].nbytes)

        f32b, i8b = hist_bytes("float32"), hist_bytes("int8")
        out["history_bytes_f32"] = f32b
        out["history_bytes_int8"] = i8b
        out["megakernel_int8_bytes_frac"] = i8b / max(f32b, 1)

        # tpe quality keys re-run ARMED over a small zoo mix through the
        # real scheduler tick — visibility, not the gated series
        os.environ["HYPEROPT_TPU_MEGAKERNEL"] = armed_mode
        from hyperopt_tpu.obs.quality import summarize_run
        from hyperopt_tpu.service.scheduler import StudyScheduler
        from hyperopt_tpu.zoo import make_study_mix

        items = make_study_mix(3, 1)
        sched = StudyScheduler(wal=False)
        sids = [sched.create_study(m.domain.space, seed=m.seed,
                                   n_startup_jobs=5) for m in items]
        done = [0] * len(items)
        while any(done[i] < items[i].budget for i in range(len(items))):
            wave = [(sids[i], min(2, items[i].budget - done[i]))
                    for i in range(len(items))
                    if done[i] < items[i].budget]
            answers = sched.ask_many(wave)
            for i, m in enumerate(items):
                for a in answers.get(sids[i], ()):
                    sched.tell(sids[i], a["tid"],
                               float(m.domain.objective(a["params"])))
                    done[i] += 1
        t2t, regrets, solved = [], [], 0
        for i, m in enumerate(items):
            s = summarize_run(
                list(sched._studies[sids[i]].trials.losses())[:m.budget],
                m.budget, loss_target=m.domain.loss_target,
                optimum=m.domain.optimum)
            t2t.append(s["trials_to_target"])
            solved += 1 if s["solved"] else 0
            if s["final_regret"] is not None:
                regrets.append(s["final_regret"])
        out["armed_trials_to_target_tpe"] = float(np.mean(t2t))
        if regrets:
            out["armed_final_regret_tpe"] = float(np.mean(regrets))
        out["armed_solved_frac_tpe"] = solved / len(items)
        out["armed_quality_fallbacks"] = megakernel.fallback_count()
    finally:
        if prev is None:
            os.environ.pop("HYPEROPT_TPU_MEGAKERNEL", None)
        else:
            os.environ["HYPEROPT_TPU_MEGAKERNEL"] = prev
    return out


# ---------------------------------------------------------------------------
# hang-proof orchestration (see module docstring)
# ---------------------------------------------------------------------------

# every jax-touching stage, in the order the child runs them.  Each entry:
# (stage name, thunk).  Thunks are resolved inside the child process only.
_JAX_STAGES = (
    # FIRST: peak_bytes_in_use is process-cumulative, so the devmem
    # stage's peak must be recorded before any other stage allocates
    ("devmem", bench_devmem),
    ("jax_same_grid", lambda: bench_jax(n_cand=24)),
    ("jax_scaled", lambda: bench_jax(n_cand=8192)),
    ("jax_batched", lambda: bench_jax(n_cand=8192, batch=64, repeats=20)),
    ("jax_batched_256", lambda: bench_jax(n_cand=8192, batch=256, repeats=10)),
    # wide-batch design point: BASELINE config #5 proposes 10k trials per
    # generation, so kilowide proposal batches are the realistic shape; the
    # per-dispatch fixed overhead (~7 ms over the tunnel) amortizes away and
    # the kernel runs at its ~275M cand/s saturation rate
    ("jax_batched_1024", lambda: bench_jax(n_cand=8192, batch=1024, repeats=5)),
    ("branin_device_1000", bench_branin_device),
    ("branin_fmin_tpe", bench_branin_fmin),
    # per-ask latency percentiles of the interactive loop, plus the
    # lookahead=1 dispatch/readback-overlap comparison (ISSUE 4)
    ("ask_latency", bench_ask_latency),
    # persistent-compilation-cache cold vs warm (forced-CPU subprocesses)
    ("compile_cache", bench_compile_cache),
    # forensics overhead bar: flight ring on vs off on the disarmed loop
    ("flight_overhead", bench_flight_overhead),
    # capture-plane overhead bar: armed-but-idle profiler vs off (ISSUE 7)
    ("profiler_overhead", bench_profiler_overhead),
    # request-trace + SLO plane overhead bar: armed vs disarmed per-ask
    # delta through the real handler path (ISSUE 11)
    ("trace_overhead", bench_trace_overhead),
    # elastic-fleet recovery latency: dead controller -> survivor holds the
    # reclaimed shard lease (ISSUE 8; bench_gate key recovery_latency_sec)
    ("fleet_recovery", bench_fleet_recovery),
    ("hr_conditional_tpe", bench_hr_conditional),
    ("parallel_trials_10k", bench_parallel_trials),
    ("parallel_trials_10k_tpe", bench_parallel_trials_tpe),
    ("parallel_trials_10k_tpe_rosen",
     lambda: bench_parallel_trials_tpe(domain="rosenbrock4")),
    # BASELINE config #5's HPO-B role: the seeded tabular-surrogate domain
    # (zoo._hpob_surrogate) instead of the Branin stand-in
    ("parallel_trials_10k_tpe_hpob",
     lambda: bench_parallel_trials_tpe(domain="hpob_surrogate")),
    ("ml_cv", bench_ml_cv),
    # jnp-vs-pallas EI crossover by component count (ISSUE 6 satellite):
    # keeps pallas_ei.py's MEASURED VERDICT current; jnp-only off TPU
    ("pallas_ei", bench_pallas_ei),
    # ISSUE 19: quantized-history fused-suggest megakernel — fused vs
    # unfused cand/sec by (components, candidates, hist_cap), the int8
    # byte fraction at equal cap (gated ≤0.30 absolute), and the tpe
    # quality keys re-run with the kernel armed
    ("megakernel", bench_megakernel),
    # ISSUE 9 headline: 1k concurrent studies batched onto cohort ticks —
    # studies/sec, per-ask p99, slot utilization, vs the sequential loop
    ("multi_study", bench_multi_study),
    # ISSUE 10: durable serving plane — crash-restart availability gap
    # (WAL replay + in-flight regeneration) and the shed rate at 2x ask
    # capacity through the real handler path
    ("service_resume", bench_service_resume),
    # ISSUE 12: replicated serving fleet — ask/tell throughput across
    # 1→4 in-process replicas (lease-partitioned shards, per-shard
    # epoch WALs) and the shard failover latency after a replica death
    ("fleet_scale", bench_fleet_scale),
    # ISSUE 14: cold-start compile plane — brand-new-space first-ask
    # tail at the warming rand floor vs post-promotion warm asks, the
    # background compile queue, and the census kernel bank's reuse
    # across a simulated restart
    ("coldstart", bench_coldstart),
    # ISSUE 15: storage-integrity plane — WAL checksum overhead on the
    # real serving path (gated ≤5% absolute), planted-garbage GC
    # reclaim, offline scrub throughput
    ("store_integrity", bench_store_integrity),
    # ISSUE 16: the standing per-algo search-quality table — the zoo mix
    # to budget under tpe/rand/anneal/mix/atpe (the megakernel's quality
    # bars: trials_to_target_*, final_regret_*, solved_frac_*)
    ("search_quality", bench_search_quality),
    # ISSUE 16: quality-plane overhead bar — armed vs disarmed per-tell
    # delta through the real handler path (gated ≤5% absolute)
    ("quality_overhead", bench_quality_overhead),
    # ISSUE 17: cost-attribution overhead bar (armed vs disarmed per-tell
    # delta, gated ≤5% absolute) + the deterministic skewed-placement
    # shard_heat_skew pin
    ("load_attribution", bench_load_attribution),
    # ISSUE 18: blackbox-prober bars — tenant overhead with a hot canary
    # prober armed (gated ≤5% absolute) + inject→detect wall latency of
    # a chaos-corrupted serving path
    ("blackbox_probe", bench_blackbox_probe),
    # ISSUE 20: tenant-observatory bars — armed-vs-disarmed tenant-plane
    # per-ask delta (gated ≤5% absolute) + the light-tenant p99 skew
    # under a noisy neighbour with the DRR wave packer armed
    ("tenant_fairness", bench_tenant_fairness),
)

_PROBE_SNIPPET = (
    "import jax, jax.numpy as jnp; d = jax.devices(); "
    "x = jnp.ones((128, 128)); (x @ x).block_until_ready(); "
    "print('PROBE_OK', d[0].platform)"
)


def _forced_cpu_env(env, n_devices=None):
    from hyperopt_tpu._env import forced_cpu_env

    return forced_cpu_env(env, n_devices)


def _probe_backend(timeout=120):
    """Return the ambient jax platform name, or None if init fails/hangs."""
    try:
        proc = subprocess.run(
            [sys.executable, "-c", _PROBE_SNIPPET],
            capture_output=True, text=True, timeout=timeout,
        )
    except subprocess.TimeoutExpired:
        return None
    for line in (proc.stdout or "").splitlines():
        if line.startswith("PROBE_OK"):
            return line.split()[1]
    return None


def _jax_stage_child(only=None):
    """Child mode: run jax stages (all, or just ``only``), one flushed JSON
    line per stage."""
    import jax

    # persistent XLA compilation cache: a fresh bench process pays compile
    # time only the first time a given kernel shape is ever seen on this
    # machine (jit caches are per-process; the disk cache is not)
    jax.config.update("jax_compilation_cache_dir", "/root/repo/.jax_cache")
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    platform = jax.devices()[0].platform

    stages = [(n, t) for n, t in _JAX_STAGES if only is None or n in only]
    for name, thunk in stages:
        try:
            result = thunk()
            result.setdefault("backend", platform)
            rec = {"stage": name, "ok": True, "result": result}
        except Exception as e:  # a stage failure must not kill later stages
            rec = {"stage": name, "ok": False,
                   "error": f"{type(e).__name__}: {e}"}
        print(json.dumps(rec, default=float), flush=True)


def _run_stage_child(env, timeout, only=None):
    """Run the stage child; return {stage: record} for whatever completed.

    A hang is handled by the timeout: the child is killed and the stages it
    already flushed are recovered from the partial stdout.
    """
    cmd = [sys.executable, os.path.abspath(__file__), "--jax-stages"]
    if only:
        cmd += list(only)
    try:
        proc = subprocess.run(
            cmd,
            env=env, capture_output=True, text=True, timeout=timeout,
            cwd=os.path.dirname(os.path.abspath(__file__)),
        )
        out, err = proc.stdout, proc.stderr
    except subprocess.TimeoutExpired as e:
        out = e.stdout.decode() if isinstance(e.stdout, bytes) else (e.stdout or "")
        err = e.stderr.decode() if isinstance(e.stderr, bytes) else (e.stderr or "")
        print(f"bench: stage child timed out after {timeout}s", file=sys.stderr)
    stages = {}
    for line in (out or "").splitlines():
        try:
            rec = json.loads(line)
        except ValueError:
            continue
        if isinstance(rec, dict) and "stage" in rec:
            stages[rec["stage"]] = rec
    if not stages and err:
        print(f"bench: stage child stderr tail:\n{err[-2000:]}", file=sys.stderr)
    return stages


def main():
    detail = {}
    detail["numpy_cpu"] = bench_numpy()

    platform = _probe_backend()
    stages = {}
    if platform is not None:
        stages = _run_stage_child(dict(os.environ), timeout=1500)
    missing = [n for n, _ in _JAX_STAGES
               if not stages.get(n, {}).get("ok")]
    if missing:
        print(f"bench: retrying stages on forced CPU: {missing}",
              file=sys.stderr)
        cpu_stages = _run_stage_child(_forced_cpu_env(os.environ),
                                      timeout=1200, only=missing)
        for n in missing:
            rec = cpu_stages.get(n)
            if rec and rec.get("ok"):
                rec["result"]["backend"] = "cpu-fallback"
                stages[n] = rec

    for name, _ in _JAX_STAGES:
        rec = stages.get(name)
        detail[name] = (rec["result"] if rec and rec.get("ok")
                        else {"error": (rec or {}).get("error", "not run")})
    detail["sharded_scaling_cpu_mesh"] = bench_sharded_scaling()
    # the ISSUE 6 headline stage: fused tell+ask sharded over the 8-device
    # CPU mesh — candidates/sec per shard count (bench_gate key
    # ``sharded_cand_per_sec``), 64x candidate batches, bf16 history bytes
    detail["sharded_suggest"] = bench_sharded_suggest()
    # device-utilization roll-up: achieved FLOP/s + busy fraction for every
    # stage that reported one, in one block — the bench_*_detail.txt
    # artifacts answer "how hard did the hardware work" without re-running
    util_summary = {}
    for name, _ in _JAX_STAGES:
        rec = stages.get(name)
        if not (rec and rec.get("ok")):
            continue
        u = (rec["result"].get("device_utilization")
             or (rec["result"].get("obs") or {}).get("utilization"))
        if u:
            util_summary[name] = u
    detail["device_utilization"] = util_summary
    print(json.dumps(detail, indent=2, default=float), file=sys.stderr)

    # headline = the best of the batched design points (all honest
    # strict-readback best-of-3 measurements; wider batches amortize the
    # fixed dispatch overhead toward the kernel's saturation rate — the
    # BASELINE config-#5 parallel-suggest shape proposes 10k per generation)
    candidates = [stages.get("jax_batched"), stages.get("jax_batched_256"),
                  stages.get("jax_batched_1024")]
    ok = [c for c in candidates if c and c.get("ok")]
    headline = max(ok, key=lambda c: c["result"]["candidates_per_sec"]) if ok else None
    if headline:
        cps = headline["result"]["candidates_per_sec"]
        backend = headline["result"].get("backend", "unknown")
        speedup = cps / detail["numpy_cpu"]["candidates_per_sec"]
    else:
        # total jax failure: still emit the line so the round records data
        cps = detail["numpy_cpu"]["candidates_per_sec"]
        backend = "none"
        speedup = 1.0
    # perf breakdown (compile sec / execute sec / cache hit rate) from the
    # obs metrics the stage children collected: BENCH_*.json tracks where
    # the time goes, not just the headline number
    obs_summary = {}
    for stage_name in ("branin_device_1000", "branin_fmin_tpe"):
        rec = stages.get(stage_name)
        if rec and rec.get("ok") and rec["result"].get("obs"):
            obs_summary[stage_name] = rec["result"]["obs"]
    # the interactive-loop latency shape rides the headline line: per-ask
    # p50/p95/p99 for tpe+rand plus whether lookahead=1 improved the
    # blocked-time p50 over the synchronous loop (ISSUE 4 acceptance bar)
    rec = stages.get("ask_latency")
    if rec and rec.get("ok"):
        r = rec["result"]
        obs_summary["ask_latency"] = {
            "tpe": {k: r.get("tpe", {}).get(k)
                    for k in ("ask_p50_ms", "ask_p95_ms", "ask_p99_ms")},
            "rand": {k: r.get("rand", {}).get(k)
                     for k in ("ask_p50_ms", "ask_p95_ms", "ask_p99_ms")},
            "pipelined_p50_improved": (r.get("pipelined")
                                       or {}).get("p50_improved"),
        }
    # cold-vs-warm persistent-compile-cache seconds (ISSUE 4 tentpole #4)
    rec = stages.get("compile_cache")
    if rec and rec.get("ok"):
        obs_summary["compile_cache"] = {
            k: rec["result"].get(k)
            for k in ("cold_fmin_sec", "warm_fmin_sec", "warm_speedup")}
    # the flight-recorder before/after delta rides the headline line: the
    # "<2% disarmed overhead" acceptance bar stays visible round over round
    rec = stages.get("flight_overhead")
    if rec and rec.get("ok"):
        obs_summary["flight_overhead"] = {
            k: rec["result"].get(k)
            for k in ("flight_off_sec", "flight_on_sec", "overhead_frac")}
    # the armed-but-idle capture-plane delta rides the headline line: the
    # "annotations are free while no capture runs" bar, gated absolute
    # lower-is-better (profiler_overhead_frac) by scripts/bench_gate.py
    rec = stages.get("profiler_overhead")
    if rec and rec.get("ok"):
        obs_summary["profiler_overhead"] = {
            k: rec["result"].get(k)
            for k in ("profiler_off_sec", "profiler_on_sec",
                      "profiler_overhead_frac")}
    # the request-trace/SLO plane delta rides the headline line: the
    # armed-vs-disarmed per-ask cost through the real handler path
    # (ISSUE 11), gated absolute lower-is-better (trace_overhead_frac)
    rec = stages.get("trace_overhead")
    if rec and rec.get("ok"):
        obs_summary["trace_overhead"] = {
            k: rec["result"].get(k)
            for k in ("trace_off_sec", "trace_on_sec",
                      "trace_overhead_frac", "trace_overhead_us_per_ask")}
    # peak device memory rides the headline line (lower-is-better, gated by
    # scripts/bench_gate.py): a leaked cap-sized buffer fails the gate
    rec = stages.get("devmem")
    if rec and rec.get("ok"):
        obs_summary["devmem"] = {
            k: rec["result"].get(k)
            for k in ("peak_hbm_bytes", "bytes_limit", "hbm_watermark_frac",
                      "history_bytes", "memory_stats_available")}
    # the sharded fused suggest (ISSUE 6 tentpole) rides the headline line:
    # candidates/sec by shard count, the 64x candidate-batch multiple, and
    # the bf16 history byte reduction at unchanged cap
    ss = detail.get("sharded_suggest") or {}
    if "error" not in ss and ss:
        obs_summary["sharded_suggest"] = {
            "cand_per_sec_by_shards": {
                k.split("_", 1)[1]: round(v["sharded_cand_per_sec"], 1)
                for k, v in ss.items() if k.startswith("shards_")},
            "cand_batch_multiple": ss.get("cand_batch_multiple"),
            "bf16_reduction_x": ss.get("bf16_reduction_x"),
        }
    # the multi-study serving throughput (ISSUE 9 tentpole) rides the
    # headline line: studies/sec at 1k concurrent studies, per-ask p99,
    # slot utilization and the vs-sequential-loop multiple
    rec = stages.get("multi_study")
    if rec and rec.get("ok"):
        obs_summary["multi_study"] = {
            k: rec["result"].get(k)
            for k in ("n_studies", "studies_per_sec", "study_ask_p99_ms",
                      "slot_utilization_frac", "vs_sequential_x")}
    # the durable-serving stage (ISSUE 10) rides along: crash-restart
    # availability gap + overload shed rate at 2x ask capacity
    rec = stages.get("service_resume")
    if rec and rec.get("ok"):
        obs_summary["service_resume"] = {
            k: rec["result"].get(k)
            for k in ("resume_latency_sec", "resume_studies",
                      "resume_regenerated", "shed_rate_frac",
                      "lost_tells")}
    # the replicated-fleet stage (ISSUE 12) rides along: throughput by
    # replica count and the shard failover (reclaim + WAL replay) latency
    rec = stages.get("fleet_scale")
    if rec and rec.get("ok"):
        r = rec["result"]
        obs_summary["fleet_scale"] = {
            "by_replicas": {
                k: round(v["fleet_studies_per_sec"], 1)
                for k, v in (r.get("by_replicas") or {}).items()},
            "fleet_studies_per_sec": r.get("fleet_studies_per_sec"),
            "reclaim_latency_sec": r.get("reclaim_latency_sec"),
        }
    # the cold-start stage (ISSUE 14) rides along: brand-new-space
    # first-ask tail (warming rand floor) vs warm, compile queue depth,
    # and the census kernel bank's reuse across a simulated restart
    rec = stages.get("coldstart")
    if rec and rec.get("ok"):
        obs_summary["coldstart"] = {
            k: rec["result"].get(k)
            for k in ("cold_study_ask_p99_ms", "warm_study_ask_p99_ms",
                      "compile_queue_depth_max", "bank_hit_frac",
                      "warming_studies_seen")}
    # the storage-integrity stage (ISSUE 15) rides along: checksum
    # overhead on the serving path, GC reclaim, scrub throughput
    rec = stages.get("store_integrity")
    if rec and rec.get("ok"):
        obs_summary["store_integrity"] = {
            k: rec["result"].get(k)
            for k in ("checksum_overhead_frac", "gc_reclaimed_bytes",
                      "scrub_records_per_sec",
                      "study_round_p99_ms_checksum")}
    # the per-algo search-quality table (ISSUE 16) rides along: the
    # megakernel's quality bars, visible round over round
    rec = stages.get("search_quality")
    if rec and rec.get("ok"):
        r = rec["result"]
        obs_summary["search_quality"] = {
            a: {k: (r.get("per_algo") or {}).get(a, {}).get(k)
                for k in ("trials_to_target", "final_regret",
                          "solved_frac")}
            for a in ("tpe", "rand", "anneal", "mix", "atpe")}
    # the quality-plane overhead bar (ISSUE 16) rides along: armed vs
    # disarmed per-tell delta, gated absolute (quality_overhead_frac)
    rec = stages.get("quality_overhead")
    if rec and rec.get("ok"):
        obs_summary["quality_overhead"] = {
            k: rec["result"].get(k)
            for k in ("quality_off_sec", "quality_on_sec",
                      "quality_overhead_frac",
                      "quality_overhead_us_per_tell")}
    # the cost-attribution bar (ISSUE 17) rides the same way: armed vs
    # disarmed delta + the skewed-placement heat-skew pin
    rec = stages.get("load_attribution")
    if rec and rec.get("ok"):
        obs_summary["load_attribution"] = {
            k: rec["result"].get(k)
            for k in ("load_off_sec", "load_on_sec",
                      "attribution_overhead_frac",
                      "attribution_overhead_us_per_tell",
                      "shard_heat_skew")}
    # the megakernel stage (ISSUE 19) rides along: armed cand/sec at the
    # largest grid point, the int8 byte fraction at equal cap, and the
    # armed tpe quality re-run over the small zoo mix
    rec = stages.get("megakernel")
    if rec and rec.get("ok"):
        obs_summary["megakernel"] = {
            k: rec["result"].get(k)
            for k in ("armed_mode", "megakernel_cand_per_sec",
                      "megakernel_int8_bytes_frac",
                      "megakernel_fallbacks",
                      "armed_trials_to_target_tpe",
                      "armed_final_regret_tpe",
                      "armed_solved_frac_tpe")}
    # the blackbox-prober bars (ISSUE 18): tenant overhead with a hot
    # prober armed + chaos inject→detect latency
    rec = stages.get("blackbox_probe")
    if rec and rec.get("ok"):
        obs_summary["blackbox_probe"] = {
            k: rec["result"].get(k)
            for k in ("probe_off_rps", "probe_on_rps",
                      "probe_overhead_frac", "detect_cycles",
                      "probe_detection_latency_sec")}
    # the headline stage IS the TPE candidate-proposal path: surface its
    # achieved-FLOP/s + busy fraction on the metric line itself, so the
    # hardware-efficiency claim is answerable from the one-line artifact
    headline_util = (headline["result"].get("device_utilization", {})
                     if headline else {})
    headline_rec = {
        "metric": "tpe_candidate_proposal_throughput",
        "value": round(cps, 1),
        "unit": "candidates/sec",
        "vs_baseline": round(speedup, 2),
        "backend": backend,
        "device_utilization": headline_util,
        "obs": obs_summary,
    }
    print(json.dumps(headline_rec, default=float))

    # append this run to the perf-trajectory store (.obs/trajectory.jsonl,
    # obs/trajectory.py): headline keys + tail-mined latency/memory metrics
    # + git rev + mesh/dtype config, so scripts/bench_gate.py gates against
    # a windowed history instead of one baseline file.  Fail-open — a
    # store problem must never fail the bench that just ran.
    try:
        from hyperopt_tpu.obs import trajectory

        config = {
            "hist_dtype": os.environ.get("HYPEROPT_TPU_HIST_DTYPE", "f32"),
            "shard": os.environ.get("HYPEROPT_TPU_SHARD") or None,
            "payload": os.environ.get("HYPEROPT_TPU_PAYLOAD") or None,
        }

        # name the representative scalar per metric exactly — the tail
        # miner's first occurrence is text order (numpy baseline first),
        # not the TPE-loop figure the trend should plot
        def _stage_val(stage, key):
            r = stages.get(stage)
            return r["result"].get(key) if r and r.get("ok") else None

        ss_by_shards = (obs_summary.get("sharded_suggest") or {}).get(
            "cand_per_sec_by_shards") or {}
        keys_override = {
            "candidates_per_sec": cps if headline else None,
            "trials_per_sec": _stage_val("parallel_trials_10k_tpe",
                                         "trials_per_sec"),
            "cv_fits_per_sec": _stage_val("ml_cv", "cv_fits_per_sec"),
            "peak_hbm_bytes": _stage_val("devmem", "peak_hbm_bytes"),
            "history_bytes": _stage_val("devmem", "history_bytes"),
            "profiler_overhead_frac": _stage_val(
                "profiler_overhead", "profiler_overhead_frac"),
            "trace_overhead_frac": _stage_val(
                "trace_overhead", "trace_overhead_frac"),
            "studies_per_sec": _stage_val("multi_study", "studies_per_sec"),
            "study_ask_p99_ms": _stage_val("multi_study",
                                           "study_ask_p99_ms"),
            "slot_utilization_frac": _stage_val("multi_study",
                                                "slot_utilization_frac"),
            "resume_latency_sec": _stage_val("service_resume",
                                             "resume_latency_sec"),
            "shed_rate_frac": _stage_val("service_resume",
                                         "shed_rate_frac"),
            "fleet_studies_per_sec": _stage_val("fleet_scale",
                                                "fleet_studies_per_sec"),
            "reclaim_latency_sec": _stage_val("fleet_scale",
                                              "reclaim_latency_sec"),
            "cold_study_ask_p99_ms": _stage_val("coldstart",
                                                "cold_study_ask_p99_ms"),
            "compile_queue_depth_max": _stage_val(
                "coldstart", "compile_queue_depth_max"),
            "bank_hit_frac": _stage_val("coldstart", "bank_hit_frac"),
            "checksum_overhead_frac": _stage_val(
                "store_integrity", "checksum_overhead_frac"),
            "gc_reclaimed_bytes": _stage_val("store_integrity",
                                             "gc_reclaimed_bytes"),
            "scrub_records_per_sec": _stage_val(
                "store_integrity", "scrub_records_per_sec"),
            # the standing per-algo quality table + the plane's cost
            **{f"{k}_{a}": _stage_val("search_quality", f"{k}_{a}")
               for k in ("trials_to_target", "final_regret",
                         "solved_frac")
               for a in ("tpe", "rand", "anneal", "mix", "atpe")},
            "quality_overhead_frac": _stage_val(
                "quality_overhead", "quality_overhead_frac"),
            "attribution_overhead_frac": _stage_val(
                "load_attribution", "attribution_overhead_frac"),
            "shard_heat_skew": _stage_val("load_attribution",
                                          "shard_heat_skew"),
            "probe_overhead_frac": _stage_val(
                "blackbox_probe", "probe_overhead_frac"),
            "probe_detection_latency_sec": _stage_val(
                "blackbox_probe", "probe_detection_latency_sec"),
            "megakernel_cand_per_sec": _stage_val(
                "megakernel", "megakernel_cand_per_sec"),
            "megakernel_int8_bytes_frac": _stage_val(
                "megakernel", "megakernel_int8_bytes_frac"),
            "tenant_overhead_frac": _stage_val(
                "tenant_fairness", "tenant_overhead_frac"),
            "tenant_p99_skew": _stage_val("tenant_fairness",
                                          "tenant_p99_skew"),
            # widest mesh = the scaling design point
            "sharded_cand_per_sec": next(
                (v for _, v in sorted(ss_by_shards.items(),
                                      key=lambda kv: -int(kv[0]))
                 if isinstance(v, (int, float))), None),
            **{k: (obs_summary.get("ask_latency") or {}).get(
                "tpe", {}).get(k)
               for k in ("ask_p50_ms", "ask_p95_ms", "ask_p99_ms")},
        }
        # mine the detail block ONLY: every stage result lives there, and
        # headline_rec re-summarizes a subset — concatenating both would
        # store each summarized metric twice and break positional gating
        rec = trajectory.record_from_headline(
            headline_rec,
            detail_tail=json.dumps(detail, default=float),
            config=config, keys_override=keys_override)
        path = trajectory.append(rec)
        print(f"bench: appended trajectory record to {path} "
              f"({len(rec['keys'])} keys)", file=sys.stderr)
    except Exception as e:
        print(f"bench: trajectory append failed (non-fatal): "
              f"{type(e).__name__}: {e}", file=sys.stderr)


if __name__ == "__main__":
    if "--jax-stages" in sys.argv:
        names = sys.argv[sys.argv.index("--jax-stages") + 1:]
        _jax_stage_child(only=set(names) or None)
    else:
        main()
