#!/bin/sh
# Hermetic test run: force CPU JAX and bypass the ambient axon TPU hook
# (PALLAS_AXON_POOL_IPS triggers a remote-TPU claim in sitecustomize at every
# interpreter start; tests must not contend for the single chip).
exec env PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu python -m pytest tests/ -q "$@"
