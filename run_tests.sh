#!/bin/sh
# Hermetic test run: force CPU JAX and bypass the ambient axon TPU hook
# (PALLAS_AXON_POOL_IPS triggers a remote-TPU claim in sitecustomize at every
# interpreter start; tests must not contend for the single chip).
# Opt-in perf gate: BENCH_GATE=1 additionally compares the two newest
# BENCH_r*.json artifacts (scripts/bench_gate.py) and fails on a
# regression; with fewer than two rounds recorded it passes.
# Opt-in trace gate: TRACE_GATE=1 additionally runs a tiny armed
# two-controller run end-to-end, exports it via obs.report --export-trace
# and validates the trace-event invariants (scripts/validate_trace.py).
# Opt-in donation gate: DONATION_GATE=1 additionally re-runs the
# zero-copy suite under forced-CPU JAX with the strict allocation checks
# armed — pins that no ask→tell tick allocates a cap-sized history copy
# (buffer pointers stable, live cap-sized buffer count non-increasing).
# Opt-in serve gate: SERVE_GATE=1 additionally arms the live scrape
# server on a short real fmin, scrapes /metrics + /snapshot MID-RUN and
# validates the exposition-format / snapshot-shape invariants
# (scripts/validate_scrape.py --self-test).
# Opt-in shard gate: SHARD_GATE=1 additionally runs the forced-8-device
# sharded-equivalence suite (mesh shapes {1,2,4,8} bit-identical to
# single-chip, replicated AND capacity-sharded history) plus the scaling
# smoke (scripts/shard_smoke.py).
# Opt-in profile gate: PROFILE_GATE=1 additionally runs the real
# CPU-backend device-capture round trip — /profile?sec=1 against a live
# run, merge the capture artifact with the host spans, validate the
# merged trace (scripts/validate_trace.py --profile-self-test).
# Opt-in chaos gate: CHAOS_GATE=1 additionally re-runs the resilience
# suites and then scripts/chaos_smoke.py — a real 3-controller elastic
# fleet under a seeded SIGTERM/SIGKILL schedule must converge to a final
# history bit-identical to the undisturbed same-seed run, leave readable
# flight dumps in the store, and replay bitwise when resumed at a
# different fleet size.
# Opt-in service gate: SERVICE_GATE=1 additionally re-runs the ask/tell
# service suites and then scripts/service_smoke.py — a real subprocess
# server drives 100 concurrent HTTP studies to convergence, with the
# /studies table and /metrics exposition linted.
# Opt-in service chaos gate: SERVICE_CHAOS_GATE=1 additionally re-runs
# the durability suites and then scripts/service_chaos_smoke.py — a
# real subprocess server is SIGKILLed mid-wave under concurrent HTTP
# traffic and restarted on the same store root; every study must finish
# bit-identical to an undisturbed reference, 2x-capacity overload must
# shed with 429/Retry-After and lose zero tells, and injected tick
# faults must walk the degrade ladder without killing the server.
# Opt-in fleet gate: FLEET_GATE=1 additionally re-runs the replicated-
# serving-fleet suites (epoch leases incl. fake-clock reclaim races,
# in-process migration determinism, 307 routing) and then
# scripts/fleet_smoke.py — a real 3-subprocess-replica fleet over one
# store root: SIGKILL one replica under concurrent ServiceClient
# drivers (survivors reclaim its shard leases and adopt its studies by
# epoch-WAL replay), then a scripted rolling restart of all replicas;
# every study must finish bit-identical to the undisturbed
# single-server reference with zero lost and zero duplicated tells and
# bounded ask p99.
# Opt-in compile gate: COMPILE_GATE=1 additionally re-runs the
# cold-start compile-plane suite and then scripts/coldstart_smoke.py —
# a real subprocess server with the plane armed serves brand-new spaces
# under concurrent load with no ask ever blocking on an XLA compile
# (warming rand floor, flagged), promotes them once the background
# queue drains, and a restart on the same store pre-warms the census
# kernel bank so the same spaces' first TPE asks are served on-device.
# Opt-in store gate: STORE_GATE=1 additionally re-runs the storage-
# integrity suites (checksummed WAL classification table, quarantine
# semantics, ENOSPC backpressure) and then scripts/store_chaos_smoke.py
# — a real subprocess server under concurrent clients with seeded WAL
# bit-flips and injected ENOSPC: corrupt studies quarantine (410)
# instead of crashing the boot, healthy studies lose zero acknowledged
# tells and propose bitwise vs an undisturbed reference, 507 sheds
# carry Retry-After and recover when space frees, and scrub detects
# 100% of the injected corruptions with --repair booting clean.
# Opt-in SLO gate: SLO_GATE=1 additionally re-runs the request-trace /
# SLO / timeline suites and then scripts/slo_smoke.py — a real
# subprocess server with tracing + SLO + access log armed serves one
# traced ServiceClient ask; the trace id must correlate across the
# response, the on-disk WAL ask record, GET /study/<id>/timeline and
# obs.report --study, /metrics must lint with the slo_* gauge families,
# and the server must still drain cleanly on SIGTERM.
# Opt-in quality gate: QUALITY_GATE=1 additionally re-runs the search-
# quality suites and then scripts/quality_smoke.py — a real subprocess
# server with the quality plane armed (the default) runs the zoo mix
# under tpe AND rand; tpe must beat rand on summed trials-to-target by
# the server's own telemetry, a budget-starved study must flag stagnant
# on /studies with a stagnation event on its timeline, and /metrics
# must lint with the quality_* gauge families — then bench_gate
# --explain prints the windowed per-metric verdicts.
# Opt-in kernel gate: KERNEL_GATE=1 additionally re-runs the megakernel
# / quantized-history suites and then scripts/kernel_smoke.py — a real
# subprocess server with HYPEROPT_TPU_MEGAKERNEL armed (interpret
# emulation on CPU) serves the zoo mix to budget; a disarmed server and
# an armed-but-off (MEGAKERNEL=0) server must propose bit-identically
# (pinned directly through the scheduler AND over HTTP, with zero new
# threads on the disarmed path), and the armed server must drain
# cleanly on SIGTERM (exit 0).
# Opt-in load gate: LOAD_GATE=1 additionally re-runs the cost-
# attribution suites and then scripts/load_smoke.py — a real
# 3-subprocess-replica fleet with a ~10:1 skewed study placement:
# /fleet/load must serve the merged heat table on every replica with
# the hot shard hottest and the skew gauge reflecting the imbalance,
# /metrics must lint with the service_load_* gauge families, the
# heat-aware volunteer handoff must drain the hottest shard first,
# the durable heat ledger must replay after a SIGKILL (the adopter
# inherits the shard's heat), and zero tells may be lost throughout.
# Opt-in tenant gate: TENANT_GATE=1 additionally re-runs the tenant-
# observatory suites and then scripts/tenant_smoke.py — a real
# subprocess server under a ~10:1 adversarial tenant mix: the light
# tenant's ask p99 stays bounded vs its own solo baseline, the noisy
# tenant trips its per-tenant ask budget with typed 429s carrying
# Retry-After, GET /tenants serves the bounded attribution table,
# /metrics lints with the service_tenant_* roll-up families
# (validate_scrape.py --require-tenant), probe traffic never mints a
# tenant row, zero tells are lost, and SIGTERM drains cleanly.
PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu python -m pytest tests/ -q "$@"
rc=$?
[ "$rc" -ne 0 ] && exit "$rc"
if [ "${BENCH_GATE:-0}" = "1" ]; then
    # windowed mode imports hyperopt_tpu (for the direction table), which
    # can pull jax in — scrub the env like every other gate
    PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu python scripts/bench_gate.py || exit 1
fi
if [ "${TRACE_GATE:-0}" = "1" ]; then
    PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu python scripts/validate_trace.py --self-test || exit 1
fi
if [ "${DONATION_GATE:-0}" = "1" ]; then
    # tests/test_shard_suggest.py -k donation pins the SHARDED path too:
    # per-shard buffer pointers stable across ticks, stale-handle guard;
    # tests/test_batched_suggest.py -k donation pins the STUDY-axis
    # cohort stack (no S x cap copy per wave)
    PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu DONATION_GATE=1 \
        python -m pytest tests/test_pipeline.py tests/test_shard_suggest.py \
        tests/test_batched_suggest.py -q -k donation || exit 1
fi
if [ "${SERVE_GATE:-0}" = "1" ]; then
    PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu python scripts/validate_scrape.py --self-test || exit 1
fi
if [ "${SHARD_GATE:-0}" = "1" ]; then
    # test_batched_suggest.py rides along: the study-axis-sharded cohort
    # must stay bit-identical with HYPEROPT_TPU_SHARD armed
    PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
        python -m pytest tests/test_sharding.py tests/test_shard_suggest.py \
        tests/test_batched_suggest.py -q || exit 1
    python scripts/shard_smoke.py || exit 1
fi
if [ "${PROFILE_GATE:-0}" = "1" ]; then
    PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu python scripts/validate_trace.py --profile-self-test || exit 1
fi
if [ "${CHAOS_GATE:-0}" = "1" ]; then
    PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
        python -m pytest tests/test_membership.py tests/test_chaos.py \
        tests/test_fleet.py -q || exit 1
    PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu python scripts/chaos_smoke.py || exit 1
fi
if [ "${SERVICE_GATE:-0}" = "1" ]; then
    PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
        python -m pytest tests/test_service.py tests/test_batched_suggest.py \
        -q || exit 1
    PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu python scripts/service_smoke.py || exit 1
fi
if [ "${SERVICE_CHAOS_GATE:-0}" = "1" ]; then
    PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
        python -m pytest tests/test_journal.py tests/test_overload.py \
        tests/test_service.py -q || exit 1
    PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu python scripts/service_chaos_smoke.py || exit 1
fi
if [ "${FLEET_GATE:-0}" = "1" ]; then
    PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
        python -m pytest tests/test_epoch_leases.py \
        tests/test_service_fleet.py tests/test_membership.py -q || exit 1
    PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu python scripts/fleet_smoke.py || exit 1
fi
if [ "${COMPILE_GATE:-0}" = "1" ]; then
    PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
        python -m pytest tests/test_compile_plane.py tests/test_service.py \
        -q || exit 1
    PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu python scripts/coldstart_smoke.py || exit 1
fi
if [ "${STORE_GATE:-0}" = "1" ]; then
    PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
        python -m pytest tests/test_integrity.py tests/test_journal.py \
        tests/test_filestore.py -q || exit 1
    PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu python scripts/store_chaos_smoke.py || exit 1
fi
if [ "${SLO_GATE:-0}" = "1" ]; then
    PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
        python -m pytest tests/test_reqtrace.py tests/test_slo.py \
        tests/test_timeline.py -q || exit 1
    PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu python scripts/slo_smoke.py || exit 1
fi
if [ "${QUALITY_GATE:-0}" = "1" ]; then
    PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
        python -m pytest tests/test_quality.py tests/test_timeline.py \
        -q || exit 1
    PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu python scripts/quality_smoke.py || exit 1
    PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu python scripts/bench_gate.py --explain || exit 1
fi
if [ "${LOAD_GATE:-0}" = "1" ]; then
    PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
        python -m pytest tests/test_load.py tests/test_service_fleet.py \
        -q || exit 1
    PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu python scripts/load_smoke.py || exit 1
fi
if [ "${KERNEL_GATE:-0}" = "1" ]; then
    PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
        python -m pytest tests/test_megakernel.py tests/test_shard_suggest.py \
        tests/test_batched_suggest.py tests/test_journal.py -q || exit 1
    PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu python scripts/kernel_smoke.py || exit 1
fi
if [ "${TENANT_GATE:-0}" = "1" ]; then
    PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
        python -m pytest tests/test_tenant.py tests/test_overload.py \
        tests/test_service.py -q || exit 1
    PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu python scripts/tenant_smoke.py || exit 1
fi
if [ "${PROBE_GATE:-0}" = "1" ]; then
    PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
        python -m pytest tests/test_prober.py -q || exit 1
    PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu python scripts/probe_smoke.py || exit 1
fi
exit 0
