"""Property-based space-layer tests (hypothesis).

Beyond the reference's example-based doctrine: random space structures
(mixed families, nested conditional branches, random valid parameters) must
always produce in-bounds, correctly-quantized samples, consistent activity
masks, and a faithful flat→structured assembly.  Catches family/param edge
cases no hand-written table covers.
"""

import math

import numpy as np
import pytest

hypothesis = pytest.importorskip("hypothesis")
from hypothesis import HealthCheck, given, settings  # noqa: E402
from hypothesis import strategies as st  # noqa: E402

import jax
import jax.numpy as jnp

from hyperopt_tpu import hp
from hyperopt_tpu.spaces import compile_space

# per-test settings (NOT a load_profile at import: hypothesis profiles are
# process-global and would silently weaken other files' property tests)
_SETTINGS = settings(deadline=None, max_examples=15,
                     suppress_health_check=[HealthCheck.too_slow])

_finite = st.floats(-50, 50, allow_nan=False, allow_infinity=False)


@st.composite
def leaf_param(draw, label):
    """One hp.* leaf plus a validator(value) -> bool."""
    fam = draw(st.sampled_from(
        ["uniform", "quniform", "loguniform", "normal", "qnormal",
         "lognormal", "randint", "uniformint"]))
    if fam == "uniform":
        low = draw(_finite)
        high = low + draw(st.floats(0.5, 40))
        return hp.uniform(label, low, high), lambda v: low <= v <= high
    if fam == "quniform":
        low = draw(st.floats(-40, 40))
        high = low + draw(st.floats(1.0, 40))
        q = draw(st.sampled_from([0.5, 1.0, 2.0]))
        return hp.quniform(label, low, high, q), (
            lambda v: low - q <= v <= high + q
            and abs(v / q - round(v / q)) < 1e-4
        )
    if fam == "loguniform":
        low = draw(st.floats(-5, 1))
        high = low + draw(st.floats(0.5, 4))
        return hp.loguniform(label, low, high), (
            lambda v: math.exp(low) * 0.999 <= v <= math.exp(high) * 1.001
        )
    if fam == "normal":
        mu = draw(_finite)
        sigma = draw(st.floats(0.1, 10))
        return hp.normal(label, mu, sigma), (
            lambda v: abs(v - mu) < 8 * sigma  # 8-sigma: p(false alarm) ~ 0
        )
    if fam == "qnormal":
        mu = draw(st.floats(-20, 20))
        sigma = draw(st.floats(0.5, 5))
        q = draw(st.sampled_from([1.0, 2.0]))
        return hp.qnormal(label, mu, sigma, q), (
            lambda v: abs(v / q - round(v / q)) < 1e-4
        )
    if fam == "lognormal":
        mu = draw(st.floats(-2, 2))
        sigma = draw(st.floats(0.1, 1.5))
        return hp.lognormal(label, mu, sigma), lambda v: v > 0
    if fam == "randint":
        upper = draw(st.integers(1, 50))
        return hp.randint(label, upper), (
            lambda v: 0 <= v < upper and float(v).is_integer()
        )
    low = draw(st.integers(-20, 20))
    high = low + draw(st.integers(1, 30))
    return hp.uniformint(label, low, high), (
        lambda v: low <= v <= high and float(v).is_integer()
    )


@st.composite
def space_and_validators(draw):
    n_top = draw(st.integers(1, 4))
    space = {}
    validators = {}
    for i in range(n_top):
        label = f"p{i}"
        node, check = draw(leaf_param(label))
        space[label] = node
        validators[label] = check
    if draw(st.booleans()):  # one conditional branch pair
        b0, c0 = draw(leaf_param("b0"))
        b1, c1 = draw(leaf_param("b1"))
        space["branch"] = hp.choice("branch", [{"v": b0}, {"v": b1}])
        validators["b0"] = c0
        validators["b1"] = c1
    return space, validators


@_SETTINGS
@given(space_and_validators(), st.integers(0, 2**31 - 1))
def test_samples_respect_bounds_and_structure(sv, seed):
    space, validators = sv
    cs = compile_space(space)
    key = jax.random.PRNGKey(seed)

    # structured host sample: only live labels appear; all validated
    s = cs.sample(key)
    for label in space:
        if label == "branch":
            assert s["branch"] == {"v": s["branch"]["v"]}
        else:
            assert validators[label](s[label]), (label, s[label])

    # vmapped flat samples: every ACTIVE value validates
    keys = jax.vmap(lambda i: jax.random.fold_in(key, i))(
        jnp.arange(32, dtype=jnp.uint32))
    flats = jax.jit(jax.vmap(cs.sample_flat))(keys)
    active = jax.vmap(cs.active_flat)(flats)
    for label, check in validators.items():
        vals = np.asarray(flats[label])
        act = np.asarray(active[label])
        for v, a in zip(vals, act):
            if a:
                assert check(float(v)), (label, float(v))
    # conditional exclusivity: exactly one branch live per draw
    if "branch" in space:
        a0 = np.asarray(active["b0"])
        a1 = np.asarray(active["b1"])
        assert np.all(a0 ^ a1)


@_SETTINGS
@given(space_and_validators(), st.integers(0, 2**31 - 1))
def test_assemble_matches_flat(sv, seed):
    space, _ = sv
    cs = compile_space(space)
    flat = {k: np.asarray(v) for k, v in
            cs.sample_flat_jit(jax.random.PRNGKey(seed)).items()}
    s = cs.assemble(flat)
    for label in space:
        if label == "branch":
            idx = int(flat["branch"])
            live = "b0" if idx == 0 else "b1"
            assert s["branch"]["v"] == pytest.approx(
                float(flat[live]), rel=1e-5, abs=1e-5)
        else:
            assert s[label] == pytest.approx(
                float(flat[label]), rel=1e-5, abs=1e-5)


@_SETTINGS
@given(space_and_validators(), st.integers(0, 2**31 - 1),
       st.integers(0, 64))
def test_tpe_proposals_valid_for_arbitrary_histories(sv, seed, n_obs):
    # the full proposal kernel must emit in-bounds, finite values for EVERY
    # label under arbitrary history masks: empty below set, labels with zero
    # live observations (a never-taken branch), partially-active slots
    from hyperopt_tpu.algos import tpe

    space, validators = sv
    cs = compile_space(space)
    cfg = {"prior_weight": 1.0, "n_EI_candidates": 16, "gamma": 0.25, "LF": 25}
    rng = np.random.default_rng(seed)
    cap = 64
    has = np.zeros(cap, bool)
    has[:n_obs] = True
    # histories drawn FROM THE PRIOR so per-label values are family-valid
    keys = jax.vmap(lambda i: jax.random.fold_in(
        jax.random.PRNGKey(seed), i))(jnp.arange(cap, dtype=jnp.uint32))
    flats = jax.jit(jax.vmap(cs.sample_flat))(keys)
    acts = jax.vmap(cs.active_flat)(flats)
    history = {
        "losses": jnp.asarray(
            np.where(has, rng.normal(size=cap), np.inf).astype(np.float32)),
        "has_loss": jnp.asarray(has),
        "vals": {l: jnp.asarray(np.asarray(flats[l], np.float32)) for l in cs.labels},
        "active": {l: jnp.asarray(np.asarray(acts[l]) & has) for l in cs.labels},
    }
    propose = jax.jit(tpe.build_propose(cs, cfg))
    out = propose(history, jax.random.PRNGKey(seed ^ 0x5A5A))
    for label in cs.labels:
        v = float(np.asarray(out[label]))
        assert np.isfinite(v), (label, v)
        if label in validators:
            assert validators[label](v), (label, v)
        elif label == "branch":
            assert v in (0.0, 1.0)
