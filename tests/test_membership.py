"""Lease-plane unit tests (ISSUE 8): claim exclusivity, heartbeat/expiry/
reclaim ordering (fake clock via ``os.utime`` — lease age IS file mtime),
write-once params, membership liveness, and the deterministic re-bucketing
math that makes a resumed fleet of any size replay bitwise.
"""

import os
import time

import pytest

from hyperopt_tpu.parallel.membership import (
    FleetMembership,
    n_occupied_shards,
    shard_trials,
)


def _age(member, gen, shard, sec):
    """Fake clock: push a lease's mtime ``sec`` seconds into the past."""
    path = member._lease_path(gen, shard)
    t = time.time() - sec
    os.utime(path, (t, t))


# ---------------------------------------------------------------------------
# re-bucketing math
# ---------------------------------------------------------------------------


def test_shard_trials_partitions_every_generation():
    for B in (1, 3, 8, 13):
        for S in (1, 2, 4, 8):
            shards = [shard_trials(B, S, s) for s in range(S)]
            flat = sorted(j for js in shards for j in js)
            assert flat == list(range(B))  # disjoint, complete
            # occupied-shard count: exactly the non-empty prefix
            occ = n_occupied_shards(B, S)
            assert all(shards[s] for s in range(occ))
            assert all(not shards[s] for s in range(occ, S))


def test_shard_trials_independent_of_fleet_size():
    # the map depends only on (B, n_shards, shard) — there is no fleet-size
    # input to drift on; pin a literal so a refactor can't silently change
    # the trial->shard bucketing (that would break bitwise replay of every
    # existing fleet store)
    assert shard_trials(8, 4, 1) == [1, 5]
    assert shard_trials(10, 4, 3) == [3, 7]


# ---------------------------------------------------------------------------
# claims
# ---------------------------------------------------------------------------


def test_claim_is_exclusive(tmp_path):
    a = FleetMembership(tmp_path, owner="a", lease_ttl=30)
    b = FleetMembership(tmp_path, owner="b", lease_ttl=30)
    assert a.try_claim(0, 2)
    assert not b.try_claim(0, 2)  # exactly one winner
    assert b.metrics.counter("lease.contention").value >= 1


def test_claim_refused_once_result_published(tmp_path):
    a = FleetMembership(tmp_path, owner="a", lease_ttl=30)
    b = FleetMembership(tmp_path, owner="b", lease_ttl=30)
    assert a.try_claim(1, 0)
    a.publish(1, 0, b"blob")
    # publish released the lease AND parked the terminal state
    assert not os.path.exists(a._lease_path(1, 0))
    assert not b.try_claim(1, 0)
    assert b.read_result(1, 0) == b"blob"


def test_missing_shards_tracks_results(tmp_path):
    a = FleetMembership(tmp_path, owner="a", lease_ttl=30)
    assert a.missing_shards(0, 4) == [0, 1, 2, 3]
    a.try_claim(0, 1)
    a.publish(0, 1, b"x")
    assert a.missing_shards(0, 4) == [0, 2, 3]


def test_claim_order_is_a_rotation(tmp_path):
    a = FleetMembership(tmp_path, owner="abc:1", lease_ttl=30)
    shards = [0, 1, 2, 3, 4]
    got = a.claim_order(shards)
    assert sorted(got) == shards  # permutation: nothing dropped
    assert got == a.claim_order(shards)  # deterministic per owner
    assert a.claim_order([]) == []


# ---------------------------------------------------------------------------
# expiry / reclaim ordering (fake clock)
# ---------------------------------------------------------------------------


def test_fresh_lease_not_reclaimed(tmp_path):
    a = FleetMembership(tmp_path, owner="a", lease_ttl=30)
    b = FleetMembership(tmp_path, owner="b", lease_ttl=30)
    assert a.try_claim(0, 0)
    assert b.reclaim_stale(0, 1) == 0
    assert not b.try_claim(0, 0)


def test_stale_lease_reclaimed_then_reclaimable(tmp_path):
    a = FleetMembership(tmp_path, owner="dead", lease_ttl=5)
    b = FleetMembership(tmp_path, owner="live", lease_ttl=5)
    assert a.try_claim(0, 0)
    _age(a, 0, 0, 60)  # the holder died: heartbeats stopped long ago
    assert b.reclaim_stale(0, 1) == 1
    assert b.try_claim(0, 0)  # survivor takes over
    assert b.metrics.counter("lease.reclaims").value == 1


def test_reclaim_ordering_only_expired_leases(tmp_path):
    a = FleetMembership(tmp_path, owner="a", lease_ttl=5)
    b = FleetMembership(tmp_path, owner="b", lease_ttl=5)
    assert a.try_claim(0, 0)
    assert a.try_claim(0, 1)
    _age(a, 0, 0, 60)  # only shard 0 expired
    assert b.reclaim_stale(0, 2) == 1
    assert b.try_claim(0, 0)
    assert not b.try_claim(0, 1)  # fresh lease survives the sweep


def test_heartbeat_defers_expiry(tmp_path):
    a = FleetMembership(tmp_path, owner="a", lease_ttl=5)
    b = FleetMembership(tmp_path, owner="b", lease_ttl=5)
    assert a.try_claim(0, 0)
    _age(a, 0, 0, 60)
    a.heartbeat_shard(0, 0)  # mtime -> NOW: the holder is alive after all
    assert b.reclaim_stale(0, 1) == 0


def test_reclaim_skips_published_and_clears_leftover_lease(tmp_path):
    a = FleetMembership(tmp_path, owner="a", lease_ttl=5)
    b = FleetMembership(tmp_path, owner="b", lease_ttl=5)
    assert a.try_claim(0, 0)
    # publish raced the release: write the result but leave the lease
    # behind by hand (the crash-between-publish-and-release window)
    from hyperopt_tpu.filestore import _atomic_write

    _atomic_write(a._result_path(0, 0), b"done")
    _age(a, 0, 0, 60)
    assert b.reclaim_stale(0, 1) == 0  # a published shard is terminal
    assert not os.path.exists(a._lease_path(0, 0))  # leftover swept
    assert b.missing_shards(0, 1) == []


def test_concurrent_reclaimers_single_winner(tmp_path):
    a = FleetMembership(tmp_path, owner="dead", lease_ttl=5)
    b = FleetMembership(tmp_path, owner="s1", lease_ttl=5)
    c = FleetMembership(tmp_path, owner="s2", lease_ttl=5)
    assert a.try_claim(0, 0)
    _age(a, 0, 0, 60)
    # both survivors sweep: the rename-to-private-name claim means exactly
    # one frees the lease (the other sees FileNotFoundError and moves on)
    n = b.reclaim_stale(0, 1) + c.reclaim_stale(0, 1)
    assert n == 1


# ---------------------------------------------------------------------------
# params / members / checksums
# ---------------------------------------------------------------------------


def test_params_write_once_and_verified(tmp_path):
    a = FleetMembership(tmp_path, owner="a")
    b = FleetMembership(tmp_path, owner="b")
    params = {"seed": 0, "batch": 8, "n_shards": 4}
    assert a.ensure_params(params) is True      # first writer
    assert b.ensure_params(dict(params)) is False  # joiner verifies
    with pytest.raises(ValueError, match="identical params"):
        b.ensure_params({"seed": 1, "batch": 8, "n_shards": 4})


def test_members_join_age_out_leave(tmp_path):
    a = FleetMembership(tmp_path, owner="a", lease_ttl=5, member_ttl=30)
    b = FleetMembership(tmp_path, owner="b", lease_ttl=5, member_ttl=30)
    a.join()
    b.join()
    assert set(a.live_members()) == {"a", "b"}
    # b dies: its member record ages past member_ttl
    t = time.time() - 120
    os.utime(b._member_path(), (t, t))
    assert a.live_members() == ["a"]
    # heartbeat resurrects liveness
    b.heartbeat_member()
    assert set(a.live_members()) == {"a", "b"}
    b.leave()
    assert a.live_members() == ["a"]


def test_checksum_audit_roundtrip(tmp_path):
    a = FleetMembership(tmp_path, owner="host:1")
    b = FleetMembership(tmp_path, owner="host:2")
    a.write_checksum(3, "abc123")
    b.write_checksum(3, "abc123")
    assert a.read_checksums(3) == {"host-1": "abc123", "host-2": "abc123"}
    assert a.read_checksums(4) == {}
