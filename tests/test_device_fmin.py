"""On-device lax.scan fmin tests (no reference analog; SURVEY.md §7.1
"one suggestion per call" row)."""

import numpy as np
import pytest

import jax.numpy as jnp

from hyperopt_tpu import Trials, hp
from hyperopt_tpu.device_fmin import fmin_device
from hyperopt_tpu.zoo import ZOO


def test_device_fmin_quadratic_converges():
    best, loss = fmin_device(lambda d: (d["x"] - 1.0) ** 2,
                             {"x": hp.uniform("x", -5, 5)},
                             max_evals=150, seed=0)
    assert loss < 0.05
    assert abs(best["x"] - 1.0) < 0.5


def test_device_fmin_branin():
    dom = ZOO["branin"]
    best, loss = fmin_device(dom.objective, dom.space, max_evals=300, seed=0,
                             gamma=2.0, linear_forgetting=100)
    assert loss < 0.9
    assert set(best) == {"x", "y"}


def test_device_fmin_beats_prior_sampling():
    dom = ZOO["quadratic1"]
    _, tpe_loss = fmin_device(dom.objective, dom.space, max_evals=120, seed=0)
    # pure prior sampling = startup forever
    _, rand_loss = fmin_device(dom.objective, dom.space, max_evals=120, seed=0,
                               n_startup_jobs=10**9)
    assert tpe_loss <= rand_loss * 1.1 + 1e-3


def test_device_fmin_conditional_space():
    space = hp.choice("c", [
        {"k": 0, "x": hp.uniform("xa", -5, 5)},
        {"k": 1, "x": hp.uniform("xb", 5, 15)},
    ])
    best, loss = fmin_device(lambda d: (d["x"] - 2.0) ** 2, space,
                             max_evals=100, seed=0)
    assert best["c"] == 0
    assert "xa" in best and "xb" not in best
    assert loss < 1.0


def test_device_fmin_nan_objective_recorded_not_fatal():
    def obj(d):
        return jnp.where(d["x"] < 0, jnp.nan, d["x"])

    best, loss = fmin_device(obj, {"x": hp.uniform("x", -5, 5)},
                             max_evals=60, seed=0)
    assert np.isfinite(loss)
    assert best["x"] >= 0


def test_device_fmin_return_trials():
    dom = ZOO["quadratic1"]
    trials = fmin_device(dom.objective, dom.space, max_evals=40, seed=0,
                         return_trials=True)
    assert isinstance(trials, Trials)
    assert len(trials) == 40
    assert trials.argmin  # reference-shaped docs work end-to-end
    losses = [l for l in trials.losses() if l is not None]
    assert min(losses) == trials.best_trial["result"]["loss"]


def test_device_fmin_deterministic_per_seed():
    dom = ZOO["quadratic1"]
    a = fmin_device(dom.objective, dom.space, max_evals=50, seed=7)
    b = fmin_device(dom.objective, dom.space, max_evals=50, seed=7)
    assert a == b


def test_fmin_device_mixed_structure_conditional():
    # branches with DIFFERENT hyperparameter sets run fully on-device via
    # the union-merge traced assembly (inactive branch slots read as zeros)
    import jax.numpy as jnp

    space = {
        "lr": hp.loguniform("lr", -6, 0),
        "arch": hp.choice("arch", [
            {"w": hp.quniform("w", 16, 256, 16)},
            {"h": hp.randint("h", 1, 9)},
        ]),
    }

    def obj(d):
        a = d["arch"]
        return (jnp.log(d["lr"]) + 3.0) ** 2 + 0.001 * (a["w"] + a["h"])

    best, loss = fmin_device(obj, space, max_evals=100, seed=1)
    assert loss < 0.5
    assert best["arch"] in (0, 1)
