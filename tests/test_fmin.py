"""Driver-loop tests (parity target: hyperopt/tests/test_fmin.py)."""

import os
import pickle
import tempfile

import numpy as np
import pytest

from hyperopt_tpu import (
    AllTrialsFailed,
    STATUS_FAIL,
    STATUS_OK,
    Trials,
    fmin,
    generate_trials_to_calculate,
    hp,
    space_eval,
)
from hyperopt_tpu.algos import rand, tpe
from hyperopt_tpu.early_stop import no_progress_loss


SPACE = {"x": hp.uniform("x", -5, 5)}


def quad(d):
    return (d["x"] - 1.0) ** 2


def test_fmin_converges_rand():
    best = fmin(quad, SPACE, algo=rand.suggest, max_evals=80,
                rstate=np.random.default_rng(0), show_progressbar=False)
    assert abs(best["x"] - 1.0) < 1.0


def test_fmin_default_algo_is_tpe():
    best = fmin(quad, SPACE, max_evals=25, rstate=np.random.default_rng(0),
                show_progressbar=False)
    assert "x" in best


def test_fmin_trials_capture():
    t = Trials()
    fmin(quad, SPACE, algo=rand.suggest, max_evals=10, trials=t,
         rstate=np.random.default_rng(0), show_progressbar=False)
    assert len(t) == 10
    assert all(s == STATUS_OK for s in t.statuses())
    assert min(t.losses()) == t.best_trial["result"]["loss"]


def test_fmin_seed_reproducible():
    r1 = fmin(quad, SPACE, algo=rand.suggest, max_evals=10,
              rstate=np.random.default_rng(42), show_progressbar=False)
    r2 = fmin(quad, SPACE, algo=rand.suggest, max_evals=10,
              rstate=np.random.default_rng(42), show_progressbar=False)
    assert r1 == r2


def test_fmin_env_seed(monkeypatch):
    monkeypatch.setenv("HYPEROPT_FMIN_SEED", "7")
    r1 = fmin(quad, SPACE, algo=rand.suggest, max_evals=5, show_progressbar=False)
    r2 = fmin(quad, SPACE, algo=rand.suggest, max_evals=5, show_progressbar=False)
    assert r1 == r2


def test_fmin_timeout():
    import time

    calls = []

    def slow(d):
        calls.append(1)
        time.sleep(0.25)
        return d["x"] ** 2

    t = Trials()
    fmin(slow, SPACE, algo=rand.suggest, max_evals=1000, trials=t, timeout=1,
         rstate=np.random.default_rng(0), show_progressbar=False)
    assert 0 < len(t) < 1000


def test_fmin_timeout_validation():
    with pytest.raises(Exception):
        fmin(quad, SPACE, algo=rand.suggest, max_evals=5, timeout=-1,
             show_progressbar=False)
    with pytest.raises(Exception):
        fmin(quad, SPACE, algo=rand.suggest, max_evals=5, timeout=True,
             show_progressbar=False)


def test_fmin_loss_threshold():
    t = Trials()
    fmin(quad, SPACE, algo=rand.suggest, max_evals=1000, trials=t,
         loss_threshold=5.0, rstate=np.random.default_rng(0), show_progressbar=False)
    assert len(t) < 1000
    assert min(t.losses()) <= 5.0


def test_fmin_loss_threshold_validation():
    with pytest.raises(Exception):
        fmin(quad, SPACE, algo=rand.suggest, max_evals=5, loss_threshold="x",
             show_progressbar=False)


def test_fmin_early_stop_fn():
    t = Trials()
    fmin(quad, SPACE, algo=rand.suggest, max_evals=500, trials=t,
         early_stop_fn=no_progress_loss(10), rstate=np.random.default_rng(0),
         show_progressbar=False)
    assert len(t) < 500


def test_fmin_tpe_crosses_history_capacity_bucket():
    # 150 TPE evals crosses the 128-slot PaddedHistory bucket mid-run: the
    # fused tell+ask kernel re-specializes on the 256-cap shapes and the
    # device mirror re-uploads — the optimizer must keep improving across
    # the boundary and the trial docs stay intact
    t = Trials()
    fmin(quad, SPACE, algo=tpe.suggest, max_evals=150, trials=t,
         max_queue_len=4, rstate=np.random.default_rng(0),
         show_progressbar=False)
    assert len(t) == 150
    assert t.history_object(("x",)).cap == 256
    losses = [l for l in t.losses() if l is not None]
    assert len(losses) == 150
    # the post-growth tail is still posterior-guided, not prior noise: its
    # best lands near the optimum (a uniform draw on [-5,5] hits
    # quad<1.0 with p≈0.1; 22 prior draws would miss far more often than
    # the seed-pinned posterior does)
    assert min(losses[128:]) < 1.0
    assert min(losses) < 0.05


def test_fmin_points_to_evaluate():
    t = generate_trials_to_calculate([{"x": 0.0}, {"x": 1.0}])
    best = fmin(quad, SPACE, algo=rand.suggest, max_evals=12, trials=t,
                rstate=np.random.default_rng(0), show_progressbar=False)
    # trial 1 pinned exactly at the optimum x=1
    assert t.trials[1]["misc"]["vals"]["x"] == [1.0]
    assert best["x"] == 1.0

    best2 = fmin(quad, SPACE, algo=rand.suggest, max_evals=5,
                 points_to_evaluate=[{"x": 1.0}],
                 rstate=np.random.default_rng(0), show_progressbar=False)
    assert best2["x"] == 1.0


def test_fmin_trials_save_file_roundtrip(tmp_path):
    f = str(tmp_path / "trials.pkl")
    fmin(quad, SPACE, algo=rand.suggest, max_evals=6, trials_save_file=f,
         rstate=np.random.default_rng(0), show_progressbar=False)
    with open(f, "rb") as fh:
        t = pickle.load(fh)
    assert len(t) == 6
    # resume continues from the checkpoint
    fmin(quad, SPACE, algo=rand.suggest, max_evals=10, trials_save_file=f,
         rstate=np.random.default_rng(1), show_progressbar=False)
    with open(f, "rb") as fh:
        t2 = pickle.load(fh)
    assert len(t2) == 10


def test_fmin_exception_propagates():
    def bad(d):
        raise RuntimeError("boom")

    with pytest.raises(RuntimeError):
        fmin(bad, SPACE, algo=rand.suggest, max_evals=3,
             rstate=np.random.default_rng(0), show_progressbar=False)


def test_fmin_catch_eval_exceptions():
    def flaky(d):
        if d["x"] < 0:
            raise RuntimeError("boom")
        return d["x"]

    t = Trials()
    fmin(flaky, SPACE, algo=rand.suggest, max_evals=20, trials=t,
         catch_eval_exceptions=True, rstate=np.random.default_rng(0),
         show_progressbar=False)
    # failed trials are excluded from the refreshed view but were attempted
    assert len(t) <= 20
    assert all(l >= 0 for l in t.losses() if l is not None)


def test_fmin_all_trials_failed():
    def bad(d):
        return {"status": STATUS_FAIL}

    with pytest.raises(AllTrialsFailed):
        fmin(bad, SPACE, algo=rand.suggest, max_evals=3,
             rstate=np.random.default_rng(0), show_progressbar=False)


def test_fmin_return_argmin_false():
    out = fmin(quad, SPACE, algo=rand.suggest, max_evals=3, return_argmin=False,
               rstate=np.random.default_rng(0), show_progressbar=False)
    assert out is None


def test_fmin_dict_result_with_extras():
    def obj(d):
        return {"loss": d["x"] ** 2, "status": STATUS_OK, "custom": 42}

    t = Trials()
    fmin(obj, SPACE, algo=rand.suggest, max_evals=4, trials=t,
         rstate=np.random.default_rng(0), show_progressbar=False)
    assert t.results[0]["custom"] == 42


def test_fmin_attachments():
    def obj(d):
        return {"loss": d["x"] ** 2, "status": STATUS_OK,
                "attachments": {"blob": b"\x00\x01"}}

    t = Trials()
    fmin(obj, SPACE, algo=rand.suggest, max_evals=2, trials=t,
         rstate=np.random.default_rng(0), show_progressbar=False)
    assert t.trial_attachments(t.trials[0])["blob"] == b"\x00\x01"


def test_fmin_max_queue_len():
    t = Trials()
    fmin(quad, SPACE, algo=rand.suggest, max_evals=12, trials=t, max_queue_len=4,
         rstate=np.random.default_rng(0), show_progressbar=False)
    assert len(t) == 12


def test_space_eval_roundtrip():
    space = hp.choice("c", [
        {"kind": "a", "x": hp.uniform("x", -1, 1)},
        {"kind": "b", "y": hp.loguniform("y", -2, 2)},
    ])
    out = space_eval(space, {"c": 0, "x": 0.5})
    assert out == {"kind": "a", "x": 0.5}
    out = space_eval(space, {"c": [1], "y": [1.5]})
    assert out["kind"] == "b"
    assert out["y"] == pytest.approx(1.5)


def test_trials_fmin_method():
    t = Trials()
    best = t.fmin(quad, SPACE, algo=rand.suggest, max_evals=8,
                  rstate=np.random.default_rng(0), show_progressbar=False)
    assert len(t) == 8
    assert "x" in best


def test_phase_timings_recorded():
    # SURVEY.md §5 tracing row: per-phase wall-clock counters on the trials
    from hyperopt_tpu.algos import tpe as _tpe

    t = Trials()
    fmin(lambda d: (d["x"] - 1.0) ** 2, {"x": hp.uniform("x", -5, 5)},
         algo=_tpe.suggest, max_evals=25, trials=t,
         rstate=np.random.default_rng(0), show_progressbar=False)
    pt = t.phase_timings
    assert pt["suggest"]["count"] >= 25 // 1 - 21  # at least the TPE calls
    assert pt["evaluate"]["count"] > 0
    assert pt["refresh"]["count"] > 0
    assert all(e["sec"] >= 0 for e in pt.values())
    fracs = sum(e["frac"] for e in pt.summary().values())
    assert fracs == pytest.approx(1.0)
    # survives the pickle round-trip (resume keeps accumulating)
    import pickle as _p

    t2 = _p.loads(_p.dumps(t))
    assert t2.phase_timings["suggest"]["count"] == pt["suggest"]["count"]


def test_jax_profiler_trace_hook(tmp_path, monkeypatch):
    # HYPEROPT_TPU_PROFILE=full:<dir> wraps the loop in jax.profiler.trace
    # (the legacy whole-run mode; the bare <dir> form arms the bounded
    # capture plane instead — obs/profiler.py, tests/test_profiler.py)
    monkeypatch.setenv("HYPEROPT_TPU_PROFILE",
                       "full:" + str(tmp_path / "prof"))
    t = Trials()
    fmin(lambda d: d["x"] ** 2, {"x": hp.uniform("x", -5, 5)},
         algo=rand.suggest, max_evals=5, trials=t,
         rstate=np.random.default_rng(0), show_progressbar=False)
    traces = list((tmp_path / "prof").rglob("*"))
    assert traces, "no profiler artifacts written"


def test_profile_dir_arms_bounded_plane_not_whole_run(tmp_path, monkeypatch):
    # the bare-dir form must NOT open a whole-run trace session (it would
    # starve every on-demand /profile and stall capture for the run's
    # lifetime) — it arms RunObs.profiler and leaves the loop unwrapped
    monkeypatch.setenv("HYPEROPT_TPU_PROFILE", str(tmp_path / "cap"))
    t = Trials()
    fmin(lambda d: d["x"] ** 2, {"x": hp.uniform("x", -5, 5)},
         algo=rand.suggest, max_evals=5, trials=t,
         rstate=np.random.default_rng(0), show_progressbar=False)
    # no whole-run artifacts; the capture dir stays empty until a capture
    assert not list((tmp_path / "cap").rglob("*.trace.json.gz"))


# ---------------------------------------------------------------------------
# device_loop: the chunked device stepper behind fmin(device_loop=...)
# ---------------------------------------------------------------------------


def test_device_loop_matches_reference_semantics():
    # queue-1 fresh-posterior loop on device: full doc parity, optimizes,
    # deterministic in rstate
    import numpy as np

    from hyperopt_tpu.zoo import ZOO

    dom = ZOO["branin"]

    def run(seed):
        t = Trials()
        fmin(dom.objective, dom.space, algo=tpe.suggest, max_evals=60,
             trials=t, rstate=np.random.default_rng(seed),
             show_progressbar=False, device_loop=True)
        return t

    t1, t1b, t2 = run(0), run(0), run(1)
    assert len(t1) == 60
    best = min(l for l in t1.losses() if l is not None)
    assert best < 2.0, best
    # doc schema intact: argmin, best_trial, idxs/vals per label
    assert set(t1.argmin) == {"x", "y"}
    doc = t1.best_trial
    assert doc["state"] == 2 and doc["result"]["status"] == "ok"
    # deterministic in rstate; sensitive to it
    np.testing.assert_array_equal(t1.losses(), t1b.losses())
    assert list(t1.losses()) != list(t2.losses())


def test_device_loop_loss_threshold_and_early_stop():
    import numpy as np

    from hyperopt_tpu.early_stop import no_progress_loss
    from hyperopt_tpu.zoo import ZOO

    dom = ZOO["quadratic1"]
    t = Trials()
    fmin(dom.objective, dom.space, algo=tpe.suggest, max_evals=200, trials=t,
         loss_threshold=1.0, rstate=np.random.default_rng(0),
         show_progressbar=False, device_loop=True)
    # stopped at a chunk boundary well before 200
    assert len(t) < 200
    assert min(l for l in t.losses() if l is not None) <= 1.0

    t2 = Trials()
    fmin(dom.objective, dom.space, algo=tpe.suggest, max_evals=200, trials=t2,
         early_stop_fn=no_progress_loss(2), rstate=np.random.default_rng(0),
         show_progressbar=False, device_loop=True)
    assert len(t2) < 200


def test_device_loop_conditional_space_and_partial_tuning():
    import functools

    import numpy as np

    from hyperopt_tpu.zoo import ZOO

    dom = ZOO["ml_model_select_cv"]  # hp.choice between model families
    t = Trials()
    algo = functools.partial(tpe.suggest, n_EI_candidates=32, gamma=0.5)
    fmin(dom.objective, dom.space, algo=algo, max_evals=40, trials=t,
         rstate=np.random.default_rng(0), show_progressbar=False,
         device_loop=True)
    assert len(t) == 40
    doc = t.best_trial
    # inactive branch params have empty idxs in the docs
    m = doc["misc"]["vals"]["model"][0]
    inactive = "lr_mlp" if m == 0 else "lr_lin"
    assert doc["misc"]["vals"][inactive] == []


def test_device_loop_incremental_runs_continue():
    # repeated FMinIter.run() (the iterator protocol) must keep using the
    # device path, continuing from the device-side history it populated —
    # and the whole incremental run must equal one single run() bit-for-bit
    import numpy as np

    from hyperopt_tpu.base import Domain
    from hyperopt_tpu.fmin import FMinIter
    from hyperopt_tpu.zoo import ZOO

    dom = ZOO["branin"]

    def make_iter(trials):
        return FMinIter(
            tpe.suggest, Domain(dom.objective, dom.space), trials,
            max_evals=40, rstate=np.random.default_rng(7),
            show_progressbar=False, device_loop=True)

    # chunk-aligned increments consume the same per-chunk seed sequence as a
    # single run, so the whole incremental run is bitwise identical to it
    t_inc = Trials()
    it = make_iter(t_inc)
    it.run(10)
    assert len(t_inc) == 10
    it.run(30)
    assert len(t_inc) == 40

    t_one = Trials()
    make_iter(t_one).run(40)
    np.testing.assert_array_equal(t_inc.losses(), t_one.losses())

    # mid-chunk boundaries continue too (seed alignment shifts, so only
    # semantics are asserted, not bitwise equality)
    t_mid = Trials()
    it2 = make_iter(t_mid)
    it2.run(15)
    assert len(t_mid) == 15
    it2.run(25)
    assert len(t_mid) == 40
    assert min(l for l in t_mid.losses() if l is not None) < 2.0

    # foreign (non-device-loop) history still refuses device_loop=True
    t_foreign = Trials()
    fmin(dom.objective, dom.space, algo=tpe.suggest, max_evals=5,
         trials=t_foreign, rstate=np.random.default_rng(0),
         show_progressbar=False)
    import pytest

    with pytest.raises(ValueError, match="ineligible"):
        make_iter(t_foreign).run(5)


def test_device_loop_uniformint_objective_traces():
    # integer-consuming objectives (table lookup on hp.uniformint) must be
    # eligible: the probe and the traced loop deliver i32 for every is_int
    # family, matching the host loop's Python ints
    import jax.numpy as jnp
    import numpy as np

    from hyperopt_tpu import hp

    table = jnp.asarray([9.0, 4.0, 1.0, 0.0, 1.0, 4.0, 9.0, 16.0])
    space = {"depth": hp.uniformint("depth", 0, 7)}

    def obj(d):
        return table[d["depth"]]  # float indexing would fail the trace

    t = Trials()
    fmin(obj, space, algo=tpe.suggest, max_evals=30, trials=t,
         rstate=np.random.default_rng(0), show_progressbar=False,
         device_loop=True)  # True: raises if wrongly declared untraceable
    assert len(t) == 30
    assert min(l for l in t.losses() if l is not None) == 0.0
