"""Resilience-layer tests (ISSUE 8): the deterministic fault-injection
plane (``hyperopt_tpu.chaos``), the retry/backoff policy (``retry.py``),
monotonic-clock trial deadlines and retries in the executor, the worker's
heartbeat-join + retry hardening, and the filestore reserve backoff.

The acceptance pin rides here too: with chaos DISARMED a run starts zero
new threads and its proposals are bit-identical to a never-imported-chaos
run — the same invariant every obs plane in this repo holds.
"""

import datetime
import os
import subprocess
import sys
import threading
import time

import cloudpickle
import numpy as np
import pytest

import hyperopt_tpu.chaos as chaos
import hyperopt_tpu.filestore as filestore_mod
from hyperopt_tpu import JOB_STATE_DONE, JOB_STATE_ERROR, fmin, hp
from hyperopt_tpu.base import (
    JOB_STATE_CANCEL,
    JOB_STATE_RUNNING,
    Domain,
    Trials,
    coarse_utcnow,
)
from hyperopt_tpu.algos import tpe
from hyperopt_tpu.filestore import FileTrials
from hyperopt_tpu.parallel import ExecutorTrials
from hyperopt_tpu.retry import RetryPolicy
from hyperopt_tpu.worker import FileWorker


SPACE = {"x": hp.uniform("x", -5, 5)}


def quad(d):
    return (d["x"] - 1.0) ** 2


@pytest.fixture(autouse=True)
def _disarm_chaos():
    """Every test leaves the process disarmed (env is clean in the suite,
    so reset() == disarmed)."""
    yield
    chaos.reset()


def _insert_new(trials, domain, n, seed=0):
    from hyperopt_tpu.algos import rand

    ids = trials.new_trial_ids(n)
    docs = rand.suggest(ids, domain, trials, seed)
    trials.insert_trial_docs(docs)
    return ids


# ---------------------------------------------------------------------------
# chaos spec grammar + determinism
# ---------------------------------------------------------------------------


def test_parse_spec_valid():
    plan = chaos.parse_spec("7:kill@gen:2;ioerr@io:0.5;stall@trial:1.0:0.1")
    assert plan is not None and plan.seed == 7
    assert [r.action for r in plan.rules] == ["kill", "ioerr", "stall"]
    assert plan.rules[0].count == 2
    assert plan.rules[1].prob == 0.5
    assert plan.rules[2].sec == 0.1


@pytest.mark.parametrize("raw", [
    "", "0", "off",            # explicitly disabled
    "nonsense",                # no seed
    "7:",                      # no rules
    "x:kill@gen:1",            # bad seed
    "7:frob@gen:1",            # unknown action
    "7:kill@gen",              # missing count
    "7:stall@gen:0.5",         # missing seconds
    "7:ioerr@io:notafloat",    # bad probability
])
def test_parse_spec_disarms_on_bad_or_empty(raw):
    assert chaos.parse_spec(raw) is None


def test_count_rule_fires_on_exact_hit():
    plan = chaos.parse_spec("1:term@gen:3")
    assert plan.check("gen") == []
    assert plan.check("gen") == []
    assert plan.check("gen") == [("term",)]
    assert plan.check("gen") == []          # one-shot
    assert plan.check("other") == []        # site-scoped


def test_probabilistic_schedule_is_seeded_deterministic():
    a = chaos.parse_spec("42:ioerr@io:0.3")
    b = chaos.parse_spec("42:ioerr@io:0.3")
    seq_a = [bool(a.check("io", io=True)) for _ in range(200)]
    seq_b = [bool(b.check("io", io=True)) for _ in range(200)]
    assert seq_a == seq_b
    assert any(seq_a) and not all(seq_a)  # it actually fires, sometimes
    c = chaos.parse_spec("43:ioerr@io:0.3")
    assert seq_a != [bool(c.check("io", io=True)) for _ in range(200)]


def test_ioerr_ignored_at_plain_points():
    plan = chaos.configure("1:ioerr@gen:1.0")
    assert plan.check("gen", io=False) == []  # point() never raises
    assert plan.check("gen", io=True) == [("ioerr",)]


def test_io_point_raises_through_atomic_write(tmp_path):
    chaos.configure("3:ioerr@io:1.0")
    with pytest.raises(OSError, match="chaos"):
        filestore_mod._atomic_write(str(tmp_path / "f"), b"x")
    chaos.configure(None)
    filestore_mod._atomic_write(str(tmp_path / "f"), b"x")  # disarmed: fine
    assert (tmp_path / "f").read_bytes() == b"x"


def test_stall_sleeps_at_site():
    chaos.configure("5:stall@gen:1.0:0.05")
    t0 = time.perf_counter()
    chaos.point("gen")
    assert time.perf_counter() - t0 >= 0.03


def test_term_kills_process_at_scheduled_site():
    code = ("import hyperopt_tpu.chaos as c; c.configure('1:term@x:2'); "
            "c.point('x'); print('alive', flush=True); c.point('x'); "
            "print('unreachable', flush=True)")
    p = subprocess.run([sys.executable, "-c", code], capture_output=True,
                       text=True, timeout=120,
                       env={**os.environ, "JAX_PLATFORMS": "cpu",
                            "PALLAS_AXON_POOL_IPS": ""})
    assert "alive" in p.stdout
    assert "unreachable" not in p.stdout
    assert p.returncode != 0  # died at the 2nd hit


def test_injection_counted_in_metrics():
    from hyperopt_tpu.obs import MetricsRegistry

    reg = MetricsRegistry("chaos-test")
    chaos.configure("1:stall@gen:1.0:0.0")
    chaos.point("gen", metrics=reg)
    assert reg.counter("chaos.stall.gen").value == 1


def test_disarmed_no_new_threads_and_proposals_bit_identical():
    def run(seed=11):
        t = Trials()
        fmin(quad, SPACE, algo=tpe.suggest, max_evals=10, trials=t,
             rstate=np.random.default_rng(seed), show_progressbar=False)
        return t

    chaos.reset()  # env-resolved: disarmed
    t_plain = run()
    before = {th.name for th in threading.enumerate()}
    t_again = run()
    after = {th.name for th in threading.enumerate()}
    assert after - before == set()  # chaos plane starts NOTHING
    # armed-on-a-never-hit-site is behaviorally identical too (no draws
    # outside matched sites)
    chaos.configure("9:kill@nosuchsite:1")
    t_armed = run()
    assert t_plain.losses() == t_again.losses() == t_armed.losses()
    for a, b in zip(t_plain.trials, t_armed.trials):
        assert a["misc"]["vals"] == b["misc"]["vals"]


# ---------------------------------------------------------------------------
# retry policy
# ---------------------------------------------------------------------------


def test_retry_policy_deterministic_jittered_backoff():
    p = RetryPolicy(max_retries=3, base_delay=0.5, max_delay=4.0, jitter=0.5)
    d0 = p.delay(0, key="t1")
    assert d0 == p.delay(0, key="t1")       # deterministic
    assert 0.25 <= d0 <= 0.5                # jitter window
    assert p.delay(0, key="t2") != d0       # keys decorrelate
    assert p.delay(10, key="t1") <= 4.0     # capped
    assert RetryPolicy(1, jitter=0.0).delay(2) == 2.0  # pure exponential


def test_retry_policy_coerce_and_budget():
    assert RetryPolicy.coerce(None).max_retries == 0
    assert RetryPolicy.coerce(3).max_retries == 3
    p = RetryPolicy(2)
    assert RetryPolicy.coerce(p) is p
    assert p.retries_left(1) and p.retries_left(2) and not p.retries_left(3)
    with pytest.raises(TypeError):
        RetryPolicy.coerce("nope")


def test_retry_policy_from_env():
    assert RetryPolicy.from_env({}).max_retries == 0
    p = RetryPolicy.from_env({"HYPEROPT_TPU_TRIAL_RETRIES": "2:0.1"})
    assert p.max_retries == 2 and p.base_delay == 0.1
    assert RetryPolicy.from_env(
        {"HYPEROPT_TPU_TRIAL_RETRIES": "bogus"}).max_retries == 0


# ---------------------------------------------------------------------------
# executor: monotonic deadlines + retries
# ---------------------------------------------------------------------------


def test_executor_cancel_uses_monotonic_not_wall_clock():
    t = ExecutorTrials(n_workers=1, timeout=10.0, refresh=False)
    fake = {"now": 1000.0}
    t._monotonic = lambda: fake["now"]
    doc = {"tid": 1, "state": JOB_STATE_RUNNING, "misc": {},
           "result": None, "book_time": coarse_utcnow(), "owner": "w"}
    t._dynamic_trials.append(doc)
    t._deadlines[1] = fake["now"] + 10.0
    # NTP step / suspended host: wall book_time is suddenly 10 hours old,
    # but the monotonic deadline has NOT expired — the trial must survive
    doc["book_time"] = coarse_utcnow() - datetime.timedelta(hours=10)
    t._cancel_timed_out()
    assert doc["state"] == JOB_STATE_RUNNING
    # real elapsed time past the budget: cancelled
    fake["now"] += 10.5
    t._cancel_timed_out()
    assert doc["state"] == JOB_STATE_CANCEL
    assert 1 not in t._deadlines
    t.shutdown()


def test_executor_resumed_running_trial_gets_fresh_budget():
    t = ExecutorTrials(n_workers=1, timeout=10.0, refresh=False)
    fake = {"now": 50.0}
    t._monotonic = lambda: fake["now"]
    # a RUNNING doc from a resumed checkpoint: no deadline recorded (the
    # old process's monotonic clock is meaningless here)
    doc = {"tid": 7, "state": JOB_STATE_RUNNING, "misc": {},
           "result": None, "book_time": coarse_utcnow(), "owner": "w"}
    t._dynamic_trials.append(doc)
    t._cancel_timed_out()
    assert doc["state"] == JOB_STATE_RUNNING  # stamped, not cancelled
    assert t._deadlines[7] == 60.0
    fake["now"] = 61.0
    t._cancel_timed_out()
    assert doc["state"] == JOB_STATE_CANCEL
    t.shutdown()


def test_executor_deadlines_not_pickled():
    t = ExecutorTrials(n_workers=1, timeout=10.0, refresh=False)
    t._deadlines[3] = 123.0
    state = t.__getstate__()
    assert state["_deadlines"] == {}
    t.shutdown()


def test_executor_retries_flaky_objective_and_records_attempts():
    calls = {"n": 0}

    def flaky(d):
        calls["n"] += 1
        if calls["n"] % 2 == 1:  # every first attempt fails
            raise RuntimeError("transient")
        return quad(d)

    t = ExecutorTrials(n_workers=1,
                       retry=RetryPolicy(max_retries=2, base_delay=0.01))
    fmin(flaky, SPACE, algo=tpe.suggest, max_evals=2, trials=t,
         max_queue_len=1, rstate=np.random.default_rng(0),
         show_progressbar=False)
    t.shutdown()
    assert t.count_by_state_unsynced(JOB_STATE_DONE) == 2
    assert [d["misc"]["attempts"] for d in t.trials] == [2, 2]
    assert t.metrics.counter("trials.retries").value == 2
    assert t.metrics.histogram("retry.backoff_sec").count == 2


def test_executor_cancel_during_backoff_stops_retries():
    from hyperopt_tpu.algos import rand

    calls = {"n": 0}

    def bad(d):
        calls["n"] += 1
        raise RuntimeError("always")

    t = ExecutorTrials(n_workers=1, refresh=False,
                       retry=RetryPolicy(max_retries=5, base_delay=0.01))
    domain = Domain(bad, SPACE)
    t.attachments["FMinIter_Domain"] = domain
    (trial,) = rand.suggest(t.new_trial_ids(1), domain, t, 0)
    t._dynamic_trials.append(trial)

    def cancel_during_backoff(delay):
        with t._lock:
            trial["state"] = JOB_STATE_CANCEL

    t._sleep = cancel_during_backoff
    t._run_one(trial)
    # the docstring guarantee: a trial cancelled between attempts is NOT
    # re-evaluated (the re-run's result could only ever be discarded)
    assert calls["n"] == 1
    assert t.metrics.counter("results.discarded").value >= 1
    t.shutdown()


def test_executor_deadlines_cleared_on_normal_finish():
    t = ExecutorTrials(n_workers=1, timeout=60.0)
    fmin(quad, SPACE, algo=tpe.suggest, max_evals=3, trials=t,
         max_queue_len=1, rstate=np.random.default_rng(0),
         show_progressbar=False)
    t.shutdown()
    assert t.count_by_state_unsynced(JOB_STATE_DONE) == 3
    assert t._deadlines == {}  # no per-trial leak over a long run


def test_executor_no_retry_by_default():
    def bad(d):
        raise RuntimeError("permanent")

    t = ExecutorTrials(n_workers=1)
    with pytest.raises(Exception):
        fmin(bad, SPACE, algo=tpe.suggest, max_evals=2, trials=t,
             max_queue_len=1, rstate=np.random.default_rng(0),
             show_progressbar=False)
    t.shutdown()
    assert t.count_by_state_unsynced(JOB_STATE_ERROR) == 2
    assert all(d["misc"]["attempts"] == 1 for d in t.trials)


# ---------------------------------------------------------------------------
# worker: heartbeat lifecycle + retries
# ---------------------------------------------------------------------------


def _hb_threads():
    return [th for th in threading.enumerate()
            if th.is_alive() and th.name.startswith("hyperopt-heartbeat")]


def test_worker_joins_heartbeat_on_objective_exception(tmp_path):
    def bad(d):
        raise RuntimeError("objective boom")

    t = FileTrials(tmp_path / "s")
    domain = Domain(bad, SPACE)
    t.attachments["FMinIter_Domain"] = cloudpickle.dumps(domain)
    _insert_new(t, domain, 1)
    w = FileWorker(str(tmp_path / "s"), poll_interval=0.05,
                   heartbeat_interval=0.05)
    assert w.run_one(reserve_timeout=5) is False
    # the satellite fix: no beating thread may survive the exception path
    # (a leaked beat can resurrect running/<tid>.pkl after a concurrent
    # reclaim already swept it)
    assert _hb_threads() == []
    t.refresh()
    assert t.count_by_state_unsynced(JOB_STATE_ERROR) == 1
    (doc,) = w.store.load_all()
    assert doc["misc"]["attempts"] == 1


def test_worker_retries_then_succeeds_and_records_attempts(tmp_path):
    marker = tmp_path / "failed_once"

    def flaky(d):
        if not marker.exists():
            marker.write_text("x")
            raise RuntimeError("transient")
        return quad(d)

    t = FileTrials(tmp_path / "s")
    domain = Domain(flaky, SPACE)
    t.attachments["FMinIter_Domain"] = cloudpickle.dumps(domain)
    _insert_new(t, domain, 1)
    w = FileWorker(str(tmp_path / "s"), poll_interval=0.05,
                   heartbeat_interval=0.05,
                   retry=RetryPolicy(max_retries=2, base_delay=0.01))
    assert w.run_one(reserve_timeout=5) is True
    assert _hb_threads() == []
    t.refresh()
    assert t.count_by_state_unsynced(JOB_STATE_DONE) == 1
    assert t.trials[0]["misc"]["attempts"] == 2
    assert w.store.metrics.counter("trials.retries").value >= 1


def test_worker_heartbeat_thread_survives_store_write_failure(tmp_path,
                                                              monkeypatch):
    marker = tmp_path / "evaluated"  # the domain is CLOUDPICKLED: closure
    # state would mutate the worker's copy, not ours — mark via the fs

    def slowish(d, _marker=str(marker)):
        time.sleep(0.3)  # several heartbeat intervals
        with open(_marker, "w") as f:
            f.write("x")
        return quad(d)

    t = FileTrials(tmp_path / "s")
    domain = Domain(slowish, SPACE)
    t.attachments["FMinIter_Domain"] = cloudpickle.dumps(domain)
    _insert_new(t, domain, 1)
    w = FileWorker(str(tmp_path / "s"), poll_interval=0.05,
                   heartbeat_interval=0.05)

    def bad_heartbeat(doc):
        raise OSError("nfs blip")

    # every heartbeat WRITE fails: the beat loop must log-and-continue
    # (a dead beat thread would guarantee a stale reclaim of live work),
    # and the trial still finishes
    monkeypatch.setattr(w.store, "heartbeat", bad_heartbeat)
    assert w.run_one(reserve_timeout=5) is True
    assert marker.exists()
    assert _hb_threads() == []
    t.refresh()
    assert t.count_by_state_unsynced(JOB_STATE_DONE) == 1


def test_worker_poll_loop_survives_injected_store_io_error(tmp_path):
    t = FileTrials(tmp_path / "s")
    domain = Domain(quad, SPACE)
    t.attachments["FMinIter_Domain"] = cloudpickle.dumps(domain)
    _insert_new(t, domain, 1)
    w = FileWorker(str(tmp_path / "s"), poll_interval=0.01,
                   heartbeat_interval=0.05)
    # seeded intermittent store-write failure: reserve retries through it
    chaos.configure("11:ioerr@io:0.5")
    try:
        ok = w.run_one(reserve_timeout=10)
    finally:
        chaos.configure(None)
    assert _hb_threads() == []
    t.refresh()
    if ok:  # finish() may itself have lost its write — the claim survives
        assert t.count_by_state_unsynced(JOB_STATE_DONE) == 1


# ---------------------------------------------------------------------------
# filestore: reserve contention backoff
# ---------------------------------------------------------------------------


def test_reserve_backs_off_on_contention(tmp_path, monkeypatch):
    t = FileTrials(tmp_path / "s")
    domain = Domain(quad, SPACE)
    t.attachments["FMinIter_Domain"] = cloudpickle.dumps(domain)
    _insert_new(t, domain, 3)
    store = t.store
    sleeps = []
    store._sleep = sleeps.append

    real_rename = os.rename
    fails = {"n": 2}

    def contended(src, dst, *a, **kw):
        if "running" in str(dst) and fails["n"] > 0:
            fails["n"] -= 1
            raise FileNotFoundError(src)  # another worker won the race
        return real_rename(src, dst, *a, **kw)

    monkeypatch.setattr(filestore_mod.os, "rename", contended)
    doc = store.reserve("me")
    assert doc is not None  # third candidate claimed
    assert len(sleeps) == 2
    assert 0 < sleeps[0] <= 0.001        # attempt 0: jittered 1ms base
    assert sleeps[1] <= 0.002            # attempt 1: doubled, capped
    hist = store.metrics.histogram("reserve.backoff_sec")
    assert hist.count >= 2
    assert store.metrics.counter("reserve.contention").value >= 2
