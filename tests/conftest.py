"""Test configuration: run JAX on a virtual 8-device CPU mesh.

Mirrors the reference's doctrine of testing "distributed" as multi-process on
one host (SURVEY.md §4): here, multi-chip sharding is tested on
``--xla_force_host_platform_device_count=8`` CPU devices.  Must run before the
first ``import jax`` in any test module.
"""

import os

# Force CPU even when the ambient environment points JAX at a TPU tunnel
# (JAX_PLATFORMS=axon): the test suite must be hermetic and fast.
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture
def rng():
    return np.random.default_rng(0)
