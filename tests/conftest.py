"""Test configuration: run JAX on a virtual 8-device CPU mesh.

Mirrors the reference's doctrine of testing "distributed" as multi-process on
one host (SURVEY.md §4): here, multi-chip sharding is tested on
``--xla_force_host_platform_device_count=8`` CPU devices.

The ambient environment registers an 'axon' TPU-tunnel PJRT plugin via
sitecustomize at interpreter start, so by conftest time ``jax`` may already
be imported with ``JAX_PLATFORMS=axon`` captured.  Env vars alone are too
late; ``jax.config.update`` still wins as long as no backend has been
initialized — which is guaranteed here because conftest runs before any test
imports.  The suite must be hermetic and fast, and must never contend for
the one real TPU chip.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pytest  # noqa: E402

assert jax.devices()[0].platform == "cpu", (
    "test suite must run on CPU, got " + jax.devices()[0].platform
)


@pytest.fixture
def rng():
    return np.random.default_rng(0)
