"""Perf-trajectory store (hyperopt_tpu/obs/trajectory.py) + the windowed
regression gate (scripts/bench_gate.py) + the --trend renderer.

All tier-1 (CPU, fast).  The load-bearing invariants pinned here:

* the store is append-only JSONL whose readers tolerate a torn final
  line (a bench killed mid-append never blinds the gate to the history);
* backfill from the checked-in ``BENCH_r*.json`` is idempotent and
  captures the headline + tail-mined metrics per round;
* the windowed gate is direction-aware (higher-is-better throughputs vs
  lower-is-better latencies vs absolute-deviation overhead fractions),
  passes on stable history, FAILS on a synthetic injected regression,
  and never gates keys its direction table doesn't know;
* occurrence-count mismatches in tail-mined series skip positionally
  instead of misaligning (differently-truncated recorded tails).
"""

import json
import os
import sys

import pytest

from hyperopt_tpu.obs import trajectory
from hyperopt_tpu.obs.report import main as report_main, render_trend

sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "scripts"))
import bench_gate  # noqa: E402  (scripts/bench_gate.py)


def _rec(value=100.0, ask_p50=2.0, overhead=0.005, rnd=None,
         source="bench.py", series=None, keys_extra=None):
    keys = {"value": value, "ask_p50_ms": ask_p50,
            "profiler_overhead_frac": overhead}
    if keys_extra:
        keys.update(keys_extra)
    return {"kind": "bench", "ts": 1000.0 + (rnd or 0), "round": rnd,
            "source": source, "git_rev": "abc1234", "rc": 0,
            "backend": "cpu", "config": {},
            "keys": keys,
            "series": dict(series or {"ask_p50_ms": [ask_p50],
                                      "profiler_overhead_frac": [overhead]})}


def _store(tmp_path, records):
    path = str(tmp_path / ".obs" / "trajectory.jsonl")
    for r in records:
        trajectory.append(r, path)
    return path


# ---------------------------------------------------------------------------
# store: append-only, torn-line tolerant
# ---------------------------------------------------------------------------


def test_append_load_roundtrip_and_torn_line(tmp_path):
    path = _store(tmp_path, [_rec(rnd=1), _rec(rnd=2)])
    # a bench killed mid-append leaves a torn final line
    with open(path, "a") as f:
        f.write('{"kind": "bench", "ts": 3, "keys": {"value": 1')
    records = trajectory.load(path)
    assert [r["round"] for r in records] == [1, 2]  # torn line skipped
    # and the gate still runs over the surviving history
    regs, notes = bench_gate.windowed_compare(
        records[:-1], records[-1], trajectory.KEY_DIRECTIONS)
    assert regs == []


def test_load_missing_store_is_empty(tmp_path):
    assert trajectory.load(str(tmp_path / "nope.jsonl")) == []


# ---------------------------------------------------------------------------
# backfill from BENCH_r*.json
# ---------------------------------------------------------------------------


def _fake_bench_artifact(tmp_path, n, value, tail_metrics=""):
    rec = {"n": n, "cmd": "python bench.py", "rc": 0,
           "tail": '{"metric": "x", "value": %s%s}' % (value, tail_metrics),
           "parsed": {"metric": "tpe_candidate_proposal_throughput",
                      "value": value, "vs_baseline": 2.0,
                      "backend": "cpu"}}
    path = tmp_path / f"BENCH_r{n:02d}.json"
    path.write_text(json.dumps(rec))
    return str(path)


def test_backfill_mines_rounds_and_is_idempotent(tmp_path):
    _fake_bench_artifact(tmp_path, 1, 100.0,
                         ', "trials_per_sec": 50.0')
    _fake_bench_artifact(tmp_path, 2, 120.0,
                         ', "trials_per_sec": 60.0, "ask_p50_ms": 2.5')
    store = str(tmp_path / ".obs" / "trajectory.jsonl")
    appended = trajectory.backfill(root=str(tmp_path), path=store)
    assert appended == [1, 2]
    records = trajectory.load(store)
    assert [r["round"] for r in records] == [1, 2]
    assert records[0]["keys"]["value"] == 100.0
    # tail metrics stay in series ONLY for backfilled rounds: a recorded
    # tail's first occurrence can name a different stage than the live
    # keys_override representative, so it must not share the scalar key
    assert "trials_per_sec" not in records[0]["keys"]
    assert records[0]["series"]["trials_per_sec"] == [50.0]
    assert records[1]["series"]["ask_p50_ms"] == [2.5]
    assert records[0]["source"] == "BENCH_r01.json"
    # idempotent: a second backfill appends nothing
    assert trajectory.backfill(root=str(tmp_path), path=store) == []
    assert len(trajectory.load(store)) == 2
    # force re-appends
    assert trajectory.backfill(root=str(tmp_path), path=store,
                               force=True) == [1, 2]
    assert len(trajectory.load(store)) == 4


def test_repo_store_is_seeded_with_bench_history():
    # the satellite acceptance: >= 5 backfilled records committed, so the
    # windowed gate has history from day one
    records = trajectory.load()
    rounds = [r.get("round") for r in records if r.get("round") is not None]
    assert len(rounds) >= 5
    assert rounds == sorted(rounds)


def test_record_from_headline_stamps_rev_and_config():
    rec = trajectory.record_from_headline(
        {"value": 42.0, "vs_baseline": 3.0, "backend": "cpu"},
        detail_tail='{"ask_p50_ms": 1.5, "ask_p50_ms": 2.5}',
        config={"hist_dtype": "bf16"})
    assert rec["keys"]["value"] == 42.0
    assert rec["keys"]["ask_p50_ms"] == 1.5  # first occurrence
    assert rec["series"]["ask_p50_ms"] == [1.5, 2.5]
    assert rec["config"] == {"hist_dtype": "bf16"}
    assert rec["source"] == "bench.py"
    # this repo IS a git checkout: the live record carries its rev
    assert rec["git_rev"]


def test_key_directions_cover_gated_tail_metrics():
    # every tail-mined metric the store records has explicit direction
    # metadata — the "learns the new trajectory keys" satellite
    for name in trajectory.TAIL_METRICS:
        meta = trajectory.KEY_DIRECTIONS[name]
        assert meta["direction"] in ("higher", "lower")
        assert meta["threshold"] > 0
    assert trajectory.KEY_DIRECTIONS["profiler_overhead_frac"]["absolute"]


# ---------------------------------------------------------------------------
# windowed gate semantics
# ---------------------------------------------------------------------------


def _history(n=5, **kw):
    return [_rec(rnd=i + 1, **kw) for i in range(n)]


def test_windowed_gate_passes_on_stable_history():
    hist = _history(5)
    regs, notes = bench_gate.windowed_compare(
        hist, _rec(value=101.0, ask_p50=1.9), trajectory.KEY_DIRECTIONS)
    assert regs == []
    assert any("value" in n for n in notes)


def test_windowed_gate_fails_on_injected_throughput_regression():
    hist = _history(5)
    # higher-is-better: a 40% drop vs the median trips the 20% threshold
    regs, _ = bench_gate.windowed_compare(
        hist, _rec(value=60.0), trajectory.KEY_DIRECTIONS)
    assert any(r.startswith("value:") for r in regs)


def test_windowed_gate_fails_on_injected_latency_rise():
    hist = _history(5)
    # lower-is-better: ask_p50 2.0 -> 3.5 is a 75% rise vs the 35% bound
    regs, _ = bench_gate.windowed_compare(
        hist, _rec(ask_p50=3.5), trajectory.KEY_DIRECTIONS)
    assert any(r.startswith("ask_p50_ms") for r in regs)


def test_windowed_gate_absolute_threshold_for_overhead_frac():
    hist = _history(5, overhead=0.004)
    # profiler_overhead_frac gates the ABSOLUTE value (0.35 — decisively
    # above the stage's ±15-20% wall-clock noise): a plane that stopped
    # being idle (+50%) fails even though near-zero fractions make
    # relative bounds meaningless, while noise-scale swings pass
    regs, _ = bench_gate.windowed_compare(
        hist, _rec(overhead=0.50), trajectory.KEY_DIRECTIONS)
    assert any(r.startswith("profiler_overhead_frac") for r in regs)
    regs, _ = bench_gate.windowed_compare(
        hist, _rec(overhead=0.17), trajectory.KEY_DIRECTIONS)
    assert not any(r.startswith("profiler_overhead_frac") for r in regs)


def test_windowed_gate_scalar_view_gates_despite_series_shape_change():
    # real histories change series shape across PRs (stages added,
    # differently-truncated tails), so the positional pass alone would
    # never engage — the representative scalar view must still gate
    hist = [_rec(rnd=i + 1, keys_extra={"trials_per_sec": 100.0},
                 series={"trials_per_sec": [100.0, 50.0]})
            for i in range(5)]
    new = _rec(keys_extra={"trials_per_sec": 40.0},
               series={"trials_per_sec": [40.0, 20.0, 10.0]})  # new shape
    regs, _ = bench_gate.windowed_compare(
        hist, new, trajectory.KEY_DIRECTIONS)
    assert any(r.startswith("trials_per_sec") for r in regs)


def test_load_filters_non_bench_records(tmp_path):
    path = _store(tmp_path, [_rec(rnd=1)])
    with open(path, "a") as f:
        f.write(json.dumps({"kind": "span", "name": "suggest",
                            "ts": 1.0}) + "\n")
    recs = trajectory.load(path)
    assert len(recs) == 1 and recs[0]["kind"] == "bench"


def test_windowed_gate_zero_median_records_instead_of_gating():
    # history_bytes can be all-zero on a backend where memory_stats() is
    # None; the first run that MEASURES a real value must not fail the
    # gate (a zero median makes every relative bound degenerate)
    hist = _history(5, keys_extra={"history_bytes": 0.0},
                    series={"history_bytes": [0.0]})
    new = _rec(keys_extra={"history_bytes": 4096.0},
               series={"history_bytes": [4096.0]})
    regs, notes = bench_gate.windowed_compare(
        hist, new, trajectory.KEY_DIRECTIONS)
    assert not any(r.startswith("history_bytes") for r in regs)
    assert any("median is 0" in n for n in notes)


def test_windowed_gate_median_robust_to_one_noisy_round():
    # one catastrophic round in the window must not poison the baseline
    # (the exact failure mode of the pairwise newest-vs-previous gate)
    hist = _history(4) + [_rec(value=5.0, rnd=5)]
    regs, _ = bench_gate.windowed_compare(
        hist, _rec(value=95.0), trajectory.KEY_DIRECTIONS)
    assert regs == []


def test_windowed_gate_skips_mismatched_series_counts():
    hist = _history(5, series={"sharded_cand_per_sec": [10.0, 19.0, 36.0]})
    new = _rec(series={"sharded_cand_per_sec": [10.0, 19.0]})
    regs, notes = bench_gate.windowed_compare(
        hist, new, trajectory.KEY_DIRECTIONS)
    assert not any("sharded" in r for r in regs)
    assert any("no matching history" in n for n in notes)


def test_windowed_gate_positional_series_regression():
    hist = _history(5, series={"sharded_cand_per_sec": [10.0, 19.0, 36.0]})
    new = _rec(series={"sharded_cand_per_sec": [10.0, 19.0, 20.0]})
    regs, _ = bench_gate.windowed_compare(
        hist, new, trajectory.KEY_DIRECTIONS)
    assert any(r.startswith("sharded_cand_per_sec[2]") for r in regs)


def test_windowed_gate_unknown_keys_never_gate():
    hist = _history(5, keys_extra={"mystery_metric": 100.0})
    regs, notes = bench_gate.windowed_compare(
        hist, _rec(keys_extra={"mystery_metric": 1.0}),
        trajectory.KEY_DIRECTIONS)
    assert not any("mystery" in r for r in regs)
    assert any("mystery" in n and "ungated" in n for n in notes)


def test_windowed_gate_window_limits_history():
    # six ancient slow rounds + four recent fast ones: window=4 sees only
    # the fast era, so a return to the ancient value IS a regression
    hist = _history(6, value=10.0) + [
        _rec(value=100.0, rnd=i + 7) for i in range(4)]
    regs, _ = bench_gate.windowed_compare(
        hist, _rec(value=10.0), trajectory.KEY_DIRECTIONS, window=4)
    assert any(r.startswith("value:") for r in regs)
    # window=10 folds the slow-majority era back in: the median returns
    # to the ancient value and the same run passes
    regs, _ = bench_gate.windowed_compare(
        hist, _rec(value=10.0), trajectory.KEY_DIRECTIONS, window=10)
    assert regs == []


# ---------------------------------------------------------------------------
# bench_gate CLI: windowed main + legacy fallback
# ---------------------------------------------------------------------------


def test_bench_gate_cli_windowed_pass_and_fail(tmp_path, capsys):
    _store(tmp_path, _history(5) + [_rec(value=99.0, rnd=6)])
    assert bench_gate.main(["--dir", str(tmp_path)]) == 0
    out = capsys.readouterr().out
    assert "windowed" in out and "ok" in out

    tmp2 = tmp_path / "fail"
    tmp2.mkdir()
    _store(tmp2, _history(5) + [_rec(value=10.0, rnd=6)])
    assert bench_gate.main(["--dir", str(tmp2)]) == 1
    err = capsys.readouterr().err
    assert "REGRESSION" in err and "value" in err


def test_bench_gate_cli_backend_matched_history(tmp_path, capsys):
    # a CPU dev-box run must not gate against (or poison) TPU history:
    # with no same-backend record the gate records "no history" and
    # passes instead of failing the cross-backend compare
    tpu = _history(5)
    for r in tpu:
        r["backend"] = "tpu"
    _store(tmp_path, tpu + [_rec(value=1.0, rnd=6)])  # 100x "drop", cpu
    assert bench_gate.main(["--dir", str(tmp_path)]) == 0
    out = capsys.readouterr().out
    assert "backend=cpu" in out and "5 other-backend" in out

    # same-backend history still gates: one more cpu run, then a real drop
    _store(tmp_path, [_rec(value=1.0, rnd=7), _rec(value=0.1, rnd=8)])
    assert bench_gate.main(["--dir", str(tmp_path)]) == 1


def test_bench_gate_cli_threshold_override(tmp_path):
    _store(tmp_path, _history(5) + [_rec(value=85.0, rnd=6)])
    # 15% drop: passes the default 20%, fails an overridden 5%
    assert bench_gate.main(["--dir", str(tmp_path)]) == 0
    assert bench_gate.main(["--dir", str(tmp_path),
                            "--threshold", "0.05"]) == 1


def test_bench_gate_cli_falls_back_to_legacy_without_store(tmp_path,
                                                           capsys):
    _fake_bench_artifact(tmp_path, 1, 100.0)
    _fake_bench_artifact(tmp_path, 2, 95.0)
    assert bench_gate.main(["--dir", str(tmp_path)]) == 0
    out = capsys.readouterr().out
    assert "BENCH_r01.json -> BENCH_r02.json" in out


def test_bench_gate_cli_single_record_store_falls_back(tmp_path, capsys):
    _store(tmp_path, [_rec(rnd=1)])
    _fake_bench_artifact(tmp_path, 1, 100.0)
    assert bench_gate.main(["--dir", str(tmp_path)]) == 0
    out = capsys.readouterr().out
    assert "falling back" in out


# ---------------------------------------------------------------------------
# --trend renderer
# ---------------------------------------------------------------------------


def test_render_trend_directions_and_sparklines():
    records = _history(6)
    records[-1]["keys"]["value"] = 140.0
    text = render_trend(records)
    assert "bench trajectory" in text
    assert "value" in text and "higher=better" in text
    assert "ask_p50_ms" in text and "lower=better" in text
    assert "100 -> 140" in text
    assert "abc1234" in text  # per-run rev line


def test_render_trend_segments_mixed_backends():
    # a tpu→cpu switch is a hardware change, not a 1000x regression: keys
    # render one sparkline row per backend instead of one mixed line
    recs = [dict(_rec(value=1e8, rnd=i + 1), backend="tpu")
            for i in range(2)] + [_rec(value=5e5)]  # _rec defaults to cpu
    text = render_trend(recs)
    assert "value [tpu]" in text and "value [cpu]" in text
    assert "2 tpu runs" in text and "1 cpu runs" in text


def test_render_trend_empty_store():
    text = render_trend([])
    assert "store is empty" in text


def test_report_trend_cli(tmp_path, capsys):
    path = _store(tmp_path, _history(3))
    assert report_main(["--trend", path]) == 0
    out = capsys.readouterr().out
    assert "bench trajectory" in out and "value" in out
    # --trend is its own view
    assert report_main(["--trend", "--merge", path]) == 2
    # a missing store errors cleanly
    assert report_main(["--trend", str(tmp_path / "nope.jsonl")]) == 2
    # a scripted consumer must get an error, not text with exit 0
    assert report_main(["--trend", "--format", "json", path]) == 2
    assert report_main(["--trend", path, path]) == 2
