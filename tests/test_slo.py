"""ISSUE 11: the SLO error-budget plane — burn-rate math under a fake
clock (window rotation, budget exhaustion, recovery), gauge exposition
through the Prometheus lint, edge-triggered escalation, the env spec
grammar, and the report/snapshot surfaces."""

import os
import sys

from hyperopt_tpu._env import parse_service_slo
from hyperopt_tpu.obs.metrics import MetricsRegistry
from hyperopt_tpu.obs.slo import (DEFAULT_TARGETS, FAST_BURN, Objective,
                                  SLOPlane, WINDOWS)

sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "scripts"))
import validate_scrape  # noqa: E402  (scripts/validate_scrape.py)


class Clock:
    def __init__(self, t=1_000_000.0):
        self.t = t

    def __call__(self):
        return self.t

    def tick(self, sec):
        self.t += sec


def _plane(clock, targets=None, metrics=None, **kw):
    return SLOPlane(targets, metrics=metrics, clock=clock, **kw)


# ---------------------------------------------------------------------------
# burn-rate math
# ---------------------------------------------------------------------------


def test_all_good_traffic_burns_nothing():
    clock = Clock()
    obj = Objective("availability", 0.999)
    for _ in range(100):
        obj.record(True, clock())
    s = obj.status(clock())
    assert s["burn_fast"] == 0.0 and s["burn_slow"] == 0.0
    assert s["budget_remaining_frac"] == 1.0
    assert not s["exhausted"] and not s["fast_alerting"]


def test_all_bad_traffic_burns_at_inverse_budget():
    clock = Clock()
    obj = Objective("availability", 0.999)  # budget 0.1%
    for _ in range(50):
        obj.record(False, clock())
    s = obj.status(clock())
    # 100% bad over a 0.1% budget = burn rate 1000x
    assert abs(s["burn_fast"] - 1000.0) < 1e-9
    assert s["exhausted"] and s["fast_alerting"] and s["slow_alerting"]
    assert s["budget_remaining_frac"] < 0


def test_burn_rate_one_at_exact_budget_spend():
    clock = Clock()
    obj = Objective("o", 0.9)  # 10% budget
    for i in range(1000):
        obj.record(i % 10 != 0, clock())  # exactly 10% bad
    s = obj.status(clock())
    assert abs(s["burn_fast"] - 1.0) < 1e-9
    assert abs(s["budget_remaining_frac"]) < 1e-9


def test_idle_service_is_not_burning():
    clock = Clock()
    obj = Objective("o", 0.99)
    s = obj.status(clock())
    assert s["burn_fast"] == 0.0 and s["window_events"] == 0
    assert not s["exhausted"]


def test_window_rotation_ages_bad_events_out():
    clock = Clock()
    obj = Objective("o", 0.9)
    for _ in range(100):
        obj.record(False, clock())  # a terrible minute
    assert abs(obj.burn_rate(WINDOWS["fast"][0], clock()) - 10.0) < 1e-9
    # 6 minutes later the 5m window has rotated past it...
    clock.tick(6 * 60)
    for _ in range(100):
        obj.record(True, clock())
    assert obj.burn_rate(WINDOWS["fast"][0], clock()) == 0.0
    # ...but the 1h window still remembers (100 bad / 200 total / 0.1)
    assert abs(obj.burn_rate(WINDOWS["fast"][1], clock()) - 5.0) < 1e-9
    # and after the 6h window passes, the budget fully recovers
    clock.tick(7 * 3600)
    obj.record(True, clock())
    s = obj.status(clock())
    assert s["budget_remaining_frac"] == 1.0 and not s["exhausted"]


def test_exhaustion_and_recovery_cycle():
    clock = Clock()
    obj = Objective("o", 0.9)
    for _ in range(9):
        obj.record(True, clock())
    obj.record(False, clock())  # 10% bad = budget exactly spent
    assert obj.status(clock())["exhausted"]  # remaining <= 0
    # an hour of clean traffic dilutes the bad fraction under budget
    for _ in range(60):
        clock.tick(60)
        for _ in range(10):
            obj.record(True, clock())
    s = obj.status(clock())
    assert not s["exhausted"] and s["budget_remaining_frac"] > 0.8


def test_pair_alerting_needs_both_windows():
    """The fast pair alerts on min(5m, 1h): a single bad burst trips the
    5m window but not the 1h — no page (the SRE-workbook guard against
    paging on one bad minute)."""
    clock = Clock()
    obj = Objective("o", 0.999)
    # seed the 1h window with lots of good traffic, then one bad burst
    for _ in range(50_000):
        obj.record(True, clock())
    clock.tick(50 * 60)
    for _ in range(100):
        obj.record(False, clock())
    s = obj.status(clock())
    assert obj.burn_rate(WINDOWS["fast"][0], clock()) >= FAST_BURN
    assert s["burn_fast"] < FAST_BURN  # the 1h window vetoes
    assert not s["fast_alerting"]


# ---------------------------------------------------------------------------
# the plane: routing, gauges, escalation
# ---------------------------------------------------------------------------


def test_record_request_routing():
    clock = Clock()
    plane = _plane(clock)
    plane.record_request("ask", 200, latency_sec=0.010)
    plane.record_request("ask", 200, latency_sec=5.0)  # slow: bad latency
    plane.record_request("ask", 429, shed=True)        # shed: bad shed
    plane.record_request("tell", 500)                  # bad availability
    st = plane.status()
    assert st["availability"]["window_events"] == 4
    av_good, av_bad = plane.objectives["availability"].window_counts(
        3600, clock())
    assert (av_good, av_bad) == (3, 1)
    lat_good, lat_bad = plane.objectives["ask_latency"].window_counts(
        3600, clock())
    assert (lat_good, lat_bad) == (1, 1)  # the 429 never counts latency
    sh_good, sh_bad = plane.objectives["shed_rate"].window_counts(
        3600, clock())
    assert (sh_good, sh_bad) == (2, 1)


def test_gauges_pass_the_exposition_lint():
    from hyperopt_tpu.obs.serve import prometheus_text

    clock = Clock()
    reg = MetricsRegistry("slo-test-ns")
    plane = _plane(clock, metrics=reg)
    for i in range(20):
        plane.record_request("ask", 200 if i % 2 else 503,
                             latency_sec=0.01)
    plane.publish()
    names = dict(reg.iter_metrics())
    for obj in DEFAULT_TARGETS:
        for leaf in ("burn_fast", "burn_slow", "budget_remaining_frac",
                     "fast_alerting", "slow_alerting", "exhausted"):
            assert f"slo.{obj}.{leaf}" in names, (obj, leaf)
    # the full exposition (slo_* families included) lints clean
    import hyperopt_tpu.obs.metrics as metrics_mod

    metrics_mod.adopt_metrics("slo-test-ns", reg)
    try:
        text = prometheus_text(["slo-test-ns"])
        assert "hyperopt_tpu_slo_availability_burn_fast" in text
        assert validate_scrape.validate_metrics_text(text) == []
    finally:
        metrics_mod.reset_metrics("slo-test-ns")


def test_escalation_fires_once_per_episode_with_cooldown():
    clock = Clock()
    fired = []
    plane = _plane(clock, escalation=lambda: fired.append(clock()),
                   eval_interval=0.0, escalation_cooldown=600.0)
    # page-hot traffic: everything 5xx
    for _ in range(10):
        plane.record_request("ask", 500, latency_sec=0.01)
    assert len(fired) == 1  # edge-triggered: once, not per request
    for _ in range(10):
        plane.record_request("ask", 500, latency_sec=0.01)
    assert len(fired) == 1
    # recovery clears the edge... but the cooldown still holds
    clock.tick(7 * 3600)
    plane.record_request("ask", 200, latency_sec=0.01)
    assert not plane.status()["availability"]["fast_alerting"]
    for _ in range(10):
        plane.record_request("ask", 500, latency_sec=0.01)
    assert len(fired) == 2  # cooldown (600s) long passed: a new episode
    assert plane.escalations == 2


def test_escalation_hook_failure_never_cascades():
    clock = Clock()

    def boom():
        raise RuntimeError("capture exploded")

    plane = _plane(clock, escalation=boom, eval_interval=0.0)
    for _ in range(5):
        plane.record_request("ask", 500, latency_sec=0.01)  # must not raise


def test_bad_target_rejected():
    import pytest

    with pytest.raises(ValueError):
        Objective("o", 1.0)
    with pytest.raises(ValueError):
        Objective("o", 0.0)


# ---------------------------------------------------------------------------
# env grammar
# ---------------------------------------------------------------------------


def test_parse_service_slo_grammar():
    assert parse_service_slo({}) is not None  # default ON
    assert parse_service_slo({"HYPEROPT_TPU_SERVICE_SLO": "off"}) is None
    assert parse_service_slo({"HYPEROPT_TPU_SERVICE_SLO": "0"}) is None
    t = parse_service_slo({"HYPEROPT_TPU_SERVICE_SLO":
                           "avail=99.5,ask_p99_ms=250,ask_pct=95,shed=2"})
    assert abs(t["availability"]["target"] - 0.995) < 1e-9
    assert t["ask_latency"]["threshold_ms"] == 250.0
    assert abs(t["ask_latency"]["target"] - 0.95) < 1e-9
    assert abs(t["shed_rate"]["target"] - 0.98) < 1e-9
    # malformed tokens keep the defaults, never raise
    t = parse_service_slo({"HYPEROPT_TPU_SERVICE_SLO": "avail=banana,,x=1"})
    assert t["availability"]["target"] == DEFAULT_TARGETS[
        "availability"]["target"]
    # shed=0 stays a valid (0,1) target
    t = parse_service_slo({"HYPEROPT_TPU_SERVICE_SLO": "shed=0"})
    assert 0 < t["shed_rate"]["target"] < 1
    SLOPlane(t)  # constructible


# ---------------------------------------------------------------------------
# surfaces: snapshot section + report banner
# ---------------------------------------------------------------------------


def test_snapshot_and_report_surfaces():
    from hyperopt_tpu.obs.report import _slo_lines
    from hyperopt_tpu.service.scheduler import StudyScheduler
    from hyperopt_tpu.service.server import ServiceHTTPServer

    srv = ServiceHTTPServer(0, scheduler=StudyScheduler(wal=False),
                            slo=True)
    assert srv.slo is not None
    for i in range(10):
        srv.slo.record_request("ask", 500, latency_sec=0.01)
    snap = srv.snapshot_dict()
    assert "slo" in snap
    assert snap["slo"]["availability"]["exhausted"]
    # the report section renders the budget bars + the banner from the
    # published gauges
    metrics = srv.scheduler.metrics.snapshot()["metrics"]
    out = []
    _slo_lines(metrics, out)
    text = "\n".join(out)
    assert "availability" in text and "budget" in text
    assert "ERROR-BUDGET-EXHAUSTED" in text
