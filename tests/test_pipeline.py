"""Zero-copy pipelined ask→tell (ISSUE 4): buffer donation, the
dispatch/readback split + ``lookahead`` overlap, lean multihost payloads
and the persistent compilation cache.

Golden values in this file were captured from the PRE-donation synchronous
loop (commit b7c53aa) with fixed seeds: ``lookahead=0`` on the donated
fused path must reproduce them bit for bit.
"""

import copy
import functools
import os
import pickle

import numpy as np
import pytest

import jax

from hyperopt_tpu import Trials, fmin, hp
from hyperopt_tpu.base import Domain
from hyperopt_tpu.exceptions import StaleHistoryError
from hyperopt_tpu.fmin import FMinIter
from hyperopt_tpu.algos import rand, tpe

SPACE = {"x": hp.uniform("x", -5, 5), "lr": hp.loguniform("lr", -3, 1)}


def obj(d):
    return (d["x"] - 1.0) ** 2 + (d["lr"] - 0.5) ** 2


def _vals(t, label):
    return [float(d["misc"]["vals"][label][0]).hex() for d in t.trials]


# captured from the pre-PR synchronous fused path (seed 1234, 24 evals,
# n_startup_jobs=8) — the lookahead=0 bit-identity pin
GOLD_QL1_X = ['-0x1.f9c2ec0000000p+0', '-0x1.f7f3e00000000p-2', '0x1.258da00000000p+2', '-0x1.6d90740000000p+1', '-0x1.9c01480000000p-1', '-0x1.25c6b40000000p+2', '0x1.3f9d180000000p-1', '0x1.047edc0000000p+2', '0x1.f7fcc00000000p+0', '0x1.458ae20000000p+1', '0x1.5ccb8c0000000p+0', '0x1.03e68a0000000p+1', '-0x1.3dc1740000000p+2', '0x1.cb25a00000000p+1', '0x1.80eba20000000p-2', '-0x1.950b1a0000000p+1', '0x1.0bbf580000000p+0', '0x1.1f08f80000000p+0', '0x1.85ad400000000p+1', '0x1.0387820000000p+0', '-0x1.32e10e0000000p+0', '0x1.3a43f00000000p-2', '0x1.3bc8da0000000p+2', '-0x1.07edf40000000p+1']
GOLD_QL1_LR = ['0x1.e8f7420000000p-5', '0x1.16480c0000000p-3', '0x1.6c61440000000p+0', '0x1.5396e40000000p-2', '0x1.f8760a0000000p-3', '0x1.1d3e440000000p-3', '0x1.7e43e20000000p+0', '0x1.365e640000000p-2', '0x1.2e124c0000000p+1', '0x1.f7b91a0000000p-1', '0x1.9a4a800000000p-1', '0x1.85d7920000000p-1', '0x1.1bce460000000p-1', '0x1.4378a20000000p+1', '0x1.194d480000000p-1', '0x1.50898c0000000p-3', '0x1.c301d00000000p-5', '0x1.d49f3c0000000p-5', '0x1.9f939a0000000p-5', '0x1.5c55fe0000000p-4', '0x1.69c2dc0000000p-4', '0x1.6de73c0000000p-4', '0x1.495bf80000000p-4', '0x1.7847660000000p-3']
GOLD_QL4_X = ['-0x1.f9c2ec0000000p+0', '-0x1.6650de0000000p+1', '-0x1.b351680000000p+0', '0x1.ceae0e0000000p+1', '-0x1.a0aa040000000p+0', '-0x1.2b21620000000p+2', '0x1.335d5a0000000p+2', '0x1.3fab100000000p+2', '0x1.3770e40000000p+1', '0x1.030e3c0000000p+1', '0x1.4927020000000p+1', '0x1.34a2dc0000000p+1', '0x1.ccf4c40000000p-2', '0x1.e6a7920000000p-2', '0x1.7d02400000000p-1', '0x1.23bab80000000p-1', '0x1.f2836a0000000p+1', '0x1.cd17540000000p+1', '-0x1.04c9540000000p+2', '0x1.e2d81e0000000p+1', '0x1.0fcdb20000000p+1', '0x1.e46a9a0000000p+0', '0x1.9da5940000000p+0', '0x1.a864be0000000p+0']

# captured from the pre-PR driver: single-process fold digest of a
# 24-eval conditional-space run — pins that the payload/device-mirror
# rework kept the fold byte-identical
GOLD_MH_CHECKSUM = "2e34e3dc7a77f3fcd82fed14adf23dfa961310049c1253a962b928eae2374252"
GOLD_MH_BEST = "0x1.c0beec0000000p-5"

MH_SPACE = {"x": hp.uniform("x", -5, 5), "m": hp.choice("m", [
    {"kind": 0, "a": hp.uniform("a", 0, 1)},
    {"kind": 1, "b": hp.uniform("b", -1, 0)},
])}


def mh_obj(s):
    inner = s["m"]
    extra = inner.get("a", 0.0) if inner["kind"] == 0 else -inner.get("b", 0.0)
    return (s["x"] - 1.0) ** 2 + extra


# ---------------------------------------------------------------------------
# lookahead=0 golden bit-identity + lookahead=1 masked-posterior semantics
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("ql,gold_x,gold_lr", [
    (1, GOLD_QL1_X, GOLD_QL1_LR),
    (4, GOLD_QL4_X, None),
])
def test_lookahead0_bitwise_matches_pre_pr_golden(ql, gold_x, gold_lr):
    t = Trials()
    algo = functools.partial(tpe.suggest, n_startup_jobs=8)
    fmin(obj, SPACE, algo=algo, max_evals=24, trials=t, max_queue_len=ql,
         lookahead=0, rstate=np.random.default_rng(1234),
         show_progressbar=False)
    assert _vals(t, "x") == gold_x
    if gold_lr is not None:
        assert _vals(t, "lr") == gold_lr


def test_lookahead1_equals_pending_masked_reference():
    # lookahead=1 proposals must equal a reference run where the pending
    # trial's loss is masked from the posterior — hyperopt's standard
    # async-evaluation semantics (Bergstra et al. 2011)
    from hyperopt_tpu.base import JOB_STATE_NEW

    n_startup = 6
    max_evals = 14
    algo = functools.partial(tpe.suggest, n_startup_jobs=n_startup)
    t = Trials()
    fmin(obj, SPACE, algo=algo, max_evals=max_evals, trials=t,
         max_queue_len=1, lookahead=1, rstate=np.random.default_rng(5),
         show_progressbar=False)
    assert len(t) == max_evals

    # replay the per-ask seed stream the loop drew
    rng = np.random.default_rng(5)
    seeds = [rng.integers(2**31 - 1) for _ in range(max_evals)]

    for i in range(n_startup, max_evals):
        # ask i was dispatched while trial i-1 was still pending: docs
        # 0..i-2 DONE, doc i-1 present but loss-less
        docs = [copy.deepcopy(t.trials[j]) for j in range(i)]
        docs[i - 1]["state"] = JOB_STATE_NEW
        docs[i - 1]["result"] = {"status": "new"}
        ref = Trials()
        ref.insert_trial_docs(docs)
        ref.refresh()
        ref_docs = tpe.suggest([i], Domain(obj, SPACE), ref, seeds[i],
                               n_startup_jobs=n_startup)
        for label in ("x", "lr"):
            assert ref_docs[0]["misc"]["vals"][label] == \
                t.trials[i]["misc"]["vals"][label], f"trial {i} / {label}"


def test_lookahead_converges_and_counts():
    t = Trials()
    fmin(obj, SPACE, algo=functools.partial(tpe.suggest, n_startup_jobs=8),
         max_evals=40, trials=t, lookahead=2, max_queue_len=2,
         rstate=np.random.default_rng(0), show_progressbar=False)
    assert len(t) == 40
    assert min(l for l in t.losses() if l is not None) < 0.5
    assert t.obs_metrics.counter("suggest.speculative").value > 0


def test_lookahead_validation():
    with pytest.raises(ValueError, match="lookahead"):
        fmin(obj, SPACE, algo=lambda ids, d, t, s: [], max_evals=4,
             lookahead=1, show_progressbar=False)
    with pytest.raises(ValueError, match="lookahead"):
        fmin(obj, SPACE, algo=tpe.suggest, max_evals=4, lookahead=-1,
             show_progressbar=False)
    # the device loop pipelines on device already: lookahead>0 makes a
    # device_loop=True run ineligible instead of being silently ignored
    from hyperopt_tpu.zoo import ZOO

    dom = ZOO["quadratic1"]
    with pytest.raises(ValueError, match="lookahead"):
        fmin(dom.objective, dom.space, algo=tpe.suggest, max_evals=10,
             lookahead=1, device_loop=True,
             rstate=np.random.default_rng(0), show_progressbar=False)


def test_rand_suggest_async_equals_sync():
    dom = Domain(obj, SPACE)
    t1, t2 = Trials(), Trials()
    docs_sync = rand.suggest([0, 1, 2], dom, t1, 99)
    handle = rand.suggest_async([0, 1, 2], Domain(obj, SPACE), t2, 99)
    docs_async = handle.result()
    assert handle.result() is docs_async  # idempotent
    for a, b in zip(docs_sync, docs_async):
        assert a["misc"]["vals"] == b["misc"]["vals"]


# ---------------------------------------------------------------------------
# buffer donation: in-place fold, stale-handle guard, pickle boundary
# ---------------------------------------------------------------------------


def _populated_trials(n=8):
    t = Trials()
    fmin(obj, SPACE, algo=rand.suggest, max_evals=n, trials=t,
         rstate=np.random.default_rng(0), show_progressbar=False)
    return t


def test_donation_folds_in_place():
    dom = Domain(obj, SPACE)
    t = _populated_trials()
    ph = t.history_object(dom.cs.labels)
    tpe.suggest(t.new_trial_ids(1), dom, t, 17, n_startup_jobs=5)
    old = ph._dev
    ptrs = {
        "losses": old["losses"].unsafe_buffer_pointer(),
        "x": old["vals"]["x"].unsafe_buffer_pointer(),
    }
    tpe.suggest(t.new_trial_ids(1), dom, t, 18, n_startup_jobs=5)
    # the previous handle was donated (consumed), and the committed mirror
    # reuses its buffers in place — no cap-sized copy materialized
    assert old["losses"].is_deleted()
    assert ph._dev["losses"].unsafe_buffer_pointer() == ptrs["losses"]
    assert ph._dev["vals"]["x"].unsafe_buffer_pointer() == ptrs["x"]
    assert not ph._donated  # committed, not pending


@pytest.mark.skipif(not os.environ.get("DONATION_GATE"),
                    reason="opt-in: DONATION_GATE=1 ./run_tests.sh")
def test_donation_gate_no_cap_sized_copy_per_tick():
    # the strict allocation gate: across many ticks, every history leaf
    # keeps its buffer pointer and the number of LIVE cap-sized f32 device
    # buffers does not grow — i.e. no tick allocates a cap-sized copy
    import jax.numpy as jnp

    dom = Domain(obj, SPACE)
    t = _populated_trials()
    ph = t.history_object(dom.cs.labels)
    tpe.suggest(t.new_trial_ids(1), dom, t, 1000, n_startup_jobs=5)
    ptrs = {l: ph._dev["vals"][l].unsafe_buffer_pointer()
            for l in dom.cs.labels}
    ptrs["losses"] = ph._dev["losses"].unsafe_buffer_pointer()

    def live_cap_f32():
        return sum(1 for a in jax.live_arrays()
                   if a.shape == (ph.cap,) and a.dtype == jnp.float32)

    n0 = live_cap_f32()
    for i in range(12):
        tpe.suggest(t.new_trial_ids(1), dom, t, 2000 + i, n_startup_jobs=5)
        assert ph._dev["losses"].unsafe_buffer_pointer() == ptrs["losses"]
        for l in dom.cs.labels:
            assert ph._dev["vals"][l].unsafe_buffer_pointer() == ptrs[l]
    assert live_cap_f32() <= n0


def test_stale_handle_guard():
    dom = Domain(obj, SPACE)
    t = _populated_trials()
    ph = t.history_object(dom.cs.labels)
    dev, rows = ph.device_state(donate=True)
    # the classic donated-buffer-reuse crash becomes a clear error
    with pytest.raises(StaleHistoryError, match="commit_device"):
        ph.device_state()
    with pytest.raises(StaleHistoryError, match="donated"):
        ph.device_view()
    # host materialization never depends on the (possibly invalid) mirror
    host = ph.host_materialize()
    assert len(host["losses"]) == ph.n
    ph.commit_device(dev)  # hand a handle back: guard clears
    ph.device_state()
    ph.abandon_device()
    assert ph._dev is None and not ph._donated


def test_pickle_midrun_with_donation_resumes_bitwise():
    # satellite regression: pickling Trials mid-run (device mirror live,
    # donation enabled) and resuming must reproduce the uninterrupted run
    algo = functools.partial(tpe.suggest, n_startup_jobs=6)

    def make_iter(trials, rng):
        return FMinIter(algo, Domain(obj, SPACE), trials, rstate=rng,
                        max_evals=20, show_progressbar=False)

    t_full = Trials()
    make_iter(t_full, np.random.default_rng(3)).run(20)

    rng = np.random.default_rng(3)
    t_a = Trials()
    make_iter(t_a, rng).run(12)
    labels = Domain(obj, SPACE).cs.labels
    assert t_a.history_object(labels)._dev is not None  # mirror live
    t_b = pickle.loads(pickle.dumps(t_a))
    assert t_b._history is None  # device state never traveled
    make_iter(t_b, rng).run(8)
    assert [d["misc"]["vals"] for d in t_b.trials] == \
        [d["misc"]["vals"] for d in t_full.trials]
    np.testing.assert_array_equal(t_b.losses(), t_full.losses())


def test_device_loop_chunk_donates_state():
    from hyperopt_tpu.device_fmin import DeviceLoopRunner
    from hyperopt_tpu.zoo import ZOO

    dom_z = ZOO["quadratic1"]
    runner = DeviceLoopRunner(Domain(dom_z.objective, dom_z.space),
                              {"prior_weight": 1.0, "n_EI_candidates": 24,
                               "gamma": 0.25, "LF": 25}, 5, 40)
    state = runner.init_state()
    old_losses = state[2]
    ptr = old_losses.unsafe_buffer_pointer()
    state2, rows = runner.run_chunk(state, 0, 10, 0)
    assert rows.shape[0] == 10
    assert old_losses.is_deleted()
    assert state2[2].unsafe_buffer_pointer() == ptr


# ---------------------------------------------------------------------------
# lean multihost payloads
# ---------------------------------------------------------------------------


def test_payload_roundtrip_and_fold_bitwise():
    from hyperopt_tpu.parallel import payload

    rng = np.random.default_rng(0)
    W, L = 16, 5
    losses = rng.normal(size=W).astype(np.float32)
    losses[3] = np.nan  # failed trial
    losses[7] = np.inf  # objective returned inf
    active = rng.random((W, L)) < 0.6
    evaluated = np.ones(W, bool)
    evaluated[-2:] = False  # padding rows

    for fmt in ("u8", "f32"):
        wire = payload.to_wire(losses, active, evaluated, fmt)
        assert wire.dtype == np.uint8
        assert wire.shape == (W, payload.row_nbytes(L, fmt))
        lo, ac, ev = payload.from_wire(wire, L, fmt)
        # bit-pattern exact, incl. the NaN
        assert lo.tobytes() == losses.tobytes()
        np.testing.assert_array_equal(ac, active)
        np.testing.assert_array_equal(ev, evaluated)

    # the lean rows are at least half the wide f32 rows
    assert payload.row_nbytes(L, "u8") * 2 <= payload.row_nbytes(L, "f32")

    # fold from either wire format is byte-identical
    labels = tuple(f"p{i}" for i in range(L))
    flats = {l: rng.uniform(-1, 1, W).astype(np.float32) for l in labels}

    def fold_via(fmt):
        cap = 32
        hist = {"losses": np.full(cap, np.inf, np.float32),
                "has_loss": np.zeros(cap, bool),
                "vals": {l: np.zeros(cap, np.float32) for l in labels},
                "active": {l: np.zeros(cap, bool) for l in labels}}
        raw = np.full(cap, np.nan, np.float32)
        lo, ac, ev = payload.from_wire(
            payload.to_wire(losses, active, evaluated, fmt), L, fmt)
        k = int(ev.sum())
        payload.fold_generation(hist, raw, 0, labels,
                                {l: flats[l][:k] for l in labels},
                                lo[:k], ac[:k])
        return (hist["losses"].tobytes(), hist["has_loss"].tobytes(),
                raw.tobytes(),
                b"".join(hist["vals"][l].tobytes() for l in labels),
                b"".join(hist["active"][l].tobytes() for l in labels))

    assert fold_via("u8") == fold_via("f32")


def test_payload_wire_format_env(monkeypatch):
    from hyperopt_tpu.parallel import payload

    assert payload.wire_format({}) == "u8"
    assert payload.wire_format({"HYPEROPT_TPU_PAYLOAD": "f32"}) == "f32"
    with pytest.raises(ValueError):
        payload.wire_format({"HYPEROPT_TPU_PAYLOAD": "zstd"})


def test_multihost_single_fold_checksum_golden():
    # the payload + device-mirror rework must keep the driver's fold (and
    # its divergence digest) byte-identical to the pre-PR driver
    from hyperopt_tpu.parallel.driver import fmin_multihost

    res = fmin_multihost(mh_obj, MH_SPACE, max_evals=24, batch=4, seed=7,
                         n_startup=8, _force_single=True)
    assert res.checksum == GOLD_MH_CHECKSUM
    assert float(res.best_loss).hex() == GOLD_MH_BEST


# ---------------------------------------------------------------------------
# persistent compilation cache
# ---------------------------------------------------------------------------


def test_compile_cache_env_and_kwarg(tmp_path, monkeypatch):
    import hyperopt_tpu._env as _env

    old_flag = _env._CACHE_CONFIGURED
    old_explicit = _env._EXPLICIT_DIR
    old_dir = getattr(jax.config, "jax_compilation_cache_dir", None)
    old_min = getattr(jax.config,
                      "jax_persistent_cache_min_compile_time_secs", 1.0)
    try:
        target = tmp_path / "cc"
        monkeypatch.setenv("HYPEROPT_TPU_COMPILE_CACHE", str(target))
        _env.enable_persistent_compilation_cache()
        assert jax.config.jax_compilation_cache_dir == str(target)
        assert os.path.isdir(target)
        assert jax.config.jax_persistent_cache_min_compile_time_secs == 0.0

        # an explicit dir argument (fmin's compile_cache=) wins over a
        # prior configuration
        target2 = tmp_path / "cc2"
        monkeypatch.delenv("HYPEROPT_TPU_COMPILE_CACHE")
        _env.enable_persistent_compilation_cache(str(target2))
        assert jax.config.jax_compilation_cache_dir == str(target2)

        # opt-out beats everything
        monkeypatch.setenv("HYPEROPT_TPU_NO_CACHE", "1")
        _env.enable_persistent_compilation_cache(str(tmp_path / "cc3"))
        assert jax.config.jax_compilation_cache_dir == str(target2)
    finally:
        _env._CACHE_CONFIGURED = old_flag
        _env._EXPLICIT_DIR = old_explicit
        jax.config.update("jax_compilation_cache_dir", old_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          old_min)


# ---------------------------------------------------------------------------
# obs: dispatch/readback split + inflight gauge
# ---------------------------------------------------------------------------


def test_obs_dispatch_readback_spans_and_inflight_gauge(tmp_path):
    import json

    stream = tmp_path / "run.jsonl"
    t = Trials()
    fmin(obj, SPACE, algo=functools.partial(tpe.suggest, n_startup_jobs=6),
         max_evals=12, trials=t, lookahead=1, obs=str(stream),
         rstate=np.random.default_rng(0), show_progressbar=False)
    names = set()
    metrics = {}
    with open(stream) as f:
        for line in f:
            rec = json.loads(line)
            if rec.get("kind") == "span":
                names.add(rec.get("name"))
            if rec.get("kind") == "metrics":
                metrics = rec["snapshot"]["metrics"]
    assert "suggest.dispatch" in names
    assert "suggest.readback" in names
    assert "suggest.inflight" in metrics
    assert metrics["suggest.speculative"] > 0
    assert metrics["ask.blocked_sec"]["count"] == 12
    # aggregate view mirrors the split, and phase counts stay ONE per ask
    # in pipelined mode (speculative dispatches are not double-counted
    # under "suggest")
    assert t.phase_timings["suggest"]["count"] == 12
    assert t.phase_timings["suggest.dispatch"]["count"] == 12
    assert t.phase_timings["suggest.readback"]["count"] == 12
