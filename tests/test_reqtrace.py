"""ISSUE 11: request-scoped trace context — wire-format parsing, hostile
header fuzzing (degrade-to-fresh-trace, 400-never-500), client trace
continuity across retries, response echo, and the disarmed pins (zero
new threads; armed tracing never changes proposals)."""

import json
import threading

import pytest

from hyperopt_tpu import hp
from hyperopt_tpu._env import parse_reqtrace, parse_service_access_log
from hyperopt_tpu.obs import reqtrace
from hyperopt_tpu.service.client import ServiceClient
from hyperopt_tpu.service.scheduler import StudyScheduler
from hyperopt_tpu.service.server import ServiceHTTPServer

SPACE_SPEC = {"x": {"dist": "uniform", "args": [-5, 5]}}
VALID_TP = "00-4bf92f3577b34da6a3ce929d0e0e4736-00f067aa0ba902b7-01"


def _server(**kw):
    kw.setdefault("slo", False)  # slo plane has its own suite
    return ServiceHTTPServer(0, scheduler=StudyScheduler(wal=False), **kw)


# ---------------------------------------------------------------------------
# wire format
# ---------------------------------------------------------------------------


def test_parse_valid_traceparent():
    ctx = reqtrace.parse(VALID_TP)
    assert ctx is not None
    assert ctx.trace_id == "4bf92f3577b34da6a3ce929d0e0e4736"
    assert ctx.span_id == "00f067aa0ba902b7"
    assert ctx.traceparent().startswith("00-4bf92f3577b34da6a3ce929d0e0e4736-")


def test_mint_and_child_shapes():
    root = reqtrace.mint()
    assert len(root.trace_id) == 32 and len(root.span_id) == 16
    assert reqtrace.parse(root.traceparent()) is not None
    kid = reqtrace.child(root)
    assert kid.trace_id == root.trace_id
    assert kid.span_id != root.span_id
    assert kid.parent_id == root.span_id
    # two mints never collide (the ids ARE the correlation key)
    assert reqtrace.mint().trace_id != root.trace_id


def test_contextvar_use_and_restore():
    assert reqtrace.current() is None
    ctx = reqtrace.mint()
    with reqtrace.use(ctx):
        assert reqtrace.current() is ctx
        assert reqtrace.current_trace_id() == ctx.trace_id
        with reqtrace.use(reqtrace.child(ctx)) as inner:
            assert reqtrace.current() is inner
        assert reqtrace.current() is ctx
    assert reqtrace.current() is None
    with reqtrace.use(None):  # None is a no-op, not an error
        assert reqtrace.current() is None


#: the hostile traceparent corpus: every entry must parse to None
HOSTILE_TRACEPARENTS = [
    "",  # empty
    "00",  # truncated
    "00-4bf92f3577b34da6a3ce929d0e0e4736",  # missing span/flags
    "00-4bf92f3577b34da6a3ce929d0e0e4736-00f067aa0ba902b7",  # no flags
    "ff-4bf92f3577b34da6a3ce929d0e0e4736-00f067aa0ba902b7-01",  # ver ff
    "0-4bf92f3577b34da6a3ce929d0e0e4736-00f067aa0ba902b7-01",  # 1-char ver
    "zz-4bf92f3577b34da6a3ce929d0e0e4736-00f067aa0ba902b7-01",  # non-hex
    "00-4bf92f3577b34da6a3ce929d0e0e47-00f067aa0ba902b7-01",  # short trace
    "00-4bf92f3577b34da6a3ce929d0e0e4736ab-00f067aa0ba902b7-01",  # long
    "00-4BF92F3577B34DA6A3CE929D0E0E4736-00f067aa0ba902b7-01",  # UPPERCASE
    "00-4bf92f3577b34da6a3ce929d0e0e4736-00f067aa0ba902-01",  # short span
    "00-4bf92f3577b34da6a3ce929d0e0e4736-00f067aa0ba902b7zz-01",  # bad span
    "00-" + "0" * 32 + "-00f067aa0ba902b7-01",  # all-zero trace id
    "00-4bf92f3577b34da6a3ce929d0e0e4736-" + "0" * 16 + "-01",  # zero span
    "00-4bf92f3577b34da6a3ce929d0e0e4736-00f067aa0ba902b7-1",  # short flags
    "00-4bf92f3577b34da6a3ce929d0e0e4736-00f067aa0ba902b7-01-extra",  # v00+
    "00-4bf92f3577b34da6a3ce929d0e0e4736-00f067aa0ba902b7-01\n",  # ctl byte
    "00-4bf92f3577b34da6a3ce929d0e0e4736-00f067aa0ba902b7-01\x00",  # NUL
    "\x1b[2J" + VALID_TP,  # ANSI escape prefix
    "00-4bf92f3577b34da6a3ce929d0e0e4736-00f067aa0ba902b7-01" + "a" * 500,
    "a" * 10_000,  # oversized
    "тест-4bf92f3577b34da6a3ce929d0e0e4736-00f067aa0ba902b7-01",  # non-ascii
]


@pytest.mark.parametrize("header", HOSTILE_TRACEPARENTS,
                         ids=range(len(HOSTILE_TRACEPARENTS)))
def test_hostile_traceparent_parses_to_none(header):
    assert reqtrace.parse(header) is None


def test_parse_non_string_inputs():
    for bad in (None, 7, b"00-aa-bb-01", ["00"], {"tp": 1}):
        assert reqtrace.parse(bad) is None


def test_forward_compat_version():
    # a future version with a trailing field still parses (the W3C
    # forward-compat rule); version 00 with extra fields does not
    ctx = reqtrace.parse(VALID_TP.replace("00-", "01-", 1) + "-future")
    assert ctx is not None and ctx.trace_id == VALID_TP.split("-")[1]


def test_sanitize_request_id():
    assert reqtrace.sanitize_request_id("req-1_2.3:ok") == "req-1_2.3:ok"
    assert reqtrace.sanitize_request_id("") is None
    assert reqtrace.sanitize_request_id("x" * 1000) is None
    assert reqtrace.sanitize_request_id("evil\nheader") is None
    assert reqtrace.sanitize_request_id("\x00\x01") is None
    assert reqtrace.sanitize_request_id(42) is None


# ---------------------------------------------------------------------------
# server-side: degrade to fresh, echo, 400-never-500
# ---------------------------------------------------------------------------


def _mk_study(srv, **kw):
    body = {"space": SPACE_SPEC, "seed": 7, "n_startup_jobs": 2}
    body.update(kw)
    code, r = srv.handle("POST", "/study", body)
    assert code == 200, r
    return r["study_id"]


def test_valid_traceparent_continues_the_trace():
    srv = _server()
    sid = _mk_study(srv)
    code, r = srv.handle("POST", "/ask", {"study_id": sid},
                         headers={"traceparent": VALID_TP})
    assert code == 200
    assert r["trace"] == "4bf92f3577b34da6a3ce929d0e0e4736"


@pytest.mark.parametrize("header", HOSTILE_TRACEPARENTS,
                         ids=range(len(HOSTILE_TRACEPARENTS)))
def test_hostile_header_degrades_to_fresh_trace_never_5xx(header):
    srv = _server()
    sid = _mk_study(srv)
    code, r = srv.handle("POST", "/ask", {"study_id": sid},
                         headers={"traceparent": header})
    # the request itself is FINE: it must be served (200), with a FRESH
    # trace (never the hostile value), and never a 5xx
    assert code == 200, (header, r)
    assert isinstance(r.get("trace"), str) and len(r["trace"]) == 32
    assert r["trace"] != header
    assert all(c in "0123456789abcdef" for c in r["trace"])


def test_hostile_header_on_bad_request_answers_4xx_never_500():
    srv = _server()
    for header in HOSTILE_TRACEPARENTS[:8]:
        # a malformed BODY under a hostile header: still the typed 4xx
        code, r = srv.handle("POST", "/ask", {},
                             headers={"traceparent": header,
                                      "x-request-id": "bad\x00id"})
        assert code == 400, (header, code, r)
        assert "trace" in r  # errors carry the correlation id too


def test_trace_echoed_on_404_and_quota_429():
    srv = ServiceHTTPServer(
        0, scheduler=StudyScheduler(max_studies=1, wal=False), slo=False)
    _mk_study(srv)
    code, r = srv.handle("POST", "/ask", {"study_id": "study-nope"},
                         headers={"traceparent": VALID_TP})
    assert code == 404
    assert r["trace"] == "4bf92f3577b34da6a3ce929d0e0e4736"
    code, r = srv.handle("POST", "/study", {"space": SPACE_SPEC},
                         headers={"traceparent": VALID_TP})
    assert code == 429  # quota
    assert r["trace"] == "4bf92f3577b34da6a3ce929d0e0e4736"


def test_request_id_echoed_when_sane_dropped_when_hostile():
    srv = _server()
    sid = _mk_study(srv)
    code, r = srv.handle("POST", "/ask", {"study_id": sid},
                         headers={"x-request-id": "req-42"})
    assert code == 200 and r["request_id"] == "req-42"
    code, r = srv.handle("POST", "/ask", {"study_id": sid},
                         headers={"x-request-id": "evil\x00" + "x" * 500})
    assert code == 200 and "request_id" not in r


def test_client_trace_state_is_per_thread(monkeypatch):
    """A shared client serving concurrent requests must not cross-
    attribute traces between threads (the attempt header and
    last_trace/last_spans are thread-local)."""
    import threading as _threading

    seen = {}
    barrier = _threading.Barrier(2)

    def fake_once(self, method, path, body):
        barrier.wait(timeout=10)  # both threads mid-attempt together
        tp = (self._attempt_headers or {}).get("traceparent")
        seen[_threading.current_thread().name] = reqtrace.parse(tp)
        barrier.wait(timeout=10)
        return 200, {"ok": True}, None

    monkeypatch.setattr(ServiceClient, "_once", fake_once)
    c = ServiceClient("http://127.0.0.1:1", trace=True)
    results = {}

    def drive():
        c.request("POST", "/ask", {})
        results[_threading.current_thread().name] = (c.last_trace,
                                                     list(c.last_spans))

    threads = [_threading.Thread(target=drive, name=f"t{i}")
               for i in range(2)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert len(seen) == 2
    # each thread saw ITS OWN trace on the wire and in last_trace
    for name in ("t0", "t1"):
        assert seen[name].trace_id == results[name][0]
        assert [seen[name].span_id] == results[name][1]
    assert results["t0"][0] != results["t1"][0]


def test_report_study_rejects_format_json(capsys):
    from hyperopt_tpu.obs import report

    assert report.main(["--format", "json", "--study", "s1",
                        "/tmp/nope"]) == 2
    assert "renders text only" in capsys.readouterr().err


def test_timeline_endpoint_routes_and_404s():
    srv = _server()
    sid = _mk_study(srv)
    code, r = srv.handle("POST", "/ask", {"study_id": sid})
    assert code == 200
    code, tl = srv.handle("GET", f"/study/{sid}/timeline", {})
    assert code == 200
    assert tl["study_id"] == sid
    events = [e["event"] for e in tl["events"]]
    assert "admit" in events and "ask" in events
    ask = next(e for e in tl["events"] if e["event"] == "ask")
    assert ask["tids"] == [0]
    code, _ = srv.handle("GET", "/study/nope/timeline", {})
    assert code == 404
    assert srv.handle("GET", "/study//timeline", {})[0] == 404
    assert srv.handle("GET", "/study/a/b/timeline", {})[0] == 404


# ---------------------------------------------------------------------------
# client-side: one trace across retries, fresh span per attempt
# ---------------------------------------------------------------------------


def test_client_one_trace_across_retries(monkeypatch):
    sent = []

    def fake_once(self, method, path, body):
        sent.append((self._attempt_headers or {}).get("traceparent"))
        if len(sent) < 3:
            return 429, {"ok": False, "retry_after": 0.0}, "1"
        return 200, {"ok": True, "trace": "ignored"}, None

    monkeypatch.setattr(ServiceClient, "_once", fake_once)
    c = ServiceClient("http://127.0.0.1:1", retry=5, sleep=lambda s: None,
                      trace=True)
    status, payload = c.request("POST", "/ask", {"study_id": "s"})
    assert status == 200
    assert len(sent) == 3 and all(tp for tp in sent)
    parsed = [reqtrace.parse(tp) for tp in sent]
    # ONE trace id across every attempt, a FRESH span id per attempt
    assert len({p.trace_id for p in parsed}) == 1
    assert len({p.span_id for p in parsed}) == 3
    assert c.last_trace == parsed[0].trace_id
    assert c.last_spans == [p.span_id for p in parsed]


def test_client_disarmed_sends_no_header(monkeypatch):
    sent = []

    def fake_once(self, method, path, body):
        sent.append(self._attempt_headers)
        return 200, {"ok": True}, None

    monkeypatch.setattr(ServiceClient, "_once", fake_once)
    c = ServiceClient("http://127.0.0.1:1", trace=False)
    c.request("GET", "/studies")
    assert sent == [None]
    assert c.last_trace is None


# ---------------------------------------------------------------------------
# env knobs
# ---------------------------------------------------------------------------


def test_parse_reqtrace_grammar():
    assert parse_reqtrace({}) is True  # default ON
    assert parse_reqtrace({"HYPEROPT_TPU_REQTRACE": "1"}) is True
    for off in ("0", "off", "false", "no"):
        assert parse_reqtrace({"HYPEROPT_TPU_REQTRACE": off}) is False


def test_parse_access_log_grammar(tmp_path):
    assert parse_service_access_log({}) is None
    assert parse_service_access_log(
        {"HYPEROPT_TPU_SERVICE_ACCESS_LOG": "off"}) is None
    p = str(tmp_path / "access.jsonl")
    assert parse_service_access_log(
        {"HYPEROPT_TPU_SERVICE_ACCESS_LOG": p}) == p


# ---------------------------------------------------------------------------
# disarmed pins: no new threads, proposals bit-identical armed vs not
# ---------------------------------------------------------------------------


def test_disarmed_server_starts_no_new_threads():
    before = {th.ident for th in threading.enumerate()}
    srv = ServiceHTTPServer(0, scheduler=StudyScheduler(wal=False),
                            trace=False, slo=False, access_log=None)
    sid = _mk_study(srv)
    code, r = srv.handle("POST", "/ask", {"study_id": sid})
    assert code == 200
    assert "trace" not in r  # the pre-PR payload shape
    after = {th.ident for th in threading.enumerate()}
    assert after == before


def test_armed_tracing_never_changes_proposals():
    """The determinism pin: trace ids are metadata — the proposal
    stream with tracing (and hostile headers!) is bit-identical to the
    disarmed stream at the same seed."""
    def drive(trace_armed, headers):
        srv = ServiceHTTPServer(
            0, scheduler=StudyScheduler(wal=False), trace=trace_armed,
            slo=False)
        sid = _mk_study(srv, seed=123)
        out = []
        for i in range(6):
            code, r = srv.handle("POST", "/ask", {"study_id": sid},
                                 headers=headers)
            assert code == 200
            t = r["trials"][0]
            out.append((t["tid"], repr(t["params"]["x"])))
            code, _ = srv.handle("POST", "/tell", {
                "study_id": sid, "tid": t["tid"], "loss": float(i % 3)})
            assert code == 200
        return out

    disarmed = drive(False, None)
    armed = drive(True, {"traceparent": VALID_TP})
    hostile = drive(True, {"traceparent": HOSTILE_TRACEPARENTS[4]})
    assert disarmed == armed == hostile


def test_access_log_works_with_tracing_disarmed(tmp_path):
    """The knobs are independent: REQTRACE=off must not silence an
    armed access log — records land with ``trace: null``."""
    srv = ServiceHTTPServer(
        0, scheduler=StudyScheduler(wal=False), trace=False, slo=False,
        access_log=str(tmp_path / "a.jsonl"))
    sid = _mk_study(srv)
    code, r = srv.handle("POST", "/ask", {"study_id": sid})
    assert code == 200 and "trace" not in r
    recs = [json.loads(ln) for ln in
            (tmp_path / "a.jsonl").read_text().splitlines()]
    assert [r["path"] for r in recs] == ["/study", "/ask"]
    assert all(r.get("trace") is None for r in recs)


def test_slo_and_access_log_armed_still_zero_threads(tmp_path):
    before = {th.ident for th in threading.enumerate()}
    srv = ServiceHTTPServer(
        0, scheduler=StudyScheduler(wal=False), trace=True, slo=True,
        access_log=str(tmp_path / "access.jsonl"))
    sid = _mk_study(srv)
    srv.handle("POST", "/ask", {"study_id": sid})
    assert {th.ident for th in threading.enumerate()} == before
    # the access log wrote one JSONL record per request, trace included
    recs = [json.loads(ln) for ln in
            (tmp_path / "access.jsonl").read_text().splitlines()]
    assert [r["path"] for r in recs] == ["/study", "/ask"]
    assert all(r["kind"] == "access" and len(r["trace"]) == 32
               and "latency_ms" in r and "status" in r for r in recs)
    assert recs[1]["study_id"] == sid
