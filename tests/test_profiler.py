"""Device-profiling plane (hyperopt_tpu/obs/profiler.py) + the merged
host/device Perfetto export (obs/export.py) + kernel attribution
(health.roofline_table).

All tier-1 (CPU, fast).  The load-bearing invariants pinned here:

* the DISARMED hot path is untouched — no profile env/kwarg means no new
  threads, a shared null annotation context, and TPE proposals
  bit-identical to an armed run's;
* every capture is BOUNDED (``max_capture_sec`` clamps a typo'd
  duration) and EXCLUSIVE (a concurrent request reports busy, never
  raises into the run);
* the watchdog stall escalation takes exactly ONE bounded capture per
  run — a six-hour hang produces one device trace, not 72;
* ``/profile`` fails OPEN: disarmed plane, bad duration, busy session
  and unsupported backends all answer structured JSON, never a 500 from
  a raised exception;
* a capture artifact merges into the host-span export in the reserved
  device pid range, every track group named, timestamps wall-aligned —
  and the merged artifact passes scripts/validate_trace.py's lint.
"""

import gzip
import json
import os
import sys
import threading
import urllib.request

import numpy as np
import pytest

from hyperopt_tpu import Trials, fmin, hp
from hyperopt_tpu.algos import tpe
from hyperopt_tpu.obs import ObsConfig, RunObs
from hyperopt_tpu.obs.export import (DEVICE_PID_BASE, device_trace_events,
                                     export_trace)
from hyperopt_tpu.obs.flight import FlightRecorder
from hyperopt_tpu.obs.health import roofline_table
from hyperopt_tpu.obs.profiler import (DeviceProfiler, annotation_ctx,
                                       find_capture_artifact,
                                       split_profile_mode)
from hyperopt_tpu.obs.report import main as report_main, render
from hyperopt_tpu.obs.watchdog import Watchdog

sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "scripts"))
import validate_trace  # noqa: E402  (scripts/validate_trace.py)

SPACE = {"x": hp.uniform("x", -5, 5), "y": hp.uniform("y", 0, 3)}


def quad(d):
    return (d["x"] - 1.0) ** 2 + d["y"]


# ---------------------------------------------------------------------------
# env/kwarg grammar
# ---------------------------------------------------------------------------


def test_split_profile_mode_grammar():
    assert split_profile_mode("") == (None, None)
    assert split_profile_mode(None) == (None, None)
    assert split_profile_mode("  ") == (None, None)
    assert split_profile_mode("/tmp/caps") == ("/tmp/caps", None)
    assert split_profile_mode("full:/tmp/trace") == (None, "/tmp/trace")
    assert split_profile_mode("full:") == (None, None)


def test_obsconfig_from_env_routes_profile_modes(monkeypatch):
    monkeypatch.setenv("HYPEROPT_TPU_PROFILE", "/tmp/capdir")
    cfg = ObsConfig.from_env()
    assert cfg.profile_dir == "/tmp/capdir" and cfg.profile_full is None
    monkeypatch.setenv("HYPEROPT_TPU_PROFILE", "full:/tmp/whole")
    cfg = ObsConfig.from_env()
    assert cfg.profile_dir is None and cfg.profile_full == "/tmp/whole"


# ---------------------------------------------------------------------------
# bounded, exclusive, fail-open captures
# ---------------------------------------------------------------------------


class _FakeSleep:
    def __init__(self):
        self.calls = []

    def __call__(self, sec):
        self.calls.append(sec)


def _stubbed_profiler(tmp_path, monkeypatch, **kw):
    """A DeviceProfiler whose jax.profiler session is a no-op and whose
    capture sleep is recorded, not waited."""
    import jax.profiler as jp

    monkeypatch.setattr(jp, "start_trace", lambda d: None)
    monkeypatch.setattr(jp, "stop_trace", lambda: None)
    sleep = _FakeSleep()
    prof = DeviceProfiler(str(tmp_path / "caps"), clock=sleep, **kw)
    return prof, sleep


def test_capture_clamps_to_max_duration(tmp_path, monkeypatch):
    prof, sleep = _stubbed_profiler(tmp_path, monkeypatch,
                                    max_capture_sec=30.0)
    rec = prof.capture(3600, reason="ondemand")  # a typo'd hour
    assert rec["ok"] and rec["sec"] == 30.0
    assert sleep.calls == [30.0]
    assert rec["reason"] == "ondemand"
    assert prof.capture_count == 1


def test_capture_rejects_bad_durations(tmp_path, monkeypatch):
    prof, sleep = _stubbed_profiler(tmp_path, monkeypatch)
    for bad in ("abc", None, 0, -1):
        rec = prof.capture(bad)
        assert not rec["ok"] and "error" in rec
    assert sleep.calls == []  # nothing ever captured
    assert prof.capture_count == 0


def test_concurrent_capture_reports_busy(tmp_path, monkeypatch):
    prof, _ = _stubbed_profiler(tmp_path, monkeypatch)
    with prof._lock:  # a capture is in flight on another thread
        rec = prof.capture(1)
    assert not rec["ok"] and "in progress" in rec["error"]


def test_unsupported_backend_fails_open_and_warns_once(
        tmp_path, monkeypatch, caplog):
    import logging

    import jax.profiler as jp

    def boom(d):
        raise RuntimeError("profiler not supported on this backend")

    monkeypatch.setattr(jp, "start_trace", boom)
    prof = DeviceProfiler(str(tmp_path / "caps"), clock=_FakeSleep())
    with caplog.at_level(logging.WARNING,
                         logger="hyperopt_tpu.obs.profiler"):
        r1 = prof.capture(1)
        r2 = prof.capture(1)
    assert not r1["ok"] and "RuntimeError" in r1["error"]
    assert not r2["ok"]
    warnings = [r for r in caplog.records
                if "capture unavailable" in r.getMessage()]
    assert len(warnings) == 1  # once-logged, not per capture


def test_real_cpu_capture_roundtrip(tmp_path):
    """One REAL (tiny) jax.profiler capture on the CPU backend: artifact
    located, record ok, wall time bounded."""
    import jax
    import jax.numpy as jnp

    prof = DeviceProfiler(str(tmp_path / "caps"), max_capture_sec=2.0)

    done = threading.Event()

    def work():
        # give the capture something to record
        while not done.is_set():
            jax.block_until_ready(jnp.ones((64, 64)) @ jnp.ones((64, 64)))

    worker = threading.Thread(target=work, daemon=True)
    worker.start()
    try:
        rec = prof.capture(0.3, reason="test")
    finally:
        done.set()
        worker.join()
    assert rec["ok"], rec.get("error")
    # the requested duration is clamped (the wall clock additionally pays
    # one-time profiler init/convert overhead, which is unbounded-ish on a
    # cold CPU backend — the SLEEP bound is pinned by the fake-clock tests)
    assert rec["sec"] == 0.3
    assert rec["trace_json"] and os.path.exists(rec["trace_json"])
    assert find_capture_artifact(rec["dir"]) == rec["trace_json"]
    assert prof.captures == [rec]


# ---------------------------------------------------------------------------
# stall escalation: ONE bounded capture per run (fake-clock watchdog)
# ---------------------------------------------------------------------------


def test_profile_on_stall_once_per_run(tmp_path, monkeypatch):
    class _Clock:
        t = 0.0

        def __call__(self):
            return self.t

    clock = _Clock()
    wd = Watchdog(quiet_sec=300.0, clock=clock, flight=FlightRecorder())
    wd.retain()
    prof, sleep = _stubbed_profiler(tmp_path, monkeypatch,
                                    stall_capture_sec=5.0)
    wd.add_escalation(prof.capture_on_stall)
    wd.beat("fmin.tick", n=1)

    clock.t = 301.0  # first quiet period elapses: stall + ONE capture
    assert wd.check() is not None
    assert prof.capture_count == 1
    assert sleep.calls == [5.0]  # the bounded stall duration

    clock.t = 700.0  # a SECOND stall: no second capture (once per run)
    assert wd.check() is not None
    assert prof.capture_count == 1
    assert sleep.calls == [5.0]
    assert prof.captures and prof.captures[0]["reason"] == "stall"


def test_stall_capture_retries_after_foreign_session_conflict(tmp_path,
                                                              monkeypatch):
    """Our lock only covers this DeviceProfiler; jax's one-session limit
    is process-wide.  A foreign session (another run's profiler, a user's
    own jax.profiler.trace) makes start_trace raise 'already active' —
    that must report BUSY (retryable, budget kept), not latch the
    once-per-run stall budget the way a truly unsupported backend does."""
    import jax.profiler as jp

    def foreign_conflict(d):
        raise RuntimeError("Another profiler session is already active.")

    monkeypatch.setattr(jp, "start_trace", foreign_conflict)
    monkeypatch.setattr(jp, "stop_trace", lambda: None)
    sleep = _FakeSleep()
    prof = DeviceProfiler(str(tmp_path / "caps"), clock=sleep)
    rec = prof.capture_on_stall()
    assert not rec["ok"] and rec.get("busy")
    assert not prof._stall_captured  # budget NOT consumed
    monkeypatch.setattr(jp, "start_trace", lambda d: None)  # session ended
    rec = prof.capture_on_stall()
    assert rec["ok"] and prof._stall_captured  # the hang still gets a trace


def test_stall_capture_referenced_from_postmortem(tmp_path, monkeypatch):
    """The whole point of the escalation: a hang's flight dump points at
    the device trace.  The capture record lands in the process-global
    flight ring, so a dump written after the stall carries it — and the
    postmortem renderer surfaces it."""
    from hyperopt_tpu.obs.flight import get_flight
    from hyperopt_tpu.obs.report import render_postmortem

    prof, _ = _stubbed_profiler(tmp_path, monkeypatch)
    fr = get_flight()
    was = fr.enabled
    fr.enabled = True
    try:
        rec = prof.capture_on_stall()
    finally:
        fr.enabled = was
    assert rec["ok"] and rec["reason"] == "stall"
    # the ring carries the capture record (tail of a subsequent dump)
    tail = [r for r in fr.records() if r.get("kind") == "profile"]
    assert tail and tail[-1]["dir"] == rec["dir"]
    # and the postmortem renderer points at the artifact
    dump = [
        {"kind": "flight_dump", "reason": "SIGTERM",
         "ts": rec["ts"] + 10.0},
        dict(tail[-1]),
    ]
    text = render_postmortem(dump, name="run.flight.jsonl")
    assert "device captures" in text
    assert "stall" in text and rec["dir"] in text


def test_watchdog_escalation_failure_never_kills_detector():
    class _Clock:
        t = 0.0

        def __call__(self):
            return self.t

    clock = _Clock()
    wd = Watchdog(quiet_sec=10.0, clock=clock, flight=FlightRecorder())
    wd.retain()

    def bad_escalation(rec):
        raise RuntimeError("escalation exploded")

    wd.add_escalation(bad_escalation)
    wd.beat("fmin.tick")
    clock.t = 11.0
    assert wd.check() is not None  # the stall still reports
    wd.remove_escalation(bad_escalation)
    clock.t = 22.0
    assert wd.check() is not None


# ---------------------------------------------------------------------------
# /profile endpoint: fail-open contract
# ---------------------------------------------------------------------------


def _get_json(url, timeout=15):
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return json.loads(r.read().decode())


def test_profile_endpoint_not_armed_fails_open():
    obs = RunObs(ObsConfig(level="basic", http_port=0), run_id="prof-off")
    try:
        assert obs.profiler is None
        body = _get_json(obs.http.url + "/profile?sec=1")
        assert body["ok"] is False
        assert "not armed" in body["error"]
    finally:
        obs.finish()


def test_profile_endpoint_bounded_capture_and_bad_params(
        tmp_path, monkeypatch):
    obs = RunObs(ObsConfig(level="basic", http_port=0,
                           profile_dir=str(tmp_path / "caps")),
                 run_id="prof-on")
    try:
        assert obs.profiler is not None
        # stub the session so the endpoint answers instantly
        import jax.profiler as jp

        monkeypatch.setattr(jp, "start_trace", lambda d: None)
        monkeypatch.setattr(jp, "stop_trace", lambda: None)
        sleep = _FakeSleep()
        obs.profiler._sleep = sleep
        obs.profiler.max_capture_sec = 2.0

        body = _get_json(obs.http.url + "/profile?sec=999")
        assert body["ok"] is True
        assert body["sec"] == 2.0 and sleep.calls == [2.0]  # clamped
        assert body["reason"] == "http"

        body = _get_json(obs.http.url + "/profile?sec=abc")
        assert body["ok"] is False and "bad capture duration" in body["error"]
    finally:
        obs.finish()


# ---------------------------------------------------------------------------
# disarmed hot path untouched (the standing invariant, extended)
# ---------------------------------------------------------------------------


def _tpe_run(seed=11, max_evals=10, **kw):
    t = Trials()
    fmin(quad, SPACE, algo=tpe.suggest, max_evals=max_evals, trials=t,
         rstate=np.random.default_rng(seed), show_progressbar=False, **kw)
    return t


def test_disarmed_no_new_threads_and_armed_proposals_bit_identical(
        tmp_path):
    t_plain = _tpe_run()
    before = {th.name for th in threading.enumerate()}
    t_again = _tpe_run()
    after = {th.name for th in threading.enumerate()}
    assert before == after  # a disarmed run starts ZERO new threads
    # an ARMED capture plane (annotations live on every tick, no capture
    # triggered) proposes bit-identically to the disarmed loop
    t_armed = _tpe_run(profile=str(tmp_path / "caps"))
    assert t_plain.losses() == t_again.losses() == t_armed.losses()
    for a, b in zip(t_plain.trials, t_armed.trials):
        assert a["misc"]["vals"] == b["misc"]["vals"]


def test_disarmed_annotation_is_shared_null_context():
    obs = RunObs(ObsConfig(level="basic"), run_id="ann-off")
    try:
        assert obs.profiler is None
        # one shared object per call path — no per-tick construction on
        # the disarmed hot loop
        assert obs.annotate("fmin.tick", step=1) is obs.annotate("x")
        assert annotation_ctx(None, "fmin.tick") is annotation_ctx(None, "y")
    finally:
        obs.finish()


def test_armed_annotations_usable_without_active_session(tmp_path):
    obs = RunObs(ObsConfig(level="basic",
                           profile_dir=str(tmp_path / "caps")),
                 run_id="ann-on")
    try:
        with obs.annotate("fmin.tick", step=3, tid=7, n=1):
            pass  # TraceAnnotation no-ops while no session records
        with obs.annotate("device.chunk", start=0, limit=8):
            pass
    finally:
        obs.finish()


# ---------------------------------------------------------------------------
# export: device capture merge + validate_trace lint
# ---------------------------------------------------------------------------


def _fake_capture_json(tmp_path, gz=True):
    data = {"traceEvents": [
        {"ph": "M", "pid": 7, "tid": 0, "name": "process_name",
         "args": {"name": "/device:TPU:0"}},
        {"ph": "X", "pid": 7, "tid": 1, "ts": 10.0, "dur": 5.0,
         "name": "fused_ei_kernel"},
        {"ph": "X", "pid": 7, "tid": 1, "ts": 20.0,
         "name": "fmin.tick#step=3,tid=7#"},  # TraceMe-encoded ids
        {"ph": "X", "pid": 9, "tid": 1, "ts": 12.0, "name": "nodur"},
        {"ph": "B", "pid": 7, "tid": 1, "ts": 1.0, "name": "dropped"},
    ]}
    if gz:
        path = tmp_path / "cap.trace.json.gz"
        with gzip.open(path, "wt") as f:
            json.dump(data, f)
    else:
        path = tmp_path / "cap.trace.json"
        path.write_text(json.dumps(data))
    return str(path)


def test_device_trace_events_remap_shift_name(tmp_path):
    path = _fake_capture_json(tmp_path)
    events, n_pids = device_trace_events(path, DEVICE_PID_BASE,
                                         name="cap1", epoch_offset_sec=2.0)
    assert n_pids == 2  # pids 7 and 9 remap densely
    metas = [e for e in events if e["ph"] == "M"]
    names = {e["pid"]: e["args"]["name"] for e in metas
             if e["name"] == "process_name"}
    assert names[DEVICE_PID_BASE] == "device:cap1:/device:TPU:0"
    assert names[DEVICE_PID_BASE + 1].startswith("device:cap1:")  # synth
    xs = {e["name"]: e for e in events if e["ph"] == "X"}
    assert xs["fused_ei_kernel"]["ts"] == pytest.approx(2.0e6 + 10.0)
    assert xs["nodur"]["dur"] == 0.0  # X without a duration repaired
    assert xs["nodur"]["pid"] == DEVICE_PID_BASE + 1
    assert "dropped" not in xs  # only viewer-meaningful phases survive


def test_export_merges_device_capture_and_lints_clean(tmp_path):
    cap = _fake_capture_json(tmp_path, gz=False)
    host = [
        {"kind": "span", "name": "suggest", "ts": 1.0, "wall_sec": 0.5,
         "tname": "MainThread"},
    ]
    trace = export_trace([("run.jsonl", host)],
                         device_traces=[("cap1", cap, 1.0)])
    events = trace["traceEvents"]
    assert validate_trace.validate_events(events) == []
    pids = {e["pid"] for e in events if e["ph"] != "M"}
    assert 0 in pids and DEVICE_PID_BASE in pids
    # a vanished artifact degrades to a skipped track group, not a raise
    trace2 = export_trace([("run.jsonl", host)],
                          device_traces=[("gone", str(tmp_path / "no.gz"),
                                          1.0)])
    assert {e["pid"] for e in trace2["traceEvents"]} == {0}


def test_export_cli_resolves_capture_path_relative_to_stream(tmp_path,
                                                             monkeypatch,
                                                             capsys):
    # profiler.py records trace_json relative to the RUN's cwd; exporting
    # from another directory must retry next to the stream file instead
    # of silently dropping the capture
    run_dir = tmp_path / "rundir"
    run_dir.mkdir()
    cap = _fake_capture_json(run_dir, gz=False)
    rel = os.path.relpath(cap, run_dir)
    (run_dir / "run.jsonl").write_text(json.dumps(
        {"kind": "profile", "ok": True, "ts": 2.0, "t0": 2.0,
         "reason": "http", "dir": "caps", "trace_json": rel}) + "\n")
    monkeypatch.chdir(tmp_path)  # NOT the run's directory
    out = str(tmp_path / "merged.json")
    assert report_main(["--export-trace", out,
                        str(run_dir / "run.jsonl")]) == 0
    events = json.loads((tmp_path / "merged.json").read_text())
    events = events["traceEvents"] if isinstance(events, dict) else events
    assert any(e.get("pid", 0) >= DEVICE_PID_BASE for e in events)
    # a genuinely missing artifact warns instead of silently dropping
    (run_dir / "run.jsonl").write_text(json.dumps(
        {"kind": "profile", "ok": True, "ts": 2.0, "t0": 2.0,
         "reason": "http", "dir": "caps", "trace_json": "gone.json"}) + "\n")
    assert report_main(["--export-trace", out,
                        str(run_dir / "run.jsonl")]) == 0
    assert "skipping device capture" in capsys.readouterr().err


def test_validate_trace_lints_merged_artifact_invariants():
    base = [{"ph": "M", "pid": 0, "tid": 0, "name": "process_name",
             "args": {"name": "host"}}]
    # unnamed track group
    errs = validate_trace.validate_events(
        base + [{"ph": "X", "pid": 5, "tid": 0, "ts": 1, "dur": 1,
                 "name": "k"}])
    assert any("no process_name" in e for e in errs)
    # counter series going backwards in ts
    errs = validate_trace.validate_events(base + [
        {"ph": "C", "pid": 0, "tid": 3, "ts": 10, "name": "a",
         "args": {"v": 1}},
        {"ph": "C", "pid": 0, "tid": 3, "ts": 10, "name": "b",
         "args": {"v": 1}},
        {"ph": "C", "pid": 0, "tid": 3, "ts": 5, "name": "a",
         "args": {"v": 2}},
    ])
    assert any("counter 'a' ts goes backwards" in e for e in errs)
    # non-numeric counter value
    errs = validate_trace.validate_events(base + [
        {"ph": "C", "pid": 0, "tid": 3, "ts": 1, "name": "a",
         "args": {"v": "high"}}])
    assert any("non-numeric" in e for e in errs)
    # a loop-boundary annotation stripped of its ids
    errs = validate_trace.validate_events(base + [
        {"ph": "X", "pid": 0, "tid": 1, "ts": 1, "dur": 1,
         "name": "fmin.tick"}])
    assert any("carries no ids" in e for e in errs)
    # ids as args OR TraceMe-encoded both pass
    ok = validate_trace.validate_events(base + [
        {"ph": "X", "pid": 0, "tid": 1, "ts": 1, "dur": 1,
         "name": "fmin.tick", "args": {"step": 3}},
        {"ph": "X", "pid": 0, "tid": 1, "ts": 2, "dur": 1,
         "name": "device.chunk#start=0#"},
    ])
    assert ok == []


# ---------------------------------------------------------------------------
# kernel attribution: the roofline join
# ---------------------------------------------------------------------------


def test_roofline_table_joins_cost_and_execute_spans():
    dev = {"chunk.flops": 100.0, "chunk.bytes": 8.0,
           "chunk.execute_sec": {"count": 2, "sum": 0.4},
           "suggest.flops": 50.0, "suggest.bytes": 0.0}
    rows = roofline_table(dev, phases={"suggest": {"sec": 1.0, "count": 4}})
    assert rows["chunk"]["achieved_flops_per_sec"] == pytest.approx(500.0)
    assert rows["chunk"]["arithmetic_intensity"] == pytest.approx(12.5)
    assert rows["chunk"]["pct_of_ask"] == pytest.approx(0.4)
    # static-only program (no execute spans yet) keeps its reader
    assert "dispatches" not in rows["suggest"]
    assert rows["suggest"]["arithmetic_intensity"] is None  # bytes 0


def test_report_renders_roofline_and_capture_sections():
    records = [
        {"kind": "span", "name": "suggest", "ts": 1.0, "wall_sec": 1.0},
        {"kind": "metrics", "ts": 2.0, "snapshot": {"shared": {"device": {
            "metrics": {"chunk.flops": 100.0, "chunk.bytes": 8.0,
                        "chunk.execute_sec": {"count": 2, "sum": 0.4,
                                              "min": 0.1, "max": 0.3}},
        }}}},
        {"kind": "profile", "reason": "http", "ts": 3.0, "ok": True,
         "sec": 1.0, "wall_sec": 1.01, "dir": "/tmp/c1",
         "trace_json": "/tmp/c1/x.trace.json.gz"},
        {"kind": "profile", "reason": "stall", "ts": 4.0, "ok": False,
         "error": "capture already in progress"},
    ]
    text = render(records)
    assert "kernel roofline" in text
    assert "x2" in text and "500.0F/s" in text
    assert "device captures" in text
    assert "http" in text and "/tmp/c1/x.trace.json.gz" in text
    assert "stall" in text and "FAILED" in text


# ---------------------------------------------------------------------------
# fmin plumbing: profile= kwarg
# ---------------------------------------------------------------------------


def test_fmin_profile_kwarg_arms_plane(tmp_path, monkeypatch):
    import hyperopt_tpu.obs as obs_mod

    seen = {}
    orig = obs_mod.RunObs.resolve.__func__

    def spy(cls, obs, totals=None, run_id=None):
        bundle = orig(cls, obs, totals=totals, run_id=run_id)
        seen.setdefault("profiler", bundle.profiler)
        seen.setdefault("cfg", bundle.config)
        return bundle

    monkeypatch.setattr(obs_mod.RunObs, "resolve", classmethod(spy))
    cap_dir = str(tmp_path / "caps")
    t = _tpe_run(max_evals=4, profile=cap_dir)
    assert len(t) == 4  # the run itself is unaffected
    assert seen["cfg"].profile_dir == cap_dir
    assert seen["profiler"] is not None
    assert seen["profiler"].out_dir == cap_dir
    # full:<dir> routes to the legacy whole-run mode instead
    seen.clear()
    _tpe_run(max_evals=3, profile="full:" + cap_dir)
    assert seen["cfg"].profile_full == cap_dir
    assert seen["cfg"].profile_dir is None
    assert seen["profiler"] is None


def test_trials_expose_programmatic_capture_handle(tmp_path):
    """The documented programmatic trigger is
    ``trials.obs_profiler.capture(sec)`` — the handle must exist on an
    armed run (even without an obs= stream), be None disarmed, and drop
    from pickles (it holds the capture lock)."""
    import pickle

    t = _tpe_run(max_evals=3, profile=str(tmp_path / "caps"))
    assert t.obs_profiler is not None
    assert t.obs_profiler.out_dir == str(tmp_path / "caps")
    assert callable(t.obs_profiler.capture)
    t2 = pickle.loads(pickle.dumps(t))
    assert getattr(t2, "obs_profiler", None) is None
    assert _tpe_run(max_evals=3).obs_profiler is None  # disarmed


def test_fmin_profile_kwarg_ignored_with_prebuilt_runobs(tmp_path, caplog):
    import logging

    obs = RunObs(ObsConfig(level="basic"), run_id="prebuilt")
    with caplog.at_level(logging.WARNING, logger="hyperopt_tpu.fmin"):
        t = Trials()
        fmin(quad, SPACE, algo=tpe.suggest, max_evals=3, trials=t,
             rstate=np.random.default_rng(0), show_progressbar=False,
             obs=obs, profile=str(tmp_path / "caps"))
    assert any("ignored" in r.getMessage() for r in caplog.records)
