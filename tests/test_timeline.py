"""ISSUE 11: end-to-end request correlation + per-study audit timelines +
WAL back-compat.

The headline acceptance pin: with tracing armed, ONE `ServiceClient` ask
against a real HTTP server yields ONE trace id observable at all five
layers — the client attempt span, the server handler span, the wave
span's fan-in links, the cohort-tick annotation, and the WAL ask record
— and `obs.report --study` renders the full timeline from the store.
Plus: pre-ISSUE-11 journals (no `trace`/`ts` fields) resume
bit-identically, and the flow-event export of a traced run passes the
`scripts/validate_trace.py` lint.
"""

import json
import os
import sys

from hyperopt_tpu import hp
from hyperopt_tpu.obs import report
from hyperopt_tpu.obs.flight import get_flight
from hyperopt_tpu.service.client import ServiceClient
from hyperopt_tpu.service.journal import StudyJournal, wal_path_for
from hyperopt_tpu.service.scheduler import StudyScheduler
from hyperopt_tpu.service.server import ServiceHTTPServer

sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "scripts"))

SPACE = {"x": hp.uniform("x", -5, 5)}
SPACE_SPEC = {"x": {"dist": "uniform", "args": [-5, 5]}}


def _ring_records():
    return get_flight().records()


# ---------------------------------------------------------------------------
# the five-layer correlation pin
# ---------------------------------------------------------------------------


def test_one_trace_observable_at_all_five_layers(tmp_path):
    store = str(tmp_path / "store")
    sched = StudyScheduler(store_root=store)
    srv = ServiceHTTPServer(0, scheduler=sched, slo=False, trace=True)
    assert srv.start()
    try:
        client = ServiceClient(srv.url, trace=True)
        sid = client.create_study(space=SPACE_SPEC, seed=5,
                                  n_startup_jobs=1)
        client.ask(sid)  # startup rand (burns the rand phase)
        client.tell(sid, 0, loss=0.25)
        trials = client.ask(sid)  # THE traced TPE ask
        assert len(trials) == 1
        trace = client.last_trace
        assert isinstance(trace, str) and len(trace) == 32

        # filter the WHOLE ring by the trace id: the ring is process-
        # global and bounded, so under a full suite run its length stays
        # pinned at the cap while content shifts — positional windows
        # lie, the (unique) trace id does not
        by_name = {}
        for r in _ring_records():
            attrs = r.get("attrs") or {}
            if attrs.get("trace") == trace or trace in (
                    attrs.get("links") or []):
                by_name.setdefault(r.get("name"), []).append(r)
        # layer 1: the client attempt span
        assert "client.request" in by_name
        assert by_name["client.request"][-1]["attrs"]["span"] in \
            client.last_spans
        # layer 2: the server handler span (a CHILD span of the client's
        # attempt — same trace, different span id)
        assert "service.handle" in by_name
        assert by_name["service.handle"][-1]["attrs"]["span"] not in \
            client.last_spans
        # layer 3: the wave span links the request trace (fan-in)
        assert trace in by_name["service.wave"][-1]["attrs"]["links"]
        # layer 4: the cohort-tick annotation carries it too
        assert trace in by_name["service.tick"][-1]["attrs"]["links"]
        # layer 5: the WAL ask record is stamped with it
        wal = list(StudyJournal(wal_path_for(store)).records())
        ask_recs = [r for r in wal if r["kind"] == "ask"
                    and r.get("algo") == "tpe"]
        assert ask_recs and ask_recs[-1]["trace"] == trace
        # and the live timeline endpoint shows the same id on the ask
        import urllib.request

        with urllib.request.urlopen(
                f"{srv.url}/study/{sid}/timeline", timeout=30) as r:
            tl = json.loads(r.read())
        tpe_asks = [e for e in tl["events"]
                    if e["event"] == "ask" and e.get("algo") == "tpe"]
        assert tpe_asks and tpe_asks[-1]["trace"] == trace
    finally:
        srv.stop()

    # obs.report --study renders the complete timeline from the store
    # (admit + both asks + the tell), trace ids included
    rendered = report.render_study_timeline(
        sid, [("wal", list(StudyJournal(wal_path_for(store)).records()))])
    assert "admit" in rendered and "tell" in rendered
    assert "algo=tpe" in rendered and "algo=rand" in rendered
    assert trace[:16] in rendered


def test_report_study_cli_accepts_store_root(tmp_path, capsys):
    store = str(tmp_path / "store")
    sched = StudyScheduler(store_root=store)
    srv = ServiceHTTPServer(0, scheduler=sched, slo=False, trace=True)
    code, r = srv.handle("POST", "/study", {"space": SPACE_SPEC,
                                            "seed": 3,
                                            "n_startup_jobs": 1})
    sid = r["study_id"]
    srv.handle("POST", "/ask", {"study_id": sid})
    rc = report.main(["--study", sid, store])
    out = capsys.readouterr().out
    assert rc == 0
    assert f"study timeline: {sid}" in out and "ask" in out
    # unknown study: renders the empty-timeline notice, not a crash
    rc = report.main(["--study", "study-nope", store])
    assert rc == 0
    assert "no WAL records" in capsys.readouterr().out
    # missing stream: clean error
    assert report.main(["--study", sid, str(tmp_path / "nope")]) == 2


def test_shed_and_resume_boundary_appear_in_timeline(tmp_path):
    store = str(tmp_path / "store")
    sched = StudyScheduler(store_root=store)
    sid = sched.create_study(SPACE, seed=11, n_startup_jobs=1,
                             space_spec={"space": SPACE_SPEC})
    a = sched.ask(sid)[0]
    sched.tell(sid, a["tid"], 0.5)
    sched.ask(sid)
    # a restart on the same WAL: the resumed scheduler's timeline marks
    # the crash-resume boundary after the replayed history
    sched2 = StudyScheduler(store_root=store)
    tl = sched2.study_timeline(sid)
    events = [e["event"] for e in tl["events"]]
    assert "resume" in events
    assert events.index("admit") < events.index("resume")
    replayed = [e for e in tl["events"] if e.get("replay")]
    assert replayed  # the pre-crash history is flagged as replayed


# ---------------------------------------------------------------------------
# WAL back-compat: pre-ISSUE-11 journals resume bit-identical
# ---------------------------------------------------------------------------


def _drive(sched, sid, n):
    out = []
    for i in range(n):
        a = sched.ask(sid)[0]
        out.append((a["tid"], repr(a["params"]["x"])))
        sched.tell(sid, a["tid"], float((a["params"]["x"] - 1.0) ** 2))
    return out


def _strip_issue11_fields(rec):
    """A faithful pre-ISSUE-11 record: no ``trace`` ever, no ``ts`` on
    ask/tell/close (admit/snapshot always had one)."""
    rec = {k: v for k, v in rec.items() if k != "trace"}
    if rec.get("kind") in ("ask", "tell", "close"):
        rec.pop("ts", None)
    return rec


def test_pre_issue11_wal_resumes_bit_identical(tmp_path):
    # the reference: an uninterrupted run
    ref = StudyScheduler(wal=False)
    ref_sid = ref.create_study(SPACE, seed=42, n_startup_jobs=2)
    ref_seq = _drive(ref, ref_sid, 6)

    # a run that crashed after 3 rounds, journaled in the OLD format
    store = str(tmp_path / "store")
    s1 = StudyScheduler(store_root=store)
    sid = s1.create_study(SPACE, seed=42, n_startup_jobs=2,
                          space_spec={"space": SPACE_SPEC})
    seq1 = _drive(s1, sid, 3)
    wal_path = wal_path_for(store)
    old_recs = [_strip_issue11_fields(r)
                for r in StudyJournal(wal_path).records()]
    with open(wal_path, "w", encoding="utf-8") as f:
        for rec in old_recs:
            f.write(json.dumps(rec, sort_keys=True,
                               separators=(",", ":")) + "\n")
    assert not any("trace" in r or ("ts" in r and r["kind"] == "ask")
                   for r in StudyJournal(wal_path).records())

    # resume from the stripped journal: proposals must continue the
    # reference stream bit-for-bit
    s2 = StudyScheduler(store_root=store)
    assert s2.last_resume["studies"] == 1
    assert s2.last_resume["errors"] == 0
    seq2 = _drive(s2, sid, 3)
    assert seq1 + seq2 == ref_seq


def test_armed_tracing_wal_resumes_bit_identical(tmp_path):
    """The forward pin: a WAL written WITH trace fields replays to the
    same proposals as the uninterrupted run — replay ignores the
    metadata entirely."""
    ref = StudyScheduler(wal=False)
    ref_sid = ref.create_study(SPACE, seed=9, n_startup_jobs=2)
    ref_seq = _drive(ref, ref_sid, 6)

    store = str(tmp_path / "store")
    s1 = StudyScheduler(store_root=store)
    srv = ServiceHTTPServer(0, scheduler=s1, slo=False, trace=True)
    code, r = srv.handle("POST", "/study", {"space": SPACE_SPEC,
                                            "seed": 9,
                                            "n_startup_jobs": 2})
    sid = r["study_id"]
    seq1 = []
    for i in range(3):
        code, a = srv.handle("POST", "/ask", {"study_id": sid})
        t = a["trials"][0]
        seq1.append((t["tid"], repr(t["params"]["x"])))
        srv.handle("POST", "/tell", {
            "study_id": sid, "tid": t["tid"],
            "loss": float((t["params"]["x"] - 1.0) ** 2)})
    # the armed WAL really carries trace ids on its TPE ask records
    wal = list(StudyJournal(wal_path_for(store)).records())
    assert any(r.get("trace") for r in wal if r["kind"] == "ask")
    s2 = StudyScheduler(store_root=store)
    seq2 = _drive(s2, sid, 3)
    assert seq1 + seq2 == ref_seq


# ---------------------------------------------------------------------------
# flow-event export of a traced run passes the trace lint
# ---------------------------------------------------------------------------


def test_flow_events_lint_clean(tmp_path):
    import validate_trace  # scripts/ (path injected above)

    sched = StudyScheduler(wal=False)
    srv = ServiceHTTPServer(0, scheduler=sched, slo=False, trace=True)
    sid = srv.handle("POST", "/study", {"space": SPACE_SPEC, "seed": 1,
                                        "n_startup_jobs": 1})[1]["study_id"]
    srv.handle("POST", "/ask", {"study_id": sid})
    srv.handle("POST", "/tell", {"study_id": sid, "tid": 0, "loss": 0.1})
    code, a = srv.handle("POST", "/ask", {"study_id": sid})
    trace = a["trace"]

    stream = tmp_path / "svc.jsonl"
    with open(stream, "w") as f:
        for rec in _ring_records():
            f.write(json.dumps(rec, default=str) + "\n")
    out = str(tmp_path / "trace.json")
    assert report.main(["--export-trace", out, str(stream)]) == 0
    assert validate_trace.validate_file(out) == []
    events = json.load(open(out))["traceEvents"]
    flows = [e for e in events if e.get("cat") == "reqtrace"]
    # the traced ask's flow: at least handler -> wave -> tick = s, t, f
    mine = [e for e in flows if (e.get("args") or {}).get("trace") == trace]
    phs = [e["ph"] for e in mine]
    assert phs.count("s") == 1 and phs.count("f") == 1
    assert len(mine) >= 3


def test_top_renders_service_snapshot():
    """obs.top's service view (ISSUE 11 satellite): a serving-process
    /snapshot renders the study table, shed rate, ladder state and SLO
    budget bars — pre-PR the dashboard showed nothing for a server."""
    from hyperopt_tpu.obs import top

    sched = StudyScheduler(wal=False)
    srv = ServiceHTTPServer(0, scheduler=sched, slo=True, trace=True)
    sid = srv.handle("POST", "/study", {"space": SPACE_SPEC, "seed": 2,
                                        "n_startup_jobs": 1})[1]["study_id"]
    code, a = srv.handle("POST", "/ask", {"study_id": sid})
    srv.handle("POST", "/tell", {"study_id": sid,
                                 "tid": a["trials"][0]["tid"],
                                 "loss": 0.5})
    frame = top.render_frame([("svc", srv.snapshot_dict())], {})
    assert "SERVICE" in frame
    assert "studies 1/1" in frame
    assert "slo availability" in frame
    assert sid[:24] in frame
    assert "trials" in frame
    # a dead source still renders as a dead row next to it
    frame = top.render_frame(
        [("svc", srv.snapshot_dict()), ("gone", {"error": "refused"})], {})
    assert "DEAD" in frame and "SERVICE" in frame


def test_flow_export_without_traces_unchanged(tmp_path):
    """A stream with no trace-stamped spans exports zero flow events —
    the merged-artifact gate (TRACE_GATE) stays green on pre-PR
    streams."""
    from hyperopt_tpu.obs.export import flow_events

    assert flow_events([
        {"ph": "X", "ts": 1.0, "pid": 0, "tid": 0, "name": "a",
         "args": {}},
        {"ph": "i", "ts": 2.0, "pid": 0, "tid": 0, "name": "b"},
    ]) == []


def test_flow_export_skips_foreign_non_hex_trace_ids():
    """A foreign producer stamping a non-hex trace attr must not kill
    the export — its arc is skipped, valid flows still emit."""
    from hyperopt_tpu.obs.export import flow_events

    mk = lambda ts, trace: {"ph": "X", "ts": ts, "pid": 0, "tid": 0,  # noqa: E731
                            "name": "s", "args": {"trace": trace}}
    flows = flow_events([mk(1.0, "req-1"), mk(2.0, "req-1"),
                         mk(3.0, "abc123"), mk(4.0, "abc123")])
    assert {f["args"]["trace"] for f in flows} == {"abc123"}


def test_slo_record_fault_does_not_disable_the_plane():
    """A transient SLO-record fault must not freeze the slo_* gauges at
    stale values — the plane logs once and keeps recording."""
    sched = StudyScheduler(wal=False)
    srv = ServiceHTTPServer(0, scheduler=sched, slo=True, trace=True)
    sid = srv.handle("POST", "/study", {"space": SPACE_SPEC, "seed": 4,
                                        "n_startup_jobs": 1})[1]["study_id"]
    boom = {"n": 0}
    orig = srv.slo.record_request

    def flaky(*a, **kw):
        boom["n"] += 1
        if boom["n"] == 1:
            raise RuntimeError("transient registry fault")
        return orig(*a, **kw)

    srv.slo.record_request = flaky
    assert srv.handle("POST", "/ask", {"study_id": sid})[0] == 200
    assert srv.slo is not None  # still armed
    assert srv.handle("POST", "/tell", {"study_id": sid, "tid": 0,
                                        "loss": 0.1})[0] == 200
    assert boom["n"] == 2  # the plane kept recording after the fault
