"""ISSUE 10: overload control (deadlines, bounded admission, shed
breaker, Retry-After EWMA) and the device-fault degrade ladder —
policy-object tests with fake clocks plus scheduler/server integration
pins (disarmed behavior bit-identical; faults walk the ladder and
recover; drain refuses admissions but lands tells).
"""

import numpy as np
import pytest

from hyperopt_tpu import hp
from hyperopt_tpu.obs.metrics import get_metrics, reset_metrics
from hyperopt_tpu.service import StudyScheduler
from hyperopt_tpu.service.overload import (LADDER_LEVELS, AdmissionGuard,
                                           Deadline, DeadlineExceeded,
                                           DegradeLadder, NonFiniteProposal,
                                           OverloadError, is_device_fault)
from hyperopt_tpu.service.server import ServiceHTTPServer

SPACE = {"x": hp.uniform("x", -5, 5)}


class FakeClock:
    def __init__(self, t=100.0):
        self.t = t

    def __call__(self):
        return self.t


# ---------------------------------------------------------------------------
# Deadline
# ---------------------------------------------------------------------------


def test_deadline_header_tightens_never_loosens():
    clk = FakeClock()
    d = Deadline.from_request("500", 30000.0, clock=clk)
    assert d.remaining() == pytest.approx(0.5)
    d = Deadline.from_request("60000", 30000.0, clock=clk)
    assert d.remaining() == pytest.approx(30.0)  # server default wins
    d = Deadline.from_request(None, None, clock=clk)
    assert d.remaining() is None and not d.expired()
    d = Deadline.from_request("garbage", 1000.0, clock=clk)
    assert d.remaining() == pytest.approx(1.0)  # bad header ignored
    d = Deadline.from_request("-5", None, clock=clk)
    assert d.remaining() is None  # non-positive header ignored


def test_deadline_is_monotonic_and_checks():
    clk = FakeClock()
    d = Deadline(100.0, clock=clk)
    assert not d.expired()
    clk.t += 0.2
    assert d.expired() and d.remaining() == 0.0
    with pytest.raises(DeadlineExceeded):
        d.check("ask")


# ---------------------------------------------------------------------------
# AdmissionGuard
# ---------------------------------------------------------------------------


def test_guard_bounds_asks_and_releases():
    g = AdmissionGuard(max_queue=2)
    t1 = g.admit_ask()
    t2 = g.admit_ask()
    with pytest.raises(OverloadError):
        g.admit_ask()
    g.release(t1)
    t3 = g.admit_ask()  # freed slot admits again
    g.release(t2)
    g.release(t3)


def test_guard_sheds_ask_before_tell():
    """The breaker: tells get TELL_SLACK x the ask bound."""
    g = AdmissionGuard(max_queue=1)
    g.admit_ask()
    with pytest.raises(OverloadError):
        g.admit_ask()
    tokens = [g.admit_tell() for _ in range(g.TELL_SLACK)]
    with pytest.raises(OverloadError):
        g.admit_tell()
    for t in tokens:
        g.release(t)


def test_guard_retry_after_tracks_wave_ewma():
    g = AdmissionGuard(max_queue=2)
    for _ in range(10):
        g.observe_wave(0.8)
    assert g.wave_ewma() == pytest.approx(0.8, rel=0.05)
    g.admit_ask()
    g.admit_ask()
    with pytest.raises(OverloadError) as ei:
        g.admit_ask()
    assert ei.value.retry_after == pytest.approx(0.8, rel=0.05)
    # measured from the EWMA, not the 50ms cold floor
    g2 = AdmissionGuard(max_queue=2)
    g2.admit_ask()
    g2.admit_ask()
    with pytest.raises(OverloadError) as ei:
        g2.admit_ask()
    assert ei.value.retry_after == pytest.approx(0.05)  # cold floor


def test_guard_sheds_unservable_deadline():
    clk = FakeClock()
    g = AdmissionGuard(max_queue=8, clock=clk)
    for _ in range(10):
        g.observe_wave(2.0)  # waves take ~2s
    tight = Deadline(100.0, clock=clk)  # 100ms budget
    with pytest.raises(OverloadError):
        g.admit_ask(tight)
    roomy = Deadline(10000.0, clock=clk)
    g.release(g.admit_ask(roomy))
    # cold guard (no EWMA yet) admits and learns
    g2 = AdmissionGuard(max_queue=8, clock=clk)
    g2.release(g2.admit_ask(Deadline(1.0, clock=clk)))


def test_guard_counts_sheds_in_metrics():
    reset_metrics("ovl_test")
    m = get_metrics("ovl_test")
    g = AdmissionGuard(max_queue=1, metrics=m)
    g.admit_ask()
    with pytest.raises(OverloadError):
        g.admit_ask()
    snap = m.snapshot()["metrics"]
    assert snap["service.shed.ask"] == 1
    assert snap["service.queue_depth"] == 1


# ---------------------------------------------------------------------------
# DegradeLadder
# ---------------------------------------------------------------------------


def test_ladder_walks_down_and_recovers():
    lad = DegradeLadder(recover_after=3)
    assert lad.level() == 0 and not lad.degraded
    assert lad.record_fault() == 1
    assert lad.record_fault() == 2
    assert lad.record_fault() == 3
    assert lad.record_fault() == 3  # floor holds
    assert lad.spec()["rand"] is True
    for _ in range(2):
        assert lad.record_clean_wave() == 3
    assert lad.record_clean_wave() == 2  # probe up after patience
    assert lad.record_fault() == 3  # probe failed: straight back down
    for _ in range(3 * 3):
        lad.record_clean_wave()
    assert lad.level() == 0
    assert ("down", 0, 1) in lad.transitions
    assert ("up", 3, 2) in lad.transitions


def test_ladder_levels_shape():
    assert LADDER_LEVELS[0]["cand_scale"] == 1.0
    assert LADDER_LEVELS[1]["cand_scale"] == 0.5
    assert LADDER_LEVELS[2]["cap_limit"] == 64
    assert LADDER_LEVELS[3]["rand"] is True


def test_is_device_fault_classification():
    class XlaRuntimeError(Exception):
        pass

    assert is_device_fault(OSError("chaos: injected I/O error at tick"))
    assert is_device_fault(NonFiniteProposal("nan"))
    assert is_device_fault(XlaRuntimeError("boom"))
    assert is_device_fault(RuntimeError("RESOURCE_EXHAUSTED: out of memory"))
    assert is_device_fault(RuntimeError("INVALID_ARGUMENT: lowering"))
    assert not is_device_fault(ValueError("host bug"))
    assert not is_device_fault(KeyError("host bug"))


# ---------------------------------------------------------------------------
# scheduler integration
# ---------------------------------------------------------------------------


def _drive(sched, sid, n):
    out = []
    for _ in range(n):
        a = sched.ask(sid)[0]
        sched.tell(sid, a["tid"], float((a["params"]["x"]) ** 2))
        out.append((a["tid"], repr(a["params"]["x"]), a.get("degraded")))
    return out


def test_no_faults_means_bit_identical_to_unarmed():
    """The determinism pin: an armed ladder that never faults serves
    proposals bit-identical to a ladder-free scheduler."""
    plain = StudyScheduler(wal=False, degrade=False)
    armed = StudyScheduler(wal=False, degrade=8)
    ps = plain.create_study(SPACE, seed=77, n_startup_jobs=3)
    as_ = armed.create_study(SPACE, seed=77, n_startup_jobs=3)
    a = _drive(plain, ps, 10)
    b = _drive(armed, as_, 10)
    assert [x[:2] for x in a] == [x[:2] for x in b]
    assert not any(x[2] for x in b)  # nothing flagged degraded
    assert armed.degrade.level() == 0 and armed.degrade.faults == 0


def test_tick_faults_walk_to_rand_and_recover(monkeypatch):
    """Persistent device faults degrade to flagged rand service without
    ever failing an ask; clean waves climb back to full quality."""
    from hyperopt_tpu.service import scheduler as sched_mod

    sched = StudyScheduler(wal=False, degrade=2)
    sid = sched.create_study(SPACE, seed=5, n_startup_jobs=2)
    _drive(sched, sid, 2)  # startup rand

    orig = sched_mod._Cohort.tick

    def oom(self, *a, **k):
        raise RuntimeError("RESOURCE_EXHAUSTED: out of memory while "
                           "allocating")

    monkeypatch.setattr(sched_mod._Cohort, "tick", oom)
    seen_degraded = []
    for _ in range(5):
        a = sched.ask(sid)[0]
        sched.tell(sid, a["tid"], 1.0)
        seen_degraded.append(a.get("degraded"))
    assert all(seen_degraded), seen_degraded
    assert sched.degrade.level() == 3  # rand floor under permanent OOM
    a = sched.ask(sid)[0]
    assert a["algo"] == "rand"
    sched.tell(sid, a["tid"], 1.0)

    monkeypatch.setattr(sched_mod._Cohort, "tick", orig)
    flags = []
    for _ in range(12):
        a = sched.ask(sid)[0]
        sched.tell(sid, a["tid"], 1.0)
        flags.append(bool(a.get("degraded")))
    assert sched.degrade.level() == 0  # fully recovered
    assert flags[-1] is False
    snap = sched.metrics.snapshot()["metrics"]
    assert snap["service.degrade.down"] >= 3
    assert snap["service.degrade.up"] >= 3
    assert snap["service.degraded"] == 0


def test_non_finite_proposals_are_a_fault(monkeypatch):
    """NaN readback (poisoned posterior) is treated as a device fault:
    the wave retries down-ladder and ultimately serves finite rand
    proposals instead of handing the client NaN."""
    from hyperopt_tpu.service import scheduler as sched_mod

    sched = StudyScheduler(wal=False, degrade=4)
    sid = sched.create_study(SPACE, seed=6, n_startup_jobs=2)
    _drive(sched, sid, 2)

    orig = sched_mod._Cohort.tick

    def nan_tick(self, demand, **k):
        L = len(self.cs.labels)
        B = max(len(ids) for ids, _ in demand.values())
        return np.full((self.n_slots, B, L), np.nan, np.float32)

    monkeypatch.setattr(sched_mod._Cohort, "tick", nan_tick)
    a = sched.ask(sid)[0]
    assert np.isfinite(a["params"]["x"])
    assert a.get("degraded") and a.get("algo") == "rand"
    assert sched.degrade.faults >= 1
    monkeypatch.setattr(sched_mod._Cohort, "tick", orig)


def test_ladder_disabled_fails_the_ask(monkeypatch):
    from hyperopt_tpu.service import scheduler as sched_mod

    sched = StudyScheduler(wal=False, degrade=False)
    sid = sched.create_study(SPACE, seed=5, n_startup_jobs=2)
    _drive(sched, sid, 2)
    monkeypatch.setattr(
        sched_mod._Cohort, "tick",
        lambda self, *a, **k: (_ for _ in ()).throw(
            RuntimeError("RESOURCE_EXHAUSTED")))
    with pytest.raises(RuntimeError):
        sched.ask(sid)
    assert sched.study_status(sid)["n_pending"] == 0  # quota released


def test_ask_deadline_expired_sheds_cleanly():
    clk = FakeClock()
    sched = StudyScheduler(wal=False)
    sid = sched.create_study(SPACE, seed=5, n_startup_jobs=2)
    d = Deadline(50.0, clock=clk)
    clk.t += 1.0  # expired before entry
    with pytest.raises(DeadlineExceeded):
        sched.ask(sid, deadline=d)
    assert sched.study_status(sid)["n_pending"] == 0


def test_drain_refuses_admissions_but_lands_tells(tmp_path):
    from hyperopt_tpu.service import DrainingError

    sched = StudyScheduler(store_root=str(tmp_path))
    sid = sched.create_study(SPACE, seed=5, n_startup_jobs=2,
                             space_spec={"space": {
                                 "x": {"dist": "uniform",
                                       "args": [-5, 5]}}})
    a = sched.ask(sid)[0]
    assert sched.drain(timeout=5.0) is True
    with pytest.raises(DrainingError):
        sched.ask(sid)
    with pytest.raises(DrainingError):
        sched.create_study(SPACE, seed=9)
    sched.tell(sid, a["tid"], 0.5)  # the in-flight result still lands
    assert sched.study_status(sid)["n_pending"] == 0


# ---------------------------------------------------------------------------
# server integration
# ---------------------------------------------------------------------------


def test_server_sheds_with_retry_after():
    sched = StudyScheduler(wal=False)
    guard = AdmissionGuard(max_queue=1, metrics=sched.metrics)
    server = ServiceHTTPServer(0, scheduler=sched, guard=guard)
    code, r = server.handle("POST", "/study", {
        "space": {"x": {"dist": "uniform", "args": [-5, 5]}},
        "seed": 1, "n_startup_jobs": 1})
    assert code == 200
    sid = r["study_id"]
    guard.admit_ask()  # occupy the only slot
    code, r = server.handle("POST", "/ask", {"study_id": sid})
    assert code == 429
    assert r["ok"] is False and r["retry_after"] > 0


def test_server_deadline_header_is_honored():
    clk = FakeClock()
    sched = StudyScheduler(wal=False)
    guard = AdmissionGuard(max_queue=8, metrics=sched.metrics, clock=clk)
    for _ in range(10):
        guard.observe_wave(5.0)  # very slow waves
    server = ServiceHTTPServer(0, scheduler=sched, guard=guard)
    code, r = server.handle("POST", "/study", {
        "space": {"x": {"dist": "uniform", "args": [-5, 5]}},
        "seed": 1, "n_startup_jobs": 1})
    sid = r["study_id"]
    code, r = server.handle("POST", "/ask", {"study_id": sid},
                            headers={"x-deadline-ms": "100"})
    assert code == 429  # predicted wait 5s >> 100ms budget
    assert "deadline" in r["error"]


def test_server_counts_status_classes_and_draining_503():
    sched = StudyScheduler(wal=False)
    server = ServiceHTTPServer(0, scheduler=sched)
    server.handle("GET", "/studies", {})
    server.handle("POST", "/ask", {"study_id": "nope"})
    sched.drain(timeout=1.0)
    code, r = server.handle("POST", "/study", {
        "space": {"x": {"dist": "uniform", "args": [-5, 5]}}})
    assert code == 503 and r["retry_after"] is not None
    snap = sched.metrics.snapshot()["metrics"]
    assert snap["service.http.studies.2xx"] >= 1
    assert snap["service.http.ask.4xx"] >= 1
    assert snap["service.http.study.5xx"] >= 1


def test_server_500_lands_in_flight_ring(monkeypatch):
    from hyperopt_tpu.obs.flight import get_flight

    sched = StudyScheduler(wal=False)
    server = ServiceHTTPServer(0, scheduler=sched)
    monkeypatch.setattr(sched, "studies_status",
                        lambda: (_ for _ in ()).throw(KeyError("bug")))
    code, r = server.handle("GET", "/studies", {})
    assert code == 500
    recs = [r_ for r_ in get_flight().records()
            if r_.get("kind") == "service_error"]
    assert recs and "KeyError" in recs[-1]["error"]


# ---------------------------------------------------------------------------
# obs.report service-health section
# ---------------------------------------------------------------------------


def test_report_service_section_renders():
    from hyperopt_tpu.obs import report

    metrics = {
        "service.asks": 120, "service.tells": 118, "service.ticks": 40,
        "service.studies_created": 12,
        "service.wave_sec": {"count": 40, "p50": 0.02, "p99": 0.09},
        "service.shed.ask": 30, "service.shed.tell": 0,
        "service.shed.deadline": 4,
        "service.degraded": 2, "service.degrade.down": 3,
        "service.degrade.up": 1, "service.degrade.faults": 3,
        "service.degraded_asks": 9,
        "service.wal.replay_studies": 12, "service.wal.replay_asks": 80,
        "service.wal.replay_regenerated": 5,
        "service.wal.replay_duplicate_tells": 2,
        "service.wal.compactions": 1, "service.wal.sync_errors": 0,
        "service.http.ask.2xx": 90, "service.http.ask.4xx": 30,
        "service.http.study.5xx": 1,
    }
    records = [{"kind": "metrics", "snapshot": {"metrics": metrics}}]
    text = report.render(records)
    assert "service health" in text
    assert "asks 120" in text and "tells 118" in text
    assert "shed" in text and "30" in text
    assert "degrade  level 2" in text and "DEGRADED" in text
    assert "replayed studies 12" in text and "compactions 1" in text
    assert "4xx x30" in text and "5xx x1" in text


def test_report_without_service_metrics_unchanged():
    from hyperopt_tpu.obs import report

    records = [{"kind": "metrics",
                "snapshot": {"metrics": {"trials": 5}}}]
    assert "service health" not in report.render(records)
