"""Child process for the signal-path forensics test (tests/test_flight.py).

Runs a host-loop ``fmin`` whose objective signals readiness after a few
trials and then blocks; the parent SIGTERMs the process mid-``evaluate``
and asserts the flight recorder dumped a parseable ``*.flight.jsonl``
(armed purely via ``HYPEROPT_TPU_FLIGHT`` — the obs stream itself stays
disarmed, which is exactly the "disarmed run leaves forensics anyway"
property the tentpole exists for).
"""

import sys
import time

import numpy as np

from hyperopt_tpu import Trials, fmin, hp
from hyperopt_tpu.algos import rand


def main():
    ready_path = sys.argv[1]
    n_before_hang = int(sys.argv[2]) if len(sys.argv) > 2 else 3
    state = {"n": 0}

    def objective(d):
        state["n"] += 1
        if state["n"] >= n_before_hang:
            with open(ready_path, "w") as f:
                f.write("ready")
            time.sleep(300)  # the parent SIGTERMs us inside this evaluate
        return (d["x"] - 1.0) ** 2

    fmin(objective, {"x": hp.uniform("x", -5, 5)}, algo=rand.suggest,
         max_evals=50, trials=Trials(), rstate=np.random.default_rng(0),
         show_progressbar=False)


if __name__ == "__main__":
    main()
