"""ISSUE 16: the search-quality observability plane.

The acceptance pins:

* the streaming plateau detector mirrors ``early_stop.no_progress_loss``
  math exactly (same improvement test, edge-triggered per episode);
* ``Study.best_loss`` is O(1) after the first read — no per-call rescan
  of the result docs — and stays consistent across WAL replay;
* armed telemetry NEVER changes proposals: armed == disarmed
  bit-identical, directly and over HTTP;
* improvement/stagnation timeline events survive crash-resume
  (replay-flagged, resume-twice idempotent) and an armed scheduler
  replays pre-ISSUE-16 WALs bitwise;
* the per-algo quality keys really GATE: an injected regression on
  ``trials_to_target_tpe`` / ``final_regret_tpe`` / ``solved_frac_tpe``
  fails ``scripts/bench_gate.py``'s windowed compare, and
  ``quality_overhead_frac`` gates against its fixed absolute bar from
  the very first record.
"""

import json
import os
import sys

import pytest

from hyperopt_tpu import hp
from hyperopt_tpu._env import parse_quality, parse_quality_slo
from hyperopt_tpu.obs.quality import (
    DEFAULT_PLATEAU_WINDOW,
    QualityPlane,
    StudyQuality,
    merge_status,
    quality_record,
    summarize_run,
)
from hyperopt_tpu.obs.slo import QUALITY_TARGETS, SLOPlane
from hyperopt_tpu.service.journal import StudyJournal, wal_path_for
from hyperopt_tpu.service.scheduler import StudyScheduler
from hyperopt_tpu.service.server import ServiceHTTPServer

sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "scripts"))

SPACE = {"x": hp.uniform("x", -5, 5)}
SPACE_SPEC = {"x": {"dist": "uniform", "args": [-5, 5]}}


# ---------------------------------------------------------------------------
# the streaming detector: no_progress_loss math, edge-triggered
# ---------------------------------------------------------------------------


def test_detector_mirrors_no_progress_loss_math():
    # pct=10: an improvement needs loss < best - |best| * 0.10, where
    # best is the pure running min — exactly ``no_progress_loss``'s
    # ``best_loss`` reference
    q = StudyQuality("s", "c", window=5, pct=10.0)
    assert q.observe(10.0) == "improvement"   # first ok loss always
    assert q.observe(9.5) is None             # < 10 but not by 10%
    assert q.best == 9.5                      # best still tracks the min
    assert q.observe(8.9) is None             # needs < 9.5 - 0.95 = 8.55
    assert q.observe(7.9) == "improvement"    # < 8.9 - 0.89 = 8.01
    assert q.since_improvement == 0


def test_stagnation_is_edge_triggered_and_clears():
    q = StudyQuality("s", "c", window=3)
    assert q.observe(1.0) == "improvement"
    assert q.observe(1.0) is None
    assert q.observe(1.0) is None
    assert q.observe(1.0) == "stagnation"     # crossing the window fires
    assert q.stagnant
    # the plateau keeps going: ONE event, not one per tell
    assert q.observe(1.0) is None
    assert q.observe(1.0) is None
    assert q.observe(0.5) == "improvement"    # improvement clears the flag
    assert not q.stagnant
    assert q.observe(0.5) is None
    assert q.observe(0.5) is None
    assert q.observe(0.5) == "stagnation"     # and the detector re-arms
    assert q.stagnations == 2


def test_failed_trials_count_toward_stagnation_not_best():
    q = StudyQuality("s", "c", window=2)
    q.observe(3.0)
    assert q.observe(None) is None
    assert q.observe(None) == "stagnation"
    assert q.best == 3.0 and q.n_told == 3


def test_regret_solved_and_curve():
    q = StudyQuality("s", "c", optimum=1.0, loss_target=1.5, window=5)
    q.observe(4.0)
    assert q.regret == 3.0 and not q.solved
    q.observe(1.2)
    assert q.solved and q.trials_to_target == 2
    assert q.regret == pytest.approx(0.2)
    q.observe(0.5)  # beats the recorded optimum: clamped, not negative
    assert q.regret == 0.0
    assert q.curve == [(1, 4.0), (2, 1.2), (3, 0.5)]
    d = q.status_dict()
    assert d["solved"] and d["trials_to_target"] == 2
    assert d["best_loss"] == 0.5 and d["regret"] == 0.0


def test_ewma_rises_on_wins_decays_on_plateau():
    q = StudyQuality("s", "c", alpha=0.5)
    q.observe(10.0)
    q.observe(6.0)                 # delta 4
    rate = q.ewma
    assert rate > 0
    q.observe(7.0)                 # non-improving: decay toward zero
    assert q.ewma < rate


def test_summarize_run():
    s = summarize_run([5.0, None, 2.0, 1.0, 3.0], budget=5,
                      loss_target=2.0, optimum=0.5)
    assert s["best"] == 1.0 and s["solved"]
    assert s["trials_to_target"] == 3          # 1-based, first clearing
    assert s["final_regret"] == pytest.approx(0.5)
    # unsolved runs charge the full budget — aggregation must penalize
    s = summarize_run([5.0, 4.0], budget=20, loss_target=1.0)
    assert not s["solved"] and s["trials_to_target"] == 20
    assert summarize_run([], budget=3)["best"] is None


def test_merge_status_across_planes():
    a = {"studies": 2, "stagnant": 1, "solved": 1, "improvements": 5,
         "stagnations": 1, "stagnant_frac": 0.5,
         "cohorts": {"tpe_branin": {"studies": 2, "stagnant": 1,
                                    "solved": 1, "best_loss": 0.5,
                                    "best_regret": 0.1}}}
    b = {"studies": 1, "stagnant": 0, "solved": 0, "improvements": 2,
         "stagnations": 0, "stagnant_frac": 0.0,
         "cohorts": {"tpe_branin": {"studies": 1, "stagnant": 0,
                                    "solved": 0, "best_loss": 0.4,
                                    "best_regret": None}}}
    m = merge_status([a, b])
    assert m["studies"] == 3 and m["stagnant"] == 1
    assert m["stagnant_frac"] == pytest.approx(1 / 3)
    c = m["cohorts"]["tpe_branin"]
    assert c["studies"] == 3 and c["best_loss"] == 0.4
    assert c["best_regret"] == 0.1             # None never wins the min
    assert merge_status([]) is None
    assert merge_status([a, None]) is a        # single plane passes through


def test_quality_record_shape():
    rec = quality_record("test", {"tpe": {"trials_to_target": 3}},
                         config={"n": 1})
    assert rec["kind"] == "quality" and rec["source"] == "test"
    assert rec["algos"]["tpe"]["trials_to_target"] == 3
    json.dumps(rec)  # store rows must be JSON-serializable


def test_env_knobs(monkeypatch):
    monkeypatch.delenv("HYPEROPT_TPU_QUALITY", raising=False)
    assert parse_quality()                      # default ON for serving
    for off in ("0", "off", "false", "no"):
        assert not parse_quality({"HYPEROPT_TPU_QUALITY": off})
    assert parse_quality({"HYPEROPT_TPU_QUALITY": "1"})
    # the SLO rider: default on, explicit off, and the token grammar
    assert parse_quality_slo({}) == QUALITY_TARGETS
    assert parse_quality_slo({"HYPEROPT_TPU_QUALITY_SLO": "off"}) is None
    t = parse_quality_slo({"HYPEROPT_TPU_QUALITY_SLO": "stagnant=25"})
    assert t["stagnation"]["target"] == pytest.approx(0.75)
    # malformed tokens warn once and fall back to the defaults
    assert parse_quality_slo(
        {"HYPEROPT_TPU_QUALITY_SLO": "stagnant=banana"}) == QUALITY_TARGETS


def test_slo_stagnation_objective_records():
    slo = SLOPlane(metrics=None, clock=lambda: 1000.0)
    slo.add_objective("stagnation", QUALITY_TARGETS["stagnation"])
    slo.add_objective("stagnation", {"target": 0.5})  # idempotent
    assert slo.objectives["stagnation"].target == 0.90
    for _ in range(9):
        slo.record_quality(False, now=1000.0)
    slo.record_quality(True, now=1000.0)
    st = slo.status(now=1000.0)["stagnation"]
    assert st["budget_remaining_frac"] < 1.0
    # disarmed plane: record_quality is a no-op, not a KeyError
    SLOPlane(metrics=None).record_quality(True)


# ---------------------------------------------------------------------------
# Study.best_loss: O(1) after first read, consistent across replay
# ---------------------------------------------------------------------------


def test_best_loss_is_cached_not_rescanned():
    sched = StudyScheduler(wal=False)
    sid = sched.create_study(SPACE, seed=7, n_startup_jobs=10)
    losses = [0.9, 0.4, 0.7]
    for loss in losses:
        a = sched.ask(sid)[0]
        sched.tell(sid, a["tid"], loss)
    st = sched._studies[sid]
    assert st.best_loss() == 0.4
    # tamper a settled doc's loss bypassing the scheduler: a cached best
    # must NOT see it (pre-PR the O(n) rescan on every status read would)
    for r in st.trials.results:
        if r.get("loss") == 0.4:
            r["loss"] = -99.0
    assert st.best_loss() == 0.4               # O(1) cached read
    st.mark_best_dirty()
    assert st.best_loss() == -99.0             # the rescan path still works


def test_best_loss_ignores_failed_trials():
    sched = StudyScheduler(wal=False)
    sid = sched.create_study(SPACE, seed=3, n_startup_jobs=10)
    a = sched.ask(sid)[0]
    sched.tell(sid, a["tid"], 0.8)
    b = sched.ask(sid)[0]
    sched.tell(sid, b["tid"], None, status="fail")
    assert sched._studies[sid].best_loss() == 0.8


def test_best_loss_consistent_across_wal_replay(tmp_path):
    store = str(tmp_path / "store")
    s1 = StudyScheduler(store_root=store)
    sid = s1.create_study(SPACE, seed=11, n_startup_jobs=10,
                          space_spec={"space": SPACE_SPEC})
    for loss in (0.9, 0.2, 0.5):
        a = s1.ask(sid)[0]
        s1.tell(sid, a["tid"], loss)
    assert s1._studies[sid].best_loss() == 0.2
    s2 = StudyScheduler(store_root=store)
    st = s2._studies[sid]
    assert st.best_loss() == 0.2
    # and the replayed cache is LIVE, not stale: a better tell updates it
    a = s2.ask(sid)[0]
    s2.tell(sid, a["tid"], 0.1)
    assert st.best_loss() == 0.1


# ---------------------------------------------------------------------------
# armed == disarmed: telemetry never changes proposals
# ---------------------------------------------------------------------------


def _drive(sched, sid, n):
    out = []
    for _ in range(n):
        a = sched.ask(sid)[0]
        out.append((a["tid"], repr(a["params"]["x"])))
        sched.tell(sid, a["tid"], float((a["params"]["x"] - 1.0) ** 2))
    return out


def test_armed_equals_disarmed_bit_identical():
    on = StudyScheduler(wal=False, quality=QualityPlane())
    off = StudyScheduler(wal=False, quality=False)
    assert on.quality is not None and off.quality is None
    sid_on = on.create_study(SPACE, seed=21, n_startup_jobs=2)
    sid_off = off.create_study(SPACE, seed=21, n_startup_jobs=2)
    assert _drive(on, sid_on, 8) == _drive(off, sid_off, 8)
    # the armed run really observed: telemetry exists, proposals match
    q = on.quality.study_status(sid_on)
    assert q is not None and q["n_told"] == 8


def test_armed_equals_disarmed_over_http():
    def drive(srv, sid, n):
        seq = []
        for _ in range(n):
            code, a = srv.handle("POST", "/ask", {"study_id": sid})
            assert code == 200
            t = a["trials"][0]
            seq.append((t["tid"], repr(t["params"]["x"])))
            code, _ = srv.handle("POST", "/tell", {
                "study_id": sid, "tid": t["tid"],
                "loss": float((t["params"]["x"] - 1.0) ** 2)})
            assert code == 200
        return seq

    seqs = {}
    for armed in (True, False):
        sched = StudyScheduler(
            wal=False, quality=QualityPlane() if armed else False)
        srv = ServiceHTTPServer(0, scheduler=sched, slo=armed, trace=False)
        code, r = srv.handle("POST", "/study", {
            "space": SPACE_SPEC, "seed": 33, "n_startup_jobs": 2})
        seqs[armed] = drive(srv, r["study_id"], 8)
        if armed:
            # the armed server's surfaces carry the quality sections
            snap = srv.snapshot_dict()
            assert snap["quality"]["studies"] == 1
            assert snap["studies"][0]["quality"]["n_told"] == 8
    assert seqs[True] == seqs[False]


# ---------------------------------------------------------------------------
# crash-resume: events replay-flagged, idempotent, back-compat bitwise
# ---------------------------------------------------------------------------


def _quality_events(sched, sid):
    return [e for e in sched.study_timeline(sid)["events"]
            if e["event"] in ("improvement", "stagnation")]


def test_quality_events_replay_flagged_and_idempotent(tmp_path):
    store = str(tmp_path / "store")
    s1 = StudyScheduler(store_root=store)
    sid = s1.create_study(SPACE, seed=5, n_startup_jobs=1,
                          space_spec={"space": SPACE_SPEC})
    # one improvement, then a full plateau window => one stagnation
    a = s1.ask(sid)[0]
    s1.tell(sid, a["tid"], 1.0)
    for _ in range(DEFAULT_PLATEAU_WINDOW):
        a = s1.ask(sid)[0]
        s1.tell(sid, a["tid"], 2.0)            # never improves
    live = _quality_events(s1, sid)
    assert [e["event"] for e in live] == ["improvement", "stagnation"]
    assert not any(e.get("replay") for e in live)
    assert s1.quality.study_status(sid)["stagnant"]

    # crash-resume: same events, now replay-flagged, tracker state rebuilt
    s2 = StudyScheduler(store_root=store)
    ev2 = _quality_events(s2, sid)
    assert [e["event"] for e in ev2] == ["improvement", "stagnation"]
    assert all(e.get("replay") for e in ev2)
    assert s2.quality.study_status(sid)["stagnant"]
    assert (s2.quality.study_status(sid)["n_told"]
            == s1.quality.study_status(sid)["n_told"])

    # resume-twice: replay is idempotent, no duplicated events
    s3 = StudyScheduler(store_root=store)
    assert ([e["event"] for e in _quality_events(s3, sid)]
            == ["improvement", "stagnation"])


def test_pre_issue16_wal_replays_bitwise_on_armed_scheduler(tmp_path):
    """A WAL written before this PR carries no quality-derived records
    at all (the plane writes none — events live in memory, telemetry in
    gauges), so the pre-ISSUE-16 format IS the current format.  The pin:
    an armed scheduler replays it to bit-identical proposals."""
    ref = StudyScheduler(wal=False, quality=False)
    ref_sid = ref.create_study(SPACE, seed=42, n_startup_jobs=2)
    ref_seq = _drive(ref, ref_sid, 6)

    store = str(tmp_path / "store")
    s1 = StudyScheduler(store_root=store, quality=False)  # pre-PR writer
    sid = s1.create_study(SPACE, seed=42, n_startup_jobs=2,
                          space_spec={"space": SPACE_SPEC})
    seq1 = _drive(s1, sid, 3)
    # the WAL holds nothing quality-specific for the armed reader to see
    kinds = {r["kind"] for r in
             StudyJournal(wal_path_for(store)).records()}
    assert kinds <= {"admit", "ask", "tell", "close", "snapshot"}

    s2 = StudyScheduler(store_root=store)   # armed (the default)
    assert s2.quality is not None
    assert s2.last_resume["errors"] == 0
    seq2 = _drive(s2, sid, 3)
    assert seq1 + seq2 == ref_seq
    # and the armed reader rebuilt telemetry from the replayed tells
    assert s2.quality.study_status(sid)["n_told"] == 6


def test_quality_fault_never_fails_a_tell():
    sched = StudyScheduler(wal=False, quality=QualityPlane())

    def boom(st, loss, replay=False):
        raise RuntimeError("tracker exploded")

    sched.quality.observe_tell = boom
    sid = sched.create_study(SPACE, seed=2, n_startup_jobs=1)
    a = sched.ask(sid)[0]
    sched.tell(sid, a["tid"], 0.5)             # must not raise
    assert sched._studies[sid].best_loss() == 0.5


# ---------------------------------------------------------------------------
# the quality keys really gate: injected regression fails bench_gate
# ---------------------------------------------------------------------------


def _bench_rec(ts, **keys):
    return {"kind": "bench", "ts": ts, "backend": "cpu",
            "source": "test", "keys": keys}


_GOOD = dict(trials_to_target_tpe=20.0, final_regret_tpe=0.5,
             solved_frac_tpe=0.8, quality_overhead_frac=0.01)


def test_injected_quality_regression_fails_the_gate(tmp_path):
    import bench_gate  # scripts/ (path injected above)
    from hyperopt_tpu.obs.trajectory import KEY_DIRECTIONS

    history = [_bench_rec(float(i), **_GOOD) for i in range(3)]
    # a healthy new round passes
    regs, _ = bench_gate.windowed_compare(
        history, _bench_rec(3.0, **_GOOD), KEY_DIRECTIONS)
    assert regs == []
    # degrade each quality axis past its threshold: the gate must fail
    for key, bad in (("trials_to_target_tpe", 30.0),   # +50% > 30% bar
                     ("final_regret_tpe", 1.5),        # +200% > 75% bar
                     ("solved_frac_tpe", 0.4)):        # -50% > 30% bar
        new = _bench_rec(3.0, **{**_GOOD, key: bad})
        regs, _ = bench_gate.windowed_compare(history, new, KEY_DIRECTIONS)
        assert any(key in r for r in regs), (key, regs)
    # end-to-end through the store path (the QUALITY_GATE surface)
    store = str(tmp_path / "trajectory.jsonl")
    with open(store, "w") as f:
        for rec in history + [_bench_rec(3.0, **{**_GOOD,
                                                 "final_regret_tpe": 9.0})]:
            f.write(json.dumps(rec) + "\n")
    assert bench_gate._windowed_main(store, 5, None, explain=True) == 1
    with open(store, "a") as f:
        f.write(json.dumps(_bench_rec(4.0, **_GOOD)) + "\n")
    assert bench_gate._windowed_main(store, 5, None) == 0


def test_quality_overhead_gates_absolute_from_first_run(tmp_path):
    """``quality_overhead_frac`` uses the fixed absolute bar (the
    profiler/checksum overhead pattern): it gates with NO history at
    all — the very first recorded round already enforces ≤5%."""
    import bench_gate
    from hyperopt_tpu.obs.trajectory import KEY_DIRECTIONS

    old = _bench_rec(0.0, trials_per_sec=100.0)  # no quality keys at all
    over = _bench_rec(1.0, quality_overhead_frac=0.09)
    regs, _ = bench_gate.windowed_compare([old], over, KEY_DIRECTIONS)
    assert any("quality_overhead_frac" in r for r in regs)
    ok = _bench_rec(1.0, quality_overhead_frac=0.04)
    regs, _ = bench_gate.windowed_compare([old], ok, KEY_DIRECTIONS)
    assert regs == []
