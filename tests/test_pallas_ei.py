"""Pallas EI-kernel tests (CPU lane: exercises the jnp twin + the fallback
dispatch logic; the TPU lowering itself was validated on hardware — see
hyperopt_tpu/pallas_ei.py MEASURED VERDICT)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from hyperopt_tpu import pallas_ei
from hyperopt_tpu.algos import tpe


def _models(m=65, seed=0):
    rng = np.random.default_rng(seed)
    def one(z):
        w = np.abs(rng.random(m)).astype(np.float32)
        w[z] = 0.0  # a dead (masked) component
        w /= w.sum()
        return (jnp.asarray(w),
                jnp.asarray(rng.uniform(-5, 5, m).astype(np.float32)),
                jnp.asarray(rng.uniform(0.1, 2.0, m).astype(np.float32)))
    return one(3), one(7)


def test_ei_diff_matches_tpe_lpdf_pair():
    # the kernel's math contract: ei_diff == gmm1_lpdf_b - gmm1_lpdf_a for
    # the untruncated case (truncation terms are scalar shifts the caller
    # applies; they cancel out of the difference only when p_accepts match,
    # so compare against the untruncated lpdfs directly)
    (wb, mb, sb), (wa, ma, sa) = _models()
    x = jnp.asarray(np.random.default_rng(1).uniform(-5, 5, 2048).astype(np.float32))
    got = pallas_ei.ei_diff_reference(x, wb, mb, sb, wa, ma, sa)
    inf = float("inf")
    want = (tpe.gmm1_lpdf(x, wb, mb, sb, -inf, inf, None)
            - tpe.gmm1_lpdf(x, wa, ma, sa, -inf, inf, None))
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


def test_ei_diff_dispatch_and_fallback():
    (wb, mb, sb), (wa, ma, sa) = _models()
    x = jnp.asarray(np.random.default_rng(2).uniform(-5, 5, 8192).astype(np.float32))
    out = pallas_ei.ei_diff(x, wb, mb, sb, wa, ma, sa)  # CPU: jnp twin
    ref = pallas_ei.ei_diff_reference(x, wb, mb, sb, wa, ma, sa)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)
    # non-tiling candidate count always takes the fallback, on any backend
    x_odd = x[:100]
    out2 = pallas_ei.ei_diff(x_odd, wb, mb, sb, wa, ma, sa)
    np.testing.assert_allclose(
        np.asarray(out2),
        np.asarray(pallas_ei.ei_diff_reference(x_odd, wb, mb, sb, wa, ma, sa)),
        rtol=1e-5, atol=1e-5)


def test_ei_diff_dead_components_do_not_poison():
    (wb, mb, sb), (wa, ma, sa) = _models()
    x = jnp.asarray(np.linspace(-5, 5, 1024).astype(np.float32))
    out = np.asarray(pallas_ei.ei_diff(x, wb, mb, sb, wa, ma, sa))
    assert np.isfinite(out).all()
