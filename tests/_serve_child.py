"""Child process for the live-scrape test (tests/test_serve.py).

Runs a host-loop ``fmin`` with the scrape server armed on an ephemeral
port (``obs_http=0``).  The first evaluated trial writes the server's URL
to the handshake file; subsequent trials are slow enough that the parent
can scrape ``/metrics`` and ``/snapshot`` while the run is demonstrably
mid-flight.
"""

import os
import sys
import time

import numpy as np

from hyperopt_tpu import Trials, fmin, hp
from hyperopt_tpu.algos import rand


def main():
    url_file = sys.argv[1]
    trials = Trials()
    state = {"written": False}

    def objective(d):
        if not state["written"]:
            with open(url_file + ".tmp", "w") as f:
                f.write(trials.obs_http_url or "DISABLED")
            os.replace(url_file + ".tmp", url_file)
            state["written"] = True
        time.sleep(0.05)
        return (d["x"] - 1.0) ** 2

    fmin(objective, {"x": hp.uniform("x", -5, 5)}, algo=rand.suggest,
         max_evals=60, trials=trials, rstate=np.random.default_rng(0),
         show_progressbar=False, obs_http=0)
    print("CHILD_DONE", flush=True)


if __name__ == "__main__":
    main()
