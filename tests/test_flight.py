"""Crash & stall forensics (hyperopt_tpu/obs/{flight,watchdog,export}.py):
flight-recorder ring + signal dumps, hang watchdog, Perfetto export, and
the post-mortem renderer.

All tier-1 (CPU, fast).  The signal-path test is a real subprocess killed
mid-``fmin`` — the acceptance scenario: a SIGTERM'd child leaves a
parseable ``*.flight.jsonl`` that ``obs.report --postmortem`` renders.
"""

import json
import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

from hyperopt_tpu import Trials, fmin, hp
from hyperopt_tpu.algos import rand
from hyperopt_tpu.obs import get_flight, read_jsonl
from hyperopt_tpu.obs.flight import FlightRecorder, flight_path_for
from hyperopt_tpu.obs.report import main as report_main, render_postmortem
from hyperopt_tpu.obs.trace import JsonlSink, Tracer, iter_jsonl
from hyperopt_tpu.obs.watchdog import Watchdog

sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "scripts"))
import validate_trace  # noqa: E402  (scripts/validate_trace.py)

SPACE = {"x": hp.uniform("x", -5, 5)}


def quad(d):
    return (d["x"] - 1.0) ** 2


# ---------------------------------------------------------------------------
# flight recorder: ring bounds, always-on feed, dump lifecycle
# ---------------------------------------------------------------------------


def test_flight_ring_bounded_count_and_bytes():
    fr = FlightRecorder(max_records=8, max_bytes=1 << 20)
    for i in range(100):
        fr.record({"kind": "event", "name": f"e{i}", "ts": float(i)})
    recs = fr.records()
    assert len(recs) == 8
    assert recs[0]["name"] == "e92" and recs[-1]["name"] == "e99"

    # byte bound trips before the count bound for fat records
    fr = FlightRecorder(max_records=10_000, max_bytes=2_000)
    for i in range(1_000):
        fr.record({"kind": "event", "name": "x" * 100, "ts": float(i)})
    assert len(fr.records()) < 100  # ~150 estimated bytes per record
    assert fr._bytes <= 2_000


def test_flight_dump_enforces_exact_byte_budget(tmp_path):
    fr = FlightRecorder(max_records=10_000, max_bytes=3_000)
    fr.max_bytes = 10 ** 9  # let the ring grow...
    for i in range(200):
        fr.record({"kind": "event", "name": "y" * 50, "ts": float(i)})
    fr.max_bytes = 3_000  # ...then dump under a tight exact budget
    path = tmp_path / "budget.flight.jsonl"
    fr.dump("test", path=str(path))
    assert os.path.getsize(path) <= 3_000 + 200  # header + slack
    recs = read_jsonl(path)
    # newest records survive the budget; the header still leads
    assert recs[0]["kind"] == "flight_dump"
    assert recs[-1]["name"] == "y" * 50


def test_disarmed_spans_feed_flight_ring():
    fr = get_flight()
    fr.clear()
    tr = Tracer(run_id="flight-t")  # no sink: the disarmed fast path
    with tr.span("suggest"):
        pass
    tr.event("stop_reason", why="test")
    names = [(r.get("kind"), r.get("name")) for r in fr.records()]
    assert ("span", "suggest") in names
    assert ("event", "stop_reason") in names


def test_disarmed_fmin_leaves_flight_records():
    fr = get_flight()
    fr.clear()
    fmin(quad, SPACE, algo=rand.suggest, max_evals=4,
         rstate=np.random.default_rng(0), show_progressbar=False)
    kinds = {(r.get("kind"), r.get("name", r.get("event")))
             for r in fr.records()}
    assert ("span", "suggest") in kinds
    assert ("trial_event", "trial_finished") in kinds


def test_open_spans_reported_in_dump(tmp_path):
    fr = FlightRecorder()
    tr = Tracer(flight=fr)
    path = str(tmp_path / "open.flight.jsonl")
    with tr.span("evaluate"):
        fr.dump("mid-span", path=path)
    recs = read_jsonl(path)
    opened = [r for r in recs if r.get("kind") == "open_span"]
    assert [r["name"] for r in opened] == ["evaluate"]
    assert opened[0]["age_sec"] >= 0
    assert opened[0]["thread"] == "MainThread"
    # after a clean exit the span is closed: a later dump reports none
    fr.dump("after", path=path)
    assert not [r for r in read_jsonl(path) if r.get("kind") == "open_span"]


def test_armed_fmin_derives_and_releases_flight_target(tmp_path):
    path = str(tmp_path / "armed.jsonl")
    fmin(quad, SPACE, algo=rand.suggest, max_evals=3,
         rstate=np.random.default_rng(0), show_progressbar=False, obs=path)
    # the derived per-run target (armed.flight.jsonl) was removed at
    # finish(): clean exits must not litter
    assert flight_path_for(path) not in get_flight()._targets


# ---------------------------------------------------------------------------
# satellite: _Span stack-leak fix (disarm mid-span)
# ---------------------------------------------------------------------------


def test_span_stack_survives_midspan_disarm(tmp_path):
    tr = Tracer(sink=JsonlSink(tmp_path / "mid.jsonl"), run_id="t")
    with tr.span("outer"):
        tr.sink = None  # disarmed mid-span (RunObs.finish on re-entry)
    # the armed __enter__ pushed; the disarmed __exit__ must still pop —
    # otherwise every later span on this thread nests under a ghost
    assert tr._stack() == []
    with tr.span("after") as s:
        assert s._pushed is False  # disarmed now: no stack bookkeeping
    tr.sink = JsonlSink(tmp_path / "mid2.jsonl")
    with tr.span("rearmed") as s:
        assert s.depth == 0 and s.parent_id is None


# ---------------------------------------------------------------------------
# satellite: JsonlSink survives a dead filesystem
# ---------------------------------------------------------------------------


def test_sink_disables_on_oserror_instead_of_raising(tmp_path, caplog):
    target = tmp_path / "is_a_dir.jsonl"
    target.mkdir()  # open() will raise IsADirectoryError (an OSError)
    sink = JsonlSink(target)
    with caplog.at_level("ERROR", logger="hyperopt_tpu.obs.trace"):
        sink.write({"kind": "span", "name": "a"})  # must not raise
        sink.write({"kind": "span", "name": "b"})
        sink.write({"kind": "span", "name": "c"})
    assert sink._dead
    # log-once: the disable is reported exactly one time
    assert sum("disabling the JSONL stream" in r.message
               for r in caplog.records) == 1
    # the instrumented path keeps working on the dead sink
    tr = Tracer(sink=sink, run_id="dead")
    with tr.span("still_fine"):
        pass
    assert tr._stack() == []
    # pickling resets the latch: a resumed process retries fresh
    import pickle

    sink2 = pickle.loads(pickle.dumps(sink))
    assert sink2._dead is False


# ---------------------------------------------------------------------------
# satellite: streaming reader
# ---------------------------------------------------------------------------


def test_iter_jsonl_streams_and_wrapper_matches(tmp_path):
    import types

    path = tmp_path / "s.jsonl"
    with open(path, "w") as f:
        for i in range(5):
            f.write(json.dumps({"kind": "event", "i": i}) + "\n")
        f.write('{"kind": "event", "i": 5, "torn')  # killed mid-write
    it = iter_jsonl(path)
    assert isinstance(it, types.GeneratorType)
    assert next(it)["i"] == 0  # lazily consumable, record by record
    rest = list(it)
    assert [r["i"] for r in rest] == [1, 2, 3, 4]  # torn line skipped
    assert read_jsonl(path) == list(iter_jsonl(path))


# ---------------------------------------------------------------------------
# watchdog: fake clock — once per quiet period, not per tick
# ---------------------------------------------------------------------------


class _Clock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


class _ListSink:
    def __init__(self):
        self.records = []

    def write(self, rec):
        self.records.append(rec)


def test_watchdog_fires_once_per_quiet_period():
    clock = _Clock()
    wd = Watchdog(quiet_sec=300.0, clock=clock, flight=FlightRecorder())
    wd.retain()  # a run is live (RunObs does this)
    sink = _ListSink()
    wd.attach_sink(sink)
    wd.beat("fmin.tick", n=1)

    # ticks inside the quiet period: silent
    for t in (10.0, 100.0, 299.0):
        clock.t = t
        assert wd.check() is None
    # first tick past the quiet period: exactly one stall
    clock.t = 301.0
    rec = wd.check()
    assert rec is not None and rec["kind"] == "stall"
    # subsequent ticks in the SAME quiet period: silent, not per-tick
    for t in (302.0, 350.0, 500.0, 600.9):
        clock.t = t
        assert wd.check() is None
    # a second full quiet period of silence: the next (single) report
    clock.t = 601.1
    assert wd.check() is not None
    assert wd.stall_count == 2
    assert len(sink.records) == 2

    # recovery re-arms: a beat, then silence, fires again after quiet_sec
    clock.t = 700.0
    wd.beat("fmin.tick", n=2)
    clock.t = 900.0
    assert wd.check() is None
    clock.t = 1000.5
    rec = wd.check()
    assert rec is not None and wd.stall_count == 3


def test_watchdog_quiesces_without_live_runs():
    clock = _Clock()
    wd = Watchdog(quiet_sec=10.0, clock=clock, flight=FlightRecorder())
    wd.retain()
    wd.beat("fmin.tick")
    wd.release()  # the run finished (RunObs.finish)
    # the process outlives the run: NEVER report its idleness as a stall
    for t in (100.0, 1000.0, 100000.0):
        clock.t = t
        assert wd.check() is None
    # a resumed run (rearm) re-enables detection
    wd.retain()
    clock.t += 50.0
    assert wd.check() is not None


def test_watchdog_stall_record_contents():
    clock = _Clock()
    fr = FlightRecorder()
    wd = Watchdog(quiet_sec=60.0, clock=clock, flight=fr)
    wd.retain()
    wd.beat("driver.allgather", point="losses", mark="pre", gen=7)
    clock.t = 100.0
    rec = wd.check()
    beats = rec["last_heartbeats"]
    assert beats["driver.allgather"]["age_sec"] == pytest.approx(100.0)
    # the named blocked collective: detail survives verbatim
    assert beats["driver.allgather"]["detail"] == {
        "point": "losses", "mark": "pre", "gen": 7}
    # this (main) thread's stack is captured, watchdog-free
    assert any("MainThread" in name for name in rec["stacks"])
    frames = rec["stacks"]["MainThread"]
    assert any("test_flight" in f for f in frames)
    # the stall landed in the flight ring too
    assert any(r.get("kind") == "stall" for r in fr.records())


def test_fmin_feeds_global_watchdog():
    from hyperopt_tpu.obs.watchdog import get_watchdog

    wd = get_watchdog()
    if wd is None:
        pytest.skip("global watchdog disabled via HYPEROPT_TPU_WATCHDOG")
    wd._beats.pop("fmin.tick", None)
    fmin(quad, SPACE, algo=rand.suggest, max_evals=3,
         rstate=np.random.default_rng(0), show_progressbar=False)
    assert "fmin.tick" in wd._beats
    assert "fmin.evaluate" in wd._beats


# ---------------------------------------------------------------------------
# signal-path forensics: SIGTERM'd child leaves a renderable flight dump
# ---------------------------------------------------------------------------


def test_sigterm_child_leaves_parseable_flight_dump(tmp_path, capsys):
    flight_path = str(tmp_path / "child.flight.jsonl")
    ready_path = str(tmp_path / "ready")
    child = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                         "_flight_child.py")
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = {**os.environ, "JAX_PLATFORMS": "cpu",
           "PALLAS_AXON_POOL_IPS": "",
           "PYTHONPATH": repo_root + os.pathsep
           + os.environ.get("PYTHONPATH", ""),
           "HYPEROPT_TPU_FLIGHT": flight_path}
    proc = subprocess.Popen([sys.executable, child, ready_path],
                            env=env, stdout=subprocess.DEVNULL,
                            stderr=subprocess.PIPE, cwd=repo_root)
    try:
        deadline = time.time() + 120
        while not os.path.exists(ready_path):
            assert proc.poll() is None, (
                "child died before hanging:\n"
                + proc.stderr.read().decode()[-2000:])
            assert time.time() < deadline, "child never reached the hang"
            time.sleep(0.1)
        proc.send_signal(signal.SIGTERM)
        rc = proc.wait(timeout=30)
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=30)
    assert rc == -signal.SIGTERM  # default disposition preserved

    # the dump exists and parses with the ordinary JSONL reader
    assert os.path.exists(flight_path)
    recs = read_jsonl(flight_path)
    kinds = {r.get("kind") for r in recs}
    assert "flight_dump" in kinds
    head = [r for r in recs if r["kind"] == "flight_dump"][-1]
    assert head["reason"] == "signal:SIGTERM"
    # the process died INSIDE evaluate: reported as an open span
    open_names = {r["name"] for r in recs if r.get("kind") == "open_span"}
    assert "evaluate" in open_names and "run" in open_names
    # trial lifecycle made it into the ring: the hanging trial is claimed
    # but never finished
    claimed = {r["tid"] for r in recs
               if r.get("event") == "trial_claimed"}
    finished = {r["tid"] for r in recs
                if r.get("event") == "trial_finished"}
    assert claimed - finished, "the hanging trial should be in flight"
    # faulthandler wiring: the hard-fault file was armed next to the dump
    assert os.path.exists(flight_path + ".faults")

    # --postmortem renders it (the golden-substring contract)
    assert report_main(["--postmortem", flight_path]) == 0
    out = capsys.readouterr().out
    assert "reason=signal:SIGTERM" in out
    assert "open spans at death" in out
    assert "evaluate" in out
    assert "in-flight trials" in out
    assert "last records" in out


# ---------------------------------------------------------------------------
# post-mortem renderer (unit)
# ---------------------------------------------------------------------------


def test_render_postmortem_sections():
    t0 = 1000.0
    recs = [
        {"kind": "span", "name": "suggest", "ts": t0 - 5.0,
         "wall_sec": 0.2},
        {"kind": "trial_event", "event": "trial_new", "tid": 3,
         "ts": t0 - 4.0},
        {"kind": "trial_event", "event": "trial_claimed", "tid": 3,
         "ts": t0 - 3.5},
        {"kind": "stall", "ts": t0 - 1.0, "quiet_sec": 1.0,
         "quiet_for_sec": 2.5, "stall_count": 1,
         "stacks": {"MainThread": ["f.py:1 hang"]},
         "last_heartbeats": {}},
        {"kind": "flight_dump", "reason": "signal:SIGTERM", "ts": t0,
         "pid": 42, "n_records": 4},
        {"kind": "open_span", "name": "evaluate", "ts": t0 - 3.0,
         "age_sec": 3.0, "thread": "MainThread"},
        {"kind": "last_heartbeats", "ts": t0, "beats": {
            "driver.allgather": {"age_sec": 2.0, "ts": t0 - 2.0,
                                 "detail": {"point": "losses",
                                            "mark": "pre"}}}},
    ]
    text = render_postmortem(recs, name="child.flight.jsonl")
    assert "reason=signal:SIGTERM" in text
    assert "evaluate" in text and "open for" in text
    assert "driver.allgather" in text and '"point": "losses"' in text
    assert "STALL" in text or "stall record" in text
    assert "tid      3" in text and "claimed" in text
    assert "f.py:1 hang" in text


def test_render_postmortem_tolerates_plain_stream():
    # a live (non-dump) stream still renders — no flight_dump header
    text = render_postmortem([
        {"kind": "span", "name": "suggest", "ts": 1.0, "wall_sec": 0.1}])
    assert "no flight_dump header" in text


# ---------------------------------------------------------------------------
# trace export + validator
# ---------------------------------------------------------------------------


def test_export_trace_single_stream_validates(tmp_path):
    path = str(tmp_path / "run.jsonl")
    fmin(quad, SPACE, algo=rand.suggest, max_evals=5,
         rstate=np.random.default_rng(0), show_progressbar=False, obs=path)
    out = str(tmp_path / "run.trace.json")
    assert report_main(["--export-trace", out, path]) == 0
    assert validate_trace.validate_file(out) == []
    with open(out) as f:
        trace = json.load(f)
    events = trace["traceEvents"]
    spans = [e for e in events if e["ph"] == "X"]
    assert {"run", "suggest", "evaluate"} <= {e["name"] for e in spans}
    trials = [e for e in events if e.get("cat") == "trial"]
    assert len(trials) >= 10  # new/claimed/finished per trial
    # process metadata names the stream
    meta = [e for e in events if e["ph"] == "M"
            and e["name"] == "process_name"]
    assert meta and meta[0]["args"]["name"] == "run.jsonl"


def test_export_trace_merged_controllers_validates(tmp_path):
    from hyperopt_tpu.obs import ObsConfig, RunObs
    from hyperopt_tpu.obs.health import controller_stream_path
    from hyperopt_tpu.parallel.driver import fmin_multihost

    base = str(tmp_path / "mh.jsonl")
    streams = []
    for pidx in range(2):
        p = controller_stream_path(base, pidx)
        obs = RunObs(ObsConfig(level="trace", jsonl_path=p),
                     run_id=f"mh-p{pidx}")
        fmin_multihost(quad, SPACE, max_evals=4, batch=2, seed=0, obs=obs,
                       _force_single=True)
        streams.append(p)
    out = str(tmp_path / "mh.trace.json")
    assert report_main(["--merge", "--export-trace", out] + streams) == 0
    assert validate_trace.validate_file(out) == []
    with open(out) as f:
        events = json.load(f)["traceEvents"]
    # controllers land in separate track groups, each named after its file
    assert {e["pid"] for e in events} == {0, 1}
    names = {e["args"]["name"] for e in events
             if e["ph"] == "M" and e["name"] == "process_name"}
    assert names == {"mh.p0.jsonl", "mh.p1.jsonl"}
    # propose/evaluate/fold spans exist per controller
    for pid in (0, 1):
        spans = {e["name"] for e in events
                 if e["ph"] == "X" and e["pid"] == pid}
        assert {"propose", "evaluate", "fold"} <= spans


def test_validator_rejects_broken_traces():
    # every pid carrying timeline events must be a NAMED track group (the
    # merged host+device lint) — as the real exporter always emits
    ok = [{"name": "process_name", "ph": "M", "pid": 0, "tid": 0,
           "args": {"name": "p"}},
          {"name": "a", "ph": "X", "ts": 1.0, "dur": 2.0,
           "pid": 0, "tid": 0}]
    assert validate_trace.validate_events(ok) == []
    # non-monotonic ts on one track
    bad_ts = ok + [{"name": "b", "ph": "X", "ts": 0.5, "dur": 1.0,
                    "pid": 0, "tid": 0}]
    assert any("backwards" in e for e in validate_trace.validate_events(bad_ts))
    # negative duration
    bad_dur = [{"name": "a", "ph": "X", "ts": 1.0, "dur": -2.0,
                "pid": 0, "tid": 0}]
    assert any("bad dur" in e for e in validate_trace.validate_events(bad_dur))
    # unmatched B/E
    dangling = [{"name": "a", "ph": "B", "ts": 1.0, "pid": 0, "tid": 0}]
    assert any("unclosed" in e
               for e in validate_trace.validate_events(dangling))
    orphan_e = [{"name": "a", "ph": "E", "ts": 1.0, "pid": 0, "tid": 0}]
    assert any("E without" in e
               for e in validate_trace.validate_events(orphan_e))
    # unknown phase
    assert any("unknown ph" in e for e in validate_trace.validate_events(
        [{"name": "a", "ph": "Z", "ts": 1.0, "pid": 0, "tid": 0}]))


# ---------------------------------------------------------------------------
# filestore: flight dumps as attachments
# ---------------------------------------------------------------------------


def test_fileworker_retains_watchdog_and_arms_flight(tmp_path):
    from hyperopt_tpu.obs.watchdog import get_watchdog
    from hyperopt_tpu.worker import FileWorker

    wd = get_watchdog()
    before = wd._active if wd is not None else None
    w = FileWorker(str(tmp_path / "store"))
    try:
        # the worker's crash dump lands inside the store it serves
        assert w.flight_dump.startswith(
            os.path.join(str(tmp_path / "store"), "attachments"))
        assert w.flight_dump in get_flight()._targets
        if wd is not None:
            # a standalone worker counts as a live run, or stall detection
            # would silently no-op in worker processes
            assert wd._active == before + 1
    finally:
        get_flight().remove_target(w.flight_dump)
        if wd is not None:
            wd.release()


def test_filestore_flight_dump_attachment_roundtrip(tmp_path):
    from hyperopt_tpu.filestore import FileStore

    store = FileStore(str(tmp_path / "store"))
    path = store.flight_dump_path("host:123")
    assert os.path.dirname(path).endswith("attachments")
    assert ":" not in os.path.basename(path)
    fr = FlightRecorder()
    fr.record({"kind": "event", "name": "worker_died", "ts": 1.0})
    fr.dump("signal:SIGKILL-adjacent", path=path)
    dumps = store.read_flight_dumps()
    assert list(dumps) == ["host-123"]
    assert any(r.get("name") == "worker_died" for r in dumps["host-123"])
    # arm_flight registers the store path on the global recorder
    armed = store.arm_flight("host:456")
    assert armed in get_flight()._targets
    get_flight().remove_target(armed)  # leave the global state clean
