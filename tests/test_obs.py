"""The run-telemetry subsystem (hyperopt_tpu/obs/): span tracer, metrics
registry, trial-lifecycle event log, report renderer, and the
instrumentation wired through all four execution paths.

All tier-1 (CPU, fast): JSONL round-trips use tmp_path, the FileStore
kill-and-reload test drops every live object before re-opening the store.
"""

import json
import pickle
import subprocess
import sys

import numpy as np
import pytest

from hyperopt_tpu import Trials, fmin, hp
from hyperopt_tpu.algos import rand
from hyperopt_tpu.obs import (
    EventLog,
    JsonlSink,
    ObsConfig,
    PhaseTimings,
    RunObs,
    Tracer,
    get_metrics,
    read_jsonl,
    reset_metrics,
)
from hyperopt_tpu.obs.events import (
    TRIAL_CLAIMED,
    TRIAL_FINISHED,
    TRIAL_NEW,
    TRIAL_RECLAIMED,
    FileEventSink,
    load_events,
)
from hyperopt_tpu.obs.metrics import MetricsRegistry
from hyperopt_tpu.obs.report import main as report_main, render
from hyperopt_tpu.utils import LRUCache

SPACE = {"x": hp.uniform("x", -5, 5)}


def quad(d):
    return (d["x"] - 1.0) ** 2


# ---------------------------------------------------------------------------
# trace: span nesting + JSONL round-trip
# ---------------------------------------------------------------------------


def test_span_nesting_jsonl_roundtrip(tmp_path):
    path = tmp_path / "spans.jsonl"
    tr = Tracer(sink=JsonlSink(path), run_id="t1")
    with tr.span("outer", gen=3):
        with tr.span("inner_a"):
            pass
        with tr.span("inner_b"):
            pass
    recs = read_jsonl(path)
    assert [r["name"] for r in recs] == ["inner_a", "inner_b", "outer"]
    by_name = {r["name"]: r for r in recs}
    outer = by_name["outer"]
    assert outer["depth"] == 0 and outer["parent_id"] is None
    assert outer["attrs"] == {"gen": 3}
    for child in ("inner_a", "inner_b"):
        assert by_name[child]["parent_id"] == outer["span_id"]
        assert by_name[child]["depth"] == 1
    # children closed before the parent: wall clocks nest
    assert outer["wall_sec"] >= by_name["inner_a"]["wall_sec"]
    assert all(r["wall_sec"] >= 0 and r["cpu_sec"] >= 0 for r in recs)
    assert all(r["run_id"] == "t1" for r in recs)


def test_span_aggregates_into_totals():
    totals = PhaseTimings()
    tr = Tracer(totals=totals)
    for _ in range(3):
        with tr.span("suggest"):
            pass
    with tr.span("run", aggregate=False):  # umbrella: excluded from totals
        pass
    assert totals["suggest"]["count"] == 3
    assert "run" not in totals
    fracs = sum(e["frac"] for e in totals.summary().values())
    assert fracs == pytest.approx(1.0)


def test_span_records_error_and_unwinds(tmp_path):
    tr = Tracer(sink=JsonlSink(tmp_path / "err.jsonl"))
    with pytest.raises(ValueError):
        with tr.span("boom"):
            raise ValueError("x")
    # stack unwound: the next span is top-level again
    with tr.span("after") as s:
        assert s.depth == 0
    recs = read_jsonl(tmp_path / "err.jsonl")
    assert {r["name"]: r.get("error") for r in recs} == {
        "boom": "ValueError", "after": None}


def test_jsonl_skips_torn_final_line(tmp_path):
    path = tmp_path / "torn.jsonl"
    with open(path, "w") as f:
        f.write(json.dumps({"kind": "span", "name": "a"}) + "\n")
        f.write('{"kind": "span", "name": "b", "wal')  # killed mid-write
    recs = read_jsonl(path)
    assert len(recs) == 1 and recs[0]["name"] == "a"


# ---------------------------------------------------------------------------
# metrics: snapshot determinism
# ---------------------------------------------------------------------------


def _feed(reg):
    reg.counter("jobs").inc(5)
    reg.gauge("depth").set(3)
    h = reg.histogram("lat")
    for v in [0.1, 0.2, 0.3, 0.4, 0.5]:
        h.observe(v)


def test_metrics_snapshot_deterministic():
    a, b = MetricsRegistry("ns"), MetricsRegistry("ns")
    _feed(a)
    _feed(b)
    assert a.snapshot() == b.snapshot()
    assert a.to_json() == b.to_json()
    snap = a.snapshot()
    assert snap["metrics"]["jobs"] == 5
    assert snap["metrics"]["depth"] == 3
    lat = snap["metrics"]["lat"]
    assert lat["count"] == 5 and lat["min"] == 0.1 and lat["max"] == 0.5
    assert lat["p50"] == pytest.approx(0.3)


def test_histogram_bounded_memory():
    h = MetricsRegistry("ns").histogram("x", maxlen=16)
    for i in range(10_000):
        h.observe(float(i))
    s = h.snapshot()
    assert s["count"] == 10_000  # running stats exact over the full stream
    assert s["min"] == 0.0 and s["max"] == 9999.0
    assert len(h._ring) == 16  # percentile buffer stays bounded


def test_registry_process_global_per_namespace():
    reset_metrics("t-global")
    get_metrics("t-global").counter("c").inc()
    assert get_metrics("t-global").counter("c").value == 1
    reset_metrics("t-global")
    assert get_metrics("t-global").counter("c").value == 0


# ---------------------------------------------------------------------------
# events: durable log persists through FileStore kill-and-reload
# ---------------------------------------------------------------------------


def test_event_log_file_sink_roundtrip(tmp_path):
    path = tmp_path / "ev.jsonl"
    log = EventLog(sink=FileEventSink(path))
    log.emit(TRIAL_NEW, 7)
    log.emit(TRIAL_FINISHED, 7, status="ok", sec=0.5)
    recs = load_events(path)
    assert [r["event"] for r in recs] == [TRIAL_NEW, TRIAL_FINISHED]
    assert recs[1]["tid"] == 7 and recs[1]["status"] == "ok"


def test_filestore_events_survive_kill_and_reload(tmp_path):
    from hyperopt_tpu.filestore import FileStore

    root = str(tmp_path / "store")
    store = FileStore(root)
    [tid] = store.new_trial_ids(1)
    doc = {"state": 0, "tid": tid, "misc": {"tid": tid}, "result": {},
           "owner": None, "book_time": None, "refresh_time": None,
           "version": 0, "spec": None, "exp_key": None}
    store.write_doc(doc)
    claimed = store.reserve(owner="w1")
    assert claimed["tid"] == tid
    store.finish(claimed, result={"loss": 1.0, "status": "ok"})
    del store, claimed  # the writing process "dies"

    reopened = FileStore(root)
    events = reopened.read_events()
    seq = [r["event"] for r in events if r["tid"] == tid]
    assert seq == [TRIAL_NEW, TRIAL_CLAIMED, TRIAL_FINISHED]
    finished = [r for r in events if r["event"] == TRIAL_FINISHED][0]
    assert finished["status"] == "ok" and finished["owner"] == "w1"
    # the log rides the attachments namespace (a real FileStore attachment)
    assert "obs_events.jsonl" in reopened.attachment_names()


def test_filestore_reclaim_emits_event(tmp_path):
    from hyperopt_tpu.filestore import FileStore

    store = FileStore(str(tmp_path / "store"))
    [tid] = store.new_trial_ids(1)
    doc = {"state": 0, "tid": tid, "misc": {"tid": tid}, "result": {},
           "owner": None, "book_time": None, "refresh_time": None,
           "version": 0, "spec": None, "exp_key": None}
    store.write_doc(doc)
    store.reserve(owner="w1")
    n = store.reclaim_stale(reserve_timeout=0.0)  # heartbeat instantly stale
    assert n == 1
    reclaims = [r for r in store.read_events()
                if r["event"] == TRIAL_RECLAIMED]
    assert len(reclaims) == 1 and reclaims[0]["tid"] == tid


# ---------------------------------------------------------------------------
# PhaseTimings back-compat: trials.phase_timings through the tracer
# ---------------------------------------------------------------------------


def test_phase_timings_backcompat_and_pickle():
    t = Trials()
    fmin(quad, SPACE, algo=rand.suggest, max_evals=8, trials=t,
         rstate=np.random.default_rng(0), show_progressbar=False)
    pt = t.phase_timings
    assert isinstance(pt, PhaseTimings)
    assert pt["suggest"]["count"] >= 1 and pt["evaluate"]["count"] >= 1
    assert "run" not in pt  # the umbrella span stays out of phase totals
    # historical import path still resolves (old pickles reference it)
    from hyperopt_tpu.fmin import PhaseTimings as FminPhaseTimings

    assert FminPhaseTimings is PhaseTimings
    t2 = pickle.loads(pickle.dumps(t))
    assert t2.phase_timings["suggest"]["count"] == pt["suggest"]["count"]
    # a resumed fmin keeps accumulating into the unpickled dict
    fmin(quad, SPACE, algo=rand.suggest, max_evals=10, trials=t2,
         rstate=np.random.default_rng(1), show_progressbar=False)
    assert t2.phase_timings["suggest"]["count"] > pt["suggest"]["count"]


# ---------------------------------------------------------------------------
# end-to-end: armed fmin -> JSONL stream -> report
# ---------------------------------------------------------------------------


def test_fmin_obs_stream_and_report(tmp_path, capsys):
    path = str(tmp_path / "run.jsonl")
    t = Trials()
    fmin(quad, SPACE, algo=rand.suggest, max_evals=6, trials=t,
         rstate=np.random.default_rng(0), show_progressbar=False, obs=path)
    recs = read_jsonl(path)
    kinds = {r["kind"] for r in recs}
    assert {"span", "trial_event", "metrics"} <= kinds
    spans = {r["name"] for r in recs if r["kind"] == "span"}
    assert {"run", "suggest", "evaluate", "refresh"} <= spans
    events = [r for r in recs if r["kind"] == "trial_event"]
    assert sum(r["event"] == TRIAL_NEW for r in events) == 6
    assert sum(r["event"] == TRIAL_FINISHED for r in events) == 6
    snap = [r for r in recs if r["kind"] == "metrics"][-1]["snapshot"]
    assert snap["metrics"]["trials.completed"] == 6
    assert "suggest" in snap["phase_timings"]

    assert report_main([path]) == 0
    out = capsys.readouterr().out
    assert "phase-time breakdown" in out
    assert "suggest" in out
    assert "trial-state waterfall" in out
    assert "trial_finished=6" in out


def test_report_module_cli(tmp_path):
    path = str(tmp_path / "run.jsonl")
    fmin(quad, SPACE, algo=rand.suggest, max_evals=3,
         rstate=np.random.default_rng(0), show_progressbar=False, obs=path)
    proc = subprocess.run(
        [sys.executable, "-m", "hyperopt_tpu.obs.report", path, "--top", "2"],
        capture_output=True, text=True,
        env={**__import__("os").environ, "JAX_PLATFORMS": "cpu"},
    )
    assert proc.returncode == 0, proc.stderr[-500:]
    assert "slowest trials" in proc.stdout


def test_obs_env_flag_arms_stream(tmp_path, monkeypatch):
    path = str(tmp_path / "env.jsonl")
    monkeypatch.setenv("HYPEROPT_TPU_OBS", path)
    cfg = ObsConfig.from_env()
    assert cfg.level == "trace" and cfg.jsonl_path == path
    fmin(quad, SPACE, algo=rand.suggest, max_evals=3,
         rstate=np.random.default_rng(0), show_progressbar=False)
    assert any(r["kind"] == "span" for r in read_jsonl(path))


def test_device_loop_obs_compile_execute_split(tmp_path):
    # the device-stepped loop decomposes suggest into compile vs execute
    from hyperopt_tpu.zoo import ZOO

    dom = ZOO["branin"]
    path = str(tmp_path / "dev.jsonl")
    from hyperopt_tpu.algos import tpe

    t = Trials()
    fmin(dom.objective, dom.space, algo=tpe.suggest, max_evals=12, trials=t,
         device_loop=True, rstate=np.random.default_rng(0),
         show_progressbar=False, obs=path)
    dev = get_metrics("device").snapshot()["metrics"]
    assert dev["chunk.execute_sec"]["count"] >= 1
    assert "chunk.compile_sec" in dev or dev["run_cache.hits"] >= 1
    assert {"run_cache.hits", "run_cache.misses"} <= set(dev)
    snap = [r for r in read_jsonl(path) if r["kind"] == "metrics"][-1]
    assert "device" in snap["snapshot"]["shared"]


def test_executor_metrics_and_events():
    from hyperopt_tpu.parallel.executor import ExecutorTrials

    t = ExecutorTrials(n_workers=2)
    try:
        fmin(quad, SPACE, algo=rand.suggest, max_evals=6, trials=t,
             rstate=np.random.default_rng(0), show_progressbar=False)
    finally:
        t.shutdown()
    m = t.metrics.snapshot()["metrics"]
    assert m["trials.completed"] == 6
    assert m["dispatched"] == 6
    assert m["n_workers"] == 2
    assert m["trial_sec"]["count"] == 6
    seq = [r["event"] for r in t.obs_events.records() if r["tid"] == 0]
    assert seq[0] == TRIAL_NEW and TRIAL_FINISHED in seq


def test_multihost_single_obs(tmp_path):
    from hyperopt_tpu.parallel.driver import fmin_multihost

    ck = str(tmp_path / "ck.pkl")
    path = str(tmp_path / "mh.jsonl")
    r = fmin_multihost(quad, SPACE, max_evals=8, batch=4, seed=0,
                       checkpoint_file=ck, obs=path, _force_single=True)
    assert r.n_evals == 8
    recs = read_jsonl(path)
    spans = {s["name"] for s in recs if s["kind"] == "span"}
    assert {"propose", "evaluate", "fold"} <= spans
    snap = [x for x in recs if x["kind"] == "metrics"][-1]["snapshot"]
    assert snap["metrics"]["generations"] == 2
    assert snap["metrics"]["checkpoint.save_sec"]["count"] == 2


# ---------------------------------------------------------------------------
# LRUCache hardening (ADVICE.md round 5)
# ---------------------------------------------------------------------------


def test_lru_cache_rejects_degenerate_maxsize():
    with pytest.raises(AssertionError):
        LRUCache(0)
    with pytest.raises(AssertionError):
        LRUCache(-3)


def test_lru_cache_stored_none_is_a_hit():
    c = LRUCache(2)
    c.put("k", None)
    sentinel = object()
    assert c.get("k", default=sentinel) is None  # hit, not the default
    assert c.get("absent", default=sentinel) is sentinel
    assert c.hits == 1 and c.misses == 1
    assert c.stats() == {"hits": 1, "misses": 1, "size": 1, "maxsize": 2}


def test_lru_cache_eviction_and_overwrite():
    c = LRUCache(2)
    c.put("a", 1)
    c.put("b", 2)
    c.put("a", 10)  # overwrite must not evict "b"
    assert c.get("b") == 2
    c.put("c", 3)  # evicts the least-recently-used ("a")
    assert c.get("a") is None
    assert c.get("b") == 2 and c.get("c") == 3


# ---------------------------------------------------------------------------
# report renderer unit coverage
# ---------------------------------------------------------------------------


def test_render_handles_empty_sections():
    text = render([{"kind": "span", "name": "solo", "ts": 0.0,
                    "wall_sec": 1.0, "cpu_sec": 0.5, "span_id": 1,
                    "parent_id": None, "depth": 0}])
    assert "solo" in text
    assert "no trial events" in text


def test_render_waterfall_latencies():
    recs = []
    for tid, (t_new, t_claim, t_done) in enumerate(
            [(0.0, 1.0, 3.0), (0.0, 2.0, 7.0)]):
        recs.append({"kind": "trial_event", "event": TRIAL_NEW,
                     "tid": tid, "ts": t_new})
        recs.append({"kind": "trial_event", "event": TRIAL_CLAIMED,
                     "tid": tid, "ts": t_claim})
        recs.append({"kind": "trial_event", "event": TRIAL_FINISHED,
                     "tid": tid, "ts": t_done, "status": "ok"})
    text = render(recs, top=1)
    assert "queue (new->claimed)" in text
    assert "run (claimed->finished)" in text
    assert "tid      1" in text  # the 5s trial is the slowest
    assert "tid      0" not in text.split("slowest trials")[1].split("==")[0]


def test_runobs_resolve_passthrough():
    r = RunObs(ObsConfig(level="basic"))
    assert RunObs.resolve(r) is r
    r2 = RunObs.resolve(None)
    assert isinstance(r2, RunObs)
    with pytest.raises(TypeError):
        ObsConfig.resolve(123)
