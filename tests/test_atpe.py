"""Adaptive-TPE tests.

Parity target: ``hyperopt/tests/test_atpe_basic.py`` (smoke: models load,
suggest runs) — extended here with predictor-behavior checks, since our
predictor is an analytic rule set rather than shipped lightgbm binaries
(see hyperopt_tpu/algos/atpe.py module docstring).
"""

import numpy as np
import pytest

from hyperopt_tpu import Trials, fmin, hp
from hyperopt_tpu.algos import atpe, rand, tpe
from hyperopt_tpu.base import Domain
from hyperopt_tpu.spaces import compile_space
from hyperopt_tpu.zoo import ZOO


def _feats(**over):
    base = {"n_trials": 50, "loss_spread": 0.5, "recent_improvement": 0.5,
            "fail_frac": 0.0}
    base.update(over)
    return base


def _space_feats(**over):
    base = {"n_params": 4, "n_conditional": 0, "frac_conditional": 0.0,
            "frac_log": 0.0, "frac_discrete": 0.0, "max_cond_depth": 0}
    base.update(over)
    return base


# ---------------------------------------------------------------------------
# featurizers
# ---------------------------------------------------------------------------


def test_featurize_space_counts_families_and_conditionals():
    cs = compile_space({
        "lr": hp.loguniform("lr", -6, 0),
        "n": hp.randint("n", 1, 9),
        "arch": hp.choice("arch", [
            {"w": hp.uniform("w", 0, 1)},
            {"d": hp.qloguniform("d", 0, 3, 1)},
        ]),
    })
    f = atpe.featurize_space(cs)
    assert f["n_params"] == 5  # lr, n, arch, w, d
    assert f["n_conditional"] == 2  # w and d live under arch branches
    assert 0 < f["frac_log"] <= 0.5  # lr and d
    assert 0 < f["frac_discrete"]  # n and arch's selector
    assert f["max_cond_depth"] == 1


def test_featurize_trials_signals():
    t = Trials()
    fmin(lambda d: (d["x"] - 1.0) ** 2, {"x": hp.uniform("x", -5, 5)},
         algo=rand.suggest, max_evals=20, trials=t,
         rstate=np.random.default_rng(0), show_progressbar=False)
    f = atpe.featurize_trials(t)
    assert f["n_trials"] == 20
    assert 0.0 <= f["loss_spread"] <= 1.0
    assert 0.0 <= f["recent_improvement"] <= 1.0
    assert f["fail_frac"] == 0.0


# ---------------------------------------------------------------------------
# predictor: monotonicities + bucketing (the cache-friendliness contract)
# ---------------------------------------------------------------------------


def test_gamma_widens_when_stuck_and_sharpens_on_progress():
    stuck = atpe.predict_tpe_params(
        _space_feats(), _feats(recent_improvement=0.0, loss_spread=0.0))
    progressing = atpe.predict_tpe_params(
        _space_feats(), _feats(recent_improvement=1.0, loss_spread=0.8))
    assert stuck["gamma"] > progressing["gamma"]
    for p in (stuck, progressing):
        assert 0.1 <= p["gamma"] <= 0.5


def test_candidates_scale_with_dimensionality():
    small = atpe.predict_tpe_params(_space_feats(n_params=1), _feats())
    big = atpe.predict_tpe_params(_space_feats(n_params=30), _feats())
    assert big["n_EI_candidates"] >= small["n_EI_candidates"]
    for p in (small, big):
        n = p["n_EI_candidates"]
        assert 32 <= n <= 512 and (n & (n - 1)) == 0  # power-of-two bucket


def test_forgetting_window_tracks_history():
    short = atpe.predict_tpe_params(_space_feats(), _feats(n_trials=10))
    long = atpe.predict_tpe_params(_space_feats(), _feats(n_trials=400))
    assert short["linear_forgetting"] == 25  # never below reference default
    assert long["linear_forgetting"] > short["linear_forgetting"]


def test_startup_grows_with_conditionality():
    flat = atpe.predict_tpe_params(_space_feats(n_params=6), _feats())
    cond = atpe.predict_tpe_params(
        _space_feats(n_params=6, frac_conditional=0.8), _feats())
    assert cond["n_startup_jobs"] >= flat["n_startup_jobs"]


def test_predicted_cfgs_are_bucketed_for_jit_cache():
    # sweep a realistic trajectory of history features: the number of DISTINCT
    # kernel cfgs must stay small, else every suggest call recompiles
    # (ADVICE.md round-3 medium finding)
    rng = np.random.default_rng(0)
    cfgs = set()
    for n in range(20, 400, 7):
        tf = _feats(n_trials=n,
                    loss_spread=float(rng.uniform(0, 1)),
                    recent_improvement=float(rng.uniform(0, 1)))
        p = atpe.predict_tpe_params(_space_feats(), tf)
        cfgs.add((p["gamma"], p["n_EI_candidates"], p["linear_forgetting"],
                  p["prior_weight"]))
    assert len(cfgs) <= 40  # coarse buckets, not a fresh cfg per call


# ---------------------------------------------------------------------------
# end-to-end
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("domain", ["branin", "distractor"])
def test_atpe_suggest_end_to_end(domain):
    dom = ZOO[domain]
    t = Trials()
    n_kernels_before = len(tpe._suggest_jit_cache)
    best = fmin(dom.objective, dom.space, algo=atpe.suggest, max_evals=40,
                trials=t, rstate=np.random.default_rng(0),
                show_progressbar=False)
    assert len(t) == 40
    assert best
    losses = [l for l in t.losses() if l is not None]
    assert min(losses) < losses[0] + 1e-9  # improved (or started at) the best
    # bounded compile count: the bucketed cfgs must not blow up the jit cache
    assert len(tpe._suggest_jit_cache) - n_kernels_before <= 6


def test_atpe_optimizer_overrides_win():
    dom = ZOO["branin"]
    t = Trials()
    opt = atpe.ATPEOptimizer(n_EI_candidates=64, gamma=0.3)
    domain = Domain(dom.objective, dom.space)
    rec = opt.recommend(domain, t)
    assert rec["n_EI_candidates"] == 64 and rec["gamma"] == 0.3


def test_predict_is_budget_aware():
    # round-5: random startup must never eat more than ~a fifth of a known
    # eval budget (the round-4 rule spent up to 60 of 75 evals exploring)
    wide_cond = _space_feats(n_params=25, frac_conditional=0.9)
    no_budget = atpe.predict_tpe_params(wide_cond, _feats(n_trials=0))
    assert no_budget["n_startup_jobs"] >= 40  # the unconstrained rule
    capped = atpe.predict_tpe_params(
        wide_cond, {**_feats(n_trials=0), "budget": 75})
    assert capped["n_startup_jobs"] <= 15
    # and fmin actually surfaces the budget on the trials object
    import numpy as np

    from hyperopt_tpu import Trials, fmin
    from hyperopt_tpu.zoo import ZOO

    t = Trials()
    dom = ZOO["quadratic1"]
    fmin(dom.objective, dom.space, algo=atpe.suggest, max_evals=25, trials=t,
         rstate=np.random.default_rng(0), show_progressbar=False)
    assert t.max_evals_hint == 25
    assert atpe.featurize_trials(t)["budget"] == 25


def test_predict_gamma_and_candidates_bounded():
    # gamma adaptation clips at 0.35 and n_EI_candidates no longer ramps
    # with history length (both measured hurting low-dim domains, BASELINE.md)
    stuck_small = atpe.predict_tpe_params(
        _space_feats(n_params=2),
        _feats(n_trials=70, loss_spread=0.05, recent_improvement=0.0))
    assert stuck_small["gamma"] <= 0.35 + 1e-9
    early = atpe.predict_tpe_params(_space_feats(n_params=2), _feats(n_trials=20))
    late = atpe.predict_tpe_params(_space_feats(n_params=2), _feats(n_trials=70))
    assert early["n_EI_candidates"] == late["n_EI_candidates"]
