"""Durable file-store backend tests.

Parity target: ``hyperopt/tests/test_mongoexp.py`` doctrine — REAL worker
subprocesses against one shared store (the reference spawns a real mongod +
real ``hyperopt-mongo-worker`` processes; here the store is a directory and
the workers are ``python -m hyperopt_tpu.worker``), atomic reserve with no
double-claim, heartbeats, worker-crash reclaim, attachments.
"""

import datetime
import os
import pickle
import subprocess
import sys
import threading
import time

import cloudpickle
import numpy as np
import pytest

from hyperopt_tpu import JOB_STATE_DONE, JOB_STATE_NEW, JOB_STATE_RUNNING, fmin, hp
from hyperopt_tpu.algos import rand, tpe
from hyperopt_tpu.base import Domain, coarse_utcnow
from hyperopt_tpu.filestore import FileStore, FileTrials
from hyperopt_tpu.worker import FileWorker

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SPACE = {"x": hp.uniform("x", -5, 5)}


def _worker_env():
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)  # never claim the real chip
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    return env


def _spawn_worker(store, *extra):
    return subprocess.Popen(
        [sys.executable, "-m", "hyperopt_tpu.worker", "--store", str(store),
         "--reserve-timeout", "20", "--poll-interval", "0.1",
         "--heartbeat-interval", "0.2", "--stale-after", "5", *extra],
        env=_worker_env(), cwd=REPO,
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
    )


def _insert_new(trials, domain, n, seed=0):
    ids = trials.new_trial_ids(n)
    docs = rand.suggest(ids, domain, trials, seed)
    trials.insert_trial_docs(docs)
    return ids


# ---------------------------------------------------------------------------
# store primitives
# ---------------------------------------------------------------------------


def test_counter_is_cross_process_monotonic(tmp_path):
    store = FileStore(tmp_path / "s")
    a = store.new_trial_ids(3)
    b = FileStore(tmp_path / "s").new_trial_ids(2)  # second handle, same dir
    assert a == [0, 1, 2] and b == [3, 4]


def test_reserve_is_single_claim(tmp_path):
    t = FileTrials(tmp_path / "s")
    domain = Domain(lambda d: d["x"] ** 2, SPACE)
    _insert_new(t, domain, 20)
    store = t.store
    claimed = []
    lock = threading.Lock()

    def grab():
        while True:
            doc = store.reserve("t")
            if doc is None:
                return
            with lock:
                claimed.append(doc["tid"])

    threads = [threading.Thread(target=grab) for _ in range(8)]
    [th.start() for th in threads]
    [th.join() for th in threads]
    assert sorted(claimed) == list(range(20))  # every job claimed exactly once


def test_stale_running_doc_is_reclaimed(tmp_path):
    t = FileTrials(tmp_path / "s")
    domain = Domain(lambda d: d["x"] ** 2, SPACE)
    _insert_new(t, domain, 1)
    store = t.store
    doc = store.reserve("dead-worker")
    assert doc is not None
    # fake an old heartbeat
    doc["refresh_time"] = coarse_utcnow() - datetime.timedelta(seconds=120)
    store.write_doc(doc)
    assert store.count(JOB_STATE_NEW) == 0
    assert store.reclaim_stale(30) == 1
    assert store.count(JOB_STATE_NEW) == 1
    assert store.count(JOB_STATE_RUNNING) == 0
    # a live heartbeat is NOT reclaimed
    doc2 = store.reserve("live-worker")
    store.heartbeat(doc2)
    assert store.reclaim_stale(30) == 0


def test_in_process_worker_evaluates(tmp_path):
    t = FileTrials(tmp_path / "s")
    domain = Domain(lambda d: (d["x"] - 1.0) ** 2, SPACE)
    t.attachments["FMinIter_Domain"] = cloudpickle.dumps(domain)
    _insert_new(t, domain, 3)
    w = FileWorker(str(tmp_path / "s"), poll_interval=0.05)
    for _ in range(3):
        assert w.run_one(reserve_timeout=5)
    t.refresh()
    assert t.count_by_state_unsynced(JOB_STATE_DONE) == 3
    assert all(np.isfinite(l) for l in t.losses())


# ---------------------------------------------------------------------------
# real worker subprocesses (mongo-worker doctrine)
# ---------------------------------------------------------------------------


def test_fmin_with_real_worker_subprocesses(tmp_path):
    store = tmp_path / "s"
    t = FileTrials(store)
    workers = [_spawn_worker(store) for _ in range(2)]
    try:
        best = fmin(lambda d: (d["x"] - 1.0) ** 2, SPACE, algo=rand.suggest,
                    max_evals=12, trials=t, max_queue_len=4,
                    rstate=np.random.default_rng(0), show_progressbar=False)
    finally:
        for w in workers:
            w.terminate()
            w.wait(timeout=10)
    assert len(t) == 12
    assert t.count_by_state_unsynced(JOB_STATE_DONE) == 12
    assert "x" in best
    owners = {d["owner"] for d in t.trials}
    assert owners  # workers stamped their identity


def test_fmin_tpe_with_real_workers_and_crash_recovery(tmp_path):
    # one worker is killed -9 mid-trial; its claim goes stale, is reclaimed,
    # and the run still completes (the mongo worker-crash doctrine)
    store = tmp_path / "s"
    flag = tmp_path / "slow.flag"
    flag.write_text("1")

    def obj(d, _flag=str(flag)):
        import os as _os
        import time as _time

        if _os.path.exists(_flag):
            _time.sleep(30)  # the trial the victim worker hangs on
        return (d["x"] - 1.0) ** 2

    t = FileTrials(store)
    victim = _spawn_worker(store, "--stale-after", "1")
    result = {}

    def drive():
        result["best"] = fmin(obj, SPACE, algo=tpe.suggest, max_evals=25,
                              trials=t, max_queue_len=4,
                              rstate=np.random.default_rng(0),
                              show_progressbar=False)

    driver = threading.Thread(target=drive)
    driver.start()
    # wait for the victim to claim a job, then kill it hard
    deadline = time.time() + 30
    while time.time() < deadline and t.store.count(JOB_STATE_RUNNING) == 0:
        time.sleep(0.1)
    assert t.store.count(JOB_STATE_RUNNING) > 0, "victim never claimed a job"
    victim.kill()
    victim.wait(timeout=10)
    flag.unlink()  # remaining trials evaluate fast
    rescuer = _spawn_worker(store, "--stale-after", "1")
    try:
        driver.join(timeout=120)
        assert not driver.is_alive(), "fmin did not finish after crash recovery"
    finally:
        rescuer.terminate()
        rescuer.wait(timeout=10)
    assert t.count_by_state_unsynced(JOB_STATE_DONE) == 25
    assert "best" in result


def test_filetrials_is_durable_across_handles(tmp_path):
    store = tmp_path / "s"
    t = FileTrials(store)
    domain = Domain(lambda d: d["x"] ** 2, SPACE)
    t.attachments["FMinIter_Domain"] = cloudpickle.dumps(domain)
    _insert_new(t, domain, 4)
    w = FileWorker(str(store), poll_interval=0.05)
    for _ in range(4):
        w.run_one(reserve_timeout=5)
    # a brand-new handle (fresh process analog) sees everything
    t2 = FileTrials(store)
    assert len(t2) == 4
    assert t2.count_by_state_unsynced(JOB_STATE_DONE) == 4
    assert t2.losses() == pytest.approx(t2.losses())
    h = t2.padded_history(("x",))
    assert h["n"] == 4


def test_store_cancel_and_reclaim_to_cancel(tmp_path):
    from hyperopt_tpu import JOB_STATE_CANCEL

    store = FileStore(tmp_path / "s")
    t = FileTrials(tmp_path / "s")
    domain = Domain(lambda d: d["x"] ** 2, SPACE)
    _insert_new(t, domain, 3)
    # cancel a NEW doc directly
    tids = sorted(d["tid"] for d in t.store.load_all())
    assert t.store.cancel(tids[0])
    # claim one, age its heartbeat, reclaim straight to CANCEL
    doc = t.store.reserve("test-owner")
    assert doc is not None
    doc["refresh_time"] = coarse_utcnow() - datetime.timedelta(seconds=60)
    t.store.heartbeat(doc)  # writes the stale refresh_time back
    doc["refresh_time"] = coarse_utcnow() - datetime.timedelta(seconds=60)
    import pickle as _p

    from hyperopt_tpu.filestore import _atomic_write

    _atomic_write(t.store._path(JOB_STATE_RUNNING, doc["tid"]), _p.dumps(doc))
    assert t.store.reclaim_stale(30, to_cancel=True) == 1
    t.refresh()
    states = {d["tid"]: d["state"] for d in t.store.load_all()}
    assert list(states.values()).count(JOB_STATE_CANCEL) == 2
    # cancelled docs surface as loss-less fails, not crashes
    assert t.count_by_state_unsynced(JOB_STATE_CANCEL) == 2
    # cancel_unfinished sweeps the remaining NEW doc
    t.cancel_unfinished()
    assert t.count_by_state_unsynced(JOB_STATE_CANCEL) == 3
    assert t.count_by_state_unsynced([JOB_STATE_NEW, JOB_STATE_RUNNING]) == 0


def test_finish_after_cancel_drops_result_no_duplicate(tmp_path):
    # the cancel-vs-finish race: the driver cancels a RUNNING trial while the
    # worker is still evaluating.  finish() must lose the rename-claim and
    # drop its result — the tid must appear exactly once, as CANCEL.
    from hyperopt_tpu import JOB_STATE_CANCEL

    t = FileTrials(tmp_path / "s")
    domain = Domain(lambda d: d["x"] ** 2, SPACE)
    _insert_new(t, domain, 1)
    doc = t.store.reserve("worker")
    assert t.store.cancel(doc["tid"])  # driver-side timeout fires
    assert t.store.finish(doc, result={"loss": 1.0, "status": "ok"}) is False
    docs = t.store.load_all()
    assert len(docs) == 1 and docs[0]["state"] == JOB_STATE_CANCEL
    # and the reverse interleaving: finish wins, cancel finds nothing
    _insert_new(t, domain, 1)
    doc2 = t.store.reserve("worker")
    assert t.store.finish(doc2, result={"loss": 2.0, "status": "ok"}) is True
    assert not t.store.cancel(doc2["tid"])
    states = [d["state"] for d in t.store.load_all() if d["tid"] == doc2["tid"]]
    assert states == [JOB_STATE_DONE]


def test_load_all_dedupes_by_state_precedence(tmp_path):
    # a residual race can leave one tid in two directories; readers must see
    # exactly one doc, preferring the more-terminal state
    from hyperopt_tpu import JOB_STATE_CANCEL

    t = FileTrials(tmp_path / "s")
    domain = Domain(lambda d: d["x"] ** 2, SPACE)
    _insert_new(t, domain, 1)
    doc = t.store.reserve("worker")
    # forge the duplicate: same tid in both running/ and cancel/
    dup = dict(doc, state=JOB_STATE_CANCEL)
    from hyperopt_tpu.filestore import _atomic_write

    _atomic_write(t.store._path(JOB_STATE_CANCEL, doc["tid"]), pickle.dumps(dup))
    docs = t.store.load_all()
    assert len(docs) == 1 and docs[0]["state"] == JOB_STATE_CANCEL
    # DONE shadows CANCEL (finished work keeps its result)
    done = dict(doc, state=JOB_STATE_DONE, result={"loss": 0.5, "status": "ok"})
    _atomic_write(t.store._path(JOB_STATE_DONE, doc["tid"]), pickle.dumps(done))
    docs = t.store.load_all()
    assert len(docs) == 1 and docs[0]["state"] == JOB_STATE_DONE
    t.refresh()
    assert len(t) == 1


def test_orphan_claim_is_swept_back_to_new(tmp_path):
    # a crash between the finish()/cancel()/reclaim_stale() rename-claim and
    # the terminal write leaves a '*.pkl.finish.<pid>'-style claim that
    # load_all ignores — the trial would vanish from every state (advisor
    # finding, round 4).  reclaim_stale must recover aged claims to NEW.
    t = FileTrials(tmp_path / "s")
    domain = Domain(lambda d: d["x"] ** 2, SPACE)
    _insert_new(t, domain, 2)
    doc = t.store.reserve("worker")
    run_path = t.store._path(JOB_STATE_RUNNING, doc["tid"])
    claim = f"{run_path}.finish.99999"
    os.rename(run_path, claim)  # simulated crash mid-finish
    assert all(d["tid"] != doc["tid"] for d in t.store.load_all())  # vanished
    # fresh claims are not touched (a live transition may be in flight)
    assert t.store.reclaim_stale(30) == 0
    assert os.path.exists(claim)
    # age it past the reserve timeout -> recovered to NEW for re-evaluation
    old = time.time() - 120
    os.utime(claim, (old, old))
    assert t.store.reclaim_stale(30) == 1
    recovered = [d for d in t.store.load_all() if d["tid"] == doc["tid"]]
    assert len(recovered) == 1 and recovered[0]["state"] == JOB_STATE_NEW
    assert not os.path.exists(claim)
    # an orphaned CANCEL claim completes its transition to CANCEL — a
    # cancelled job must never be resurrected to NEW and re-run
    from hyperopt_tpu import JOB_STATE_CANCEL

    doc2 = t.store.reserve("worker")
    run2 = t.store._path(JOB_STATE_RUNNING, doc2["tid"])
    claim2 = f"{run2}.cancel.88888"
    os.rename(run2, claim2)  # simulated crash mid-cancel
    os.utime(claim2, (old, old))
    assert t.store.reclaim_stale(30) == 1
    got = [d for d in t.store.load_all() if d["tid"] == doc2["tid"]]
    assert len(got) == 1 and got[0]["state"] == JOB_STATE_CANCEL
    assert got[0]["result"]["status"] == "fail"
    # an unreadable aged claim is removed (nothing left to preserve)
    junk = os.path.join(t.store.root, "running", "7.pkl.cancel.12345")
    with open(junk, "wb") as f:
        f.write(b"\x00not-a-pickle")
    os.utime(junk, (old, old))
    assert t.store.reclaim_stale(30) == 0
    assert not os.path.exists(junk)


def test_cancel_leaves_unreadable_claim_for_sweep(tmp_path):
    # cancel() reading back None must NOT delete the claim (the read may have
    # raced a partial write); it leaves it for the orphan sweep instead.
    t = FileTrials(tmp_path / "s")
    domain = Domain(lambda d: d["x"] ** 2, SPACE)
    _insert_new(t, domain, 1)
    tid = t.store.load_all()[0]["tid"]
    new_path = t.store._path(JOB_STATE_NEW, tid)
    with open(new_path, "wb") as f:
        f.write(b"\x00truncated")  # corrupt doc
    assert t.store.cancel(tid) is False
    claims = [f for f in os.listdir(os.path.join(t.store.root, "new"))
              if ".pkl.cancel." in f]
    assert len(claims) == 1  # preserved, not destroyed


def test_ctrl_checkpoint_survives_worker_crash(tmp_path):
    # MongoCtrl.checkpoint doctrine: a worker checkpoints a partial result,
    # then dies -9; the partial must survive in the store — reclaimed doc
    # (CANCEL here, so the trial is not silently re-run) still carries it
    from hyperopt_tpu import JOB_STATE_CANCEL, fmin_pass_expr_memo_ctrl

    store = tmp_path / "s"
    t = FileTrials(store)

    @fmin_pass_expr_memo_ctrl
    def obj(expr, memo, ctrl):
        ctrl.checkpoint({"status": "ok", "partial_steps": 7})
        time.sleep(60)  # killed long before this returns
        return {"status": "ok", "loss": 0.0}

    domain = Domain(obj, SPACE)
    t.attachments["FMinIter_Domain"] = cloudpickle.dumps(domain)
    _insert_new(t, domain, 1)
    victim = _spawn_worker(store, "--stale-after", "1")
    try:
        deadline = time.time() + 30
        seen = False
        while time.time() < deadline and not seen:
            docs = t.store.load_all()
            seen = any(
                d["state"] == JOB_STATE_RUNNING
                and d.get("result", {}).get("partial_steps") == 7
                for d in docs
            )
            time.sleep(0.1)
        assert seen, "checkpointed partial result never reached the store"
    finally:
        victim.kill()
        victim.wait(timeout=10)
    time.sleep(1.5)  # age the last heartbeat past stale-after
    assert t.store.reclaim_stale(1.0, to_cancel=True) == 1
    docs = t.store.load_all()
    assert len(docs) == 1
    assert docs[0]["state"] == JOB_STATE_CANCEL
    assert docs[0]["result"]["partial_steps"] == 7  # survived the crash


def test_filetrials_pickle_roundtrip(tmp_path):
    t = FileTrials(tmp_path / "s")
    domain = Domain(lambda d: d["x"] ** 2, SPACE)
    _insert_new(t, domain, 2)
    t2 = pickle.loads(pickle.dumps(t))
    assert t2.store.root == t.store.root
    assert t2.count_by_state_unsynced(JOB_STATE_NEW) == 2


def test_randomized_concurrent_storm_no_trial_lost(tmp_path):
    """Property-style race test: threads hammer ONE store with random
    reserve/finish/heartbeat/cancel/reclaim interleavings (including
    immediate-staleness reclaims, which force the finish-vs-reclaim and
    heartbeat-vs-reclaim races on purpose).  Afterwards the safety
    invariants of the rename protocol must hold: every inserted trial
    exists EXACTLY once (state precedence collapses transient duplicates),
    in a legal state, with no claim files left behind and no thread having
    seen an exception.  The at-least-once semantics (a reclaimed trial may
    be evaluated twice; the loser's finish is dropped) are the documented
    contract — what must never happen is a lost or double-counted tid."""
    from hyperopt_tpu.base import (JOB_STATE_CANCEL, JOB_STATE_ERROR,
                                   JOB_STATE_NEW)

    store = FileStore(tmp_path / "storm")
    N = 48
    tids = store.new_trial_ids(N)
    for tid in tids:
        store.write_doc({
            "state": JOB_STATE_NEW, "tid": tid, "spec": None, "result": {},
            "misc": {"tid": tid, "cmd": None, "idxs": {}, "vals": {}},
            "exp_key": None, "owner": None, "version": 0,
            "book_time": None, "refresh_time": None,
        })

    stop = threading.Event()
    errors = []

    def worker(seed):
        rng = np.random.default_rng(seed)
        held = []
        try:
            while not stop.is_set():
                op = int(rng.integers(10))
                if op < 4:
                    d = store.reserve(f"w{seed}")
                    if d is not None:
                        held.append(d)
                elif op < 6 and held:
                    d = held.pop(int(rng.integers(len(held))))
                    if rng.integers(2):
                        store.finish(d, result={"loss": 1.0, "status": "ok"})
                    else:
                        store.finish(d, error=RuntimeError("storm"))
                elif op < 7 and held:
                    store.heartbeat(held[-1])
                elif op < 8:
                    store.cancel(int(rng.integers(N)))
                else:
                    # reserve_timeout=0 treats EVERY running doc as stale:
                    # the adversarial schedule for the claim protocol
                    store.reclaim_stale(
                        0 if rng.integers(2) else 30,
                        to_cancel=bool(rng.integers(2)))
        except Exception:  # pragma: no cover - the assertion target
            import traceback

            errors.append(traceback.format_exc())

    threads = [threading.Thread(target=worker, args=(s,)) for s in range(4)]
    for t in threads:
        t.start()
    time.sleep(6.0)
    stop.set()
    for t in threads:
        t.join(timeout=30)
        assert not t.is_alive()
    assert not errors, errors

    # drain: everything still NEW/RUNNING settles via the public API
    store.reclaim_stale(0, to_cancel=True)   # running -> cancel
    while True:
        d = store.reserve("drainer")
        if d is None:
            break
        store.finish(d, result={"loss": 0.0, "status": "ok"})
    store.reclaim_stale(0, to_cancel=True)

    docs = store.load_all()
    seen = [d["tid"] for d in docs]
    assert sorted(seen) == sorted(tids), f"lost={set(tids) - set(seen)}"
    legal = {JOB_STATE_DONE, JOB_STATE_ERROR, JOB_STATE_CANCEL, JOB_STATE_NEW,
             JOB_STATE_RUNNING}
    for d in docs:
        assert d["state"] in legal
    # PHYSICAL uniqueness, not the precedence-collapsed view load_all gives:
    # after the drain the zombie guards (_settled checks in reserve/
    # reclaim/sweep) must have converged every tid to exactly one state
    # directory — precedence dedup is for transient races, not steady state
    locs = {}
    for d in ("new", "running", "done", "error", "cancel"):
        for f in os.listdir(tmp_path / "storm" / d):
            if f.endswith(".pkl"):
                locs.setdefault(int(f[:-4]), []).append(d)
    assert sorted(locs) == sorted(tids)
    dups = {t: ds for t, ds in locs.items() if len(ds) > 1}
    assert not dups, dups
    # no claim files left anywhere (finish/reclaim/cancel all cleaned up or
    # were swept by the orphan sweep)
    leftovers = [
        os.path.join(dirpath, f)
        for dirpath, _, files in os.walk(tmp_path / "storm")
        for f in files
        if ".pkl." in f
    ]
    assert not leftovers, leftovers
