"""Search-health diagnostics, device-utilization accounting, and the
multi-controller telemetry merge (hyperopt_tpu/obs/health.py + the armed
suggest paths).

All tier-1 (CPU, fast).  The two load-bearing invariants pinned here:

* disarmed runs are untouched — the TPE hot path compiles the same
  program under the same jit cache key and fetches no extra buffers;
* armed and disarmed runs propose IDENTICAL trials — the diagnostics are
  pure post-processing, no extra RNG.
"""

import functools
import json
import os
import subprocess
import sys

import numpy as np
import pytest

from hyperopt_tpu import Trials, fmin, hp
from hyperopt_tpu.algos import anneal, rand, tpe
from hyperopt_tpu.base import Domain
from hyperopt_tpu.fmin import FMinIter
from hyperopt_tpu.obs import ObsConfig, get_metrics, read_jsonl, reset_metrics
from hyperopt_tpu.obs.health import (
    HEALTH_STATS,
    controller_stream_path,
    live_health_postfix,
    utilization_snapshot,
)
from hyperopt_tpu.obs.report import main as report_main, render, render_merged
from hyperopt_tpu.progress import format_postfix

SPACE = {"hx": hp.uniform("hx", -5, 5), "hy": hp.uniform("hy", 0, 10),
         "hc": hp.choice("hc", [0.0, 1.0, 2.0])}


def objective(d):
    return (d["hx"] - 1.0) ** 2 + (d["hy"] - 3.0) ** 2 + d["hc"]


TPE = functools.partial(tpe.suggest, n_startup_jobs=4, prior_eps=0.3)


def _run(obs=None, seed=0, max_evals=10, algo=TPE, **kw):
    t = Trials()
    fmin(objective, SPACE, algo=algo, max_evals=max_evals, trials=t,
         rstate=np.random.default_rng(seed), show_progressbar=False,
         obs=obs, **kw)
    return t


# ---------------------------------------------------------------------------
# the disarmed hot path is untouched
# ---------------------------------------------------------------------------


def test_tpe_disarmed_hot_path_no_extra_compile(tmp_path):
    cache = tpe._suggest_jit_cache._d
    before = set(cache)
    _run(obs=None, seed=1)
    disarmed_keys = set(cache) - before
    # exactly one new fused program, under the historical 2-tuple key —
    # no health marker, hence no diagnostics outputs in its signature
    assert len(disarmed_keys) == 1
    (key,) = disarmed_keys
    assert len(key) == 2 and "health" not in key
    # a second disarmed run reuses it (no recompile)
    _run(obs=None, seed=2)
    assert set(cache) - before == disarmed_keys
    # arming compiles the diagnostics variant under its OWN key and leaves
    # the disarmed entry alone
    _run(obs=str(tmp_path / "armed.jsonl"), seed=3)
    armed_keys = set(cache) - before - disarmed_keys
    assert len(armed_keys) == 1
    (akey,) = armed_keys
    assert akey[-1] == "health" and akey[:2] == key


def test_tpe_armed_matches_disarmed_proposals(tmp_path):
    t_plain = _run(obs=None, seed=7)
    t_armed = _run(obs=str(tmp_path / "run.jsonl"), seed=7)
    assert t_plain.losses() == t_armed.losses()
    for a, b in zip(t_plain.trials, t_armed.trials):
        assert a["misc"]["vals"] == b["misc"]["vals"]


# ---------------------------------------------------------------------------
# armed TPE: health records + metrics + report section
# ---------------------------------------------------------------------------


def test_tpe_health_stream_metrics_and_report(tmp_path, capsys):
    path = str(tmp_path / "run.jsonl")
    t = _run(obs=path, seed=0, max_evals=12)
    recs = read_jsonl(path)
    health = [r for r in recs if r["kind"] == "health"]
    assert health, "armed TPE run emitted no health records"
    tpe_recs = [r for r in health if r["algo"] == "tpe"]
    assert len(tpe_recs) == 12 - 4  # one per post-startup ask (queue 1)
    r = tpe_recs[0]
    for name in HEALTH_STATS:
        if name != "prior_take":
            assert name in r, name
    assert r["n_below"] >= 1 and r["n_below"] + r["n_above"] >= 4
    assert set(r["labels"]) == {"hx", "hy", "hc"}
    assert 0.0 <= r["dup_rate"] <= 1.0
    assert r["labels"]["hx"]["eff_components"] >= 1.0
    assert 0.0 < r["labels"]["hx"]["prior_mass_frac"] <= 1.0

    # metrics namespace carries the aggregates (snapshot embedded in stream)
    snap = [x for x in recs if x["kind"] == "metrics"][-1]["snapshot"]
    m = snap["metrics"]
    assert m["health.asks"] == len(tpe_recs)
    assert m["health.ei_p50"]["count"] == len(tpe_recs)
    assert "health.prior_fallbacks" in m
    assert m["health.n_below"] >= 1

    # report renders the search-health section
    assert report_main([path]) == 0
    out = capsys.readouterr().out
    assert "search health" in out
    assert "EI p50" in out and "dup rate" in out
    assert "prior fallback" in out
    assert "below/above split" in out
    assert t.losses()  # the run itself behaved


def test_tpe_health_deterministic_across_seeded_runs(tmp_path):
    paths = [str(tmp_path / f"run{i}.jsonl") for i in (1, 2)]
    for p in paths:
        _run(obs=p, seed=42, max_evals=10)

    def health_of(p):
        out = []
        for r in read_jsonl(p):
            if r["kind"] == "health":
                r = dict(r)
                r.pop("ts")       # wall clock differs
                r.pop("run_id")   # process-global counter differs
                out.append(r)
        return out

    a, b = health_of(paths[0]), health_of(paths[1])
    assert a and a == b


# ---------------------------------------------------------------------------
# rand / anneal: the cheap subset
# ---------------------------------------------------------------------------


def test_rand_health_cheap_subset(tmp_path):
    path = str(tmp_path / "rand.jsonl")
    _run(obs=path, algo=rand.suggest, max_evals=8, max_queue_len=4)
    health = [r for r in read_jsonl(path) if r["kind"] == "health"]
    assert health and all(r["algo"] == "rand" for r in health)
    r = health[0]
    assert r["n"] >= 2
    assert set(r["labels"]) == {"hx", "hy", "hc"}
    assert 0.0 <= r["dup_rate"] <= 1.0 and r["spread"] >= 0.0
    # prior draws over a continuous space should not collapse
    assert r["labels"]["hx"]["spread"] > 0.0


def test_anneal_health_cheap_subset(tmp_path):
    path = str(tmp_path / "anneal.jsonl")
    _run(obs=path, algo=anneal.suggest, max_evals=8, max_queue_len=4)
    health = [r for r in read_jsonl(path) if r["kind"] == "health"]
    assert health and all(r["algo"] == "anneal" for r in health)


def test_rand_queue1_records_no_degenerate_health(tmp_path):
    # a width-1 batch has no dup/spread to speak of: nothing is recorded
    path = str(tmp_path / "rand1.jsonl")
    _run(obs=path, algo=rand.suggest, max_evals=4, max_queue_len=1)
    assert [r for r in read_jsonl(path) if r["kind"] == "health"] == []


# ---------------------------------------------------------------------------
# report golden renders
# ---------------------------------------------------------------------------


def _health_rec(**over):
    rec = {"kind": "health", "algo": "tpe", "ts": 1.0, "run_id": "r",
           "n": 1, "n_label_proposals": 2, "n_below": 2, "n_above": 6,
           "prior_takes": 0, "ei_p10": -1.0, "ei_p50": 0.5, "ei_p90": 1.0,
           "ei_max": 1.5, "sel_rank": 0.0, "dup_rate": 0.0,
           "eff_components": 3.0, "prior_mass_frac": 0.5,
           "labels": {
               "x": {"ei_p50": 0.5, "dup_rate": 0.0,
                     "eff_components": 3.0, "prior_mass_frac": 0.5},
               "y": {"ei_p50": 0.5, "dup_rate": 0.0,
                     "eff_components": 3.0, "prior_mass_frac": 0.5},
           }}
    rec.update(over)
    return rec


def test_report_health_section_golden():
    recs = [
        _health_rec(),
        _health_rec(ts=2.0, ei_p50=0.9, dup_rate=0.25, prior_takes=1,
                    n_below=3, n_above=5,
                    labels={"x": {"ei_p50": 0.9, "dup_rate": 0.25,
                                  "eff_components": 4.0,
                                  "prior_mass_frac": 0.33},
                            "y": {"ei_p50": 0.9, "dup_rate": 0.25,
                                  "eff_components": 4.0,
                                  "prior_mass_frac": 0.33}}),
    ]
    lines = render(recs).splitlines()
    health = lines[lines.index("== search health " + "=" * 47):]
    assert health[1] == "  asks: tpe=2"
    assert health[2] == "  EI p50        first +0.5  last +0.9  ▁█"
    assert health[3] == "  EI sel rank   mean 0.00  (0 = pure argmax)"
    assert health[4] == "  dup rate      first 0.0%  last 25.0%  ▁█"
    assert health[5] == "  prior fallback  1/4 label-proposals  ▁█"
    assert health[6] == "  below/above split (last ask): 3/5"
    assert health[7] == "  per-param (last ask):"
    assert health[8] == "    x  eff_comp 4.0  prior_mass 0.33  dup 25.0%"
    assert health[9] == "    y  eff_comp 4.0  prior_mass 0.33  dup 25.0%"


def _controller_stream(path, pid, ag_mean):
    rid = f"mh-p{pid}"
    with open(path, "w") as f:
        def w(r):
            f.write(json.dumps(r) + "\n")

        w({"kind": "event", "name": "controller", "ts": 1.0, "run_id": rid,
           "attrs": {"pid": pid, "n_processes": 2}})
        for gen in range(2):
            for j, (name, wall) in enumerate(
                    [("propose", 0.1), ("evaluate", 0.2 + pid * 0.1),
                     ("fold", 0.01)]):
                w({"kind": "span", "name": name, "ts": 1.0 + gen + j * 0.1,
                   "wall_sec": wall, "cpu_sec": wall / 2,
                   "span_id": gen * 3 + j + 1, "parent_id": None,
                   "depth": 0, "run_id": rid})
        w({"kind": "event", "name": "controller_divergence", "ts": 3.0,
           "run_id": rid,
           "attrs": {"pid": pid, "gen": 2, "n_done": 8,
                     "checksums": ["0xa", "0xb"]}})
        h = {"count": 2, "sum": ag_mean * 2, "mean": ag_mean, "min": ag_mean,
             "max": ag_mean, "p50": ag_mean, "p90": ag_mean, "p99": ag_mean}
        w({"kind": "metrics", "run_id": rid,
           "snapshot": {"metrics": {"generations": 2,
                                    "allgather.losses_sec": h}}})


def test_report_merge_golden(tmp_path, capsys):
    p0 = str(tmp_path / "mh.p0.jsonl")
    p1 = str(tmp_path / "mh.p1.jsonl")
    _controller_stream(p0, 0, 0.010)
    _controller_stream(p1, 1, 0.025)
    assert report_main(["--merge", p0, p1]) == 0
    out = capsys.readouterr().out
    assert "== controllers" in out
    assert "mh.p0.jsonl  run_id=mh-p0  gens=2  spans=6" in out
    assert ("  allgather.losses_sec       mh.p0.jsonl 10.0ms  "
            "mh.p1.jsonl 25.0ms  skew 15.0ms (2.5x)") in out
    assert "== per-controller phase breakdown" in out
    assert "gen=2 n_done=8: reported by mh.p0.jsonl, mh.p1.jsonl" in out


def test_report_merge_of_real_streams(tmp_path, capsys):
    # two real (single-controller) fmin_multihost streams merge cleanly
    from hyperopt_tpu.parallel.driver import fmin_multihost

    def quad(d):
        return (d["hx"] - 1.0) ** 2

    paths = []
    for i in (0, 1):
        p = str(tmp_path / f"run.p{i}.jsonl")
        fmin_multihost(quad, {"hx": hp.uniform("hx", -5, 5)}, max_evals=8,
                       batch=4, seed=i, obs=p, _force_single=True)
        paths.append(p)
    assert report_main(["--merge"] + paths) == 0
    out = capsys.readouterr().out
    assert "run.p0.jsonl" in out and "run.p1.jsonl" in out
    assert "gens=2" in out
    assert "propose" in out and "fold" in out
    assert "no divergence events" in out


def test_report_multiple_streams_require_merge_flag(tmp_path, capsys):
    p = str(tmp_path / "a.jsonl")
    with open(p, "w") as f:
        f.write(json.dumps({"kind": "event", "name": "x", "ts": 0.0}) + "\n")
    assert report_main([p, p]) == 2
    assert "--merge" in capsys.readouterr().err


def test_report_tolerates_truncated_final_line(tmp_path, capsys, caplog):
    path = str(tmp_path / "torn.jsonl")
    with open(path, "w") as f:
        f.write(json.dumps({"kind": "span", "name": "suggest", "ts": 1.0,
                            "wall_sec": 0.5, "cpu_sec": 0.2, "span_id": 1,
                            "parent_id": None, "depth": 0}) + "\n")
        f.write('{"kind": "metrics", "run_id": "r", "snap')  # killed mid-write
    import logging

    with caplog.at_level(logging.WARNING, logger="hyperopt_tpu.obs.trace"):
        assert report_main([path]) == 0
    assert "suggest" in capsys.readouterr().out
    assert any("skipping unparseable JSONL record" in r.message
               for r in caplog.records)


# ---------------------------------------------------------------------------
# RunObs re-entry (iterator-protocol FMinIter)
# ---------------------------------------------------------------------------


def test_runobs_rearm_keeps_counters_across_reentry():
    domain = Domain(objective, SPACE)
    t = Trials()
    it = FMinIter(rand.suggest, domain, t,
                  rstate=np.random.default_rng(0), max_evals=6,
                  show_progressbar=False, obs=ObsConfig(level="basic"))
    rid = it.obs.run_id
    try:
        next(it)  # run(1) -> finish() releases the namespace
        assert it.obs.metrics.counter("trials.completed").value == 1
        # between runs the namespace is released; a by-id lookup would get
        # a fresh empty registry...
        assert get_metrics(rid) is not it.obs.metrics
        # ...and rearm (run() calls it at every entry) re-adopts the
        # bundle's own registry, displacing the imposter
        it.obs.rearm()
        assert get_metrics(rid) is it.obs.metrics
        next(it)  # full re-entry: counters keep accumulating, not dropped
        assert it.obs.metrics.counter("trials.completed").value == 2
    finally:
        reset_metrics(rid)


# ---------------------------------------------------------------------------
# device-utilization accounting
# ---------------------------------------------------------------------------


def test_utilization_snapshot_joins_cost_and_execute():
    from hyperopt_tpu.device_fmin import fmin_device
    from hyperopt_tpu.zoo import ZOO

    dom = ZOO["branin"]
    fmin_device(dom.objective, dom.space, max_evals=16, seed=0)
    dev = get_metrics("device").snapshot()["metrics"]
    if "whole_run.flops" not in dev:
        pytest.skip("backend reports no cost_analysis")
    util = utilization_snapshot(wall_sec=1e9)
    assert "whole_run" in util
    wr = util["whole_run"]
    assert wr["flops_per_dispatch"] > 0
    assert wr["achieved_flops_per_sec"] > 0
    assert 0.0 <= wr["busy_fraction"] <= 1.0
    assert util["device_busy_fraction"] <= 1.0


def test_live_postfix_and_format(tmp_path):
    path = str(tmp_path / "run.jsonl")
    t = _run(obs=path, seed=0, max_evals=8)
    obs = t.obs_health
    s = live_health_postfix(obs)
    assert s is not None and "EI p50" in s and "dup" in s
    full = format_postfix(1.25, obs)
    assert full.startswith("best loss: 1.25") and "EI p50" in full
    # disarmed: exactly the historical string
    assert format_postfix(1.25, None) == "best loss: 1.25"


def test_trials_pickle_drops_obs_health(tmp_path):
    import pickle

    t = _run(obs=str(tmp_path / "run.jsonl"), seed=0, max_evals=6)
    assert t.obs_health is not None
    t2 = pickle.loads(pickle.dumps(t))
    assert not hasattr(t2, "obs_health")
    assert len(t2.trials) == len(t.trials)


# ---------------------------------------------------------------------------
# multi-controller stream naming
# ---------------------------------------------------------------------------


def test_controller_stream_path():
    assert controller_stream_path("run.jsonl", 0) == "run.p0.jsonl"
    assert controller_stream_path("/a/b/run.jsonl", 3) == "/a/b/run.p3.jsonl"
    assert controller_stream_path("run", 1) == "run.p1.jsonl"


# ---------------------------------------------------------------------------
# bench gate
# ---------------------------------------------------------------------------


def _write_bench(dirpath, n, value, vs_baseline, tails=(100.0, 200.0)):
    rec = {"n": n, "parsed": {"metric": "tpe_candidate_proposal_throughput",
                              "value": value, "unit": "candidates/sec",
                              "vs_baseline": vs_baseline},
           "tail": "".join(f'"trials_per_sec": {t},\n' for t in tails)}
    with open(os.path.join(dirpath, f"BENCH_r{n:02d}.json"), "w") as f:
        json.dump(rec, f)


def _gate(tmp_path, *args):
    proc = subprocess.run(
        [sys.executable, os.path.join(os.path.dirname(__file__), os.pardir,
                                      "scripts", "bench_gate.py"),
         "--dir", str(tmp_path), *args],
        capture_output=True, text=True)
    return proc.returncode, proc.stdout + proc.stderr


def test_bench_gate_no_baseline_passes(tmp_path):
    rc, out = _gate(tmp_path)
    assert rc == 0 and "empty" in out
    _write_bench(tmp_path, 1, 1e8, 1000.0)
    rc, out = _gate(tmp_path)
    assert rc == 0 and "no baseline" in out


def test_bench_gate_detects_regression(tmp_path):
    _write_bench(tmp_path, 1, 1e8, 1000.0, tails=(100.0, 200.0))
    _write_bench(tmp_path, 2, 0.5e8, 990.0, tails=(99.0, 198.0))
    rc, out = _gate(tmp_path)
    assert rc == 1
    assert "REGRESSION" in out and "headline.value" in out
    # within-threshold round passes (stage metrics 1% down, headline equal)
    _write_bench(tmp_path, 3, 0.5e8, 990.0, tails=(98.0, 196.0))
    rc, out = _gate(tmp_path)
    assert rc == 0 and "ok" in out


def test_bench_gate_skips_misaligned_stage_sequences(tmp_path):
    _write_bench(tmp_path, 1, 1e8, 1000.0, tails=(100.0,))
    _write_bench(tmp_path, 2, 1e8, 1000.0, tails=(1.0, 1.0))  # new stage
    rc, out = _gate(tmp_path)
    assert rc == 0 and "skipping positional comparison" in out
