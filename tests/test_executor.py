"""Async executor-backend tests (parity targets: hyperopt/tests/test_mongoexp.py
atomic-claim / worker-crash doctrine, hyperopt/tests/test_spark.py parallelism).
"""

import threading
import time

import numpy as np
import pytest

from hyperopt_tpu import (
    JOB_STATE_CANCEL,
    JOB_STATE_DONE,
    JOB_STATE_ERROR,
    STATUS_OK,
    fmin,
    hp,
)
from hyperopt_tpu.algos import rand, tpe
from hyperopt_tpu.parallel import ExecutorTrials


SPACE = {"x": hp.uniform("x", -5, 5)}


def test_async_fmin_end_to_end():
    t = ExecutorTrials(n_workers=4)
    best = fmin(lambda d: (d["x"] - 1.0) ** 2, SPACE, algo=rand.suggest,
                max_evals=16, trials=t, max_queue_len=4,
                rstate=np.random.default_rng(0), show_progressbar=False)
    t.shutdown()
    assert len(t) == 16
    assert t.count_by_state_unsynced(JOB_STATE_DONE) == 16
    assert "x" in best
    # async path went through the cloudpickled domain attachment
    assert isinstance(t.attachments["FMinIter_Domain"], bytes)


def test_async_runs_in_parallel():
    t = ExecutorTrials(n_workers=8)

    def slow(d):
        time.sleep(0.3)
        return d["x"] ** 2

    t0 = time.perf_counter()
    fmin(slow, SPACE, algo=rand.suggest, max_evals=8, trials=t, max_queue_len=8,
         rstate=np.random.default_rng(0), show_progressbar=False)
    dt = time.perf_counter() - t0
    t.shutdown()
    assert len(t) == 8
    # load-insensitive parallelism proof: evaluation intervals must overlap
    # (a wall-clock bound alone flakes on a contended CI machine)
    intervals = sorted(
        (d["book_time"], d["refresh_time"]) for d in t.trials
        if d.get("book_time") and d.get("refresh_time")
    )
    assert len(intervals) == 8
    overlapping = sum(
        1 for (s1, e1), (s2, _) in zip(intervals, intervals[1:]) if s2 < e1
    )
    assert overlapping >= 4, (overlapping, dt)
    # and the wall clock must beat serial (8 x 0.3s) with generous margin
    assert dt < 2.3, dt


def test_async_worker_exception_marks_error():
    t = ExecutorTrials(n_workers=2)

    def flaky(d):
        if d["x"] < 0:
            raise RuntimeError("boom")
        return d["x"]

    fmin(flaky, SPACE, algo=rand.suggest, max_evals=12, trials=t, max_queue_len=4,
         rstate=np.random.default_rng(0), show_progressbar=False)
    t.shutdown()
    states = [d["state"] for d in t._dynamic_trials]
    assert JOB_STATE_ERROR in states  # crashes recorded, driver survived
    assert all(s in (JOB_STATE_DONE, JOB_STATE_ERROR) for s in states)
    errs = [d for d in t._dynamic_trials if d["state"] == JOB_STATE_ERROR]
    assert all("boom" in d["misc"]["error"][1] for d in errs)


def test_async_no_double_claim(tmp_path):
    # the objective is cloudpickled (domain attachment), so closures lose
    # identity — record evaluations through the filesystem instead
    log = tmp_path / "evals.log"
    t = ExecutorTrials(n_workers=8)

    def record(d):
        with open(log, "a") as f:
            f.write(f"{d['x']}\n")
        time.sleep(0.01)
        return d["x"] ** 2

    fmin(record, SPACE, algo=rand.suggest, max_evals=24, trials=t,
         max_queue_len=8, rstate=np.random.default_rng(0), show_progressbar=False)
    t.shutdown()
    # every trial evaluated exactly once despite redundant pool submissions
    assert len(log.read_text().splitlines()) == 24


def test_async_tpe_works():
    t = ExecutorTrials(n_workers=4)
    fmin(lambda d: (d["x"] - 1.0) ** 2, SPACE, algo=tpe.suggest, max_evals=30,
         trials=t, max_queue_len=2, rstate=np.random.default_rng(0),
         show_progressbar=False)
    t.shutdown()
    assert len(t) == 30
    assert min(l for l in t.losses() if l is not None) < 1.0


def test_traceable_batch_eval():
    from hyperopt_tpu.zoo import ZOO

    dom = ZOO["branin"]
    t = ExecutorTrials(n_workers=2, traceable=True)
    fmin(dom.objective, dom.space, algo=rand.suggest, max_evals=16, trials=t,
         max_queue_len=8, rstate=np.random.default_rng(0), show_progressbar=False)
    t.shutdown()
    assert len(t) == 16
    assert all(r["status"] == STATUS_OK for r in t.results)
    # sanity: losses match a host-side recomputation of the same specs
    for d in t.trials[:4]:
        spec = {k: v[0] for k, v in d["misc"]["vals"].items() if v}
        expect = float(dom.objective({"x": spec["x"], "y": spec["y"]}))
        assert d["result"]["loss"] == pytest.approx(expect, rel=1e-4)


def _doc(i, state, loss=None):
    return {
        "tid": i, "spec": None,
        "result": {"status": STATUS_OK, "loss": float(i if loss is None else loss)}
        if state == JOB_STATE_DONE else {"status": "new"},
        "misc": {"tid": i, "cmd": None, "idxs": {"x": [i]}, "vals": {"x": [float(i)]}},
        "state": state, "exp_key": None, "owner": None, "version": 0,
        "book_time": None, "refresh_time": None,
    }


def test_padded_history_folds_out_of_order_completions():
    # a RUNNING doc must NOT hide later DONE docs from the posterior
    # (head-of-line blocking), and must still fold once it completes
    from hyperopt_tpu import Trials
    from hyperopt_tpu.base import JOB_STATE_RUNNING

    t = Trials()
    t.insert_trial_docs(
        [_doc(0, JOB_STATE_DONE), _doc(1, JOB_STATE_RUNNING), _doc(2, JOB_STATE_DONE)]
    )
    t.refresh()
    h = t.padded_history(("x",))
    assert h["n"] == 2  # DONE trials behind the in-flight one are visible
    assert sorted(h["vals"]["x"][:2].tolist()) == [0.0, 2.0]
    # the slow trial completes -> next call folds it too
    t._dynamic_trials[1]["result"] = {"status": STATUS_OK, "loss": 1.0}
    t._dynamic_trials[1]["state"] = JOB_STATE_DONE
    h = t.padded_history(("x",))
    assert h["n"] == 3
    assert h["has_loss"][:3].all()
    assert sorted(h["vals"]["x"][:3].tolist()) == [0.0, 1.0, 2.0]


def test_padded_history_many_stuck_trials_dont_starve_posterior():
    # posterior must see every DONE trial even with several stuck RUNNING docs
    from hyperopt_tpu import Trials
    from hyperopt_tpu.base import JOB_STATE_RUNNING

    t = Trials()
    states = [JOB_STATE_RUNNING if i % 3 == 0 else JOB_STATE_DONE for i in range(30)]
    t.insert_trial_docs([_doc(i, s) for i, s in enumerate(states)])
    t.refresh()
    h = t.padded_history(("x",))
    assert h["n"] == sum(1 for s in states if s == JOB_STATE_DONE)
    # repeated calls are idempotent while nothing settles
    h2 = t.padded_history(("x",))
    assert h2["n"] == h["n"]


def test_insert_before_domain_attachment_not_lost():
    # docs inserted before FMinIter attaches the domain must still run:
    # refresh() redispatches NEW trials once the attachment exists
    t = ExecutorTrials(n_workers=2)
    ids = t.new_trial_ids(2)
    docs = [{
        "tid": i, "spec": None, "result": {"status": "new"},
        "misc": {"tid": i, "cmd": ("domain_attachment", "FMinIter_Domain"),
                 "idxs": {"x": [i]}, "vals": {"x": [float(i)]}},
        "state": 0, "exp_key": None, "owner": None, "version": 0,
        "book_time": None, "refresh_time": None,
    } for i in ids]
    t.insert_trial_docs(docs)  # no domain yet: workers no-op
    time.sleep(0.2)
    assert t.count_by_state_unsynced(JOB_STATE_DONE) == 0

    import cloudpickle

    from hyperopt_tpu import Domain

    t.attachments["FMinIter_Domain"] = cloudpickle.dumps(
        Domain(lambda d: d["x"] ** 2, SPACE)
    )
    deadline = time.time() + 5
    while time.time() < deadline and t.count_by_state_unsynced(JOB_STATE_DONE) < 2:
        t.refresh()
        time.sleep(0.05)
    t.shutdown()
    assert t.count_by_state_unsynced(JOB_STATE_DONE) == 2


def test_executor_trials_pickle_roundtrip():
    import pickle

    t = ExecutorTrials(n_workers=2)
    fmin(lambda d: d["x"] ** 2, SPACE, algo=rand.suggest, max_evals=4, trials=t,
         max_queue_len=2, rstate=np.random.default_rng(0), show_progressbar=False)
    t.shutdown()
    t2 = pickle.loads(pickle.dumps(t))
    assert len(t2) == 4
    assert t2.losses() == t.losses()


def test_per_trial_timeout_sets_cancel_state():
    # SURVEY §2.1 spark row: timeout → JOB_STATE_CANCEL.  A sleeping
    # objective must end CANCEL within the per-trial budget; fast trials
    # complete normally.
    t = ExecutorTrials(n_workers=4, timeout=0.5)

    def sometimes_hangs(d):
        if d["x"] < 0:
            time.sleep(8)
        return d["x"] ** 2

    t0 = time.perf_counter()
    fmin(sometimes_hangs, SPACE, algo=rand.suggest, max_evals=8, trials=t,
         max_queue_len=8, rstate=np.random.default_rng(0),
         show_progressbar=False)
    dt = time.perf_counter() - t0
    t.shutdown(wait=False)
    states = [d["state"] for d in t._dynamic_trials]
    assert JOB_STATE_CANCEL in states
    assert JOB_STATE_DONE in states
    assert dt < 10, f"driver blocked on hung trial for {dt:.1f}s"
    cancelled = [d for d in t._dynamic_trials if d["state"] == JOB_STATE_CANCEL]
    assert all(d["result"]["status"] == "fail" for d in cancelled)
    # losses() treats cancelled trials as loss-less, argmin still works
    assert min(l for l in t.losses() if l is not None) >= 0.0


def test_fmin_timeout_does_not_block_on_hung_trial():
    # fmin(timeout=...) used to stop *asking* but wait forever on in-flight
    # trials; now block_until_done cancels them once the deadline passes
    t = ExecutorTrials(n_workers=2)

    def hang(d):
        time.sleep(8)
        return d["x"]

    t0 = time.perf_counter()
    fmin(hang, SPACE, algo=rand.suggest, max_evals=4, trials=t, timeout=1,
         max_queue_len=2, rstate=np.random.default_rng(0),
         show_progressbar=False, return_argmin=False)
    dt = time.perf_counter() - t0
    t.shutdown(wait=False)
    assert dt < 15, f"fmin blocked {dt:.1f}s past its 1s timeout"
    assert all(
        d["state"] in (JOB_STATE_CANCEL,) for d in t._dynamic_trials
    ), [d["state"] for d in t._dynamic_trials]


def test_dispatch_submits_each_trial_once():
    # insert/refresh used to resubmit every still-NEW doc (O(n^2) submissions
    # over a run); now each doc reaches the pool exactly once
    calls = []

    class Counting(ExecutorTrials):
        def _run_one(self, trial):
            calls.append(trial["tid"])
            super()._run_one(trial)

    t = Counting(n_workers=4)
    fmin(lambda d: d["x"] ** 2, SPACE, algo=rand.suggest, max_evals=16, trials=t,
         max_queue_len=4, rstate=np.random.default_rng(0), show_progressbar=False)
    t.shutdown()
    assert sorted(calls) == sorted(t.tids)


def test_ctrl_checkpoint_partial_survives_error():
    # Ctrl.checkpoint through the async backend: a worker that crashes after
    # checkpointing must leave its partial result on the ERROR doc
    # (SURVEY.md §5 checkpoint row: mid-trial partials persist)
    from hyperopt_tpu import fmin_pass_expr_memo_ctrl

    t = ExecutorTrials(n_workers=2)

    @fmin_pass_expr_memo_ctrl
    def obj(expr, memo, ctrl):
        ctrl.checkpoint({"status": STATUS_OK, "partial_steps": 3})
        raise RuntimeError("crash after checkpoint")

    fmin(obj, SPACE, algo=rand.suggest, max_evals=2, trials=t,
         max_queue_len=2, rstate=np.random.default_rng(0),
         show_progressbar=False, return_argmin=False,
         catch_eval_exceptions=True)
    t.shutdown()
    errored = [d for d in t._dynamic_trials if d["state"] == JOB_STATE_ERROR]
    assert errored, [d["state"] for d in t._dynamic_trials]
    for d in errored:
        assert d["result"]["partial_steps"] == 3
        assert d["misc"]["error"][1] == "crash after checkpoint"


def test_ctrl_checkpoint_partial_survives_cancel():
    # per-trial timeout cancellation must MERGE over a checkpointed partial
    # result, not clobber it
    from hyperopt_tpu import fmin_pass_expr_memo_ctrl

    t = ExecutorTrials(n_workers=2, timeout=0.5)

    @fmin_pass_expr_memo_ctrl
    def obj(expr, memo, ctrl):
        ctrl.checkpoint({"status": STATUS_OK, "partial_steps": 9})
        time.sleep(8)
        return {"status": STATUS_OK, "loss": 1.0}

    fmin(obj, SPACE, algo=rand.suggest, max_evals=2, trials=t, timeout=2,
         max_queue_len=2, rstate=np.random.default_rng(0),
         show_progressbar=False, return_argmin=False)
    t.shutdown(wait=False)
    cancelled = [d for d in t._dynamic_trials if d["state"] == JOB_STATE_CANCEL]
    assert cancelled, [d["state"] for d in t._dynamic_trials]
    for d in cancelled:
        assert d["result"]["partial_steps"] == 9
        assert d["result"]["status"] == "fail"
