"""ISSUE 20: the tenant observatory.

The acceptance pins:

* tenant ids are hostile input: sanitation is a hard 400 (never a 500)
  for control bytes, over-long ids, non-strings and the reserved
  ``other`` bucket — and a 10k-distinct-id cardinality bomb leaves the
  ledger bounded at top-K named rows + ``other``;
* armed attribution + DRR packing NEVER change proposals: armed ==
  disarmed bit-identical, directly and over HTTP — and disarmed really
  is ``scheduler.tenants is None``: zero threads, zero allocations
  traced to the tenant module on the serving path;
* per-tenant admission budgets shed ONE tenant (typed 429 +
  ``Retry-After``) while others keep admitting;
* pre-ISSUE-20 journals (no tenant field on admit records) replay
  bitwise on a tenant-armed scheduler, and a SIGKILLed armed run
  resumes with its tenant table rebuilt from the admit records — no
  new WAL record kinds;
* the surfaces: /tenants, /snapshot + /healthz sections, the
  ``service.tenant.*`` gauge families (scrape-lintable), fleet-merged
  tenant heat, obs.report --tenants, the obs.top TENANT row, Perfetto
  per-tenant counter tracks — and the new bench keys really gate.
"""

import json
import os
import signal
import subprocess
import sys
import threading
import time
import tracemalloc

import pytest

from hyperopt_tpu import hp
from hyperopt_tpu._env import (
    parse_tenant,
    parse_tenant_quota,
    parse_tenant_slo,
    parse_tenant_top_k,
)
from hyperopt_tpu.obs.slo import TENANT_TARGETS, SLOPlane
from hyperopt_tpu.obs.tenant import (
    ANON,
    OTHER,
    TenantLedger,
    merge_status,
    read_tenant_heat,
    sanitize_tenant,
)
from hyperopt_tpu.service.overload import AdmissionGuard, OverloadError
from hyperopt_tpu.service.scheduler import StudyScheduler
from hyperopt_tpu.service.server import ServiceHTTPServer

sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "scripts"))

SPACE = {"x": hp.uniform("x", -5, 5)}
SPACE_SPEC = {"x": {"dist": "uniform", "args": [-5, 5]}}

#: the hostile-id fuzz corpus: every entry must be REJECTED (ValueError
#: direct, 400 over HTTP) without ever minting a ledger row
HOSTILE_IDS = [
    "a" * 129,                     # over the 128 cap
    "evil\nname",                  # header-splitting newline
    "evil\rname",
    "nul\x00byte",
    "tab\tname",                   # control byte (< 32)
    "esc\x1b[31m",                 # terminal escape injection
    "del\x7fchar",
    OTHER,                         # the reserved eviction bucket
    123, 1.5, ["a"], {"t": "x"}, True,   # non-strings
]


def _drive(sched, sid, n):
    seq = []
    for _ in range(n):
        a = sched.ask(sid)[0]
        seq.append((a["tid"], repr(a["params"]["x"])))
        sched.tell(sid, a["tid"], float((a["params"]["x"] - 1.0) ** 2))
    return seq


# ---------------------------------------------------------------------------
# sanitation: tenant ids are hostile input
# ---------------------------------------------------------------------------


def test_sanitize_tenant_contract():
    assert sanitize_tenant(None) == ANON
    assert sanitize_tenant("") == ANON
    assert sanitize_tenant(None, default=None) is None
    assert sanitize_tenant("team-a") == "team-a"
    assert sanitize_tenant("a" * 128) == "a" * 128    # at the cap: fine
    assert sanitize_tenant("Ünïcode-ok") == "Ünïcode-ok"
    for bad in HOSTILE_IDS:
        with pytest.raises((ValueError, TypeError)):
            sanitize_tenant(bad)


def test_hostile_tenant_ids_400_never_500_and_mint_no_rows():
    sched = StudyScheduler(wal=False, quality=False, load=False,
                           tenants=TenantLedger())
    srv = ServiceHTTPServer(0, scheduler=sched, trace=False, slo=False)
    code, r = srv.handle("POST", "/study", {
        "space": SPACE_SPEC, "seed": 1, "n_startup_jobs": 4})
    assert code == 200
    sid = r["study_id"]
    for bad in HOSTILE_IDS:
        if not isinstance(bad, str):
            continue                     # header values are strings
        for method, path, body in (
                ("POST", "/ask", {"study_id": sid}),
                ("GET", "/studies", None),
                ("GET", "/tenants", None),
                ("POST", "/study", {"space": SPACE_SPEC, "seed": 2})):
            code, p = srv.handle(method, path, body,
                                 headers={"x-tenant": bad})
            assert code == 400, (bad, path, code, p)
            assert p["ok"] is False
    # hostile BODY tenants on POST /study: 400, typed, never a study
    for bad in HOSTILE_IDS:
        code, p = srv.handle("POST", "/study", {
            "space": SPACE_SPEC, "seed": 3, "tenant": bad})
        assert code == 400, (bad, code, p)
    # nothing hostile ever minted a ledger row OR a study (the one
    # clean study above files under anon)
    assert set(sched.tenants.status()["table"]) <= {ANON}
    assert len(sched._studies) == 1


def test_cardinality_bomb_stays_bounded_at_top_k_plus_other():
    led = TenantLedger(top_k=16)
    for i in range(10_000):
        led.observe_tick([(f"bot-{i:05d}", 1)], device_sec=0.001)
    st = led.status()
    assert st["tenants"] <= 16 + 1                    # named rows + other
    assert OTHER in st["table"]
    assert st["evictions"] >= 10_000 - 17
    # totals survive eviction: every ask is still accounted somewhere
    assert sum(r["asks"] for r in st["table"].values()) == 10_000
    assert led.device_ms == pytest.approx(10_000 * 1.0)
    # the default bound matches the env knob default
    assert TenantLedger().top_k == parse_tenant_top_k({}) == 64


# ---------------------------------------------------------------------------
# attribution math
# ---------------------------------------------------------------------------


def test_tick_share_attribution_and_request_accounting():
    led = TenantLedger()
    led.note_study("acme")
    led.note_study("acme")
    led.note_study("umbrella")
    # one 4 ms tick: acme asked 3 of the 4 rows, umbrella 1
    led.observe_tick([("acme", 2), ("acme", 1), ("umbrella", 1)],
                     device_sec=0.004, hbm_bytes=400.0)
    a = led.status()["table"]["acme"]
    assert a["device_ms"] == pytest.approx(3.0)
    assert a["asks"] == 3 and a["studies"] == 2
    assert a["hbm_bytes"] == pytest.approx(300.0)
    u = led.status()["table"]["umbrella"]
    assert u["device_ms"] == pytest.approx(1.0)
    assert led.device_ms == pytest.approx(4.0)
    # tells and request-level accounting ride separately
    led.observe_tell("acme")
    led.observe_request("acme", latency_sec=0.010)
    led.observe_request("acme", shed=True)
    a = led.status()["table"]["acme"]
    assert a["tells"] == 1 and a["sheds"] == 1
    assert a["ask_p99_ms"] == pytest.approx(10.0, rel=0.2)
    assert led.sheds == 1
    led.forget_study("umbrella")
    assert led.status()["table"]["umbrella"]["studies"] == 0


def test_drr_order_prefers_the_light_tenant():
    led = TenantLedger()
    for _ in range(50):
        led.observe_tick([("noisy", 4)], device_sec=0.040)
    led.observe_tick([("light", 1)], device_sec=0.001)
    order = led.drr_order(["noisy", "light"])
    assert order[0] == "light"
    # repeated calls stay stable and bounded (the deficit clamp)
    for _ in range(200):
        order = led.drr_order(["noisy", "light", "noisy"])
        assert sorted(order) == ["light", "noisy"]    # deduped
    # degenerate shapes: unknown tenants and singletons never throw
    assert led.drr_order([]) == []
    assert led.drr_order(["solo"]) == ["solo"]
    assert sorted(led.drr_order(["a", "b"])) == ["a", "b"]


def test_merge_status_and_tenant_heat(tmp_path):
    a, b = TenantLedger(), TenantLedger()
    a.observe_tick([("acme", 1)], device_sec=0.009)
    a.observe_tell("acme")
    b.observe_tick([("acme", 1), ("umbrella", 2)], device_sec=0.003)
    m = merge_status([a.status(), b.status(), None])
    assert m["asks"] == 4 and m["tells"] == 1
    assert m["device_ms"] == pytest.approx(12.0)
    assert m["table"]["acme"]["device_ms"] == pytest.approx(10.0)
    assert m["table"]["umbrella"]["device_ms"] == pytest.approx(2.0)
    assert merge_status([]) is None

    # the durable view piggybacks the load plane's heat records: MAX
    # per (shard, tenant) across cumulative snapshots, SUM across shards
    from hyperopt_tpu.obs.load import HeatLedger, heat_path_for

    root = str(tmp_path)
    led = HeatLedger(heat_path_for(root, "rep-a"))
    led.append({"kind": "heat", "replica": "rep-a", "shard": 0,
                "heat_ms": 10.0, "busy_frac": 0.5, "ts": 1.0,
                "tenants": {"acme": 5.0}})
    led.append({"kind": "heat", "replica": "rep-a", "shard": 0,
                "heat_ms": 30.0, "busy_frac": 0.5, "ts": 2.0,
                "tenants": {"acme": 25.0, "umbrella": 2.0}})
    HeatLedger(heat_path_for(root, "rep-b")).append(
        {"kind": "heat", "replica": "rep-b", "shard": 1, "heat_ms": 7.0,
         "busy_frac": 0.1, "ts": 3.0, "tenants": {"acme": 7.0}})
    # pre-ISSUE-20 record (no tenants field): tolerated silently
    HeatLedger(heat_path_for(root, "rep-c")).append(
        {"kind": "heat", "replica": "rep-c", "shard": 2, "heat_ms": 1.0,
         "busy_frac": 0.1, "ts": 4.0})
    heat = read_tenant_heat(root)["tenants"]
    assert heat["acme"] == pytest.approx(32.0)        # max(5,25) + 7
    assert heat["umbrella"] == pytest.approx(2.0)
    assert read_tenant_heat(str(tmp_path / "empty")) == {"tenants": {}}


def test_gauges_publish_flat_names():
    from hyperopt_tpu.obs.metrics import MetricsRegistry

    reg = MetricsRegistry()
    led = TenantLedger(metrics=reg)
    led.observe_tick([("acme", 1)], device_sec=0.002)
    led.observe_request("acme", shed=True)
    led.publish()
    snap = reg.snapshot()["metrics"]
    assert snap["service.tenant.tracked"] == 1
    assert snap["service.tenant.sheds"] == 1
    assert snap["service.tenant.acme.device_ms"] == pytest.approx(2.0)
    assert snap["service.tenant.acme.asks"] == 1


# ---------------------------------------------------------------------------
# armed == disarmed: the observatory never changes proposals
# ---------------------------------------------------------------------------


def test_armed_equals_disarmed_bit_identical():
    on = StudyScheduler(wal=False, quality=False, load=False,
                        tenants=TenantLedger())
    off = StudyScheduler(wal=False, quality=False, load=False,
                         tenants=False)
    assert on.tenants is not None and off.tenants is None
    seqs = {}
    for sched in (on, off):
        a = sched.create_study(SPACE, seed=21, n_startup_jobs=2,
                               study_id="st-a", tenant="acme")
        b = sched.create_study(SPACE, seed=22, n_startup_jobs=2,
                               study_id="st-b", tenant="umbrella")
        seq = []
        for _ in range(6):                 # interleaved: DRR sees both
            seq += _drive(sched, a, 1) + _drive(sched, b, 1)
        seqs[sched is on] = seq
    assert seqs[True] == seqs[False]
    st = on.tenants.status()
    assert st["table"]["acme"]["tells"] == 6
    assert st["table"]["acme"]["device_ms"] > 0.0


def test_armed_equals_disarmed_over_http_and_surfaces():
    def drive(srv, sid, tenant, n):
        seq = []
        for _ in range(n):
            code, a = srv.handle("POST", "/ask", {"study_id": sid},
                                 headers={"x-tenant": tenant})
            assert code == 200
            t = a["trials"][0]
            seq.append((t["tid"], repr(t["params"]["x"])))
            code, _ = srv.handle("POST", "/tell", {
                "study_id": sid, "tid": t["tid"],
                "loss": float((t["params"]["x"] - 1.0) ** 2)},
                headers={"x-tenant": tenant})
            assert code == 200
        return seq

    seqs = {}
    for armed in (True, False):
        sched = StudyScheduler(wal=False, quality=False, load=False,
                               tenants=TenantLedger() if armed else False)
        srv = ServiceHTTPServer(0, scheduler=sched, slo=armed,
                                trace=False)
        code, r = srv.handle("POST", "/study", {
            "space": SPACE_SPEC, "seed": 33, "n_startup_jobs": 2,
            "study_id": "st-h", "tenant": "acme"},
            headers={"x-tenant": "ignored-when-body-wins"})
        assert code == (200 if armed else 200)
        seqs[armed] = drive(srv, r["study_id"], "acme", 8)
        code, ten = srv.handle("GET", "/tenants", None)
        assert code == 200
        if armed:
            assert ten["armed"] and "acme" in ten["table"]
            assert ten["table"]["acme"]["tells"] == 8
            snap = srv.snapshot_dict()
            assert snap["tenants"]["table"]["acme"]["device_ms"] > 0
            hz = srv.healthz_dict()
            assert hz["tenants"]["tracked"] == 1
            code, rows = srv.handle("GET", "/studies", None)
            assert rows["studies"][0]["tenant"] == "acme"
        else:
            assert ten["armed"] is False and "table" not in ten
            assert "tenants" not in srv.snapshot_dict()
    assert seqs[True] == seqs[False]


def test_disarmed_is_none_no_threads_no_tenant_allocations():
    n0 = threading.active_count()
    sched = StudyScheduler(wal=False, quality=False, load=False,
                           tenants=False)
    assert sched.tenants is None
    sid = sched.create_study(SPACE, seed=9, n_startup_jobs=2)
    _drive(sched, sid, 3)                  # compile outside the trace
    tenant_py = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "hyperopt_tpu", "obs", "tenant.py")
    tracemalloc.start()
    try:
        _drive(sched, sid, 3)              # device waves, disarmed
        snap = tracemalloc.take_snapshot()
    finally:
        tracemalloc.stop()
    stats = snap.filter_traces(
        [tracemalloc.Filter(True, tenant_py)]).statistics("filename")
    assert stats == []                     # zero tenant-plane allocations
    # and the armed plane spawns no threads either
    TenantLedger().observe_tick([("a", 1)], device_sec=0.001)
    assert threading.active_count() == n0


def test_tenant_fault_never_fails_the_wave_or_tell():
    sched = StudyScheduler(wal=False, quality=False, load=False,
                           tenants=TenantLedger())

    def boom(*a, **kw):
        raise RuntimeError("tenant ledger exploded")

    sched.tenants.observe_tick = boom
    sched.tenants.observe_tell = boom
    sched.tenants.drr_order = boom
    sched.tenants.note_study = boom
    sid = sched.create_study(SPACE, seed=2, n_startup_jobs=1,
                             tenant="acme")
    seq = _drive(sched, sid, 3)            # asks past startup: device waves
    assert len(seq) == 3
    assert sched._studies[sid].best_loss() is not None


# ---------------------------------------------------------------------------
# per-tenant admission budgets
# ---------------------------------------------------------------------------


def test_admission_guard_per_tenant_budget():
    g = AdmissionGuard(max_queue=100, tenant_quota=2)
    t1 = g.admit_ask(tenant="noisy")
    t2 = g.admit_ask(tenant="noisy")
    with pytest.raises(OverloadError) as ei:
        g.admit_ask(tenant="noisy")
    assert "ask budget" in str(ei.value)
    assert ei.value.retry_after > 0.0
    # ...while every other tenant keeps admitting
    t3 = g.admit_ask(tenant="light")
    t4 = g.admit_ask()                                # anon traffic too
    g.release(t1, tenant="noisy")
    g.release(t2, tenant="noisy")
    g.release(t3, tenant="light")
    g.release(t4)
    # drop-at-zero: the inflight map is bounded by concurrency, not
    # by tenant cardinality
    assert g._tenant_inflight == {}
    g.admit_ask(tenant="noisy")                       # budget freed
    # disarmed (the default): no quota, no map entries
    g2 = AdmissionGuard(max_queue=4)
    assert g2.tenant_quota is None
    for _ in range(4):
        g2.admit_ask(tenant="noisy")
    assert g2._tenant_inflight == {}


def test_per_tenant_429_rides_the_http_path():
    sched = StudyScheduler(wal=False, quality=False, load=False,
                           tenants=TenantLedger())
    srv = ServiceHTTPServer(0, scheduler=sched, trace=False, slo=False)
    srv.guard = AdmissionGuard(max_queue=100, tenant_quota=1,
                               metrics=sched.metrics)
    code, r = srv.handle("POST", "/study", {
        "space": SPACE_SPEC, "seed": 5, "n_startup_jobs": 8,
        "tenant": "noisy"})
    assert code == 200
    sid = r["study_id"]
    held = srv.guard.admit_ask(tenant="noisy")        # hold the budget
    code, p = srv.handle("POST", "/ask", {"study_id": sid},
                         headers={"x-tenant": "noisy"})
    assert code == 429
    assert "ask budget" in p["error"] and p["retry_after"] > 0
    # the other tenant is untouched by noisy's exhaustion
    code, p = srv.handle("POST", "/ask", {"study_id": sid},
                         headers={"x-tenant": "light"})
    assert code == 200
    srv.guard.release(held, tenant="noisy")
    code, _ = srv.handle("POST", "/ask", {"study_id": sid},
                         headers={"x-tenant": "noisy"})
    assert code == 200
    # the shed was attributed to the tenant that caused it
    assert sched.tenants.status()["table"]["noisy"]["sheds"] == 1


# ---------------------------------------------------------------------------
# WAL back-compat + crash-resume
# ---------------------------------------------------------------------------


def test_pre_issue20_journal_replays_bitwise_on_armed_scheduler(tmp_path):
    """A journal written with the tenant plane OFF carries no tenant
    fields at all (the pre-ISSUE-20 shape); an armed scheduler must
    replay it bitwise and file the studies under ``anon``."""
    ref = StudyScheduler(wal=False, tenants=False)
    rsid = ref.create_study(SPACE, seed=7, n_startup_jobs=3)
    want = _drive(ref, rsid, 12)

    wal = str(tmp_path / "wal.jsonl")
    s1 = StudyScheduler(wal=wal, tenants=False)
    sid = s1.create_study(SPACE, seed=7, n_startup_jobs=3,
                          space_spec={"space": SPACE_SPEC},
                          study_id="study-a")
    first = _drive(s1, sid, 7)
    del s1                                            # crash, no drain
    from hyperopt_tpu.service import StudyJournal

    admits = [r for r in StudyJournal(wal).records()
              if r["kind"] == "admit"]
    assert admits and all("tenant" not in (r.get("kwargs") or {})
                          for r in admits)
    s2 = StudyScheduler(wal=wal)                      # tenants armed
    assert s2.tenants is not None
    assert s2.last_resume["errors"] == 0
    rest = _drive(s2, sid, 5)
    assert first + rest == want
    assert s2.tenants.status()["table"][ANON]["studies"] == 1


def test_tenant_stamped_journal_rebuilds_table_on_resume(tmp_path):
    """An armed run's admit records carry the tenant; resume rebuilds
    the attribution table from replay (note_study + observe_tell COUNT
    during replay — replay IS the crash-resume rebuild)."""
    ref = StudyScheduler(wal=False, tenants=False)
    rsid = ref.create_study(SPACE, seed=11, n_startup_jobs=3)
    want = _drive(ref, rsid, 10)

    wal = str(tmp_path / "wal.jsonl")
    s1 = StudyScheduler(wal=wal)
    sid = s1.create_study(SPACE, seed=11, n_startup_jobs=3,
                          space_spec={"space": SPACE_SPEC},
                          study_id="study-t", tenant="acme")
    first = _drive(s1, sid, 6)
    del s1
    from hyperopt_tpu.service import StudyJournal

    admit = next(r for r in StudyJournal(wal).records()
                 if r["kind"] == "admit")
    assert admit["kwargs"]["tenant"] == "acme"        # stamped, optional
    s2 = StudyScheduler(wal=wal)
    row = s2.tenants.status()["table"]["acme"]
    assert row["studies"] == 1 and row["tells"] == 6  # rebuilt via replay
    rest = _drive(s2, sid, 4)
    assert first + rest == want
    # and the tenant column survives onto /studies rows
    assert s2._studies[sid].status_dict()["tenant"] == "acme"


def test_sigkilled_armed_run_resumes_with_tenant_table(tmp_path):
    root = str(tmp_path / "store")
    child = (
        "import sys\n"
        "from hyperopt_tpu import hp\n"
        "from hyperopt_tpu.service.scheduler import StudyScheduler\n"
        "s = StudyScheduler(store_root=sys.argv[1])\n"
        "spec = {'space': {'x': {'dist': 'uniform', 'args': [-5, 5]}}}\n"
        "sid = s.create_study({'x': hp.uniform('x', -5, 5)}, seed=3,\n"
        "                     n_startup_jobs=2, study_id='study-k',\n"
        "                     tenant='acme', space_spec=spec)\n"
        "print('READY', flush=True)\n"
        "while True:\n"
        "    a = s.ask(sid)[0]\n"
        "    s.tell(sid, a['tid'], float(a['params']['x'] ** 2))\n")
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PYTHONPATH=os.pathsep.join(filter(None, (
                   os.path.dirname(os.path.dirname(
                       os.path.abspath(__file__))),
                   os.environ.get("PYTHONPATH")))))
    env.pop("PALLAS_AXON_POOL_IPS", None)
    proc = subprocess.Popen([sys.executable, "-c", child, root], env=env,
                            stdout=subprocess.PIPE, text=True)
    try:
        assert proc.stdout.readline().startswith("READY")
        from hyperopt_tpu.service.journal import wal_path_for

        wal = wal_path_for(root)
        deadline = time.time() + 120.0
        while time.time() < deadline:
            try:
                if open(wal, "rb").read().count(b'"kind":"tell"') >= 4:
                    break
            except OSError:
                pass
            time.sleep(0.05)
        else:
            pytest.fail("child never told 4 trials")
        proc.send_signal(signal.SIGKILL)              # mid-wave, maybe
    finally:
        proc.kill()
        proc.wait()
    s2 = StudyScheduler(store_root=root)
    assert s2.last_resume["studies"] == 1
    row = s2.tenants.status()["table"]["acme"]
    assert row["studies"] == 1 and row["tells"] >= 4
    # and serving continues under the same principal
    a = s2.ask("study-k")[0]
    s2.tell("study-k", a["tid"], 0.5)


# ---------------------------------------------------------------------------
# env knobs + per-tenant SLOs
# ---------------------------------------------------------------------------


def test_env_knobs(monkeypatch):
    monkeypatch.delenv("HYPEROPT_TPU_TENANT", raising=False)
    assert parse_tenant()                             # default ON
    for off in ("0", "off", "false", "no"):
        assert not parse_tenant({"HYPEROPT_TPU_TENANT": off})
    assert parse_tenant({"HYPEROPT_TPU_TENANT": "1"})
    assert parse_tenant_top_k({}) == 64
    assert parse_tenant_top_k({"HYPEROPT_TPU_TENANT_TOP_K": "8"}) == 8
    assert parse_tenant_top_k({"HYPEROPT_TPU_TENANT_TOP_K": "0"}) == 64
    assert parse_tenant_top_k(
        {"HYPEROPT_TPU_TENANT_TOP_K": "banana"}) == 64
    assert parse_tenant_quota({}) is None             # default: no budget
    assert parse_tenant_quota({"HYPEROPT_TPU_TENANT_QUOTA": "6"}) == 6
    for off in ("0", "off"):
        assert parse_tenant_quota(
            {"HYPEROPT_TPU_TENANT_QUOTA": off}) is None
    assert parse_tenant_quota(
        {"HYPEROPT_TPU_TENANT_QUOTA": "banana"}) is None
    # the SLO grammar
    assert parse_tenant_slo({}) == TENANT_TARGETS
    assert parse_tenant_slo({}) is not TENANT_TARGETS  # a copy
    assert parse_tenant_slo({"HYPEROPT_TPU_TENANT_SLO": "off"}) is None
    t = parse_tenant_slo({"HYPEROPT_TPU_TENANT_SLO":
                          "avail=0.999,ask_ms=500"})
    assert t["availability"]["target"] == 0.999
    assert t["ask_p99"]["threshold_ms"] == 500.0
    assert t["shed_rate"] == TENANT_TARGETS["shed_rate"]
    assert parse_tenant_slo(
        {"HYPEROPT_TPU_TENANT_SLO": "avail=banana"}) == TENANT_TARGETS


def test_slo_record_event_and_bounded_tenant_objectives():
    slo = SLOPlane(metrics=None, clock=lambda: 1000.0)
    slo.add_objective("tenant:acme:availability",
                      TENANT_TARGETS["availability"])
    for _ in range(9):
        slo.record_event("tenant:acme:availability", False, now=1000.0)
    slo.record_event("tenant:acme:availability", True, now=1000.0)
    st = slo.status(now=1000.0)["tenant:acme:availability"]
    assert st["budget_remaining_frac"] < 1.0
    # unknown objective: a no-op, never a KeyError
    slo.record_event("tenant:ghost:availability", True, now=1000.0)

    # the server installs objectives per request-seen tenant, bounded
    # at top-K — past the bound new tenants attribute but don't mint
    # objective state (the burn plane's cardinality stays bounded)
    sched = StudyScheduler(wal=False, quality=False, load=False,
                           tenants=TenantLedger())
    srv = ServiceHTTPServer(0, scheduler=sched, trace=False)
    assert srv.slo is not None and srv.tenant_slo is not None
    srv._tenant_obj_bound = 2
    code, r = srv.handle("POST", "/study", {
        "space": SPACE_SPEC, "seed": 8, "n_startup_jobs": 9})
    sid = r["study_id"]
    for t in ("t-a", "t-b", "t-c"):
        code, _ = srv.handle("POST", "/ask", {"study_id": sid},
                             headers={"x-tenant": t})
        assert code == 200
    objs = [o for o in srv.slo.objectives if o.startswith("tenant:")]
    assert {o.split(":")[1] for o in objs} == {"t-a", "t-b"}
    assert all(f"tenant:{t}:{k}" in srv.slo.objectives
               for t in ("t-a", "t-b")
               for k in ("availability", "ask_p99", "shed_rate"))
    # probe traffic attributes to NO tenant (same exclusion as SLOs)
    code, _ = srv.handle("POST", "/ask", {"study_id": sid},
                         headers={"x-tenant": "canary", "x-probe": "1"})
    assert code == 200
    assert "canary" not in sched.tenants.status()["table"]


# ---------------------------------------------------------------------------
# the scrape contract + render surfaces
# ---------------------------------------------------------------------------


def test_metrics_scrape_lints_with_tenant_families():
    from hyperopt_tpu.obs.serve import prometheus_text
    from validate_scrape import (
        TENANT_FAMILIES,
        validate_metrics_text,
        validate_tenant_families,
    )

    sched = StudyScheduler(wal=False, quality=False, load=False,
                           tenants=TenantLedger())
    srv = ServiceHTTPServer(0, scheduler=sched, trace=False, slo=False)
    code, r = srv.handle("POST", "/study", {
        "space": SPACE_SPEC, "seed": 4, "n_startup_jobs": 2,
        "tenant": "team/a b"})
    sid = r["study_id"]
    for _ in range(3):
        code, a = srv.handle("POST", "/ask", {"study_id": sid},
                             headers={"x-tenant": "team/a b"})
        srv.handle("POST", "/tell", {
            "study_id": sid, "tid": a["trials"][0]["tid"], "loss": 0.5},
            headers={"x-tenant": "team/a b"})
    srv._refresh_tenant_gauges()          # the /metrics-dispatch refresh
    text = prometheus_text([sched.metrics.namespace])
    assert validate_metrics_text(text) == []
    assert validate_tenant_families(text) == []
    for fam in TENANT_FAMILIES:
        assert fam in text
    # hostile-ish tenant label characters were mangled, not emitted raw
    assert "hyperopt_tpu_service_tenant_team_a_b_asks" in text


def test_report_tenants_view(tmp_path, capsys):
    from hyperopt_tpu.obs.load import HeatLedger, heat_path_for
    from hyperopt_tpu.obs.report import main, render_tenants

    # dict view (a /tenants payload): full columns + the noisy banner
    status = {
        "tenants": 2, "top_k": 64, "evictions": 0, "device_ms": 100.0,
        "asks": 12, "tells": 10, "sheds": 3,
        "table": {
            "noisy": {"device_ms": 90.0, "asks": 10, "tells": 8,
                      "sheds": 3, "studies": 4, "hbm_bytes": 0.0,
                      "ewma_ms": 9.0, "ask_p99_ms": 40.0},
            "light": {"device_ms": 10.0, "asks": 2, "tells": 2,
                      "sheds": 0, "studies": 1, "hbm_bytes": 0.0,
                      "ewma_ms": 1.0, "ask_p99_ms": 5.0}}}
    text = render_tenants(status)
    assert "tenants" in text and "noisy" in text and "light" in text
    assert "NOISY-TENANT" in text         # 90% share > the 50% banner bar
    # store-root view: the durable fleet heat
    root = str(tmp_path)
    HeatLedger(heat_path_for(root, "rep-a")).append(
        {"kind": "heat", "replica": "rep-a", "shard": 0, "heat_ms": 9.0,
         "busy_frac": 0.1, "ts": 1.0, "tenants": {"acme": 9.0}})
    assert "acme" in render_tenants(root)
    payload = tmp_path / "tenants.json"
    payload.write_text(json.dumps(status))
    assert main(["--tenants", str(payload)]) == 0
    assert "NOISY-TENANT" in capsys.readouterr().out
    assert main(["--tenants", root]) == 0
    capsys.readouterr()
    # --tenants is its own view and text-only
    assert main(["--tenants", root, "--trend"]) == 2
    assert main(["--tenants", root, "--format", "json"]) == 2


def test_top_renders_tenant_row():
    from hyperopt_tpu.obs.top import _render_service_source

    snap = {"sections": {"service": {}}, "studies": [],
            "tenants": {"tenants": 2, "asks": 12, "device_ms": 100.0,
                        "sheds": 3, "evictions": 1,
                        "table": {"noisy": {"device_ms": 90.0},
                                  "light": {"device_ms": 10.0}}}}
    out = []
    _render_service_source("svc", snap, out, 8)
    row = next(line for line in out if "TENANT" in line)
    assert "tracked 2" in row and "top noisy (90%)" in row
    assert "NOISY" in row and "sheds 3" in row
    # disarmed snapshots render no row
    out2 = []
    _render_service_source("svc", {"sections": {"service": {}},
                                   "studies": []}, out2, 8)
    assert not any("TENANT" in line for line in out2)


def test_export_emits_per_tenant_counters(tmp_path):
    from hyperopt_tpu.obs.export import write_trace

    stream = [
        {"kind": "run_meta", "ts": 1.0, "run_id": "r"},
        {"kind": "metrics", "ts": 2.0, "snapshot": {
            "metrics": {"service.tenant.acme.device_ms": 12.0},
            "tenants": {"table": {"umbrella": {"device_ms": 7.0}}}}},
    ]
    out = str(tmp_path / "trace.json")
    write_trace(out, [("s", iter(stream))])
    events = json.load(open(out))["traceEvents"]
    ten = {e["name"]: e for e in events if e.get("cat") == "tenant"}
    assert ten["tenant.acme"]["args"]["device_ms"] == 12.0
    assert ten["tenant.umbrella"]["args"]["device_ms"] == 7.0
    assert all(e["ph"] == "C" for e in ten.values())


# ---------------------------------------------------------------------------
# the new bench keys really gate
# ---------------------------------------------------------------------------


def _bench_rec(ts, **keys):
    return {"kind": "bench", "ts": ts, "backend": "cpu",
            "source": "test", "keys": keys}


def test_tenant_overhead_gates_absolute_from_first_run():
    import bench_gate
    from hyperopt_tpu.obs.trajectory import KEY_DIRECTIONS

    old = _bench_rec(0.0, trials_per_sec=100.0)       # no tenant keys yet
    over = _bench_rec(1.0, tenant_overhead_frac=0.09)
    regs, _ = bench_gate.windowed_compare([old], over, KEY_DIRECTIONS)
    assert any("tenant_overhead_frac" in r for r in regs)
    ok = _bench_rec(1.0, tenant_overhead_frac=0.04)
    regs, _ = bench_gate.windowed_compare([old], ok, KEY_DIRECTIONS)
    assert regs == []


def test_tenant_p99_skew_gates_windowed_lower_is_better():
    import bench_gate
    from hyperopt_tpu.obs.trajectory import KEY_DIRECTIONS, TAIL_METRICS

    assert "tenant_p99_skew" in TAIL_METRICS
    assert "tenant_overhead_frac" in TAIL_METRICS
    history = [_bench_rec(float(i), tenant_p99_skew=1.2)
               for i in range(3)]
    bad = _bench_rec(3.0, tenant_p99_skew=2.0)        # +67% > the 50% bar
    regs, _ = bench_gate.windowed_compare(history, bad, KEY_DIRECTIONS)
    assert any("tenant_p99_skew" in r for r in regs)
    ok = _bench_rec(3.0, tenant_p99_skew=1.3)
    regs, _ = bench_gate.windowed_compare(history, ok, KEY_DIRECTIONS)
    assert regs == []
